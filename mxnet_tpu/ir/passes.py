"""mxnet_tpu.ir.passes — rewrite-pass pipeline over the typed graph IR.

Each pass is a pure ``Graph -> Graph`` function (via :class:`PassManager`
for users; the lowering layer runs the same passes through a map-tracking
:class:`_Work` so capture-side leaf/slot numbering survives the
rewrites). These are the whole-graph optimizations XLA cannot do across
this stack's dispatch boundaries — they run ONCE per canonical graph,
before jit, and every capture that lowers the same math shares the
result (Relay's "pass, not vigil" discipline, arXiv 1810.00952; the
lowered artifact is one compiled program per canonical graph, the TVM
move of arXiv 1802.04799).

Passes:

* ``cse``       — merge structurally identical subexpressions (same op,
                  static attrs, input wiring). The bulk window captures
                  a fresh node per imperative call even when the math
                  repeats; CSE collapses the repeats to one slot.
* ``fold``      — pre-evaluate constant islands (``_const``/``_filled``/
                  ``_arange`` roots and the pure math over them) into
                  baked array constants at build time.
* ``cast_sink`` — parity-exact cast cleanup: identity casts
                  (target == input dtype) vanish; lossless-widening
                  round trips (``bf16 → f32 → bf16``) collapse to the
                  source value. The mixed-precision checkpoint/AMP
                  boundary pattern.
* ``dce``       — drop nodes and leaves no output depends on (the dead
                  branches earlier rewrites strand, plus capture-side
                  dead results the window recorded but nobody read).
* ``donation``  — annotate the donation policy: leaves consumed exactly
                  once whose aval matches an output are safe donation
                  candidates (``meta['donatable_leaves']``); lowering
                  applies them only when the caller opts in (capture
                  paths never donate implicitly — the caller's NDArrays
                  own those buffers).
* ``quant``     — OPT-IN (not in ``DEFAULT_PASSES``): rewrite eligible
                  fp32 matmul nodes (``dot`` without transposes,
                  ``FullyConnected``) into fused dynamically-quantized
                  bodies — per-channel int8 weight quantize → int8×int8
                  MXU matmul (``preferred_element_type=int32``) → fp32
                  rescale. Single-node in-place rewrite: slot numbering
                  and wiring are untouched, so capture maps survive.
                  The graph-level complement of ``quant.quantize_model``
                  for callers that opt whole captured programs in:
                  ``PassManager(DEFAULT_PASSES + ("quant",))``.

Per-pass node/edge deltas are kept in :data:`PASS_STATS` (fixed keys, no
unbounded growth — GL006) and mirrored into the observability registry
as ``ir_pass_*`` counters on each run.
"""
from __future__ import annotations

import numpy as np

from ..base import OP_REGISTRY, _freeze, env_cap as _env_cap, resolve_dtype
from .graph import Graph

__all__ = ["PassManager", "DEFAULT_PASSES", "PASS_STATS", "pass_stats"]

# ops whose value is fully determined by static attrs (no inputs): the
# roots constant folding grows islands from
_CONST_ROOT_OPS = ("_const", "_filled", "_arange")

# folding a huge _filled would bake megabytes into the program text; XLA
# folds those fine on its own. Islands above this element count stay.
_FOLD_MAX_ELEMS = _env_cap("MXNET_IR_FOLD_MAX_ELEMS", 65536)

_PASS_NAMES = ("cse", "fold", "cast_sink", "dce", "donation", "quant")

# fixed-key stats table (one entry per pass — bounded by construction);
# tools/diagnose.py and ir.lower.stats() read it, the observability "ir"
# collector exports it
PASS_STATS = {name: {"runs": 0, "nodes_removed": 0, "edges_removed": 0,
                     "rewrites": 0} for name in _PASS_NAMES}


def pass_stats():
    return {k: dict(v) for k, v in PASS_STATS.items()}


def _note(name, graph_before, graph_after, rewrites):
    st = PASS_STATS[name]
    st["runs"] += 1
    dn = graph_before.n_nodes - graph_after.n_nodes
    de = graph_before.n_edges - graph_after.n_edges
    st["nodes_removed"] += max(dn, 0)
    st["edges_removed"] += max(de, 0)
    st["rewrites"] += rewrites
    try:  # mirror into the metrics registry (lazy: avoids an import cycle)
        from ..observability import registry

        if dn > 0:
            registry.counter("ir_pass_%s_nodes_removed" % name).inc(dn)
        if de > 0:
            registry.counter("ir_pass_%s_edges_removed" % name).inc(de)
        if rewrites:
            registry.counter("ir_pass_%s_rewrites" % name).inc(rewrites)
    except Exception:
        pass  # registry unavailable (partial import): stats table still has it


class _Work:
    """Mutable pass workspace with capture-map tracking. ``slot_rep``
    accumulates slot→spec replacements (CSE merges, cast bypasses);
    ``leaf_back[j]`` is the input-graph leaf behind current leaf ``j``.
    ``resolve`` follows replacement chains so later passes and the final
    maps all see through earlier rewrites."""

    def __init__(self, graph):
        self.nodes = list(graph.nodes)
        self.leaf_sigs = list(graph.leaf_sigs)
        self.outputs = list(graph.outputs)
        self.meta = dict(graph.meta)
        self.leaf_back = list(range(len(graph.leaf_sigs)))
        self.slot_rep = {}
        self._in_slots = sum(n.n_out for n in graph.nodes)

    def resolve(self, spec):
        if getattr(self, "_rep_final", False):
            # post-renumber: values live in the FINAL slot space, whose
            # numbers may coincide with stale keys — single-step only
            return self.slot_rep.get(spec, spec) if spec >= 0 else spec
        while spec >= 0 and spec in self.slot_rep:
            spec = self.slot_rep[spec]
        return spec

    def graph(self):
        return Graph(self.nodes, self.leaf_sigs, self.outputs, self.meta)

    def finish(self):
        """(final Graph, leaf_sel, slot_fwd): ``leaf_sel[j]`` is the
        input-graph leaf behind final program arg ``j``; ``slot_fwd``
        maps every input-graph slot to its final spec (through merges
        and renumbering; None = dead)."""
        g = self.graph()
        renumber = getattr(self, "_renumber", None)
        slot_fwd = {}
        for s in range(self._in_slots):
            if s in self.slot_rep:
                slot_fwd[s] = self.resolve(s)  # already final-space
            elif renumber is not None:
                slot_fwd[s] = renumber.get(s)  # None when DCE'd
            else:
                slot_fwd[s] = s
        return g, tuple(self.leaf_back), slot_fwd


# ---------------------------------------------------------------- passes


def _apply_reps(work):
    """Rewrite all wiring through the accumulated slot replacements."""
    if not work.slot_rep:
        return
    res = work.resolve
    work.nodes = [n.replace(specs=tuple(res(s) for s in n.specs),
                            kw_specs=tuple(res(s) for s in n.kw_specs))
                  for n in work.nodes]
    work.outputs = [res(s) for s in work.outputs]


def _cse(work):
    """Merge structurally identical nodes. Pinned nodes (tape probe
    injection sites) are opaque: never merged away, never a merge
    target — a probe perturbs its slot's value, so aliasing it with
    other uses would change gradients."""
    seen = {}
    rewrites = 0
    bases, s = [], 0
    for n in work.nodes:
        bases.append(s)
        s += n.n_out
    for i, n in enumerate(work.nodes):
        if n.pinned:
            continue
        key = (n.op, n.static_key,
               tuple(work.resolve(x) for x in n.specs),
               tuple(work.resolve(x) for x in n.kw_specs),
               n.kw_names, n.n_out)
        try:
            first = seen.setdefault(key, i)
        except TypeError:  # unhashable static_key: skip defensively
            continue
        if first != i and not work.nodes[first].pinned:
            for j in range(n.n_out):
                work.slot_rep[bases[i] + j] = bases[first] + j
            rewrites += 1
    _apply_reps(work)
    return rewrites


def _fold(work):
    """Replace constant islands with baked array constants. A node is
    constant when it is a const root (no inputs, static-only) or every
    input resolves to a constant slot; boundary nodes (constant nodes
    with a non-constant consumer, or outputs) become ``_ir_const``
    nodes holding the pre-evaluated value; interior nodes die (DCE
    sweeps them)."""
    bases, s = [], 0
    for n in work.nodes:
        bases.append(s)
        s += n.n_out
    const = {}   # node idx -> evaluated value (single-output only)
    rewrites = 0
    for i, n in enumerate(work.nodes):
        if n.pinned or n.n_out != 1 or n.kw_names:
            continue
        is_root = n.op in _CONST_ROOT_OPS and not n.specs
        deps_const = n.specs and all(
            s >= 0 and s in const for s in
            (work.resolve(x) for x in n.specs))
        if not (is_root or deps_const):
            continue
        try:
            vals = [const[work.resolve(x)] for x in n.specs]
            v = n.fn(*vals, **n.static) if n.static else n.fn(*vals)
            v = np.asarray(v)
        except Exception:
            continue  # not host-evaluable: leave it to runtime
        if v.size > getattr(work, "fold_max_elems", _FOLD_MAX_ELEMS):
            continue
        const[bases[i]] = v
    if not const:
        return 0
    # rebuild: constant slots that still have non-constant consumers (or
    # are outputs) become baked-constant nodes
    slot_is_const = set(const)
    used_by_nonconst = set()
    for i, n in enumerate(work.nodes):
        if bases[i] in const:
            continue
        for x in n.specs + n.kw_specs:
            r = work.resolve(x)
            if r in slot_is_const:
                used_by_nonconst.add(r)
    for s_ in work.outputs:
        r = work.resolve(s_)
        if r in slot_is_const:
            used_by_nonconst.add(r)
    for i, n in enumerate(work.nodes):
        sl = bases[i]
        if sl in const and sl in used_by_nonconst \
                and n.op != "_ir_const":
            v = const[sl]
            work.nodes[i] = Node_const(v, n)
            rewrites += 1
    return rewrites


def Node_const(value, like):
    """A baked constant node: value pre-evaluated at pass time, embedded
    as a program constant (XLA hoists it)."""
    from .graph import Node

    arr = np.asarray(value)

    def _ir_const(*, value=None):
        import jax.numpy as jnp

        return jnp.asarray(arr)

    return Node("_ir_const", _ir_const, {"value": arr},
                _freeze({"value": arr}), (), aval=like.aval, sig=like.sig)


def _lossless_widen(src, mid):
    """True when casting ``src`` → ``mid`` loses nothing (so a later cast
    from ``mid`` equals a cast from ``src``). Conservative float/int
    ladder; unknown combos are not lossless."""
    try:
        src, mid = np.dtype(src), np.dtype(mid)
    except TypeError:
        return False
    if src == mid:
        return True
    flt = {"bfloat16": 8, "float16": 11, "float32": 24, "float64": 53}
    if src.name in flt and mid.name in flt:
        # mantissa AND exponent must both widen; bf16's exponent range
        # equals f32's, f16's does not cover bf16
        exp = {"bfloat16": 8, "float16": 5, "float32": 8, "float64": 11}
        return flt[mid.name] >= flt[src.name] and \
            exp[mid.name] >= exp[src.name]
    if src.kind in "iu" and mid.kind in "iu":
        return (src.kind == mid.kind and mid.itemsize >= src.itemsize) or \
            (src.kind == "u" and mid.kind == "i"
             and mid.itemsize > src.itemsize)
    return False


def _cast_sink(work):
    """Parity-exact cast cleanup (the bf16 mixed-precision pattern):

    * ``cast(x, dtype(x))``                  → ``x``
    * ``cast(cast(x, mid), t)`` with a lossless ``x → mid`` widen
                                             → ``cast(x, t)``

    Rewrites never bypass a pinned producer (its slot's value is
    observed by tape probe injection)."""
    owner = {}
    bases, s = [], 0
    for i, n in enumerate(work.nodes):
        bases.append(s)
        for j in range(n.n_out):
            owner[s + j] = i
        s += n.n_out

    def producer(spec):
        return work.nodes[owner[spec]] if spec >= 0 else None

    def spec_dtype(spec):
        if spec >= 0:
            n = producer(spec)
            return None if n is None or n.aval is None else n.aval.dtype
        from .graph import _SIG_LIST

        sid = work.leaf_sigs[~spec]
        if sid is None:  # untyped leaf (structural-only graph)
            return None
        sig = _SIG_LIST[sid]
        return sig[0] if type(sig) is tuple else None

    rewrites = 0
    for i, n in enumerate(work.nodes):
        if n.op != "cast" or n.pinned:
            continue
        src = work.resolve(n.specs[0])
        target = resolve_dtype(n.static.get("dtype"))
        # collapse a lossless-widening inner cast first
        inner = producer(src)
        if inner is not None and inner.op == "cast" and not inner.pinned:
            inner_src = work.resolve(inner.specs[0])
            sdt = spec_dtype(inner_src)
            if sdt is not None and target is not None and \
                    _lossless_widen(sdt, inner.aval.dtype
                                    if inner.aval is not None
                                    else resolve_dtype(
                                        inner.static.get("dtype"))):
                work.nodes[i] = n.replace(specs=(inner_src,))
                src = inner_src
                rewrites += 1
        # identity cast: target == input dtype
        sdt = spec_dtype(src)
        if sdt is not None and target is not None \
                and np.dtype(sdt) == np.dtype(target):
            prod = producer(src)
            if prod is None or not prod.pinned:
                work.slot_rep[bases[i]] = src
                rewrites += 1
    _apply_reps(work)
    return rewrites


def _dce(work):
    """Drop nodes and leaves no output (transitively) uses, renumbering
    slots and leaves. Outputs — including the tape's pinned probe
    slots, which lowering always lists as outputs — are the roots."""
    owner = {}
    bases, s = [], 0
    for i, n in enumerate(work.nodes):
        bases.append(s)
        for j in range(n.n_out):
            owner[s + j] = i
        s += n.n_out
    live_nodes = set()
    live_leaves = set()
    stack = [sp for sp in work.outputs]
    while stack:
        sp = stack.pop()
        if sp < 0:
            live_leaves.add(~sp)
            continue
        ni = owner[sp]
        if ni in live_nodes:
            continue
        live_nodes.add(ni)
        n = work.nodes[ni]
        stack.extend(n.specs + n.kw_specs)
    if len(live_nodes) == len(work.nodes) and \
            len(live_leaves) == len(work.leaf_sigs):
        return 0
    # renumber kept nodes (original relative order) and kept leaves
    kept = [i for i in range(len(work.nodes)) if i in live_nodes]
    new_bases, s = {}, 0
    for i in kept:
        new_bases[i] = s
        s += work.nodes[i].n_out
    leaf_map = {}
    new_leaf_sigs, new_leaf_back = [], []
    for li in range(len(work.leaf_sigs)):
        if li in live_leaves:
            leaf_map[li] = len(new_leaf_sigs)
            new_leaf_sigs.append(work.leaf_sigs[li])
            new_leaf_back.append(work.leaf_back[li])

    def remap(spec):
        if spec < 0:
            return ~leaf_map[~spec]
        return new_bases[owner[spec]] + (spec - bases[owner[spec]])

    renumber = {}
    for i in kept:
        for j in range(work.nodes[i].n_out):
            renumber[bases[i] + j] = new_bases[i] + j
    rewrites = len(work.nodes) - len(kept)
    work.nodes = [work.nodes[i].replace(
        specs=tuple(remap(s) for s in work.nodes[i].specs),
        kw_specs=tuple(remap(s) for s in work.nodes[i].kw_specs))
        for i in kept]
    work.outputs = [remap(s) for s in work.outputs]
    work.leaf_sigs = new_leaf_sigs
    work.leaf_back = new_leaf_back
    # flatten every replacement chain in the OLD slot space, then remap
    # into the final space; from here on resolve() is single-step
    # (_rep_final) — final slot numbers may coincide with stale old keys
    flat = {k: work.resolve(k) for k in list(work.slot_rep)}
    work.slot_rep = {
        k: (renumber.get(v, v) if v >= 0
            else (~leaf_map[~v] if ~v in leaf_map else v))
        for k, v in flat.items()}
    work._rep_final = True
    prev = getattr(work, "_renumber", None)
    work._renumber = renumber if prev is None else {
        k: renumber.get(v, v) for k, v in prev.items()}
    return rewrites


def _donation(work):
    """Annotate the automatic donation policy: a leaf is a donation
    candidate when it is an array leaf consumed by exactly ONE wiring
    edge and some output aval matches its signature (XLA can then alias
    the input buffer into that output). Annotation only — lowering
    donates solely when the caller opts in."""
    from .graph import _SIG_LIST

    uses = {}
    for n in work.nodes:
        for s in n.specs + n.kw_specs:
            if s < 0:
                uses[~s] = uses.get(~s, 0) + 1
    for s in work.outputs:
        if s < 0:
            uses[~s] = uses.get(~s, 0) + 2  # passthrough output: never donate
    out_sigs = set()
    owner = {}
    base = 0
    for n in work.nodes:
        for j in range(n.n_out):
            owner[base + j] = n
        base += n.n_out
    for s in work.outputs:
        if s >= 0 and owner[s].sig is not None:
            out_sigs.add(owner[s].sig)
    cands = tuple(sorted(
        li for li, cnt in uses.items()
        if cnt == 1 and work.leaf_sigs[li] is not None
        and type(_SIG_LIST[work.leaf_sigs[li]]) is tuple
        and work.leaf_sigs[li] in out_sigs))
    work.meta["donatable_leaves"] = cands
    return len(cands)


def _quant_node_fn(op, orig):
    """Fused dynamically-quantized body replacing one matmul node.
    Branches only on trace-time static properties (ndim/dtype/static
    attrs) and falls back to the original body for ineligible inputs, so
    the rewrite is always safe to apply."""

    def fn(a, b, *rest, **static):
        import jax
        import jax.numpy as jnp

        from ..quantization import _quantize_act, quantize_weight, \
            quantized_fully_connected

        f32 = np.dtype(np.float32)
        if op == "FullyConnected":
            x, w = a, b
            nh = static.get("num_hidden")
            if w.ndim != 2 or np.dtype(w.dtype) != f32 \
                    or np.dtype(x.dtype) != f32 \
                    or (nh is not None and w.shape[0] != nh):
                return orig(a, b, *rest, **static)
            if static.get("flatten", True) and x.ndim > 2:
                x = jnp.reshape(x, (x.shape[0], -1))
            bias = None
            if rest and rest[0] is not None \
                    and not static.get("no_bias", False):
                bias = rest[0]
            qw, ws = quantize_weight(w, axis=0)
            return quantized_fully_connected(x, qw, ws, bias)
        # dot: a @ b with b (in, out) — per-column weight channels
        if static.get("transpose_a") or static.get("transpose_b") \
                or a.ndim != 2 or b.ndim != 2 \
                or np.dtype(a.dtype) != f32 or np.dtype(b.dtype) != f32:
            return orig(a, b, *rest, **static)
        qb, b_scale = quantize_weight(b, axis=1)
        qa, a_scale = _quantize_act(a, None, qb.dtype, 127.0, True)
        acc = jax.lax.dot_general(qa, qb, (((1,), (0,)), ((), ())),
                                  preferred_element_type=jnp.int32)
        return acc.astype(jnp.float32) * (a_scale * b_scale.reshape(-1))

    return fn


def _quant(work):
    """Opt-in quantized-matmul rewrite (see module docstring). In-place
    single-node rewrites only: n_out, specs and slot numbering are
    preserved, so no _apply_reps / renumbering is needed."""
    rewrites = 0
    for i, n in enumerate(work.nodes):
        if n.pinned or n.kw_names or n.op not in ("dot", "FullyConnected"):
            continue
        if n.op == "dot" and (n.static.get("transpose_a")
                              or n.static.get("transpose_b")):
            continue
        work.nodes[i] = n.replace(op="_quant_" + n.op,
                                  fn=_quant_node_fn(n.op, n.fn))
        rewrites += 1
    return rewrites


_PASS_FNS = {"cse": _cse, "fold": _fold, "cast_sink": _cast_sink,
             "dce": _dce, "donation": _donation, "quant": _quant}

DEFAULT_PASSES = ("cse", "fold", "cast_sink", "dce", "donation")


class PassManager:
    """Ordered pipeline of rewrite passes. :meth:`run` is the pure
    ``Graph -> Graph`` form (pass-unit tests, user experimentation);
    :meth:`run_work` is the map-tracking form lowering uses. The
    pipeline is deterministic: same input graph → same output graph,
    byte-identical canonical keys (tests assert it).

    The config surface (``passes`` ordering + ``fold_max_elems``) is the
    autotuner's search space: ``ir.tune`` persists winning configs as
    :meth:`config` dicts and rebuilds them with :meth:`from_config`, and
    lowering consults the tuned-config store before falling back to
    ``PassManager()`` (= ``DEFAULT_PASSES``)."""

    def __init__(self, passes=DEFAULT_PASSES, fold_max_elems=None):
        unknown = [p for p in passes if p not in _PASS_FNS]
        if unknown:
            raise ValueError("unknown IR passes %s (have %s)"
                             % (unknown, sorted(_PASS_FNS)))
        self.passes = tuple(passes)
        # None = the process default (MXNET_IR_FOLD_MAX_ELEMS); a tuned
        # config pins an explicit cap so the fold decision travels with
        # the config, not the environment
        self.fold_max_elems = (None if fold_max_elems is None
                               else int(fold_max_elems))

    def config(self):
        """JSON-serializable config dict (the tuned-store entry body)."""
        cfg = {"passes": list(self.passes)}
        if self.fold_max_elems is not None:
            cfg["fold_max_elems"] = self.fold_max_elems
        return cfg

    @classmethod
    def from_config(cls, cfg):
        return cls(passes=tuple(cfg.get("passes", DEFAULT_PASSES)),
                   fold_max_elems=cfg.get("fold_max_elems"))

    def run_work(self, work):
        if self.fold_max_elems is not None:
            work.fold_max_elems = self.fold_max_elems
        for name in self.passes:
            before = work.graph()
            rewrites = _PASS_FNS[name](work)
            _note(name, before, work.graph(), rewrites)
        return work

    def run(self, graph):
        return self.run_work(_Work(graph)).graph()


def optimize(graph, pm=None):
    """(final Graph, leaf_sel, slot_fwd) — the lowering entry point."""
    w = (pm or PassManager()).run_work(_Work(graph))
    return w.finish()
