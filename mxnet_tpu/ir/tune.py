"""mxnet_tpu.ir.tune — cost-model-driven autotuning over the typed IR.

The TVM thesis (arXiv 1802.04799) applied to this stack: schedules are
*searched*, not hand-authored. Every knob that decides real step time —
the PassManager configuration (pass ordering, quant placement,
cast-sink on/off, the constant-fold size cap), the per-graph donation
policy, the imperative bulk watermark (``MXNET_ENGINE_BULK_SIZE``),
serve bucket sets, and the flash-attention block tables — becomes a
candidate space this module searches with two instruments the repo
already trusts:

* the **costs ledger** (observability.costs, PR 13): every candidate is
  compiled once and its deterministic flops / bytes-accessed / peak-HBM
  columns prune the space BEFORE anything is timed, so the search
  measures only plausibly-winning configs (μ-cuDNN's decompose-to-fit
  parameters are workload-dependent, arXiv 1804.04806 — but most of a
  grid is dominated and never worth a stopwatch);
* **paired-step timing** (PERF.md methodology): run-level A/B on a
  shared box swings ±50%, so the objective interleaves ONE step per arm
  and takes the median of per-pair deltas — contention hits both sides
  of every pair.

Winners persist to a JSON store keyed by ``ir.graph.canonical_key``
(``MXNET_TUNE_STORE``, or ``<MXNET_COMP_CACHE_DIR>/tuned.json`` so the
tuned configs ride the comp-cache to every replica; in-memory when
neither is set). ``ir.lower.prepare`` consults :func:`pass_manager_for`
before falling back to ``DEFAULT_PASSES`` — tuning is paid once per
topology and a fresh process reloads the winner with ZERO re-search
(tests pin this with the retrace watchdog armed).

Every candidate the search may emit is parity-gated at ≤1e-6 against
the DEFAULT_PASSES output on deterministic example inputs; ``quant`` —
the one pass that intentionally changes numerics — is excluded from the
default space and only enters via ``include_quant=True``, where the
same gate applies (so it only survives on graphs it cannot touch).
"""
from __future__ import annotations

import json
import os
import threading
import time

import numpy as np

from .. import base
from ..observability import costs as _costs
from . import graph as _g
from . import passes as _p

__all__ = ["search", "candidate_configs", "rank_candidates",
           "paired_step_ms", "pass_manager_for", "install", "lookup",
           "fit_buckets", "tune_buckets", "tune_bulk_watermark",
           "tune_flash_blocks", "flash_block_candidates", "store_path",
           "get_store", "reset_store", "stats", "reset_stats"]

TUNED_BY = "mxnet_tpu.ir.tune"

# fixed-key search telemetry (GL006: bounded by construction) — the
# observability "tune" collector and tools/diagnose.py read this via
# stats()
_STATS = {
    "searches": 0,          # search() invocations this process
    "candidates": 0,        # configs probed (compiled for cost columns)
    "pruned": 0,            # dominated by the cost ledger — never timed
    "timed": 0,             # survivors measured with paired steps
    "parity_rejects": 0,    # candidates discarded for output mismatch
    "installs": 0,          # winners written to the store
    "store_hits": 0,        # lower-path lookups that found a tuned config
    "store_misses": 0,      # lookups that fell back to DEFAULT_PASSES
    "last_search": None,    # summary dict of the most recent search()
}

_lock = threading.Lock()


def _utcnow():
    return time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime())


# ------------------------------------------------------------------ store


def store_path():
    """Resolved tuned-config store path, or None (in-memory only).
    ``MXNET_TUNE_STORE`` wins; otherwise the store lives inside the
    persistent comp-cache directory so tuned configs ship with the
    compiled executables they pair with."""
    p = os.environ.get("MXNET_TUNE_STORE")
    if p:
        return p
    cc = os.environ.get("MXNET_COMP_CACHE_DIR")
    if cc:
        return os.path.join(cc, "tuned.json")
    return None


class TunedStore:
    """Persistent ``key -> record`` map of tuning winners.

    Keys are namespaced: ``graph:<canonical sha>`` (PassManager
    configs), ``engine:bulk_size``, ``serve:buckets:<server name>``,
    ``flash:blocks``. Records always carry ``tuned_by`` / ``swept_at``
    / ``backend`` provenance next to the config itself. Writes are
    atomic (tmp + ``os.replace``) so a crashed search never leaves a
    half-written store; loads are lazy and a malformed file degrades to
    empty with a warning (tuning must never break lowering)."""

    VERSION = 1

    def __init__(self, path=None):
        self.path = path
        self._entries = None
        self._lock = threading.Lock()

    def _load(self):
        if self._entries is not None:
            return self._entries
        entries = {}
        if self.path and os.path.exists(self.path):
            try:
                with open(self.path) as f:
                    raw = json.load(f)
                entries = dict(raw.get("entries", {}))
            except Exception as e:
                import warnings

                warnings.warn("ignoring malformed tuned-config store %s "
                              "(%s); starting empty" % (self.path, e))
        self._entries = entries
        return entries

    def _save(self):
        if not self.path:
            return
        d = os.path.dirname(self.path)
        if d:
            os.makedirs(d, exist_ok=True)
        tmp = self.path + ".tmp"
        with open(tmp, "w") as f:
            json.dump({"version": self.VERSION, "entries": self._entries},
                      f, indent=1, sort_keys=True)
            f.write("\n")
        os.replace(tmp, self.path)

    def get(self, key):
        with self._lock:
            return self._load().get(key)

    def put(self, key, record):
        with self._lock:
            self._load()[key] = record
            self._save()

    def keys(self):
        with self._lock:
            return sorted(self._load())

    def __len__(self):
        with self._lock:
            return len(self._load())


_store = None


def get_store():
    global _store
    with _lock:
        if _store is None:
            _store = TunedStore(store_path())
        return _store


def reset_store():
    """Test hook: drop the in-process store handle so the next access
    re-resolves the path (e.g. after changing ``MXNET_TUNE_STORE``)."""
    global _store
    with _lock:
        _store = None


def reset_stats():
    with _lock:
        for k in _STATS:
            _STATS[k] = None if k == "last_search" else 0


def stats():
    """The observability "tune" collector / diagnose "Autotuning"
    section payload."""
    with _lock:
        out = dict(_STATS)
    st = get_store()
    out["store"] = {"path": st.path, "entries": len(st),
                    "keys": st.keys()[:16]}
    return out


# ------------------------------------------------- lower-path integration


def lookup(key):
    """Raw store record for canonical graph ``key``, or None."""
    return get_store().get("graph:" + key)


def pass_manager_for(key):
    """The tuned :class:`~mxnet_tpu.ir.passes.PassManager` for canonical
    graph ``key``, or None to fall back to ``DEFAULT_PASSES``. This is
    the hook ``ir.lower.prepare`` consults on every entry build — a hit
    means the search already ran (this process or any process sharing
    the store) and lowering replays the winner with zero re-search."""
    rec = lookup(key)
    with _lock:
        if rec is None:
            _STATS["store_misses"] += 1
        else:
            _STATS["store_hits"] += 1
    if rec is None:
        return None
    try:
        return _p.PassManager.from_config(rec["config"])
    except Exception:
        return None  # stale/foreign record: DEFAULT_PASSES, never a crash


def install(key, config, objective=None, cost=None, tuned_by=None):
    """Persist a winning config for canonical graph ``key`` and evict
    the live IR-cache entry so the NEXT lowering of this topology
    rebuilds with the tuned config (one retrace at install time, zero
    after — the watchdog-armed contract tests pin)."""
    rec = {"config": dict(config),
           "tuned_by": tuned_by or (TUNED_BY + ".search"),
           "swept_at": _utcnow(),
           "backend": _backend_name()}
    if objective:
        rec["objective"] = objective
    if cost:
        rec["cost"] = cost
    get_store().put("graph:" + key, rec)
    base._IR_CACHE.pop(key, None)
    with _lock:
        _STATS["installs"] += 1
    return rec


def _backend_name():
    try:
        import jax

        return jax.default_backend()
    except Exception:
        return None


# -------------------------------------------------------- candidate space


def candidate_configs(include_quant=False):
    """Deterministic candidate list over the PassManager surface: pass
    orderings (fold before/after CSE, cast-sink placement), pass subsets
    (cast-sink off, donation off), and the constant-fold size cap (the
    ``MXNET_IR_FOLD_MAX_ELEMS`` axis — larger caps bake bigger constant
    islands into the program; XLA refuses to pre-evaluate expensive ops
    like ``dot`` over constants, so this is a real lever, measured in
    tools/tune_bench.py). ``quant`` only enters on request: it is the
    one pass that intentionally changes numerics, and the parity gate
    will reject it anywhere it actually fires."""
    orderings = [
        ("cse", "fold", "cast_sink", "dce", "donation"),
        ("fold", "cse", "cast_sink", "dce", "donation"),
        ("cse", "cast_sink", "fold", "dce", "donation"),
        ("cse", "fold", "dce", "donation"),        # cast_sink off
        ("cse", "fold", "cast_sink", "dce"),       # donation off
    ]
    if include_quant:
        orderings.append(
            ("cse", "fold", "cast_sink", "dce", "donation", "quant"))
    caps = (None, 262144, 1048576)  # None = the process default (65536)
    out = []
    for cap in caps:
        for o in orderings:
            cfg = {"passes": list(o)}
            if cap is not None:
                cfg["fold_max_elems"] = cap
            out.append(cfg)
    return out


def config_key(cfg):
    """Stable string identity of a config (ranking tiebreak, dedupe)."""
    return json.dumps(cfg, sort_keys=True)


def example_leaves(cgraph, seed=0):
    """Deterministic example inputs for a canonical graph's leaves —
    the values every candidate is parity-checked and timed on. Array
    leaves only: scalar-typed or untyped leaves make the probe program
    ambiguous, and every graph the capture layers lower has array
    leaves."""
    rs = np.random.RandomState(seed)
    vals = []
    for sid in cgraph.leaf_sigs:
        sig = None if sid is None else _g.sig_value(sid)
        if type(sig) is not tuple:
            raise ValueError(
                "tune.search needs array-typed leaves (got %r)" % (sig,))
        dt, shape = np.dtype(sig[0]), sig[1]
        if dt.kind in "iu":
            vals.append(rs.randint(0, 8, size=shape).astype(dt))
        elif dt.kind == "b":
            vals.append((rs.rand(*shape) > 0.5))
        else:
            vals.append(rs.standard_normal(shape).astype(dt))
    return vals


class _Probe:
    """One candidate, compiled once: the optimized graph, its AOT
    executable, the cost-ledger columns, and the outputs on the example
    inputs (the parity evidence and the timing operands)."""

    __slots__ = ("config", "compiled", "args", "cost", "outputs",
                 "n_nodes")

    def __init__(self, config, compiled, args, cost, outputs, n_nodes):
        self.config = config
        self.compiled = compiled
        self.args = args
        self.cost = cost
        self.outputs = outputs
        self.n_nodes = n_nodes

    def step(self):
        import jax

        jax.block_until_ready(self.compiled(*self.args))


def _probe(cgraph, pm, leaves, config):
    """Compile one candidate AOT and read its cost columns. Probe
    programs are throwaway search artifacts — deliberately NOT routed
    through the persistent funnel (they must not crowd real programs
    out of the comp-cache), so the direct jit is intentional."""
    import jax

    final, leaf_sel, _ = _p.optimize(cgraph, pm)
    run = _g.build_runner(final)

    def fwd(*leaf_vals):
        return run(leaf_vals)

    args = [leaves[li] for li in leaf_sel]
    avals = [jax.ShapeDtypeStruct(a.shape, a.dtype) for a in args]
    jfn = jax.jit(fwd)  # graphlint: disable=GL008
    compiled = jfn.lower(*avals).compile()
    cost = _costs._analyze(compiled)
    outputs = [np.asarray(o) for o in compiled(*args)]
    return _Probe(config, compiled, args, cost, outputs, final.n_nodes)


def _parity_ok(base_outs, cand_outs, tol=1e-6):
    if len(base_outs) != len(cand_outs):
        return False
    for a, b in zip(base_outs, cand_outs):
        if a.shape != b.shape or a.dtype != b.dtype:
            return False
        if not np.allclose(np.asarray(a, np.float64),
                           np.asarray(b, np.float64), rtol=tol, atol=tol):
            return False
    return True


def rank_candidates(rows):
    """Deterministic cost-model ranking: ascending (bytes_accessed,
    flops, peak_hbm_bytes), config-key tiebreak. Pure — same ledger
    columns in, same order out, in any process (the pruning-determinism
    test contract)."""
    return sorted(rows, key=lambda r: (
        float(r["cost"]["bytes_accessed"]), float(r["cost"]["flops"]),
        float(r["cost"]["peak_hbm_bytes"]), r["config_key"]))


def _cost_plausible(cand_cost, base_cost):
    """Ledger gate: a candidate is worth a stopwatch only if it strictly
    improves at least one first-order column — bytes accessed (the
    memory-bound proxy), flops, or peak HBM."""
    return (cand_cost["bytes_accessed"] < base_cost["bytes_accessed"]
            or cand_cost["flops"] < base_cost["flops"]
            or cand_cost["peak_hbm_bytes"] < base_cost["peak_hbm_bytes"])


# ------------------------------------------------------------- the search


def paired_step_ms(fn_a, fn_b, pairs=5):
    """PERF.md paired-step objective: interleave ONE step per arm so
    shared-box contention hits both sides of every pair; report the
    median per-arm step wall and the median per-pair delta (a - b, ms).
    Callers warm both arms first (compiles must never land in a pair)."""
    deltas, a_ms, b_ms = [], [], []
    for _ in range(max(1, int(pairs))):
        t0 = time.perf_counter()
        fn_a()
        t1 = time.perf_counter()
        fn_b()
        t2 = time.perf_counter()
        a, b = (t1 - t0) * 1e3, (t2 - t1) * 1e3
        a_ms.append(a)
        b_ms.append(b)
        deltas.append(a - b)

    def med(v):
        return sorted(v)[len(v) // 2]

    return {"a_ms": round(med(a_ms), 6), "b_ms": round(med(b_ms), 6),
            "delta_ms": round(med(deltas), 6)}


def search(raw_graph, pairs=5, top_k=3, include_quant=False,
           install_winner=True, configs=None):
    """Search the PassManager space for one graph and (optionally)
    install the winner.

    Flow: canonicalize → probe DEFAULT_PASSES (the baseline) → probe
    each candidate config (one AOT compile each, outputs parity-gated
    at ≤1e-6) → prune everything the cost ledger says is not plausibly
    faster → paired-step time the top ``top_k`` survivors against the
    baseline → the fastest strict improvement (wall AND ledger
    direction) is installed under ``graph:<canonical key>``.

    Returns a report dict; ``report["winner"]`` is None when nothing
    beat the baseline (the store is then left untouched — DEFAULT_PASSES
    was already optimal for this topology)."""
    canon = _g.canonicalize(raw_graph)
    cgraph = canon.graph
    key = _g.canonical_key(cgraph)
    leaves = example_leaves(cgraph)
    baseline = _probe(cgraph, _p.PassManager(), leaves,
                      {"passes": list(_p.DEFAULT_PASSES)})
    rows = []
    parity_rejects = 0
    cand_cfgs = list(configs) if configs is not None \
        else candidate_configs(include_quant)
    for cfg in cand_cfgs:
        try:
            probe = _probe(cgraph, _p.PassManager.from_config(cfg),
                           leaves, cfg)
        except Exception:
            continue  # config not buildable for this graph: skip, no crash
        if not _parity_ok(baseline.outputs, probe.outputs):
            parity_rejects += 1
            continue
        rows.append({"config": cfg, "config_key": config_key(cfg),
                     "cost": probe.cost, "probe": probe,
                     "n_nodes": probe.n_nodes})
    plausible = [r for r in rows
                 if _cost_plausible(r["cost"], baseline.cost)]
    timed = rank_candidates(plausible)[:max(0, int(top_k))]
    pruned = len(rows) - len(timed)
    # warm both arms (jit probes already executed once in _probe, but an
    # explicit warm step keeps any lazy backend work out of pair 0)
    baseline.step()
    results = []
    for r in timed:
        r["probe"].step()
        t = paired_step_ms(baseline.step, r["probe"].step, pairs=pairs)
        results.append({
            "config": r["config"], "config_key": r["config_key"],
            "cost": {k: r["cost"][k] for k in
                     ("flops", "bytes_accessed", "peak_hbm_bytes")},
            "baseline_step_ms": t["a_ms"], "tuned_step_ms": t["b_ms"],
            "delta_ms": t["delta_ms"],
        })
    # winner: fastest measured, but only a STRICT improvement on both
    # instruments — wall (median per-pair delta > 0) and the ledger
    # direction the acceptance contract asserts (bytes or peak HBM)
    winner = None
    for res in sorted(results, key=lambda r: (-r["delta_ms"],
                                              r["config_key"])):
        cc = res["cost"]
        if res["delta_ms"] > 0 and (
                cc["bytes_accessed"] < baseline.cost["bytes_accessed"]
                or cc["peak_hbm_bytes"] < baseline.cost["peak_hbm_bytes"]):
            winner = res
            break
    report = {
        "key": key,
        "baseline_cost": {k: baseline.cost[k] for k in
                          ("flops", "bytes_accessed", "peak_hbm_bytes")},
        "candidates": len(rows) + parity_rejects,
        "parity_rejects": parity_rejects,
        "pruned": pruned,
        "timed": results,
        "pairs": pairs,
        "winner": winner,
    }
    with _lock:
        _STATS["searches"] += 1
        _STATS["candidates"] += len(rows) + parity_rejects
        _STATS["pruned"] += pruned
        _STATS["timed"] += len(results)
        _STATS["parity_rejects"] += parity_rejects
        _STATS["last_search"] = {
            "key": key[:16], "candidates": report["candidates"],
            "pruned": pruned, "timed": len(results), "pairs": pairs,
            "winner": None if winner is None else winner["config_key"],
        }
    if winner is not None and install_winner:
        install(key, winner["config"],
                objective={"baseline_step_ms": winner["baseline_step_ms"],
                           "tuned_step_ms": winner["tuned_step_ms"],
                           "delta_ms": winner["delta_ms"],
                           "pairs": pairs},
                cost={"baseline": report["baseline_cost"],
                      "tuned": winner["cost"]})
    return report


# -------------------------------------------------------- serve buckets


def fit_buckets(size_counts, max_buckets=6, max_size=None):
    """Optimal bucket set for a measured request-size histogram:
    minimize total PAD ROWS (the waste ServeMetrics' per-bucket
    histograms surface) with at most ``max_buckets`` buckets, boundaries
    chosen from the observed sizes. Deterministic DP — same histogram,
    same buckets, any process. ``max_size`` (e.g. the current largest
    bucket) is always covered so retuning never shrinks admissible
    requests. Replaces the blind pow2 default when real traffic says
    otherwise."""
    counts = {int(s): int(c) for s, c in dict(size_counts).items()
              if int(s) > 0 and int(c) > 0}
    if max_size is not None:
        counts.setdefault(int(max_size), 0)
    if not counts:
        raise ValueError("fit_buckets needs a non-empty size histogram")
    sizes = sorted(counts)
    n = len(sizes)
    k = min(max(1, int(max_buckets)), n)
    # prefix sums for O(1) segment pad cost: covering sizes[j..i] with
    # bucket sizes[i] pads (sizes[i] - s) rows for each request of size s
    pc = [0] * (n + 1)
    psc = [0] * (n + 1)
    for i, s in enumerate(sizes):
        pc[i + 1] = pc[i] + counts[s]
        psc[i + 1] = psc[i] + counts[s] * s

    def seg(j, i):
        return sizes[i] * (pc[i + 1] - pc[j]) - (psc[i + 1] - psc[j])

    INF = float("inf")
    dp = [[INF] * (k + 1) for _ in range(n)]
    back = [[-1] * (k + 1) for _ in range(n)]
    for i in range(n):
        dp[i][1] = seg(0, i)
        for b in range(2, k + 1):
            for j in range(1, i + 1):
                c = dp[j - 1][b - 1]
                if c == INF:
                    continue
                c += seg(j, i)
                if c < dp[i][b]:
                    dp[i][b] = c
                    back[i][b] = j - 1
    best_b = min(range(1, k + 1), key=lambda b: (dp[n - 1][b], b))
    buckets = []
    i, b = n - 1, best_b
    while i >= 0 and b >= 1:
        buckets.append(sizes[i])
        i, b = back[i][b], b - 1
        if b == 0:
            break
    return tuple(sorted(buckets))


def tune_buckets(server, max_buckets=6, apply=True, install_record=True):
    """Fit a bucket set to a live server's measured request-size
    histogram (ServeMetrics), optionally rebuild the server on it
    (``ModelServer.retune_buckets`` — new pool, warm compile, batcher
    rewire), and persist the winner under ``serve:buckets:<name>``."""
    hist = server.metrics.request_rows()
    if not hist:
        raise ValueError(
            "no request-size history on %r — serve traffic (or replay a "
            "trace) before tuning buckets" % server.name)
    buckets = fit_buckets(hist, max_buckets=max_buckets,
                          max_size=server.buckets[-1])
    before = tuple(server.buckets)
    pad_before = _pad_rows(hist, before)
    pad_after = _pad_rows(hist, buckets)
    if install_record:
        get_store().put("serve:buckets:" + server.name, {
            "config": {"buckets": list(buckets)},
            "tuned_by": TUNED_BY + ".tune_buckets",
            "swept_at": _utcnow(), "backend": _backend_name(),
            "objective": {"pad_rows_before": pad_before,
                          "pad_rows_after": pad_after,
                          "requests": sum(hist.values())},
        })
    if apply and buckets != before:
        server.retune_buckets(buckets)
    return {"buckets": buckets, "before": before,
            "pad_rows_before": pad_before, "pad_rows_after": pad_after}


def _pad_rows(hist, buckets):
    bs = sorted(buckets)
    total = 0
    for s, c in hist.items():
        b = next((x for x in bs if x >= s), bs[-1])
        total += max(0, b - s) * c
    return total


# ------------------------------------------------------- bulk watermark


def tune_bulk_watermark(candidates=(0, 5, 15, 30, 60), rounds=8,
                        chain=24, shape=(64, 64), apply=False,
                        install_record=True):
    """Search the imperative bulk-window watermark
    (``MXNET_ENGINE_BULK_SIZE``) on a representative fusible op chain.
    Round-robin interleaved (one step per candidate per round — the
    paired-step discipline generalized to N arms), median step wall per
    candidate. The winner persists under ``engine:bulk_size``;
    ``apply=True`` also calls ``engine.set_bulk_size`` on it."""
    from .. import engine
    from .. import ndarray as nd

    candidates = tuple(dict.fromkeys(int(c) for c in candidates))

    def step(size):
        prev = engine.set_bulk_size(size)
        try:
            x = nd.ones(shape)
            for _ in range(chain):
                x = x * 1.0009765625 + 0.5
            x.asnumpy()
        finally:
            engine.set_bulk_size(prev)

    for c in candidates:  # warm: compile each watermark's window splits
        step(c)
    walls = {c: [] for c in candidates}
    for _ in range(max(1, int(rounds))):
        for c in candidates:
            t0 = time.perf_counter()
            step(c)
            walls[c].append((time.perf_counter() - t0) * 1e3)
    medians = {c: round(sorted(v)[len(v) // 2], 6)
               for c, v in walls.items()}
    winner = min(candidates, key=lambda c: (medians[c], c))
    if install_record:
        get_store().put("engine:bulk_size", {
            "config": {"bulk_size": winner},
            "tuned_by": TUNED_BY + ".tune_bulk_watermark",
            "swept_at": _utcnow(), "backend": _backend_name(),
            "objective": {"medians_ms": {str(c): medians[c]
                                         for c in candidates},
                          "rounds": rounds, "chain": chain},
        })
    if apply:
        engine.set_bulk_size(winner)
    return {"winner": winner, "medians_ms": medians}


# --------------------------------------------------- flash block tables

# VMEM is ~16 MB/core (pallas guide); candidates whose working set —
# Q-block resident + streamed K/V blocks (double-buffered) + fp32
# row-stat and accumulator scratch — exceeds a conservative budget are
# pruned before any kernel runs
_VMEM_BUDGET_BYTES = 12 * 2 ** 20
_FLASH_GRID = (128, 256, 512)


def flash_block_candidates(seq, head_dim, dtype_bytes=2,
                           vmem_budget=_VMEM_BUDGET_BYTES):
    """(block_q, block_k) candidates for one sequence length, pruned by
    the VMEM footprint model — the cost-model stage of the flash search
    (no hardware needed, deterministic)."""
    from ..ops.pallas import flash_attention as fa

    cands = []
    for bq in _FLASH_GRID:
        for bk in _FLASH_GRID:
            if bq > seq or bk > seq:
                continue
            # labels must time what they claim: skip non-divisor blocks
            # the kernel entry would silently shrink onto another label
            if fa._largest_divisor_block(seq, bq) != bq \
                    or fa._largest_divisor_block(seq, bk) != bk:
                continue
            footprint = (
                bq * head_dim * dtype_bytes          # resident Q block
                + 2 * 2 * bk * head_dim * dtype_bytes  # K+V, double-buffered
                + 2 * bq * fa.LANES * 4              # m/l row stats (fp32)
                + bq * head_dim * 4)                 # fp32 accumulator
            if footprint > vmem_budget:
                continue
            cands.append((bq, bk))
    return sorted(cands)


def tune_flash_blocks(seqs=(128, 256, 512, 2048), batch=1, heads=4,
                      dim=128, pairs=5, interpret=False, apply=False,
                      vmem_budget=_VMEM_BUDGET_BYTES):
    """Search flash-attention (block_q, block_k) per sequence bucket and
    write the winners through the SAME artifact writer flash_sweep uses
    (``flash_attention.write_block_artifact``) — retiring the hand-run
    table. TPU-gated: off-TPU the Pallas kernels only run under
    ``interpret=True`` (tests use tiny shapes there); timings from the
    interpreter are for plumbing only and are labelled as such."""
    import jax
    import jax.numpy as jnp

    from ..ops.pallas import flash_attention as fa

    if not interpret and _backend_name() != "tpu":
        raise RuntimeError(
            "flash block tuning needs a TPU backend (pass interpret=True "
            "only for plumbing tests — interpreter timings are not "
            "schedule evidence)")
    winners = {}
    rows = []
    for seq in seqs:
        cands = flash_block_candidates(seq, dim,
                                       vmem_budget=vmem_budget)
        if not cands:
            continue
        k1, k2, k3 = jax.random.split(jax.random.PRNGKey(0), 3)
        shape = (batch, heads, seq, dim)
        q = jax.random.normal(k1, shape, jnp.bfloat16)
        k = jax.random.normal(k2, shape, jnp.bfloat16)
        v = jax.random.normal(k3, shape, jnp.bfloat16)
        best = None
        for bq, bk in cands:
            def step(bq=bq, bk=bk):
                jax.block_until_ready(fa.flash_attention(
                    q, k, v, block_q=bq, block_k=bk,
                    interpret=interpret))

            step()  # warm (compile) outside the pairs
            if best is None:
                t0 = time.perf_counter()
                step()
                ms = (time.perf_counter() - t0) * 1e3
                best = {"blocks": (bq, bk), "ms": ms, "step": step}
                rows.append({"seq": seq, "block_q": bq, "block_k": bk,
                             "ms": round(ms, 4)})
                continue
            t = paired_step_ms(best["step"], step, pairs=pairs)
            rows.append({"seq": seq, "block_q": bq, "block_k": bk,
                         "ms": t["b_ms"]})
            if t["delta_ms"] > 0:  # incumbent median-slower: replace
                best = {"blocks": (bq, bk), "ms": t["b_ms"], "step": step}
        winners[seq] = best["blocks"]
    if not winners:
        raise ValueError("no timeable (seq, block) candidates")
    blocks = {s: list(winners[s]) for s in winners}
    blocks[0] = blocks[min(winners)]
    result = {"winners": {str(s): list(b) for s, b in winners.items()},
              "rows": rows, "interpret": interpret}
    if apply:
        result["artifact"] = fa.write_block_artifact(
            blocks,
            source="ir.tune.tune_flash_blocks",
            swept_at=_utcnow(),
            tuned_by=TUNED_BY + ".tune_flash_blocks"
            + (" (interpret — plumbing only)" if interpret else ""),
            backend=_backend_name())
    return result
