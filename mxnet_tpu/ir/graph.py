"""mxnet_tpu.ir.graph — ONE typed graph IR under all three captures.

The repo grew three parallel structural-graph representations — the bulk
window's ``LazyExpr`` DAG (engine/ndarray), the autograd tape's
``TapeNode`` region (autograd), and the ``Symbol`` DAG (symbol) — each
with its own cache-key scheme and its own lowering. This module is the
single canonical form they all convert into (Relay's "one typed IR, many
frontends" move, arXiv 1810.00952, applied to this stack's captures):

* a :class:`Graph` is immutable and *typed*: nodes carry
  ``(op, static attrs, input wiring)``, values carry interned
  ``(shape, dtype, sharding)`` avals via the signature interner below;
* wiring is the spec-int convention every capture already speaks —
  ``s >= 0`` is value slot ``s`` (node *i* with ``n_out`` outputs owns
  ``n_out`` consecutive slots), ``~li`` is graph leaf ``li`` (a program
  input);
* :func:`canonicalize` renumbers any capture's graph into a
  deterministic DFS-from-outputs form, and :func:`canonical_key` hashes
  that form content-addressed — identical math captured imperatively,
  on the tape, or symbolically produces the SAME key, so all three hit
  the same compiled program in ``ir.lower``'s cache.

The signature interner (``_sig_id``) and abstract-evaluation cache
(``_AVAL_CACHE`` / ``_infer_aval``) moved here from ``ndarray`` — they
were the per-capture key-assembly machinery and are now the one shared
implementation (``ndarray`` keeps aliases for its hot loop and for
back-compat). This module imports only ``base``/jax/numpy: every capture
layer can import it without cycles.
"""
from __future__ import annotations

import functools
import hashlib
import threading

import jax
import numpy as np

from ..base import (OP_REGISTRY, BoundedCache as _BoundedCache, _freeze,
                    env_cap as _env_cap)

__all__ = ["Node", "Graph", "GraphBuilder", "Canonical", "canonicalize",
           "canonical_key", "from_window", "from_symbol", "build_runner",
           "interner_stats"]


# ------------------------------------------------------------- interner
#
# Signature interning: a signature — (dtype, shape) for arrays, the
# python/numpy scalar TYPE for weak-typed scalar leaves — is replaced by
# a small process-global int everywhere the hot loops touch it (bulk
# window leaf_sigs, tape leaf wiring, aval-cache keys, IR graph leaves).
# Hashing int tuples is several times cheaper than hashing nested dtype
# tuples, and this runs per imperative op.
#
# The table is CAPPED (MXNET_SIG_INTERN_CAP; graphlint GL006): ids index
# into _SIG_LIST, so entries can never be evicted without invalidating
# every cache key built from them. Once the cap is hit, _sig_id returns
# None for NEW signatures and the capture layers fall back to eager
# dispatch for values carrying them — steady-state workloads (a bounded
# signature set) never notice; adversarial shape churn degrades
# gracefully instead of growing host memory without bound.
_SIG_IDS = {}
_SIG_LIST = []
_SIG_INTERN_CAP = _env_cap("MXNET_SIG_INTERN_CAP", 65536)

# Inserts are serialized (racecheck): two dispatcher threads interning
# the same fresh signature could both claim len(_SIG_LIST) as its id and
# leave _SIG_IDS pointing past the list. Hits stay lock-free — the dict
# probe is the per-op hot path; the lock is only taken on a miss.
_SIG_LOCK = threading.Lock()


def _sig_id(sig):
    i = _SIG_IDS.get(sig)
    if i is not None:
        return i
    with _SIG_LOCK:
        return _sig_id_locked(sig)


def _sig_id_locked(sig):
    # seam for analysis.concurrency's runtime race probe (inside the lock)
    i = _SIG_IDS.get(sig)
    if i is None:
        if len(_SIG_IDS) >= _SIG_INTERN_CAP:
            return None  # table full — caller bails to eager dispatch
        i = len(_SIG_LIST)
        _SIG_LIST.append(sig)
        _SIG_IDS[sig] = i  # publish only after the list holds the entry
    return i


def sig_value(i):
    """The interned signature behind id ``i``."""
    return _SIG_LIST[i]


def interner_stats():
    return {"entries": len(_SIG_IDS), "cap": _SIG_INTERN_CAP}


# (op, static-attrs key, input sig-ids) -> (output ShapeDtypeStruct, its
# sig-id), or None when the combo is not abstractly evaluable to ONE
# array (multi-output result — e.g. split/topk whose arity depends on
# kwargs — or eval_shape raised). One abstract evaluation per distinct
# combo while cached; the hot loops pay a dict probe. Capped
# (MXNET_AVAL_CACHE_CAP, insertion-order eviction — graphlint GL006):
# static-attr diversity is unbounded, a miss only re-runs eval_shape.
_AVAL_CACHE = _BoundedCache(_env_cap("MXNET_AVAL_CACHE_CAP", 65536))
_AVAL_MISS = object()


def _infer_aval(opdef, kwargs, in_sig_ids):
    """Abstract-evaluate one op from input signatures alone (a
    representative value stands in for scalar leaves: only the type can
    affect promotion, never the value). Returns the cache entry."""
    try:
        sigs = [_SIG_LIST[i] for i in in_sig_ids]
        ins = [jax.ShapeDtypeStruct(s[1], s[0]) if type(s) is tuple else s(1)
               for s in sigs]
        fn = (functools.partial(opdef.fn, **kwargs) if kwargs else opdef.fn)
        av = jax.eval_shape(fn, *ins)
    except Exception:
        return None  # let the eager path raise the real, well-located error
    if not isinstance(av, jax.ShapeDtypeStruct):
        return None
    sid = _sig_id((av.dtype, tuple(av.shape)))
    if sid is None:  # intern table at cap: mark combo non-lazy
        return None
    return (av, sid)


def infer_aval_cached(opname, static_key, kwargs, in_sigs, opdef=None):
    """Cached (aval, sig-id) for one op application, or None when not
    single-output evaluable — the one inference path shared by the bulk
    window (via ndarray's aliases) and the symbol builder."""
    key = (opname, static_key, tuple(in_sigs))
    entry = _AVAL_CACHE.get(key, _AVAL_MISS)
    if entry is _AVAL_MISS:
        entry = _AVAL_CACHE[key] = _infer_aval(
            opdef if opdef is not None else OP_REGISTRY[opname], kwargs,
            in_sigs)
    return entry


# ------------------------------------------------------------- the graph


class Node:
    """One typed IR node: a pure registry-op application.

    ``specs`` wire positional inputs (spec ints); ``kw_names``/
    ``kw_specs`` wire traced keyword inputs (the tape's rng-key arrays);
    ``static`` holds the non-traced attrs splatted into ``fn`` and
    ``static_key`` their frozen, hashable form. ``n_out`` slots are
    produced (flattened tree leaves for multi-output ops). ``aval``/
    ``sig`` describe the output when known (single-output nodes); passes
    that need types skip nodes without them. ``pinned`` marks nodes
    whose value slot is externally observed mid-program (tape probe
    injection sites) — rewrite passes must neither merge nor bypass
    them."""

    __slots__ = ("op", "fn", "static", "static_key", "specs", "kw_names",
                 "kw_specs", "n_out", "aval", "sig", "pinned")

    def __init__(self, op, fn, static, static_key, specs, kw_names=(),
                 kw_specs=(), n_out=1, aval=None, sig=None, pinned=False):
        self.op = op
        self.fn = fn
        self.static = static
        self.static_key = static_key
        self.specs = tuple(specs)
        self.kw_names = tuple(kw_names)
        self.kw_specs = tuple(kw_specs)
        self.n_out = n_out
        self.aval = aval
        self.sig = sig
        self.pinned = pinned

    def replace(self, **kw):
        d = {s: getattr(self, s) for s in self.__slots__}
        d.update(kw)
        return Node(**d)

    def ident(self):
        """Structural identity for keys/CSE: everything that determines
        the node's value given its inputs (fn is derived from op)."""
        return (self.op, self.static_key, self.specs, self.kw_names,
                self.kw_specs, self.n_out, self.pinned)


class Graph:
    """Immutable typed graph: ``nodes`` in a valid topological order,
    ``leaf_sigs`` (interned signature id per program input), ``outputs``
    (spec ints), and ``meta`` (pass annotations, e.g. the donation
    policy). Value slots number the flattened node outputs in node
    order."""

    __slots__ = ("nodes", "leaf_sigs", "outputs", "meta")

    def __init__(self, nodes, leaf_sigs, outputs, meta=None):
        self.nodes = tuple(nodes)
        self.leaf_sigs = tuple(leaf_sigs)
        self.outputs = tuple(outputs)
        self.meta = dict(meta or {})

    @property
    def n_nodes(self):
        return len(self.nodes)

    @property
    def n_edges(self):
        return sum(len(n.specs) + len(n.kw_specs) for n in self.nodes)

    def slot_bases(self):
        """First value slot of each node."""
        bases, s = [], 0
        for n in self.nodes:
            bases.append(s)
            s += n.n_out
        return bases

    def slot_owner(self):
        """slot index -> (node index, output position)."""
        own = {}
        s = 0
        for i, n in enumerate(self.nodes):
            for j in range(n.n_out):
                own[s] = (i, j)
                s += 1
        return own


class GraphBuilder:
    """Incremental Graph construction shared by the three capture
    converters. ``leaf`` interns a program input (deduped by caller
    identity), ``add`` appends a node and returns its FIRST value slot;
    ``build`` freezes the result."""

    def __init__(self):
        self.nodes = []
        self.leaf_sigs = []
        self._leaf_ids = {}
        self._nslots = 0

    def leaf(self, ident, sig=None, sig_id=None, untyped=False):
        """Spec int (~li) for a leaf, deduped by ``ident``; returns None
        when the signature interner is at cap (caller bails).
        ``untyped=True`` admits a leaf with no signature (sig entry
        None) — the structural-only form serve's per-bucket compilation
        uses; type-dependent passes skip what they can't see."""
        li = self._leaf_ids.get(ident)
        if li is None:
            if untyped:
                sid = None
            else:
                sid = sig_id if sig_id is not None else _sig_id(sig)
                if sid is None:
                    return None
            li = self._leaf_ids[ident] = len(self.leaf_sigs)
            self.leaf_sigs.append(sid)
        return ~li

    def add(self, op, fn, static, static_key, specs, kw_names=(),
            kw_specs=(), n_out=1, aval=None, sig=None, pinned=False):
        first = self._nslots
        self.nodes.append(Node(op, fn, static, static_key, specs, kw_names,
                               kw_specs, n_out, aval, sig, pinned))
        self._nslots += n_out
        return first

    @property
    def n_slots(self):
        return self._nslots

    def build(self, outputs, meta=None):
        return Graph(self.nodes, self.leaf_sigs, outputs, meta)


# ------------------------------------------------------- capture: window


def from_window(nodes, key_parts, leaf_sigs, out_slots):
    """Convert a flushed bulk window (``engine._BulkWindow`` contents at
    flush time) into a Graph. The window's creation order is already a
    topological order and its specs already speak the spec-int
    convention, so this is a typed re-wrap, not a walk: ``key_parts[i]``
    carries the frozen static attrs the incremental key build already
    computed."""
    return Graph(
        (Node(n.op, n.fn, n.static, kp[1], n.specs, aval=n._aval,
              sig=n._sigid) for n, kp in zip(nodes, key_parts)),
        leaf_sigs, out_slots)


# ------------------------------------------------------- capture: symbol

# symbol ops evaluated by dedicated _eval branches (control flow,
# grouping, host closures) — never representable as a single typed node
_SYM_UNSUPPORTED = frozenset(
    ("_group", "_item", "_cond", "_foreach", "_while", "_callable"))


class UnsupportedGraph(Exception):
    """Raised by the symbol converter for graphs the IR cannot represent
    (control flow, rng draws, multi-output ops) — callers fall back to
    the legacy per-capture lowering."""


def symbol_skeleton(roots):
    """Structural skeleton of a deterministic Symbol DAG: a list of
    ``(op, attrs, static_key, specs)`` steps over named leaves, plus the
    leaf (variable) names in first-use order and the output specs.
    Signature-independent — combine with runtime value signatures via
    :func:`from_symbol`. Raises :class:`UnsupportedGraph` for graphs the
    IR cannot represent."""
    steps = []
    leaf_names = []
    leaf_pos = {}
    memo = {}

    def visit(s):
        got = memo.get(id(s))
        if got is not None:
            return got
        if s._op is None:  # variable: a named leaf (shared by name)
            li = leaf_pos.get(s.name)
            if li is None:
                li = leaf_pos[s.name] = len(leaf_names)
                leaf_names.append(s.name)
            spec = ~li
            memo[id(s)] = spec
            return spec
        if s._op in _SYM_UNSUPPORTED:
            raise UnsupportedGraph(s._op)
        opdef = OP_REGISTRY.get(s._op)
        if opdef is None or opdef.needs_rng or opdef.n_outputs != 1:
            raise UnsupportedGraph(s._op)
        attrs = s._attrs
        if "key" in attrs or "out" in attrs:
            raise UnsupportedGraph("%s: traced attr" % s._op)
        try:
            static_key = _freeze(attrs)
            hash(static_key)
        except TypeError:
            raise UnsupportedGraph("%s: unhashable attrs" % s._op)
        specs = tuple(visit(i) for i in s._inputs)
        idx = len(steps)
        steps.append((s._op, attrs, static_key, specs))
        memo[id(s)] = idx
        return idx

    out_specs = tuple(visit(r) for r in roots)
    return steps, leaf_names, out_specs


def from_symbol(skeleton, leaf_sig_ids=None):
    """Build a typed Graph from a symbol skeleton and the interned
    signatures of the values bound to its leaves (eval-time); per-node
    avals are inferred through the shared aval cache. Raises
    :class:`UnsupportedGraph` when any node is not single-output
    evaluable at these signatures (the legacy eval path then raises the
    real, well-located error).

    ``leaf_sig_ids=None`` builds the STRUCTURAL-ONLY form (untyped
    leaves, no aval inference) — serve's per-bucket compilation path,
    where signatures arrive per bucket at jit time; type-dependent
    rewrites simply skip."""
    steps, leaf_names, out_specs = skeleton
    b = GraphBuilder()
    if leaf_sig_ids is None:
        for name in leaf_names:
            b.leaf(name, untyped=True)
        for op, attrs, static_key, specs in steps:
            b.add(op, OP_REGISTRY[op].fn, attrs, static_key, specs)
        return b.build(out_specs)
    for name, sid in zip(leaf_names, leaf_sig_ids):
        if b.leaf(name, sig_id=sid) is None:
            raise UnsupportedGraph("signature interner at cap")
    slot_sigs = []
    for op, attrs, static_key, specs in steps:
        opdef = OP_REGISTRY[op]
        in_sigs = tuple(leaf_sig_ids[~s] if s < 0 else slot_sigs[s]
                        for s in specs)
        entry = infer_aval_cached(op, static_key, attrs, in_sigs, opdef)
        if entry is None:
            raise UnsupportedGraph("%s: not single-output evaluable" % op)
        av, sid = entry
        b.add(op, opdef.fn, attrs, static_key, specs, aval=av, sig=sid)
        slot_sigs.append(sid)
    return b.build(out_specs)


# --------------------------------------------------------- canonical form


class Canonical:
    """Result of :func:`canonicalize`: the canonical graph plus the maps
    back to the capture's numbering. ``leaf_perm[j]`` is the ORIGINAL
    leaf index behind canonical leaf ``j``; ``slot_map`` maps original
    value slots to canonical slots (absent = unreachable, dropped)."""

    __slots__ = ("graph", "leaf_perm", "slot_map", "dropped_nodes")

    def __init__(self, graph, leaf_perm, slot_map, dropped_nodes):
        self.graph = graph
        self.leaf_perm = leaf_perm
        self.slot_map = slot_map
        self.dropped_nodes = dropped_nodes


def canonicalize(graph):
    """Renumber a capture-ordered graph into the canonical form: nodes in
    deterministic DFS-from-outputs post-order (inputs visited
    left-to-right), leaves renumbered by first use in that order,
    unreachable nodes and leaves dropped. Identical math captured by any
    frontend converges here — the content the key hashes."""
    owner = graph.slot_owner()
    nodes = graph.nodes
    order = []          # original node indices, canonical order
    state = {}          # original node idx -> 1 (on stack) / 2 (done)

    # iterative DFS: symbol/tape graphs can be deep (resnet-scale chains
    # overflow the recursion limit)
    for root in graph.outputs:
        if root < 0:
            continue
        stack = [owner[root][0]]
        while stack:
            ni = stack[-1]
            st = state.get(ni)
            if st == 2:
                stack.pop()
                continue
            if st == 1:
                state[ni] = 2
                order.append(ni)
                stack.pop()
                continue
            state[ni] = 1
            n = nodes[ni]
            # push children in REVERSE so the leftmost input completes
            # first (deterministic post-order)
            for s in reversed(n.specs + n.kw_specs):
                if s >= 0 and state.get(owner[s][0]) is None:
                    stack.append(owner[s][0])

    new_idx = {ni: k for k, ni in enumerate(order)}
    new_bases, s = [], 0
    for ni in order:
        new_bases.append(s)
        s += nodes[ni].n_out

    leaf_perm = []      # canonical leaf -> original leaf
    leaf_new = {}       # original leaf -> canonical leaf
    slot_map = {}       # original slot -> canonical slot

    def remap(spec):
        if spec >= 0:
            ni, j = owner[spec]
            return new_bases[new_idx[ni]] + j
        li = ~spec
        nl = leaf_new.get(li)
        if nl is None:
            nl = leaf_new[li] = len(leaf_perm)
            leaf_perm.append(li)
        return ~nl

    new_nodes = []
    for ni in order:
        n = nodes[ni]
        new_nodes.append(n.replace(
            specs=tuple(remap(s) for s in n.specs),
            kw_specs=tuple(remap(s) for s in n.kw_specs)))
    new_outputs = tuple(remap(s) for s in graph.outputs)
    for old, (ni, j) in owner.items():
        if ni in new_idx:
            slot_map[old] = new_bases[new_idx[ni]] + j
    lsigs = tuple(graph.leaf_sigs[li] for li in leaf_perm)
    cg = Graph(new_nodes, lsigs, new_outputs, graph.meta)
    return Canonical(cg, tuple(leaf_perm), slot_map,
                     len(nodes) - len(order))


def _render_sig(sig):
    """Process-stable rendering of an interned signature for the
    content-addressed key (intern IDS are process-local; the key must be
    byte-identical across processes)."""
    if sig is None:
        return ("u",)  # untyped leaf (structural-only graphs)
    if type(sig) is tuple:  # array: (dtype, shape)
        return ("a", str(np.dtype(sig[0])), tuple(sig[1]))
    return ("s", getattr(sig, "__name__", str(sig)))  # weak scalar type


def canonical_key(cgraph):
    """Content-addressed key of a CANONICAL graph: sha256 over a stable
    rendering of (node idents, leaf signatures, outputs). Same canonical
    graph → byte-identical key, in any process — the one cache key the
    bulk/tape/symbol schemes collapse into."""
    payload = ("irv1",
               tuple(n.ident() for n in cgraph.nodes),
               tuple(_render_sig(None if i is None else _SIG_LIST[i])
                     for i in cgraph.leaf_sigs),
               cgraph.outputs)
    return hashlib.sha256(repr(payload).encode()).hexdigest()


# ------------------------------------------------------------- execution


def build_runner(graph, probes=None):
    """Pure replay function of a Graph: ``run(leaf_vals, probe_vals)``
    evaluates nodes in order and returns the output tuple. ``probes``
    (value slot -> probe index) adds ``probe_vals[k]`` to a slot's value
    at its production site — the tape's intermediate-gradient injection
    points. The returned function is jax-traceable; lowering jits it
    through ``base._jit_backed``."""
    steps = [(n.fn, n.static, n.specs, n.kw_names, n.kw_specs, n.n_out,
              n.op) for n in graph.nodes]
    outputs = graph.outputs
    probe = dict(probes or {})

    def run(lv, tv=()):
        env = []
        for fn, static, specs, kwn, kws, n_out, op in steps:
            vals = [env[s] if s >= 0 else lv[~s] for s in specs]
            # named_scope stamps the IR node's op name into the HLO
            # metadata (op_name=...), so optimized-HLO sinks carry their
            # graph provenance end to end (tools/profile_hlo_map.py,
            # observability.costs). Trace-time only — zero runtime cost,
            # and invisible to the default lowered text the comp-cache
            # digests, so content keys are unchanged.
            with jax.named_scope(op):
                if kwn or static:
                    kw = {k: (env[s] if s >= 0 else lv[~s])
                          for k, s in zip(kwn, kws)}
                    r = fn(*vals, **kw, **static)
                else:
                    r = fn(*vals)
            flat = jax.tree_util.tree_leaves(r) if n_out != 1 else [r]
            for v in flat:
                pk = probe.get(len(env))
                env.append(v if pk is None else v + tv[pk])
        return tuple(env[s] if s >= 0 else lv[~s] for s in outputs)

    return run
