"""mxnet_tpu.ir — one typed graph IR and rewrite-pass pipeline under all
three captures (ROADMAP #4, the refactor that unlocks).

The bulk window's ``LazyExpr`` DAG, the autograd tape's structural
region, and the ``Symbol`` graph all convert into the same canonical
typed :class:`~mxnet_tpu.ir.graph.Graph` (nodes carry
``(op, static attrs, input wiring)``; values carry interned
``(shape, dtype)`` avals), run the same rewrite-pass pipeline (CSE,
constant folding, bf16 cast-sinking, dead-subgraph elimination, and the
donation-policy annotator — :mod:`mxnet_tpu.ir.passes`), and lower
through the same content-addressed cache to ONE compiled artifact per
canonical graph via ``base._jit_backed``
(:mod:`mxnet_tpu.ir.lower`) — so the persistent compilation store and
AOT snapshots apply unchanged, and identical math from any capture
shares one compiled program.

Relay's typed-IR + pass-pipeline design (arXiv 1810.00952) and TVM's
one-artifact-per-graph lowering (arXiv 1802.04799), applied to this
stack's three frontends.
"""
from . import graph, lower, passes, tune  # noqa: F401
from .graph import (Graph, GraphBuilder, Node, UnsupportedGraph,  # noqa: F401
                    build_runner, canonical_key, canonicalize, from_symbol,
                    from_window, symbol_skeleton)
from .lower import lower_forward, prepare, stats, tape_program  # noqa: F401
from .passes import DEFAULT_PASSES, PassManager, pass_stats  # noqa: F401

__all__ = ["Graph", "GraphBuilder", "Node", "UnsupportedGraph",
           "build_runner", "canonical_key", "canonicalize", "from_symbol",
           "from_window", "symbol_skeleton", "lower_forward", "prepare",
           "tape_program", "stats", "PassManager", "DEFAULT_PASSES",
           "pass_stats", "graph", "passes", "lower", "tune"]
