"""mxnet_tpu.ir.lower — one compiled artifact per canonical graph.

The collapse point of the three cache-key schemes: the bulk window, the
autograd tape, and the Symbol executors all land here with a typed
:class:`~mxnet_tpu.ir.graph.Graph`; lowering canonicalizes it, looks up
the content-addressed key in ONE shared cache (``base._IR_CACHE``,
``MXNET_IR_CACHE_CAP``), runs the rewrite-pass pipeline on a miss, and
jits the optimized replay through ``base._jit_backed`` — so the
persistent cross-process compilation store and the AOT snapshot layer
(mxnet_tpu.cache, PR 7) apply to every capture unchanged, and identical
math from ANY capture shares one compiled program (TVM's
one-artifact-per-graph lowering, arXiv 1802.04799).

Counter semantics are preserved per capture: a real program build bumps
the owning capture's compile counter (``engine.bulk_compile_counter`` /
``tape_compile_counter`` / ``symbol_compile_counter``) with the canonical
key as the watchdog note — a cache HIT from a different capture bumps
nothing, which is exactly the cross-capture dedup the counters now also
prove (tests assert "3 captures, 1 compile").
"""
from __future__ import annotations

import threading

from .. import base
from ..base import _jit_backed, _key_note
from . import graph as _g
from . import passes as _p

__all__ = ["lower_forward", "prepare", "tape_program", "stats",
           "reset_stats"]

_lock = threading.Lock()   # entry construction only — never the hit path

# build accounting for tools/diagnose.py, tools/ir_bench.py and the
# observability "ir" collector (fixed keys — GL006)
_BUILD_STATS = {"graph_builds": 0, "program_builds": 0,
                "tuned_builds": 0, "last_build": None}


class IREntry:
    """One canonical graph's cache entry: the pass-optimized graph, the
    capture maps, and every program lowered from it (``fwd`` for the
    forward captures; tape layouts key their own variants)."""

    __slots__ = ("key", "graph", "leaf_sel", "slot_fwd", "programs",
                 "nodes_canonical", "nodes_final", "edges_canonical",
                 "edges_final")

    def __init__(self, key, cgraph, pm=None):
        final, leaf_sel, slot_fwd = _p.optimize(cgraph, pm)
        self.key = key
        self.graph = final
        self.leaf_sel = leaf_sel      # final program arg j -> canonical leaf
        self.slot_fwd = slot_fwd      # canonical slot -> final spec (or None)
        self.programs = {}
        self.nodes_canonical = cgraph.n_nodes
        self.nodes_final = final.n_nodes
        self.edges_canonical = cgraph.n_edges
        self.edges_final = final.n_edges


def _tuned_pm(key):
    """The autotuned PassManager for this canonical key, or None for
    ``DEFAULT_PASSES``. Lazy and exception-guarded: the tuned-config
    store is an optimization, never a lowering dependency — a missing
    or broken store must lower exactly like the pre-tuner repo."""
    import sys

    t = sys.modules.get("mxnet_tpu.ir.tune")
    if t is None:
        from . import tune as t  # first lookup pays the import; ~ms
    try:
        return t.pass_manager_for(key)
    except Exception:
        return None


def _counter(kind):
    from .. import engine

    return {"bulk": engine.bulk_compile_counter,
            "tape": engine.tape_compile_counter,
            "symbol": engine.symbol_compile_counter}[kind]


def prepare(raw_graph):
    """(canonical, entry): canonicalize a capture's graph and get (or
    build) its shared cache entry. The entry build — passes included —
    runs once per canonical key; steady state is hash + dict hit."""
    canon = _g.canonicalize(raw_graph)
    key = _g.canonical_key(canon.graph)
    ent = base._IR_CACHE.get(key)
    if ent is None:
        with _lock:
            ent = base._IR_CACHE.get(key)
            if ent is None:
                pm = _tuned_pm(key)
                ent = base._IR_CACHE[key] = IREntry(key, canon.graph, pm)
                _BUILD_STATS["graph_builds"] += 1
                if pm is not None:
                    _BUILD_STATS["tuned_builds"] += 1
                _BUILD_STATS["last_build"] = {
                    "key": key[:16],
                    "tuned": pm is not None,
                    "nodes_captured": raw_graph.n_nodes,
                    "nodes_canonical": ent.nodes_canonical,
                    "nodes_final": ent.nodes_final,
                    "edges_canonical": ent.edges_canonical,
                    "edges_final": ent.edges_final,
                }
    return canon, ent


def lower_forward(raw_graph, kind, hint=None):
    """Lower a forward capture to ``(prog, arg_sel)``: ``prog`` is the
    jitted optimized program (shared across captures via the canonical
    key), ``arg_sel[j]`` the CAPTURE leaf index to pass as program arg
    ``j``. Only an actual program build bumps ``kind``'s compile
    counter."""
    canon, ent = prepare(raw_graph)
    prog = ent.programs.get("fwd")
    if prog is None:
        # build OUTSIDE the lock (racecheck GL013: a compile can take
        # seconds and would stall every other entry's first build); the
        # double-checked publish below keeps one winner, whose build is
        # the only one counted
        fresh = _jit_backed(_fwd_fn(ent.graph), tier=kind,
                            hint=hint or ("ir-" + kind))
        with _lock:
            prog = ent.programs.get("fwd")
            if prog is None:
                # note carries the CAPTURE kind + canonical key: watchdog
                # warnings name both the frontend and the offending graph
                _counter(kind).bump(note=_key_note(kind, ent.key))
                prog = ent.programs["fwd"] = fresh
                _BUILD_STATS["program_builds"] += 1
    sel = tuple(canon.leaf_perm[c] for c in ent.leaf_sel)
    return prog, sel


def _fwd_fn(final_graph):
    run = _g.build_runner(final_graph)

    def fwd(*leaf_vals):
        return run(leaf_vals)

    return fwd


def tape_program(ent, variant_key, builder, donate=()):
    """Cached jitted tape program over an entry's optimized graph.
    ``variant_key`` carries the head/grad/donation layout (canonical
    space — deterministic); ``builder()`` returns the pure program fn.
    A miss bumps ``engine.tape_compile_counter`` with the composite key
    as the watchdog note."""
    key = ("tape", ent.key, variant_key)
    prog = ent.programs.get(key)
    if prog is None:
        # build outside the lock, publish under it (racecheck GL013)
        fresh = _jit_backed(builder(), donate=tuple(donate) or None,
                            tier="tape", hint="tape")
        with _lock:
            prog = ent.programs.get(key)
            if prog is None:
                _counter("tape").bump(note=_key_note("tape", key))
                prog = ent.programs[key] = fresh
                _BUILD_STATS["program_builds"] += 1
    return prog


def program_count():
    """Live compiled programs across all canonical entries — the number
    the cross-capture dedup test pins to 1."""
    return sum(len(e.programs) for e in base._IR_CACHE.values()
               if isinstance(e, IREntry))


def stats():
    """The observability/diagnose "Graph IR" section payload."""
    return {
        "cache": {"entries": len(base._IR_CACHE),
                  "cap": base._IR_CACHE.cap,
                  "evictions": base._IR_CACHE.evictions,
                  "programs": program_count()},
        "interner": _g.interner_stats(),
        "builds": dict(_BUILD_STATS),
        "passes": _p.pass_stats(),
    }


def reset_stats():
    """Test/bench hook: zero the build tallies (cache stays warm)."""
    _BUILD_STATS["graph_builds"] = 0
    _BUILD_STATS["program_builds"] = 0
    _BUILD_STATS["tuned_builds"] = 0
    _BUILD_STATS["last_build"] = None
