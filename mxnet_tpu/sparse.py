"""Sparse arrays: CSR and RowSparse (ref: src/ndarray/ndarray.cc sparse paths,
python/mxnet/ndarray/sparse.py).

Design note: XLA:TPU has no native sparse kernels — the MXU wants dense tiles.
MXNet uses sparse mainly for (a) huge embedding gradients (row_sparse) and
(b) CSR feature matrices. The TPU-native stance: keep storage-format parity
and convert at the op boundary; row_sparse gradients are carried as
(indices, values) and applied with scatter-add (XLA fuses this well), which is
what lazy_update SGD does on the reference.
"""
from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from .ndarray import NDArray, invoke

__all__ = ["CSRNDArray", "RowSparseNDArray", "csr_matrix", "row_sparse_array",
           "dot"]


class CSRNDArray:
    stype = "csr"

    def __init__(self, data, indices, indptr, shape):
        self.data = data if isinstance(data, NDArray) else NDArray(jnp.asarray(data))
        self.indices = indices if isinstance(indices, NDArray) else NDArray(jnp.asarray(indices, jnp.int32))
        self.indptr = indptr if isinstance(indptr, NDArray) else NDArray(jnp.asarray(indptr, jnp.int32))
        self.shape = tuple(shape)

    @property
    def dtype(self):
        return self.data.dtype

    def asnumpy(self):
        return self.todense().asnumpy()

    def todense(self):
        n, m = self.shape
        indptr = self.indptr._data
        # row id per nnz via searchsorted on indptr
        nnz = self.data.shape[0]
        rows = jnp.searchsorted(indptr, jnp.arange(nnz), side="right") - 1
        dense = jnp.zeros(self.shape, self.data.dtype)
        dense = dense.at[rows, self.indices._data].add(self.data._data)
        return NDArray(dense)

    tostype = lambda self, stype: self.todense() if stype == "default" else self


class RowSparseNDArray:
    stype = "row_sparse"

    def __init__(self, data, indices, shape):
        self.data = data if isinstance(data, NDArray) else NDArray(jnp.asarray(data))
        self.indices = indices if isinstance(indices, NDArray) else NDArray(jnp.asarray(indices, jnp.int32))
        self.shape = tuple(shape)

    @property
    def dtype(self):
        return self.data.dtype

    def asnumpy(self):
        return self.todense().asnumpy()

    def todense(self):
        dense = jnp.zeros(self.shape, self.data.dtype)
        dense = dense.at[self.indices._data].add(self.data._data)
        return NDArray(dense)

    def tostype(self, stype):
        return self.todense() if stype == "default" else self


def csr_matrix(arg1, shape=None, ctx=None, dtype=None):
    if isinstance(arg1, tuple) and len(arg1) == 3:
        data, indices, indptr = arg1
        return CSRNDArray(data, indices, indptr, shape)
    a = np.asarray(arg1.asnumpy() if isinstance(arg1, NDArray) else arg1)
    indptr = [0]
    indices = []
    data = []
    for row in a:
        nz = np.nonzero(row)[0]
        indices.extend(nz.tolist())
        data.extend(row[nz].tolist())
        indptr.append(len(indices))
    return CSRNDArray(np.asarray(data, a.dtype), np.asarray(indices, np.int32),
                      np.asarray(indptr, np.int32), a.shape)


def row_sparse_array(arg1, shape=None, ctx=None, dtype=None):
    if isinstance(arg1, tuple) and len(arg1) == 2:
        data, indices = arg1
        return RowSparseNDArray(data, indices, shape)
    a = np.asarray(arg1.asnumpy() if isinstance(arg1, NDArray) else arg1)
    rows = np.nonzero(a.any(axis=tuple(range(1, a.ndim))))[0]
    return RowSparseNDArray(a[rows], rows.astype(np.int32), a.shape)


def dot(lhs, rhs, transpose_a=False, transpose_b=False):
    """csr × dense → dense (ref: src/operator/tensor/dot.cc sparse kernels).
    Converts at the boundary — dense matmul rides the MXU."""
    if isinstance(lhs, CSRNDArray):
        lhs = lhs.todense()
    if isinstance(rhs, (CSRNDArray, RowSparseNDArray)):
        rhs = rhs.todense()
    return invoke("dot", (lhs, rhs), {"transpose_a": transpose_a,
                                      "transpose_b": transpose_b})
