"""Sparse arrays: CSR and RowSparse (ref: src/ndarray/ndarray.cc sparse paths,
python/mxnet/ndarray/sparse.py, src/operator/tensor/dot.cc).

Design note: XLA:TPU has no native sparse kernels — the MXU wants dense tiles.
MXNet uses sparse mainly for (a) huge embedding gradients (row_sparse) and
(b) CSR feature matrices. The TPU-native stance:

* storage-format parity at the API level (CSRNDArray / RowSparseNDArray with
  data/indices/indptr, cast_storage, retain, tostype);
* csr x dense dot computed sparsely via segment-sum over nnz (no densify) —
  XLA lowers gather + segment_sum well, and nnz stays static per array so the
  kernel is jittable;
* row_sparse gradients carried as (indices, values) and applied with
  scatter-add / scatter row updates, which is what the reference's
  lazy_update SGD/Adam do (ref: src/operator/optimizer_op.cc SGDUpdateRsp).

Eager-path ops (cast_storage from dense, elemwise merges) use host nonzero /
unique — they run outside jit, like MXNet's sparse ops run on CPU.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from .base import register_op
from .ndarray import NDArray, invoke

__all__ = ["CSRNDArray", "RowSparseNDArray", "csr_matrix", "row_sparse_array",
           "dot", "cast_storage", "retain", "add", "subtract", "multiply",
           "elemwise_add", "elemwise_sub", "elemwise_mul", "add_n", "zeros",
           "array"]


def _as_jnp(x, dtype=None):
    if isinstance(x, NDArray):
        x = x._data
    a = jnp.asarray(x)
    return a.astype(dtype) if dtype is not None else a


class CSRNDArray:
    """Compressed sparse row matrix (ref: python/mxnet/ndarray/sparse.py
    CSRNDArray)."""

    stype = "csr"

    def __init__(self, data, indices, indptr, shape):
        self.data = data if isinstance(data, NDArray) else NDArray(jnp.asarray(data))
        self.indices = indices if isinstance(indices, NDArray) else NDArray(_as_jnp(indices, jnp.int32))
        self.indptr = indptr if isinstance(indptr, NDArray) else NDArray(_as_jnp(indptr, jnp.int32))
        self.shape = tuple(int(s) for s in shape)

    @property
    def dtype(self):
        return self.data.dtype

    @property
    def nnz(self):
        return int(self.data.shape[0])

    def _row_ids(self):
        """Row id per nnz via searchsorted on indptr — static-shape, jittable."""
        nnz = self.data.shape[0]
        return jnp.searchsorted(self.indptr._data, jnp.arange(nnz), side="right") - 1

    def asnumpy(self):
        return self.todense().asnumpy()

    def todense(self):
        dense = jnp.zeros(self.shape, self.data.dtype)
        dense = dense.at[self._row_ids(), self.indices._data].add(self.data._data)
        return NDArray(dense)

    def astype(self, dtype):
        return CSRNDArray(self.data.astype(dtype), self.indices, self.indptr, self.shape)

    def tostype(self, stype):
        return cast_storage(self, stype)

    def copyto(self, other):
        """Write our contents into ``other`` (ref: ndarray.py copyto semantics).
        Dense targets receive the densified matrix in place."""
        if getattr(other, "shape", self.shape) != self.shape:
            raise ValueError("copyto shape mismatch: %s vs %s"
                             % (self.shape, other.shape))
        if isinstance(other, CSRNDArray):
            other.data = NDArray(self.data._data)
            other.indices = NDArray(self.indices._data)
            other.indptr = NDArray(self.indptr._data)
            return other
        if isinstance(other, NDArray):
            other._data = self.todense()._data
            return other
        raise TypeError("cannot copyto %r" % (type(other),))

    def __getitem__(self, key):
        """Row slicing (contiguous), as the reference supports for CSR."""
        if isinstance(key, int):
            if not -self.shape[0] <= key < self.shape[0]:
                raise IndexError("row %d out of range for %s" % (key, self.shape))
            if key < 0:
                key += self.shape[0]
            key = slice(key, key + 1)
        start, stop, step = key.indices(self.shape[0])
        if step != 1:
            raise ValueError("CSR slicing requires step 1")
        if stop < start:
            raise ValueError("CSR slice %r is reversed/empty for %d rows"
                             % (key, self.shape[0]))
        indptr = np.asarray(self.indptr.asnumpy())
        lo, hi = int(indptr[start]), int(indptr[stop])
        new_indptr = indptr[start:stop + 1] - lo
        return CSRNDArray(NDArray(self.data._data[lo:hi]),
                          NDArray(self.indices._data[lo:hi]),
                          np.asarray(new_indptr, np.int32),
                          (stop - start, self.shape[1]))

    def __repr__(self):
        return "<CSRNDArray %s @%d nnz>" % (self.shape, self.nnz)


class RowSparseNDArray:
    """Row-sparse tensor: a subset of rows is stored densely
    (ref: python/mxnet/ndarray/sparse.py RowSparseNDArray)."""

    stype = "row_sparse"

    def __init__(self, data, indices, shape):
        self.data = data if isinstance(data, NDArray) else NDArray(jnp.asarray(data))
        self.indices = indices if isinstance(indices, NDArray) else NDArray(_as_jnp(indices, jnp.int32))
        self.shape = tuple(int(s) for s in shape)

    @property
    def dtype(self):
        return self.data.dtype

    @property
    def nnz_rows(self):
        return int(self.indices.shape[0])

    def asnumpy(self):
        return self.todense().asnumpy()

    def todense(self):
        dense = jnp.zeros(self.shape, self.data.dtype)
        dense = dense.at[self.indices._data].add(self.data._data)
        return NDArray(dense)

    def astype(self, dtype):
        return RowSparseNDArray(self.data.astype(dtype), self.indices, self.shape)

    def tostype(self, stype):
        return cast_storage(self, stype)

    def retain(self, indices):
        return retain(self, indices)

    def __repr__(self):
        return "<RowSparseNDArray %s @%d rows>" % (self.shape, self.nnz_rows)


def csr_matrix(arg1, shape=None, ctx=None, dtype=None):
    if isinstance(arg1, tuple) and len(arg1) == 3:
        data, indices, indptr = arg1
        return CSRNDArray(data, indices, indptr, shape)
    a = np.asarray(arg1.asnumpy() if isinstance(arg1, (NDArray, CSRNDArray, RowSparseNDArray)) else arg1)
    if dtype is not None:
        a = a.astype(dtype)
    indptr = [0]
    indices = []
    data = []
    for row in a:
        nz = np.nonzero(row)[0]
        indices.extend(nz.tolist())
        data.extend(row[nz].tolist())
        indptr.append(len(indices))
    return CSRNDArray(np.asarray(data, a.dtype), np.asarray(indices, np.int32),
                      np.asarray(indptr, np.int32), a.shape)


def row_sparse_array(arg1, shape=None, ctx=None, dtype=None):
    if isinstance(arg1, tuple) and len(arg1) == 2:
        data, indices = arg1
        return RowSparseNDArray(data, indices, shape)
    a = np.asarray(arg1.asnumpy() if isinstance(arg1, (NDArray, CSRNDArray, RowSparseNDArray)) else arg1)
    if dtype is not None:
        a = a.astype(dtype)
    rows = np.nonzero(a.any(axis=tuple(range(1, a.ndim))))[0]
    return RowSparseNDArray(a[rows], rows.astype(np.int32), a.shape)


def array(source_array, ctx=None, dtype=None):
    """sparse.array: preserve the input's storage type (ref:
    python/mxnet/ndarray/sparse.py array)."""
    if isinstance(source_array, CSRNDArray):
        return CSRNDArray(source_array.data, source_array.indices,
                          source_array.indptr, source_array.shape)
    if isinstance(source_array, RowSparseNDArray):
        return RowSparseNDArray(source_array.data, source_array.indices,
                                source_array.shape)
    return csr_matrix(source_array, dtype=dtype)


def zeros(stype, shape, ctx=None, dtype=None):
    """All-zero sparse array of the given storage type (ref:
    python/mxnet/ndarray/sparse.py zeros)."""
    dtype = dtype or np.float32
    shape = (shape,) if isinstance(shape, int) else tuple(shape)
    if stype == "csr":
        if len(shape) != 2:
            raise ValueError("csr storage requires a 2-D shape, got %s" % (shape,))
        return CSRNDArray(np.zeros((0,), dtype), np.zeros((0,), np.int32),
                          np.zeros((shape[0] + 1,), np.int32), shape)
    if stype == "row_sparse":
        return RowSparseNDArray(np.zeros((0,) + shape[1:], dtype),
                                np.zeros((0,), np.int32), shape)
    return NDArray(jnp.zeros(shape, dtype))


def dense_to_row_sparse_padded(arr):
    """Device-side dense → row_sparse for gradient carrying.

    Unlike :func:`row_sparse_array` (which pulls the full array to host), only
    a scalar — the touched-row count — syncs to host; the row list is built on
    device with ``jnp.nonzero(size=...)`` padded to the next power of two, so
    the optimizer's jitted lazy step compiles O(log n) distinct shapes instead
    of one per batch. Padding slots carry row index == nrows (out of bounds):
    the lazy stepper gathers them as zeros and drops them on scatter.
    """
    x = arr._data if isinstance(arr, NDArray) else jnp.asarray(arr)
    nrows = x.shape[0]
    rowmask = jnp.any(x != 0, axis=tuple(range(1, x.ndim)))
    count = int(rowmask.sum())  # single scalar device→host sync
    size = 1 if count == 0 else 1 << (count - 1).bit_length()
    size = min(size, nrows)
    size = max(size, count)
    (rows,) = jnp.nonzero(rowmask, size=size, fill_value=nrows)
    rows = rows.astype(jnp.int32)
    vals = jnp.take(x, rows, axis=0, mode="fill", fill_value=0)
    return RowSparseNDArray(NDArray(vals), NDArray(rows), x.shape)


def cast_storage(arr, stype):
    """Convert between 'default', 'csr', 'row_sparse'
    (ref: src/operator/tensor/cast_storage.cc)."""
    cur = getattr(arr, "stype", "default")
    if cur == stype:
        return arr
    if stype == "default":
        return arr.todense() if cur != "default" else arr
    dense = arr.todense() if cur != "default" else arr
    return csr_matrix(dense) if stype == "csr" else row_sparse_array(dense)


def retain(rsp, indices):
    """Keep only the requested rows of a RowSparseNDArray
    (ref: src/operator/tensor/sparse_retain.cc)."""
    if not isinstance(rsp, RowSparseNDArray):
        raise TypeError("retain expects RowSparseNDArray")
    want = np.asarray(indices.asnumpy() if isinstance(indices, NDArray) else indices,
                      np.int64)
    have = np.asarray(rsp.indices.asnumpy(), np.int64)
    keep_mask = np.isin(have, want)
    keep = np.nonzero(keep_mask)[0]
    return RowSparseNDArray(NDArray(rsp.data._data[keep]),
                            have[keep].astype(np.int32), rsp.shape)


def _merge_rsp(lhs, rhs, op):
    """Union-merge two RowSparseNDArrays row-wise (eager, host index math)."""
    li = np.asarray(lhs.indices.asnumpy(), np.int64)
    ri = np.asarray(rhs.indices.asnumpy(), np.int64)
    union = np.union1d(li, ri)
    lpos = np.searchsorted(union, li)
    rpos = np.searchsorted(union, ri)
    out = jnp.zeros((len(union),) + lhs.shape[1:], jnp.result_type(lhs.dtype, rhs.dtype))
    if op == "mul":
        a = out.at[lpos].add(lhs.data._data)
        b = jnp.zeros_like(out).at[rpos].add(rhs.data._data)
        merged = a * b
    else:
        merged = out.at[lpos].add(lhs.data._data)
        rdata = rhs.data._data if op == "add" else -rhs.data._data
        merged = merged.at[rpos].add(rdata)
    return RowSparseNDArray(NDArray(merged), union.astype(np.int32), lhs.shape)


def elemwise_add(lhs, rhs):
    """rsp+rsp → rsp; anything involving dense → dense
    (ref: src/operator/tensor/elemwise_binary_op_basic.cc)."""
    return _elemwise(lhs, rhs, "add")


def elemwise_sub(lhs, rhs):
    return _elemwise(lhs, rhs, "sub")


def elemwise_mul(lhs, rhs):
    return _elemwise(lhs, rhs, "mul")


def _elemwise(lhs, rhs, op):
    if isinstance(lhs, RowSparseNDArray) and isinstance(rhs, RowSparseNDArray):
        if lhs.shape != rhs.shape:
            raise ValueError("shape mismatch %s vs %s" % (lhs.shape, rhs.shape))
        return _merge_rsp(lhs, rhs, op)
    ld = lhs.todense() if hasattr(lhs, "todense") else lhs
    rd = rhs.todense() if hasattr(rhs, "todense") else rhs
    fn = {"add": lambda a, b: a + b, "sub": lambda a, b: a - b,
          "mul": lambda a, b: a * b}[op]
    return fn(ld, rd)


add = elemwise_add
subtract = elemwise_sub
multiply = elemwise_mul


def add_n(*arrs):
    """Sum of N arrays; stays row_sparse when all inputs are
    (ref: src/operator/tensor/elemwise_sum.cc)."""
    arrs = arrs[0] if len(arrs) == 1 and isinstance(arrs[0], (list, tuple)) else arrs
    out = arrs[0]
    for a in arrs[1:]:
        out = elemwise_add(out, a)
    return out


@register_op("_csr_dot")
def _csr_dot(vals, indices, indptr, rhs, *, nrows, ncols, transpose_a=False):
    """Sparse csr x dense without densifying the lhs.

    Forward: out[r, :] = sum_{nnz in row r} data * rhs[col, :] — a gather over
    rhs rows followed by segment_sum by row id. transpose_a scatters into
    out[col, :] instead. Shapes are static in nnz, so both paths jit cleanly.
    Registered as an op so autograd records it: gradients flow to vals and
    rhs through the gather/segment_sum VJPs.
    (ref: src/operator/tensor/dot.cc DotCsrDnsDns / DotCsrTDnsDns)
    """
    nnz = vals.shape[0]
    rows = jnp.searchsorted(indptr, jnp.arange(nnz), side="right") - 1
    cols = indices
    if rhs.ndim == 1:                            # matvec
        if transpose_a:
            out = jnp.zeros((ncols,), jnp.result_type(vals, rhs))
            return out.at[cols].add(vals * rhs[rows])
        return jax.ops.segment_sum(vals * rhs[cols], rows, num_segments=nrows)
    if transpose_a:
        # (csr.T @ rhs)[c] += v * rhs[r] for each nnz (r, c, v)
        contrib = vals[:, None] * rhs[rows]      # (nnz, k)
        out = jnp.zeros((ncols, rhs.shape[1]), contrib.dtype)
        return out.at[cols].add(contrib)
    contrib = vals[:, None] * rhs[cols]          # (nnz, k)
    return jax.ops.segment_sum(contrib, rows, num_segments=nrows)


def dot(lhs, rhs, transpose_a=False, transpose_b=False):
    """Sparse-aware dot (ref: src/operator/tensor/dot.cc).

    csr x dense runs a true sparse kernel (gather + segment_sum over nnz);
    other sparse combinations densify at the boundary so the matmul rides
    the MXU.
    """
    if isinstance(lhs, CSRNDArray) and isinstance(rhs, NDArray) and not transpose_b:
        return invoke("_csr_dot", (lhs.data, lhs.indices, lhs.indptr, rhs),
                      {"nrows": lhs.shape[0], "ncols": lhs.shape[1],
                       "transpose_a": transpose_a})
    if isinstance(lhs, (CSRNDArray, RowSparseNDArray)):
        lhs = lhs.todense()
    if isinstance(rhs, (CSRNDArray, RowSparseNDArray)):
        rhs = rhs.todense()
    return invoke("dot", (lhs, rhs), {"transpose_a": transpose_a,
                                      "transpose_b": transpose_b})
