"""Automatic mixed precision (ref: python/mxnet/amp/amp.py).

MXNet AMP casts whitelisted ops to fp16 with dynamic loss scaling. On TPU the
native format is bfloat16: same exponent range as fp32, so **no loss scaling is
needed** — AMP reduces to (1) casting matmul/conv-heavy params+activations to
bf16 and (2) keeping normalization params, reductions and optimizer master
weights in fp32 (optimizer multi_precision=True).
"""
from __future__ import annotations

import jax.numpy as jnp

_initialized = False


def init(target_dtype="bfloat16", target_precision_ops=None,
         conditional_fp32_ops=None, fp32_ops=None):
    global _initialized
    _initialized = True


def init_trainer(trainer):
    trainer.optimizer.multi_precision = True
    return trainer


def convert_hybrid_block(block, target_dtype="bfloat16", cast_optional_params=False):
    """Cast a Gluon block's params to bf16, keeping norm/stat params fp32
    (the standard TPU recipe)."""
    block.cast(target_dtype)
    _fix_norms(block)
    return block


convert_model = convert_hybrid_block


def _fix_norms(block):
    from .gluon.nn.basic_layers import BatchNorm, LayerNorm, InstanceNorm, GroupNorm

    if isinstance(block, (BatchNorm, LayerNorm, InstanceNorm, GroupNorm)):
        for p in block._reg_params.values():
            p.cast(jnp.float32)
    for child in block._children.values():
        _fix_norms(child)


class LossScaler:
    """API-compat only: bf16 needs no loss scaling (exponent range == fp32)."""

    def __init__(self, init_scale=1.0, **kwargs):
        self.loss_scale = 1.0

    def scale(self, loss):
        return loss

    def unscale(self, grads):
        return grads

    def update(self, overflow=False):
        pass


def scale_loss(loss, trainer):
    import contextlib

    @contextlib.contextmanager
    def ctx():
        yield loss if not isinstance(loss, (list, tuple)) else loss

    return ctx()
