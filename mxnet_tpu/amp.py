"""Automatic mixed precision (ref: python/mxnet/amp/amp.py).

MXNet AMP casts whitelisted ops to fp16 with dynamic loss scaling. On TPU the
native format is bfloat16: same exponent range as fp32, so **no loss scaling is
needed** — AMP reduces to (1) casting matmul/conv-heavy params+activations to
bf16 and (2) keeping normalization params, reductions and optimizer master
weights in fp32 (optimizer multi_precision=True).
"""
from __future__ import annotations

import jax.numpy as jnp

_initialized = False


def init(target_dtype="bfloat16", target_precision_ops=None,
         conditional_fp32_ops=None, fp32_ops=None):
    global _initialized
    _initialized = True


def init_trainer(trainer):
    trainer.optimizer.multi_precision = True
    return trainer


def convert_hybrid_block(block, target_dtype="bfloat16", cast_optional_params=False):
    """Cast a Gluon block's params to bf16, keeping norm/stat params fp32
    (the standard TPU recipe)."""
    block.cast(target_dtype)
    _fix_norms(block)
    return block


convert_model = convert_hybrid_block


def _fix_norms(block):
    from .gluon.nn.basic_layers import BatchNorm, LayerNorm, InstanceNorm, GroupNorm

    if isinstance(block, (BatchNorm, LayerNorm, InstanceNorm, GroupNorm)):
        for p in block._reg_params.values():
            p.cast(jnp.float32)
    for child in block._children.values():
        _fix_norms(child)


class LossScaler:
    """Dynamic loss scaling for fp16 (ref: python/mxnet/amp/loss_scaler.py).

    bf16 — the TPU default — needs NO scaling (exponent range == fp32), so
    ``convert_hybrid_block`` never engages this class; construct it with
    ``init_scale=1`` for a no-op. For float16 the upstream semantics apply:
    multiply the loss by ``loss_scale``, check grads with the fused
    ``multi_all_finite`` reduction, halve on overflow (skipping the step),
    and double again after ``scale_window`` clean steps."""

    def __init__(self, init_scale=2.0 ** 16, scale_factor=2.0,
                 scale_window=2000, min_scale=1.0, max_scale=2.0 ** 24,
                 **kwargs):
        self.loss_scale = float(init_scale)
        self._factor = float(scale_factor)
        self._window = int(scale_window)
        self._min, self._max = float(min_scale), float(max_scale)
        self._unskipped = 0

    def scale(self, loss):
        if self.loss_scale == 1.0:
            return loss
        return loss * self.loss_scale

    def unscale(self, grads):
        if self.loss_scale == 1.0:
            return grads
        inv = 1.0 / self.loss_scale
        one = lambda g: g * inv  # NDArray.__mul__ and jnp both handle this
        return one(grads) if not isinstance(grads, (list, tuple)) \
            else type(grads)(one(g) for g in grads)

    def has_overflow(self, grads):
        """True if any grad element is non-finite — ONE fused device
        reduction over the whole list (ops/legacy_ops.py multi_all_finite),
        a single scalar transfer instead of per-array syncs."""
        from .ops.legacy_ops import multi_all_finite
        if not isinstance(grads, (list, tuple)):
            grads = [grads]
        raw = [g._data if hasattr(g, "_data") else g for g in grads]
        if not raw:
            return False
        return bool(float(multi_all_finite(*raw)[0]) == 0.0)

    def update(self, overflow=False):
        """Post-step adjustment; returns the (possibly new) scale. The step
        itself should be SKIPPED by the caller when ``overflow`` — upstream
        trainers drop the update and only touch the scale."""
        if overflow:
            self.loss_scale = max(self.loss_scale / self._factor, self._min)
            self._unskipped = 0
        else:
            self._unskipped += 1
            if self._unskipped >= self._window:
                self.loss_scale = min(self.loss_scale * self._factor,
                                      self._max)
                self._unskipped = 0
        return self.loss_scale


def scale_loss(loss, trainer):
    import contextlib

    @contextlib.contextmanager
    def ctx():
        yield loss if not isinstance(loss, (list, tuple)) else loss

    return ctx()
