"""NumPy-compatible array API (ref: python/mxnet/numpy/multiarray.py — MXNet
2.x's ``mx.np``). Thin numpy-style signatures over the same NDArray/registry
machinery; exposed as ``mxnet_tpu.np``."""
from __future__ import annotations

import jax.numpy as jnp
import numpy as _onp

from .ndarray import (NDArray, array, invoke, zeros, ones, full, arange,  # noqa: F401
                      linspace, eye)
from .nd import random  # noqa: F401

newaxis = None
pi = _onp.pi
e = _onp.e
inf = _onp.inf
nan = _onp.nan
float32 = _onp.float32
float64 = _onp.float64
int32 = _onp.int32
int64 = _onp.int64
bfloat16 = jnp.bfloat16


def _ax(fn_name):
    def f(a, axis=None, keepdims=False):
        return invoke(fn_name, (a,), {"axis": axis, "keepdims": keepdims})

    f.__name__ = fn_name
    return f


sum = _ax("sum")
mean = _ax("mean")
prod = _ax("prod")
max = _ax("max")
min = _ax("min")
var = _ax("var")
std = _ax("std")
amax = max
amin = min


def argmax(a, axis=None):
    return invoke("argmax", (a,), {"axis": axis})


def argmin(a, axis=None):
    return invoke("argmin", (a,), {"axis": axis})


def _u(fn_name):
    def f(a):
        return invoke(fn_name, (a,), {})

    f.__name__ = fn_name
    return f


abs = _u("abs")
exp = _u("exp")
expm1 = _u("expm1")
log = _u("log")
log1p = _u("log1p")
log2 = _u("log2")
log10 = _u("log10")
sqrt = _u("sqrt")
cbrt = _u("cbrt")
square = _u("square")
sign = _u("sign")
ceil = _u("ceil")
floor = _u("floor")
sin = _u("sin")
cos = _u("cos")
tan = _u("tan")
arcsin = _u("arcsin")
arccos = _u("arccos")
arctan = _u("arctan")
sinh = _u("sinh")
cosh = _u("cosh")
tanh = _u("tanh")
negative = _u("negative")
reciprocal = _u("reciprocal")


def _b(fn_name):
    def f(a, b):
        return invoke(fn_name, (a, b), {})

    f.__name__ = fn_name
    return f


add = _b("add")
subtract = _b("subtract")
multiply = _b("multiply")
divide = _b("divide")
true_divide = divide
mod = _b("mod")
power = _b("power")
maximum = _b("maximum")
minimum = _b("minimum")
hypot = _b("hypot")
arctan2 = _b("arctan2")
equal = _b("equal")
not_equal = _b("not_equal")
greater = _b("greater")
greater_equal = _b("greater_equal")
less = _b("lesser")
less_equal = _b("lesser_equal")
logical_and = _b("logical_and")
logical_or = _b("logical_or")
logical_xor = _b("logical_xor")
dot = _b("matmul")
matmul = _b("matmul")


def where(cond, x, y):
    return invoke("where", (cond, x, y), {})


def clip(a, a_min, a_max):
    return invoke("clip", (a,), {"a_min": a_min, "a_max": a_max})


def reshape(a, newshape):
    return invoke("reshape", (a,), {"shape": tuple(newshape) if not isinstance(newshape, int) else (newshape,)})


def transpose(a, axes=None):
    return invoke("transpose", (a,), {"axes": tuple(axes) if axes else None})


def swapaxes(a, a1, a2):
    return invoke("swapaxes", (a,), {"dim1": a1, "dim2": a2})


def expand_dims(a, axis):
    return invoke("expand_dims", (a,), {"axis": axis})


def squeeze(a, axis=None):
    return invoke("squeeze", (a,), {"axis": axis})


def concatenate(seq, axis=0):
    return invoke("concat", tuple(seq), {"dim": axis})


def stack(seq, axis=0):
    return invoke("stack", tuple(seq), {"axis": axis})


def split(a, indices_or_sections, axis=0):
    return invoke("split", (a,), {"num_outputs": indices_or_sections, "axis": axis})


def tile(a, reps):
    return invoke("tile", (a,), {"reps": reps})


def repeat(a, repeats, axis=None):
    return invoke("repeat", (a,), {"repeats": repeats, "axis": axis})


def flip(a, axis):
    return invoke("flip", (a,), {"axis": axis})


def broadcast_to(a, shape):
    return invoke("broadcast_to", (a,), {"shape": tuple(shape)})


def cumsum(a, axis=None):
    return invoke("cumsum", (a,), {"axis": axis})


def sort(a, axis=-1):
    return invoke("sort", (a,), {"axis": axis})


def argsort(a, axis=-1):
    return invoke("argsort", (a,), {"axis": axis})


def take(a, indices, axis=0):
    return invoke("take", (a, indices), {"axis": axis})


def einsum(subscripts, *operands):
    vals = [o._data if isinstance(o, NDArray) else jnp.asarray(o) for o in operands]
    return NDArray(jnp.einsum(subscripts, *vals))


def asarray(a, dtype=None):
    return array(a, dtype=dtype)


def asnumpy(a):
    return a.asnumpy() if isinstance(a, NDArray) else _onp.asarray(a)


def zeros_like(a):
    return invoke("zeros_like", (a,), {})


def ones_like(a):
    return invoke("ones_like", (a,), {})


# ---------------------------------------------------------------------------
# Full-surface delegation (ref: python/mxnet/numpy/multiarray.py — MXNet 2.x
# implements the numpy API op-by-op in C++; here jax.numpy IS that API on
# TPU, so any name not explicitly wrapped above delegates to jnp with
# NDArray unwrap/wrap. Dispatch stays imperative-async: each call is an XLA
# op launch, exactly like the explicit wrappers.)
# ---------------------------------------------------------------------------

import types as _types


def _unwrap_tree(v):
    if isinstance(v, NDArray):
        return v._data
    if isinstance(v, (list, tuple)):
        return type(v)(_unwrap_tree(x) for x in v)
    return v


def _wrap_tree(v):
    if isinstance(v, jnp.ndarray) and not isinstance(v, _onp.ndarray):
        return NDArray(v)
    if isinstance(v, tuple):
        wrapped = [_wrap_tree(x) for x in v]
        if hasattr(v, "_fields"):  # namedtuple results (SVDResult, EighResult)
            return type(v)(*wrapped)
        return tuple(wrapped)
    if isinstance(v, list):
        return [_wrap_tree(x) for x in v]
    return v


def _delegate(fn, name):
    def g(*args, **kwargs):
        args = [_unwrap_tree(a) for a in args]
        kwargs = {k: _unwrap_tree(v) for k, v in kwargs.items()}
        return _wrap_tree(fn(*args, **kwargs))

    g.__name__ = name
    g.__qualname__ = name
    g.__doc__ = "mx.np.%s — delegates to jax.numpy.%s (TPU-native)." % (name, name)
    return g


class _DelegatedModule(_types.ModuleType):
    """Namespace view over a jnp submodule (linalg, fft) with NDArray I/O."""

    def __init__(self, base, name):
        super().__init__(name)
        self._base = base

    def __getattr__(self, name):
        fn = getattr(self._base, name)
        if not callable(fn):
            return fn
        g = _delegate(fn, name)
        setattr(self, name, g)
        return g


linalg = _DelegatedModule(jnp.linalg, "mxnet_tpu.np.linalg")
fft = _DelegatedModule(jnp.fft, "mxnet_tpu.np.fft")


# Names whose semantics are purely host-side (business-day calendars,
# structured/record arrays, file-backed memmaps, build introspection, legacy
# matrix/poly classes, utility submodules). jnp deliberately omits them; the
# TPU-native stance is that they never touch the device, so the classic numpy
# implementations ARE the correct ones (ref: python/mxnet/numpy/__init__.py
# re-exports the same names from its bundled numpy).
_ONP_HOST_NAMES = frozenset((
    "ScalarType", "asmatrix", "bmat", "broadcast", "busday_count",
    "busday_offset", "busdaycalendar", "char", "clongdouble", "complex256",
    "core", "ctypeslib", "datetime_as_string", "datetime_data", "dtypes",
    "emath", "exceptions", "f2py", "flatiter", "float128", "fromregex",
    "get_include", "getbufsize", "geterrcall", "info", "is_busday",
    "isfortran", "isnat", "lib", "ma", "matrix", "may_share_memory",
    "memmap", "nditer", "nested_iters", "poly1d", "polynomial", "putmask",
    "rec", "recarray", "record", "require", "sctypeDict", "setbufsize",
    "seterrcall", "shares_memory", "show_config", "show_runtime", "strings",
    "testing", "typecodes", "typing",
))


def __getattr__(name):
    import sys
    fn = getattr(jnp, name, None)
    if fn is None:
        if name in _ONP_HOST_NAMES and hasattr(_onp, name):
            v = getattr(_onp, name)
            setattr(sys.modules[__name__], name, v)
            return v
        raise AttributeError("mx.np has no attribute %r" % name)
    if not callable(fn) or isinstance(fn, type):
        return fn  # dtypes, constants
    g = _delegate(fn, name)
    setattr(sys.modules[__name__], name, g)
    return g


# host-semantics names jnp doesn't carry: delegate to classic numpy where the
# semantics are host-side anyway (IO, printing, error state), alias the rest
True_ = _onp.True_
False_ = _onp.False_
byte, ubyte, short, ushort = _onp.byte, _onp.ubyte, _onp.short, _onp.ushort
intc, uintc, intp, uintp = _onp.intc, _onp.uintc, _onp.intp, _onp.uintp
long, ulong = _onp.int64, _onp.uint64
longlong, ulonglong = _onp.longlong, _onp.ulonglong
half, longdouble = _onp.half, _onp.longdouble
str_, bytes_, void = _onp.str_, _onp.bytes_, _onp.void
datetime64, timedelta64 = _onp.datetime64, _onp.timedelta64
little_endian = _onp.little_endian


def asanyarray(a, dtype=None):
    return asarray(a, dtype=dtype)


def ascontiguousarray(a, dtype=None):
    return asarray(a, dtype=dtype)


def asfortranarray(a, dtype=None):
    return asarray(a, dtype=dtype)  # layout is XLA's concern on TPU


def asarray_chkfinite(a, dtype=None):
    out = asarray(a, dtype=dtype)
    if not _onp.isfinite(out.asnumpy()).all():
        raise ValueError("array must not contain infs or NaNs")
    return out


def copyto(dst, src):
    dst._data = asarray(src)._data


def in1d(ar1, ar2, **kwargs):
    return _wrap_tree(jnp.isin(_unwrap_tree(asarray(ar1)._data),
                               _unwrap_tree(asarray(ar2)._data), **kwargs))


def trapz(y, x=None, dx=1.0, axis=-1):
    return _wrap_tree(jnp.trapezoid(_unwrap_tree(asarray(y)._data),
                                    None if x is None else asarray(x)._data,
                                    dx=dx, axis=axis))


def row_stack(tup):
    return _wrap_tree(jnp.vstack([_unwrap_tree(asarray(t)._data)
                                  for t in tup]))


def _host_fn(name):
    fn = getattr(_onp, name)

    def g(*args, **kwargs):
        args = [a.asnumpy() if isinstance(a, NDArray) else a for a in args]
        return fn(*args, **kwargs)

    g.__name__ = name
    return g


# host-side IO / formatting — results feed back through asarray when needed
loadtxt = _host_fn("loadtxt")
genfromtxt = _host_fn("genfromtxt")
savetxt = _host_fn("savetxt")
savez_compressed = _host_fn("savez_compressed")
array2string = _host_fn("array2string")
format_float_positional = _host_fn("format_float_positional")
format_float_scientific = _host_fn("format_float_scientific")
base_repr = _host_fn("base_repr")
binary_repr = _host_fn("binary_repr")
typename = _host_fn("typename")
min_scalar_type = _host_fn("min_scalar_type")
common_type = _host_fn("common_type")
mintypecode = _host_fn("mintypecode")
real_if_close = _host_fn("real_if_close")
errstate = _onp.errstate
geterr, seterr = _onp.geterr, _onp.seterr
ndenumerate, ndindex = _onp.ndenumerate, _onp.ndindex
