"""NumPy-compatible array API (ref: python/mxnet/numpy/multiarray.py — MXNet
2.x's ``mx.np``). Thin numpy-style signatures over the same NDArray/registry
machinery; exposed as ``mxnet_tpu.np``."""
from __future__ import annotations

import jax.numpy as jnp
import numpy as _onp

from .ndarray import (NDArray, array, invoke, zeros, ones, full, arange,  # noqa: F401
                      linspace, eye)
from .nd import random  # noqa: F401

newaxis = None
pi = _onp.pi
e = _onp.e
inf = _onp.inf
nan = _onp.nan
float32 = _onp.float32
float64 = _onp.float64
int32 = _onp.int32
int64 = _onp.int64
bfloat16 = jnp.bfloat16


def _ax(fn_name):
    def f(a, axis=None, keepdims=False):
        return invoke(fn_name, (a,), {"axis": axis, "keepdims": keepdims})

    f.__name__ = fn_name
    return f


sum = _ax("sum")
mean = _ax("mean")
prod = _ax("prod")
max = _ax("max")
min = _ax("min")
var = _ax("var")
std = _ax("std")
amax = max
amin = min


def argmax(a, axis=None):
    return invoke("argmax", (a,), {"axis": axis})


def argmin(a, axis=None):
    return invoke("argmin", (a,), {"axis": axis})


def _u(fn_name):
    def f(a):
        return invoke(fn_name, (a,), {})

    f.__name__ = fn_name
    return f


abs = _u("abs")
exp = _u("exp")
expm1 = _u("expm1")
log = _u("log")
log1p = _u("log1p")
log2 = _u("log2")
log10 = _u("log10")
sqrt = _u("sqrt")
cbrt = _u("cbrt")
square = _u("square")
sign = _u("sign")
ceil = _u("ceil")
floor = _u("floor")
sin = _u("sin")
cos = _u("cos")
tan = _u("tan")
arcsin = _u("arcsin")
arccos = _u("arccos")
arctan = _u("arctan")
sinh = _u("sinh")
cosh = _u("cosh")
tanh = _u("tanh")
negative = _u("negative")
reciprocal = _u("reciprocal")


def _b(fn_name):
    def f(a, b):
        return invoke(fn_name, (a, b), {})

    f.__name__ = fn_name
    return f


add = _b("add")
subtract = _b("subtract")
multiply = _b("multiply")
divide = _b("divide")
true_divide = divide
mod = _b("mod")
power = _b("power")
maximum = _b("maximum")
minimum = _b("minimum")
hypot = _b("hypot")
arctan2 = _b("arctan2")
equal = _b("equal")
not_equal = _b("not_equal")
greater = _b("greater")
greater_equal = _b("greater_equal")
less = _b("lesser")
less_equal = _b("lesser_equal")
logical_and = _b("logical_and")
logical_or = _b("logical_or")
logical_xor = _b("logical_xor")
dot = _b("matmul")
matmul = _b("matmul")


def where(cond, x, y):
    return invoke("where", (cond, x, y), {})


def clip(a, a_min, a_max):
    return invoke("clip", (a,), {"a_min": a_min, "a_max": a_max})


def reshape(a, newshape):
    return invoke("reshape", (a,), {"shape": tuple(newshape) if not isinstance(newshape, int) else (newshape,)})


def transpose(a, axes=None):
    return invoke("transpose", (a,), {"axes": tuple(axes) if axes else None})


def swapaxes(a, a1, a2):
    return invoke("swapaxes", (a,), {"dim1": a1, "dim2": a2})


def expand_dims(a, axis):
    return invoke("expand_dims", (a,), {"axis": axis})


def squeeze(a, axis=None):
    return invoke("squeeze", (a,), {"axis": axis})


def concatenate(seq, axis=0):
    return invoke("concat", tuple(seq), {"dim": axis})


def stack(seq, axis=0):
    return invoke("stack", tuple(seq), {"axis": axis})


def split(a, indices_or_sections, axis=0):
    return invoke("split", (a,), {"num_outputs": indices_or_sections, "axis": axis})


def tile(a, reps):
    return invoke("tile", (a,), {"reps": reps})


def repeat(a, repeats, axis=None):
    return invoke("repeat", (a,), {"repeats": repeats, "axis": axis})


def flip(a, axis):
    return invoke("flip", (a,), {"axis": axis})


def broadcast_to(a, shape):
    return invoke("broadcast_to", (a,), {"shape": tuple(shape)})


def cumsum(a, axis=None):
    return invoke("cumsum", (a,), {"axis": axis})


def sort(a, axis=-1):
    return invoke("sort", (a,), {"axis": axis})


def argsort(a, axis=-1):
    return invoke("argsort", (a,), {"axis": axis})


def take(a, indices, axis=0):
    return invoke("take", (a, indices), {"axis": axis})


def einsum(subscripts, *operands):
    vals = [o._data if isinstance(o, NDArray) else jnp.asarray(o) for o in operands]
    return NDArray(jnp.einsum(subscripts, *vals))


def asarray(a, dtype=None):
    return array(a, dtype=dtype)


def asnumpy(a):
    return a.asnumpy() if isinstance(a, NDArray) else _onp.asarray(a)


def zeros_like(a):
    return invoke("zeros_like", (a,), {})


def ones_like(a):
    return invoke("ones_like", (a,), {})
