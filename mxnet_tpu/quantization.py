"""int8/fp8 quantization (ref: src/operator/quantization/*.cc, python/mxnet/
contrib/quantization.py).

MXNet's int8 path targets MKLDNN/TensorRT kernels with calibrated ranges.
TPU-native: symmetric per-channel quantized weights + dynamic per-tensor
quantized activations, accumulating on the MXU via ``preferred_element_type``
(int32 for int8, fp32 for fp8), rescaled in fp32 — the standard XLA low-bit
inference recipe. ``quantize_model`` swaps eligible Dense/Conv2D layers
in-place for inference; the swapped twins register their quantized weights as
grad-less Parameters so checkpoints and serve snapshots round-trip bit-exactly.

Modes: ``int8`` (always available), ``e4m3``/``e5m2`` (fp8, capability-probed
per jax build like the flash-attention gate — see :func:`fp8_supported`).
The serving-facing façade lives in :mod:`mxnet_tpu.quant`.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from .base import register_op
from .gluon import nn
from .gluon.block import HybridBlock
from .ndarray import NDArray

__all__ = ["quantize", "dequantize", "quantize_weight",
           "quantized_fully_connected", "quantized_conv", "QuantizedDense",
           "QuantizedConv2D", "quantize_model", "calibrate_model",
           "fp8_supported", "quant_dtype", "stats"]

# symmetric-quantization ranges per mode; fp8 qmax values are the finite
# maxima of the respective formats (e4m3: 448, e5m2: 57344)
_QMAX = {"int8": 127.0, "e4m3": 448.0, "e5m2": 57344.0}
_FP8_NAMES = {"e4m3": "float8_e4m3fn", "e5m2": "float8_e5m2"}

# capability-probe cache (int8 seed keeps the dict non-empty by construction;
# fp8 entries fill in lazily per probed mode)
_FP8_SUPPORT = {"int8": True}

# subsystem telemetry read by observability's "quant" collector (fixed keys;
# quantize_model/calibrate_model update them in place)
_QUANT_STATS = {
    "quantized_layers": 0,
    "weight_bytes_quantized": 0,
    "weight_bytes_fp32": 0,
    "calibrated_layers": 0,
    "calib_mode": "none",
    "mode": "none",
}


def stats():
    """Quantization telemetry snapshot (observability ``quant`` section)."""
    return dict(_QUANT_STATS)


def fp8_supported(mode="e4m3"):
    """True when this jax build can run an fp8 ``dot_general`` for ``mode``
    (``e4m3``/``e5m2``). Probed once with a tiny eager matmul and cached —
    the same lazy capability-gate pattern as the flash-attention probe."""
    got = _FP8_SUPPORT.get(mode)
    if got is not None:
        return got
    ok = False
    name = _FP8_NAMES.get(mode)
    if name is not None and hasattr(jnp, name):
        try:
            dt = getattr(jnp, name)
            a = jnp.ones((2, 2), dt)
            out = jax.lax.dot_general(a, a, (((1,), (1,)), ((), ())),
                                      preferred_element_type=jnp.float32)
            ok = bool(np.asarray(out).shape == (2, 2))
        except Exception:
            ok = False
    _FP8_SUPPORT[mode] = ok
    return ok


def quant_dtype(mode):
    """The storage dtype for a quantization mode."""
    if mode == "int8":
        return jnp.int8
    name = _FP8_NAMES.get(mode)
    if name is None:
        raise ValueError("quantization mode must be one of %s, got %r"
                         % (sorted(_QMAX), mode))
    dt = getattr(jnp, name, None)
    if dt is None:
        raise RuntimeError("this jax build has no %s dtype — use mode='int8'"
                           % name)
    return dt


def _check_mode(mode):
    if mode not in _QMAX:
        raise ValueError("quantization mode must be one of %s, got %r"
                         % (sorted(_QMAX), mode))
    if mode != "int8" and not fp8_supported(mode):
        raise RuntimeError(
            "fp8 mode %r unsupported by this jax build/backend (capability "
            "probe failed) — use mode='int8'" % mode)


def _dtype_qparams(dt):
    """(qmax, integral) for a quantized storage dtype — dt is a static
    attribute of the weight array, so branching on it is trace-safe."""
    dt = np.dtype(dt)
    if dt == np.dtype(np.int8):
        return 127.0, True
    for mode, name in _FP8_NAMES.items():
        if hasattr(jnp, name) and dt == np.dtype(getattr(jnp, name)):
            return _QMAX[mode], False
    raise TypeError("unsupported quantized weight dtype %r" % (dt,))


@register_op("contrib_quantize", nondiff=True, n_outputs=2)
def quantize(x, *, axis=None):
    """Symmetric int8: returns (q, scale). axis=None → per-tensor;
    axis=i → per-slice along dim i (ref: quantize_v2-inl.h)."""
    if axis is None:
        amax = jnp.max(jnp.abs(x))
    else:
        red = tuple(d for d in range(x.ndim) if d != axis)
        amax = jnp.max(jnp.abs(x), axis=red, keepdims=True)
    scale = jnp.maximum(amax, 1e-8) / 127.0
    q = jnp.clip(jnp.round(x / scale), -127, 127).astype(jnp.int8)
    return q, scale


@register_op("contrib_dequantize", nondiff=True)
def dequantize(q, scale):
    return q.astype(jnp.float32) * scale


def quantize_weight(w, axis=0, mode="int8"):
    """Eager symmetric per-slice weight quantization: (q, scale) with scale
    keeping dims along ``axis``. int8 rounds; fp8 casts (the format's own
    mantissa rounding applies)."""
    red = tuple(d for d in range(w.ndim) if d != axis)
    amax = jnp.max(jnp.abs(w), axis=red, keepdims=True)
    qmax = _QMAX[mode]
    scale = jnp.maximum(amax, 1e-8) / qmax
    if mode == "int8":
        q = jnp.clip(jnp.round(w / scale), -127, 127).astype(jnp.int8)
    else:
        q = jnp.clip(w / scale, -qmax, qmax).astype(quant_dtype(mode))
    return q, scale


def _quantize_act(x, x_scale, dt, qmax, integral):
    """Dynamic (x_scale=None) or static (calibrated scale) quantized
    activations in the weight's storage dtype."""
    if x_scale is None:
        amax = jnp.max(jnp.abs(x))
        x_scale = jnp.maximum(amax, 1e-8) / qmax
    if integral:
        qx = jnp.clip(jnp.round(x / x_scale), -qmax, qmax).astype(dt)
    else:
        qx = jnp.clip(x / x_scale, -qmax, qmax).astype(dt)
    return qx, x_scale


@register_op("quantized_fully_connected", nondiff=True)
def quantized_fully_connected(x, qweight, w_scale, bias=None, *, x_scale=None):
    """x fp → quantized (dynamic per-tensor, or static when a calibrated
    x_scale is given); low-bit matmul accumulated on the MXU — int32 for
    int8 weights, fp32 for fp8. qweight: (out, in) int8/fp8;
    w_scale: (out, 1) fp32."""
    qmax, integral = _dtype_qparams(qweight.dtype)
    qx, x_scale = _quantize_act(x, x_scale, qweight.dtype, qmax, integral)
    acc = jax.lax.dot_general(
        qx, qweight, (((qx.ndim - 1,), (1,)), ((), ())),
        preferred_element_type=jnp.int32 if integral else jnp.float32)
    y = acc.astype(jnp.float32) * (x_scale * w_scale.reshape(-1))
    if bias is not None:
        y = y + bias
    return y


@register_op("quantized_conv", nondiff=True)
def quantized_conv(x, qweight, w_scale, bias=None, *, stride=1, pad=0, dilate=1,
                   num_group=1, x_scale=None):
    """Quantized convolution (ref: src/operator/quantization/
    quantized_conv.cc — the cuDNN int8x4 path). Per-tensor quantized
    activations (dynamic or calibrated-static) × per-output-channel quantized
    weights, MXU accumulation (int32 for int8, fp32 for fp8), fp32 rescale.
    qweight: (O, I, *K); w_scale: (O, 1, 1, ...) fp32."""
    from .ops.functional import _pair

    nd = x.ndim - 2
    stride, pad, dilate = _pair(stride, nd), _pair(pad, nd), _pair(dilate, nd)
    qmax, integral = _dtype_qparams(qweight.dtype)
    qx, x_scale = _quantize_act(x, x_scale, qweight.dtype, qmax, integral)
    spatial = "DHW"[-nd:]
    lhs = "NC" + spatial
    dn = jax.lax.conv_dimension_numbers(x.shape, qweight.shape,
                                        (lhs, "OI" + spatial, lhs))
    acc = jax.lax.conv_general_dilated(
        qx, qweight, window_strides=stride, padding=[(p, p) for p in pad],
        rhs_dilation=dilate, dimension_numbers=dn, feature_group_count=num_group,
        preferred_element_type=jnp.int32 if integral else jnp.float32)
    oscale = (x_scale * w_scale.reshape(-1)).reshape((1, -1) + (1,) * nd)
    y = acc.astype(jnp.float32) * oscale
    if bias is not None:
        y = y + bias.reshape((1, -1) + (1,) * nd)
    return y


class _LayerCollector:
    """Records input-activation statistics during calibration forwards
    (ref: contrib/quantization.py _LayerOutputMinMaxCollector /
    _LayerHistogramCollector)."""

    def __init__(self, mode="naive", num_bins=8001):
        self.mode = mode
        self.num_bins = num_bins
        self.amax = 0.0
        self.hist = None          # allocated in pass 2 (entropy mode)
        self.phase = 1

    def collect(self, x):
        if isinstance(x, NDArray):
            a = x.asnumpy()
        else:
            a = np.asarray(x)
        a = np.abs(a.astype(np.float32)).ravel()
        if self.phase == 1:
            self.amax = max(self.amax, float(a.max(initial=0.0)))
        else:
            h, _ = np.histogram(a, bins=self.num_bins, range=(0.0, self.amax))
            self.hist = h if self.hist is None else self.hist + h

    def threshold(self):
        if self.mode == "naive" or self.hist is None:
            return self.amax
        return _optimal_threshold(self.hist, self.amax)


def _smooth_distribution(d, eps=1e-4):
    """Move eps mass onto zero entries so KL stays finite (ref:
    contrib/quantization.py _smooth_distribution)."""
    is_zero = d == 0
    n_zero = int(is_zero.sum())
    n_nonzero = d.size - n_zero
    if n_zero == 0 or n_nonzero == 0:
        return d
    eps1 = eps * n_zero / n_nonzero
    # floor at eps so entries smaller than the deducted mass stay positive
    return np.where(is_zero, eps, np.maximum(d - eps1 * (d > 0), eps))


def _optimal_threshold(hist, amax, num_quantized_bins=255):
    """KL-divergence-minimizing clip threshold (ref: contrib/quantization.py
    _get_optimal_threshold, the TensorRT entropy-calibration scheme). For each
    candidate threshold: the reference distribution p is the clipped histogram
    with the clipped-away outlier mass folded into its edge bin; q is the
    255-level quantization of the UNFOLDED clipped histogram — so clipping
    cost appears as p/q divergence at the edge rather than being free."""
    num_bins = hist.size
    if amax <= 0 or hist.sum() == 0:
        return amax
    best_kl, best_i = np.inf, num_bins
    hist = hist.astype(np.float64)
    for i in range(num_quantized_bins, num_bins + 1,
                   max(1, (num_bins - num_quantized_bins) // 128)):
        sliced = hist[:i]
        if sliced.sum() == 0:
            continue
        p = sliced.copy()
        p[-1] += hist[i:].sum()             # reference keeps the clipped mass
        # quantize the clipped histogram into 255 coarse bins, spreading each
        # coarse bin's mass uniformly over its NONZERO fine bins
        idx = (np.arange(i) * num_quantized_bins // i).clip(
            0, num_quantized_bins - 1)
        q_coarse = np.bincount(idx, weights=sliced, minlength=num_quantized_bins)
        nz = (sliced != 0).astype(np.float64)
        nz_count = np.bincount(idx, weights=nz, minlength=num_quantized_bins)
        q = np.where(nz > 0,
                     q_coarse[idx] / np.maximum(nz_count[idx], 1.0), 0.0)
        p = _smooth_distribution(p / p.sum())
        q = _smooth_distribution(q / max(q.sum(), 1e-12))
        kl = float(np.sum(p * np.log(p / q)))
        if kl < best_kl:
            best_kl, best_i = kl, i
    return amax * best_i / num_bins


class QuantizedDense(HybridBlock):
    """Inference-only Dense with pre-quantized int8/fp8 weights.

    ``qweight``/``w_scale``/``bias`` are registered as grad-less Parameters
    (not raw jnp attributes), so ``save_parameters``/``export``/
    ``serve.snapshot`` round-trip the quantized net bit-exactly and the
    serving param store picks them up like any other weight."""

    def __init__(self, dense: nn.Dense, mode="int8", **kwargs):
        super().__init__(prefix=dense.prefix, **kwargs)
        _check_mode(mode)
        w = dense.weight.data()._data.astype(jnp.float32)
        qw, ws = quantize_weight(w, axis=0, mode=mode)
        self._mode = mode
        self.qweight = self.params.get("qweight", shape=tuple(qw.shape),
                                       dtype=quant_dtype(mode),
                                       differentiable=False)
        self.qweight.set_data(NDArray(qw))
        self.w_scale = self.params.get("w_scale", shape=tuple(ws.shape),
                                       dtype="float32", differentiable=False)
        self.w_scale.set_data(NDArray(jnp.asarray(ws, jnp.float32)))
        if hasattr(dense, "bias") and dense.bias is not None:
            b = dense.bias.data()._data.astype(jnp.float32)
            self.bias = self.params.get("bias", shape=tuple(b.shape),
                                        dtype="float32", differentiable=False)
            self.bias.set_data(NDArray(b))
        self._flatten = dense._flatten
        self._act = dense.act
        self._x_scale = None      # static activation scale after calibration
        self._collector = None

    def hybrid_forward(self, F, x, qweight, w_scale, bias=None):
        if self._flatten:
            x = F.flatten(x)  # Dense(flatten=True) semantics, e.g. pooled NCHW
        if self._collector is not None:
            self._collector.collect(x)
        y = F.quantized_fully_connected(x, qweight, w_scale, bias,
                                        x_scale=self._x_scale)
        if self._act is not None:
            y = self._act(y)
        return y


class QuantizedConv2D(HybridBlock):
    """Inference-only Conv2D with pre-quantized per-output-channel weights
    (ref: quantized_conv.cc). Grouped convs keep the same layout. Weights
    live in grad-less Parameters — see :class:`QuantizedDense`."""

    def __init__(self, conv, mode="int8", **kwargs):
        super().__init__(prefix=conv.prefix, **kwargs)
        _check_mode(mode)
        w = conv.weight.data()._data.astype(jnp.float32)
        qw, ws = quantize_weight(w, axis=0, mode=mode)
        self._mode = mode
        self.qweight = self.params.get("qweight", shape=tuple(qw.shape),
                                       dtype=quant_dtype(mode),
                                       differentiable=False)
        self.qweight.set_data(NDArray(qw))
        self.w_scale = self.params.get("w_scale", shape=tuple(ws.shape),
                                       dtype="float32", differentiable=False)
        self.w_scale.set_data(NDArray(jnp.asarray(ws, jnp.float32)))
        if getattr(conv, "bias", None) is not None:
            b = conv.bias.data()._data.astype(jnp.float32)
            self.bias = self.params.get("bias", shape=tuple(b.shape),
                                        dtype="float32", differentiable=False)
            self.bias.set_data(NDArray(b))
        k = conv._kwargs
        self._conv_kw = dict(stride=k["stride"], pad=k["pad"], dilate=k["dilate"],
                             num_group=k["num_group"])
        self._act = conv.act
        self._x_scale = None
        self._collector = None

    def hybrid_forward(self, F, x, qweight, w_scale, bias=None):
        if self._collector is not None:
            self._collector.collect(x)
        y = F.quantized_conv(x, qweight, w_scale, bias,
                             x_scale=self._x_scale, **self._conv_kw)
        if self._act is not None:
            y = self._act(y)
        return y


def _quantized_layers(block, out):
    for child in block._children.values():
        if isinstance(child, (QuantizedDense, QuantizedConv2D)):
            out.append(child)
        else:
            _quantized_layers(child, out)
    return out


def _hybrid_blocks(block, out):
    if isinstance(block, HybridBlock):
        out.append(block)
    for child in block._children.values():
        _hybrid_blocks(child, out)
    return out


def _invalidate_execs(block):
    """Drop every compiled executable in the subtree. Swapping a child on an
    already-hybridized block (quantize_model) or freezing a new static
    activation scale (calibrate_model) changes the traced program; a stale
    ``_cached_execs`` entry would silently keep running the old fp32 code."""
    for b in _hybrid_blocks(block, []):
        b._cached_execs = {}


def calibrate_model(block, calib_data, mode="naive", num_bins=8001):
    """Freeze static activation scales from calibration batches (ref:
    contrib/quantization.py calib_mode='naive'|'entropy').

    ``calib_data``: iterable of input batches (materialized to a list so
    entropy's second histogram pass sees the same batches); each element is
    the net's positional input (or a tuple of them). Calibration forwards run
    imperatively — hybridized blocks are temporarily de-activated so the
    collectors see concrete arrays, and every compiled executable is dropped
    afterwards (the frozen scale is a trace-time constant)."""
    if mode not in ("naive", "entropy"):
        raise ValueError("calib mode must be 'naive' or 'entropy', got %r" % (mode,))
    calib_data = list(calib_data)
    if not calib_data:
        raise ValueError("calib_data is empty — zero calibration batches "
                         "would freeze degenerate activation scales")
    layers = _quantized_layers(block, [])
    if not layers:
        return block
    for l in layers:
        l._collector = _LayerCollector(mode, num_bins)
        l._x_scale = None         # dynamic during calibration forwards

    hbs = _hybrid_blocks(block, [])
    prev_active = [(b, b._active) for b in hbs]
    for b in hbs:
        b._active = False

    def _run():
        for batch in calib_data:
            block(*batch) if isinstance(batch, tuple) else block(batch)

    try:
        _run()                    # pass 1: amax
        if mode == "entropy":
            for l in layers:
                l._collector.phase = 2
            _run()                # pass 2: histograms over [0, amax]
    finally:
        for b, a in prev_active:
            b._active = a
    for l in layers:
        t = l._collector.threshold()
        l._x_scale = max(t, 1e-8) / _QMAX[l._mode]
        l._collector = None
    _invalidate_execs(block)
    _QUANT_STATS["calibrated_layers"] = len(layers)
    _QUANT_STATS["calib_mode"] = mode
    return block


def _swap_children(block, exclude, mode):
    from .gluon.nn.conv_layers import Conv2D

    swapped = []
    for name, child in list(block._children.items()):
        if isinstance(child, (QuantizedDense, QuantizedConv2D)):
            continue              # idempotent: snapshot load re-applies
        q = None
        if not any(e in child.prefix for e in exclude):
            if isinstance(child, nn.Dense):
                q = QuantizedDense(child, mode=mode)
            elif isinstance(child, Conv2D):
                # fp8 conv is untested territory on most backends — convs
                # always take the int8 path; fp8 targets the matmuls
                q = QuantizedConv2D(child, mode="int8")
        if q is not None:
            block._children[name] = q
            if hasattr(block, name):
                object.__setattr__(block, name, q)
            swapped.append(q)
        else:
            swapped.extend(_swap_children(child, exclude, mode))
    return swapped


def quantize_model(block, exclude=(), mode="int8", calib_mode="none",
                   calib_data=None, num_bins=8001):
    """Replace Dense/Conv2D children with their quantized twins (in place),
    skipping names matching any substring in `exclude`; optionally calibrate
    static activation ranges (ref: contrib/quantization.py:quantize_model —
    calib_mode none/naive/entropy). ``mode``: int8 (default) or fp8
    e4m3/e5m2 where :func:`fp8_supported` says the build can. Safe to call
    on an already-quantized model (no-op on quantized children — the
    snapshot loader relies on this). Compiled executables in the subtree are
    invalidated so the next forward runs the quantized program."""
    _check_mode(mode)
    swapped = _swap_children(block, exclude, mode)
    _invalidate_execs(block)
    if swapped:
        qb = fb = 0
        for q in _quantized_layers(block, []):
            qw = q.qweight.data()
            qb += qw._data.nbytes + q.w_scale.data()._data.nbytes
            fb += qw.size * 4
        _QUANT_STATS["quantized_layers"] = len(_quantized_layers(block, []))
        _QUANT_STATS["weight_bytes_quantized"] = int(qb)
        _QUANT_STATS["weight_bytes_fp32"] = int(fb)
        _QUANT_STATS["mode"] = mode
    if calib_mode != "none":
        if calib_data is None:
            raise ValueError("calib_mode=%r requires calib_data" % (calib_mode,))
        calibrate_model(block, calib_data, mode=calib_mode, num_bins=num_bins)
    return block
