"""int8 quantization (ref: src/operator/quantization/*.cc, python/mxnet/
contrib/quantization.py).

MXNet's int8 path targets MKLDNN/TensorRT kernels with calibrated ranges.
TPU-native: symmetric per-channel int8 weights + dynamic per-tensor int8
activations, accumulating in int32 on the MXU (``preferred_element_type``),
rescaled in fp32 — the standard XLA int8 inference recipe. ``quantize_model``
swaps eligible Dense layers in-place for inference.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from .base import register_op
from .gluon import nn
from .gluon.block import HybridBlock
from .ndarray import NDArray

__all__ = ["quantize", "dequantize", "quantized_fully_connected",
           "quantized_conv", "QuantizedDense", "QuantizedConv2D",
           "quantize_model"]


@register_op("contrib_quantize", nondiff=True, n_outputs=2)
def quantize(x, *, axis=None):
    """Symmetric int8: returns (q, scale). axis=None → per-tensor;
    axis=i → per-slice along dim i (ref: quantize_v2-inl.h)."""
    if axis is None:
        amax = jnp.max(jnp.abs(x))
    else:
        red = tuple(d for d in range(x.ndim) if d != axis)
        amax = jnp.max(jnp.abs(x), axis=red, keepdims=True)
    scale = jnp.maximum(amax, 1e-8) / 127.0
    q = jnp.clip(jnp.round(x / scale), -127, 127).astype(jnp.int8)
    return q, scale


@register_op("contrib_dequantize", nondiff=True)
def dequantize(q, scale):
    return q.astype(jnp.float32) * scale


@register_op("quantized_fully_connected", nondiff=True)
def quantized_fully_connected(x, qweight, w_scale, bias=None):
    """x fp → dynamic int8; int8×int8 matmul accumulated in int32 on the MXU.
    qweight: (out, in) int8; w_scale: (out, 1) fp32."""
    qx, x_scale = quantize(x)
    acc = jax.lax.dot_general(
        qx, qweight, (((qx.ndim - 1,), (1,)), ((), ())),
        preferred_element_type=jnp.int32)
    y = acc.astype(jnp.float32) * (x_scale * w_scale.reshape(-1))
    if bias is not None:
        y = y + bias
    return y


@register_op("quantized_conv", nondiff=True)
def quantized_conv(x, qweight, w_scale, bias=None, *, stride=1, pad=0, dilate=1,
                   num_group=1):
    """int8 convolution (ref: src/operator/quantization/quantized_conv.cc —
    the cuDNN int8x4 path). Dynamic per-tensor int8 activations ×
    per-output-channel int8 weights, int32 accumulation on the MXU, fp32
    rescale. qweight: (O, I, *K) int8; w_scale: (O, 1, 1, ...) fp32."""
    from .ops.functional import _pair

    nd = x.ndim - 2
    stride, pad, dilate = _pair(stride, nd), _pair(pad, nd), _pair(dilate, nd)
    qx, x_scale = quantize(x)
    spatial = "DHW"[-nd:]
    lhs = "NC" + spatial
    dn = jax.lax.conv_dimension_numbers(x.shape, qweight.shape,
                                        (lhs, "OI" + spatial, lhs))
    acc = jax.lax.conv_general_dilated(
        qx, qweight, window_strides=stride, padding=[(p, p) for p in pad],
        rhs_dilation=dilate, dimension_numbers=dn, feature_group_count=num_group,
        preferred_element_type=jnp.int32)
    oscale = (x_scale * w_scale.reshape(-1)).reshape((1, -1) + (1,) * nd)
    y = acc.astype(jnp.float32) * oscale
    if bias is not None:
        y = y + bias.reshape((1, -1) + (1,) * nd)
    return y


class QuantizedDense(HybridBlock):
    """Inference-only Dense with pre-quantized int8 weights."""

    def __init__(self, dense: nn.Dense, **kwargs):
        super().__init__(prefix=dense.prefix, **kwargs)
        w = dense.weight.data()._data.astype(jnp.float32)
        qw, ws = quantize(w, axis=0)
        self._qw = jnp.asarray(qw)
        self._ws = jnp.asarray(ws)
        self._bias = (dense.bias.data()._data.astype(jnp.float32)
                      if hasattr(dense, "bias") and dense.bias is not None else None)
        self._flatten = dense._flatten
        self._act = dense.act

    def hybrid_forward(self, F, x):
        if self._flatten:
            x = F.flatten(x)  # Dense(flatten=True) semantics, e.g. pooled NCHW
        # raw jnp weights pass through both facades unchanged
        y = F.quantized_fully_connected(x, self._qw, self._ws, self._bias)
        if self._act is not None:
            y = self._act(y)
        return y


class QuantizedConv2D(HybridBlock):
    """Inference-only Conv2D with pre-quantized per-output-channel int8
    weights (ref: quantized_conv.cc). Grouped convs keep the same layout."""

    def __init__(self, conv, **kwargs):
        super().__init__(prefix=conv.prefix, **kwargs)
        w = conv.weight.data()._data.astype(jnp.float32)
        qw, ws = quantize(w, axis=0)
        self._qw = jnp.asarray(qw)
        self._ws = jnp.asarray(ws)
        self._bias = (conv.bias.data()._data.astype(jnp.float32)
                      if getattr(conv, "bias", None) is not None else None)
        k = conv._kwargs
        self._conv_kw = dict(stride=k["stride"], pad=k["pad"], dilate=k["dilate"],
                             num_group=k["num_group"])
        self._act = conv.act

    def hybrid_forward(self, F, x):
        y = F.quantized_conv(x, self._qw, self._ws, self._bias, **self._conv_kw)
        if self._act is not None:
            y = self._act(y)
        return y


def quantize_model(block, exclude=()):
    """Replace Dense/Conv2D children with their int8 twins (in place),
    skipping names matching any substring in `exclude` (ref:
    contrib/quantization.py:quantize_model)."""
    from .gluon.nn.conv_layers import Conv2D

    for name, child in list(block._children.items()):
        q = None
        if not any(e in child.prefix for e in exclude):
            if isinstance(child, nn.Dense):
                q = QuantizedDense(child)
            elif isinstance(child, Conv2D):
                q = QuantizedConv2D(child)
        if q is not None:
            block._children[name] = q
            if hasattr(block, name):
                object.__setattr__(block, name, q)
        else:
            quantize_model(child, exclude)
    return block
