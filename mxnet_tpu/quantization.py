"""int8 quantization (ref: src/operator/quantization/*.cc, python/mxnet/
contrib/quantization.py).

MXNet's int8 path targets MKLDNN/TensorRT kernels with calibrated ranges.
TPU-native: symmetric per-channel int8 weights + dynamic per-tensor int8
activations, accumulating in int32 on the MXU (``preferred_element_type``),
rescaled in fp32 — the standard XLA int8 inference recipe. ``quantize_model``
swaps eligible Dense layers in-place for inference.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from .base import register_op
from .gluon import nn
from .gluon.block import HybridBlock
from .ndarray import NDArray

__all__ = ["quantize", "dequantize", "quantized_fully_connected",
           "quantized_conv", "QuantizedDense", "QuantizedConv2D",
           "quantize_model", "calibrate_model"]


@register_op("contrib_quantize", nondiff=True, n_outputs=2)
def quantize(x, *, axis=None):
    """Symmetric int8: returns (q, scale). axis=None → per-tensor;
    axis=i → per-slice along dim i (ref: quantize_v2-inl.h)."""
    if axis is None:
        amax = jnp.max(jnp.abs(x))
    else:
        red = tuple(d for d in range(x.ndim) if d != axis)
        amax = jnp.max(jnp.abs(x), axis=red, keepdims=True)
    scale = jnp.maximum(amax, 1e-8) / 127.0
    q = jnp.clip(jnp.round(x / scale), -127, 127).astype(jnp.int8)
    return q, scale


@register_op("contrib_dequantize", nondiff=True)
def dequantize(q, scale):
    return q.astype(jnp.float32) * scale


def _quantize_act(x, x_scale):
    """Dynamic (x_scale=None) or static (calibrated scale) int8 activations."""
    if x_scale is None:
        return quantize(x)
    qx = jnp.clip(jnp.round(x / x_scale), -127, 127).astype(jnp.int8)
    return qx, x_scale


@register_op("quantized_fully_connected", nondiff=True)
def quantized_fully_connected(x, qweight, w_scale, bias=None, *, x_scale=None):
    """x fp → int8 (dynamic per-tensor, or static when a calibrated x_scale is
    given); int8×int8 matmul accumulated in int32 on the MXU.
    qweight: (out, in) int8; w_scale: (out, 1) fp32."""
    qx, x_scale = _quantize_act(x, x_scale)
    acc = jax.lax.dot_general(
        qx, qweight, (((qx.ndim - 1,), (1,)), ((), ())),
        preferred_element_type=jnp.int32)
    y = acc.astype(jnp.float32) * (x_scale * w_scale.reshape(-1))
    if bias is not None:
        y = y + bias
    return y


@register_op("quantized_conv", nondiff=True)
def quantized_conv(x, qweight, w_scale, bias=None, *, stride=1, pad=0, dilate=1,
                   num_group=1, x_scale=None):
    """int8 convolution (ref: src/operator/quantization/quantized_conv.cc —
    the cuDNN int8x4 path). Per-tensor int8 activations (dynamic or
    calibrated-static) × per-output-channel int8 weights, int32 accumulation
    on the MXU, fp32 rescale. qweight: (O, I, *K) int8; w_scale: (O, 1, 1, ...)
    fp32."""
    from .ops.functional import _pair

    nd = x.ndim - 2
    stride, pad, dilate = _pair(stride, nd), _pair(pad, nd), _pair(dilate, nd)
    qx, x_scale = _quantize_act(x, x_scale)
    spatial = "DHW"[-nd:]
    lhs = "NC" + spatial
    dn = jax.lax.conv_dimension_numbers(x.shape, qweight.shape,
                                        (lhs, "OI" + spatial, lhs))
    acc = jax.lax.conv_general_dilated(
        qx, qweight, window_strides=stride, padding=[(p, p) for p in pad],
        rhs_dilation=dilate, dimension_numbers=dn, feature_group_count=num_group,
        preferred_element_type=jnp.int32)
    oscale = (x_scale * w_scale.reshape(-1)).reshape((1, -1) + (1,) * nd)
    y = acc.astype(jnp.float32) * oscale
    if bias is not None:
        y = y + bias.reshape((1, -1) + (1,) * nd)
    return y


class _LayerCollector:
    """Records input-activation statistics during calibration forwards
    (ref: contrib/quantization.py _LayerOutputMinMaxCollector /
    _LayerHistogramCollector)."""

    def __init__(self, mode="naive", num_bins=8001):
        import numpy as np

        self.mode = mode
        self.num_bins = num_bins
        self.amax = 0.0
        self.hist = None          # allocated in pass 2 (entropy mode)
        self.phase = 1

    def collect(self, x):
        import numpy as np

        if isinstance(x, NDArray):
            a = x.asnumpy()
        else:
            a = np.asarray(x)
        a = np.abs(a.astype(np.float32)).ravel()
        if self.phase == 1:
            self.amax = max(self.amax, float(a.max(initial=0.0)))
        else:
            h, _ = np.histogram(a, bins=self.num_bins, range=(0.0, self.amax))
            self.hist = h if self.hist is None else self.hist + h

    def threshold(self):
        if self.mode == "naive" or self.hist is None:
            return self.amax
        return _optimal_threshold(self.hist, self.amax)


def _smooth_distribution(d, eps=1e-4):
    """Move eps mass onto zero entries so KL stays finite (ref:
    contrib/quantization.py _smooth_distribution)."""
    import numpy as np

    is_zero = d == 0
    n_zero = int(is_zero.sum())
    n_nonzero = d.size - n_zero
    if n_zero == 0 or n_nonzero == 0:
        return d
    eps1 = eps * n_zero / n_nonzero
    # floor at eps so entries smaller than the deducted mass stay positive
    return np.where(is_zero, eps, np.maximum(d - eps1 * (d > 0), eps))


def _optimal_threshold(hist, amax, num_quantized_bins=255):
    """KL-divergence-minimizing clip threshold (ref: contrib/quantization.py
    _get_optimal_threshold, the TensorRT entropy-calibration scheme). For each
    candidate threshold: the reference distribution p is the clipped histogram
    with the clipped-away outlier mass folded into its edge bin; q is the
    255-level quantization of the UNFOLDED clipped histogram — so clipping
    cost appears as p/q divergence at the edge rather than being free."""
    import numpy as np

    num_bins = hist.size
    if amax <= 0 or hist.sum() == 0:
        return amax
    best_kl, best_i = np.inf, num_bins
    hist = hist.astype(np.float64)
    for i in range(num_quantized_bins, num_bins + 1,
                   max(1, (num_bins - num_quantized_bins) // 128)):
        sliced = hist[:i]
        if sliced.sum() == 0:
            continue
        p = sliced.copy()
        p[-1] += hist[i:].sum()             # reference keeps the clipped mass
        # quantize the clipped histogram into 255 coarse bins, spreading each
        # coarse bin's mass uniformly over its NONZERO fine bins
        idx = (np.arange(i) * num_quantized_bins // i).clip(
            0, num_quantized_bins - 1)
        q_coarse = np.bincount(idx, weights=sliced, minlength=num_quantized_bins)
        nz = (sliced != 0).astype(np.float64)
        nz_count = np.bincount(idx, weights=nz, minlength=num_quantized_bins)
        q = np.where(nz > 0,
                     q_coarse[idx] / np.maximum(nz_count[idx], 1.0), 0.0)
        p = _smooth_distribution(p / p.sum())
        q = _smooth_distribution(q / max(q.sum(), 1e-12))
        kl = float(np.sum(p * np.log(p / q)))
        if kl < best_kl:
            best_kl, best_i = kl, i
    return amax * best_i / num_bins


class QuantizedDense(HybridBlock):
    """Inference-only Dense with pre-quantized int8 weights."""

    def __init__(self, dense: nn.Dense, **kwargs):
        super().__init__(prefix=dense.prefix, **kwargs)
        w = dense.weight.data()._data.astype(jnp.float32)
        qw, ws = quantize(w, axis=0)
        self._qw = jnp.asarray(qw)
        self._ws = jnp.asarray(ws)
        self._bias = (dense.bias.data()._data.astype(jnp.float32)
                      if hasattr(dense, "bias") and dense.bias is not None else None)
        self._flatten = dense._flatten
        self._act = dense.act
        self._x_scale = None      # static activation scale after calibration
        self._collector = None

    def hybrid_forward(self, F, x):
        if self._flatten:
            x = F.flatten(x)  # Dense(flatten=True) semantics, e.g. pooled NCHW
        if self._collector is not None:
            self._collector.collect(x)
        # raw jnp weights pass through both facades unchanged
        y = F.quantized_fully_connected(x, self._qw, self._ws, self._bias,
                                        x_scale=self._x_scale)
        if self._act is not None:
            y = self._act(y)
        return y


class QuantizedConv2D(HybridBlock):
    """Inference-only Conv2D with pre-quantized per-output-channel int8
    weights (ref: quantized_conv.cc). Grouped convs keep the same layout."""

    def __init__(self, conv, **kwargs):
        super().__init__(prefix=conv.prefix, **kwargs)
        w = conv.weight.data()._data.astype(jnp.float32)
        qw, ws = quantize(w, axis=0)
        self._qw = jnp.asarray(qw)
        self._ws = jnp.asarray(ws)
        self._bias = (conv.bias.data()._data.astype(jnp.float32)
                      if getattr(conv, "bias", None) is not None else None)
        k = conv._kwargs
        self._conv_kw = dict(stride=k["stride"], pad=k["pad"], dilate=k["dilate"],
                             num_group=k["num_group"])
        self._act = conv.act
        self._x_scale = None
        self._collector = None

    def hybrid_forward(self, F, x):
        if self._collector is not None:
            self._collector.collect(x)
        y = F.quantized_conv(x, self._qw, self._ws, self._bias,
                             x_scale=self._x_scale, **self._conv_kw)
        if self._act is not None:
            y = self._act(y)
        return y


def _quantized_layers(block, out):
    for child in block._children.values():
        if isinstance(child, (QuantizedDense, QuantizedConv2D)):
            out.append(child)
        else:
            _quantized_layers(child, out)
    return out


def calibrate_model(block, calib_data, mode="naive", num_bins=8001):
    """Freeze static activation scales from calibration batches (ref:
    contrib/quantization.py calib_mode='naive'|'entropy').

    ``calib_data``: iterable of input batches (materialized to a list so
    entropy's second histogram pass sees the same batches); each element is
    the net's positional input (or a tuple of them). Runs imperatively —
    calibrate BEFORE hybridize()."""
    if mode not in ("naive", "entropy"):
        raise ValueError("calib mode must be 'naive' or 'entropy', got %r" % (mode,))
    calib_data = list(calib_data)
    if not calib_data:
        raise ValueError("calib_data is empty — zero calibration batches "
                         "would freeze degenerate activation scales")
    layers = _quantized_layers(block, [])
    if not layers:
        return block
    for l in layers:
        l._collector = _LayerCollector(mode, num_bins)
        l._x_scale = None         # dynamic during calibration forwards

    def _run():
        for batch in calib_data:
            block(*batch) if isinstance(batch, tuple) else block(batch)

    _run()                        # pass 1: amax
    if mode == "entropy":
        for l in layers:
            l._collector.phase = 2
        _run()                    # pass 2: histograms over [0, amax]
    for l in layers:
        t = l._collector.threshold()
        l._x_scale = max(t, 1e-8) / 127.0
        l._collector = None
    return block


def quantize_model(block, exclude=(), calib_mode="none", calib_data=None,
                   num_bins=8001):
    """Replace Dense/Conv2D children with their int8 twins (in place),
    skipping names matching any substring in `exclude`; optionally calibrate
    static activation ranges (ref: contrib/quantization.py:quantize_model —
    calib_mode none/naive/entropy)."""
    from .gluon.nn.conv_layers import Conv2D

    for name, child in list(block._children.items()):
        q = None
        if not any(e in child.prefix for e in exclude):
            if isinstance(child, nn.Dense):
                q = QuantizedDense(child)
            elif isinstance(child, Conv2D):
                q = QuantizedConv2D(child)
        if q is not None:
            block._children[name] = q
            if hasattr(block, name):
                object.__setattr__(block, name, q)
        else:
            quantize_model(child, exclude, calib_mode="none")
    if calib_mode != "none":
        if calib_data is None:
            raise ValueError("calib_mode=%r requires calib_data" % (calib_mode,))
        calibrate_model(block, calib_data, mode=calib_mode, num_bins=num_bins)
    return block
