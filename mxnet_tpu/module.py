"""Module API adapter (ref: python/mxnet/module/module.py).

The legacy Module trains a Symbol graph. Here Module binds the Symbol to a
jitted executor; SoftmaxOutput heads get their MXNet training semantics
(backward = softmax - one_hot(label)) by constructing the cross-entropy loss
over the head's logits.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from . import initializer as init_mod
from . import metric as metric_mod
from . import optimizer as opt_mod
from .context import current_context
from .ndarray import NDArray
from .symbol import Symbol

__all__ = ["Module", "BucketingModule", "SequentialModule"]


class Module:
    def __init__(self, symbol, data_names=("data",), label_names=("softmax_label",),
                 context=None, logger=None):
        self._symbol = symbol
        self._data_names = list(data_names)
        self._label_names = list(label_names or [])
        self._ctx = context or current_context()
        self._exec = None
        self._arg_params = {}
        self._optimizer = None
        self._opt_states = {}
        self._n_main_outputs = 1
        self._aux_update_names = []
        self._pred_pool = None
        self.binded = False
        self.params_initialized = False

    def bind(self, data_shapes, label_shapes=None, for_training=True,
             inputs_need_grad=False, force_rebind=False, **kwargs):
        if self.binded and not force_rebind:
            return
        shapes = {}
        for name, shape in data_shapes:
            shapes[name] = tuple(shape)
        for name, shape in (label_shapes or []):
            shapes[name] = tuple(shape)
        self._data_shapes = shapes
        self._for_training = for_training
        self._inputs_need_grad = inputs_need_grad
        self._exec = None
        self._pred_pool = None  # rebind invalidates the inference pool
        self.binded = True

    def init_params(self, initializer=None, arg_params=None, aux_params=None,
                    allow_missing=False, force_init=False, allow_extra=False):
        assert self.binded
        if arg_params is None and getattr(self, "_preloaded_params", None):
            # Module.load stashed the checkpoint's params for bind time
            pre_arg, pre_aux = self._preloaded_params
            arg_params = dict(pre_arg)
            arg_params.update(pre_aux or {})
        initializer = initializer or init_mod.Uniform(0.01)
        arg_names = self._symbol.list_arguments()
        # infer parameter shapes from data shapes via eval_shape with zeros
        inferred = self._infer_param_shapes()
        for n in arg_names:
            if n in self._data_names or n in self._label_names:
                continue
            if arg_params and n in arg_params:
                self._arg_params[n] = arg_params[n]
                continue
            arr = NDArray(jnp.zeros(inferred[n], jnp.float32))
            initializer(init_mod.InitDesc(n), arr)
            self._arg_params[n] = arr
            arr.attach_grad()
        self._pred_pool = None  # pool captures param objects; re-resolve
        self.params_initialized = True

    def _infer_param_shapes(self):
        """Infer every argument's shape from the bound data/label shapes —
        graph shape inference (ref: src/executor/graph_executor.cc infer
        pass), so params need no declared shape= on their variables."""
        from .shape_inference import format_infer_errors, infer_shapes_partial

        known = dict(self._data_shapes)
        var_shapes, _, errors = infer_shapes_partial(self._symbol, known)
        missing = [n for n, s in var_shapes.items() if s is None]
        if missing:
            raise ValueError(
                "shape inference could not determine %s from data shapes %s; "
                "declare shape= on those variables%s"
                % (missing, known, format_infer_errors(errors)))
        return var_shapes

    def forward(self, data_batch, is_train=None):
        if is_train is None:
            is_train = getattr(self, "_for_training", True)
        feed = {}
        for name, arr in zip(self._data_names, data_batch.data):
            feed[name] = arr
        if data_batch.label:
            for name, arr in zip(self._label_names, data_batch.label):
                feed[name] = arr
        self._last_feed = feed
        if self._exec is None:
            args = dict(self._arg_params)
            for n in self._data_names + self._label_names:
                if n in feed:
                    args[n] = feed[n]
            grads = {n: NDArray(jnp.zeros_like(a._data))
                     for n, a in self._arg_params.items()}
            if getattr(self, "_inputs_need_grad", False):
                for n in self._data_names:
                    a = feed[n]
                    d = a._data if isinstance(a, NDArray) else jnp.asarray(a)
                    grads[n] = NDArray(jnp.zeros_like(d))
            self._exec = self._bn_aux_symbol().bind(self._ctx, args, grads)
        self._exec.forward(is_train=bool(is_train), **feed)
        outs = self._exec.outputs
        n_main = self._n_main_outputs
        if is_train and len(outs) > n_main:
            # BatchNorm aux write-back (upstream: executor aux_states are
            # copied back after each training forward): the hidden
            # new-moving-mean/var outputs land in the bound moving vars
            # IN PLACE, so the next forward (and eval mode) sees them
            for name, new in zip(self._aux_update_names, outs[n_main:]):
                self._arg_params[name]._data = new._data
        return outs[:n_main]

    def _bn_aux_symbol(self):
        """Wrap the bound symbol so each BatchNorm's hidden updated-stat
        outputs are fetched alongside the main outputs (ref:
        src/executor/graph_executor.cc aux-state write-back)."""
        from .symbol import Group, Symbol, _attr_symbols

        self._aux_update_names = []
        # a _group's head count is its input list (Symbol._n_outputs stays
        # at the constructor default for groups)
        self._n_main_outputs = len(self._symbol._inputs) \
            if self._symbol._op == "_group" else 1
        items, seen, stack = [], set(), [self._symbol]
        while stack:
            s = stack.pop()
            if id(s) in seen or not isinstance(s, Symbol):
                continue
            seen.add(id(s))
            if (s._op == "BatchNorm" and len(s._inputs) >= 5
                    and s._inputs[3].is_var() and s._inputs[4].is_var()):
                items.append(Symbol("_item", [s], {"index": 1},
                                    name=s.name + "_mm_upd"))
                items.append(Symbol("_item", [s], {"index": 2},
                                    name=s.name + "_mv_upd"))
                self._aux_update_names += [s._inputs[3].name,
                                           s._inputs[4].name]
            stack.extend(s._inputs)
            stack.extend(_attr_symbols(s._attrs))
        if not items:
            return self._symbol
        mains = ([self._symbol[i] for i in range(self._n_main_outputs)]
                 if self._symbol._op == "_group" else [self._symbol])
        return Group(mains + items)

    def backward(self, out_grads=None):
        if out_grads is None and self._symbol._op == "SoftmaxOutput":
            # MXNet semantics: d(logits) = softmax - one_hot(label). The
            # probs are a mandated output of the head, so the grad from them
            # is already a single elementwise pass — the same one-pass
            # backward the fused pallas xent kernel (ops/pallas/softmax_xent)
            # achieves by reconstructing p from its saved lse. one_hot via
            # iota-compare, NOT .at[].set(): scatter is a serialized op on
            # TPU, the compare fuses into the subtract.
            prob = self._exec.outputs[0]._data
            label = self._last_feed[self._label_names[0]]
            label = label._data if isinstance(label, NDArray) else jnp.asarray(label)
            cols = jax.lax.broadcasted_iota(jnp.int32, prob.shape, prob.ndim - 1)
            onehot = (cols == label.astype(jnp.int32)[:, None]).astype(prob.dtype)
            grad = (prob - onehot) / prob.shape[0]
            out_grads = [NDArray(grad)]
        elif out_grads is None:
            out_grads = [NDArray(jnp.ones(o.shape, o.dtype))
                         for o in self._exec.outputs[:self._n_main_outputs]]
        elif isinstance(out_grads, NDArray):
            out_grads = [out_grads]
        out_grads = list(out_grads)
        if len(out_grads) < self._n_main_outputs:
            raise ValueError("backward needs %d output gradients, got %d"
                             % (self._n_main_outputs, len(out_grads)))
        # aux stat fetches are NOT differentiated through (upstream treats
        # aux states as non-gradient): zero cotangents for the tail ONLY
        out_grads += [NDArray(jnp.zeros(o.shape, o.dtype))
                      for o in self._exec.outputs[len(out_grads):]]
        self._exec.backward(out_grads)

    def get_outputs(self):
        return self._exec.outputs[:self._n_main_outputs]

    def get_input_grads(self):
        """(ref: module/base_module.py:get_input_grads) — requires
        bind(inputs_need_grad=True)."""
        assert getattr(self, "_inputs_need_grad", False), \
            "bind with inputs_need_grad=True"
        return [self._exec.grad_dict[n] for n in self._data_names]

    def init_optimizer(self, kvstore="local", optimizer="sgd", optimizer_params=None,
                       force_init=False):
        optimizer_params = optimizer_params or {"learning_rate": 0.01}
        self._optimizer = (optimizer if isinstance(optimizer, opt_mod.Optimizer)
                           else opt_mod.create(optimizer, **optimizer_params))

    def update(self):
        aux = set(self._aux_update_names)
        for i, (n, p) in enumerate(sorted(self._arg_params.items())):
            # aux states (BN moving stats) are written back by forward, not
            # optimized — an optimizer step (esp. weight decay) would erode
            # the statistics (upstream excludes aux from updates)
            if n in aux:
                continue
            g = self._exec.grad_dict.get(n)
            if g is None:
                continue
            if i not in self._opt_states:
                self._opt_states[i] = self._optimizer.create_state(i, p)
            self._opt_states[i] = self._optimizer.update(i, p, g, self._opt_states[i])

    def fit(self, train_data, eval_data=None, eval_metric="accuracy",
            num_epoch=1, optimizer="sgd", optimizer_params=None,
            initializer=None, batch_end_callback=None, **kwargs):
        """(ref: module/base_module.py:fit)"""
        if not self.binded:
            first = next(iter(train_data))
            train_data.reset()
            self.bind([(n, tuple(a.shape)) for n, a in zip(self._data_names, first.data)],
                      [(n, tuple(a.shape)) for n, a in zip(self._label_names, first.label or [])])
        if not self.params_initialized:
            self.init_params(initializer)
        self.init_optimizer(optimizer=optimizer, optimizer_params=optimizer_params)
        em = metric_mod.create(eval_metric)
        for epoch in range(num_epoch):
            em.reset()
            train_data.reset()
            for batch in train_data:
                self.forward_backward(batch)
                self.update()
                # pad-aware like score: the SAME metric over the SAME data
                # must agree between the fit loop and score()
                outs, labels = self._strip_pad(batch, self.get_outputs(),
                                               list(batch.label or []))
                em.update(labels, outs)
        return em.get()

    # -- BaseModule conveniences (ref: module/base_module.py) ---------------

    @property
    def symbol(self):
        return self._symbol

    @property
    def data_names(self):
        return list(self._data_names)

    @property
    def output_names(self):
        return self._symbol.list_outputs()

    @property
    def data_shapes(self):
        from .io import DataDesc
        return [DataDesc(n, self._data_shapes[n]) for n in self._data_names
                if n in getattr(self, "_data_shapes", {})]

    @property
    def label_shapes(self):
        from .io import DataDesc
        return [DataDesc(n, self._data_shapes[n]) for n in self._label_names
                if n in getattr(self, "_data_shapes", {})]

    @property
    def output_shapes(self):
        _, outs, _ = self._symbol.infer_shape(
            **{n: s for n, s in getattr(self, "_data_shapes", {}).items()})
        return list(zip(self.output_names, outs))

    def forward_backward(self, data_batch):
        """(ref: base_module.py:forward_backward)"""
        self.forward(data_batch, is_train=True)
        self.backward()

    def update_metric(self, eval_metric, labels, pre_sliced=False):
        """(ref: base_module.py:update_metric). All labels pair with all
        main outputs (EvalMetric.update zips lists); pre_sliced flattens
        upstream's per-device label slices."""
        if not isinstance(labels, (list, tuple)):
            labels = [labels]
        elif pre_sliced:
            labels = [l for sl in labels for l in
                      (sl if isinstance(sl, (list, tuple)) else [sl])]
        eval_metric.update(list(labels), self.get_outputs())

    @staticmethod
    def _strip_pad(batch, outs, labels):
        """Drop an iterator's wrap-around rows so metrics don't
        double-count them (predict strips identically)."""
        pad = getattr(batch, "pad", 0) or 0
        if not pad:
            return outs, labels
        outs = [NDArray(o._data[:o.shape[0] - pad]) for o in outs]
        labels = [NDArray(l._data[:l.shape[0] - pad]) for l in labels]
        return outs, labels

    def _predict_pool(self):
        """Shared bucketed inference executor (serve.executor_pool) for
        predict/score-style eval: ONE compiled program at the bound-batch
        bucket serves every batch — including the iterator's final padded
        partial batch, which the bound executor used to retrace at its
        smaller shape. Returns (pool, input_names), or (None, None) when
        the graph isn't poolable (stochastic eval graph, missing params) —
        the per-batch forward path serves those."""
        if self._pred_pool is not None:
            return self._pred_pool
        from .serve.executor_pool import BucketedExecutor, symbol_infer_fn

        self._pred_pool = (None, None)
        shapes = getattr(self, "_data_shapes", None)
        if shapes and self.params_initialized:
            arg_names = set(self._symbol.list_arguments())
            input_names = [n for n in self._data_names + self._label_names
                           if n in arg_names and n in shapes]
            fn, pnames = symbol_infer_fn([self._symbol], input_names)
            if fn is not None and all(n in self._arg_params for n in pnames):
                plist = [self._arg_params[n] for n in pnames]

                def params_fn():
                    return [p._data for p in plist]

                bucket = shapes[self._data_names[0]][0]
                self._pred_pool = (
                    BucketedExecutor(fn, params_fn, buckets=(bucket,),
                                     name="module.predict"), input_names)
        return self._pred_pool

    def _pool_batch_inputs(self, batch, input_names, rows):
        """Assemble predict-pool inputs from a DataBatch; absent labels
        (predict on unlabeled iterators) feed zeros at the bound shape —
        eval outputs can't depend on them row-wise."""
        feed = dict(zip(self._data_names, batch.data))
        if batch.label:
            feed.update(zip(self._label_names, batch.label))
        ins = []
        for n in input_names:
            a = feed.get(n)
            if a is None:
                ins.append(np.zeros((rows,) + tuple(self._data_shapes[n][1:]),
                                    np.float32))
            else:
                ins.append(a.asnumpy() if isinstance(a, NDArray)
                           else np.asarray(a))
        return ins

    def predict(self, eval_data, num_batch=None, merge_batches=True,
                reset=True, always_output_list=False):
        """(ref: base_module.py:predict) — run inference over an iterator,
        concatenating per-batch outputs along axis 0. Deterministic graphs
        route through the shared bucketed executor pool (one compiled
        program for all batches, partial final batch padded); others fall
        back to the per-batch bound-executor forward."""
        if reset and hasattr(eval_data, "reset"):
            eval_data.reset()
        pool, input_names = self._predict_pool()
        per_batch = []  # list over batches of the (pad-stripped) output list
        for i, batch in enumerate(eval_data):
            if num_batch is not None and i >= num_batch:
                break
            pad = getattr(batch, "pad", 0) or 0
            if pool is not None:
                from .serve.executor_pool import PoolError

                rows = batch.data[0].shape[0]
                try:
                    ins = self._pool_batch_inputs(batch, input_names, rows)
                    outs = pool.run(ins, n_real=rows - pad)
                except PoolError:  # e.g. a batch wider than the bound bucket
                    outs = None
                if outs is not None and pool.row_aligned:
                    per_batch.append([NDArray(o) for o in outs])
                    continue
                # outputs don't carry the batch on axis 0 (or the batch
                # doesn't fit the bucket): padding is not sliceable —
                # disable the pool and recompute via forward
                pool = None
                self._pred_pool = (None, None)
            self.forward(batch, is_train=False)
            outs = self.get_outputs()
            if pad:
                outs = [NDArray(o._data[:o.shape[0] - pad]) for o in outs]
            per_batch.append(outs)
        if not per_batch:
            return []
        if not merge_batches:
            # upstream contract: a list over batches (each a list of outputs)
            return per_batch
        merged = [NDArray(jnp.concatenate([outs[j]._data
                                           for outs in per_batch], axis=0))
                  for j in range(len(per_batch[0]))]
        if len(merged) == 1 and not always_output_list:
            return merged[0]
        return merged

    def score(self, eval_data, eval_metric, num_batch=None, reset=True):
        """(ref: base_module.py:score)"""
        em = metric_mod.create(eval_metric)
        em.reset()
        if reset and hasattr(eval_data, "reset"):
            eval_data.reset()
        for i, batch in enumerate(eval_data):
            if num_batch is not None and i >= num_batch:
                break
            self.forward(batch, is_train=False)
            outs, labels = self._strip_pad(batch, self.get_outputs(),
                                           list(batch.label or []))
            em.update(labels, outs)
        return em.get_name_value()

    _AUX_SUFFIXES = ("moving_mean", "moving_var", "running_mean",
                     "running_var")

    def _is_aux(self, name):
        return name in getattr(self, "_aux_update_names", ()) \
            or name.endswith(self._AUX_SUFFIXES)

    def get_params(self):
        """(arg_params, aux_params) with BN moving stats in the AUX dict —
        the upstream split (executors store them as aux_states); internally
        they live in _arg_params for the forward write-back."""
        args = {n: v for n, v in self._arg_params.items()
                if not self._is_aux(n)}
        aux = {n: v for n, v in self._arg_params.items() if self._is_aux(n)}
        return args, aux

    def set_params(self, arg_params, aux_params=None, allow_missing=False,
                   force_init=True, allow_extra=False):
        """Write values IN PLACE: the bound executor's arg_dict holds the
        same NDArray objects as _arg_params (bind shares, forward reads
        arg_dict), so replacing dict entries after bind would be a silent
        no-op for subsequent forwards — upstream set_params writes through
        to the executors (ref: module/module.py:set_params).

        ``allow_extra=False`` rejects names the module doesn't know (a typo
        would otherwise land in a dead dict entry no executor reads);
        ``allow_missing=False`` requires every module parameter present."""
        given = dict(arg_params or {})
        given.update(aux_params or {})
        known = set(self._arg_params)  # snapshot BEFORE mutating in the loop
        if not known:
            # pre-bind there is nothing to validate names against, so a
            # typo'd name cannot be caught and would become a dead dict
            # entry — warn LOUDLY (ADVICE r4) while keeping the documented
            # pre-bind flow (values apply at bind time)
            import warnings

            warnings.warn(
                "set_params before bind/init_params: parameter names cannot "
                "be validated against the module — a misspelled name would "
                "be silently unused; prefer binding first")
            for n, v in given.items():
                self._arg_params[n] = v if isinstance(v, NDArray) \
                    else NDArray(jnp.asarray(v))
            return
        extra = sorted(set(given) - known)
        if extra and not allow_extra:
            raise ValueError(
                "set_params: unknown parameter(s) %s (module has %s...); "
                "pass allow_extra=True to ignore"
                % (extra[:5], sorted(known)[:5]))
        missing = sorted(known - set(given))
        if missing and not allow_missing:
            raise ValueError(
                "set_params: missing parameter(s) %s; pass "
                "allow_missing=True to keep current values"
                % (missing[:5],))
        kept = []
        for n, v in given.items():
            if n not in known:
                continue  # allow_extra: ignored, like upstream
            new = v._data if isinstance(v, NDArray) else jnp.asarray(v)
            cur = self._arg_params[n]
            if tuple(new.shape) != tuple(cur._data.shape):
                raise ValueError(
                    "set_params: %r has shape %s; module expects %s"
                    % (n, tuple(new.shape), tuple(cur._data.shape)))
            if not force_init:
                kept.append(n)
            else:
                cur._data = new.astype(cur._data.dtype)
        if kept:
            import warnings
            warnings.warn("set_params: force_init=False kept %d already-"
                          "initialized parameter(s) (e.g. %r)"
                          % (len(kept), kept[0]))

    def save_checkpoint(self, prefix, epoch):
        """prefix-symbol.json + prefix-NNNN.params, the mx.model layout
        (ref: module/module.py:save_checkpoint)."""
        from . import model as _model
        arg, aux = self.get_params()
        _model.save_checkpoint(prefix, epoch, self._symbol, arg, aux)

    @staticmethod
    def load(prefix, epoch, data_names=("data",), label_names=("softmax_label",),
             context=None, **kwargs):
        """Rebuild a Module from a save_checkpoint layout
        (ref: module/module.py:Module.load). Params apply at bind time."""
        from . import model as _model
        sym, arg, aux = _model.load_checkpoint(prefix, epoch)
        mod = Module(sym, data_names, label_names, context, **kwargs)
        mod._preloaded_params = (arg, aux)
        return mod


class BucketingModule(Module):
    """(ref: module/bucketing_module.py) — per-bucket executors; each bucket is
    one jit cache entry keyed by its shapes, so XLA recompiles per bucket
    exactly like MXNet rebinds per bucket."""

    def __init__(self, sym_gen, default_bucket_key=None, context=None, **kwargs):
        self._sym_gen = sym_gen
        self._default_key = default_bucket_key
        sym, data_names, label_names = sym_gen(default_bucket_key)
        super().__init__(sym, data_names, label_names, context)
        self._buckets = {}
        self._curr_module = None

    def switch_bucket(self, bucket_key, data_shapes=None):
        if bucket_key not in self._buckets:
            sym, data_names, label_names = self._sym_gen(bucket_key)
            m = Module(sym, data_names, label_names, self._ctx)
            # buckets share weights, optimizer, and optimizer state — one
            # model, several compiled shapes (ref: bucketing_module.py:
            # shared_module binding)
            m._arg_params = self._arg_params
            m._opt_states = self._opt_states
            self._buckets[bucket_key] = m
        m = self._buckets[bucket_key]
        m._optimizer = getattr(self, "_optimizer", None)
        self._curr_module = m
        return m

    def forward(self, data_batch, is_train=None):
        """Route by the batch's bucket_key; each bucket is a cached compiled
        executor (ref: bucketing_module.py:forward)."""
        key = getattr(data_batch, "bucket_key", None)
        key = self._default_key if key is None else key
        m = self.switch_bucket(key)
        return m.forward(data_batch, is_train)

    def _predict_pool(self):
        # bucketing modules pick their graph per batch (bucket_key), so a
        # single pooled program can't serve predict — per-bucket executors
        # already are the bucketed cache here
        return None, None

    def backward(self, out_grads=None):
        self._curr_module.backward(out_grads)

    def update(self):
        self._curr_module.update()

    def get_outputs(self):
        return self._curr_module.get_outputs()

    @property
    def _exec(self):
        # fit()/metrics read outputs via self._exec — route to the bucket
        # module currently bound (base __init__'s write lands in __dict__
        # via the setter below, used only before the first forward)
        if getattr(self, "_curr_module", None) is not None:
            return self._curr_module._exec
        return self.__dict__.get("_exec_base")

    @_exec.setter
    def _exec(self, v):
        self.__dict__["_exec_base"] = v


class SequentialModule:
    """Chain of Modules where module i's outputs feed module i+1's data
    (ref: python/mxnet/module/sequential_module.py). Intermediate modules
    bind with ``inputs_need_grad=True`` so the backward pass hands each
    stage's input grads to the stage before it as ``out_grads``."""

    def __init__(self, logger=None):
        self._modules = []
        self._take_labels = []
        self.binded = False
        self.params_initialized = False

    def add(self, module, take_labels=False):
        if self.binded:
            raise RuntimeError("add() after bind()")
        self._modules.append(module)
        self._take_labels.append(bool(take_labels))
        return self

    def bind(self, data_shapes, label_shapes=None, for_training=True,
             inputs_need_grad=False, force_rebind=False, **kwargs):
        if self.binded and not force_rebind:
            return
        assert self._modules, "add() at least one module before bind()"
        cur = [(n, tuple(s)) for n, s in data_shapes]
        for i, m in enumerate(self._modules):
            lab = label_shapes if self._take_labels[i] else None
            need = inputs_need_grad if i == 0 else for_training
            m.bind(cur, lab, for_training=for_training,
                   inputs_need_grad=need, force_rebind=force_rebind)
            # next stage's data shapes = this stage's inferred output shapes
            feed = dict(cur)
            if lab:
                feed.update({n: tuple(s) for n, s in lab})
            if i + 1 < len(self._modules):
                _, out_shapes, _ = m._symbol.infer_shape(**feed)
                nxt = self._modules[i + 1]
                if len(nxt._data_names) > len(out_shapes):
                    raise ValueError(
                        "module %d expects %d inputs but module %d emits %d "
                        "outputs" % (i + 1, len(nxt._data_names), i,
                                     len(out_shapes)))
                cur = list(zip(nxt._data_names, out_shapes))
        self._for_training = for_training
        self._inputs_need_grad = inputs_need_grad
        self.binded = True

    def init_params(self, initializer=None, arg_params=None, aux_params=None,
                    **kwargs):
        assert self.binded
        for m in self._modules:
            m.init_params(initializer=initializer, arg_params=arg_params,
                          aux_params=aux_params, allow_missing=True,
                          allow_extra=True, **{k: v for k, v in kwargs.items()
                                               if k not in ("allow_missing",
                                                            "allow_extra")})
        self.params_initialized = True

    def init_optimizer(self, **kwargs):
        for m in self._modules:
            m.init_optimizer(**kwargs)

    def forward(self, data_batch, is_train=None):
        from .io import DataBatch

        batch = data_batch
        for i, m in enumerate(self._modules):
            label = (data_batch.label
                     if self._take_labels[i] else [])
            batch = DataBatch(data=list(batch.data if i == 0
                                        else self._modules[i - 1]
                                        .get_outputs()),
                              label=label)
            m.forward(batch, is_train=is_train)
        return self._modules[-1].get_outputs()

    def backward(self, out_grads=None):
        grads = out_grads
        for i in reversed(range(len(self._modules))):
            m = self._modules[i]
            m.backward(grads)
            if i > 0:
                grads = m.get_input_grads()

    def update(self):
        for m in self._modules:
            m.update()

    def get_outputs(self):
        return self._modules[-1].get_outputs()

    def get_input_grads(self):
        assert self._inputs_need_grad
        return self._modules[0].get_input_grads()

    def get_params(self):
        arg, aux = {}, {}
        for m in self._modules:
            a, x = m.get_params()
            arg.update(a)
            aux.update(x)
        return arg, aux
