"""Testing helpers (ref: python/mxnet/test_utils.py)."""
from __future__ import annotations

import numpy as np

from .context import current_context
from .ndarray import NDArray, array


def default_context():
    return current_context()


def set_default_context(ctx):
    """(ref: test_utils.py:set_default_context) — make ``ctx`` the ambient
    default for factory calls outside explicit Context scopes."""
    from . import context as _ctx_mod

    _ctx_mod._default = ctx


def list_gpus():
    """(ref: test_utils.py:list_gpus) — REAL accelerator ordinals (the cpu
    fallback device does not count). mx.gpu() is the accelerator alias
    here, so the standard upstream gate ``mx.gpu() if list_gpus() else
    mx.cpu()`` keeps selecting the TPU on TPU hosts and cpu elsewhere."""
    from .context import _accel_devices

    try:
        devs = _accel_devices()
    except RuntimeError:
        return []
    return [d.id for d in devs if d.platform != "cpu"]


def _np(x):
    return x.asnumpy() if isinstance(x, NDArray) else np.asarray(x)


def assert_almost_equal(a, b, rtol=1e-5, atol=1e-8, names=("a", "b")):
    np.testing.assert_allclose(_np(a), _np(b), rtol=rtol, atol=atol)


def almost_equal(a, b, rtol=1e-5, atol=1e-8):
    return np.allclose(_np(a), _np(b), rtol=rtol, atol=atol)


def same(a, b):
    return np.array_equal(_np(a), _np(b))


def rand_ndarray(shape, dtype=np.float32, ctx=None):
    return array(np.random.randn(*shape).astype(dtype), ctx=ctx)


def rand_shape_nd(ndim, dim=10):
    return tuple(np.random.randint(1, dim + 1, size=ndim))


def check_numeric_gradient(fn, inputs, eps=1e-3, rtol=1e-2, atol=1e-3,
                           n_checks=5):
    """Finite-difference check of autograd gradients of scalar fn(*inputs)."""
    from . import autograd

    arrs = [array(_np(x)) for x in inputs]
    for a in arrs:
        a.attach_grad()
    with autograd.record():
        out = fn(*arrs)
    out.backward()

    vals = [_np(a).copy() for a in arrs]

    def eval_at(vs):
        return float(_np(fn(*[array(v) for v in vs])).sum())

    for k, a in enumerate(arrs):
        g = a.grad.asnumpy().ravel()
        flat = vals[k].ravel()
        rng = np.random.RandomState(0)
        for i in rng.choice(flat.size, size=min(n_checks, flat.size), replace=False):
            orig = flat[i]
            flat[i] = orig + eps
            fp = eval_at(vals)
            flat[i] = orig - eps
            fm = eval_at(vals)
            flat[i] = orig
            fd = (fp - fm) / (2 * eps)
            if not np.isclose(g[i], fd, rtol=rtol, atol=atol):
                raise AssertionError(
                    "gradient mismatch at input %d elem %d: autograd %g vs fd %g"
                    % (k, i, g[i], fd))
    return True


def assert_exception(f, exception_type, *args, **kwargs):
    """Assert f(*args, **kwargs) raises exception_type (ref:
    test_utils.py:assert_exception)."""
    try:
        f(*args, **kwargs)
    except exception_type:
        return
    raise AssertionError("%r did not raise %s" % (f, exception_type.__name__))


def check_symbolic_forward(sym, inputs, expected, rtol=1e-5, atol=1e-8):
    """Bind ``sym`` with positional input arrays (matched to
    list_arguments order) and compare outputs against ``expected``
    (ref: test_utils.py:check_symbolic_forward)."""
    names = sym.list_arguments()
    args = {n: array(_np(v)) for n, v in zip(names, inputs)}
    ex = sym.bind(args=args)
    outs = ex.forward()
    if not isinstance(expected, (list, tuple)):
        expected = [expected]
    assert len(outs) == len(expected), (
        "%d outputs vs %d expected values" % (len(outs), len(expected)))
    for o, e in zip(outs, expected):
        np.testing.assert_allclose(_np(o), _np(e), rtol=rtol, atol=atol)
    return outs


def check_symbolic_backward(sym, inputs, out_grads, expected_grads,
                            rtol=1e-5, atol=1e-8, grad_req="write"):
    """Forward+backward ``sym`` and compare input gradients (ref:
    test_utils.py:check_symbolic_backward)."""
    names = sym.list_arguments()
    args = {n: array(_np(v)) for n, v in zip(names, inputs)}
    grads = {n: array(np.zeros_like(_np(v))) for n, v in zip(names, inputs)}
    ex = sym.bind(args=args, args_grad=grads, grad_req=grad_req)
    ex.forward(is_train=True)
    ex.backward([array(_np(g)) for g in out_grads]
                if isinstance(out_grads, (list, tuple))
                else array(_np(out_grads)))
    if isinstance(expected_grads, dict):
        items = expected_grads.items()
    else:
        assert len(names) == len(expected_grads), (
            "%d arguments vs %d expected gradients"
            % (len(names), len(expected_grads)))
        items = zip(names, expected_grads)
    for n, e in items:
        np.testing.assert_allclose(_np(ex.grad_dict[n]), _np(e),
                                   rtol=rtol, atol=atol)
    return ex.grad_dict
