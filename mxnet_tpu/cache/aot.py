"""AOT-compiled dispatch with a persistent disk tier (``AotFn``).

``jax.jit`` compiles lazily inside an opaque per-wrapper cache: the
executable can be neither exported (snapshot artifacts) nor seeded from
disk. ``AotFn`` makes the compile step explicit — ``lower()`` /
``compile()`` per input signature — so every program has a handle that can
be serialized, preloaded, and content-addressed in the cross-process store
(store.py), while the call path stays one dict lookup.

Two modes:

* multi-signature (default): the executor-pool / ``base.jitted`` shape —
  one wrapper serves many input signatures (buckets, op shapes); the sig
  is computed per call from leaf shapes/dtypes;
* ``single_signature=True``: the decode-loop shape — one wrapper is only
  ever called with ONE signature (fixed capacity/slots), so the hot path
  skips signature computation entirely: attribute read → call.

Robustness contract: a preloaded or deserialized executable whose avals
don't match the live call (wrong-key snapshot, reloaded params with new
shapes) raises ``TypeError`` from ``Compiled.__call__`` — the wrapper
catches exactly that, warns once, drops the bad executable and re-acquires
through lower/compile. Never a crash, one recompile.

Calls that arrive under an active trace (``jax.vjp`` over a hybrid block's
compiled fn while recording) cannot run a ``Compiled`` — they transparently
fall through to the equivalent ``jax.jit`` wrapper, which inlines under
the outer trace.
"""
from __future__ import annotations

import warnings

import numpy as np

import jax


def _arg_sig(args, kwargs):
    """Hashable signature of a call: pytree structure + per-leaf
    (shape, dtype, weak_type) for arrays, type name for Python scalars
    (value-independent: scalars are traced inputs, one program serves all
    values of a type)."""
    leaves, treedef = jax.tree_util.tree_flatten((args, kwargs))
    sig = []
    for leaf in leaves:
        shape = getattr(leaf, "shape", None)
        dtype = getattr(leaf, "dtype", None)
        if shape is not None and dtype is not None:
            sig.append((tuple(shape), str(dtype),
                        bool(getattr(leaf, "weak_type", False))))
        else:
            sig.append((type(leaf).__name__,))
    return treedef, tuple(sig)


class AotFn:
    """Per-signature AOT compile + dispatch; the one funnel between this
    stack's program builders and XLA. See the module docstring."""

    __slots__ = ("_fn", "_jit", "_execs", "_only", "_single", "tier",
                 "hint", "_warned_mismatch")

    def __init__(self, fn, donate_argnums=(), device=None, tier="jit",
                 hint="", single_signature=False):
        self._fn = fn
        kw = {}
        donate = tuple(donate_argnums or ())
        if donate:
            kw["donate_argnums"] = donate
        if device is not None:
            kw["device"] = device
        self._jit = jax.jit(fn, **kw)
        self._execs = {}      # sig -> jax.stages.Compiled
        self._only = None     # single-signature fast slot
        self._single = bool(single_signature)
        self.tier = tier
        self.hint = hint
        self._warned_mismatch = False

    # ------------------------------------------------------------ dispatch
    def __call__(self, *args, **kwargs):
        if not jax.core.trace_state_clean():
            # under an outer trace (vjp/grad over a compiled block): a
            # Compiled can't be inlined, the jit wrapper can
            return self._jit(*args, **kwargs)
        if self._single:
            compiled = self._only
            if compiled is None:
                compiled = self._acquire(args, kwargs, sig=None)
            try:
                return compiled(*args, **kwargs)
            except (TypeError, ValueError):
                self._mismatch()
                self._only = None
                return self._acquire(args, kwargs, sig=None)(*args, **kwargs)
        sig = _arg_sig(args, kwargs)
        compiled = self._execs.get(sig)
        if compiled is None:
            compiled = self._acquire(args, kwargs, sig)
        try:
            return compiled(*args, **kwargs)
        except (TypeError, ValueError):
            # aval/sharding drift at the same structural sig (params_fn
            # now returns different shapes, arrays moved device): the
            # signature is shape/dtype-level by design, so recompile once,
            # then let any genuine error surface from the fresh program
            self._mismatch()
            self._execs.pop(sig, None)
            return self._acquire(args, kwargs, sig)(*args, **kwargs)

    def _mismatch(self):
        if not self._warned_mismatch:
            self._warned_mismatch = True
            warnings.warn(
                "compiled executable for %s:%s does not match the live "
                "call signature — recompiling (stale snapshot/preload?)"
                % (self.tier, self.hint or "fn"), RuntimeWarning,
                stacklevel=3)

    # ------------------------------------------------------------ acquire
    def _acquire(self, args, kwargs, sig):
        """lower → (disk tier) → compile → (disk tier save) → cache.

        The whole acquire runs under an observability ``compile_context``
        (the serve/decode compile counters bump INSIDE the traced bodies,
        so this is where the retrace watchdog learns which program is
        being built) and its wall time feeds the compile-time gauges."""
        import time

        from . import active_store
        from ..observability import costs, note_compile, watchdog

        t0 = time.perf_counter()
        with watchdog.compile_context("%s:%s" % (self.tier,
                                                 self.hint or "fn")):
            lowered = self._jit.lower(*args, **kwargs)
            store = active_store()
            compiled = store.lookup(self.tier, lowered) if store is not None \
                else None
            if compiled is None:
                compiled = lowered.compile()
                if store is not None:
                    store.save(self.tier, lowered, compiled)
        note_compile(time.perf_counter() - t0)
        # eager cost attribution: the Compiled is in hand, profiling is
        # two XLA property reads (adopt() snapshot warm-starts have no
        # lowered handle and are skipped by design)
        costs.record_compiled(self.tier, self.hint, lowered, compiled)
        if self._single:
            self._only = compiled
        else:
            self._execs[sig if sig is not None
                        else _arg_sig(args, kwargs)] = compiled
        return compiled

    # ------------------------------------------------- snapshot interface
    @property
    def traceable(self):
        """The plain jit wrapper — for callers that need to trace through
        (``jax.vjp`` over the function while recording)."""
        return self._jit

    def sig_of(self, *args, **kwargs):
        """Public signature probe: accepts real arrays OR
        ``jax.ShapeDtypeStruct`` specs (only shape/dtype are read)."""
        return _arg_sig(args, kwargs)

    def compiled_for(self, sig=None):
        """The cached executable for ``sig`` (single-signature wrappers
        ignore it); None when not yet compiled."""
        if self._single or sig is None:
            return self._only
        return self._execs.get(sig)

    def adopt(self, compiled, sig=None):
        """Install a deserialized executable WITHOUT tracing — the
        snapshot warm-start path (zero compiles, zero traces). For
        multi-signature wrappers, ``sig`` comes from :meth:`sig_of` over
        spec structs."""
        if self._single or sig is None:
            self._only = compiled
        else:
            self._execs[sig] = compiled

    def signatures(self):
        return list(self._execs)

    def num_compiled(self):
        return (1 if self._only is not None else 0) + len(self._execs)
