"""Tier B — AOT serving snapshots (``serve.snapshot`` / ``serve.load``).

The TVM ``export_library`` idea (arXiv 1802.04799) applied to a whole
server: one artifact bundles

* the checkpoint (``checkpoint.save_for_serving`` layout for ModelServer;
  ``save_parameters`` for a generative model),
* the serving config (buckets + input specs, or slots/top_k/eos/capacity
  + warmed prompt buckets),
* the **serialized executables** of every warmed program — bucket
  dispatches for ModelServer; prefill/decode/inject/extract buckets for
  GenerativeServer.

``serve.load(prefix, snapshot=True)`` rebuilds the server by
*deserializing* those executables: no trace, no XLA compile —
``engine.serve_compile_counter`` / ``decode_compile_counter`` read 0 from
process start to the first served request. That is the horizontal-
autoscale story: a new replica is warm in seconds (process spawn + param
load + executable deserialize), not compile-minutes.

Robustness (never a crash): a truncated, stale-jaxlib, or wrong-key entry
is skipped with ONE warning and that program falls back to a lazy
recompile; a manifest from a different jax/jaxlib/backend loads params
and config but no executables (full warmup path).

Layout, for ``prefix = "export/m"``::

    m-snapshot.json     manifest (config + executable index)
    m-symbol.json       ModelServer: exported graph
    m-0000.params       checkpoint (dtype-exact npz)
    m-exec/<key>.mxc    one serialized executable per warmed program
"""
from __future__ import annotations

import json
import os
import warnings

import numpy as np

from .store import (CompCacheStore, fingerprint, load_compiled_entry,
                    pack_entry, serialize_compiled)

FORMAT = 1


def _warn(msg):
    warnings.warn(msg, RuntimeWarning, stacklevel=3)


def _exec_dir(prefix):
    return prefix + "-exec"


def _manifest_path(prefix):
    return prefix + "-snapshot.json"


def _write_exec(prefix, key, compiled):
    """Serialize one executable into the artifact; returns the manifest
    file entry or None when the backend can't serialize (the manifest
    then simply lists fewer programs — load warms those lazily)."""
    packed = serialize_compiled(compiled)
    if packed is None:
        _warn("executable %r could not be serialized on this backend — "
              "snapshot will recompile it on load" % key)
        return None
    payload, in_tree, out_tree = packed
    fname = key.replace("@", "_") + ".mxc"
    path = os.path.join(_exec_dir(prefix), fname)
    CompCacheStore.atomic_write(
        path, pack_entry(key, payload, in_tree, out_tree))
    return {"file": os.path.join(os.path.basename(_exec_dir(prefix)),
                                 fname),
            "bytes": os.path.getsize(path)}


def _read_exec(prefix, entry, key):
    path = os.path.join(os.path.dirname(prefix) or ".", entry["file"])
    compiled, _fail = load_compiled_entry(path, key,
                                          origin="snapshot executable")
    return compiled


# ---------------------------------------------------------------- saving

def save_snapshot(server, prefix, input_names=None, epoch=0):
    """Write the AOT serving artifact for a ModelServer or
    GenerativeServer. Returns the manifest path."""
    from ..serve.decoder import GenerativeServer
    from ..serve.server import ModelServer

    os.makedirs(os.path.dirname(os.path.abspath(prefix)) or ".",
                exist_ok=True)
    if isinstance(server, ModelServer):
        manifest = _save_model_snapshot(server, prefix, input_names, epoch)
    elif isinstance(server, GenerativeServer):
        manifest = _save_generative_snapshot(server, prefix, epoch)
    else:
        raise TypeError("serve.snapshot takes a ModelServer or "
                        "GenerativeServer, got %r" % type(server).__name__)
    manifest.update(format=FORMAT, fingerprint=fingerprint(),
                    name=server.name, epoch=int(epoch))
    path = _manifest_path(prefix)
    CompCacheStore.atomic_write(
        path, (json.dumps(manifest, indent=1) + "\n").encode())
    return path


def _save_model_snapshot(server, prefix, input_names, epoch):
    from ..checkpoint import save_for_serving
    from ..gluon.block import SymbolBlock

    model = server.model
    if input_names is None:
        input_names = ([s.name for s in model._inputs]
                       if isinstance(model, SymbolBlock) else ("data",))
    input_names = list(input_names)
    save_for_serving(prefix, model, epoch=epoch, input_names=input_names)
    specs = [[list(shape), str(np.dtype(dt))] for shape, dt in server._specs]
    entries = server._pool.export_executables(server._specs, server.buckets)
    if not entries:
        _warn("snapshot of %r has no warmed bucket executables — did "
              "warmup run? load will compile everything" % server.name)
    execs = {}
    for e in entries:
        fe = _write_exec(prefix, e["key"], e["compiled"])
        if fe is not None:
            fe.update(bucket=e["bucket"], donating=e["donating"])
            execs[e["key"]] = fe
    # informational for model snapshots: the exported graph already bakes
    # the quantized ops in, so load never needs to re-apply it
    return {"kind": "model", "input_names": input_names,
            "input_specs": specs, "buckets": list(server.buckets),
            "quantize": getattr(server, "quantize", None),
            "pool_state": server._pool.export_state(),
            "executables": execs}


def _save_generative_snapshot(server, prefix, epoch):
    params_file = "%s-%04d.params" % (prefix, epoch)
    server.model.save_parameters(params_file)
    entries = server.export_executables()
    if not entries:
        _warn("snapshot of %r has no compiled decode programs — did "
              "warmup run? load will compile everything" % server.name)
    execs = {}
    for e in entries:
        fe = _write_exec(prefix, e["key"], e["compiled"])
        if fe is not None:
            fe.update(kind=e["kind"], tp=e["tp"], capacity=e["capacity"])
            execs[e["key"]] = fe
    return {"kind": "generative", "slots": server.slots,
            "top_k": server.top_k, "eos_id": server.eos_id,
            "capacity": int(server.cache.capacity),
            "prefix_cache": server.prefix is not None,
            "quantize": server._quantize,
            # speculative/chunked-prefill config: spec_k and prefill_chunk
            # are part of the compiled-program keys (window width / chunk
            # length) so load must rebuild the server with the same values;
            # the draft itself is CODE (like the model) and is passed to
            # load via draft= — "draft" here is informational
            "spec_k": server.spec_k,
            "prefill_chunk": server._prefill_chunk,
            "draft": (type(server._draft).__name__
                      if server._draft is not None else None),
            "prompt_buckets": sorted({tp for tp, _ in server._prefill_fns}),
            "executables": execs}


# --------------------------------------------------------------- loading

def load_manifest(prefix):
    with open(_manifest_path(prefix)) as fh:
        m = json.load(fh)
    if m.get("format") != FORMAT:
        raise ValueError("snapshot %r has format %r, this build reads %d"
                         % (prefix, m.get("format"), FORMAT))
    return m


def load_snapshot(prefix, model=None, **server_kwargs):
    """Rebuild a server from a snapshot artifact. ``model`` is required
    for generative snapshots (the decode protocol lives in code; params
    are loaded from the artifact). Extra kwargs go to the server
    constructor (queue/deadline knobs — they are process policy, not part
    of the artifact)."""
    manifest = load_manifest(prefix)
    fp = fingerprint()
    use_execs = manifest.get("fingerprint") == fp
    if not use_execs:
        _warn("snapshot %r was built by %r but this process is %r — "
              "loading checkpoint/config only, programs will recompile"
              % (prefix, manifest.get("fingerprint"), fp))
    if manifest["kind"] == "model":
        return _load_model_snapshot(prefix, manifest, use_execs,
                                    server_kwargs)
    if manifest["kind"] == "generative":
        return _load_generative_snapshot(prefix, manifest, model,
                                         use_execs, server_kwargs)
    raise ValueError("unknown snapshot kind %r" % manifest["kind"])


def _load_model_snapshot(prefix, manifest, use_execs, server_kwargs):
    from ..checkpoint import load_for_serving
    from ..serve.server import ModelServer

    block = load_for_serving(prefix, epoch=manifest.get("epoch", 0),
                             input_names=manifest["input_names"])
    specs = [(tuple(shape), dt) for shape, dt in manifest["input_specs"]]
    server_kwargs.setdefault("buckets", tuple(manifest["buckets"]))
    srv = ModelServer(block, specs, warmup=not use_execs, **server_kwargs)
    if not use_execs:
        return srv
    srv._pool.restore_state(manifest.get("pool_state") or {})
    entries = []
    for key, fe in sorted(manifest.get("executables", {}).items()):
        compiled = _read_exec(prefix, fe, key)
        if compiled is not None:
            entries.append({"bucket": fe["bucket"],
                            "donating": fe["donating"],
                            "compiled": compiled})
    srv._pool.preload_executables(entries, srv._specs)
    if not srv._pool.row_aligned:
        # incomplete artifact (hand-edited manifest?): fall back to the
        # proving warmup rather than serve with unknown output layout
        _warn("snapshot %r carried no pool state — running warmup" % prefix)
        srv.warmup()
    return srv


def _load_generative_snapshot(prefix, manifest, model, use_execs,
                              server_kwargs):
    from ..serve.decoder import GenerativeServer

    if model is None:
        raise TypeError(
            "generative snapshots need the model instance: "
            "serve.load(prefix, snapshot=True, model=my_model) — the "
            "decode protocol is code; only params/config/executables are "
            "in the artifact")
    quantize = manifest.get("quantize") or server_kwargs.pop("quantize",
                                                             None)
    if quantize:
        # the checkpoint holds the QUANTIZED parameter tree (qweight/
        # w_scale under structural names) — swap the layers first so
        # load_parameters finds matching slots, then load bit-exact (the
        # server ctor's re-quantize is an idempotent no-op on swapped
        # layers)
        from ..quantization import quantize_model

        params = model.collect_params()
        if any(p._data is None and p._deferred_init is None
               for p in params.values()):
            # bare skeleton (the usual serve.load(model=gpt_nano()) call):
            # QuantizedDense derives qweight from a materialized fp32
            # weight at swap time, so give the skeleton throwaway values —
            # load_parameters overwrites every slot bit-exactly below
            model.initialize()
        quantize_model(model, mode=quantize)
    model.load_parameters("%s-%04d.params" % (prefix,
                                              manifest.get("epoch", 0)))
    # window width / chunk length are baked into the exported programs —
    # rebuild with the artifact's values unless the caller overrides (the
    # override then recompiles, with AotFn's one-warning recovery)
    server_kwargs.setdefault("spec_k", manifest.get("spec_k", 4))
    server_kwargs.setdefault("prefill_chunk",
                             manifest.get("prefill_chunk"))
    srv = GenerativeServer(model, slots=manifest["slots"],
                           top_k=manifest["top_k"],
                           eos_id=manifest["eos_id"],
                           prefix_cache=manifest.get("prefix_cache", True),
                           quantize=quantize,
                           **server_kwargs)
    if manifest.get("draft") and srv._draft is None:
        _warn("snapshot %r was built with a %s draft but load got no "
              "draft= — speculative programs in the artifact are skipped "
              "and the server decodes plain" % (prefix, manifest["draft"]))
    # allocate the cache at the snapshot's capacity bucket up front — a
    # fresh zero alloc, NOT a migration dispatch — so the preloaded
    # programs (all specialized to this capacity) match from token one
    if manifest.get("capacity"):
        srv.cache.ensure_capacity(manifest["capacity"])
    if not use_execs:
        return srv
    for key, fe in sorted(manifest.get("executables", {}).items()):
        if fe["kind"] in ("verify", "draftstep", "draftfill") \
                and srv._draft is None:
            continue   # warned above: no draft, plain decode only
        if fe["kind"] == "chunk" and srv._prefill_chunk is None:
            continue   # chunking disabled by a caller override
        compiled = _read_exec(prefix, fe, key)
        if compiled is not None:
            srv.preload_executable(fe["kind"], fe["tp"], fe["capacity"],
                                   compiled)
    return srv
