"""mxnet_tpu.cache — persistent cross-process compilation layer.

Two tiers (ISSUE: warm replicas in seconds, not compile-minutes):

* **Tier A — the executable store** (store.py): every jit funnel
  (``base.jitted``/``bulk_jitted``/``tape_jitted``, serve bucket and
  decode-step warmups, the hybrid compiled call) persists its compiled
  XLA executable to ``MXNET_COMP_CACHE_DIR``, content-addressed by the
  lowered HLO text + a jax/jaxlib/backend fingerprint. A fresh process
  re-traces (milliseconds) but never re-compiles (seconds-minutes) a
  program any previous process already built.
* **Tier B — AOT serving snapshots** (snapshot.py): ``serve.snapshot``
  bundles a served model's checkpoint, bucket/capacity config, input
  specs and the serialized executables of every warmed bucket into one
  artifact; ``serve.load(prefix, snapshot=True)`` rebuilds the server by
  **deserializing** those executables — no trace, no compile:
  ``engine.serve_compile_counter`` / ``decode_compile_counter`` stay 0
  from process start to the first served request.

The store is disabled by default; set ``MXNET_COMP_CACHE_DIR`` (cap via
``MXNET_COMP_CACHE_CAP`` bytes) or call :func:`configure`. Snapshots are
explicit artifacts and work regardless of the store.
"""
from __future__ import annotations

import os

from .aot import AotFn  # noqa: F401  (re-export)
from .store import CompCacheStore, fingerprint  # noqa: F401

__all__ = ["AotFn", "CompCacheStore", "configure", "active_store",
           "enabled", "disable", "fingerprint", "stats", "traceable"]

_STORE = None
_ENV_CHECKED = False


def configure(directory, cap_bytes=None):
    """Enable the persistent executable store at ``directory`` (created on
    first write). Returns the store. Also seeds jax's persistent
    compilation cache fallback lazily if executable serialization turns
    out to be unsupported on the backend."""
    global _STORE, _ENV_CHECKED
    _STORE = CompCacheStore(directory, cap_bytes=cap_bytes)
    _ENV_CHECKED = True
    return _STORE


def disable():
    """Turn the store off (tests; also lets a long-lived process detach
    from a remounted cache dir). In-memory compiled programs stay live."""
    global _STORE, _ENV_CHECKED
    _STORE = None
    _ENV_CHECKED = True


def active_store():
    """The live CompCacheStore, auto-configured from
    ``MXNET_COMP_CACHE_DIR`` on first call; None when disabled."""
    global _ENV_CHECKED
    if not _ENV_CHECKED:
        _ENV_CHECKED = True
        d = os.environ.get("MXNET_COMP_CACHE_DIR")
        if d:
            configure(d)
    return _STORE


def enabled():
    return active_store() is not None


def traceable(fn):
    """The trace-safe form of a compiled callable: AotFn → its jit
    wrapper; anything else passes through (it's already a jit object)."""
    return fn.traceable if isinstance(fn, AotFn) else fn


def persistent_backed(fn, device=None, donate_argnums=None, tier="jit",
                      hint=""):
    """An ``AotFn`` over ``fn`` when the store is enabled, else None (the
    caller keeps its plain ``jax.jit`` — zero added overhead on the
    default path). The one hook ``base._jit_backed`` calls."""
    if active_store() is None:
        return None
    return AotFn(fn, donate_argnums=donate_argnums or (), device=device,
                 tier=tier, hint=hint)


def stats():
    """Store snapshot for tools/diagnose.py + the engine counters; reports
    disabled state explicitly so the section always prints."""
    from .. import engine

    st = active_store()
    out = {
        "enabled": st is not None,
        "hits": engine.comp_cache_hit_counter.count,
        "misses": engine.comp_cache_miss_counter.count,
        "deserializes": engine.comp_cache_deserialize_counter.count,
    }
    if st is not None:
        out.update(st.scan())
    return out
