"""Disk-backed cross-process executable store (Tier A of mxnet_tpu.cache).

Every jit path in this stack (``base.jitted`` / ``bulk_jitted`` /
``tape_jitted``, the serve/decode warmup compiles, the hybrid-block
compiled call) builds its XLA program through one funnel; this store sits
under that funnel and persists the COMPILED executable across processes —
the TVM ``export_library`` idea (arXiv 1802.04799) applied to jit caches:
compile once, ship the artifact, load and serve.

Content-addressed keying: an entry's identity is the sha256 of the
**lowered StableHLO text** plus the backend/version fingerprint.  The
in-process caches key structurally (interned signatures, chain topology)
because they must be O(1) on the hot path; those keys are process-local
(intern ids are list indices).  The HLO text is what those keys *denote*,
is deterministic across processes for the same program, and makes wrong-key
collisions structurally impossible — two different programs cannot share a
digest.  Tracing still happens on a warm start (cheap, milliseconds); the
XLA compile (seconds-to-minutes on TPU) is what the store skips.

Discipline:

* single-writer atomic files — entries are written to a unique temp name
  and ``os.replace``d into place, so concurrent processes racing on the
  same key can never expose a torn read (last writer wins, both wrote the
  same bytes anyway);
* corruption / version mismatch is NEVER fatal: a truncated, stale-jaxlib
  or foreign entry logs one warning and falls back to a recompile;
* mtime+size GC: on insert, when the store exceeds ``MXNET_COMP_CACHE_CAP``
  bytes, oldest-mtime entries are evicted first (reads touch mtime, so the
  policy is LRU-ish without an index file);
* proof-hook counters mirror the ``*_compile_counter`` discipline:
  ``engine.comp_cache_hit_counter`` / ``comp_cache_miss_counter`` /
  ``comp_cache_deserialize_counter`` are what tests and tools/diagnose.py
  read.

The store is OFF unless ``MXNET_COMP_CACHE_DIR`` is set (or
:func:`configure` is called) — the default imperative/serving paths keep
their exact zero-overhead ``jax.jit`` dispatch.
"""
from __future__ import annotations

import hashlib
import os
import pickle
import tempfile
import threading
import warnings

SCHEMA = "mxc1"
ENTRY_MAGIC = "mxcexec1"
ENTRY_SUFFIX = ".mxc"

# tiers = subdirectories; one per jit funnel so diagnose.py can report
# per-tier entry counts and a GC sweep never mixes populations. The
# unified graph IR (mxnet_tpu.ir.lower) lowers every capture through
# base._jit_backed with the CAPTURE's tier name ("bulk"/"tape"/"symbol"),
# so cross-capture dedup upstream only ever SHRINKS a tier's population —
# one canonical program persists once, under the tier that built it first.
# "symbol" must be listed: its entries are written like any other tier's,
# and a tier missing here is invisible to scan()/gc() (unbounded growth).
TIERS = ("jit", "bulk", "tape", "hybrid", "symbol", "serve", "decode")


def _warn(msg):
    warnings.warn(msg, RuntimeWarning, stacklevel=3)


def fingerprint():
    """Backend/version fingerprint baked into every entry: a serialized
    executable is only valid for the exact jax/jaxlib pair and backend
    that produced it (PJRT gives no ABI stability across versions)."""
    import jax

    try:
        backend = jax.default_backend()
    except RuntimeError:  # backend init failed: still allow store writes
        backend = "unknown"
    import jaxlib

    return "|".join((SCHEMA, "jax=" + jax.__version__,
                     "jaxlib=" + jaxlib.__version__, backend))


def pack_entry(key, payload, in_tree, out_tree, fp=None):
    """Serialize one executable entry to bytes. ``key`` is the entry's
    logical identity (HLO digest for store entries, the manifest key for
    snapshot entries) — verified on read BEFORE the fingerprint so a
    wrong-key file is reported as wrong-key, not as stale."""
    return pickle.dumps({
        "magic": ENTRY_MAGIC,
        "key": key,
        "fingerprint": fp if fp is not None else fingerprint(),
        "payload": payload,
        "in_tree": in_tree,
        "out_tree": out_tree,
    }, protocol=pickle.HIGHEST_PROTOCOL)


def unpack_entry(data, expect_key, origin="compilation cache"):
    """Validate + unpickle one entry; returns the dict or None (with ONE
    warning) on any corruption, key mismatch, or version skew. The error
    taxonomy feeds the store counters: 'corrupt' (unreadable), 'wrong_key',
    'stale' (fingerprint skew)."""
    try:
        blob = pickle.loads(data)
        if not isinstance(blob, dict) or blob.get("magic") != ENTRY_MAGIC:
            raise ValueError("bad magic")
    except Exception as e:
        _warn("%s entry is corrupt (%s: %s) — recompiling"
              % (origin, type(e).__name__, e))
        return None, "corrupt"
    if expect_key is not None and blob.get("key") != expect_key:
        _warn("%s entry key mismatch (found %r, wanted %r) — recompiling"
              % (origin, blob.get("key"), expect_key))
        return None, "wrong_key"
    fp = fingerprint()
    if blob.get("fingerprint") != fp:
        _warn("%s entry was built by %r but this process is %r — "
              "recompiling" % (origin, blob.get("fingerprint"), fp))
        return None, "stale"
    return blob, None


def load_compiled_entry(path, expect_key, origin="compilation cache"):
    """Read + validate + deserialize an entry file into a callable
    ``jax.stages.Compiled``; None on ANY failure (one warning, never a
    crash). Returns (compiled_or_None, failure_kind_or_None)."""
    from .. import engine

    try:
        with open(path, "rb") as fh:
            data = fh.read()
    except OSError as e:
        _warn("%s entry unreadable (%s) — recompiling" % (origin, e))
        return None, "corrupt"
    blob, fail = unpack_entry(data, expect_key, origin=origin)
    if blob is None:
        return None, fail
    try:
        from jax.experimental import serialize_executable as se

        compiled = se.deserialize_and_load(
            blob["payload"], blob["in_tree"], blob["out_tree"])
    except Exception as e:
        _warn("%s entry failed to deserialize (%s: %s) — recompiling"
              % (origin, type(e).__name__, e))
        return None, "corrupt"
    engine.comp_cache_deserialize_counter.bump()
    return compiled, None


def serialize_compiled(compiled):
    """(payload, in_tree, out_tree) for a ``jax.stages.Compiled``, or None
    when this backend's PJRT client does not support executable
    serialization (the caller then falls back to jax's own persistent
    compilation cache, which caches at the HLO level instead)."""
    try:
        from jax.experimental import serialize_executable as se

        return se.serialize(compiled)
    except Exception:
        return None


class CompCacheStore:
    """One directory of persisted executables, in tier subdirectories.

    Thread-safe for the write path (a lock guards GC bookkeeping); reads
    are lock-free. All sizes are bytes. See the module docstring for the
    on-disk discipline.
    """

    def __init__(self, directory, cap_bytes=None):
        self.directory = os.path.abspath(directory)
        if cap_bytes is None:
            try:
                cap_bytes = int(os.environ.get("MXNET_COMP_CACHE_CAP",
                                               2 << 30))
            except ValueError:
                cap_bytes = 2 << 30
        self.cap_bytes = int(cap_bytes)
        self._lock = threading.Lock()
        self._serialization_broken = False
        # store-side counters (process-local; the cross-path hit/miss/
        # deserialize counters live in engine with the other proof hooks)
        self.writes = 0
        self.evictions = 0
        self.stale = 0
        self.corrupt = 0
        self.wrong_key = 0

    # ------------------------------------------------------------ keying
    def digest(self, key_text):
        """Content digest of a program: fingerprint + lowered HLO text."""
        h = hashlib.sha256()
        h.update(fingerprint().encode())
        h.update(b"\0")
        h.update(key_text.encode() if isinstance(key_text, str)
                 else key_text)
        return h.hexdigest()

    def entry_path(self, tier, digest):
        return os.path.join(self.directory, tier, digest + ENTRY_SUFFIX)

    # ------------------------------------------------------------ lookup
    def lookup(self, tier, lowered):
        """Compiled executable for a ``jax.stages.Lowered``, or None.
        Bumps engine.comp_cache_hit_counter / comp_cache_miss_counter."""
        from .. import engine

        digest = self.digest(lowered.as_text())
        path = self.entry_path(tier, digest)
        if not os.path.exists(path):
            engine.comp_cache_miss_counter.bump()
            return None
        compiled, fail = load_compiled_entry(path, digest)
        if compiled is None:
            with self._lock:
                if fail == "stale":
                    self.stale += 1
                elif fail == "wrong_key":
                    self.wrong_key += 1
                else:
                    self.corrupt += 1
            # a bad entry will never become good; drop it so the next
            # process pays one compile, not one warning per lookup
            try:
                os.remove(path)
            except OSError:
                pass
            engine.comp_cache_miss_counter.bump()
            return None
        engine.comp_cache_hit_counter.bump()
        try:  # LRU-ish GC signal: reads refresh mtime
            os.utime(path, None)
        except OSError:
            pass
        return compiled

    # ------------------------------------------------------------ insert
    def save(self, tier, lowered, compiled):
        """Persist a freshly compiled executable; best-effort (a full disk
        or unsupported backend degrades to 'no persistence', never an
        error). Returns True when the entry landed."""
        if self._serialization_broken:
            return False
        packed = serialize_compiled(compiled)
        if packed is None:
            # executable serialization unsupported on this backend: fall
            # back to jax's persistent compilation cache (HLO-level — it
            # skips the XLA compile but not the executable load) once
            self._serialization_broken = True
            self._enable_xla_fallback()
            return False
        payload, in_tree, out_tree = packed
        digest = self.digest(lowered.as_text())
        path = self.entry_path(tier, digest)
        try:
            data = pack_entry(digest, payload, in_tree, out_tree)
            self.atomic_write(path, data)
        except Exception as e:
            _warn("compilation cache write failed (%s: %s) — continuing "
                  "without persistence for this entry"
                  % (type(e).__name__, e))
            return False
        with self._lock:
            self.writes += 1
        self.gc()
        return True

    @staticmethod
    def atomic_write(path, data):
        """Unique-temp + rename: a reader can never observe a torn entry,
        and two processes racing the same digest both write identical
        bytes — last replace wins harmlessly."""
        os.makedirs(os.path.dirname(path), exist_ok=True)
        fd, tmp = tempfile.mkstemp(dir=os.path.dirname(path),
                                   suffix=".tmp")
        try:
            with os.fdopen(fd, "wb") as fh:
                fh.write(data)
            os.replace(tmp, path)
        except BaseException:
            try:
                os.remove(tmp)
            except OSError:
                pass
            raise

    def _enable_xla_fallback(self):
        try:
            import jax

            jax.config.update("jax_compilation_cache_dir",
                              os.path.join(self.directory, "xla"))
            jax.config.update("jax_persistent_cache_min_compile_time_secs",
                              0.5)
            _warn("executable serialization unsupported on this backend — "
                  "falling back to jax's persistent compilation cache under "
                  "%s/xla" % self.directory)
        except Exception:
            pass

    # ---------------------------------------------------------------- GC
    def _entries(self):
        """[(path, mtime, size)] across all tiers (xla fallback dir is
        jax's to manage — excluded)."""
        out = []
        for tier in TIERS:
            d = os.path.join(self.directory, tier)
            if not os.path.isdir(d):
                continue
            for name in os.listdir(d):
                if not name.endswith(ENTRY_SUFFIX):
                    continue
                p = os.path.join(d, name)
                try:
                    st = os.stat(p)
                except OSError:
                    continue
                out.append((p, st.st_mtime, st.st_size))
        return out

    def gc(self):
        """Evict oldest-mtime entries until total bytes fit the cap.
        Eviction costs at most a recompile — entries are pure caches."""
        with self._lock:
            entries = self._entries()
            total = sum(s for _, _, s in entries)
            if total <= self.cap_bytes:
                return 0
            evicted = 0
            for p, _, s in sorted(entries, key=lambda e: e[1]):
                if total <= self.cap_bytes:
                    break
                try:
                    os.remove(p)
                except OSError:
                    continue
                total -= s
                evicted += 1
            self.evictions += evicted
            return evicted

    # ------------------------------------------------------------- stats
    def scan(self):
        """Per-tier {entries, bytes} + totals — the diagnose.py payload."""
        tiers = {}
        total_n = total_b = 0
        for tier in TIERS:
            d = os.path.join(self.directory, tier)
            n = b = 0
            if os.path.isdir(d):
                for name in os.listdir(d):
                    if name.endswith(ENTRY_SUFFIX):
                        try:
                            b += os.path.getsize(os.path.join(d, name))
                            n += 1
                        except OSError:
                            pass
            tiers[tier] = {"entries": n, "bytes": b}
            total_n += n
            total_b += b
        return {"dir": self.directory, "cap_bytes": self.cap_bytes,
                "entries": total_n, "bytes": total_b, "tiers": tiers,
                "writes": self.writes, "evictions": self.evictions,
                "stale": self.stale, "corrupt": self.corrupt,
                "wrong_key": self.wrong_key}
