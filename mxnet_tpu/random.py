"""Stateful randomness over JAX's threefry counters.

MXNet keeps per-device mt19937 states seeded by ``mx.random.seed`` (ref:
src/resource.cc, python/mxnet/random.py). The TPU-native design keeps a single
root threefry key and derives a fresh subkey per draw with ``fold_in`` on a
monotone counter — deterministic under a seed, cheap, and safe to use inside
jitted code when the key is threaded explicitly (the traced path does that; see
mxnet_tpu/_trace.py).
"""
from __future__ import annotations

import threading

import jax
import jax.numpy as jnp

_state = threading.local()


def _ensure():
    if not hasattr(_state, "key"):
        # concrete even when first touched inside a jit trace (a staged key
        # stored in module state would leak a tracer out of the trace)
        with jax.ensure_compile_time_eval():
            _state.key = jax.random.PRNGKey(0)
        _state.counter = 0


def seed(seed_state, ctx=None):
    """mx.random.seed parity; ctx accepted for API compat (single key domain)."""
    with jax.ensure_compile_time_eval():
        _state.key = jax.random.PRNGKey(int(seed_state))
    _state.counter = 0


def next_key():
    _ensure()
    _state.counter += 1
    return jax.random.fold_in(_state.key, _state.counter)


def split(n=1):
    return [next_key() for _ in range(n)]


def get_state():
    _ensure()
    return (_state.key, _state.counter)


def set_state(st):
    _state.key, _state.counter = st


__all__ = ["seed", "next_key", "split", "get_state", "set_state"]


# ---------------------------------------------------------------- samplers
# Upstream mx.random re-exports the nd.random samplers at module level
# (ref: python/mxnet/random.py) — delegation is lazy to avoid an import
# cycle with the nd facade.
def _delegate(name):
    def f(*args, **kwargs):
        from . import nd

        return getattr(nd.random, name)(*args, **kwargs)

    f.__name__ = name
    f.__doc__ = "mx.random.%s — delegates to nd.random.%s" % (name, name)
    return f


uniform = _delegate("uniform")
normal = _delegate("normal")
randn = _delegate("randn")
randint = _delegate("randint")
exponential = _delegate("exponential")
gamma = _delegate("gamma")
poisson = _delegate("poisson")
negative_binomial = _delegate("negative_binomial")
multinomial = _delegate("multinomial")


def shuffle(data):
    """Random permutation along the first axis (ref: random.py:shuffle —
    upstream shuffles IN PLACE and returns None; same contract here)."""
    from . import nd

    data._data = nd.shuffle(data)._data


__all__ += ["uniform", "normal", "randn", "randint", "exponential",
            "gamma", "poisson", "negative_binomial", "multinomial",
            "shuffle"]
