"""Symbol graph → ONNX ModelProto (ref: python/mxnet/onnx/mx2onnx/_export_model.py
and _op_translations — the reference converts nnvm symbol nodes to ONNX nodes
one converter per op; this does the same over mxnet_tpu's Symbol DAG).

Entry points:
  export_model(block_or_symbol, params_or_shapes, ..., onnx_file)

A HybridBlock is first traced to a Symbol graph via ``block(sym.var('data'))``;
each Symbol node is then translated by a converter. Inference semantics:
BatchNorm exports running-stat normalization, Dropout exports identity-at-eval.
"""
from __future__ import annotations

import numpy as np

from . import proto as P
from ..symbol import Symbol

_CONVERTERS = {}


def register_converter(opname):
    def deco(fn):
        _CONVERTERS[opname] = fn
        return fn
    return deco


class _Ctx:
    """Per-export state: emitted nodes, initializers, name table."""

    def __init__(self, params, opset):
        self.nodes = []
        self.initializers = {}
        self.names = {}     # id(symbol) -> output value name
        self.multi = {}     # id(symbol) -> [name per output] for multi-output ops
        self.params = params
        self.opset = opset
        self._uid = 0

    def fresh(self, hint):
        self._uid += 1
        return "%s_%d" % (hint, self._uid)

    def emit(self, op_type, inputs, outputs, name=None, attrs=None):
        self.nodes.append(P.node_proto(op_type, inputs, outputs,
                                       name or self.fresh(op_type.lower()),
                                       attrs or {}))

    def const(self, hint, arr):
        """Add an initializer tensor, return its name."""
        name = self.fresh(hint)
        self.initializers[name] = np.asarray(arr)
        return name


def _pair(v, n=2):
    return list(v) if isinstance(v, (tuple, list)) else [v] * n


# ------------------------------------------------------------- op converters
# Each converter: (ctx, node, in_names) -> out_name (or list of out names).

@register_converter("Convolution")
def _conv(ctx, s, ins, out):
    a = s._attrs
    kernel = _pair(a.get("kernel"))
    nd = len(kernel)
    pads = _pair(a.get("pad", 0), nd)
    attrs = {"kernel_shape": kernel,
             "strides": _pair(a.get("stride", 1), nd),
             "pads": pads + pads,   # begin then end
             "dilations": _pair(a.get("dilate", 1), nd),
             "group": int(a.get("num_group", 1))}
    ctx.emit("Conv", ins, [out], attrs=attrs)


@register_converter("Deconvolution")
def _deconv(ctx, s, ins, out):
    a = s._attrs
    kernel = _pair(a.get("kernel"))
    nd = len(kernel)
    pads = _pair(a.get("pad", 0), nd)
    attrs = {"kernel_shape": kernel,
             "strides": _pair(a.get("stride", 1), nd),
             "pads": pads + pads,
             "dilations": _pair(a.get("dilate", 1), nd),
             "group": int(a.get("num_group", 1))}
    adj = a.get("adj")
    if adj:
        attrs["output_padding"] = _pair(adj, nd)
    ctx.emit("ConvTranspose", ins, [out], attrs=attrs)


@register_converter("FullyConnected")
def _fc(ctx, s, ins, out):
    a = s._attrs
    x = ins[0]
    if a.get("flatten", True):
        flat = ctx.fresh("flatten")
        ctx.emit("Flatten", [x], [flat], attrs={"axis": 1})
        # Gemm: Y = X·Wᵀ + b  (MXNet weight is (num_hidden, in))
        gemm_in = [flat, ins[1]] + ins[2:3]
        ctx.emit("Gemm", gemm_in, [out], attrs={"transB": 1, "alpha": 1.0, "beta": 1.0})
    else:
        # N-D input: MatMul against Wᵀ then Add bias
        wt = ctx.fresh("w_t")
        ctx.emit("Transpose", [ins[1]], [wt], attrs={"perm": [1, 0]})
        mm = ctx.fresh("matmul") if len(ins) > 2 else out
        ctx.emit("MatMul", [x, wt], [mm])
        if len(ins) > 2:
            ctx.emit("Add", [mm, ins[2]], [out])


@register_converter("BatchNorm")
def _bn(ctx, s, ins, out):
    a = s._attrs
    # inputs arrive as (x, gamma, beta, moving_mean, moving_var) = ONNX order
    ctx.emit("BatchNormalization", ins[:5], [out],
             attrs={"epsilon": float(a.get("eps", 1e-5)),
                    "momentum": float(a.get("momentum", 0.9))})


@register_converter("LayerNorm")
def _ln(ctx, s, ins, out):
    a = s._attrs
    ctx.emit("LayerNormalization", ins[:3], [out],
             attrs={"axis": int(a.get("axis", -1)),
                    "epsilon": float(a.get("eps", 1e-5))})


_ACT2ONNX = {"relu": "Relu", "sigmoid": "Sigmoid", "tanh": "Tanh",
             "softrelu": "Softplus", "softsign": "Softsign"}


@register_converter("Activation")
def _act(ctx, s, ins, out):
    act = s._attrs.get("act_type", "relu")
    if act in ("gelu", "gelu_erf"):
        # exact-erf gelu decomposed for opset 13 (ONNX Gelu is opset 20):
        # 0.5 * x * (1 + erf(x / sqrt(2)))
        inv = ctx.const("gelu_inv_sqrt2", np.float32(1.0 / np.sqrt(2.0)))
        sc = ctx.fresh("gelu_scaled")
        ctx.emit("Mul", [ins[0], inv], [sc])
        er = ctx.fresh("gelu_erf")
        ctx.emit("Erf", [sc], [er])
        one = ctx.const("gelu_one", np.float32(1.0))
        ad = ctx.fresh("gelu_1p")
        ctx.emit("Add", [er, one], [ad])
        half = ctx.const("gelu_half", np.float32(0.5))
        hx = ctx.fresh("gelu_halfx")
        ctx.emit("Mul", [ins[0], half], [hx])
        ctx.emit("Mul", [hx, ad], [out])
        return
    ctx.emit(_ACT2ONNX[act], ins[:1], [out])


@register_converter("LeakyReLU")
def _leaky(ctx, s, ins, out):
    a = s._attrs
    act = a.get("act_type", "leaky")
    if act == "leaky":
        ctx.emit("LeakyRelu", ins[:1], [out],
                 attrs={"alpha": float(a.get("slope", 0.25))})
    elif act == "elu":
        ctx.emit("Elu", ins[:1], [out], attrs={"alpha": float(a.get("slope", 0.25))})
    elif act == "prelu":
        ctx.emit("PRelu", ins[:2], [out])
    elif act == "gelu":
        ctx.emit("Gelu", ins[:1], [out])
    elif act == "selu":
        ctx.emit("Selu", ins[:1], [out])
    else:
        raise ValueError("cannot export LeakyReLU act_type=%s" % act)


@register_converter("Pooling")
def _pool(ctx, s, ins, out):
    a = s._attrs
    ptype = a.get("pool_type", "max")
    if a.get("global_pool"):
        op = {"max": "GlobalMaxPool", "avg": "GlobalAveragePool"}[ptype]
        ctx.emit(op, ins[:1], [out])
        return
    kernel = _pair(a.get("kernel"))
    nd = len(kernel)
    pads = _pair(a.get("pad", 0), nd)
    attrs = {"kernel_shape": kernel,
             "strides": _pair(a.get("stride") or a.get("kernel"), nd),
             "pads": pads + pads}
    if ptype == "avg":
        attrs["count_include_pad"] = int(bool(a.get("count_include_pad", True)))
        ctx.emit("AveragePool", ins[:1], [out], attrs=attrs)
    elif ptype == "max":
        ctx.emit("MaxPool", ins[:1], [out], attrs=attrs)
    elif ptype == "lp":
        attrs["p"] = int(a.get("p_value", 2))
        ctx.emit("LpPool", ins[:1], [out], attrs=attrs)
    else:
        raise ValueError("cannot export pool_type=%s" % ptype)


@register_converter("Dropout")
def _dropout(ctx, s, ins, out):
    ctx.emit("Dropout", ins[:1], [out],
             attrs={})  # inference: identity; ratio only matters in training


@register_converter("Embedding")
def _embedding(ctx, s, ins, out):
    # F.Embedding(indices, weight) → Gather(weight, indices)
    ctx.emit("Gather", [ins[1], ins[0]], [out], attrs={"axis": 0})


@register_converter("flatten")
def _flatten(ctx, s, ins, out):
    ctx.emit("Flatten", ins, [out], attrs={"axis": 1})


@register_converter("softmax")
def _softmax(ctx, s, ins, out):
    ctx.emit("Softmax", ins[:1], [out], attrs={"axis": int(s._attrs.get("axis", -1))})


@register_converter("log_softmax")
def _log_softmax(ctx, s, ins, out):
    ctx.emit("LogSoftmax", ins[:1], [out], attrs={"axis": int(s._attrs.get("axis", -1))})


@register_converter("concat")
def _concat(ctx, s, ins, out):
    ctx.emit("Concat", ins, [out], attrs={"axis": int(s._attrs.get("dim", 1))})


@register_converter("reshape")
def _reshape(ctx, s, ins, out):
    shape = ctx.const("shape", np.asarray(s._attrs["shape"], np.int64))
    ctx.emit("Reshape", [ins[0], shape], [out])


@register_converter("transpose")
def _transpose(ctx, s, ins, out):
    attrs = {}
    if s._attrs.get("axes") is not None:
        attrs["perm"] = list(s._attrs["axes"])
    ctx.emit("Transpose", ins, [out], attrs=attrs)


@register_converter("expand_dims")
def _expand_dims(ctx, s, ins, out):
    axes = ctx.const("axes", np.asarray([s._attrs["axis"]], np.int64))
    ctx.emit("Unsqueeze", [ins[0], axes], [out])


@register_converter("squeeze")
def _squeeze(ctx, s, ins, out):
    ax = s._attrs.get("axis")
    if ax is None:
        ctx.emit("Squeeze", ins, [out])
    else:
        ax = [ax] if isinstance(ax, int) else list(ax)
        axes = ctx.const("axes", np.asarray(ax, np.int64))
        ctx.emit("Squeeze", [ins[0], axes], [out])


@register_converter("clip")
def _clip(ctx, s, ins, out):
    # each bound independently from attrs (keyword form) or the next _const
    # input (positional form) — mixed calls like clip(x, -1, a_max=1) are
    # legal Python and record one of each
    nxt = [1]

    def bound(name):
        if name in s._attrs:
            return s._attrs[name]
        inp = s._inputs[nxt[0]]
        nxt[0] += 1
        if inp._op != "_const":
            raise ValueError(
                "clip: %s must be a scalar constant for ONNX export" % name)
        return inp._attrs["value"]

    lo = ctx.const("min", np.float32(bound("a_min")))
    hi = ctx.const("max", np.float32(bound("a_max")))
    ctx.emit("Clip", [ins[0], lo, hi], [out])


def _reduce(onnx_op):
    def conv(ctx, s, ins, out):
        a = s._attrs
        attrs = {"keepdims": int(bool(a.get("keepdims", False)))}
        ax = a.get("axis")
        axes = None if ax is None else ([ax] if isinstance(ax, int) else list(ax))
        if onnx_op == "ReduceSum" and axes is not None:
            # opset 13 moved ReduceSum's axes from attribute to input
            axes_in = ctx.const("axes", np.asarray(axes, np.int64))
            ctx.emit(onnx_op, [ins[0], axes_in], [out], attrs=attrs)
            return
        if axes is not None:
            attrs["axes"] = axes
        ctx.emit(onnx_op, ins[:1], [out], attrs=attrs)
    return conv


for _mx, _onnx in [("mean", "ReduceMean"), ("sum", "ReduceSum"),
                   ("max", "ReduceMax"), ("min", "ReduceMin"),
                   ("prod", "ReduceProd")]:
    register_converter(_mx)(_reduce(_onnx))


def _binop(onnx_op):
    def conv(ctx, s, ins, out):
        ctx.emit(onnx_op, ins[:2], [out])
    return conv


for _mx, _onnx in [("add", "Add"), ("subtract", "Sub"), ("multiply", "Mul"),
                   ("divide", "Div"), ("power", "Pow"), ("maximum", "Max"),
                   ("minimum", "Min"), ("broadcast_add", "Add"),
                   ("broadcast_sub", "Sub"), ("broadcast_mul", "Mul"),
                   ("broadcast_div", "Div"), ("broadcast_power", "Pow"),
                   ("broadcast_maximum", "Max"), ("broadcast_minimum", "Min"),
                   ("dot", "MatMul"), ("matmul", "MatMul"),
                   ("batch_dot", "MatMul")]:
    register_converter(_mx)(_binop(_onnx))


def _unop(onnx_op):
    def conv(ctx, s, ins, out):
        ctx.emit(onnx_op, ins[:1], [out])
    return conv


for _mx, _onnx in [("relu", "Relu"), ("sigmoid", "Sigmoid"), ("tanh", "Tanh"),
                   ("exp", "Exp"), ("log", "Log"), ("sqrt", "Sqrt"),
                   ("negative", "Neg"), ("abs", "Abs"), ("floor", "Floor"),
                   ("ceil", "Ceil"), ("round", "Round"), ("erf", "Erf"),
                   ("sin", "Sin"), ("cos", "Cos"), ("tan", "Tan"),
                   ("reciprocal", "Reciprocal"), ("sign", "Sign"),
                   ("softsign", "Softsign"), ("softrelu", "Softplus")]:
    register_converter(_mx)(_unop(_onnx))


for _mx, _onnx in [("arcsin", "Asin"), ("arccos", "Acos"),
                   ("arctan", "Atan"), ("sinh", "Sinh"), ("cosh", "Cosh"),
                   ("arcsinh", "Asinh"), ("arccosh", "Acosh"),
                   ("arctanh", "Atanh")]:
    register_converter(_mx)(_unop(_onnx))


_F32 = 1  # onnx TensorProto.FLOAT


def _cmpop(onnx_op, negate=False):
    """MXNet comparisons return float 0/1; ONNX returns bool → Cast back
    (f32, the framework's default compute dtype)."""
    def conv(ctx, s, ins, out):
        b = ctx.fresh("cmp")
        ctx.emit(onnx_op, ins[:2], [b])
        if negate:
            nb = ctx.fresh("not")
            ctx.emit("Not", [b], [nb])
            b = nb
        ctx.emit("Cast", [b], [out], attrs={"to": _F32})
    return conv


for _mx, _onnx, _neg in [
        ("broadcast_equal", "Equal", False),
        ("broadcast_not_equal", "Equal", True),
        ("broadcast_greater", "Greater", False),
        ("broadcast_greater_equal", "GreaterOrEqual", False),
        ("broadcast_lesser", "Less", False),
        ("broadcast_lesser_equal", "LessOrEqual", False),
        ("equal", "Equal", False), ("not_equal", "Equal", True),
        ("greater", "Greater", False),
        ("greater_equal", "GreaterOrEqual", False),
        ("lesser", "Less", False), ("lesser_equal", "LessOrEqual", False)]:
    register_converter(_mx)(_cmpop(_onnx, _neg))


_BOOL = 9  # onnx TensorProto.BOOL


def _logicop(onnx_op):
    def conv(ctx, s, ins, out):
        bs = []
        for i in ins[:2]:
            b = ctx.fresh("b")
            ctx.emit("Cast", [i], [b], attrs={"to": _BOOL})
            bs.append(b)
        r = ctx.fresh("logic")
        ctx.emit(onnx_op, bs, [r])
        ctx.emit("Cast", [r], [out], attrs={"to": _F32})
    return conv


for _mx, _onnx in [("logical_and", "And"), ("logical_or", "Or"),
                   ("logical_xor", "Xor"),
                   ("broadcast_logical_and", "And"),
                   ("broadcast_logical_or", "Or"),
                   ("broadcast_logical_xor", "Xor")]:
    register_converter(_mx)(_logicop(_onnx))


@register_converter("logical_not")
def _logical_not(ctx, s, ins, out):
    b = ctx.fresh("b")
    ctx.emit("Cast", ins[:1], [b], attrs={"to": _BOOL})
    r = ctx.fresh("not")
    ctx.emit("Not", [b], [r])
    ctx.emit("Cast", [r], [out], attrs={"to": _F32})


@register_converter("mod")
def _mod_conv(ctx, s, ins, out):
    # framework mod is floor modulo (jnp.mod, sign of divisor); ONNX Mod on
    # floats requires fmod=1 (truncation, sign of dividend) — decompose
    # instead: x - floor(x/y)*y, exact for both signs
    q = ctx.fresh("div")
    ctx.emit("Div", ins[:2], [q])
    fq = ctx.fresh("floor")
    ctx.emit("Floor", [q], [fq])
    prod = ctx.fresh("mul")
    ctx.emit("Mul", [fq, ins[1]], [prod])
    ctx.emit("Sub", [ins[0], prod], [out])


def _argop(onnx_op):
    def conv(ctx, s, ins, out):
        a = s._attrs
        ax = a.get("axis")
        attrs = {"axis": int(ax) if ax is not None else 0,
                 "keepdims": int(bool(a.get("keepdims", False)))}
        if ax is None:
            flat = ctx.fresh("flat")
            shp = ctx.const("shape", np.asarray([-1], np.int64))
            ctx.emit("Reshape", [ins[0], shp], [flat])
            r = ctx.fresh("arg")
            ctx.emit(onnx_op, [flat], [r], attrs=attrs)
            ctx.emit("Cast", [r], [out], attrs={"to": _F32})
            return
        r = ctx.fresh("arg")
        ctx.emit(onnx_op, ins[:1], [r], attrs=attrs)
        # MXNet argmax/argmin return float; ONNX returns int64
        ctx.emit("Cast", [r], [out], attrs={"to": _F32})
    return conv


register_converter("argmax")(_argop("ArgMax"))
register_converter("argmin")(_argop("ArgMin"))


@register_converter("norm")
def _norm_conv(ctx, s, ins, out):
    a = s._attrs
    ordv = int(a.get("ord", 2))
    op = {1: "ReduceL1", 2: "ReduceL2"}.get(ordv)
    if op is None:
        raise ValueError("norm export: only ord 1/2 map to ONNX ReduceL1/L2")
    attrs = {"keepdims": int(bool(a.get("keepdims", False)))}
    ax = a.get("axis")
    if ax is not None:
        attrs["axes"] = [ax] if isinstance(ax, int) else list(ax)
    ctx.emit(op, ins[:1], [out], attrs=attrs)


@register_converter("stack")
def _stack_conv(ctx, s, ins, out):
    axis = int(s._attrs.get("axis", 0))
    ax_in = ctx.const("axes", np.asarray([axis], np.int64))
    unsq = []
    for i in ins:
        u = ctx.fresh("unsq")
        ctx.emit("Unsqueeze", [i, ax_in], [u])
        unsq.append(u)
    ctx.emit("Concat", unsq, [out], attrs={"axis": axis})


@register_converter("take")
def _take_conv(ctx, s, ins, out):
    a = s._attrs
    mode = a.get("mode", "clip")
    if mode not in ("clip", "raise"):
        raise ValueError("take export: mode=%r unsupported" % mode)
    axis = int(a.get("axis", 0))
    idx = ctx.fresh("idx")
    ctx.emit("Cast", [ins[1]], [idx], attrs={"to": 7})  # int64 indices
    if mode == "clip":
        # ONNX Gather is out-of-bounds-undefined; reproduce MXNet's clamp
        # with Clip(idx, 0, dim-1). Static dim when the traced shape is
        # known (the usual export path), Shape-at-runtime otherwise.
        try:
            data_shape = s._inputs[0].shape
        except ValueError:
            data_shape = None
        zero = ctx.const("zero", np.asarray(0, np.int64))
        if data_shape is not None:
            hi = ctx.const("hi", np.asarray(data_shape[axis] - 1, np.int64))
        else:
            shp = ctx.fresh("shape")
            ctx.emit("Shape", [ins[0]], [shp])
            ax_c = ctx.const("axidx", np.asarray(axis, np.int64))
            dim = ctx.fresh("dim")
            ctx.emit("Gather", [shp, ax_c], [dim], attrs={"axis": 0})
            one = ctx.const("one", np.asarray(1, np.int64))
            hi = ctx.fresh("hi")
            ctx.emit("Sub", [dim, one], [hi])
        clipped = ctx.fresh("clipped")
        ctx.emit("Clip", [idx, zero, hi], [clipped])
        idx = clipped
    ctx.emit("Gather", [ins[0], idx], [out], attrs={"axis": axis})


@register_converter("InstanceNorm")
def _instance_norm_conv(ctx, s, ins, out):
    ctx.emit("InstanceNormalization", ins[:3], [out],
             attrs={"epsilon": float(s._attrs.get("eps", 1e-5))})


@register_converter("LRN")
def _lrn_conv(ctx, s, ins, out):
    a = s._attrs
    ctx.emit("LRN", ins[:1], [out], attrs={
        "size": int(a.get("nsize", 5)), "alpha": float(a.get("alpha", 1e-4)),
        "beta": float(a.get("beta", 0.75)), "bias": float(a.get("knorm", 2.0))})


@register_converter("L2Normalization")
def _l2norm_conv(ctx, s, ins, out):
    mode = s._attrs.get("mode", "instance")
    if mode != "channel":
        raise ValueError("L2Normalization export: only mode='channel' maps "
                         "to ONNX LpNormalization (axis semantics)")
    ctx.emit("LpNormalization", ins[:1], [out], attrs={"axis": 1, "p": 2})


@register_converter("log1p")
def _log1p_conv(ctx, s, ins, out):
    one = ctx.const("one", np.float32(1.0))
    ap = ctx.fresh("add")
    ctx.emit("Add", [ins[0], one], [ap])
    ctx.emit("Log", [ap], [out])


@register_converter("expm1")
def _expm1_conv(ctx, s, ins, out):
    one = ctx.const("one", np.float32(1.0))
    e = ctx.fresh("exp")
    ctx.emit("Exp", ins[:1], [e])
    ctx.emit("Sub", [e, one], [out])


@register_converter("rsqrt")
def _rsqrt_conv(ctx, s, ins, out):
    r = ctx.fresh("sqrt")
    ctx.emit("Sqrt", ins[:1], [r])
    ctx.emit("Reciprocal", [r], [out])


@register_converter("hard_sigmoid")
def _hard_sigmoid_conv(ctx, s, ins, out):
    a = s._attrs
    ctx.emit("HardSigmoid", ins[:1], [out],
             attrs={"alpha": float(a.get("alpha", 0.2)),
                    "beta": float(a.get("beta", 0.5))})


@register_converter("depth_to_space")
def _d2s_conv(ctx, s, ins, out):
    ctx.emit("DepthToSpace", ins[:1], [out],
             attrs={"blocksize": int(s._attrs["block_size"]), "mode": "DCR"})


@register_converter("space_to_depth")
def _s2d_conv(ctx, s, ins, out):
    ctx.emit("SpaceToDepth", ins[:1], [out],
             attrs={"blocksize": int(s._attrs["block_size"])})


@register_converter("gather_nd")
def _gather_nd_export(ctx, s, ins, out):
    # MXNet gather_nd leads with the index-tuple axis; ONNX GatherND wants
    # indices (..., index_depth) — move the leading axis to the back
    try:
        rank = len(s._inputs[1].shape)
    except ValueError:
        raise ValueError("gather_nd export needs a known indices rank for "
                         "the layout transpose")
    idx = ctx.fresh("idx")
    ctx.emit("Cast", [ins[1]], [idx], attrs={"to": 7})
    tr = ctx.fresh("tr")
    ctx.emit("Transpose", [idx], [tr],
             attrs={"perm": list(range(1, rank)) + [0]})
    ctx.emit("GatherND", [ins[0], tr], [out])


@register_converter("square")
def _square(ctx, s, ins, out):
    two = ctx.const("two", np.float32(2.0))
    ctx.emit("Pow", [ins[0], two], [out])


@register_converter("slice_axis")
def _slice_axis(ctx, s, ins, out):
    a = s._attrs
    end = a.get("end")
    starts = ctx.const("starts", np.asarray([a["begin"]], np.int64))
    ends = ctx.const("ends", np.asarray(
        [end if end is not None else np.iinfo(np.int64).max], np.int64))
    axes = ctx.const("axes", np.asarray([a["axis"]], np.int64))
    ctx.emit("Slice", [ins[0], starts, ends, axes], [out])


@register_converter("_const")
def _const_conv(ctx, s, ins, out):
    val = np.asarray(s._attrs["value"], np.float32)
    ctx.initializers[out] = val


@register_converter("_filled")
def _filled_conv(ctx, s, ins, out):
    a = s._attrs
    from ..base import resolve_dtype
    ctx.initializers[out] = np.full(tuple(a["shape"]), a["value"],
                                    np.dtype(resolve_dtype(a.get("dtype", "float32"))))


@register_converter("zeros_like")
def _zeros_like_conv(ctx, s, ins, out):
    # Shape→ConstantOfShape, not Mul(x, 0): type-correct for any dtype and
    # immune to 0·inf = NaN
    shp = ctx.fresh("shape")
    ctx.emit("Shape", [ins[0]], [shp])
    ctx.emit("ConstantOfShape", [shp], [out],
             attrs={"value": np.zeros(1, np.float32)})


@register_converter("multibox_prior")
def _multibox_prior_conv(ctx, s, ins, out):
    """Anchors depend only on the (static) feature-map shape and attrs, so
    they export as a precomputed constant initializer (upstream mx2onnx
    lowers _contrib_MultiBoxPrior the same way when shapes are static)."""
    from ..base import OP_REGISTRY
    import jax

    shape = s._inputs[0].shape  # requires var shapes (set by symbol_to_onnx)
    a = dict(s._attrs)
    x = np.zeros(shape, np.float32)
    anchors = np.asarray(OP_REGISTRY["multibox_prior"].fn(x, **a))
    ctx.initializers[out] = anchors.astype(np.float32)


@register_converter("_onnx_shape")
def _onnx_shape_conv(ctx, s, ins, out):
    ctx.emit("Shape", ins[:1], [out])


@register_converter("cast")
def _cast_conv(ctx, s, ins, out):
    from ..base import resolve_dtype
    code = P.np_to_onnx_dtype(np.dtype(resolve_dtype(s._attrs["dtype"])))
    ctx.emit("Cast", ins[:1], [out], attrs={"to": int(code)})


# ---- flat legacy aliases: same semantics as an already-registered converter
def _alias_conv(target):
    def conv(ctx, s, ins, out):
        return _CONVERTERS[target](ctx, s, ins, out)
    return conv


for _legacy, _target in [("Cast", "cast"), ("Concat", "concat"),
                         ("Flatten", "flatten"), ("Reshape", "reshape"),
                         ("elemwise_add", "add"), ("elemwise_sub", "subtract"),
                         ("elemwise_mul", "multiply"),
                         ("elemwise_div", "divide"),
                         ("broadcast_mod", "mod")]:
    register_converter(_legacy)(_alias_conv(_target))


def _identity_conv(ctx, s, ins, out):
    ctx.emit("Identity", ins[:1], [out])


# BlockGrad/stop_gradient is Identity at inference (ONNX has no grad graph)
for _nm in ("identity", "BlockGrad", "stop_gradient"):
    register_converter(_nm)(_identity_conv)


@register_converter("ElementWiseSum")
def _ews_conv(ctx, s, ins, out):
    ctx.emit("Sum", ins, [out])


register_converter("add_n")(_ews_conv)


@register_converter("SwapAxis")
def _swapaxis_conv(ctx, s, ins, out):
    try:
        shape = s._inputs[0].shape  # lazy jax.eval_shape through the graph
    except ValueError:
        raise ValueError("SwapAxis export needs a known input rank")
    perm = list(range(len(shape)))
    d1 = int(s._attrs.get("dim1", 0))
    d2 = int(s._attrs.get("dim2", 0))
    perm[d1], perm[d2] = perm[d2], perm[d1]
    ctx.emit("Transpose", ins[:1], [out], attrs={"perm": perm})


@register_converter("SoftmaxActivation")
def _softmax_act_conv(ctx, s, ins, out):
    if s._attrs.get("mode", "instance") != "instance":
        raise ValueError("SoftmaxActivation export: channel mode unsupported")
    # instance mode normalizes over ALL trailing dims per sample
    # (ops/extra.py:SoftmaxActivation flattens) — rank > 2 needs the
    # flatten/softmax/restore decomposition
    shape = s._inputs[0].shape
    if len(shape) <= 2:
        ctx.emit("Softmax", ins[:1], [out], attrs={"axis": -1})
        return
    flat = ctx.fresh("sa_flat")
    ctx.emit("Reshape", [ins[0], ctx.const(
        "fshape", np.asarray([shape[0], -1], np.int64))], [flat])
    sm = ctx.fresh("sa_softmax")
    ctx.emit("Softmax", [flat], [sm], attrs={"axis": -1})
    ctx.emit("Reshape", [sm, ctx.const(
        "rshape", np.asarray(shape, np.int64))], [out])


@register_converter("hypot")
def _hypot_conv(ctx, s, ins, out):
    # overflow-safe (jnp.hypot semantics): m·sqrt(1 + (n/m)²) with
    # m = max(|x|,|y|) — naive sqrt(x²+y²) infs above ~1.8e19 in f32
    ab = []
    for i in ins[:2]:
        a = ctx.fresh("abs")
        ctx.emit("Abs", [i], [a])
        ab.append(a)
    mx = ctx.fresh("hmax")
    ctx.emit("Max", ab, [mx])
    mn = ctx.fresh("hmin")
    ctx.emit("Min", ab, [mn])
    denom = ctx.fresh("hden")
    ctx.emit("Max", [mx, ctx.const("tiny", np.float32(1e-38))], [denom])
    t = ctx.fresh("hratio")
    ctx.emit("Div", [mn, denom], [t])
    t2 = ctx.fresh("ht2")
    ctx.emit("Mul", [t, t], [t2])
    onep = ctx.fresh("h1p")
    ctx.emit("Add", [t2, ctx.const("one", np.float32(1.0))], [onep])
    rt = ctx.fresh("hsqrt")
    ctx.emit("Sqrt", [onep], [rt])
    ctx.emit("Mul", [mx, rt], [out])


register_converter("broadcast_hypot")(_CONVERTERS["hypot"])


@register_converter("mish")
def _mish_conv(ctx, s, ins, out):
    sp = ctx.fresh("softplus")
    ctx.emit("Softplus", ins[:1], [sp])
    th = ctx.fresh("tanh")
    ctx.emit("Tanh", [sp], [th])
    ctx.emit("Mul", [ins[0], th], [out])


@register_converter("log_sigmoid")
def _log_sigmoid_conv(ctx, s, ins, out):
    # log(sigmoid(x)) = -softplus(-x)
    ng = ctx.fresh("neg")
    ctx.emit("Neg", ins[:1], [ng])
    sp = ctx.fresh("softplus")
    ctx.emit("Softplus", [ng], [sp])
    ctx.emit("Neg", [sp], [out])


def _float_unop_via(onnx_pred):
    """IsNaN/IsInf return bool; MXNet isnan/isinf return float 0/1."""
    def conv(ctx, s, ins, out):
        b = ctx.fresh("pred")
        ctx.emit(onnx_pred, ins[:1], [b])
        ctx.emit("Cast", [b], [out], attrs={"to": int(P.FLOAT)})
    return conv


register_converter("isnan")(_float_unop_via("IsNaN"))
register_converter("isinf")(_float_unop_via("IsInf"))


@register_converter("isfinite")
def _isfinite_conv(ctx, s, ins, out):
    nn = ctx.fresh("isnan")
    ctx.emit("IsNaN", ins[:1], [nn])
    ii = ctx.fresh("isinf")
    ctx.emit("IsInf", ins[:1], [ii])
    either = ctx.fresh("or")
    ctx.emit("Or", [nn, ii], [either])
    nb = ctx.fresh("not")
    ctx.emit("Not", [either], [nb])
    ctx.emit("Cast", [nb], [out], attrs={"to": int(P.FLOAT)})


def _scale_by(factor, hint):
    def conv(ctx, s, ins, out):
        f = ctx.const(hint, np.float32(factor))
        ctx.emit("Mul", [ins[0], f], [out])
    return conv


def _scaled_log(base, hint):
    def conv(ctx, s, ins, out):
        ln = ctx.fresh("ln")
        ctx.emit("Log", ins[:1], [ln])
        _scale_by(1.0 / np.log(base), hint)(ctx, s, [ln], out)
    return conv


register_converter("log2")(_scaled_log(2.0, "invln2"))
register_converter("log10")(_scaled_log(10.0, "invln10"))
register_converter("degrees")(_scale_by(180.0 / np.pi, "r2d"))
register_converter("radians")(_scale_by(np.pi / 180.0, "d2r"))


@register_converter("cbrt")
def _cbrt_conv(ctx, s, ins, out):
    # sign(x)·|x|^(1/3): plain Pow would NaN on negative inputs
    sg = ctx.fresh("sign")
    ctx.emit("Sign", ins[:1], [sg])
    ab = ctx.fresh("abs")
    ctx.emit("Abs", ins[:1], [ab])
    third = ctx.const("third", np.float32(1.0 / 3.0))
    pw = ctx.fresh("pow")
    ctx.emit("Pow", [ab, third], [pw])
    ctx.emit("Mul", [sg, pw], [out])


@register_converter("trunc")
def _trunc_conv(ctx, s, ins, out):
    # trunc = sign(x)·floor(|x|)  (ONNX has no Trunc node)
    sg = ctx.fresh("sign")
    ctx.emit("Sign", ins[:1], [sg])
    ab = ctx.fresh("abs")
    ctx.emit("Abs", ins[:1], [ab])
    fl = ctx.fresh("floor")
    ctx.emit("Floor", [ab], [fl])
    ctx.emit("Mul", [sg, fl], [out])


register_converter("fix")(_CONVERTERS["trunc"])


def _emit_grid_sample(ctx, data, grid_nchw, out):
    """grid (N, 2, H, W) [-1,1] (x,y) → ONNX GridSample's (N, H, W, 2);
    MXNet's corner mapping x_src=(x+1)(W-1)/2 IS align_corners=1, and its
    zero out-of-bounds taps are padding_mode='zeros'."""
    if ctx.opset < 16:
        raise ValueError("BilinearSampler/SpatialTransformer export needs "
                         "opset>=16 (GridSample); pass opset=16 to "
                         "export_model")
    gt = ctx.fresh("gs_grid")
    ctx.emit("Transpose", [grid_nchw], [gt], attrs={"perm": [0, 2, 3, 1]})
    ctx.emit("GridSample", [data, gt], [out],
             attrs={"mode": "bilinear", "padding_mode": "zeros",
                    "align_corners": 1})


@register_converter("BilinearSampler")
def _bilinear_sampler_conv(ctx, s, ins, out):
    _emit_grid_sample(ctx, ins[0], ins[1], out)


def _emit_affine_grid(ctx, theta_name, H, W, hint, out=None):
    """theta (N, 6) → grid (N, 2, H, W): one MatMul against the constant
    homogeneous base grid (the whole GridGenerator as MXU work). Writes to
    ``out`` when given, else a fresh name (returned either way)."""
    ys = np.linspace(-1.0, 1.0, H)
    xs = np.linspace(-1.0, 1.0, W)
    gy, gx = np.meshgrid(ys, xs, indexing="ij")
    base = np.stack([gx.ravel(), gy.ravel(),
                     np.ones_like(gx).ravel()]).astype(np.float32)
    th = ctx.fresh("%s_theta" % hint)
    ctx.emit("Reshape", [theta_name, ctx.const(
        "tshape", np.asarray([0, 2, 3], np.int64))], [th])
    mm = ctx.fresh("%s_mm" % hint)
    ctx.emit("MatMul", [th, ctx.const("base", base)], [mm])
    grid = out or ctx.fresh("%s_grid" % hint)
    ctx.emit("Reshape", [mm, ctx.const(
        "gshape", np.asarray([0, 2, H, W], np.int64))], [grid])
    return grid


@register_converter("GridGenerator")
def _grid_generator_conv(ctx, s, ins, out):
    a = s._attrs
    tt = a.get("transform_type", "affine")
    if tt == "affine":
        H, W = a["target_shape"]
        _emit_affine_grid(ctx, ins[0], int(H), int(W), "gg", out=out)
        return
    if tt != "warp":
        raise ValueError("GridGenerator export: transform_type %r" % tt)
    # warp: ((flow + identity_pixel_grid) * 2/(dim-1)) - 1
    shape = s._inputs[0].shape      # (N, 2, H, W)
    H, W = int(shape[2]), int(shape[3])
    gy, gx = np.meshgrid(np.arange(H, dtype=np.float32),
                         np.arange(W, dtype=np.float32), indexing="ij")
    ident = ctx.const("ident", np.stack([gx, gy])[None])       # (1,2,H,W)
    scale = ctx.const("scale", np.asarray(
        [2.0 / max(W - 1, 1), 2.0 / max(H - 1, 1)],
        np.float32).reshape(1, 2, 1, 1))
    sm = ctx.fresh("gg_sum")
    ctx.emit("Add", [ins[0], ident], [sm])
    sc = ctx.fresh("gg_scaled")
    ctx.emit("Mul", [sm, scale], [sc])
    one = ctx.const("one", np.float32(1.0))
    ctx.emit("Sub", [sc, one], [out])


@register_converter("SpatialTransformer")
def _spatial_transformer_conv(ctx, s, ins, out):
    a = s._attrs
    if a.get("transform_type", "affine") != "affine":
        raise ValueError("SpatialTransformer export: affine only")
    tshape = a.get("target_shape") or s._inputs[0].shape[2:]
    H, W = int(tshape[0]), int(tshape[1])
    grid = _emit_affine_grid(ctx, ins[1], H, W, "st")
    _emit_grid_sample(ctx, ins[0], grid, out)


# ---- Module-era output heads: inference semantics (the label input and
# grad_scale only shape the backward, which ONNX doesn't carry)
@register_converter("SoftmaxOutput")
def _softmax_output_conv(ctx, s, ins, out):
    # matches the registry kernel exactly (ops/functional.py SoftmaxOutput:
    # softmax over the LAST axis regardless of multi_output)
    ctx.emit("Softmax", ins[:1], [out], attrs={"axis": -1})


@register_converter("LogisticRegressionOutput")
def _logistic_output_conv(ctx, s, ins, out):
    ctx.emit("Sigmoid", ins[:1], [out])


def _fwd_identity_conv(ctx, s, ins, out):
    ctx.emit("Identity", ins[:1], [out])


for _nm in ("LinearRegressionOutput", "MAERegressionOutput", "MakeLoss",
            "SVMOutput", "IdentityAttachKLSparseReg"):
    register_converter(_nm)(_fwd_identity_conv)


@register_converter("ROIAlign")
def _roi_align_conv(ctx, s, ins, out):
    """rois are (R, 5) [batch_idx, x1, y1, x2, y2] — split into ONNX
    RoiAlign's (rois (R,4), batch_indices (R,)) pair."""
    a = s._attrs
    ph, pw = a["pooled_size"]
    bcol = _slice_emit(ctx, ins[1], 0, 1, 1, "ra_bidx")
    bi = ctx.fresh("ra_bi")
    ctx.emit("Cast", [bcol], [bi], attrs={"to": 7})
    bsq = ctx.fresh("ra_bsq")
    ctx.emit("Squeeze", [bi, ctx.const("ax1", np.asarray([1], np.int64))],
             [bsq])
    boxes = _slice_emit(ctx, ins[1], 1, 5, 1, "ra_boxes")
    attrs = {"output_height": int(ph), "output_width": int(pw),
             "spatial_scale": float(a.get("spatial_scale", 1.0)),
             "sampling_ratio": int(a.get("sample_ratio", 2)),
             "mode": "avg"}
    if ctx.opset >= 16:
        # our _roi_grid samples WITHOUT the -0.5 pixel-center offset — that
        # is opset-16's 'output_half_pixel' (the legacy behavior); the
        # opset-16 default is 'half_pixel', so it must be spelled out
        attrs["coordinate_transformation_mode"] = "output_half_pixel"
    ctx.emit("RoiAlign", [ins[0], boxes, bsq], [out], attrs=attrs)


def _seq_len_mask(ctx, s, ins, T, trailing_rank):
    """(T, N) bool mask: position t is valid iff t < sequence_length[n],
    unsqueezed over `trailing_rank` extra dims."""
    rng = ctx.fresh("seq_range")
    ctx.emit("Range", [ctx.const("r0", np.asarray(0, np.float32)),
                       ctx.const("rT", np.asarray(T, np.float32)),
                       ctx.const("r1", np.asarray(1, np.float32))], [rng])
    rcol = ctx.fresh("seq_rcol")
    ctx.emit("Unsqueeze", [rng, ctx.const("ax1", np.asarray([1], np.int64))],
             [rcol])                                   # (T, 1)
    cmp = ctx.fresh("seq_valid")
    ctx.emit("Less", [rcol, ins[1]], [cmp])            # (T, N) via broadcast
    for _ in range(trailing_rank):
        nxt = ctx.fresh("seq_valid_u")
        ctx.emit("Unsqueeze", [cmp, ctx.const(
            "axm1", np.asarray([-1], np.int64))], [nxt])
        cmp = nxt
    return cmp


@register_converter("SequenceMask")
def _sequence_mask_conv(ctx, s, ins, out):
    a = s._attrs
    if not a.get("use_sequence_length", False):
        ctx.emit("Identity", ins[:1], [out])
        return
    if int(a.get("axis", 0)) != 0:
        raise ValueError("SequenceMask export: only axis=0 (time-major)")
    shape = s._inputs[0].shape
    valid = _seq_len_mask(ctx, s, ins, shape[0], len(shape) - 2)
    val = ctx.const("maskval", np.float32(a.get("value", 0.0)))
    ctx.emit("Where", [valid, ins[0], val], [out])


@register_converter("SequenceLast")
def _sequence_last_conv(ctx, s, ins, out):
    a = s._attrs
    if int(a.get("axis", 0)) != 0:
        raise ValueError("SequenceLast export: only axis=0 (time-major)")
    shape = s._inputs[0].shape
    if not a.get("use_sequence_length", False):
        ctx.emit("Gather", [ins[0], ctx.const(
            "lastidx", np.asarray(shape[0] - 1, np.int64))], [out],
            attrs={"axis": 0})
        return
    # per-example last valid step: GatherND with indices [(len[n]-1, n)]
    li = ctx.fresh("sl_lastpos")
    ctx.emit("Sub", [ins[1], ctx.const("one", np.float32(1.0))], [li])
    lii = ctx.fresh("sl_lastpos_i")
    ctx.emit("Cast", [li], [lii], attrs={"to": 7})
    lcol = ctx.fresh("sl_lcol")
    ctx.emit("Unsqueeze", [lii, ctx.const("ax1b",
                                          np.asarray([1], np.int64))], [lcol])
    nrng = ctx.fresh("sl_nrange")
    ctx.emit("Range", [ctx.const("n0", np.asarray(0, np.int64)),
                       ctx.const("nN", np.asarray(shape[1], np.int64)),
                       ctx.const("n1", np.asarray(1, np.int64))], [nrng])
    ncol = ctx.fresh("sl_ncol")
    ctx.emit("Unsqueeze", [nrng, ctx.const("ax1c",
                                           np.asarray([1], np.int64))], [ncol])
    idx = ctx.fresh("sl_idx")
    ctx.emit("Concat", [lcol, ncol], [idx], attrs={"axis": 1})   # (N, 2)
    ctx.emit("GatherND", [ins[0], idx], [out])


@register_converter("SequenceReverse")
def _sequence_reverse_conv(ctx, s, ins, out):
    a = s._attrs
    if a.get("use_sequence_length", False):
        raise ValueError("SequenceReverse export: per-example lengths do "
                         "not map to a fixed ONNX node set")
    if int(a.get("axis", 0)) != 0:
        raise ValueError("SequenceReverse export: only axis=0")
    imax = np.iinfo(np.int64).max
    ctx.emit("Slice", [ins[0],
                       ctx.const("starts", np.asarray([-1], np.int64)),
                       ctx.const("ends", np.asarray([-imax], np.int64)),
                       ctx.const("axes", np.asarray([0], np.int64)),
                       ctx.const("steps", np.asarray([-1], np.int64))], [out])


@register_converter("masked_softmax")
def _masked_softmax_conv(ctx, s, ins, out):
    axis = int(s._attrs.get("axis", -1))
    if len(ins) < 2:
        ctx.emit("Softmax", ins[:1], [out], attrs={"axis": axis})
        return
    # matches the registry op exactly: softmax(where(mask, x, -1e30)) with
    # NO re-zeroing (a fully-masked row yields uniform 1/n, not zeros)
    mb = ctx.fresh("msm_bool")
    ctx.emit("Cast", [ins[1]], [mb], attrs={"to": int(P.BOOL)})
    neg = ctx.const("msm_neg", np.float32(-1e30))
    masked = ctx.fresh("msm_masked")
    ctx.emit("Where", [mb, ins[0], neg], [masked])
    ctx.emit("Softmax", [masked], [out], attrs={"axis": axis})


@register_converter("broadcast_like")
def _broadcast_like_conv(ctx, s, ins, out):
    shp = ctx.fresh("bl_shape")
    ctx.emit("Shape", [ins[1]], [shp])
    ctx.emit("Expand", [ins[0], shp], [out])


@register_converter("broadcast_axis")
def _broadcast_axis_conv(ctx, s, ins, out):
    a = s._attrs
    shape = list(s._inputs[0].shape)
    axes = a["axis"] if isinstance(a["axis"], (tuple, list)) else [a["axis"]]
    sizes = a["size"] if isinstance(a["size"], (tuple, list)) else [a["size"]]
    for ax, sz in zip(axes, sizes):
        shape[ax] = int(sz)
    ctx.emit("Expand", [ins[0], ctx.const(
        "target", np.asarray(shape, np.int64))], [out])


register_converter("broadcast_axes")(_CONVERTERS["broadcast_axis"])


@register_converter("Pad")
def _pad_legacy_conv(ctx, s, ins, out):
    a = s._attrs
    pw = a.get("pad_width")
    if pw is None:
        raise ValueError("Pad export needs pad_width")
    nd = len(pw) // 2
    begins = [int(pw[2 * i]) for i in range(nd)]
    ends = [int(pw[2 * i + 1]) for i in range(nd)]
    mode = {"constant": "constant", "edge": "edge",
            "reflect": "reflect"}[a.get("mode", "constant")]
    node_in = [ins[0], ctx.const("pads", np.asarray(begins + ends, np.int64))]
    if mode == "constant":
        node_in.append(ctx.const("padval",
                                 np.float32(a.get("constant_value", 0.0))))
    ctx.emit("Pad", node_in, [out], attrs={"mode": mode})


@register_converter("argsort")
def _argsort_conv(ctx, s, ins, out):
    a = s._attrs
    axis = int(a.get("axis", -1))
    shape = s._inputs[0].shape
    k = ctx.const("k", np.asarray([shape[axis]], np.int64))
    vals = ctx.fresh("argsort_vals")
    idx = ctx.fresh("argsort_idx")
    ctx.emit("TopK", [ins[0], k], [vals, idx],
             attrs={"axis": axis, "largest": 0 if a.get("is_ascend", True)
                    else 1, "sorted": 1})
    from ..base import resolve_dtype
    code = P.np_to_onnx_dtype(np.dtype(resolve_dtype(
        a.get("dtype", "float32"))))
    ctx.emit("Cast", [idx], [out], attrs={"to": int(code)})


@register_converter("argmax_channel")
def _argmax_channel_conv(ctx, s, ins, out):
    r = ctx.fresh("amc")
    ctx.emit("ArgMax", ins[:1], [r], attrs={"axis": 1, "keepdims": 0})
    ctx.emit("Cast", [r], [out], attrs={"to": int(P.FLOAT)})


@register_converter("GroupNorm")
def _group_norm_conv(ctx, s, ins, out):
    """Exact decomposition over standard nodes (opset 13 has no
    GroupNormalization): reshape to (N, G, rest), normalize over rest with
    the op's own eps, reshape back, per-channel affine."""
    a = s._attrs
    G = int(a.get("num_groups", 1))
    eps = float(a.get("eps", 1e-5))
    shape = s._inputs[0].shape  # lazy jax.eval_shape through the graph
    C = shape[1]
    xg = ctx.fresh("gn_grouped")
    ctx.emit("Reshape", [ins[0], ctx.const(
        "gshape", np.asarray([shape[0], G, -1], np.int64))], [xg])
    m = ctx.fresh("gn_mean")
    ctx.emit("ReduceMean", [xg], [m], attrs={"axes": [2], "keepdims": 1})
    d = ctx.fresh("gn_dev")
    ctx.emit("Sub", [xg, m], [d])
    d2 = ctx.fresh("gn_dev2")
    ctx.emit("Mul", [d, d], [d2])
    v = ctx.fresh("gn_var")
    ctx.emit("ReduceMean", [d2], [v], attrs={"axes": [2], "keepdims": 1})
    ve = ctx.fresh("gn_vareps")
    ctx.emit("Add", [v, ctx.const("eps", np.float32(eps))], [ve])
    sd = ctx.fresh("gn_std")
    ctx.emit("Sqrt", [ve], [sd])
    yn = ctx.fresh("gn_norm")
    ctx.emit("Div", [d, sd], [yn])
    yr = ctx.fresh("gn_back")
    ctx.emit("Reshape", [yn, ctx.const(
        "xshape", np.asarray(shape, np.int64))], [yr])
    cshape = ctx.const("cshape",
                       np.asarray([1, C] + [1] * (len(shape) - 2), np.int64))
    gr = ctx.fresh("gn_gamma")
    ctx.emit("Reshape", [ins[1], cshape], [gr])
    br = ctx.fresh("gn_beta")
    ctx.emit("Reshape", [ins[2], cshape], [br])
    sc = ctx.fresh("gn_scaled")
    ctx.emit("Mul", [yr, gr], [sc])
    ctx.emit("Add", [sc, br], [out])


@register_converter("UpSampling")
def _upsampling_conv(ctx, s, ins, out):
    a = s._attrs
    scale = float(a.get("scale", 2))
    scales = ctx.const("scales", np.asarray([1.0, 1.0, scale, scale],
                                            np.float32))
    if a.get("sample_type", "nearest") == "nearest":
        # jnp.repeat == asymmetric coords + floor nearest rounding
        attrs = {"mode": "nearest",
                 "coordinate_transformation_mode": "asymmetric",
                 "nearest_mode": "floor"}
    else:
        attrs = {"mode": "linear",
                 "coordinate_transformation_mode": "half_pixel"}
    ctx.emit("Resize", [ins[0], "", scales], [out], attrs=attrs)


def _emit_linear_resize(ctx, s, ins, out, ctm):
    a = s._attrs
    attrs = {"mode": "linear", "coordinate_transformation_mode": ctm}
    if a.get("height") is not None:
        n, c = s._inputs[0].shape[:2]
        sizes = ctx.const("sizes", np.asarray(
            [n, c, int(a["height"]), int(a["width"])], np.int64))
        ctx.emit("Resize", [ins[0], "", "", sizes], [out], attrs=attrs)
    else:
        scales = ctx.const("scales", np.asarray(
            [1.0, 1.0, float(a["scale_height"]), float(a["scale_width"])],
            np.float32))
        ctx.emit("Resize", [ins[0], "", scales], [out], attrs=attrs)


@register_converter("AdaptiveAvgPooling2D")
def _adaptive_avg_pool_conv(ctx, s, ins, out):
    """output_size (1,1) → GlobalAveragePool; anything else exports the op's
    exact two-matmul form (ops/functional.py:AdaptiveAvgPooling2D): L·x·R
    with the static averaging matrices as initializers — bit-identical to
    the registry op, expressible in plain ONNX (no AdaptiveAvgPool exists
    in the spec)."""
    size = s._attrs.get("output_size")
    h, w = s._inputs[0].shape[2], s._inputs[0].shape[3]
    if size is None or size == ():
        oh, ow = h, w
    elif isinstance(size, (tuple, list)):
        oh, ow = int(size[0]), int(size[1 if len(size) > 1 else 0])
    else:
        oh = ow = int(size)
    if (oh, ow) == (1, 1):
        ctx.emit("GlobalAveragePool", [ins[0]], [out])
        return
    if (oh, ow) == (h, w):
        ctx.emit("Identity", [ins[0]], [out])
        return
    from ..ops.functional import adaptive_avg_matrix

    left = ctx.const("adaptL", adaptive_avg_matrix(h, oh))    # (oh, h)
    right = ctx.const("adaptR", adaptive_avg_matrix(w, ow).T)  # (w, ow)
    rows = ctx.fresh("rows")
    ctx.emit("MatMul", [left, ins[0]], [rows])          # (B, C, oh, w)
    ctx.emit("MatMul", [rows, right], [out])            # (B, C, oh, ow)


@register_converter("BilinearResize2D")
def _bilinear_resize_conv(ctx, s, ins, out):
    _emit_linear_resize(ctx, s, ins, out, "align_corners")


@register_converter("_resize_linear_asymmetric")
def _resize_asymmetric_conv(ctx, s, ins, out):
    _emit_linear_resize(ctx, s, ins, out, "asymmetric")


@register_converter("_resize_linear_half_pixel")
def _resize_half_pixel_conv(ctx, s, ins, out):
    # preserve the ctm the op was imported with: half_pixel and
    # pytorch_half_pixel diverge when an output spatial dim is 1
    # (ops/functional.py:929), so rewriting one as the other on re-export
    # would change what onnxruntime computes
    ctm = ("pytorch_half_pixel" if s._attrs.get("pytorch_mode")
           else "half_pixel")
    _emit_linear_resize(ctx, s, ins, out, ctm)


def _slice_emit(ctx, src, start, end, axis, hint):
    out = ctx.fresh(hint)
    ctx.emit("Slice", [src,
                       ctx.const("starts", np.asarray([start], np.int64)),
                       ctx.const("ends", np.asarray([end], np.int64)),
                       ctx.const("axes", np.asarray([axis], np.int64))], [out])
    return out


@register_converter("box_nms")
def _box_nms_conv(ctx, s, ins, out):
    """box_nms → NonMaxSuppression + gather/scatter reconstruction.

    MXNet box_nms keeps boxes in place and sets suppressed SCORES to -1
    (src/operator/contrib/bounding_box.cc), so the ONNX form is: NMS selects
    surviving (batch, box) pairs; a -1-filled score plane is ScatterND-ed
    with the surviving scores; ids/boxes columns pass through unchanged."""
    a = s._attrs
    if (a.get("coord_start", 2) != 2 or a.get("score_index", 1) != 1
            or a.get("in_format", "corner") != "corner"):
        raise ValueError("box_nms export supports the standard "
                         "[id, score, x1,y1,x2,y2] corner layout only")
    id_index = a.get("id_index", 0)
    if id_index >= 0 and not a.get("force_suppress", False):
        raise ValueError(
            "box_nms export: per-class suppression (id_index>=0, "
            "force_suppress=False) cannot map to ONNX NMS, whose classes "
            "are a static scores axis — use force_suppress=True or "
            "id_index=-1")
    in_shape = s._inputs[0].shape
    if len(in_shape) != 3 or in_shape[-1] != 6:
        raise ValueError(
            "box_nms export supports (B, N, 6) data only, got %r — extra "
            "label columns or 2-D inputs would be silently dropped"
            % (in_shape,))
    data = ins[0]
    N = in_shape[-2]
    topk = int(a.get("topk", -1))
    ids = _slice_emit(ctx, data, 0, 1, 2, "nms_ids")             # (B,N,1)
    scores3 = _slice_emit(ctx, data, 1, 2, 2, "nms_scores")      # (B,N,1)
    boxes = _slice_emit(ctx, data, 2, 6, 2, "nms_boxes")         # (B,N,4)
    scoresT = ctx.fresh("nms_scoresT")
    ctx.emit("Transpose", [scores3], [scoresT], attrs={"perm": [0, 2, 1]})
    sel = ctx.fresh("nms_sel")
    ctx.emit("NonMaxSuppression",
             [boxes, scoresT,
              ctx.const("max_out", np.asarray(
                  [topk if topk > 0 else N], np.int64)),
              ctx.const("iou", np.asarray(
                  [float(a.get("overlap_thresh", 0.5))], np.float32)),
              ctx.const("score_th", np.asarray(
                  [float(a.get("valid_thresh", 0.0))], np.float32))],
             [sel])                                              # (M,3)
    bcol = _slice_emit(ctx, sel, 0, 1, 1, "nms_bi")
    icol = _slice_emit(ctx, sel, 2, 3, 1, "nms_box_i")
    idx2 = ctx.fresh("nms_idx2")
    ctx.emit("Concat", [bcol, icol], [idx2], attrs={"axis": 1})  # (M,2)
    scores2 = ctx.fresh("nms_scores2")
    ctx.emit("Squeeze", [scores3, ctx.const("axes",
                                            np.asarray([2], np.int64))],
             [scores2])                                          # (B,N)
    kept = ctx.fresh("nms_kept")
    ctx.emit("GatherND", [scores2, idx2], [kept])                # (M,)
    z = ctx.fresh("nms_zero")
    ctx.emit("Mul", [scores2, ctx.const("zero", np.float32(0.0))], [z])
    neg = ctx.fresh("nms_neg")
    ctx.emit("Add", [z, ctx.const("negone", np.float32(-1.0))], [neg])
    new2 = ctx.fresh("nms_new2")
    ctx.emit("ScatterND", [neg, idx2, kept], [new2])             # (B,N)
    new3 = ctx.fresh("nms_new3")
    ctx.emit("Unsqueeze", [new2, ctx.const("axes",
                                           np.asarray([2], np.int64))],
             [new3])
    ctx.emit("Concat", [ids, new3, boxes], [out], attrs={"axis": 2})


@register_converter("_onnx_nms")
def _onnx_nms_conv(ctx, s, ins, out):
    a = s._attrs
    # our op treats max_output=0 as "keep all" (K=N); ONNX spec reads a
    # literal 0 as "select nothing", so absent/0 exports as the box count
    max_out = int(a.get("max_output_boxes_per_class", 0))
    if max_out <= 0:
        max_out = int(s._inputs[0].shape[-2])
    node_in = [ins[0], ins[1],
               ctx.const("max_out", np.asarray([max_out], np.int64)),
               ctx.const("iou", np.asarray(
                   [float(a.get("iou_threshold", 0.0))], np.float32))]
    if a.get("score_threshold") is not None:
        # absent means "no filtering" — omit the optional input rather than
        # writing 0.0, which would newly drop negative-score boxes
        node_in.append(ctx.const("score_th", np.asarray(
            [float(a["score_threshold"])], np.float32)))
    ctx.emit("NonMaxSuppression", node_in, [out],
             attrs={"center_point_box": int(a.get("center_point_box", 0))})


@register_converter("_onnx_gather_nd")
def _onnx_gather_nd_conv(ctx, s, ins, out):
    ctx.emit("GatherND", ins[:2], [out])


@register_converter("_onnx_scatter_nd")
def _onnx_scatter_nd_conv(ctx, s, ins, out):
    ctx.emit("ScatterND", ins[:3], [out])


# ------------------------------------------------------------ recurrent ops

# MXNet gate order: LSTM [i, f, g, o], GRU [r, z, n] (src/operator/rnn-inl.h).
# ONNX gate order:  LSTM [i, o, f, c], GRU [z, r, h].
_LSTM_TO_ONNX = [0, 3, 1, 2]
_GRU_TO_ONNX = [1, 0, 2]
_LSTM_FROM_ONNX = [0, 2, 3, 1]
_GRU_FROM_ONNX = [1, 0, 2]


def _gate_perm(arr, perm, hidden):
    g = len(perm)
    return np.ascontiguousarray(
        arr.reshape((g, hidden) + arr.shape[1:])[perm].reshape(arr.shape))


@register_converter("RNN")
def _rnn_conv(ctx, s, ins, out):
    """Fused multi-layer RNN → one ONNX LSTM/GRU/RNN node per layer
    (ONNX recurrent ops are single-layer; num_directions is the only stacking
    they support). Weight initializers are re-blocked to ONNX gate order."""
    a = s._attrs
    mode = a.get("mode", "lstm")
    L = int(a.get("num_layers", 1))
    D = 2 if a.get("bidirectional") else 1
    onnx_op = {"lstm": "LSTM", "gru": "GRU"}.get(mode, "RNN")
    perm = {"lstm": _LSTM_TO_ONNX, "gru": _GRU_TO_ONNX}.get(mode, [0])

    def arr_of(name):
        if name not in ctx.initializers:
            raise ValueError("RNN export: weight %r must be a parameter" % name)
        return np.asarray(ctx.initializers[name], np.float32)

    def state_slice(state_name, layer, hint):
        sl = ctx.fresh(hint)
        ctx.emit("Slice", [state_name,
                           ctx.const("starts", np.asarray([layer * D], np.int64)),
                           ctx.const("ends", np.asarray([(layer + 1) * D], np.int64)),
                           ctx.const("axes", np.asarray([0], np.int64))], [sl])
        return sl

    cur = ins[0]
    wnames = ins[3:]
    hs, cs = [], []
    wi = 0
    for layer in range(L):
        Ws, Rs, Bs = [], [], []
        H = None
        for _ in range(D):
            wih, whh, bih, bhh = (arr_of(wnames[wi + k]) for k in range(4))
            wi += 4
            H = whh.shape[1]
            Ws.append(_gate_perm(wih, perm, H))
            Rs.append(_gate_perm(whh, perm, H))
            Bs.append(np.concatenate([_gate_perm(bih, perm, H),
                                      _gate_perm(bhh, perm, H)]))
        W = ctx.const("rnn_W", np.stack(Ws))
        R = ctx.const("rnn_R", np.stack(Rs))
        B = ctx.const("rnn_B", np.stack(Bs))
        node_in = [cur, W, R, B, "", state_slice(ins[1], layer, "rnn_h0")]
        if mode == "lstm":
            node_in.append(state_slice(ins[2], layer, "rnn_c0"))
        attrs = {"hidden_size": H,
                 "direction": "bidirectional" if D == 2 else "forward"}
        if mode == "gru":
            # our GRU applies reset AFTER the recurrent matmul+bias
            attrs["linear_before_reset"] = 1
        if onnx_op == "RNN":
            attrs["activations"] = ["Tanh" if mode == "rnn_tanh" else "Relu"] * D
        y = ctx.fresh("rnn_Y")
        yh = ctx.fresh("rnn_Yh")
        outs = [y, yh]
        if mode == "lstm":
            yc = ctx.fresh("rnn_Yc")
            outs.append(yc)
            cs.append(yc)
        ctx.emit(onnx_op, node_in, outs, attrs=attrs)
        hs.append(yh)
        # Y (T, D, N, H) → next layer's X (T, N, D*H)
        tr = ctx.fresh("rnn_tr")
        ctx.emit("Transpose", [y], [tr], attrs={"perm": [0, 2, 1, 3]})
        rs = ctx.fresh("rnn_seq")
        ctx.emit("Reshape", [tr, ctx.const("shape",
                                           np.asarray([0, 0, -1], np.int64))],
                 [rs])
        cur = rs

    def stack_states(names, hint):
        if len(names) == 1:
            return names[0]
        cat = ctx.fresh(hint)
        ctx.emit("Concat", names, [cat], attrs={"axis": 0})
        return cat

    h_out = stack_states(hs, "rnn_hn")
    # non-LSTM modes pass the input cell state through untouched
    # (ops/rnn.py returns c0) — mirror that, not hn
    c_out = stack_states(cs, "rnn_cn") if mode == "lstm" else ins[2]
    ctx.multi[id(s)] = [cur, h_out, c_out]
    ctx.names[id(s)] = cur
    return cur


@register_converter("_cond")
def _cond_conv(ctx, s, ins, out):
    """symbol.cond → ONNX If. Branch subgraphs reference outer-scope values
    by name (ONNX scoping) — the branch var symbols ARE the outer graph
    symbols, so their names are already assigned in ctx.names."""
    a = s._attrs
    pred = ctx.fresh("cond_pred")
    ctx.emit("Cast", [ins[0]], [pred], attrs={"to": int(P.BOOL)})

    # names assigned before this node belong to the OUTER scope; anything a
    # branch adds (including nodes shared between the two branches) must be
    # re-emitted per branch — ONNX subgraphs can see outer names but never a
    # sibling subgraph's internals
    outer_names = dict(ctx.names)
    outer_multi = dict(ctx.multi)

    def branch_graph(branch_sym, tag):
        saved = ctx.nodes
        ctx.nodes = []
        ctx.names = dict(outer_names)
        ctx.multi = dict(outer_multi)
        order = _toposort([branch_sym])
        for node in order:
            if node.is_var():
                if id(node) not in ctx.names:
                    raise ValueError("If export: branch var %r not in outer "
                                     "scope" % node.name)
                continue
            if id(node) in ctx.names:
                continue  # emitted in the outer graph, visible by scoping
            _convert_node(ctx, node)
        bout = ctx.names[id(branch_sym)]
        nodes = ctx.nodes
        ctx.nodes = saved
        g = P.graph_proto("%s_%s" % (s.name, tag), nodes, [],
                          [P.value_info(bout, np.float32, ())], [])
        return P.GraphAttr(g)

    try:
        then_attr = branch_graph(a["then_sym"], "then")
        else_attr = branch_graph(a["else_sym"], "else")
    finally:
        ctx.names = outer_names
        ctx.multi = outer_multi
    ctx.names[id(s)] = out

    ctx.emit("If", [pred], [out],
             attrs={"then_branch": then_attr, "else_branch": else_attr})


@register_converter("_foreach")
def _foreach_conv(ctx, s, ins, out):
    """symbol.foreach → ONNX Scan (the exact semantic match: per-step state
    threading + stacked scan outputs). Body formal inputs are [states...,
    scan slice]; free variables resolve through ONNX outer-scope naming."""
    a = s._attrs
    n_states = a["n_states"]
    roots = list(a["state_syms"]) + [a["out_sym"]]  # Scan output order

    # the loop-var Symbols ARE the body's formal inputs — find them by name
    loop_names = [a["slice_name"]] + list(a["state_names"])
    var_syms = {}
    for root in roots:
        for arg in root._arg_symbols():
            if arg.name in loop_names:
                var_syms[arg.name] = arg

    outer_names = dict(ctx.names)
    outer_multi = dict(ctx.multi)
    saved_nodes = ctx.nodes
    ctx.nodes = []
    ctx.names = dict(outer_names)
    ctx.multi = dict(outer_multi)
    try:
        input_vis = []
        for nm in list(a["state_names"]) + [a["slice_name"]]:
            if nm in var_syms:
                ctx.names[id(var_syms[nm])] = nm
            input_vis.append(P.value_info(nm, np.float32, ()))
        for node_ in _toposort(roots):
            if node_.is_var():
                if id(node_) not in ctx.names:
                    raise ValueError("Scan export: body var %r not in outer "
                                     "scope" % node_.name)
                continue
            if id(node_) in ctx.names:
                continue  # outer-scope value, visible by ONNX scoping
            _convert_node(ctx, node_)
        # graph output names must be UNIQUE: the idiomatic `return h, h`
        # body reuses one Symbol for output and state — alias repeats
        # through Identity nodes
        out_names, used = [], set()
        for r in roots:
            nm = ctx.names[id(r)]
            if nm in used:
                alias = ctx.fresh("%s_alias" % nm)
                ctx.emit("Identity", [nm], [alias])
                nm = alias
            used.add(nm)
            out_names.append(nm)
        out_vis = [P.value_info(nm, np.float32, ()) for nm in out_names]
        body = P.GraphAttr(P.graph_proto("%s_body" % s.name, ctx.nodes,
                                         input_vis, out_vis, []))
    finally:
        ctx.nodes = saved_nodes
        ctx.names = outer_names
        ctx.multi = outer_multi

    # Scan node: inputs [initial_states..., scan_input]; outputs
    # [final_states..., stacked_scan_output]
    node_inputs = [ins[1 + i] for i in range(n_states)] + [ins[0]]
    final_states = [ctx.fresh("scan_state%d" % i) for i in range(n_states)]
    ctx.emit("Scan", node_inputs, final_states + [out],
             attrs={"body": body, "num_scan_inputs": 1})
    # our _item order is [stacked_outputs, states...]
    ctx.multi[id(s)] = [out] + final_states
    ctx.names[id(s)] = out
    return out


@register_converter("_while")
def _while_conv(ctx, s, ins, out):
    """symbol while_loop → ONNX Loop. Body formals are [iter, cond_in,
    vars...]; the body emits [cond_out, new_vars..., per-step output], with
    cond_out re-evaluating the predicate on the NEW vars (ONNX's cond is
    produced by the body for the next iteration, where our masked scan
    evaluates it before each step — same executed-iteration set). The
    initial cond is the predicate over the initial values, emitted in the
    outer graph. Static-shape deviation: our executor always stacks
    max_iterations rows (masked steps emit zeros); a spec runtime stacks
    only executed rows.
    """
    a = s._attrs
    n_vars = a["n_vars"]
    var_names = list(a["var_names"])
    pred_sym = a["pred_sym"]
    roots = [pred_sym, a["out_sym"]] + list(a["var_syms"])

    var_syms = {}
    for root in roots:
        for arg in root._arg_symbols():
            if arg.name in var_names:
                var_syms[arg.name] = arg

    def _convert_scoped(root_list, bindings):
        """Convert symbols with loop-var ids bound to given value names;
        conversion cache starts from the OUTER names only, so the same
        subgraph can be re-emitted against different bindings."""
        ctx.names = dict(outer_names)
        ctx.multi = dict(outer_multi)
        for nm, val in bindings.items():
            if nm in var_syms:
                ctx.names[id(var_syms[nm])] = val
        for node_ in _toposort(root_list):
            if node_.is_var():
                if id(node_) not in ctx.names:
                    raise ValueError("Loop export: body var %r not in outer "
                                     "scope" % node_.name)
                continue
            if id(node_) in ctx.names:
                continue
            _convert_node(ctx, node_)
        return [ctx.names[id(r)] for r in root_list]

    outer_names = dict(ctx.names)
    outer_multi = dict(ctx.multi)

    # initial condition: predicate over the Loop node's initial values,
    # evaluated in the OUTER graph
    (cond0,) = _convert_scoped([pred_sym],
                               dict(zip(var_names, ins[:n_vars])))

    iter_nm = ctx.fresh("wl_iter")
    cond_in = ctx.fresh("wl_cond_in")
    saved_nodes = ctx.nodes
    ctx.nodes = []
    try:
        # pass 1: body exprs (out + new vars) on the formal var inputs
        body_roots = [a["out_sym"]] + list(a["var_syms"])
        body_outs = _convert_scoped(body_roots,
                                    dict(zip(var_names, var_names)))
        step_out, new_var_names = body_outs[0], body_outs[1:]
        # pass 2: predicate on the NEW var values (fresh scope: shared
        # subexpressions re-emit rather than alias stale bindings)
        (cond_out,) = _convert_scoped([pred_sym],
                                      dict(zip(var_names, new_var_names)))
        cond_out_b = ctx.fresh("wl_cond_out")
        ctx.emit("Cast", [cond_out], [cond_out_b],
                 attrs={"to": int(P.BOOL)})
        cond_out = cond_out_b

        input_vis = ([P.value_info(iter_nm, np.int64, ()),
                      P.value_info(cond_in, np.bool_, ())]
                     + [P.value_info(nm, np.float32, ()) for nm in var_names])
        out_names, used = [], set()
        for nm in [cond_out] + new_var_names + [step_out]:
            if nm in used:
                alias = ctx.fresh("%s_alias" % nm)
                ctx.emit("Identity", [nm], [alias])
                nm = alias
            used.add(nm)
            out_names.append(nm)
        out_vis = [P.value_info(nm, np.float32, ()) for nm in out_names]
        body = P.GraphAttr(P.graph_proto("%s_body" % s.name, ctx.nodes,
                                         input_vis, out_vis, []))
    finally:
        ctx.nodes = saved_nodes
        ctx.names = dict(outer_names)
        ctx.multi = dict(outer_multi)

    m_name = ctx.const("wl_m", np.asarray(a["max_iterations"], np.int64))
    cond0b = ctx.fresh("wl_cond0")
    ctx.emit("Cast", [cond0], [cond0b], attrs={"to": int(P.BOOL)})
    final_vars = [ctx.fresh("wl_final%d" % i) for i in range(n_vars)]
    ctx.emit("Loop", [m_name, cond0b] + list(ins[:n_vars]),
             final_vars + [out], attrs={"body": body})
    # our _item order is [stacked_outputs, final_vars...]
    ctx.multi[id(s)] = [out] + final_vars
    ctx.names[id(s)] = out
    return out


# ------------------------------------------------------------- graph walker

def _convert_node(ctx, s):
    """Translate one non-var Symbol node, registering its output name(s)."""
    if s._op == "_item":
        # projection of a multi-output op. Converters that emit every
        # output (RNN) fill ctx.multi; otherwise only index 0 exists —
        # consuming a secondary output (e.g. BatchNorm's updated running
        # stats) has no ONNX inference-graph equivalent.
        parent = s._inputs[0]
        idx = s._attrs.get("index", 0)
        multi = ctx.multi.get(id(parent))
        if multi is not None:
            ctx.names[id(s)] = multi[idx]
            return
        if idx != 0:
            raise ValueError(
                "cannot export: graph consumes output %d of %r — only "
                "the primary output of multi-output ops maps to ONNX "
                "inference graphs" % (idx, parent._op))
        ctx.names[id(s)] = ctx.names[id(parent)]
        return
    ins = [ctx.names[id(i)] for i in s._inputs]
    out = ctx.fresh(s.name or s._op)
    ctx.names[id(s)] = out
    conv = _CONVERTERS.get(s._op)
    if conv is None:
        raise ValueError("no ONNX converter for op %r (export coverage "
                         "mirrors mx2onnx/_op_translations)" % s._op)
    conv(ctx, s, ins, out)


def _toposort(outputs):
    order, seen = [], set()

    def walk(s):
        if id(s) in seen:
            return
        seen.add(id(s))
        for i in s._inputs:
            walk(i)
        order.append(s)

    for o in outputs:
        walk(o)
    return order


def symbol_to_onnx(sym_out, params, input_shapes, input_dtypes=None,
                   graph_name="mxnet_tpu", opset=13):
    """Convert a Symbol graph (single output or Group) to ModelProto bytes.

    params: {name: np.ndarray} for every non-data variable in the graph.
    input_shapes: {data_name: shape} for graph inputs.
    """
    outputs = sym_out._inputs if sym_out._op == "_group" else [sym_out]
    order = _toposort(outputs)
    ctx = _Ctx(params, opset)
    input_dtypes = input_dtypes or {}

    # name variables; params become initializers, the rest graph inputs.
    # Every var gets its static shape so converters needing shapes (RNN
    # inter-layer reshapes, multibox_prior constant-folding) can query
    # Symbol.shape (jax.eval_shape through the graph).
    graph_inputs = []
    for s in order:
        if not s.is_var():
            continue
        ctx.names[id(s)] = s.name
        if s.name in params:
            ctx.initializers[s.name] = np.asarray(params[s.name])
            if s._shape is None:
                s._shape = tuple(np.asarray(params[s.name]).shape)
        else:
            if s.name not in input_shapes:
                raise ValueError("no shape for graph input %r" % s.name)
            if s._shape is None:
                s._shape = tuple(input_shapes[s.name])
            graph_inputs.append(
                P.value_info(s.name, input_dtypes.get(s.name, np.float32),
                             input_shapes[s.name]))

    for s in order:
        if s.is_var():
            continue
        _convert_node(ctx, s)

    out_infos = [P.value_info(ctx.names[id(o)], np.float32, ())
                 for o in outputs]
    init_protos = [P.tensor_proto(n, a) for n, a in ctx.initializers.items()]
    graph = P.graph_proto(graph_name, ctx.nodes, graph_inputs, out_infos,
                          init_protos)
    return P.model_proto(graph, opset=opset).tobytes()


def export_model(model, params=None, input_shapes=None, input_types=None,
                 onnx_file=None, input_names=("data",), opset=13):
    """Export a HybridBlock or Symbol to an ONNX file
    (ref: python/mxnet/onnx/mx2onnx/_export_model.py:export_model).

    * HybridBlock: traced via block(sym.var(name) for each input_name);
      parameters are pulled from collect_params().
    * Symbol: ``params`` must map var name → array.
    Returns the path written (or the serialized bytes if onnx_file is None).
    """
    from .. import sym as _sym

    if input_shapes is None:
        raise ValueError("input_shapes is required")
    if not isinstance(input_shapes, dict):
        input_shapes = dict(zip(input_names, [tuple(s) for s in input_shapes]))
    if input_types is not None and not isinstance(input_types, dict):
        # pair by input_names order, NOT the shapes dict's insertion order
        # (a dict-shapes caller may list names in a different order)
        input_types = dict(zip(input_names, input_types))

    if isinstance(model, Symbol):
        sym_out = model
        params = {k: np.asarray(v.asnumpy() if hasattr(v, "asnumpy") else v)
                  for k, v in (params or {}).items()}
    else:
        # shapes on the trace vars: hybrid_forward code may query x.shape
        # (rnn state sizing, SSD reshape heads)
        data = [_sym.var(n, shape=tuple(input_shapes[n])) for n in input_shapes]
        sym_out = model(*data)
        if isinstance(sym_out, (list, tuple)):
            from ..symbol import Group
            sym_out = Group(list(sym_out))
        params = {p.name: p.data().asnumpy()
                  for p in model.collect_params().values()}

    buf = symbol_to_onnx(sym_out, params, input_shapes,
                         input_dtypes=input_types, opset=opset)
    if onnx_file is None:
        return buf
    with open(onnx_file, "wb") as f:
        f.write(buf)
    return onnx_file


# --------------------------- breadth batch: converters for common ops

def _reg_simple_conv(op, onnx_op):
    @register_converter(op)
    def conv(ctx, s, ins, out, _onnx=onnx_op):
        ctx.emit(_onnx, ins, [out])


_reg_simple_conv("add_n", "Sum")


def _cast(ctx, name, to):
    c = ctx.fresh("cast")
    ctx.emit("Cast", [name], [c], attrs={"to": int(to)})
    return c


@register_converter("where")
def _where_conv(ctx, s, ins, out):
    # mxnet_tpu conditions are float; ONNX Where requires bool
    ctx.emit("Where", [_cast(ctx, ins[0], P.BOOL), ins[1], ins[2]], [out])


def _reg_compare_conv(op, onnx_op):
    @register_converter(op)
    def conv(ctx, s, ins, out, _onnx=onnx_op):
        # comparisons produce bool in ONNX but float in mxnet_tpu — cast
        # inputs for Not, and cast every result back to float
        if _onnx == "Not":
            ins = [_cast(ctx, ins[0], P.BOOL)]
        b = ctx.fresh("cmp")
        ctx.emit(_onnx, ins, [b])
        ctx.emit("Cast", [b], [out], attrs={"to": int(P.FLOAT)})


_reg_compare_conv("broadcast_equal", "Equal")
_reg_compare_conv("broadcast_greater", "Greater")
_reg_compare_conv("broadcast_lesser", "Less")
_reg_compare_conv("logical_not", "Not")


def _flat_input(ctx, s, ins):
    """axis=None reduces over the FLATTENED array — reshape first so the
    exported graph matches registry semantics."""
    shp = ctx.const("flat", np.asarray([-1], np.int64))
    r = ctx.fresh("flatten1d")
    ctx.emit("Reshape", [ins[0], shp], [r])
    return r


def _reg_arg_conv(op, onnx_op):
    @register_converter(op)
    def conv(ctx, s, ins, out, _onnx=onnx_op):
        a = s._attrs
        axis = a.get("axis")
        keepdims = int(bool(a.get("keepdims", False)))
        if axis is None:
            src = _flat_input(ctx, s, ins)
            ctx.emit(_onnx, [src], [out], attrs={"axis": 0, "keepdims": 0})
        else:
            ctx.emit(_onnx, ins, [out],
                     attrs={"axis": int(axis), "keepdims": keepdims})


_reg_arg_conv("argmax", "ArgMax")
_reg_arg_conv("argmin", "ArgMin")


@register_converter("topk")
def _topk_conv(ctx, s, ins, out):
    a = s._attrs
    if a.get("ret_typ", "indices") not in ("both", "value", "indices"):
        raise ValueError("topk export: ret_typ %r unsupported" % a["ret_typ"])
    k = ctx.const("k", np.asarray([int(a.get("k", 1))], np.int64))
    vals = ctx.fresh("topk_val")
    idx = ctx.fresh("topk_idx")
    ctx.emit("TopK", [ins[0], k], [vals, idx],
             attrs={"axis": int(a.get("axis", -1)),
                    "largest": 0 if a.get("is_ascend", False) else 1})
    ctx.multi[id(s)] = [vals, idx]
    # single-output forms project the right tensor
    primary = vals if a.get("ret_typ", "indices") != "indices" else idx
    ctx.emit("Identity", [primary], [out])


@register_converter("one_hot")
def _one_hot_conv(ctx, s, ins, out):
    a = s._attrs
    depth = ctx.const("depth", np.asarray(int(a["depth"]), np.int64))
    vals = ctx.const("values", np.asarray(
        [float(a.get("off_value", 0.0)), float(a.get("on_value", 1.0))],
        np.float32))
    ctx.emit("OneHot", [ins[0], depth, vals], [out], attrs={"axis": -1})


@register_converter("cumsum")
def _cumsum_conv(ctx, s, ins, out):
    axis = s._attrs.get("axis")
    if axis is None:
        # registry default: cumsum over the FLATTENED array
        src_name = _flat_input(ctx, s, ins)
        axis_c = ctx.const("axis", np.asarray(0, np.int64))
        ctx.emit("CumSum", [src_name, axis_c], [out])
        return
    axis_c = ctx.const("axis", np.asarray(int(axis), np.int64))
    ctx.emit("CumSum", [ins[0], axis_c], [out])


@register_converter("tile")
def _tile_conv(ctx, s, ins, out):
    reps = ctx.const("repeats", np.asarray(s._attrs["reps"], np.int64))
    ctx.emit("Tile", [ins[0], reps], [out])


@register_converter("broadcast_to")
def _broadcast_to_conv(ctx, s, ins, out):
    shape = list(s._attrs["shape"])
    if any(v == 0 for v in shape):
        # MXNet's 0 sentinel (copy this input dim) has no ONNX equivalent —
        # resolve through the input's static shape, or fail loudly
        in_shape = s._inputs[0].shape
        shape = [in_shape[i] if v == 0 else v for i, v in enumerate(shape)]
    shape_c = ctx.const("shape", np.asarray(shape, np.int64))
    ctx.emit("Expand", [ins[0], shape_c], [out])


@register_converter("pad")
def _pad_conv(ctx, s, ins, out):
    a = s._attrs
    mode = a.get("mode", "constant")
    if mode not in ("constant", "edge", "reflect"):
        raise ValueError("pad export: mode %r unsupported" % (mode,))
    pw = a["pad_width"]
    n = len(pw) // 2
    # MXNet interleave (b0, e0, ...) → ONNX [begins..., ends...]
    onnx_pads = [pw[2 * i] for i in range(n)] + \
                [pw[2 * i + 1] for i in range(n)]
    pads = ctx.const("pads", np.asarray(onnx_pads, np.int64))
    cval = ctx.const("cval", np.float32(a.get("constant_value", 0.0)))
    ctx.emit("Pad", [ins[0], pads, cval], [out],
             attrs={"mode": mode})


def _split_conv_impl(ctx, s, ins, out):
    a = s._attrs
    n_out = int(a["num_outputs"])
    if a.get("squeeze_axis"):
        raise ValueError("split export: squeeze_axis unsupported")
    names = [ctx.fresh("split%d" % i) for i in range(n_out)]
    ctx.emit("Split", ins, names, attrs={"axis": int(a.get("axis", 1))})
    ctx.multi[id(s)] = names
    ctx.emit("Identity", [names[0]], [out])


register_converter("split")(_split_conv_impl)
register_converter("SliceChannel")(_split_conv_impl)


# ------------------------------------------------- ONNX-parity op converters
# (ops/extra.py "ONNX-parity ops" section: importer counterparts live in
# import_model.py; these close the round trip)

@register_converter("einsum")
def _einsum_conv(ctx, s, ins, out):
    ctx.emit("Einsum", list(ins), [out],
             attrs={"equation": s._attrs["equation"]})


@register_converter("take_along_axis")
def _take_along_axis_conv(ctx, s, ins, out):
    idx = ctx.fresh("idx64")
    ctx.emit("Cast", [ins[1]], [idx], attrs={"to": 7})  # GatherElements: int64
    ctx.emit("GatherElements", [ins[0], idx], [out],
             attrs={"axis": int(s._attrs.get("axis", 0))})


@register_converter("scatter_elements")
def _scatter_elements_conv(ctx, s, ins, out):
    idx = ctx.fresh("idx64")
    ctx.emit("Cast", [ins[1]], [idx], attrs={"to": 7})
    attrs = {"axis": int(s._attrs.get("axis", 0))}
    red = s._attrs.get("reduction", "none")
    if red != "none":
        if ctx.opset < 16:
            raise ValueError("scatter_elements with reduction=%r needs "
                             "opset>=16; pass opset=16 to export_model"
                             % red)
        attrs["reduction"] = red
    ctx.emit("ScatterElements", [ins[0], idx, ins[2]], [out], attrs=attrs)


@register_converter("trilu")
def _trilu_conv(ctx, s, ins, out):
    if ctx.opset < 14:
        raise ValueError("trilu export needs opset>=14 (Trilu); pass "
                         "opset=14 to export_model")
    k = ctx.const("k", np.asarray(int(s._attrs.get("k", 0)), np.int64))
    ctx.emit("Trilu", [ins[0], k], [out],
             attrs={"upper": int(bool(s._attrs.get("upper", True)))})


@register_converter("celu")
def _celu_conv(ctx, s, ins, out):
    ctx.emit("Celu", ins[:1], [out],
             attrs={"alpha": float(s._attrs.get("alpha", 1.0))})


@register_converter("hardswish")
def _hardswish_conv(ctx, s, ins, out):
    if ctx.opset >= 14:
        ctx.emit("HardSwish", ins[:1], [out])
        return
    # opset 13 decomposition: x * HardSigmoid(x, alpha=1/6, beta=0.5)
    hs = ctx.fresh("hsig")
    ctx.emit("HardSigmoid", ins[:1], [hs],
             attrs={"alpha": 1.0 / 6.0, "beta": 0.5})
    ctx.emit("Mul", [ins[0], hs], [out])


@register_converter("thresholded_relu")
def _thresholded_relu_conv(ctx, s, ins, out):
    ctx.emit("ThresholdedRelu", ins[:1], [out],
             attrs={"alpha": float(s._attrs.get("alpha", 1.0))})


@register_converter("logsumexp")
def _logsumexp_conv(ctx, s, ins, out):
    a = s._attrs
    attrs = {"keepdims": int(bool(a.get("keepdims", False)))}
    ax = a.get("axis")
    if ax is not None:
        attrs["axes"] = [ax] if isinstance(ax, int) else list(ax)
    ctx.emit("ReduceLogSumExp", ins[:1], [out], attrs=attrs)


@register_converter("size_array")
def _size_array_conv(ctx, s, ins, out):
    ctx.emit("Size", ins[:1], [out])


@register_converter("scaled_dot_attention")
def _sdpa_conv(ctx, s, ins, out):
    """Decompose the attention seam into MatMul/Softmax — the lowering
    upstream mx2onnx applies to gluonnlp's BERT interleaved-matmul ops
    (python/mxnet/onnx/mx2onnx/_op_translations). q,k,v (B, H, T, D);
    optional mask input (1=keep) becomes an additive -1e9; causal=True
    bakes a (1, 1, Tq, Tk) triangular additive constant (shapes are static
    at export like every symbol_to_onnx graph)."""
    a = s._attrs
    q_shape = s._inputs[0].shape
    k_shape = s._inputs[1].shape
    D = q_shape[-1]
    scale = a.get("scale") or (1.0 / np.sqrt(D))

    kt = ctx.fresh("kT")
    ctx.emit("Transpose", [ins[1]], [kt], attrs={"perm": [0, 1, 3, 2]})
    raw = ctx.fresh("scores")
    ctx.emit("MatMul", [ins[0], kt], [raw])
    sc = ctx.const("sdpa_scale", np.float32(scale))
    scores = ctx.fresh("scaled")
    ctx.emit("Mul", [raw, sc], [scores])
    if a.get("causal"):
        tq, tk = q_shape[-2], k_shape[-2]
        tri = np.where(np.arange(tk)[None, :] <= np.arange(tq)[:, None],
                       0.0, -1e9).astype(np.float32)[None, None]
        add = ctx.const("causal_bias", tri)
        nxt = ctx.fresh("causal_scores")
        ctx.emit("Add", [scores, add], [nxt])
        scores = nxt
    if len(ins) > 3:  # boolean keep-mask input
        mb = ctx.fresh("mask_bool")
        ctx.emit("Cast", [ins[3]], [mb], attrs={"to": 9})
        neg = ctx.const("sdpa_neg", np.float32(-1e9))
        masked = ctx.fresh("masked_scores")
        ctx.emit("Where", [mb, scores, neg], [masked])
        scores = masked
    probs = ctx.fresh("attn_probs")
    ctx.emit("Softmax", [scores], [probs], attrs={"axis": -1})
    ctx.emit("MatMul", [probs, ins[2]], [out])


@register_converter("_arange")
def _arange_conv(ctx, s, ins, out):
    a = s._attrs
    from ..base import resolve_dtype
    arr = np.arange(a["start"], a["stop"], a.get("step", 1.0),
                    dtype=np.dtype(resolve_dtype(a.get("dtype") or "float32")))
    rep = int(a.get("repeat", 1))
    ctx.initializers[out] = np.repeat(arr, rep) if rep != 1 else arr
