"""Symbol graph → ONNX ModelProto (ref: python/mxnet/onnx/mx2onnx/_export_model.py
and _op_translations — the reference converts nnvm symbol nodes to ONNX nodes
one converter per op; this does the same over mxnet_tpu's Symbol DAG).

Entry points:
  export_model(block_or_symbol, params_or_shapes, ..., onnx_file)

A HybridBlock is first traced to a Symbol graph via ``block(sym.var('data'))``;
each Symbol node is then translated by a converter. Inference semantics:
BatchNorm exports running-stat normalization, Dropout exports identity-at-eval.
"""
from __future__ import annotations

import numpy as np

from . import proto as P
from ..symbol import Symbol

_CONVERTERS = {}


def register_converter(opname):
    def deco(fn):
        _CONVERTERS[opname] = fn
        return fn
    return deco


class _Ctx:
    """Per-export state: emitted nodes, initializers, name table."""

    def __init__(self, params, opset):
        self.nodes = []
        self.initializers = {}
        self.names = {}     # id(symbol) -> output value name
        self.params = params
        self.opset = opset
        self._uid = 0

    def fresh(self, hint):
        self._uid += 1
        return "%s_%d" % (hint, self._uid)

    def emit(self, op_type, inputs, outputs, name=None, attrs=None):
        self.nodes.append(P.node_proto(op_type, inputs, outputs,
                                       name or self.fresh(op_type.lower()),
                                       attrs or {}))

    def const(self, hint, arr):
        """Add an initializer tensor, return its name."""
        name = self.fresh(hint)
        self.initializers[name] = np.asarray(arr)
        return name


def _pair(v, n=2):
    return list(v) if isinstance(v, (tuple, list)) else [v] * n


# ------------------------------------------------------------- op converters
# Each converter: (ctx, node, in_names) -> out_name (or list of out names).

@register_converter("Convolution")
def _conv(ctx, s, ins, out):
    a = s._attrs
    kernel = _pair(a.get("kernel"))
    nd = len(kernel)
    pads = _pair(a.get("pad", 0), nd)
    attrs = {"kernel_shape": kernel,
             "strides": _pair(a.get("stride", 1), nd),
             "pads": pads + pads,   # begin then end
             "dilations": _pair(a.get("dilate", 1), nd),
             "group": int(a.get("num_group", 1))}
    ctx.emit("Conv", ins, [out], attrs=attrs)


@register_converter("Deconvolution")
def _deconv(ctx, s, ins, out):
    a = s._attrs
    kernel = _pair(a.get("kernel"))
    nd = len(kernel)
    pads = _pair(a.get("pad", 0), nd)
    attrs = {"kernel_shape": kernel,
             "strides": _pair(a.get("stride", 1), nd),
             "pads": pads + pads,
             "dilations": _pair(a.get("dilate", 1), nd),
             "group": int(a.get("num_group", 1))}
    adj = a.get("adj")
    if adj:
        attrs["output_padding"] = _pair(adj, nd)
    ctx.emit("ConvTranspose", ins, [out], attrs=attrs)


@register_converter("FullyConnected")
def _fc(ctx, s, ins, out):
    a = s._attrs
    x = ins[0]
    if a.get("flatten", True):
        flat = ctx.fresh("flatten")
        ctx.emit("Flatten", [x], [flat], attrs={"axis": 1})
        # Gemm: Y = X·Wᵀ + b  (MXNet weight is (num_hidden, in))
        gemm_in = [flat, ins[1]] + ins[2:3]
        ctx.emit("Gemm", gemm_in, [out], attrs={"transB": 1, "alpha": 1.0, "beta": 1.0})
    else:
        # N-D input: MatMul against Wᵀ then Add bias
        wt = ctx.fresh("w_t")
        ctx.emit("Transpose", [ins[1]], [wt], attrs={"perm": [1, 0]})
        mm = ctx.fresh("matmul") if len(ins) > 2 else out
        ctx.emit("MatMul", [x, wt], [mm])
        if len(ins) > 2:
            ctx.emit("Add", [mm, ins[2]], [out])


@register_converter("BatchNorm")
def _bn(ctx, s, ins, out):
    a = s._attrs
    # inputs arrive as (x, gamma, beta, moving_mean, moving_var) = ONNX order
    ctx.emit("BatchNormalization", ins[:5], [out],
             attrs={"epsilon": float(a.get("eps", 1e-5)),
                    "momentum": float(a.get("momentum", 0.9))})


@register_converter("LayerNorm")
def _ln(ctx, s, ins, out):
    a = s._attrs
    ctx.emit("LayerNormalization", ins[:3], [out],
             attrs={"axis": int(a.get("axis", -1)),
                    "epsilon": float(a.get("eps", 1e-5))})


_ACT2ONNX = {"relu": "Relu", "sigmoid": "Sigmoid", "tanh": "Tanh",
             "softrelu": "Softplus", "softsign": "Softsign"}


@register_converter("Activation")
def _act(ctx, s, ins, out):
    ctx.emit(_ACT2ONNX[s._attrs.get("act_type", "relu")], ins[:1], [out])


@register_converter("LeakyReLU")
def _leaky(ctx, s, ins, out):
    a = s._attrs
    act = a.get("act_type", "leaky")
    if act == "leaky":
        ctx.emit("LeakyRelu", ins[:1], [out],
                 attrs={"alpha": float(a.get("slope", 0.25))})
    elif act == "elu":
        ctx.emit("Elu", ins[:1], [out], attrs={"alpha": float(a.get("slope", 0.25))})
    elif act == "prelu":
        ctx.emit("PRelu", ins[:2], [out])
    elif act == "gelu":
        ctx.emit("Gelu", ins[:1], [out])
    elif act == "selu":
        ctx.emit("Selu", ins[:1], [out])
    else:
        raise ValueError("cannot export LeakyReLU act_type=%s" % act)


@register_converter("Pooling")
def _pool(ctx, s, ins, out):
    a = s._attrs
    ptype = a.get("pool_type", "max")
    if a.get("global_pool"):
        op = {"max": "GlobalMaxPool", "avg": "GlobalAveragePool"}[ptype]
        ctx.emit(op, ins[:1], [out])
        return
    kernel = _pair(a.get("kernel"))
    nd = len(kernel)
    pads = _pair(a.get("pad", 0), nd)
    attrs = {"kernel_shape": kernel,
             "strides": _pair(a.get("stride") or a.get("kernel"), nd),
             "pads": pads + pads}
    if ptype == "avg":
        attrs["count_include_pad"] = int(bool(a.get("count_include_pad", True)))
        ctx.emit("AveragePool", ins[:1], [out], attrs=attrs)
    elif ptype == "max":
        ctx.emit("MaxPool", ins[:1], [out], attrs=attrs)
    elif ptype == "lp":
        attrs["p"] = int(a.get("p_value", 2))
        ctx.emit("LpPool", ins[:1], [out], attrs=attrs)
    else:
        raise ValueError("cannot export pool_type=%s" % ptype)


@register_converter("Dropout")
def _dropout(ctx, s, ins, out):
    ctx.emit("Dropout", ins[:1], [out],
             attrs={})  # inference: identity; ratio only matters in training


@register_converter("Embedding")
def _embedding(ctx, s, ins, out):
    # F.Embedding(indices, weight) → Gather(weight, indices)
    ctx.emit("Gather", [ins[1], ins[0]], [out], attrs={"axis": 0})


@register_converter("flatten")
def _flatten(ctx, s, ins, out):
    ctx.emit("Flatten", ins, [out], attrs={"axis": 1})


@register_converter("softmax")
def _softmax(ctx, s, ins, out):
    ctx.emit("Softmax", ins[:1], [out], attrs={"axis": int(s._attrs.get("axis", -1))})


@register_converter("log_softmax")
def _log_softmax(ctx, s, ins, out):
    ctx.emit("LogSoftmax", ins[:1], [out], attrs={"axis": int(s._attrs.get("axis", -1))})


@register_converter("concat")
def _concat(ctx, s, ins, out):
    ctx.emit("Concat", ins, [out], attrs={"axis": int(s._attrs.get("dim", 1))})


@register_converter("reshape")
def _reshape(ctx, s, ins, out):
    shape = ctx.const("shape", np.asarray(s._attrs["shape"], np.int64))
    ctx.emit("Reshape", [ins[0], shape], [out])


@register_converter("transpose")
def _transpose(ctx, s, ins, out):
    attrs = {}
    if s._attrs.get("axes") is not None:
        attrs["perm"] = list(s._attrs["axes"])
    ctx.emit("Transpose", ins, [out], attrs=attrs)


@register_converter("expand_dims")
def _expand_dims(ctx, s, ins, out):
    axes = ctx.const("axes", np.asarray([s._attrs["axis"]], np.int64))
    ctx.emit("Unsqueeze", [ins[0], axes], [out])


@register_converter("squeeze")
def _squeeze(ctx, s, ins, out):
    ax = s._attrs.get("axis")
    if ax is None:
        ctx.emit("Squeeze", ins, [out])
    else:
        ax = [ax] if isinstance(ax, int) else list(ax)
        axes = ctx.const("axes", np.asarray(ax, np.int64))
        ctx.emit("Squeeze", [ins[0], axes], [out])


@register_converter("clip")
def _clip(ctx, s, ins, out):
    lo = ctx.const("min", np.float32(s._attrs["a_min"]))
    hi = ctx.const("max", np.float32(s._attrs["a_max"]))
    ctx.emit("Clip", [ins[0], lo, hi], [out])


def _reduce(onnx_op):
    def conv(ctx, s, ins, out):
        a = s._attrs
        attrs = {"keepdims": int(bool(a.get("keepdims", False)))}
        ax = a.get("axis")
        axes = None if ax is None else ([ax] if isinstance(ax, int) else list(ax))
        if onnx_op == "ReduceSum" and axes is not None:
            # opset 13 moved ReduceSum's axes from attribute to input
            axes_in = ctx.const("axes", np.asarray(axes, np.int64))
            ctx.emit(onnx_op, [ins[0], axes_in], [out], attrs=attrs)
            return
        if axes is not None:
            attrs["axes"] = axes
        ctx.emit(onnx_op, ins[:1], [out], attrs=attrs)
    return conv


for _mx, _onnx in [("mean", "ReduceMean"), ("sum", "ReduceSum"),
                   ("max", "ReduceMax"), ("min", "ReduceMin"),
                   ("prod", "ReduceProd")]:
    register_converter(_mx)(_reduce(_onnx))


def _binop(onnx_op):
    def conv(ctx, s, ins, out):
        ctx.emit(onnx_op, ins[:2], [out])
    return conv


for _mx, _onnx in [("add", "Add"), ("subtract", "Sub"), ("multiply", "Mul"),
                   ("divide", "Div"), ("power", "Pow"), ("maximum", "Max"),
                   ("minimum", "Min"), ("broadcast_add", "Add"),
                   ("broadcast_sub", "Sub"), ("broadcast_mul", "Mul"),
                   ("broadcast_div", "Div"), ("broadcast_power", "Pow"),
                   ("broadcast_maximum", "Max"), ("broadcast_minimum", "Min"),
                   ("dot", "MatMul"), ("matmul", "MatMul"),
                   ("batch_dot", "MatMul")]:
    register_converter(_mx)(_binop(_onnx))


def _unop(onnx_op):
    def conv(ctx, s, ins, out):
        ctx.emit(onnx_op, ins[:1], [out])
    return conv


for _mx, _onnx in [("relu", "Relu"), ("sigmoid", "Sigmoid"), ("tanh", "Tanh"),
                   ("exp", "Exp"), ("log", "Log"), ("sqrt", "Sqrt"),
                   ("negative", "Neg"), ("abs", "Abs"), ("floor", "Floor"),
                   ("ceil", "Ceil"), ("round", "Round"), ("erf", "Erf"),
                   ("sin", "Sin"), ("cos", "Cos"), ("tan", "Tan"),
                   ("reciprocal", "Reciprocal"), ("sign", "Sign"),
                   ("softsign", "Softsign"), ("softrelu", "Softplus")]:
    register_converter(_mx)(_unop(_onnx))


@register_converter("square")
def _square(ctx, s, ins, out):
    two = ctx.const("two", np.float32(2.0))
    ctx.emit("Pow", [ins[0], two], [out])


@register_converter("slice_axis")
def _slice_axis(ctx, s, ins, out):
    a = s._attrs
    end = a.get("end")
    starts = ctx.const("starts", np.asarray([a["begin"]], np.int64))
    ends = ctx.const("ends", np.asarray(
        [end if end is not None else np.iinfo(np.int64).max], np.int64))
    axes = ctx.const("axes", np.asarray([a["axis"]], np.int64))
    ctx.emit("Slice", [ins[0], starts, ends, axes], [out])


@register_converter("_const")
def _const_conv(ctx, s, ins, out):
    val = np.asarray(s._attrs["value"], np.float32)
    ctx.initializers[out] = val


# ------------------------------------------------------------- graph walker

def _toposort(outputs):
    order, seen = [], set()

    def walk(s):
        if id(s) in seen:
            return
        seen.add(id(s))
        for i in s._inputs:
            walk(i)
        order.append(s)

    for o in outputs:
        walk(o)
    return order


def symbol_to_onnx(sym_out, params, input_shapes, input_dtypes=None,
                   graph_name="mxnet_tpu", opset=13):
    """Convert a Symbol graph (single output or Group) to ModelProto bytes.

    params: {name: np.ndarray} for every non-data variable in the graph.
    input_shapes: {data_name: shape} for graph inputs.
    """
    outputs = sym_out._inputs if sym_out._op == "_group" else [sym_out]
    order = _toposort(outputs)
    ctx = _Ctx(params, opset)
    input_dtypes = input_dtypes or {}

    # name variables; params become initializers, the rest graph inputs
    graph_inputs = []
    for s in order:
        if not s.is_var():
            continue
        ctx.names[id(s)] = s.name
        if s.name in params:
            ctx.initializers[s.name] = np.asarray(params[s.name])
        else:
            if s.name not in input_shapes:
                raise ValueError("no shape for graph input %r" % s.name)
            graph_inputs.append(
                P.value_info(s.name, input_dtypes.get(s.name, np.float32),
                             input_shapes[s.name]))

    for s in order:
        if s.is_var():
            continue
        if s._op == "_item":
            # projection of a multi-output op: index 0 is the op's main
            # output. Reaching an index>0 projection in the walk means the
            # graph consumes a secondary output (e.g. BatchNorm's updated
            # running stats) that no exported ONNX node produces.
            parent = s._inputs[0]
            idx = s._attrs.get("index", 0)
            if idx != 0:
                raise ValueError(
                    "cannot export: graph consumes output %d of %r — only "
                    "the primary output of multi-output ops maps to ONNX "
                    "inference graphs" % (idx, parent._op))
            ctx.names[id(s)] = ctx.names[id(parent)]
            continue
        ins = [ctx.names[id(i)] for i in s._inputs]
        out = ctx.fresh(s.name or s._op)
        ctx.names[id(s)] = out
        conv = _CONVERTERS.get(s._op)
        if conv is None:
            raise ValueError("no ONNX converter for op %r (export coverage "
                             "mirrors mx2onnx/_op_translations)" % s._op)
        conv(ctx, s, ins, out)

    out_infos = [P.value_info(ctx.names[id(o)], np.float32, ())
                 for o in outputs]
    init_protos = [P.tensor_proto(n, a) for n, a in ctx.initializers.items()]
    graph = P.graph_proto(graph_name, ctx.nodes, graph_inputs, out_infos,
                          init_protos)
    return P.model_proto(graph, opset=opset).tobytes()


def export_model(model, params=None, input_shapes=None, input_types=None,
                 onnx_file=None, input_names=("data",), opset=13):
    """Export a HybridBlock or Symbol to an ONNX file
    (ref: python/mxnet/onnx/mx2onnx/_export_model.py:export_model).

    * HybridBlock: traced via block(sym.var(name) for each input_name);
      parameters are pulled from collect_params().
    * Symbol: ``params`` must map var name → array.
    Returns the path written (or the serialized bytes if onnx_file is None).
    """
    from .. import sym as _sym

    if input_shapes is None:
        raise ValueError("input_shapes is required")
    if not isinstance(input_shapes, dict):
        input_shapes = dict(zip(input_names, [tuple(s) for s in input_shapes]))

    if isinstance(model, Symbol):
        sym_out = model
        params = {k: np.asarray(v.asnumpy() if hasattr(v, "asnumpy") else v)
                  for k, v in (params or {}).items()}
    else:
        data = [_sym.var(n) for n in input_shapes]
        sym_out = model(*data)
        if isinstance(sym_out, (list, tuple)):
            from ..symbol import Group
            sym_out = Group(list(sym_out))
        params = {p.name: p.data().asnumpy()
                  for p in model.collect_params().values()}

    buf = symbol_to_onnx(sym_out, params, input_shapes,
                         input_dtypes=input_types, opset=opset)
    if onnx_file is None:
        return buf
    with open(onnx_file, "wb") as f:
        f.write(buf)
    return onnx_file
