"""ONNX ModelProto → Symbol graph + params (ref:
python/mxnet/onnx/onnx2mx/import_model.py and _op_translations — the
reference builds an nnvm symbol per ONNX node; this builds mxnet_tpu Symbols).

Coverage mirrors what export.py emits (the model-zoo op set); unknown ops
raise with the op name so gaps are explicit.
"""
from __future__ import annotations

import numpy as np

from . import proto as P
from ..symbol import Symbol, _make, var

_IMPORTERS = {}


def register_importer(onnx_op):
    def deco(fn):
        _IMPORTERS[onnx_op] = fn
        return fn
    return deco


class _Graph:
    def __init__(self, parsed):
        self.initializers = parsed["initializers"]  # name -> np array
        self.syms = {}                              # value name -> Symbol
        self.used_params = set()

    def inp(self, name):
        """Symbol for a node input; initializer-backed names become vars."""
        if name in self.syms:
            return self.syms[name]
        if name in self.initializers:
            self.used_params.add(name)
            s = var(name)
            self.syms[name] = s
            return s
        raise KeyError("undefined ONNX value %r" % name)

    def const_value(self, name):
        """Static value of an initializer-fed input (Reshape shape etc.)."""
        if name not in self.initializers:
            raise ValueError("input %r must be a constant initializer" % name)
        return self.initializers[name]


def _sym_pair(v):
    return tuple(v)


# ----------------------------------------------------------------- importers

@register_importer("Conv")
def _conv(g, node):
    a = node["attrs"]
    pads = a.get("pads")
    nd = len(a["kernel_shape"])
    if pads:
        begin, end = pads[:nd], pads[nd:]
        if begin != end:
            raise ValueError("asymmetric Conv pads unsupported: %s" % pads)
        pad = tuple(begin)
    else:
        pad = (0,) * nd
    ins = [g.inp(n) for n in node["inputs"]]
    return _make("Convolution", *ins, kernel=tuple(a["kernel_shape"]),
                 stride=tuple(a.get("strides", (1,) * nd)), pad=pad,
                 dilate=tuple(a.get("dilations", (1,) * nd)),
                 num_group=int(a.get("group", 1)),
                 no_bias=len(ins) < 3)


@register_importer("ConvTranspose")
def _deconv(g, node):
    a = node["attrs"]
    nd = len(a["kernel_shape"])
    pads = a.get("pads")
    pad = tuple(pads[:nd]) if pads else (0,) * nd
    ins = [g.inp(n) for n in node["inputs"]]
    kw = dict(kernel=tuple(a["kernel_shape"]),
              stride=tuple(a.get("strides", (1,) * nd)), pad=pad,
              dilate=tuple(a.get("dilations", (1,) * nd)),
              num_group=int(a.get("group", 1)), no_bias=len(ins) < 3)
    if a.get("output_padding"):
        kw["adj"] = tuple(a["output_padding"])
    return _make("Deconvolution", *ins, **kw)


@register_importer("Gemm")
def _gemm(g, node):
    a = node["attrs"]
    if a.get("transA") or not a.get("transB", 0):
        raise ValueError("only Gemm(transA=0, transB=1) supported")
    alpha, beta = float(a.get("alpha", 1.0)), float(a.get("beta", 1.0))
    for name, scale in [(node["inputs"][1], alpha)] + (
            [(node["inputs"][2], beta)] if len(node["inputs"]) > 2 else []):
        if scale != 1.0:
            init = g.initializers.get(name)
            if init is None:
                raise ValueError("Gemm alpha/beta != 1 on non-initializer input")
            g.initializers[name] = np.asarray(init) * scale
    ins = [g.inp(n) for n in node["inputs"]]
    w = g.initializers.get(node["inputs"][1])
    num_hidden = int(w.shape[0]) if w is not None else 0
    return _make("FullyConnected", *ins, num_hidden=num_hidden,
                 no_bias=len(ins) < 3, flatten=True)


@register_importer("MatMul")
def _matmul(g, node):
    return _make("matmul", g.inp(node["inputs"][0]), g.inp(node["inputs"][1]))


@register_importer("BatchNormalization")
def _bn(g, node):
    a = node["attrs"]
    ins = [g.inp(n) for n in node["inputs"]]
    out = _make("BatchNorm", *ins, eps=float(a.get("epsilon", 1e-5)),
                momentum=float(a.get("momentum", 0.9)),
                use_global_stats=True)
    return out[0]


@register_importer("LayerNormalization")
def _ln(g, node):
    a = node["attrs"]
    ins = [g.inp(n) for n in node["inputs"]]
    return _make("LayerNorm", *ins, axis=int(a.get("axis", -1)),
                 eps=float(a.get("epsilon", 1e-5)))


for _onnx, _act in [("Relu", "relu"), ("Sigmoid", "sigmoid"), ("Tanh", "tanh"),
                    ("Softplus", "softrelu"), ("Softsign", "softsign")]:
    def _mk_act(act):
        def imp(g, node):
            return _make("Activation", g.inp(node["inputs"][0]), act_type=act)
        return imp
    register_importer(_onnx)(_mk_act(_act))


@register_importer("LeakyRelu")
def _leaky(g, node):
    return _make("LeakyReLU", g.inp(node["inputs"][0]), act_type="leaky",
                 slope=float(node["attrs"].get("alpha", 0.01)))


@register_importer("Elu")
def _elu(g, node):
    return _make("LeakyReLU", g.inp(node["inputs"][0]), act_type="elu",
                 slope=float(node["attrs"].get("alpha", 1.0)))


@register_importer("PRelu")
def _prelu(g, node):
    return _make("LeakyReLU", g.inp(node["inputs"][0]),
                 g.inp(node["inputs"][1]), act_type="prelu")


@register_importer("Selu")
def _selu(g, node):
    return _make("LeakyReLU", g.inp(node["inputs"][0]), act_type="selu")


@register_importer("Gelu")
def _gelu(g, node):
    return _make("LeakyReLU", g.inp(node["inputs"][0]), act_type="gelu")


def _pool(ptype):
    def imp(g, node):
        a = node["attrs"]
        nd = len(a["kernel_shape"])
        pads = a.get("pads")
        pad = tuple(pads[:nd]) if pads else (0,) * nd
        kw = dict(kernel=tuple(a["kernel_shape"]),
                  stride=tuple(a.get("strides", (1,) * nd)),
                  pad=pad, pool_type=ptype)
        if ptype == "avg":
            kw["count_include_pad"] = bool(a.get("count_include_pad", 0))
        if ptype == "lp":
            kw["p_value"] = int(a.get("p", 2))
        return _make("Pooling", g.inp(node["inputs"][0]), **kw)
    return imp


register_importer("MaxPool")(_pool("max"))
register_importer("AveragePool")(_pool("avg"))
register_importer("LpPool")(_pool("lp"))


@register_importer("GlobalAveragePool")
def _gap(g, node):
    return _make("Pooling", g.inp(node["inputs"][0]), kernel=(1, 1),
                 pool_type="avg", global_pool=True)


@register_importer("GlobalMaxPool")
def _gmp(g, node):
    return _make("Pooling", g.inp(node["inputs"][0]), kernel=(1, 1),
                 pool_type="max", global_pool=True)


@register_importer("Dropout")
def _dropout(g, node):
    return g.inp(node["inputs"][0])  # inference: identity


@register_importer("Identity")
def _identity(g, node):
    return g.inp(node["inputs"][0])


@register_importer("Gather")
def _gather(g, node):
    axis = int(node["attrs"].get("axis", 0))
    return _make("take", g.inp(node["inputs"][0]), g.inp(node["inputs"][1]),
                 axis=axis, mode="clip")


@register_importer("Flatten")
def _flatten(g, node):
    if int(node["attrs"].get("axis", 1)) != 1:
        raise ValueError("Flatten axis != 1 unsupported")
    return _make("flatten", g.inp(node["inputs"][0]))


@register_importer("Softmax")
def _softmax(g, node):
    return _make("softmax", g.inp(node["inputs"][0]),
                 axis=int(node["attrs"].get("axis", -1)))


@register_importer("LogSoftmax")
def _log_softmax(g, node):
    return _make("log_softmax", g.inp(node["inputs"][0]),
                 axis=int(node["attrs"].get("axis", -1)))


@register_importer("Concat")
def _concat(g, node):
    ins = [g.inp(n) for n in node["inputs"]]
    return _make("concat", *ins, dim=int(node["attrs"].get("axis", 1)))


@register_importer("Reshape")
def _reshape(g, node):
    shape = tuple(int(v) for v in g.const_value(node["inputs"][1]))
    return _make("reshape", g.inp(node["inputs"][0]), shape=shape)


@register_importer("Transpose")
def _transpose(g, node):
    perm = node["attrs"].get("perm")
    return _make("transpose", g.inp(node["inputs"][0]),
                 axes=tuple(perm) if perm else None)


@register_importer("Unsqueeze")
def _unsqueeze(g, node):
    if len(node["inputs"]) > 1:
        axes = [int(v) for v in g.const_value(node["inputs"][1])]
    else:
        axes = node["attrs"]["axes"]
    out = g.inp(node["inputs"][0])
    for ax in axes:
        out = _make("expand_dims", out, axis=int(ax))
    return out


@register_importer("Squeeze")
def _squeeze(g, node):
    if len(node["inputs"]) > 1:
        axes = tuple(int(v) for v in g.const_value(node["inputs"][1]))
    elif "axes" in node["attrs"]:
        axes = tuple(node["attrs"]["axes"])
    else:
        axes = None
    return _make("squeeze", g.inp(node["inputs"][0]), axis=axes)


@register_importer("Clip")
def _clip(g, node):
    lo = float(g.const_value(node["inputs"][1])) if len(node["inputs"]) > 1 else -np.inf
    hi = float(g.const_value(node["inputs"][2])) if len(node["inputs"]) > 2 else np.inf
    return _make("clip", g.inp(node["inputs"][0]), a_min=lo, a_max=hi)


@register_importer("Slice")
def _slice(g, node):
    starts = [int(v) for v in g.const_value(node["inputs"][1])]
    ends = [int(v) for v in g.const_value(node["inputs"][2])]
    axes = ([int(v) for v in g.const_value(node["inputs"][3])]
            if len(node["inputs"]) > 3 else list(range(len(starts))))
    out = g.inp(node["inputs"][0])
    imax = np.iinfo(np.int64).max
    for st, en, ax in zip(starts, ends, axes):
        out = _make("slice_axis", out, axis=ax, begin=st,
                    end=None if en >= imax else en)
    return out


def _reduce(mx_op):
    def imp(g, node):
        a = node["attrs"]
        axes = a.get("axes")
        if axes is None and len(node["inputs"]) > 1:
            # opset>=13 ReduceSum: axes is a second (initializer) input
            ax_init = g.initializers.get(node["inputs"][1])
            if ax_init is None:
                raise ValueError("%s: dynamic axes input unsupported"
                                 % node["op_type"])
            axes = [int(x) for x in np.asarray(ax_init).reshape(-1)]
        kw = {"keepdims": bool(a.get("keepdims", 1))}
        if axes is not None:
            kw["axis"] = tuple(axes) if len(axes) > 1 else int(axes[0])
        return _make(mx_op, g.inp(node["inputs"][0]), **kw)
    return imp


for _onnx, _mx in [("ReduceMean", "mean"), ("ReduceSum", "sum"),
                   ("ReduceMax", "max"), ("ReduceMin", "min"),
                   ("ReduceProd", "prod")]:
    register_importer(_onnx)(_reduce(_mx))


def _binop(mx_op):
    def imp(g, node):
        return _make(mx_op, g.inp(node["inputs"][0]), g.inp(node["inputs"][1]))
    return imp


for _onnx, _mx in [("Add", "add"), ("Sub", "subtract"), ("Mul", "multiply"),
                   ("Div", "divide"), ("Pow", "power")]:
    register_importer(_onnx)(_binop(_mx))


def _minmax(mx_op):
    def imp(g, node):
        out = g.inp(node["inputs"][0])
        for n in node["inputs"][1:]:
            out = _make(mx_op, out, g.inp(n))
        return out
    return imp


register_importer("Max")(_minmax("maximum"))
register_importer("Min")(_minmax("minimum"))


def _unop(mx_op):
    def imp(g, node):
        return _make(mx_op, g.inp(node["inputs"][0]))
    return imp


for _onnx, _mx in [("Exp", "exp"), ("Log", "log"), ("Sqrt", "sqrt"),
                   ("Neg", "negative"), ("Abs", "abs"), ("Floor", "floor"),
                   ("Ceil", "ceil"), ("Round", "round"), ("Erf", "erf"),
                   ("Sin", "sin"), ("Cos", "cos"), ("Tan", "tan"),
                   ("Reciprocal", "reciprocal"), ("Sign", "sign")]:
    register_importer(_onnx)(_unop(_mx))


@register_importer("Constant")
def _constant(g, node):
    val = node["attrs"].get("value")
    s = var(node["outputs"][0])
    g.initializers[node["outputs"][0]] = np.asarray(val)
    g.used_params.add(node["outputs"][0])
    return s


# ----------------------------------------------------------------- front end

def import_model(model_file):
    """ONNX file/bytes → (sym, arg_params, aux_params)
    (ref: python/mxnet/onnx/onnx2mx/import_model.py:import_model).

    aux_params holds BatchNorm running stats (inputs 3/4 of
    BatchNormalization), matching MXNet's arg/aux split.
    """
    if isinstance(model_file, (bytes, bytearray)):
        buf = bytes(model_file)
    else:
        with open(model_file, "rb") as f:
            buf = f.read()
    parsed = P.parse_model(buf)
    graph = parsed["graph"]
    g = _Graph(graph)

    for vi in graph["inputs"]:
        if vi["name"] not in g.initializers:
            g.syms[vi["name"]] = var(vi["name"])

    aux_names = set()
    for node in graph["nodes"]:
        if node["op"] == "BatchNormalization":
            aux_names.update(node["inputs"][3:5])

    for node in graph["nodes"]:
        imp = _IMPORTERS.get(node["op"])
        if imp is None:
            raise ValueError("no importer for ONNX op %r" % node["op"])
        out = imp(g, node)
        outs = out if isinstance(out, (list, tuple)) else [out]
        for name, s in zip(node["outputs"], outs):
            s.name = s.name if s.is_var() else name
            g.syms[name] = s

    out_syms = [g.syms[o["name"]] for o in graph["outputs"]]
    sym_out = out_syms[0] if len(out_syms) == 1 else __import__(
        "mxnet_tpu.symbol", fromlist=["Group"]).Group(out_syms)

    arg_params, aux_params = {}, {}
    for name in g.used_params:
        arr = g.initializers[name]
        (aux_params if name in aux_names else arg_params)[name] = arr
    return sym_out, arg_params, aux_params


def import_to_gluon(model_file, ctx=None):
    """ONNX file → executable SymbolBlock
    (ref: python/mxnet/onnx/onnx2mx/import_to_gluon.py)."""
    import jax.numpy as jnp

    from ..gluon.block import SymbolBlock
    from ..gluon.parameter import Parameter

    sym_out, arg_params, aux_params = import_model(model_file)
    param_names = set(arg_params) | set(aux_params)
    # list_arguments() is deterministic depth-first order — input binding in
    # SymbolBlock.forward is positional, so order must be stable
    input_names = [n for n in sym_out.list_arguments() if n not in param_names]
    inputs = [var(n) for n in input_names]
    blk = SymbolBlock(sym_out, inputs)
    for name, arr in {**arg_params, **aux_params}.items():
        p = Parameter(name, shape=arr.shape)
        p.set_data(jnp.asarray(arr))
        blk._params._params[name] = p
    return blk
