"""ONNX ModelProto → Symbol graph + params (ref:
python/mxnet/onnx/onnx2mx/import_model.py and _op_translations — the
reference builds an nnvm symbol per ONNX node; this builds mxnet_tpu Symbols).

Coverage mirrors what export.py emits (the model-zoo op set); unknown ops
raise with the op name so gaps are explicit.
"""
from __future__ import annotations

import numpy as np

from . import proto as P
from ..symbol import Symbol, _make, var

_IMPORTERS = {}


def register_importer(onnx_op):
    def deco(fn):
        _IMPORTERS[onnx_op] = fn
        return fn
    return deco


class _Graph:
    def __init__(self, parsed, opset=13):
        self.initializers = parsed["initializers"]  # name -> np array
        self.syms = {}                              # value name -> Symbol
        self.used_params = set()
        self.opset = opset

    def inp(self, name):
        """Symbol for a node input; initializer-backed names become vars."""
        if name in self.syms:
            return self.syms[name]
        if name in self.initializers:
            self.used_params.add(name)
            s = var(name)
            self.syms[name] = s
            return s
        raise KeyError("undefined ONNX value %r" % name)

    def const_value(self, name):
        """Static value of an initializer-fed input (Reshape shape etc.)."""
        if name not in self.initializers:
            raise ValueError("input %r must be a constant initializer" % name)
        return self.initializers[name]


def _sym_pair(v):
    return tuple(v)


# ----------------------------------------------------------------- importers

@register_importer("Conv")
def _conv(g, node):
    a = node["attrs"]
    pads = a.get("pads")
    nd = len(a["kernel_shape"])
    if pads:
        begin, end = pads[:nd], pads[nd:]
        if begin != end:
            raise ValueError("asymmetric Conv pads unsupported: %s" % pads)
        pad = tuple(begin)
    else:
        pad = (0,) * nd
    ins = [g.inp(n) for n in node["inputs"]]
    return _make("Convolution", *ins, kernel=tuple(a["kernel_shape"]),
                 stride=tuple(a.get("strides", (1,) * nd)), pad=pad,
                 dilate=tuple(a.get("dilations", (1,) * nd)),
                 num_group=int(a.get("group", 1)),
                 no_bias=len(ins) < 3)


@register_importer("ConvTranspose")
def _deconv(g, node):
    a = node["attrs"]
    nd = len(a["kernel_shape"])
    pads = a.get("pads")
    pad = tuple(pads[:nd]) if pads else (0,) * nd
    ins = [g.inp(n) for n in node["inputs"]]
    kw = dict(kernel=tuple(a["kernel_shape"]),
              stride=tuple(a.get("strides", (1,) * nd)), pad=pad,
              dilate=tuple(a.get("dilations", (1,) * nd)),
              num_group=int(a.get("group", 1)), no_bias=len(ins) < 3)
    if a.get("output_padding"):
        kw["adj"] = tuple(a["output_padding"])
    return _make("Deconvolution", *ins, **kw)


@register_importer("Gemm")
def _gemm(g, node):
    a = node["attrs"]
    if a.get("transA") or not a.get("transB", 0):
        raise ValueError("only Gemm(transA=0, transB=1) supported")
    alpha, beta = float(a.get("alpha", 1.0)), float(a.get("beta", 1.0))
    for name, scale in [(node["inputs"][1], alpha)] + (
            [(node["inputs"][2], beta)] if len(node["inputs"]) > 2 else []):
        if scale != 1.0:
            init = g.initializers.get(name)
            if init is None:
                raise ValueError("Gemm alpha/beta != 1 on non-initializer input")
            g.initializers[name] = np.asarray(init) * scale
    ins = [g.inp(n) for n in node["inputs"]]
    w = g.initializers.get(node["inputs"][1])
    num_hidden = int(w.shape[0]) if w is not None else 0
    return _make("FullyConnected", *ins, num_hidden=num_hidden,
                 no_bias=len(ins) < 3, flatten=True)


@register_importer("MatMul")
def _matmul(g, node):
    return _make("matmul", g.inp(node["inputs"][0]), g.inp(node["inputs"][1]))


@register_importer("BatchNormalization")
def _bn(g, node):
    a = node["attrs"]
    ins = [g.inp(n) for n in node["inputs"]]
    out = _make("BatchNorm", *ins, eps=float(a.get("epsilon", 1e-5)),
                momentum=float(a.get("momentum", 0.9)),
                use_global_stats=True)
    return out[0]


@register_importer("LayerNormalization")
def _ln(g, node):
    a = node["attrs"]
    ins = [g.inp(n) for n in node["inputs"]]
    return _make("LayerNorm", *ins, axis=int(a.get("axis", -1)),
                 eps=float(a.get("epsilon", 1e-5)))


for _onnx, _act in [("Relu", "relu"), ("Sigmoid", "sigmoid"), ("Tanh", "tanh"),
                    ("Softplus", "softrelu"), ("Softsign", "softsign")]:
    def _mk_act(act):
        def imp(g, node):
            return _make("Activation", g.inp(node["inputs"][0]), act_type=act)
        return imp
    register_importer(_onnx)(_mk_act(_act))


@register_importer("LeakyRelu")
def _leaky(g, node):
    return _make("LeakyReLU", g.inp(node["inputs"][0]), act_type="leaky",
                 slope=float(node["attrs"].get("alpha", 0.01)))


@register_importer("Elu")
def _elu(g, node):
    return _make("LeakyReLU", g.inp(node["inputs"][0]), act_type="elu",
                 slope=float(node["attrs"].get("alpha", 1.0)))


@register_importer("PRelu")
def _prelu(g, node):
    return _make("LeakyReLU", g.inp(node["inputs"][0]),
                 g.inp(node["inputs"][1]), act_type="prelu")


@register_importer("Selu")
def _selu(g, node):
    return _make("LeakyReLU", g.inp(node["inputs"][0]), act_type="selu")


@register_importer("Gelu")
def _gelu(g, node):
    return _make("LeakyReLU", g.inp(node["inputs"][0]), act_type="gelu")


def _pool(ptype):
    def imp(g, node):
        a = node["attrs"]
        nd = len(a["kernel_shape"])
        pads = a.get("pads")
        pad = tuple(pads[:nd]) if pads else (0,) * nd
        kw = dict(kernel=tuple(a["kernel_shape"]),
                  stride=tuple(a.get("strides", (1,) * nd)),
                  pad=pad, pool_type=ptype)
        if ptype == "avg":
            kw["count_include_pad"] = bool(a.get("count_include_pad", 0))
        if ptype == "lp":
            kw["p_value"] = int(a.get("p", 2))
        return _make("Pooling", g.inp(node["inputs"][0]), **kw)
    return imp


register_importer("MaxPool")(_pool("max"))
register_importer("AveragePool")(_pool("avg"))
register_importer("LpPool")(_pool("lp"))


@register_importer("GlobalAveragePool")
def _gap(g, node):
    return _make("Pooling", g.inp(node["inputs"][0]), kernel=(1, 1),
                 pool_type="avg", global_pool=True)


@register_importer("GlobalMaxPool")
def _gmp(g, node):
    return _make("Pooling", g.inp(node["inputs"][0]), kernel=(1, 1),
                 pool_type="max", global_pool=True)


@register_importer("Dropout")
def _dropout(g, node):
    return g.inp(node["inputs"][0])  # inference: identity


@register_importer("Identity")
def _identity(g, node):
    return g.inp(node["inputs"][0])


@register_importer("Gather")
def _gather(g, node):
    axis = int(node["attrs"].get("axis", 0))
    return _make("take", g.inp(node["inputs"][0]), g.inp(node["inputs"][1]),
                 axis=axis, mode="clip")


@register_importer("Flatten")
def _flatten(g, node):
    if int(node["attrs"].get("axis", 1)) != 1:
        raise ValueError("Flatten axis != 1 unsupported")
    return _make("flatten", g.inp(node["inputs"][0]))


@register_importer("Softmax")
def _softmax(g, node):
    return _make("softmax", g.inp(node["inputs"][0]),
                 axis=int(node["attrs"].get("axis", -1)))


@register_importer("LogSoftmax")
def _log_softmax(g, node):
    return _make("log_softmax", g.inp(node["inputs"][0]),
                 axis=int(node["attrs"].get("axis", -1)))


@register_importer("Concat")
def _concat(g, node):
    ins = [g.inp(n) for n in node["inputs"]]
    return _make("concat", *ins, dim=int(node["attrs"].get("axis", 1)))


@register_importer("Reshape")
def _reshape(g, node):
    shape = tuple(int(v) for v in g.const_value(node["inputs"][1]))
    return _make("reshape", g.inp(node["inputs"][0]), shape=shape)


@register_importer("Transpose")
def _transpose(g, node):
    perm = node["attrs"].get("perm")
    return _make("transpose", g.inp(node["inputs"][0]),
                 axes=tuple(perm) if perm else None)


@register_importer("Unsqueeze")
def _unsqueeze(g, node):
    if len(node["inputs"]) > 1:
        axes = [int(v) for v in g.const_value(node["inputs"][1])]
    else:
        axes = node["attrs"]["axes"]
    out = g.inp(node["inputs"][0])
    for ax in axes:
        out = _make("expand_dims", out, axis=int(ax))
    return out


@register_importer("Squeeze")
def _squeeze(g, node):
    if len(node["inputs"]) > 1:
        axes = tuple(int(v) for v in g.const_value(node["inputs"][1]))
    elif "axes" in node["attrs"]:
        axes = tuple(node["attrs"]["axes"])
    else:
        axes = None
    return _make("squeeze", g.inp(node["inputs"][0]), axis=axes)


@register_importer("Clip")
def _clip(g, node):
    lo = float(g.const_value(node["inputs"][1])) if len(node["inputs"]) > 1 else -np.inf
    hi = float(g.const_value(node["inputs"][2])) if len(node["inputs"]) > 2 else np.inf
    return _make("clip", g.inp(node["inputs"][0]), a_min=lo, a_max=hi)


@register_importer("Slice")
def _slice(g, node):
    starts = [int(v) for v in g.const_value(node["inputs"][1])]
    ends = [int(v) for v in g.const_value(node["inputs"][2])]
    axes = ([int(v) for v in g.const_value(node["inputs"][3])]
            if len(node["inputs"]) > 3 and node["inputs"][3]
            else list(range(len(starts))))
    steps = ([int(v) for v in g.const_value(node["inputs"][4])]
             if len(node["inputs"]) > 4 and node["inputs"][4]
             else [1] * len(starts))
    out = g.inp(node["inputs"][0])
    imax = np.iinfo(np.int64).max
    for st, en, ax, sp in zip(starts, ends, axes, steps):
        if sp == 1:
            out = _make("slice_axis", out, axis=ax, begin=st,
                        end=None if en >= imax else en)
        elif sp == -1 and st == -1 and en <= -imax + 1:
            # full reversal along ax (the SequenceReverse lowering)
            out = _make("reverse", out, axis=ax)
        else:
            raise ValueError(
                "Slice import: step %d (start %d, end %d) unsupported — "
                "only unit steps and full reversals map to registry ops"
                % (sp, st, en))
    return out


def _reduce(mx_op):
    def imp(g, node):
        a = node["attrs"]
        axes = a.get("axes")
        if axes is None and len(node["inputs"]) > 1:
            # opset>=13 ReduceSum: axes is a second (initializer) input
            ax_init = g.initializers.get(node["inputs"][1])
            if ax_init is None:
                raise ValueError("%s: dynamic axes input unsupported"
                                 % node["op"])
            axes = [int(x) for x in np.asarray(ax_init).reshape(-1)]
        if _reduce_is_noop(node, axes):
            return _make("identity", g.inp(node["inputs"][0]))
        kw = {"keepdims": bool(a.get("keepdims", 1))}
        if axes:
            kw["axis"] = tuple(axes) if len(axes) > 1 else int(axes[0])
        return _make(mx_op, g.inp(node["inputs"][0]), **kw)
    return imp


for _onnx, _mx in [("ReduceMean", "mean"), ("ReduceSum", "sum"),
                   ("ReduceMax", "max"), ("ReduceMin", "min"),
                   ("ReduceProd", "prod")]:
    register_importer(_onnx)(_reduce(_mx))


def _binop(mx_op):
    def imp(g, node):
        return _make(mx_op, g.inp(node["inputs"][0]), g.inp(node["inputs"][1]))
    return imp


for _onnx, _mx in [("Add", "add"), ("Sub", "subtract"), ("Mul", "multiply"),
                   ("Div", "divide"), ("Pow", "power")]:
    register_importer(_onnx)(_binop(_mx))


def _minmax(mx_op):
    def imp(g, node):
        out = g.inp(node["inputs"][0])
        for n in node["inputs"][1:]:
            out = _make(mx_op, out, g.inp(n))
        return out
    return imp


register_importer("Max")(_minmax("maximum"))
register_importer("Min")(_minmax("minimum"))


def _unop(mx_op):
    def imp(g, node):
        return _make(mx_op, g.inp(node["inputs"][0]))
    return imp


for _onnx, _mx in [("Exp", "exp"), ("Log", "log"), ("Sqrt", "sqrt"),
                   ("Neg", "negative"), ("Abs", "abs"), ("Floor", "floor"),
                   ("Ceil", "ceil"), ("Round", "round"), ("Erf", "erf"),
                   ("Sin", "sin"), ("Cos", "cos"), ("Tan", "tan"),
                   ("Reciprocal", "reciprocal"), ("Sign", "sign"),
                   ("Asin", "arcsin"), ("Acos", "arccos"),
                   ("Atan", "arctan"), ("Sinh", "sinh"), ("Cosh", "cosh"),
                   ("Asinh", "arcsinh"), ("Acosh", "arccosh"),
                   ("Atanh", "arctanh"), ("IsNaN", "isnan")]:
    register_importer(_onnx)(_unop(_mx))


@register_importer("IsInf")
def _isinf_imp(g, node):
    a = node["attrs"]
    if not int(a.get("detect_negative", 1)) or \
            not int(a.get("detect_positive", 1)):
        raise ValueError("IsInf import: one-sided detect_negative/"
                         "detect_positive not supported")
    return _make("isinf", g.inp(node["inputs"][0]))


@register_importer("Shape")
def _shape(g, node):
    return _make("_onnx_shape", g.inp(node["inputs"][0]))


@register_importer("ConstantOfShape")
def _constant_of_shape(g, node):
    val = node["attrs"].get("value")
    v = float(np.asarray(val).reshape(-1)[0]) if val is not None else 0.0
    ins = node["inputs"]
    if ins[0] in g.initializers:
        shape = tuple(int(x) for x in np.asarray(g.const_value(ins[0])))
        return _make("_filled", shape=shape, value=v)
    src = g.inp(ins[0])
    if src._op == "_onnx_shape":
        # ConstantOfShape(Shape(x)) — the zeros_like/full_like lowering
        base = _make("zeros_like", src._inputs[0])
        return base if v == 0.0 else _make("add", base, v)
    raise ValueError("ConstantOfShape: shape input must be a constant or a "
                     "Shape node")


@register_importer("Cast")
def _cast(g, node):
    to = int(node["attrs"]["to"])
    dtype = np.dtype(P.onnx_to_np_dtype(to)).name
    return _make("cast", g.inp(node["inputs"][0]), dtype=dtype)


def _import_subgraph(g, graphd, tag, bound_inputs=()):
    """Run a subgraph's nodes through the importers in a scoped symbol table
    (ONNX scoping: inner names may shadow outer; restored afterwards).
    ``bound_inputs``: {formal input name: Symbol} for loop vars. Returns the
    list of subgraph output Symbols. Shared by If/Scan (and future Loop)."""
    saved_syms = dict(g.syms)
    for k, v in graphd.get("initializers", {}).items():
        if k in g.initializers and not np.array_equal(g.initializers[k], v):
            raise ValueError(
                "%s import: subgraph initializer %r shadows an outer "
                "initializer with different data" % (tag, k))
        g.initializers[k] = v
    try:
        for nm, sy in dict(bound_inputs).items():
            g.syms[nm] = sy
        for sub in graphd["nodes"]:
            imp = _IMPORTERS.get(sub["op"])
            if imp is None:
                raise ValueError("no importer for ONNX op %r (%s subgraph)"
                                 % (sub["op"], tag))
            out = imp(g, sub)
            outs = out if isinstance(out, (list, tuple)) else [out]
            for nm, sy in zip(sub["outputs"], outs):
                g.syms[nm] = sy
        return [g.syms[vi["name"]] for vi in graphd["outputs"]]
    finally:
        g.syms = saved_syms


@register_importer("If")
def _if(g, node):
    """ONNX If → symbol.cond (lax.cond). Subgraph nodes may reference
    outer-scope values by name (ONNX scoping) — they resolve through the
    shared _Graph symbol table."""
    a = node["attrs"]
    then_s = _import_subgraph(g, a["then_branch"], "If")[0]
    else_s = _import_subgraph(g, a["else_branch"], "If")[0]
    from ..symbol import cond
    return cond(g.inp(node["inputs"][0]), then_s, else_s)


@register_importer("Scan")
def _scan_imp(g, node):
    """ONNX Scan → symbol foreach node (lax.scan). Body formal inputs are
    [states..., scan slice]; outer-scope references resolve through the
    shared symbol table like If branches."""
    from ..symbol import Symbol, _foreach_node

    a = node["attrs"]
    body = a["body"]
    num_scan = int(a.get("num_scan_inputs", 1))
    if num_scan != 1:
        raise ValueError("Scan import: only one scan input supported")
    n_states = len(node["inputs"]) - num_scan
    binput_names = [vi["name"] for vi in body["inputs"]]
    state_names, slice_name = binput_names[:n_states], binput_names[-1]

    body_outs = _import_subgraph(
        g, body, "Scan",
        bound_inputs={nm: Symbol(None, name=nm) for nm in binput_names})
    state_syms, out_sym = body_outs[:n_states], body_outs[-1]

    data = g.inp(node["inputs"][-1])
    inits = [g.inp(n) for n in node["inputs"][:n_states]]
    fnode = _foreach_node(data, inits, out_sym, state_syms, slice_name,
                          state_names)
    # ONNX output order: final_states..., stacked scan output; ours is
    # [stacked, states...]
    return [fnode[i + 1] for i in range(n_states)] + [fnode[0]]


@register_importer("Loop")
def _loop_imp(g, node):
    """ONNX Loop → symbol while_loop (masked lax.scan, the TPU-static form).

    Body formals are [iteration_num, cond_in, carried...]; body outputs are
    [cond_out, carried..., scan_outputs...]. The trip count M must be a
    constant (XLA needs a static bound to stack per-step outputs). Static-
    shape deviation from the spec: scan outputs always have M rows — rows
    after the condition turns false are zero (the while_loop masking),
    where ONNX would return only the executed prefix.
    """
    from ..symbol import Symbol, while_loop

    a = node["attrs"]
    body = a["body"]
    ins = node["inputs"]
    m_name, cond_name = ins[0], ins[1]
    carried_names = list(ins[2:])
    n_carried = len(carried_names)
    if not m_name:
        raise ValueError(
            "Loop import: trip count M is required and must be constant "
            "(a static bound is what lets XLA compile the loop)")
    M = int(np.asarray(g.const_value(m_name)).reshape(()))

    binputs = [vi["name"] for vi in body["inputs"]]
    if len(binputs) != 2 + n_carried:
        raise ValueError("Loop body has %d inputs, expected %d"
                         % (len(binputs), 2 + n_carried))
    iter_name, cond_in_name = binputs[0], binputs[1]
    bstate_names = binputs[2:]
    n_scan = len(body["outputs"]) - 1 - n_carried
    if n_scan < 0:
        raise ValueError("Loop body must output [cond, carried..., scans...]")
    if n_scan > 1:
        raise ValueError("Loop import: at most one scan output supported "
                         "(while_loop stacks a single per-step Symbol)")

    def _bool_const(v):
        f = _make("_filled", shape=(), value=1.0 if v else 0.0)
        return _make("cast", f, dtype="bool")

    iter0 = _make("cast", _make("_filled", shape=(), value=0.0),
                  dtype="int64")
    init_cond = g.inp(cond_name) if cond_name else _bool_const(True)
    init_states = [g.inp(n) for n in carried_names]

    def cond_fn(vs):
        return vs[1]

    def func(vs):
        i, c = vs[0], vs[1]
        bound = {iter_name: i, cond_in_name: c}
        bound.update(dict(zip(bstate_names, vs[2:])))
        outs = _import_subgraph(g, body, "Loop", bound_inputs=bound)
        cond_out = outs[0]
        states_out = list(outs[1:1 + n_carried])
        scan_outs = list(outs[1 + n_carried:])
        out_sym = scan_outs[0] if scan_outs else cond_out  # dummy when K=0
        i_next = _make("cast", _make("add", _make("cast", i,
                                                  dtype="float32"), 1.0),
                       dtype="int64")
        return out_sym, [i_next, cond_out] + states_out

    outputs, final_vars = while_loop(cond_fn, func,
                                     [iter0, init_cond] + init_states,
                                     max_iterations=M)
    result = list(final_vars[2:])
    if n_scan:
        result.append(outputs)
    return result


@register_importer("NonMaxSuppression")
def _nms(g, node):
    ins = node["inputs"]
    kw = {"center_point_box": int(node["attrs"].get("center_point_box", 0))}
    if len(ins) > 2 and ins[2]:
        kw["max_output_boxes_per_class"] = int(
            np.asarray(g.const_value(ins[2])).reshape(()))
    if len(ins) > 3 and ins[3]:
        kw["iou_threshold"] = float(np.asarray(g.const_value(ins[3])).reshape(()))
    if len(ins) > 4 and ins[4]:
        kw["score_threshold"] = float(np.asarray(g.const_value(ins[4])).reshape(()))
    return _make("_onnx_nms", g.inp(ins[0]), g.inp(ins[1]), **kw)


@register_importer("GatherND")
def _gather_nd(g, node):
    if node["attrs"].get("batch_dims"):
        raise ValueError("GatherND import: batch_dims unsupported")
    return _make("_onnx_gather_nd", g.inp(node["inputs"][0]),
                 g.inp(node["inputs"][1]))


@register_importer("ScatterND")
def _scatter_nd(g, node):
    return _make("_onnx_scatter_nd", g.inp(node["inputs"][0]),
                 g.inp(node["inputs"][1]), g.inp(node["inputs"][2]))


def _import_resize(g, node, scales, sizes):
    a = node["attrs"]
    mode = a.get("mode", "nearest")
    x = g.inp(node["inputs"][0])
    if mode == "nearest":
        if scales is None:
            raise ValueError("nearest Resize import needs scales")
        s = [float(v) for v in scales]
        if s[0] != 1 or s[1] != 1 or s[2] != s[3] or s[2] != int(s[2]):
            raise ValueError("nearest Resize: only uniform integer spatial "
                             "scales supported, got %r" % (s,))
        return _make("UpSampling", x, scale=int(s[2]), sample_type="nearest")
    if mode != "linear":
        raise ValueError("Resize mode %r unsupported" % mode)
    ctm = a.get("coordinate_transformation_mode", "half_pixel")
    ops = {"align_corners": "BilinearResize2D",
           "asymmetric": "_resize_linear_asymmetric",
           "half_pixel": "_resize_linear_half_pixel",
           "pytorch_half_pixel": "_resize_linear_half_pixel"}
    if ctm not in ops:
        raise ValueError("linear Resize import: coordinate_transformation_"
                         "mode %r unsupported" % ctm)
    kw = ({"pytorch_mode": ctm == "pytorch_half_pixel"}
          if ops[ctm] == "_resize_linear_half_pixel" else {})
    if sizes is not None:
        return _make(ops[ctm], x, height=int(sizes[2]), width=int(sizes[3]),
                     **kw)
    return _make(ops[ctm], x, scale_height=float(scales[2]),
                 scale_width=float(scales[3]), **kw)


@register_importer("Resize")
def _resize(g, node):
    ins = node["inputs"]
    scales = sizes = None
    if len(ins) > 2 and ins[2]:
        v = np.asarray(g.const_value(ins[2]))
        scales = v if v.size else None
    if len(ins) > 3 and ins[3]:
        sizes = np.asarray(g.const_value(ins[3]))
    return _import_resize(g, node, scales, sizes)


@register_importer("Upsample")
def _upsample(g, node):
    # opset-9 deprecated form: scales as input 1 (or attr pre-9)
    scales = node["attrs"].get("scales")
    if scales is None:
        scales = np.asarray(g.const_value(node["inputs"][1]))
    return _import_resize(g, node, np.asarray(scales, np.float64), None)


# ------------------------------------------------------------ recurrent ops

_uid = [0]


def _fresh(hint):
    _uid[0] += 1
    return "%s_%d" % (hint, _uid[0])


def _rnn_import(mode):
    """ONNX LSTM/GRU/RNN (single layer, 1-2 directions) → fused mx RNN op.
    Gate blocks are re-ordered from ONNX to MXNet order (see export.py)."""
    from .export import _GRU_FROM_ONNX, _LSTM_FROM_ONNX, _gate_perm

    def imp(g, node):
        a = node["attrs"]
        ins = node["inputs"]
        W = np.asarray(g.const_value(ins[1]), np.float32)  # (D, G*H, C)
        R = np.asarray(g.const_value(ins[2]), np.float32)  # (D, G*H, H)
        D, GH, _ = W.shape
        H = int(a.get("hidden_size", R.shape[2]))
        direction = a.get("direction", "forward")
        if direction == "reverse":
            raise ValueError("RNN import: direction=reverse unsupported")
        bi = direction == "bidirectional"
        mx_mode = mode
        if mode == "rnn":
            acts = [s.lower() for s in a.get("activations", ["Tanh"] * D)]
            if acts[0] not in ("tanh", "relu"):
                raise ValueError("RNN import: activation %r" % acts[0])
            mx_mode = "rnn_" + acts[0]
        if mode == "gru" and not a.get("linear_before_reset", 0):
            raise ValueError(
                "GRU import: linear_before_reset=0 (reset before the "
                "recurrent matmul) has no fused-op equivalent here")
        inv = {"lstm": _LSTM_FROM_ONNX, "gru": _GRU_FROM_ONNX}.get(mode, [0])
        if len(ins) > 3 and ins[3]:
            B = np.asarray(g.const_value(ins[3]), np.float32)
        else:
            B = np.zeros((D, 2 * GH), np.float32)

        wsyms = []
        for d in range(D):
            for hint, arr in [("i2h_weight", _gate_perm(W[d], inv, H)),
                              ("h2h_weight", _gate_perm(R[d], inv, H)),
                              ("i2h_bias", _gate_perm(B[d][:GH], inv, H)),
                              ("h2h_bias", _gate_perm(B[d][GH:], inv, H))]:
                name = _fresh("%s_%s" % (node.get("name") or "rnn", hint))
                g.initializers[name] = arr
                wsyms.append(g.inp(name))

        x = g.inp(ins[0])
        if len(ins) > 5 and ins[5]:
            h0 = g.inp(ins[5])
        else:
            h0 = _make("_rnn_init", x, num=D, hidden=H)
        if mode == "lstm" and len(ins) > 6 and ins[6]:
            c0 = g.inp(ins[6])
        else:
            c0 = _make("_rnn_init", x, num=D, hidden=H)
        rnn = _make("RNN", x, h0, c0, *wsyms, mode=mx_mode, num_layers=1,
                    bidirectional=bi)
        # mx out (T, N, D*H) → ONNX Y (T, D, N, H)
        y = _make("transpose",
                  _make("reshape", rnn[0], shape=(0, 0, D, H)),
                  axes=(0, 2, 1, 3))
        outs = [y, rnn[1]]
        if mode == "lstm":
            outs.append(rnn[2])
        return outs[:len(node["outputs"])]
    return imp


register_importer("LSTM")(_rnn_import("lstm"))
register_importer("GRU")(_rnn_import("gru"))
register_importer("RNN")(_rnn_import("rnn"))


@register_importer("Constant")
def _constant(g, node):
    val = node["attrs"].get("value")
    s = var(node["outputs"][0])
    g.initializers[node["outputs"][0]] = np.asarray(val)
    g.used_params.add(node["outputs"][0])
    return s


# ----------------------------------------------------------------- front end

def import_model(model_file):
    """ONNX file/bytes → (sym, arg_params, aux_params)
    (ref: python/mxnet/onnx/onnx2mx/import_model.py:import_model).

    aux_params holds BatchNorm running stats (inputs 3/4 of
    BatchNormalization), matching MXNet's arg/aux split.
    """
    if isinstance(model_file, (bytes, bytearray)):
        buf = bytes(model_file)
    else:
        with open(model_file, "rb") as f:
            buf = f.read()
    parsed = P.parse_model(buf)
    graph = parsed["graph"]
    g = _Graph(graph, opset=parsed.get("opset", 13))

    for vi in graph["inputs"]:
        if vi["name"] not in g.initializers:
            g.syms[vi["name"]] = var(vi["name"])

    aux_names = set()
    for node in graph["nodes"]:
        if node["op"] == "BatchNormalization":
            aux_names.update(node["inputs"][3:5])

    for node in graph["nodes"]:
        imp = _IMPORTERS.get(node["op"])
        if imp is None:
            raise ValueError("no importer for ONNX op %r" % node["op"])
        out = imp(g, node)
        outs = out if isinstance(out, (list, tuple)) else [out]
        for name, s in zip(node["outputs"], outs):
            s.name = s.name if s.is_var() else name
            g.syms[name] = s

    out_syms = [g.syms[o["name"]] for o in graph["outputs"]]
    sym_out = out_syms[0] if len(out_syms) == 1 else __import__(
        "mxnet_tpu.symbol", fromlist=["Group"]).Group(out_syms)

    arg_params, aux_params = {}, {}
    for name in g.used_params:
        arr = g.initializers[name]
        (aux_params if name in aux_names else arg_params)[name] = arr
    return sym_out, arg_params, aux_params


def import_to_gluon(model_file, ctx=None):
    """ONNX file → executable SymbolBlock
    (ref: python/mxnet/onnx/onnx2mx/import_to_gluon.py)."""
    import jax.numpy as jnp

    from ..gluon.block import SymbolBlock
    from ..gluon.parameter import Parameter

    sym_out, arg_params, aux_params = import_model(model_file)
    param_names = set(arg_params) | set(aux_params)
    # list_arguments() is deterministic depth-first order — input binding in
    # SymbolBlock.forward is positional, so order must be stable
    input_names = [n for n in sym_out.list_arguments() if n not in param_names]
    inputs = [var(n) for n in input_names]
    blk = SymbolBlock(sym_out, inputs)
    for name, arr in {**arg_params, **aux_params}.items():
        p = Parameter(name, shape=arr.shape)
        p.set_data(jnp.asarray(arr))
        blk._params._params[name] = p
    return blk


def _scalar(v):
    """Python scalar from a 0-d or 1-element initializer (NumPy >=1.25
    errors on int(array) with ndim > 0)."""
    return np.asarray(v).reshape(-1)[0]


# --------------------------- breadth batch: official-producer common ops

def _reg_elemwise_imp(onnx_name, op):
    @register_importer(onnx_name)
    def f(g, node, _op=op):
        ins = [g.inp(n) for n in node["inputs"]]
        return _make(_op, *ins)


# NOTE: Max/Min already have variadic importers above (pairwise fold) —
# do not re-register them with binary ops
_reg_elemwise_imp("Where", "where")
_reg_elemwise_imp("Equal", "broadcast_equal")
_reg_elemwise_imp("Greater", "broadcast_greater")
_reg_elemwise_imp("Less", "broadcast_lesser")
_reg_elemwise_imp("Not", "logical_not")
_reg_elemwise_imp("And", "broadcast_logical_and")
_reg_elemwise_imp("Or", "broadcast_logical_or")
_reg_elemwise_imp("Xor", "broadcast_logical_xor")
_reg_elemwise_imp("GreaterOrEqual", "broadcast_greater_equal")
_reg_elemwise_imp("LessOrEqual", "broadcast_lesser_equal")
_reg_elemwise_imp("Sum", "add_n")


@register_importer("Mod")
def _mod_imp(g, node):
    x, y = g.inp(node["inputs"][0]), g.inp(node["inputs"][1])
    if int(node["attrs"].get("fmod", 0)):
        # fmod semantics: x - trunc(x/y)*y (sign of dividend)
        return _make("subtract", x,
                     _make("multiply", _make("trunc", _make("divide", x, y)),
                           y))
    return _make("mod", x, y)


def _reduce_lp(ordv):
    def imp(g, node):
        a = node["attrs"]
        axes = a.get("axes")
        if axes is None and len(node["inputs"]) > 1:
            # opset>=18 moved ReduceL1/L2 axes to a second input, like
            # ReduceSum at 13 — resolve through the same initializer path
            ax_init = g.initializers.get(node["inputs"][1])
            if ax_init is None:
                raise ValueError("%s: dynamic axes input unsupported"
                                 % node["op"])
            axes = [int(x) for x in np.asarray(ax_init).reshape(-1)]
        kw = {"ord": ordv, "keepdims": bool(a.get("keepdims", 1))}
        if axes is not None:
            kw["axis"] = (tuple(int(x) for x in axes) if len(axes) > 1
                          else int(axes[0]))
        return _make("norm", g.inp(node["inputs"][0]), **kw)
    return imp


register_importer("ReduceL1")(_reduce_lp(1))
register_importer("ReduceL2")(_reduce_lp(2))


def _axes_attr_or_input(g, node, input_idx=1):
    """axes from the attr (opset<13/18) or a constant second input (newer
    opsets moved reduce axes to an initializer input)."""
    axes = node["attrs"].get("axes")
    if axes is None and len(node["inputs"]) > input_idx \
            and node["inputs"][input_idx]:
        ax_init = g.initializers.get(node["inputs"][input_idx])
        if ax_init is None:
            raise ValueError("%s: dynamic axes input unsupported"
                             % node["op"])
        axes = [int(x) for x in np.asarray(ax_init).reshape(-1)]
    return axes


def _axes_kw(axes, keepdims):
    # empty axes list (opset>=18 empty initializer) means reduce-all, same
    # as an absent attr — callers handle noop_with_empty_axes separately
    kw = {"keepdims": bool(keepdims)}
    if axes:
        kw["axis"] = (tuple(int(x) for x in axes) if len(axes) > 1
                      else int(axes[0]))
    return kw


def _reduce_is_noop(node, axes):
    # opset>=18: noop_with_empty_axes=1 with axes EMPTY **or absent
    # altogether** means identity (the spec's "empty" covers both); only
    # with the flag unset does missing axes mean reduce-all
    return ((axes is None or len(axes) == 0)
            and bool(node["attrs"].get("noop_with_empty_axes", 0)))


@register_importer("ReduceLogSumExp")
def _reduce_lse_imp(g, node):
    axes = _axes_attr_or_input(g, node)
    if _reduce_is_noop(node, axes):
        return _make("identity", g.inp(node["inputs"][0]))
    kw = _axes_kw(axes, node["attrs"].get("keepdims", 1))
    return _make("logsumexp", g.inp(node["inputs"][0]), **kw)


@register_importer("ReduceLogSum")
def _reduce_logsum_imp(g, node):
    axes = _axes_attr_or_input(g, node)
    if _reduce_is_noop(node, axes):
        return _make("identity", g.inp(node["inputs"][0]))
    kw = _axes_kw(axes, node["attrs"].get("keepdims", 1))
    return _make("log", _make("sum", g.inp(node["inputs"][0]), **kw))


@register_importer("ReduceSumSquare")
def _reduce_sumsq_imp(g, node):
    axes = _axes_attr_or_input(g, node)
    if _reduce_is_noop(node, axes):
        return _make("identity", g.inp(node["inputs"][0]))
    kw = _axes_kw(axes, node["attrs"].get("keepdims", 1))
    return _make("sum", _make("square", g.inp(node["inputs"][0])), **kw)


@register_importer("GatherElements")
def _gather_elements_imp(g, node):
    return _make("take_along_axis", g.inp(node["inputs"][0]),
                 g.inp(node["inputs"][1]),
                 axis=int(node["attrs"].get("axis", 0)))


def _scatter_elements_imp(g, node):
    red = node["attrs"].get("reduction", "none")
    if red not in ("none", "add", "mul"):
        raise ValueError("ScatterElements reduction %r unsupported" % red)
    return _make("scatter_elements", g.inp(node["inputs"][0]),
                 g.inp(node["inputs"][1]), g.inp(node["inputs"][2]),
                 axis=int(node["attrs"].get("axis", 0)), reduction=red)


register_importer("ScatterElements")(_scatter_elements_imp)
register_importer("Scatter")(_scatter_elements_imp)  # deprecated alias


@register_importer("Einsum")
def _einsum_imp(g, node):
    return _make("einsum", *[g.inp(i) for i in node["inputs"]],
                 equation=node["attrs"]["equation"])


@register_importer("Trilu")
def _trilu_imp(g, node):
    k = 0
    if len(node["inputs"]) > 1 and node["inputs"][1]:
        k_init = g.initializers.get(node["inputs"][1])
        if k_init is None:
            raise ValueError("Trilu: dynamic k input unsupported")
        k = int(np.asarray(k_init).reshape(()))
    return _make("trilu", g.inp(node["inputs"][0]), k=k,
                 upper=bool(node["attrs"].get("upper", 1)))


@register_importer("Celu")
def _celu_imp(g, node):
    return _make("celu", g.inp(node["inputs"][0]),
                 alpha=float(node["attrs"].get("alpha", 1.0)))


@register_importer("HardSwish")
def _hardswish_imp(g, node):
    return _make("hardswish", g.inp(node["inputs"][0]))


@register_importer("ThresholdedRelu")
def _thresholded_relu_imp(g, node):
    return _make("thresholded_relu", g.inp(node["inputs"][0]),
                 alpha=float(node["attrs"].get("alpha", 1.0)))


@register_importer("Size")
def _size_imp(g, node):
    # ONNX Size is a RANK-0 scalar; size_array returns shape (1,)
    return _make("reshape", _make("size_array", g.inp(node["inputs"][0])),
                 shape=())


@register_importer("Multinomial")
def _multinomial_imp(g, node):
    """ONNX Multinomial input is unnormalized LOG-probabilities (the TF
    lineage); sample_multinomial wants a probability simplex — softmax
    bridges exactly."""
    a = node["attrs"]
    dtype = {6: "int32", 7: "int64"}.get(int(a.get("dtype", 6)), "int32")
    return _make("sample_multinomial",
                 _make("softmax", g.inp(node["inputs"][0]), axis=-1),
                 shape=(int(a.get("sample_size", 1)),), dtype=dtype)


@register_importer("LpNormalization")
def _lp_norm_imp(g, node):
    a = node["attrs"]
    if int(a.get("p", 2)) != 2 or int(a.get("axis", -1)) != 1:
        raise ValueError("LpNormalization import: only p=2, axis=1 "
                         "(channel mode) supported")
    return _make("L2Normalization", g.inp(node["inputs"][0]), mode="channel")


@register_importer("LRN")
def _lrn_imp(g, node):
    a = node["attrs"]
    return _make("LRN", g.inp(node["inputs"][0]),
                 nsize=int(a.get("size", 5)),
                 alpha=float(a.get("alpha", 1e-4)),
                 beta=float(a.get("beta", 0.75)),
                 knorm=float(a.get("bias", 1.0)))


@register_importer("Mean")
def _mean_imp(g, node):
    ins = [g.inp(n) for n in node["inputs"]]
    s = _make("add_n", *ins)
    return s / float(len(ins))


@register_importer("HardSigmoid")
def _hard_sigmoid_imp(g, node):
    a = node["attrs"]
    return _make("hard_sigmoid", g.inp(node["inputs"][0]),
                 alpha=float(a.get("alpha", 0.2)),
                 beta=float(a.get("beta", 0.5)))


@register_importer("Expand")
def _expand_imp(g, node):
    # ONNX Expand broadcasts BIDIRECTIONALLY (out = broadcast(x, shape),
    # where x dims may exceed a 1 in shape) — multiply by ones(shape), which
    # has exactly those semantics; broadcast_to would reject such shapes
    if node["inputs"][1] not in g.initializers:
        src = g.inp(node["inputs"][1])
        if src._op == "_onnx_shape":
            # Expand(x, Shape(y)): mul by ones_like(y) keeps ONNX Expand's
            # BIDIRECTIONAL broadcast (x dims may exceed a 1 in the target)
            return _make("broadcast_mul", g.inp(node["inputs"][0]),
                         _make("ones_like", src._inputs[0]))
    shape = tuple(int(v) for v in g.const_value(node["inputs"][1]))
    ones = var(node["outputs"][0] + "_expand_ones")
    g.initializers[ones.name] = np.ones(shape, np.float32)
    g.used_params.add(ones.name)
    return _make("broadcast_mul", g.inp(node["inputs"][0]), ones)


@register_importer("Tile")
def _tile_imp(g, node):
    reps = tuple(int(v) for v in g.const_value(node["inputs"][1]))
    return _make("tile", g.inp(node["inputs"][0]), reps=reps)


@register_importer("GridSample")
def _grid_sample_imp(g, node):
    a = node["attrs"]
    mode = a.get("mode", "bilinear")
    if mode not in ("bilinear", "linear"):
        raise ValueError("GridSample import: mode %r unsupported" % mode)
    if a.get("padding_mode", "zeros") != "zeros":
        raise ValueError("GridSample import: padding_mode %r unsupported"
                         % a.get("padding_mode"))
    if not int(a.get("align_corners", 0)):
        # BilinearSampler's corner mapping IS align_corners=1; the default
        # (half-pixel) mapping would shift every sample
        raise ValueError("GridSample import: align_corners=0 unsupported")
    grid = _make("transpose", g.inp(node["inputs"][1]), axes=(0, 3, 1, 2))
    return _make("BilinearSampler", g.inp(node["inputs"][0]), grid)


@register_importer("RoiAlign")
def _roi_align_imp(g, node):
    """sampling_ratio=0 (the spec's adaptive mode) is approximated with a
    fixed 2x2 sample grid per bin — the common producer setting; exact for
    ROIs up to 2x the pooled size."""
    a = node["attrs"]
    if a.get("mode", "avg") != "avg":
        raise ValueError("RoiAlign import: only mode='avg'")
    # the ABSENT-attr default flipped at opset 16: 'output_half_pixel'
    # before, 'half_pixel' (pixel-center offset) from 16 on
    default_ctm = "half_pixel" if g.opset >= 16 else "output_half_pixel"
    ctm = a.get("coordinate_transformation_mode", default_ctm)
    if ctm != "output_half_pixel":
        # the kernel's grid has no -0.5 pixel-center offset; importing a
        # 'half_pixel' model would shift every ROI feature by half a pixel
        raise ValueError("RoiAlign import: coordinate_transformation_mode="
                         "%r unsupported (only 'output_half_pixel')" % ctm)
    data = g.inp(node["inputs"][0])
    boxes = g.inp(node["inputs"][1])
    bidx = g.inp(node["inputs"][2])
    bcol = _make("reshape", _make("cast", bidx, dtype="float32"),
                 shape=(-1, 1))
    rois5 = _make("concat", bcol, boxes, dim=1)
    return _make("ROIAlign", data, rois5,
                 pooled_size=(int(a["output_height"]),
                              int(a["output_width"])),
                 spatial_scale=float(a.get("spatial_scale", 1.0)),
                 sample_ratio=int(a.get("sampling_ratio", 2)) or 2)


@register_importer("Range")
def _range_imp(g, node):
    start, limit, delta = (float(g.const_value(n)) for n in node["inputs"])
    vals = np.arange(start, limit, delta)
    s = var(node["outputs"][0])
    g.initializers[node["outputs"][0]] = vals
    g.used_params.add(node["outputs"][0])
    return s


def _reg_arg_imp(onnx_name, op):
    @register_importer(onnx_name)
    def imp(g, node, _op=op):
        a = node["attrs"]
        # registry argmax/argmin honor keepdims directly
        return _make(_op, g.inp(node["inputs"][0]),
                     axis=int(a.get("axis", 0)),
                     keepdims=bool(int(a.get("keepdims", 1))))


_reg_arg_imp("ArgMax", "argmax")
_reg_arg_imp("ArgMin", "argmin")


@register_importer("TopK")
def _topk_imp(g, node):
    k = int(_scalar(g.const_value(node["inputs"][1])))
    a = node["attrs"]
    out = _make("topk", g.inp(node["inputs"][0]), k=k,
                axis=int(a.get("axis", -1)), ret_typ="both",
                is_ascend=not int(a.get("largest", 1)))
    return [out[0], out[1]]


@register_importer("Split")
def _split_imp(g, node):
    a = node["attrs"]
    axis = int(a.get("axis", 0))
    n_out = len(node["outputs"])
    if len(node["inputs"]) > 1 or "split" in a:
        sizes = (tuple(int(v) for v in g.const_value(node["inputs"][1]))
                 if len(node["inputs"]) > 1
                 else tuple(int(v) for v in a["split"]))
        if len(set(sizes)) != 1:
            raise ValueError("Split import: unequal split sizes %r not "
                             "supported" % (sizes,))
    out = _make("split", g.inp(node["inputs"][0]), num_outputs=n_out,
                axis=axis)
    return [out[i] for i in range(n_out)]


@register_importer("Pad")
def _pad_imp(g, node):
    a = node["attrs"]
    mode = a.get("mode", b"constant")
    mode = mode.decode() if isinstance(mode, bytes) else mode
    if mode not in ("constant", "edge", "reflect"):
        # the registry pad op would silently fall through to reflect
        raise ValueError("Pad import: mode %r not supported" % (mode,))
    pads = (tuple(int(v) for v in g.const_value(node["inputs"][1]))
            if len(node["inputs"]) > 1
            else tuple(int(v) for v in a.get("pads", ())))
    n = len(pads) // 2
    # ONNX: [x1_begin.. xn_begin, x1_end.. xn_end] → MXNet flat interleave
    # (b0, e0, b1, e1, ...) — the registry pad op's layout
    pad_width = tuple(v for i in range(n) for v in (pads[i], pads[n + i]))
    cval = (float(_scalar(g.const_value(node["inputs"][2])))
            if len(node["inputs"]) > 2 else 0.0)
    return _make("pad", g.inp(node["inputs"][0]), mode=mode,
                 pad_width=pad_width, constant_value=cval)


@register_importer("InstanceNormalization")
def _instancenorm_imp(g, node):
    eps = float(node["attrs"].get("epsilon", 1e-5))
    return _make("InstanceNorm", g.inp(node["inputs"][0]),
                 g.inp(node["inputs"][1]), g.inp(node["inputs"][2]),
                 eps=eps)


@register_importer("SpaceToDepth")
def _space_to_depth_imp(g, node):
    bs = int(node["attrs"]["blocksize"])
    return _make("space_to_depth", g.inp(node["inputs"][0]), block_size=bs)


@register_importer("DepthToSpace")
def _depth_to_space_imp(g, node):
    bs = int(node["attrs"]["blocksize"])
    mode = node["attrs"].get("mode", b"DCR")
    mode = mode.decode() if isinstance(mode, bytes) else mode
    if mode != "DCR":
        raise ValueError("DepthToSpace import: only DCR mode supported")
    return _make("depth_to_space", g.inp(node["inputs"][0]), block_size=bs)


@register_importer("OneHot")
def _one_hot_imp(g, node):
    axis = int(node["attrs"].get("axis", -1))
    if axis != -1:
        # registry one_hot always places the hot dim LAST; silently wrong
        # shapes are worse than failing
        raise ValueError("OneHot import: axis=%d not supported (only -1)"
                         % axis)
    depth = int(_scalar(g.const_value(node["inputs"][1])))
    vals = g.const_value(node["inputs"][2])
    off, on = float(vals[0]), float(vals[1])
    return _make("one_hot", g.inp(node["inputs"][0]), depth=depth,
                 on_value=on, off_value=off)


@register_importer("CumSum")
def _cumsum_imp(g, node):
    axis = int(_scalar(g.const_value(node["inputs"][1])))
    a = node["attrs"]
    if int(a.get("exclusive", 0)) or int(a.get("reverse", 0)):
        raise ValueError("CumSum import: exclusive/reverse not supported")
    return _make("cumsum", g.inp(node["inputs"][0]), axis=axis)
