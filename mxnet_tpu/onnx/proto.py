"""Minimal protobuf wire-format codec for ONNX, dependency-free.

The harness image has no ``onnx`` package, so we speak the protobuf wire
format directly (it is tiny: varints + length-delimited blobs). Only the
subset of onnx.proto3 that models need is implemented — ModelProto,
GraphProto, NodeProto, AttributeProto, TensorProto, ValueInfoProto
(ref: python/mxnet/onnx/mx2onnx — the reference leans on the onnx pip
package for the same job).
"""
from __future__ import annotations

import struct

import numpy as np

# ----------------------------------------------------------------- wire level

_WIRE_VARINT = 0
_WIRE_64BIT = 1
_WIRE_LEN = 2
_WIRE_32BIT = 5


def _varint(n: int) -> bytes:
    if n < 0:
        n += 1 << 64  # two's-complement, 10-byte form
    out = bytearray()
    while True:
        b = n & 0x7F
        n >>= 7
        if n:
            out.append(b | 0x80)
        else:
            out.append(b)
            return bytes(out)


class Msg:
    """Append-only protobuf message builder."""

    def __init__(self):
        self._buf = bytearray()

    def _tag(self, field, wire):
        self._buf += _varint((field << 3) | wire)

    def varint(self, field, value):
        self._tag(field, _WIRE_VARINT)
        self._buf += _varint(int(value))
        return self

    def bytes_(self, field, data):
        if isinstance(data, str):
            data = data.encode("utf-8")
        elif isinstance(data, Msg):
            data = data.tobytes()
        self._tag(field, _WIRE_LEN)
        self._buf += _varint(len(data))
        self._buf += data
        return self

    def float_(self, field, value):
        self._tag(field, _WIRE_32BIT)
        self._buf += struct.pack("<f", float(value))
        return self

    def packed_varints(self, field, values):
        payload = b"".join(_varint(int(v)) for v in values)
        return self.bytes_(field, payload)

    def packed_floats(self, field, values):
        return self.bytes_(field, struct.pack("<%df" % len(values), *map(float, values)))

    def tobytes(self) -> bytes:
        return bytes(self._buf)


def read_varint(buf, pos):
    result = 0
    shift = 0
    while True:
        b = buf[pos]
        pos += 1
        result |= (b & 0x7F) << shift
        if not b & 0x80:
            return result, pos
        shift += 7


def parse(buf):
    """Decode one message into {field: [raw values]} — varints as int,
    length-delimited as bytes, fixed32/64 as raw bytes."""
    fields = {}
    pos = 0
    n = len(buf)
    while pos < n:
        key, pos = read_varint(buf, pos)
        field, wire = key >> 3, key & 7
        if wire == _WIRE_VARINT:
            val, pos = read_varint(buf, pos)
        elif wire == _WIRE_LEN:
            ln, pos = read_varint(buf, pos)
            val = bytes(buf[pos:pos + ln])
            pos += ln
        elif wire == _WIRE_32BIT:
            val = bytes(buf[pos:pos + 4])
            pos += 4
        elif wire == _WIRE_64BIT:
            val = bytes(buf[pos:pos + 8])
            pos += 8
        else:
            raise ValueError("unsupported wire type %d" % wire)
        fields.setdefault(field, []).append(val)
    return fields


def signed(v):
    """Interpret a decoded varint as a signed int64."""
    return v - (1 << 64) if v >= (1 << 63) else v


def unpack_varints(payload):
    out = []
    pos = 0
    while pos < len(payload):
        v, pos = read_varint(payload, pos)
        out.append(signed(v))
    return out


def unpack_floats(payload):
    return list(struct.unpack("<%df" % (len(payload) // 4), payload))


def repeated_ints(raw_values):
    """Decode a repeated int field that may arrive unpacked (ints) or packed
    (proto3 default: one length-delimited blob of varints)."""
    out = []
    for raw in raw_values:
        if isinstance(raw, (bytes, bytearray)):
            out.extend(unpack_varints(raw))
        else:
            out.append(signed(raw))
    return out


def repeated_floats(raw_values):
    """Decode a repeated float field: unpacked entries are 4-byte fixed32
    chunks, packed entries are one blob of n*4 bytes."""
    out = []
    for raw in raw_values:
        out.extend(unpack_floats(raw))
    return out


# ---------------------------------------------------------------- ONNX types

# onnx.TensorProto.DataType
FLOAT, UINT8, INT8, UINT16, INT16, INT32, INT64 = 1, 2, 3, 4, 5, 6, 7
STRING, BOOL, FLOAT16, DOUBLE, UINT32, UINT64 = 8, 9, 10, 11, 12, 13
BFLOAT16 = 16

_NP2ONNX = {
    np.dtype(np.float32): FLOAT, np.dtype(np.float64): DOUBLE,
    np.dtype(np.float16): FLOAT16, np.dtype(np.int32): INT32,
    np.dtype(np.int64): INT64, np.dtype(np.int8): INT8,
    np.dtype(np.uint8): UINT8, np.dtype(np.bool_): BOOL,
}
_ONNX2NP = {v: k for k, v in _NP2ONNX.items()}


def np_to_onnx_dtype(dt):
    dt = np.dtype(dt)
    if dt.name == "bfloat16":
        return BFLOAT16
    return _NP2ONNX[dt]


def onnx_to_np_dtype(code):
    if code == BFLOAT16:
        import jax.numpy as jnp
        return np.dtype(jnp.bfloat16)
    return _ONNX2NP[code]


# AttributeProto.AttributeType
ATTR_FLOAT, ATTR_INT, ATTR_STRING, ATTR_TENSOR, ATTR_GRAPH = 1, 2, 3, 4, 5
ATTR_FLOATS, ATTR_INTS, ATTR_STRINGS = 6, 7, 8


class GraphAttr:
    """Marker for a subgraph-valued attribute (AttributeProto.g, e.g. the
    then_branch/else_branch of an If node). Holds encoded GraphProto bytes."""

    def __init__(self, graph_msg):
        self.data = graph_msg.tobytes() if hasattr(graph_msg, "tobytes") \
            else bytes(graph_msg)


def tensor_proto(name, arr):
    """TensorProto with raw_data (field 9)."""
    arr = np.ascontiguousarray(arr)
    m = Msg()
    for d in arr.shape:
        m.varint(1, d)                       # dims
    m.varint(2, np_to_onnx_dtype(arr.dtype))  # data_type
    m.bytes_(8, name)                        # name
    if arr.dtype.name == "bfloat16":
        raw = arr.view(np.uint16).astype("<u2").tobytes()
    else:
        raw = arr.astype(arr.dtype.newbyteorder("<")).tobytes()
    m.bytes_(9, raw)                         # raw_data
    return m


def parse_tensor(buf):
    """TensorProto bytes → (name, np.ndarray)."""
    f = parse(buf)
    dims = repeated_ints(f.get(1, []))
    code = f[2][0]
    name = f.get(8, [b""])[0].decode()
    if 9 in f:  # raw_data
        raw = f[9][0]
        if code == BFLOAT16:
            import jax.numpy as jnp
            arr = np.frombuffer(raw, "<u2").view(np.dtype(jnp.bfloat16))
        else:
            arr = np.frombuffer(raw, np.dtype(onnx_to_np_dtype(code)).newbyteorder("<"))
        arr = arr.reshape(dims)
    elif 4 in f:  # float_data (packed)
        arr = np.asarray(unpack_floats(f[4][0]), np.float32).reshape(dims)
    elif 7 in f:  # int64_data (packed)
        arr = np.asarray(unpack_varints(f[7][0]), np.int64).reshape(dims)
    elif 5 in f:  # int32_data (packed)
        arr = np.asarray(unpack_varints(f[5][0]),
                         onnx_to_np_dtype(code)).reshape(dims)
    else:
        arr = np.zeros(dims, onnx_to_np_dtype(code))
    return name, arr


def attr_proto(name, value):
    """AttributeProto from a python value (int/float/str/list/np.ndarray)."""
    m = Msg()
    m.bytes_(1, name)
    if isinstance(value, bool):
        m.varint(3, int(value)).varint(20, ATTR_INT)
    elif isinstance(value, int):
        m.varint(3, value).varint(20, ATTR_INT)
    elif isinstance(value, float):
        m.float_(2, value).varint(20, ATTR_FLOAT)
    elif isinstance(value, str):
        m.bytes_(4, value).varint(20, ATTR_STRING)
    elif isinstance(value, GraphAttr):
        m.bytes_(6, value.data).varint(20, ATTR_GRAPH)
    elif isinstance(value, np.ndarray):
        m.bytes_(5, tensor_proto("", value)).varint(20, ATTR_TENSOR)
    elif isinstance(value, (list, tuple)):
        if value and isinstance(value[0], float):
            for v in value:
                m.float_(7, v)
            m.varint(20, ATTR_FLOATS)
        elif value and isinstance(value[0], str):
            for v in value:
                m.bytes_(9, v)
            m.varint(20, ATTR_STRINGS)
        else:
            for v in value:
                m.varint(8, int(v))
            m.varint(20, ATTR_INTS)
    else:
        raise TypeError("unsupported attribute %r=%r" % (name, value))
    return m


def parse_attr(buf):
    """AttributeProto bytes → (name, python value)."""
    f = parse(buf)
    name = f[1][0].decode()
    atype = f.get(20, [0])[0]
    if atype == ATTR_INT:
        return name, signed(f[3][0])
    if atype == ATTR_FLOAT:
        return name, struct.unpack("<f", f[2][0])[0]
    if atype == ATTR_STRING:
        return name, f[4][0].decode()
    if atype == ATTR_TENSOR:
        return name, parse_tensor(f[5][0])[1]
    if atype == ATTR_GRAPH:
        return name, parse_graph(f[6][0])
    if atype == ATTR_INTS:
        return name, repeated_ints(f.get(8, []))
    if atype == ATTR_FLOATS:
        return name, repeated_floats(f.get(7, []))
    if atype == ATTR_STRINGS:
        return name, [raw.decode() for raw in f.get(9, [])]
    raise ValueError("unsupported attribute type %d for %s" % (atype, name))


def node_proto(op_type, inputs, outputs, name="", attrs=None):
    m = Msg()
    for i in inputs:
        m.bytes_(1, i)
    for o in outputs:
        m.bytes_(2, o)
    if name:
        m.bytes_(3, name)
    m.bytes_(4, op_type)
    for k, v in (attrs or {}).items():
        m.bytes_(5, attr_proto(k, v))
    return m


def parse_node(buf):
    f = parse(buf)
    inputs = [b.decode() for b in f.get(1, [])]
    outputs = [b.decode() for b in f.get(2, [])]
    name = f.get(3, [b""])[0].decode()
    op_type = f[4][0].decode()
    attrs = dict(parse_attr(b) for b in f.get(5, []))
    return {"op": op_type, "inputs": inputs, "outputs": outputs,
            "name": name, "attrs": attrs}


def value_info(name, dtype, shape):
    """ValueInfoProto: name + tensor type (elem_type, shape)."""
    shp = Msg()
    for d in shape:
        dim = Msg()
        if isinstance(d, str) or d is None or d < 0:
            dim.bytes_(2, str(d) if d is not None else "?")
        else:
            dim.varint(1, d)
        shp.bytes_(1, dim)
    tt = Msg()
    tt.varint(1, np_to_onnx_dtype(dtype))
    tt.bytes_(2, shp)
    tp = Msg()
    tp.bytes_(1, tt)
    m = Msg()
    m.bytes_(1, name)
    m.bytes_(2, tp)
    return m


def parse_value_info(buf):
    f = parse(buf)
    name = f[1][0].decode()
    dtype = None
    shape = None
    if 2 in f:
        tp = parse(f[2][0])
        if 1 in tp:
            tt = parse(tp[1][0])
            dtype = tt.get(1, [None])[0]
            if 2 in tt:
                shape = []
                for dim_buf in parse(tt[2][0]).get(1, []):
                    dim = parse(dim_buf)
                    if 1 in dim:
                        shape.append(signed(dim[1][0]))
                    else:
                        shape.append(dim.get(2, [b"?"])[0].decode())
    return {"name": name, "dtype": dtype, "shape": shape}


def graph_proto(name, nodes, inputs, outputs, initializers, value_infos=()):
    m = Msg()
    for nd_ in nodes:
        m.bytes_(1, nd_)
    m.bytes_(2, name)
    for t in initializers:
        m.bytes_(5, t)
    for vi in inputs:
        m.bytes_(11, vi)
    for vo in outputs:
        m.bytes_(12, vo)
    for vi in value_infos:
        m.bytes_(13, vi)
    return m


def parse_graph(buf):
    f = parse(buf)
    return {
        "name": f.get(2, [b""])[0].decode(),
        "nodes": [parse_node(b) for b in f.get(1, [])],
        "initializers": dict(parse_tensor(b) for b in f.get(5, [])),
        "inputs": [parse_value_info(b) for b in f.get(11, [])],
        "outputs": [parse_value_info(b) for b in f.get(12, [])],
    }


def model_proto(graph, opset=13, producer="mxnet_tpu", ir_version=8):
    ops = Msg()
    ops.bytes_(1, "")        # domain: default
    ops.varint(2, opset)     # version
    m = Msg()
    m.varint(1, ir_version)
    m.bytes_(2, producer)
    m.bytes_(7, graph)
    m.bytes_(8, ops)
    return m


def parse_model(buf):
    f = parse(buf)
    opset = 13
    for b in f.get(8, []):
        op = parse(b)
        if op.get(1, [b""])[0] in (b"", b"ai.onnx"):
            opset = op.get(2, [13])[0]
    return {
        "ir_version": f.get(1, [0])[0],
        "producer": f.get(2, [b""])[0].decode(),
        "opset": opset,
        "graph": parse_graph(f[7][0]),
    }


def check_model(buf):
    """Structural validation of an encoded ModelProto (the spec rules the
    official onnx.checker enforces that don't need the full type system —
    that package is unavailable here): every node input is a graph input,
    an initializer, or an earlier node's output (SSA + topological order);
    node ops are named; output names exist; subgraphs check recursively
    against the outer scope (ONNX scoping). Raises ValueError.
    """
    model = parse_model(buf)
    if not model["ir_version"]:
        raise ValueError("checker: missing ir_version")
    _check_graph(model["graph"], set(), "main")
    return model


def _check_graph(g, outer_names, tag):
    known = set(outer_names)
    known.update(vi["name"] for vi in g["inputs"])
    known.update(g["initializers"])
    known.add("")  # optional (empty) inputs are legal
    for i, node in enumerate(g["nodes"]):
        if not node["op"]:
            raise ValueError("checker: %s node %d has no op_type" % (tag, i))
        for inp in node["inputs"]:
            if inp not in known:
                raise ValueError(
                    "checker: %s node %d (%s) input %r is not a graph "
                    "input, initializer, or earlier output (SSA order)"
                    % (tag, i, node["op"], inp))
        for attr, v in node["attrs"].items():
            if isinstance(v, dict) and "nodes" in v:  # subgraph
                _check_graph(v, known, "%s/%s.%s" % (tag, node["op"], attr))
        for out in node["outputs"]:
            if out in known and out:
                raise ValueError("checker: %s node %d (%s) output %r "
                                 "redefines an existing name (SSA)"
                                 % (tag, i, node["op"], out))
            known.add(out)
    for vo in g["outputs"]:
        if vo["name"] not in known:
            raise ValueError("checker: %s graph output %r is never produced"
                             % (tag, vo["name"]))
