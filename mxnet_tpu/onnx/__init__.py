"""ONNX interop (ref: python/mxnet/onnx). Dependency-free: the protobuf wire
format is implemented in proto.py, so export/import work without the ``onnx``
pip package. ``export_model`` traces a HybridBlock (or takes a Symbol) to an
ONNX ModelProto; ``import_model`` returns (sym, arg_params, aux_params);
``import_to_gluon`` returns an executable SymbolBlock."""
from .export import export_model, symbol_to_onnx, register_converter
from .import_model import import_model, import_to_gluon, register_importer

__all__ = ["export_model", "symbol_to_onnx", "import_model",
           "import_to_gluon", "register_converter", "register_importer"]
