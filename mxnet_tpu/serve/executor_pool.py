"""Bucketed compiled-executor pool — the serving analogue of CachedOp.

MXNet's model server runs ``Module.predict`` over a bound executor; every
new batch size rebinds (re-plans memory, re-launches kernel chains). The
TPU-native version pre-compiles the model's pure inference function at a
fixed set of batch-size *buckets* (the TVM-style "ahead-of-time compiled
shapes" discipline, arXiv 1802.04799) and pads each request batch up to the
smallest fitting bucket — the μ-cuDNN micro-batch decomposition idea
(arXiv 1804.04806) applied to request coalescing. Steady-state inference is
then ONE cached XLA dispatch per batch with zero retrace:

* ``engine.serve_compile_counter`` bumps inside the traced body, so it
  fires exactly when XLA re-traces — warmup compiles every bucket up
  front, and a steady request stream must not bump it again (the same
  proof-hook discipline as ``bulk_compile_counter``/``tape_compile_counter``);
* padded input buffers are donated to XLA on TPU backends (they are
  per-request scratch, so the output can reuse their HBM — "donated output
  reuse"); params are never donated (they serve the next request);
* multi-replica: parameters are placed once per device and batches are
  round-robined over replicas by the caller (server.py) — whole-batch
  replication, the inference-side complement of ``split_and_load``.

``symbol_infer_fn`` adapts a Symbol graph (Module / SymbolBlock) into the
pool's ``fn(params, *inputs)`` shape; hybridized gluon blocks hand off via
``HybridBlock.serving_fn()``.
"""
from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp

from .. import engine
from ..base import is_tpu_backend, next_pow2  # noqa: F401  (re-export)


class PoolError(RuntimeError):
    """Misuse of the executor pool (shape/bucket mismatch)."""


class BucketedExecutor:
    """Compiled inference executors over a fixed bucket set.

    Parameters
    ----------
    fn : callable
        Pure ``fn(param_arrays, *inputs) -> output or list`` (eval mode).
    params_fn : callable
        Zero-arg callable returning the CURRENT list of parameter arrays —
        read per dispatch so a reloaded checkpoint serves without a pool
        rebuild (same shapes/dtypes = same compiled programs, no retrace).
    buckets : tuple of int or None
        Allowed padded batch sizes. None = power-of-two auto-bucketing:
        any request stream compiles at most log2(max_batch) programs
        instead of one per distinct size.
    devices : list or None
        Replica devices. None = current placement, single replica.
    donate : bool or None
        Donate the (padded, per-request) input buffers to XLA. Default: on
        for TPU backends, off elsewhere (CPU donation is a no-op + warning).
    """

    def __init__(self, fn, params_fn, buckets=None, devices=None,
                 donate=None, name="pool", batch_axis=0, pad=True):
        if batch_axis != 0:
            raise PoolError("bucketing is defined on batch axis 0")
        self.name = name
        self.buckets = tuple(sorted(set(int(b) for b in buckets))) \
            if buckets else None
        # pad=False: exact-signature mode — every batch size is its own
        # "bucket" (no zero-row padding). For callers that cannot declare
        # which inputs carry a batch axis (SymbolBlock's general graphs):
        # still one cached program per signature instead of a per-call
        # evaluation walk, but padding semantics are never assumed.
        self._pad = bool(pad)
        self._params_fn = params_fn
        self._devices = list(devices) if devices else [None]
        self._placed = {}   # replica idx -> (param-identity token, arrays)
        self._rr = 0
        self._in_dtypes = None   # captured at first dispatch / warmup
        self._row_outputs = None  # per-output: leading dim == bucket?

        def traced(params, *xs):
            # executes at TRACE time only: one bump per program build is the
            # zero-retrace proof tests/test_serve.py asserts. A snapshot
            # warm start (serve.load(snapshot=True)) never traces at all —
            # deserialized executables are adopted directly — so this
            # counter reads 0 from process start to first request there.
            engine.serve_compile_counter.bump()
            out = fn(params, *xs)
            return list(out) if isinstance(out, (list, tuple)) else [out]

        if donate is None:
            donate = is_tpu_backend()
        self._donate = bool(donate)
        # per-signature AOT dispatch (cache.AotFn): explicit lower/compile
        # per bucket so every bucket program has an exportable executable
        # handle (Tier B snapshots) and a persistent disk tier under it
        # (Tier A) — jax.jit's internal cache can do neither. One wrapper
        # per (replica, donating): a Compiled is specialized to its
        # arguments' device placement, so replicas cannot share one.
        self._aots = {}
        self._fn = traced

    # ------------------------------------------------------------ buckets
    def pick_bucket(self, n):
        """Smallest configured bucket that fits ``n`` rows (power-of-two
        round-up in auto mode). Larger-than-max requests are the batcher's
        job to split; a direct caller gets a typed error."""
        if n <= 0:
            raise PoolError("empty batch")
        if not self._pad:
            return n
        if self.buckets is None:
            return next_pow2(n)
        for b in self.buckets:
            if n <= b:
                return b
        raise PoolError("batch of %d rows exceeds the largest bucket %d"
                        % (n, self.buckets[-1]))

    @property
    def max_bucket(self):
        return self.buckets[-1] if self.buckets else None

    @property
    def num_replicas(self):
        return len(self._devices)

    # ------------------------------------------------------------ params
    def _replica_params(self, r):
        cur = self._params_fn()
        token = tuple(map(id, cur))
        dev = self._devices[r]
        hit = self._placed.get(r)
        if hit is not None and hit[0] == token:
            return hit[1]
        arrs = list(cur) if dev is None else jax.device_put(list(cur), dev)
        self._placed[r] = (token, arrs)
        return arrs

    def next_replica(self):
        r = self._rr % len(self._devices)
        self._rr += 1
        return r

    # ------------------------------------------------------------ dispatch
    def _prepare(self, inputs, bucket):
        """Host-side pad-to-bucket: numpy concat+zeros (no device ops), one
        transfer per input. Dtypes are pinned to the first-seen signature so
        a stray float64 request can never force a retrace."""
        if self._in_dtypes is None:
            self._in_dtypes = [np.asarray(x).dtype for x in inputs]
        prepped = []
        for x, dt in zip(inputs, self._in_dtypes):
            x = np.asarray(x, dtype=dt)
            n = x.shape[0]
            if n != bucket:
                pad = np.zeros((bucket - n,) + x.shape[1:], dtype=dt)
                x = np.concatenate([x, pad], axis=0)
            prepped.append(x)
        return prepped

    def _dispatch(self, inputs, replica, donate_ok=True):
        """One cached-program call. ``donate_ok`` is False when the inputs
        are caller-owned buffers (run_device without padding) — donating
        those would invalidate arrays the caller still holds."""
        dev = self._devices[replica]
        params = self._replica_params(replica)
        xs = [jnp.asarray(x) if dev is None else jax.device_put(x, dev)
              for x in inputs]
        engine.dispatch_counter.bump()
        return self._exec_for(replica, len(xs), donate_ok)(params, *xs)

    def _exec_for(self, replica, n_inputs, donate_ok):
        """The AOT wrapper a dispatch routes through: one per (replica,
        donating) — the donating variant on TPU (padded inputs are
        per-request scratch), the plain one elsewhere / for caller-owned
        buffers."""
        donating = bool(self._donate and donate_ok)
        aot = self._aots.get((replica, donating))
        if aot is None:
            from ..cache import AotFn

            aot = self._aots[(replica, donating)] = AotFn(
                self._fn,
                donate_argnums=(tuple(range(1, 1 + n_inputs))
                                if donating else ()),
                tier="serve",
                hint="%s:r%d%s" % (self.name, replica,
                                   ":donated" if donating else ""))
        return aot

    def run(self, inputs, n_real=None, replica=None, traces=None):
        """Execute a coalesced batch: pad to bucket, one cached dispatch,
        host-gather, slice off the pad rows. ``inputs`` share leading batch
        dim; returns a list of numpy outputs with ``n_real`` rows each
        (row-aligned outputs only — others returned whole).

        ``traces``: the coalesced requests' RequestTraces — each gets the
        shared ``pad`` (host pad-to-bucket) and ``dispatch`` (compiled
        call + host gather) spans closed, three clock reads per BATCH."""
        import time as _time

        n = int(np.asarray(inputs[0]).shape[0])
        n_real = n if n_real is None else int(n_real)
        bucket = self.pick_bucket(n)
        if replica is None:
            replica = self.next_replica()
        from .. import profiler
        t_pad0 = _time.perf_counter() if traces else None
        prepped = self._prepare(inputs, bucket)
        t_disp0 = _time.perf_counter() if traces else None
        if profiler.is_running():
            with profiler.serve_scope(bucket, n_real):
                outs = self._dispatch(prepped, replica)
        else:
            outs = self._dispatch(prepped, replica)
        # host gather = the only completion signal the relay honors; also
        # what the caller (a serving response) needs anyway
        outs = [np.asarray(o) for o in outs]
        if traces:
            t_done = _time.perf_counter()
            for tr in traces:
                tr.add_span("pad", t_pad0, t_disp0, bucket=bucket)
                tr.add_span("dispatch", t_disp0, t_done, bucket=bucket,
                            rows=n_real, replica=replica)
        if self._row_outputs is None:
            self._row_outputs = [o.ndim >= 1 and o.shape[0] == bucket
                                 for o in outs]
        return [o[:n_real] if row else o
                for o, row in zip(outs, self._row_outputs)]

    def run_device(self, inputs, n_real=None, replica=None):
        """Device-resident variant for framework callers (SymbolBlock
        inference, Module.predict): inputs/outputs stay jax arrays — pad
        and slice are tiny XLA ops bracketing the same cached bucket
        program, no host round-trip. Never donates (unpadded inputs are
        caller-owned buffers)."""
        n = int(inputs[0].shape[0]) if getattr(inputs[0], "ndim", 0) >= 1 \
            else 1
        n_real = n if n_real is None else int(n_real)
        bucket = self.pick_bucket(n)
        if replica is None:
            replica = self.next_replica()
        if self._in_dtypes is None:
            self._in_dtypes = [np.dtype(x.dtype) for x in inputs]
        prepped = []
        for x, dt in zip(inputs, self._in_dtypes):
            if x.dtype != dt:
                x = x.astype(dt)
            if n != bucket:
                pad = jnp.zeros((bucket - n,) + tuple(x.shape[1:]), dt)
                x = jnp.concatenate([x, pad], axis=0)
            prepped.append(x)
        from .. import profiler
        if profiler.is_running():
            with profiler.serve_scope(bucket, n_real):
                outs = self._dispatch(prepped, replica, donate_ok=False)
        else:
            outs = self._dispatch(prepped, replica, donate_ok=False)
        if self._row_outputs is None:
            self._row_outputs = [getattr(o, "ndim", 0) >= 1
                                 and o.shape[0] == bucket for o in outs]
        return [o[:n_real] if row and bucket != n_real else o
                for o, row in zip(outs, self._row_outputs)]

    @property
    def row_aligned(self):
        """True when every output carries the batch on axis 0 (known after
        the first dispatch/warmup) — the precondition for slicing padded
        rows off per request."""
        return self._row_outputs is not None and all(self._row_outputs)

    def warmup(self, input_specs, buckets=None):
        """Compile every (bucket, replica) program up front with zero-filled
        inputs. ``input_specs``: per input, (sample_shape, dtype) — shapes
        WITHOUT the batch dim. After warmup, serving is dispatch-only:
        ``engine.serve_compile_counter`` stays flat."""
        bs = buckets or self.buckets
        if bs is None:
            raise PoolError("warmup needs an explicit bucket list in "
                            "auto-bucket mode")
        self._in_dtypes = [np.dtype(dt) for _, dt in input_specs]
        for b in bs:
            zeros = [np.zeros((b,) + tuple(shape), dtype=dt)
                     for shape, dt in input_specs]
            for r in range(len(self._devices)):
                self.run(zeros, n_real=b, replica=r)
        return self

    # ------------------------------------------------ snapshot interface
    def _bucket_sig(self, aot, bucket, input_specs):
        """Call signature of a bucket dispatch, computed from shape specs
        (no arrays, no trace): (params, *padded_inputs)."""
        params = [jax.ShapeDtypeStruct(tuple(p.shape), p.dtype)
                  for p in self._params_fn()]
        xs = [jax.ShapeDtypeStruct((int(bucket),) + tuple(shape),
                                   np.dtype(dt))
              for shape, dt in input_specs]
        return aot.sig_of(params, *xs)

    def export_executables(self, input_specs, buckets):
        """Every warmed bucket's compiled executable, tagged for the
        snapshot manifest: [{key, bucket, donating, compiled}]. Replica 0
        only — a snapshot-warmed replica is a fresh single-device process
        (the horizontal-autoscale unit); extra replicas compile lazily."""
        out = []
        for donating in (False, True):
            aot = self._aots.get((0, donating))
            if aot is None:
                continue
            for b in buckets:
                c = aot.compiled_for(self._bucket_sig(aot, b, input_specs))
                if c is not None:
                    out.append({"key": "b%d_d%d" % (b, int(donating)),
                                "bucket": int(b),
                                "donating": bool(donating),
                                "compiled": c})
        return out

    def preload_executables(self, entries, input_specs):
        """Adopt deserialized bucket executables (snapshot warm start): no
        trace, no compile. Entries that don't match the live signature are
        caught at first dispatch (AotFn recompiles with one warning)."""
        for e in entries:
            aot = self._exec_for(0, len(input_specs),
                                 donate_ok=e["donating"])
            aot.adopt(e["compiled"],
                      self._bucket_sig(aot, e["bucket"], input_specs))

    def export_state(self):
        """Host-side pool state a snapshot must carry so a warm start
        needs no proving dispatch (warmup also exists to learn these)."""
        return {"in_dtypes": [str(np.dtype(dt)) for dt in self._in_dtypes]
                if self._in_dtypes else None,
                "row_outputs": self._row_outputs,
                "donate": self._donate}

    def restore_state(self, state):
        if state.get("in_dtypes"):
            self._in_dtypes = [np.dtype(d) for d in state["in_dtypes"]]
        if state.get("row_outputs") is not None:
            self._row_outputs = [bool(r) for r in state["row_outputs"]]
        if state.get("donate") is not None:
            # the exporter's donation decision rode into the executables;
            # dispatch must route the same way or warm start would retrace
            self._donate = bool(state["donate"])


def symbol_infer_fn(outputs, input_names, param_names=None):
    """Adapt a Symbol graph to the pool's ``fn(params, *inputs)`` contract.

    Returns ``(fn, param_names)`` for the EVAL-mode clone of the graph, or
    ``(None, None)`` when the eval graph still draws randomness at run time
    (mode='always' dropout etc.) — those need fresh noise per call and must
    stay on the per-call evaluation path.
    """
    from ..symbol import (Group, _graph_has_rng, _ir_infer_runner,
                          _with_training)

    combined = outputs[0] if len(outputs) == 1 else Group(list(outputs))
    ev = _with_training(combined, False)
    if _graph_has_rng(ev):
        return None, None
    # prefer the unified-IR runner: the pass-optimized graph (CSE/fold/
    # cast-sink/DCE, mxnet_tpu.ir) compiles per bucket instead of the raw
    # per-call evaluation walk; graphs the IR can't represent (control
    # flow, multi-output ops) keep the legacy _build_fn closure
    ir_runner = _ir_infer_runner(ev)
    if ir_runner is not None:
        inner, names = ir_runner
    else:
        inner, names = ev._build_fn()
    input_names = list(input_names)
    if param_names is None:
        param_names = [n for n in names if n not in input_names]
    order = []  # positional plan: ('p', i) from params, ('x', i) from inputs
    for n in names:
        if n in input_names:
            order.append(("x", input_names.index(n)))
        else:
            order.append(("p", param_names.index(n)))

    def fn(params, *xs):
        vals = [params[i] if kind == "p" else xs[i] for kind, i in order]
        return inner(*vals)

    return fn, list(param_names)
