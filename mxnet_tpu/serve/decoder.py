"""GenerativeServer — token-level continuous batching over a paged KV cache.

The autoregressive complement of ``ModelServer``: instead of coalescing
whole fixed-shape forward passes, the scheduler coalesces TOKEN STEPS.
Requests join and leave between steps by slot assignment into a padded
batch (μ-cuDNN-style request decomposition, arXiv 1804.04806, applied to
the decode loop); every step runs ONE fused compiled program for the whole
in-flight batch — embed → N transformer blocks (each writing its slot's
K/V in place at its own position) → logits → SAMPLING (greedy + temperature
/top-k over per-slot threefry keys) — so there is no per-step host argmax
and exactly one dispatch per token step with zero steady-state retrace
(``engine.decode_compile_counter`` bumps inside the traced bodies, the same
proof-hook discipline as ``serve_compile_counter``).

Prefill is split from decode (the compute-bound vs. latency-bound halves):
a joining request's whole prompt runs through one forward pass at a pow2
prompt-length bucket, writing its cache page in a single dispatch and
sampling the first token inside the program. Identical prompts hit the
``PrefixCache`` instead: the stored pages are injected by a tiny compiled
program, skipping the forward entirely.

Admission reuses ``DynamicBatcher``'s bounded queue — priority classes and
SLO-aware preemptive shedding (batcher.submit) apply to generation
requests unchanged; per-request deadlines keep ticking while a request
waits for a slot and mid-stream. Tokens stream back through per-request
iterators (``GenerationStream``).

    m = gpt_nano(); m.initialize()
    srv = mxnet_tpu.serve.GenerativeServer(m, slots=8, eos_id=None)
    with srv:
        s = srv.submit([1, 2, 3], max_new_tokens=16, temperature=0.8)
        for tok in s:          # streams as decode steps complete
            print(tok)
"""
from __future__ import annotations

import os
import queue
import threading
import time
from collections import deque

import numpy as np

import jax
import jax.numpy as jnp

from .. import _trace, engine, profiler
from ..base import next_pow2
from .batcher import DynamicBatcher, ServeError, ServeTimeout
from .kv_cache import CacheError, PagedKVCache, PrefixCache
from .metrics import GenerativeMetrics

_DONE = object()


def sample_tokens(logits, keys, positions, temps, top_k):
    """Fused in-program sampling: greedy argmax per slot, or temperature/
    top-k categorical when ``temps[slot] > 0``. Each slot's threefry key is
    folded with the generated token's sequence position, so a request's
    token stream is deterministic in (seed, position) and independent of
    every other in-flight request. Runs INSIDE the compiled step — the
    sampled ids are the only thing the host reads back."""
    logits = logits.astype(jnp.float32)
    greedy = jnp.argmax(logits, axis=-1).astype(jnp.int32)

    def _sampled(operands):
        lg, ks, pos, tp = operands
        if top_k and top_k > 0:
            kth = jax.lax.top_k(lg, int(top_k))[0][:, -1:]
            lg = jnp.where(lg < kth, -jnp.inf, lg)
        scaled = lg / jnp.maximum(tp, 1e-6)[:, None]
        subkeys = jax.vmap(jax.random.fold_in)(ks, pos)
        sampled = jax.vmap(jax.random.categorical)(subkeys, scaled)
        return jnp.where(tp > 0, sampled.astype(jnp.int32), greedy)

    # the categorical branch (top-k + per-row threefry fold/bits) is the
    # expensive half; lax.cond skips it AT RUNTIME for all-greedy batches —
    # the speculative verify samples S*K rows per round, so it saves k×
    # what the plain step does
    return jax.lax.cond(jnp.any(temps > 0), _sampled,
                        lambda operands: greedy,
                        (logits, keys, positions, temps))


class GenerationStream:
    """Per-request streaming handle: iterate generated token ids as decode
    steps complete, or block for the full sequence with ``result()``.
    Queue-phase failures (shed by priority admission, queue timeout) and
    mid-stream failures (deadline, server stop) surface as the typed
    serve exceptions on the consumer side."""

    def __init__(self, prompt, max_new_tokens, temperature, seed, priority):
        self.prompt = np.asarray(prompt, np.int32).reshape(-1)
        if self.prompt.size == 0:
            raise ServeError("empty prompt")
        self.max_new_tokens = max(1, int(max_new_tokens))
        self.temperature = float(temperature)
        self.seed = int(seed)
        self.priority = int(priority)
        self.tokens = []          # generated ids, in order
        self._prompt_ids = None   # lazy python-int view for draft histories
        self._q = queue.Queue()
        self._done = threading.Event()
        self._error = None
        self._admission = None    # batcher request handle (queue-phase SLO)
        # observability.RequestTrace (set at submit; None = tracing off):
        # queue/pad/dispatch spans + per-token step attribution; read the
        # breakdown from stream.timing() when the stream completes
        self.trace = None

    def prompt_ids(self):
        """Prompt as a list of python ints, converted once — the draft
        history path reads it every speculation round."""
        if self._prompt_ids is None:
            self._prompt_ids = [int(x) for x in self.prompt]
        return self._prompt_ids

    # ------------------------------------------------------- producer side
    def _push(self, tok):
        self.tokens.append(int(tok))
        self._q.put(int(tok))

    def _finish(self, error=None):
        if self._done.is_set():
            return False
        self._error = error
        self._done.set()
        self._q.put(_DONE)
        return True

    # ------------------------------------------------------- consumer side
    def _check_admission(self):
        # the batcher fails queue-phase requests (timeout sweep, preemptive
        # shed) on ITS handle; mirror that failure onto the stream
        a = self._admission
        if a is not None and a.done() and a._error is not None:
            self._finish(a._error)

    def __iter__(self):
        while True:
            self._check_admission()
            try:
                item = self._q.get(timeout=0.05)
            except queue.Empty:
                continue
            if item is _DONE:
                break
            yield item
        if self._error is not None:
            raise self._error

    def done(self):
        return self._done.is_set()

    @property
    def trace_id(self):
        return self.trace.trace_id if self.trace is not None else None

    def timing(self):
        """Per-request breakdown (queue_ms/pad_ms/dispatch_ms/tokens);
        None when tracing is disabled."""
        return self.trace.timing() if self.trace is not None else None

    def result(self, timeout_s=None):
        """Block until generation completes; returns the list of generated
        token ids (prompt excluded). Raises the typed failure if the
        request was shed, timed out, or errored."""
        deadline = (time.perf_counter() + timeout_s) if timeout_s else None
        while not self._done.wait(0.05):
            self._check_admission()
            if deadline is not None and time.perf_counter() > deadline:
                raise ServeTimeout("no completion within %.1fs" % timeout_s)
        if self._error is not None:
            raise self._error
        return list(self.tokens)


class GenerativeServer:
    """Continuous-batching generative decode scheduler.

    Parameters
    ----------
    model : block implementing the fixed-capacity decode protocol
        ``decode_state_spec()``, ``forward_collect_kv(F, tokens)`` and
        ``decode_step_fixed(F, tokens, k_caches, v_caches, valid_len)``
        (``models.gpt.GPTModel`` is the reference implementation).
        Must be initialized; its parameter dtype decides the cache dtype.
    slots : int
        In-flight request pages — the padded decode batch. One decode
        dispatch serves all of them; free slots are masked, so join/leave
        never recompiles.
    top_k : int
        STATIC top-k filter compiled into the sampling head (0 = off).
        Temperature is per-request (0 = greedy) and traced, so mixing
        greedy and sampled requests in one batch costs nothing.
    eos_id : int or None
        Token id that completes a request early.
    max_wait_ms / max_queue / timeout_ms
        Admission-queue knobs, as in ModelServer. ``max_queue`` is in
        REQUESTS; priority classes and SLO-aware preemptive shedding are
        the DynamicBatcher's (see batcher.submit).
    prefix_cache : bool
        Cache finished prefills keyed by the prompt's token hash; a repeat
        prompt injects the stored pages instead of re-running the forward.
    donate : bool or None
        Donate cache/state buffers to the step programs (default: ON —
        the executor-pool donation discipline; hlolint GL022 flags the
        per-step KV page allocation the undonated programs would make).
        Safe on every backend: ``cache.update()`` replaces the host
        references after each call, so a donated-away buffer is never
        re-read. ``MXNET_DECODE_DONATE=0`` force-disables (debugging
        escape hatch: keeps step inputs alive for inspection).
    quantize : None or 'int8' / 'e4m3' / 'e5m2'
        Quantized serving: weight-quantize the model in place
        (``quantization.quantize_model`` — per-channel quantized matmuls
        with MXU accumulation) AND store KV pages as int8 with per-page-
        per-head scales. Decode stays ONE dispatch per token step with
        zero steady-state retrace; the cache costs ~0.5× the bf16 bytes.
        The model must implement ``decode_step_fixed_quant`` (GPTModel
        does). fp8 modes require :func:`quantization.fp8_supported`.
    draft : None, speculative draft object, or a draft model
        Enables speculative decode: per scheduler tick the draft proposes
        ``spec_k - 1`` tokens per slot and the target scores the whole
        window in ONE wide verify dispatch (``decode_step_speculative``),
        emitting 1..spec_k tokens. Pass ``serve.NGramDraft()`` (host-side
        pattern matcher, zero extra dispatches), ``serve.ModelDraft(m)``
        (a smaller same-API model, one multi-step dispatch per round), or
        a bare model (wrapped in ``ModelDraft``). Greedy streams emit
        byte-identical tokens to plain greedy decode; sampled streams emit
        the same per-(seed, position) tokens as the plain path (the
        deterministic-draft rejection-sampling identity — see
        serve.speculative).
    spec_k : int
        Verify window width (tokens scored per verify dispatch) when a
        ``draft`` is set; ``spec_k=1`` degenerates to plain decode through
        the verify program. Static — compiled into the window shape.
    prefill_chunk : None or int
        Chunked prefill budget (pow2-rounded): prompts longer than this
        fill their cache page in fixed ``prefill_chunk``-sized chunks, ONE
        chunk per scheduler tick interleaved with decode steps, so a long
        prompt never stalls in-flight streams for more than one chunk.
        Chunked prompts bypass the prefix cache (partial pages are never
        stored). Must be >= ``spec_k`` when both are set (in-flight
        speculation windows must stay behind the chunk frontier).
    """

    def __init__(self, model, slots=8, top_k=0, eos_id=None,
                 max_wait_ms=1.0, max_queue=64, timeout_ms=30000.0,
                 prefix_cache=True, donate=None, name=None,
                 metrics_port=None, quantize=None, draft=None, spec_k=4,
                 prefill_chunk=None):
        self._quantize = quantize or None
        if self._quantize is not None:
            if not hasattr(model, "decode_step_fixed_quant"):
                raise ServeError(
                    "quantize=%r: model %s has no decode_step_fixed_quant — "
                    "the int8 paged-KV decode protocol (see models.gpt."
                    "GPTModel)" % (quantize, type(model).__name__))
            from ..quantization import quantize_model

            # weight quantization BEFORE the param-list capture below so
            # the serving param store carries qweight/w_scale pages;
            # idempotent on an already-quantized model (snapshot load)
            quantize_model(model, mode=self._quantize)
        spec = model.decode_state_spec()
        self.model = model
        self.name = name or ("generate:%s" % type(model).__name__.lower())
        self.slots = int(slots)
        self.top_k = int(top_k)
        self.eos_id = eos_id
        self.timeout_ms = float(timeout_ms)
        self._plist = list(model.collect_params().values())
        # hot-swap seam: every dispatch snapshots the param list under
        # this lock (_params), and swap_parameters writes under it — a
        # decode step sees all-old or all-new weights, never a mix
        self._params_lock = threading.Lock()
        self._swap_epoch = 0
        self.cache = PagedKVCache(
            spec["layers"], spec["heads"], spec["head_dim"], self.slots,
            spec["max_length"], dtype=spec["dtype"],
            quantize=self._quantize is not None)
        self.prefix = PrefixCache() if prefix_cache else None
        self.metrics = GenerativeMetrics(self.name)
        if donate is None:
            # default ON everywhere (not just TPU): the step/prefill/
            # inject programs overwrite their cache args wholesale, and
            # cache.update() drops the stale references after every
            # call, so aliasing input→output buffers is always safe and
            # saves one KV-page allocation per dispatch (hlolint GL022)
            donate = os.environ.get("MXNET_DECODE_DONATE", "1") != "0"
        self._donate = bool(donate)
        # compiled-program caches: the pow2 bucketing bounds each at
        # log2(max) entries — the executor-pool discipline
        self._decode_fns = {}    # capacity -> jitted step
        self._prefill_fns = {}   # (tp, capacity) -> jitted prompt fill
        self._inject_fns = {}    # (tp, capacity) -> jitted prefix replay
        self._extract_fns = {}   # (tp, capacity) -> jitted page read-out
        self._verify_fns = {}    # capacity -> jitted speculative verify
        self._chunk_fns = {}     # (tc, capacity) -> jitted prefill chunk
        # speculative decode: draft proposer + static verify window width
        self.spec_k = max(1, int(spec_k))
        if draft is not None and not hasattr(draft, "propose"):
            from .speculative import ModelDraft

            draft = ModelDraft(draft)   # a bare model: wrap it
        self._draft = draft
        if self._draft is not None:
            if self._quantize is not None and not hasattr(
                    model, "decode_step_speculative_quant"):
                raise ServeError(
                    "draft + quantize: model %s has no decode_step_"
                    "speculative_quant" % type(model).__name__)
            if not hasattr(model, "decode_step_speculative"):
                raise ServeError(
                    "draft: model %s has no decode_step_speculative — the "
                    "wide-window verify protocol (see models.gpt.GPTModel)"
                    % type(model).__name__)
            self._draft.bind(self)
        # speculation windows write K/V through valid+spec_k-1: capacity
        # sizing must leave that margin past the generation budget or the
        # clamped window write would fold back onto live positions
        self._spec_margin = (self.spec_k - 1) if self._draft is not None \
            else 0
        # chunked prefill: pow2 chunk budget + in-flight chunk jobs
        # (slot -> job dict); slots mid-chunk are owned but masked out of
        # decode until their final chunk lands
        self._prefill_chunk = None
        if prefill_chunk is not None:
            self._prefill_chunk = next_pow2(max(8, int(prefill_chunk)))
            if self._draft is not None and self._prefill_chunk < self.spec_k:
                raise ServeError(
                    "prefill_chunk=%d < spec_k=%d: speculation windows "
                    "must fit behind the chunk frontier"
                    % (self._prefill_chunk, self.spec_k))
        self._chunk_jobs = {}
        # device-side carried state beyond the cache: current input token
        # per slot, and the per-slot sampling controls
        self._tok = jnp.zeros((self.slots,), jnp.int32)
        self._keys = np.zeros((self.slots, 2), np.uint32)
        self._temps = np.zeros((self.slots,), np.float32)
        self._dev_keys = None
        self._dev_temps = None
        self._dev_active = None
        self._ctl_dirty = True
        # host bookkeeping per slot
        self._slot_req = [None] * self.slots   # admission handle (deadline)
        self._remaining = [0] * self.slots     # tokens left to generate
        self._join_q = deque()
        self._join_cond = threading.Condition()
        self._batcher = DynamicBatcher(
            self._admit_batch, max_batch=self.slots, max_wait_ms=max_wait_ms,
            max_queue=max_queue, num_dispatchers=1, metrics=self.metrics)
        self._loop_thread = None
        self._stop_flag = False
        # opt-in /metrics scrape endpoint (observability.http); None = off
        self._metrics_port = metrics_port
        self.metrics_http = None
        from . import _register
        _register(self)

    # ------------------------------------------------------------ lifecycle
    def start(self):
        """Start the background scheduler loop (admit → one fused decode
        step → stream tokens, forever). Tests drive the same tick
        synchronously via :meth:`step`."""
        self._batcher.start()
        if self._metrics_port is not None and self.metrics_http is None:
            from ..observability import MetricsHTTPServer

            self.metrics_http = MetricsHTTPServer(self._metrics_port,
                                                  health_fn=self.health)
        if self._loop_thread is None or not self._loop_thread.is_alive():
            self._stop_flag = False
            self._loop_thread = threading.Thread(
                target=self._loop, daemon=True, name="serve-decode")
            self._loop_thread.start()
        return self

    def stop(self, timeout_s=5.0, reason="server stopped"):
        """Stop the scheduler loop, reject everything in flight, and tear
        the dispatcher pool down. The loop join is bounded by
        ``timeout_s``; active slots are retired and the join queue is
        drained only AFTER the join, so slot tables keep their
        single-writer discipline (racecheck GL011 allowlist). Idempotent,
        and start() after stop() rebuilds every thread — repeated cycles
        leak no threads (pinned by tests/test_concurrency.py)."""
        self._stop_flag = True
        with self._join_cond:
            self._join_cond.notify_all()
        loop, self._loop_thread = self._loop_thread, None
        if loop is not None:
            loop.join(timeout=timeout_s)
        self._batcher.stop(drain=False, timeout_s=timeout_s, reason=reason)
        for slot in self.cache.active_slots:
            self._retire(slot, error=ServeError(reason))
        with self._join_cond:
            pending = list(self._join_q)
            self._join_q.clear()
        for req in pending:
            err = ServeError(reason)
            if req.finish(error=err):
                req.inputs._finish(err)
        if self.metrics_http is not None:
            self.metrics_http.close()
            self.metrics_http = None

    def __enter__(self):
        return self.start()

    def __exit__(self, *a):
        self.stop()

    # ------------------------------------------------------------ hot swap
    def _params(self):
        """Per-dispatch param snapshot — the seam swap_parameters flips
        through (one coherent weight set per compiled call)."""
        with self._params_lock:
            return [p.data()._data for p in self._plist]

    def swap_parameters(self, params_file):
        """Zero-downtime weight hot-swap for the generative server:
        structural validation (``checkpoint.validate_swap`` — a mismatched
        tree, including quantized qweight/w_scale pages, is rejected with
        the old weights still serving), then an atomic flip under the
        per-dispatch param lock. The prefix cache is flushed — its stored
        KV pages were computed by the OLD weights; in-flight streams keep
        their already-written pages and finish (continuity over purity:
        no request is dropped by a swap). Returns the new swap epoch."""
        from ..checkpoint import validate_swap
        from ..ndarray import NDArray

        picked = validate_swap(self.model, params_file)
        params = self.model._collect_params_with_prefix()
        staged = {n: NDArray(jnp.asarray(a)) for n, a in picked.items()}
        with self._params_lock:
            for name, arr in staged.items():
                params[name].set_data(arr)
            self._swap_epoch += 1
        if self.prefix is not None:
            self.prefix._store.clear()
        return self._swap_epoch

    # ----------------------------------------------------------- fleet
    def tokens_in_flight(self):
        """Gauge: tokens still owed across live slots + queued admissions
        (each queued request owes at least its max_new_tokens=… budget is
        unknown until join, so queued requests count 1 row each via the
        batcher queue) — the router's least-loaded score component."""
        owed = sum(self._remaining[s] for s in self.cache.active_slots)
        return int(owed)

    def health(self):
        """Cheap liveness payload for ``/health`` (and the fleet router's
        per-pick scrape): warm flag + load gauges, no ring sorts."""
        tif = self.tokens_in_flight()
        self.metrics.record_tokens_in_flight(tif)
        return {"warm": bool(self._decode_fns or self._prefill_fns),
                "running": (self._loop_thread is not None
                            and self._loop_thread.is_alive()),
                "kind": "generative",
                "queue_depth": self._batcher.queue_depth(),
                "in_flight": self.cache.num_active,
                "tokens_in_flight": tif,
                "swap_epoch": self._swap_epoch}

    def export_prefixes(self):
        """Read the prefix cache out as host arrays for cross-process
        migration: [(tokens, k_stack, v_stack, prompt_len, last_logits)].
        The retirement path: a draining worker exports, the sibling that
        inherits its sessions imports, and multi-turn conversations keep
        their KV pages across the retire."""
        if self.prefix is None:
            return []
        out = []
        for key, ent in list(self.prefix._store.items()):
            k_stack, v_stack, plen, last = ent
            out.append((np.asarray(key, np.int32), k_stack, v_stack,
                        int(plen), last))
        return out

    def import_prefixes(self, entries):
        """Adopt migrated prefix entries (see export_prefixes). Stored
        host-side; the next prompt hit injects them through the compiled
        inject program like any locally-computed prefix."""
        if self.prefix is None:
            return 0
        n = 0
        for tokens, k_stack, v_stack, plen, last in entries:
            self.prefix.put(tokens, k_stack, v_stack, plen, last)
            n += 1
        return n

    # ------------------------------------------------------------ admission
    def submit(self, prompt, max_new_tokens=16, temperature=0.0, seed=0,
               priority=0, timeout_ms=None):
        """Enqueue one generation request; returns a ``GenerationStream``.
        Sheds with ``ServerBusy`` when the admission queue is full (unless
        ``priority`` preempts a lower class — see DynamicBatcher.submit);
        the deadline covers queue wait, prefill AND generation."""
        stream = GenerationStream(prompt, max_new_tokens, temperature, seed,
                                  priority)
        tmo = self.timeout_ms if timeout_ms is None else float(timeout_ms)
        # fail impossible requests at the door, not after a queue wait
        self.cache.capacity_bucket(stream.prompt.size + stream.max_new_tokens
                                   + self._spec_margin)
        if not self._batcher._worker or not self._batcher._worker.is_alive():
            self._batcher.start()
        from ..observability import new_trace

        stream.trace = new_trace(self.name)
        req = self._batcher.submit(stream, 1, timeout_ms=tmo,
                                   priority=priority, trace=stream.trace)
        stream._admission = req
        return stream

    def generate(self, prompt, **kwargs):
        """Synchronous convenience: submit + wait; returns generated ids."""
        tmo = kwargs.get("timeout_ms", self.timeout_ms)
        return self.submit(prompt, **kwargs).result(timeout_s=tmo / 1e3 + 5.0)

    def _admit_batch(self, requests, rows):
        """Batcher dispatch callback: hand admitted requests to the decode
        loop. BLOCKS while the handover buffer is full so saturation backs
        up into the bounded admission queue (where shedding and timeouts
        live) instead of an unbounded join list."""
        for req in requests:
            with self._join_cond:
                while (not self._stop_flag
                       and len(self._join_q) >= self.slots):
                    self._join_cond.wait(0.05)
                    if req.expired():
                        break
                if self._stop_flag:
                    err = ServeError("server stopped")
                    if req.finish(error=err):
                        req.inputs._finish(err)
                    continue
                self._join_q.append(req)

    # ------------------------------------------------------------ scheduler
    def step(self):
        """One scheduler tick: admit pending joins (prefill/inject, one
        dispatch each — or a chunk-job handoff for long prompts), run AT
        MOST ONE prefill chunk, then run ONE fused decode step for the
        whole in-flight batch and deliver each live slot's token(s).
        Returns the number of slots progressed (0 = idle). The background
        loop calls this continuously; tests call it directly for
        counter-exact assertions."""
        self._admit_pending()
        chunked = self._chunk_once()
        return self._decode_once() + chunked

    def _loop(self):
        while not self._stop_flag:
            if self.step() == 0:
                time.sleep(0.001)

    # ------------------------------------------------------------- joining
    def _admit_pending(self):
        while self.cache._free:
            with self._join_cond:
                req = self._join_q.popleft() if self._join_q else None
                self._join_cond.notify_all()
            if req is None:
                return
            stream = req.inputs
            now = time.perf_counter()
            if req.done():      # queue sweep got it first
                continue
            if req.expired(now):
                err = ServeTimeout("timed out after %.1fms waiting for a "
                                   "slot" % ((now - req.t_submit) * 1e3))
                if req.finish(error=err):
                    stream._finish(err)
                    self.metrics.record_timeout()
                continue
            try:
                self._join(req, stream)
            except Exception as e:   # cache exhaustion, model error
                self.metrics.record_error()
                if req.finish(error=e):
                    stream._finish(e)

    def _join(self, req, stream):
        tr = stream.trace
        t_join = time.perf_counter()
        if tr is not None:
            # queue phase for a generation request spans admission →
            # slot assignment (batcher queue + join handover)
            tr.add_span("queue", req.t_submit, t_join)
        t0_len = int(stream.prompt.size)
        need = t0_len + stream.max_new_tokens + self._spec_margin
        self.cache.ensure_capacity(need)
        if self._draft is not None:
            self._draft.ensure_capacity()
        key = np.asarray(jax.random.PRNGKey(stream.seed), np.uint32)
        if (self._prefill_chunk is not None
                and t0_len > self._prefill_chunk):
            # chunked prefill: own the slot now, fill the page one chunk
            # per tick (interleaved with decode by step()); the slot stays
            # masked out of decode until the final chunk samples the first
            # token. Bypasses the prefix cache — partial pages are never
            # stored, and storing only whole ones would hold the very
            # stall this path removes.
            slot = self.cache.acquire(stream)
            self._chunk_jobs[slot] = {
                "req": req, "stream": stream, "pos": 0, "key": key,
                "t_join": t_join}
            self._ctl_dirty = True
            return
        slot = self.cache.acquire(stream)
        tp = min(next_pow2(t0_len), self.cache.capacity)
        padded = np.zeros((1, tp), np.int32)
        padded[0, :t0_len] = stream.prompt
        hit = self.prefix.get(stream.prompt) if self.prefix is not None \
            else None
        t_disp0 = time.perf_counter()
        if tr is not None:
            # host-side prompt pad-to-bucket (the decode analogue of the
            # pool's pad span)
            tr.add_span("pad", t_join, t_disp0, bucket=tp)
        engine.dispatch_counter.bump()
        scope = (profiler.decode_scope("prefill%d" % tp, self.slots,
                                       self.cache.num_active)
                 if profiler.is_running() else None)
        try:
            if scope is not None:
                scope.__enter__()
            kss = vss = None
            if hit is not None:
                k_stack, v_stack, plen, last = hit
                fn = self._inject_fn(tp, self.cache.capacity)
                if self._quantize:
                    # prefix entries stay in the fp format: inject
                    # re-quantizes into the slot's page (exact round-trip
                    # with extract's dequantize — same scale re-derives)
                    kcs, kss, vcs, vss, valid, toks = fn(
                        self.cache.k, self.cache.k_scale, self.cache.v,
                        self.cache.v_scale, self.cache.valid, self._tok,
                        jnp.asarray(k_stack), jnp.asarray(v_stack),
                        jnp.int32(plen), jnp.int32(slot), jnp.asarray(last),
                        jnp.asarray(key), jnp.float32(stream.temperature))
                else:
                    kcs, vcs, valid, toks = fn(
                        self.cache.k, self.cache.v, self.cache.valid,
                        self._tok, jnp.asarray(k_stack),
                        jnp.asarray(v_stack), jnp.int32(plen),
                        jnp.int32(slot), jnp.asarray(last),
                        jnp.asarray(key), jnp.float32(stream.temperature))
            else:
                fn = self._prefill_fn(tp, self.cache.capacity)
                params = self._params()
                if self._quantize:
                    kcs, kss, vcs, vss, valid, toks, last = fn(
                        params, self.cache.k, self.cache.k_scale,
                        self.cache.v, self.cache.v_scale, self.cache.valid,
                        self._tok, jnp.asarray(padded), jnp.int32(t0_len),
                        jnp.int32(slot), jnp.asarray(key),
                        jnp.float32(stream.temperature))
                else:
                    kcs, vcs, valid, toks, last = fn(
                        params, self.cache.k, self.cache.v, self.cache.valid,
                        self._tok, jnp.asarray(padded), jnp.int32(t0_len),
                        jnp.int32(slot), jnp.asarray(key),
                        jnp.float32(stream.temperature))
        finally:
            if scope is not None:
                scope.__exit__(None, None, None)
        self.cache.update(kcs, vcs, valid, kss, vss)
        self._tok = toks
        if hit is None:
            self.metrics.record_prefill()
            if self.prefix is not None:
                # one page read-out per UNIQUE prompt; repeats skip the
                # whole forward from then on
                engine.dispatch_counter.bump()
                if self._quantize:
                    ks, vs = self._extract_fn(tp, self.cache.capacity)(
                        self.cache.k, self.cache.k_scale, self.cache.v,
                        self.cache.v_scale, jnp.int32(slot))
                else:
                    ks, vs = self._extract_fn(tp, self.cache.capacity)(
                        self.cache.k, self.cache.v, jnp.int32(slot))
                self.prefix.put(stream.prompt, ks, vs, t0_len,
                                np.asarray(last))
        first = int(np.asarray(self._tok)[slot])
        now = time.perf_counter()
        if tr is not None:
            # prefill (or prefix-inject) dispatch, closed by the first-token
            # host readback; the first token is sampled inside this program
            tr.add_span("dispatch", t_disp0, now,
                        kind="inject" if hit is not None else "prefill")
            tr.tokens += 1
        if not req.finish(result=stream):
            # timed out in the same instant admission landed: roll back
            self.cache.release(slot)
            return
        if self._draft is not None:
            # draft cache fill for the new stream (one small dispatch for
            # ModelDraft, free for NGramDraft) — a target prefix hit still
            # pays this: the draft keeps no prefix cache
            self._draft.join(slot, stream, padded, t0_len)
        self._slot_req[slot] = req
        self._remaining[slot] = stream.max_new_tokens
        self._keys[slot] = key
        self._temps[slot] = stream.temperature
        self._ctl_dirty = True
        self.metrics.record_first_token((now - req.t_submit) * 1e3, t0_len)
        self._deliver(slot, first)

    # ------------------------------------------------------------- decoding
    def _decode_once(self):
        # slots mid-chunked-prefill are owned (admission can't reuse them)
        # but not decodable yet — masked out until their final chunk
        active = self.cache.active_mask(exclude=self._chunk_jobs)
        n_active = int(active.sum())
        if n_active == 0:
            return 0
        if self._ctl_dirty:
            self._dev_keys = jnp.asarray(self._keys)
            self._dev_temps = jnp.asarray(self._temps)
            self._dev_active = jnp.asarray(active)
            self._ctl_dirty = False
        if self._draft is not None:
            return self._speculate_once(active, n_active)
        fn = self._decode_fn(self.cache.capacity)
        params = self._params()
        if self._quantize:
            args = (params, self.cache.k, self.cache.k_scale, self.cache.v,
                    self.cache.v_scale, self.cache.valid, self._tok,
                    self._dev_active, self._dev_keys, self._dev_temps)
        else:
            args = (params, self.cache.k, self.cache.v, self.cache.valid,
                    self._tok, self._dev_active, self._dev_keys,
                    self._dev_temps)
        engine.dispatch_counter.bump()
        t0 = time.perf_counter()
        if profiler.is_running():
            with profiler.decode_scope("step", self.slots, n_active):
                out = fn(*args)
        else:
            out = fn(*args)
        kss = vss = None
        if self._quantize:
            kcs, kss, vcs, vss, valid, nxt = out
        else:
            kcs, vcs, valid, nxt = out
        nxt_host = np.asarray(nxt)   # ONE host gather per step — the tokens
        self.cache.update(kcs, vcs, valid, kss, vss)
        self._tok = nxt
        dt = time.perf_counter() - t0
        self.metrics.record_step(dt, n_active, n_active, self.slots,
                                 under_prefill=bool(self._chunk_jobs))
        now = time.perf_counter()
        for slot in np.nonzero(active)[0]:
            self._deliver(int(slot), int(nxt_host[slot]), now, step_s=dt)
        return n_active

    def _speculate_once(self, active, n_active):
        """One speculation round: draft proposes spec_k-1 tokens per slot
        (0 or 1 dispatch), the target scores the whole window in ONE wide
        verify dispatch, and each live slot receives its accepted prefix
        plus the verify sample at the first mismatch (1..spec_k tokens).
        Rejected draft positions need no device-side scrub: ``valid_len``
        advances only past accepted tokens and the next window overwrites
        the dead suffix in place."""
        k = self.spec_k
        draft = self._draft
        if draft.needs_history:
            hists = []
            for s in range(self.slots):
                o = self.cache.owner(s)
                hists.append(
                    o.prompt_ids() + o.tokens
                    if (o is not None and active[s]) else [])
            # host np array goes straight into the compiled call — the
            # executable's own arg staging is the cheap C++ transfer path
            # (an explicit jnp.asarray here costs a python device_put per
            # round)
            drafts = draft.propose(hists, k)
        else:
            drafts = draft.propose(None, k)
        fn = self._verify_fn(self.cache.capacity)
        params = self._params()
        if self._quantize:
            args = (params, self.cache.k, self.cache.k_scale, self.cache.v,
                    self.cache.v_scale, self.cache.valid, self._tok, drafts,
                    self._dev_active, self._dev_keys, self._dev_temps)
        else:
            args = (params, self.cache.k, self.cache.v, self.cache.valid,
                    self._tok, drafts, self._dev_active, self._dev_keys,
                    self._dev_temps)
        engine.dispatch_counter.bump()
        engine.verify_dispatch_counter.bump()
        t0 = time.perf_counter()
        if profiler.is_running():
            with profiler.decode_scope("verify%d" % k, self.slots, n_active):
                out = fn(*args)
        else:
            out = fn(*args)
        kss = vss = None
        if self._quantize:
            kcs, kss, vcs, vss, valid, nxt, emit, n_emit = out
        else:
            kcs, vcs, valid, nxt, emit, n_emit = out
        # ONE batched host gather for both outputs (two np.asarray calls
        # would sync the device twice per round)
        emit_h, n_emit_h = jax.device_get((emit, n_emit))
        self.cache.update(kcs, vcs, valid, kss, vss)
        self._tok = nxt
        dt = time.perf_counter() - t0
        emitted = int(n_emit_h.sum())
        self.metrics.record_step(dt, emitted, n_active, self.slots,
                                 under_prefill=bool(self._chunk_jobs))
        self.metrics.record_spec_round(n_active * (k - 1),
                                       emitted - n_active)
        now = time.perf_counter()
        for slot in np.nonzero(active)[0]:
            slot = int(slot)
            stream = self.cache.owner(slot)
            for tok in emit_h[slot, :n_emit_h[slot]]:
                if self.cache.owner(slot) is not stream:
                    break   # retired mid-window (EOS / budget / deadline)
                self._deliver(slot, int(tok), now, step_s=dt)
        return n_active

    def _chunk_once(self):
        """Run AT MOST one prefill chunk (FIFO across jobs): extract the
        slot's page, run ``prefill_chunk`` prompt positions through the
        wide-window step at offset ``pos``, write the page back — one
        bounded dispatch, so in-flight decode never stalls longer than one
        chunk. The final chunk samples the first token and activates the
        slot."""
        if not self._chunk_jobs:
            return 0
        slot, job = next(iter(self._chunk_jobs.items()))
        req, stream = job["req"], job["stream"]
        now = time.perf_counter()
        if req.done() or req.expired(now):
            del self._chunk_jobs[slot]
            self.cache.release(slot)
            self._ctl_dirty = True
            err = ServeTimeout("timed out after %.1fms mid-prefill"
                               % ((now - req.t_submit) * 1e3))
            if req.finish(error=err):
                stream._finish(err)
                self.metrics.record_timeout()
            with self._join_cond:
                self._join_cond.notify_all()
            return 1
        tc = self._prefill_chunk
        plen = int(stream.prompt.size)
        pos0 = job["pos"]
        seg = stream.prompt[pos0:pos0 + tc]
        chunk = np.zeros((1, tc), np.int32)
        chunk[0, :seg.size] = seg
        fn = self._chunk_fn(tc, self.cache.capacity)
        params = self._params()
        engine.dispatch_counter.bump()
        scope = (profiler.decode_scope("chunk%d" % tc, self.slots,
                                       self.cache.num_active)
                 if profiler.is_running() else None)
        try:
            if scope is not None:
                scope.__enter__()
            if self._quantize:
                kcs, kss, vcs, vss, valid, toks = fn(
                    params, self.cache.k, self.cache.k_scale, self.cache.v,
                    self.cache.v_scale, self.cache.valid, self._tok,
                    jnp.asarray(chunk), jnp.int32(pos0), jnp.int32(plen),
                    jnp.int32(slot), jnp.asarray(job["key"]),
                    jnp.float32(stream.temperature))
                self.cache.update(kcs, vcs, valid, kss, vss)
            else:
                kcs, vcs, valid, toks = fn(
                    params, self.cache.k, self.cache.v, self.cache.valid,
                    self._tok, jnp.asarray(chunk), jnp.int32(pos0),
                    jnp.int32(plen), jnp.int32(slot),
                    jnp.asarray(job["key"]),
                    jnp.float32(stream.temperature))
                self.cache.update(kcs, vcs, valid)
        finally:
            if scope is not None:
                scope.__exit__(None, None, None)
        self._tok = toks
        self.metrics.record_chunk()
        job["pos"] = pos0 + tc
        if job["pos"] < plen:
            return 1
        # final chunk: the first token was sampled in-program — activate
        del self._chunk_jobs[slot]
        first = int(np.asarray(self._tok)[slot])
        now = time.perf_counter()
        if stream.trace is not None:
            stream.trace.add_span("dispatch", job["t_join"], now,
                                  kind="chunked_prefill")
            stream.trace.tokens += 1
        self.metrics.record_prefill()
        if not req.finish(result=stream):
            self.cache.release(slot)
            self._ctl_dirty = True
            return 1
        if self._draft is not None:
            tp = min(next_pow2(plen), self.cache.capacity)
            padded = np.zeros((1, tp), np.int32)
            padded[0, :plen] = stream.prompt
            self._draft.join(slot, stream, padded, plen)
        self._slot_req[slot] = req
        self._remaining[slot] = stream.max_new_tokens
        self._keys[slot] = job["key"]
        self._temps[slot] = stream.temperature
        self._ctl_dirty = True
        self.metrics.record_first_token((now - req.t_submit) * 1e3, plen)
        self._deliver(slot, first)
        with self._join_cond:
            self._join_cond.notify_all()
        return 1

    def _deliver(self, slot, tok, now=None, step_s=None):
        """Hand one token to a slot's stream and retire the request when it
        completes (EOS / budget) or blows its deadline."""
        stream = self.cache.owner(slot)
        req = self._slot_req[slot]
        if step_s is not None and stream.trace is not None:
            # O(1) per token: attribute the shared step dispatch to this
            # request (a float add, never a span)
            stream.trace.note_decode_step(step_s, now)
        stream._push(tok)
        self._remaining[slot] -= 1
        if (self.eos_id is not None and tok == self.eos_id) \
                or self._remaining[slot] <= 0:
            self._retire(slot)
            return
        if req is not None and req.expired(now):
            self._retire(slot, error=ServeTimeout(
                "deadline passed mid-generation (after %d tokens)"
                % len(stream.tokens)))
            self.metrics.record_timeout()

    def _retire(self, slot, error=None):
        stream = self.cache.owner(slot)
        req = self._slot_req[slot]
        if stream is not None:
            if stream.trace is not None:
                # one aggregate decode span per request, emitted at retire
                stream.trace.close_decode()
            stream._finish(error)
            if error is None and req is not None:
                self.metrics.record_latency(
                    (time.perf_counter() - req.t_submit) * 1e3)
        self._slot_req[slot] = None
        self._temps[slot] = 0.0
        self._ctl_dirty = True
        if self._draft is not None:
            self._draft.release(slot)
        self.cache.release(slot)
        with self._join_cond:
            self._join_cond.notify_all()

    # ------------------------------------------------- compiled programs
    def _trace_ctx(self, params):
        ctx = _trace.trace_scope(jax.random.PRNGKey(0), False)
        return ctx

    def _jit(self, fn, donate, hint=""):
        """Decode-loop programs compile through ``cache.AotFn`` in
        single-signature mode: shapes are fixed by the (slots, capacity /
        prompt-bucket) key, so the hot per-token path is one attribute
        read — and every program has an exportable executable handle for
        Tier B snapshots plus the Tier A disk store underneath."""
        from ..cache import AotFn

        return AotFn(fn,
                     donate_argnums=donate if (self._donate and donate)
                     else (),
                     tier="decode", hint=hint or "decode",
                     single_signature=True)

    def _decode_fn(self, capacity):
        fn = self._decode_fns.get(capacity)
        if fn is not None:
            return fn
        model, plist, top_k = self.model, self._plist, self.top_k

        if self._quantize:
            def pure(params, kcs, kss, vcs, vss, valid, toks, active, keys,
                     temps):
                # trace-time bump: fires exactly when XLA retraces — the
                # zero-steady-state-retrace proof tests assert (the
                # quantized step keeps the identical contract)
                engine.decode_compile_counter.bump()
                with _trace.trace_scope(jax.random.PRNGKey(0), False) as t:
                    t.param_store = {id(p): a
                                     for p, a in zip(plist, params)}
                    logits, kcs, kss, vcs, vss = \
                        model.decode_step_fixed_quant(
                            _trace.F, toks, kcs, kss, vcs, vss, valid)
                nxt = sample_tokens(logits, keys, valid + 1, temps, top_k)
                act = active > 0
                nxt = jnp.where(act, nxt, 0)
                valid = valid + act.astype(jnp.int32)
                return kcs, kss, vcs, vss, valid, nxt

            fn = self._jit(pure, donate=(1, 2, 3, 4, 5, 6),
                           hint="step@c%d" % capacity)
            self._decode_fns[capacity] = fn
            return fn

        def pure(params, kcs, vcs, valid, toks, active, keys, temps):
            # trace-time bump: fires exactly when XLA retraces — the
            # zero-steady-state-retrace proof tests assert
            engine.decode_compile_counter.bump()
            with _trace.trace_scope(jax.random.PRNGKey(0), False) as t:
                t.param_store = {id(p): a for p, a in zip(plist, params)}
                logits, kcs, vcs = model.decode_step_fixed(
                    _trace.F, toks, kcs, vcs, valid)
            # the generated token's position is valid+1 (prefill used
            # `prompt_len` for the first token) — every token of a stream
            # folds a distinct position into its slot key
            nxt = sample_tokens(logits, keys, valid + 1, temps, top_k)
            act = active > 0
            nxt = jnp.where(act, nxt, 0)
            valid = valid + act.astype(jnp.int32)
            return kcs, vcs, valid, nxt

        fn = self._jit(pure, donate=(1, 2, 3, 4), hint="step@c%d" % capacity)
        self._decode_fns[capacity] = fn
        return fn

    def _verify_fn(self, capacity):
        """Speculative verify program: score the (current token + drafted)
        k-window in one wide dispatch, sample every row at its own
        sequence position with the slot's folded key, and accept the
        longest prefix where the sample equals the draft — the first
        mismatching row's sample IS the rejection-resample (exact for
        deterministic drafts: the proposal is one-hot, so accept-w.p.-p(d)
        and the residual distribution both collapse to 'sample from p,
        keep on agreement'). Greedy rows therefore reproduce plain greedy
        decode bit-for-bit; k=1 degenerates to the plain step."""
        fn = self._verify_fns.get(capacity)
        if fn is not None:
            return fn
        model, plist, top_k = self.model, self._plist, self.top_k
        k = self.spec_k

        def accept_emit(logits, valid, drafts, active, keys, temps):
            S, K, V = logits.shape
            # row j's token lands at sequence position valid+1+j — the
            # same per-(seed, position) fold plain decode uses, so spec
            # and plain streams sample identical tokens
            pos = valid[:, None] + 1 + jnp.arange(K, dtype=jnp.int32)[None]
            y = sample_tokens(jnp.reshape(logits, (S * K, V)),
                              jnp.repeat(keys, K, axis=0),
                              jnp.reshape(pos, (-1,)),
                              jnp.repeat(temps, K), top_k)
            y = jnp.reshape(y, (S, K))
            if K > 1:
                match = (y[:, :K - 1] == drafts).astype(jnp.int32)
                al = jnp.sum(jnp.cumprod(match, axis=1), axis=1)
            else:
                al = jnp.zeros((S,), jnp.int32)
            act = active > 0
            n_emit = jnp.where(act, al + 1, 0)
            emit = jnp.where(
                (jnp.arange(K, dtype=jnp.int32)[None] <= al[:, None])
                & act[:, None], y, 0)
            nxt = jnp.where(
                act, jnp.take_along_axis(y, al[:, None], axis=1)[:, 0], 0)
            return valid + n_emit, nxt, emit, n_emit

        if self._quantize:
            def pure(params, kcs, kss, vcs, vss, valid, toks, drafts,
                     active, keys, temps):
                # trace-time bump: zero-steady-state-retrace proof (the
                # verify DISPATCH count is engine.verify_dispatch_counter,
                # bumped at the call site)
                engine.decode_compile_counter.bump()
                window = jnp.concatenate([toks[:, None], drafts], axis=1)
                with _trace.trace_scope(jax.random.PRNGKey(0), False) as t:
                    t.param_store = {id(p): a
                                     for p, a in zip(plist, params)}
                    logits, kcs, kss, vcs, vss = \
                        model.decode_step_speculative_quant(
                            _trace.F, window, kcs, kss, vcs, vss, valid)
                valid, nxt, emit, n_emit = accept_emit(
                    logits, valid, drafts, active, keys, temps)
                return kcs, kss, vcs, vss, valid, nxt, emit, n_emit

            fn = self._jit(pure, donate=(1, 2, 3, 4, 5, 6),
                           hint="verify%d@c%d" % (k, capacity))
            self._verify_fns[capacity] = fn
            return fn

        def pure(params, kcs, vcs, valid, toks, drafts, active, keys,
                 temps):
            # trace-time bump: zero-steady-state-retrace proof (the verify
            # DISPATCH count is engine.verify_dispatch_counter, bumped at
            # the call site)
            engine.decode_compile_counter.bump()
            window = jnp.concatenate([toks[:, None], drafts], axis=1)
            with _trace.trace_scope(jax.random.PRNGKey(0), False) as t:
                t.param_store = {id(p): a for p, a in zip(plist, params)}
                logits, kcs, vcs = model.decode_step_speculative(
                    _trace.F, window, kcs, vcs, valid)
            valid, nxt, emit, n_emit = accept_emit(
                logits, valid, drafts, active, keys, temps)
            return kcs, vcs, valid, nxt, emit, n_emit

        fn = self._jit(pure, donate=(1, 2, 3, 4),
                       hint="verify%d@c%d" % (k, capacity))
        self._verify_fns[capacity] = fn
        return fn

    def _chunk_fn(self, tc, capacity):
        """Prefill-chunk program: slice the slot's page out of the shared
        buffers, run ``tc`` prompt positions through the wide-window step
        at offset ``pos0`` (``decode_step_speculative`` with a (1,) valid
        vector — prefix attention + in-window causality + the per-row
        window write are exactly the verify semantics), and write the page
        back. The final chunk (pos0 + tc >= plen) samples the first token
        at its true row and sets valid to the full prompt length;
        non-final chunks park valid at the chunk frontier, so interleaved
        decode garbage for this masked slot lands exactly where the next
        chunk overwrites it."""
        fn = self._chunk_fns.get((tc, capacity))
        if fn is not None:
            return fn
        model, plist, top_k = self.model, self._plist, self.top_k
        H, D = self.cache.heads, self.cache.head_dim
        zero = jnp.int32(0)

        def finish(logits, valid, toks, pos0, plen, slot, key, temp):
            nvalid = jnp.minimum(pos0 + tc, plen)
            valid = jax.lax.dynamic_update_slice(
                valid, jnp.reshape(nvalid, (1,)), (slot,))
            # first-token row (clamped: garbage until the final chunk,
            # overwritten by it)
            row = jnp.clip(plen - 1 - pos0, 0, tc - 1)
            last = jnp.reshape(jax.lax.dynamic_slice(
                logits, (zero, row, zero),
                (1, 1, logits.shape[2])), (1, -1))
            t0 = sample_tokens(last, key[None], plen[None], temp[None],
                               top_k)
            return valid, jax.lax.dynamic_update_slice(toks, t0, (slot,))

        if self._quantize:
            def pure(params, kcs, kss, vcs, vss, valid, toks, tokens, pos0,
                     plen, slot, key, temp):
                engine.decode_compile_counter.bump()
                pk = [jax.lax.dynamic_slice(
                    kc, (slot, zero, zero, zero), (1, H, capacity, D))
                    for kc in kcs]
                pv = [jax.lax.dynamic_slice(
                    vc, (slot, zero, zero, zero), (1, H, capacity, D))
                    for vc in vcs]
                # fresh page scale on the first chunk (slot reuse must not
                # inherit the previous stream's running max)
                wipe = (pos0 == 0)

                def slice_scale(s):
                    sl = jax.lax.dynamic_slice(
                        s, (slot, zero, zero, zero), (1, H, 1, 1))
                    return jnp.where(wipe, jnp.zeros_like(sl), sl)

                ps = [slice_scale(s) for s in kss]
                qs = [slice_scale(s) for s in vss]
                with _trace.trace_scope(jax.random.PRNGKey(0), False) as t:
                    t.param_store = {id(p): a
                                     for p, a in zip(plist, params)}
                    logits, pk, ps, pv, qs = \
                        model.decode_step_speculative_quant(
                            _trace.F, tokens, pk, ps, pv, qs,
                            jnp.reshape(pos0, (1,)))
                kcs = [jax.lax.dynamic_update_slice(
                    kc, p, (slot, zero, zero, zero))
                    for kc, p in zip(kcs, pk)]
                kss = [jax.lax.dynamic_update_slice(
                    s0, s, (slot, zero, zero, zero))
                    for s0, s in zip(kss, ps)]
                vcs = [jax.lax.dynamic_update_slice(
                    vc, p, (slot, zero, zero, zero))
                    for vc, p in zip(vcs, pv)]
                vss = [jax.lax.dynamic_update_slice(
                    s0, s, (slot, zero, zero, zero))
                    for s0, s in zip(vss, qs)]
                valid, toks = finish(logits, valid, toks, pos0, plen, slot,
                                     key, temp)
                return kcs, kss, vcs, vss, valid, toks

            fn = self._jit(pure, donate=(1, 2, 3, 4, 5, 6),
                           hint="chunk%d@c%d" % (tc, capacity))
            self._chunk_fns[(tc, capacity)] = fn
            return fn

        def pure(params, kcs, vcs, valid, toks, tokens, pos0, plen, slot,
                 key, temp):
            engine.decode_compile_counter.bump()
            pk = [jax.lax.dynamic_slice(
                kc, (slot, zero, zero, zero), (1, H, capacity, D))
                for kc in kcs]
            pv = [jax.lax.dynamic_slice(
                vc, (slot, zero, zero, zero), (1, H, capacity, D))
                for vc in vcs]
            with _trace.trace_scope(jax.random.PRNGKey(0), False) as t:
                t.param_store = {id(p): a for p, a in zip(plist, params)}
                logits, pk, pv = model.decode_step_speculative(
                    _trace.F, tokens, pk, pv, jnp.reshape(pos0, (1,)))
            kcs = [jax.lax.dynamic_update_slice(
                kc, p, (slot, zero, zero, zero)) for kc, p in zip(kcs, pk)]
            vcs = [jax.lax.dynamic_update_slice(
                vc, p, (slot, zero, zero, zero)) for vc, p in zip(vcs, pv)]
            valid, toks = finish(logits, valid, toks, pos0, plen, slot,
                                 key, temp)
            return kcs, vcs, valid, toks

        fn = self._jit(pure, donate=(1, 2, 3, 4),
                       hint="chunk%d@c%d" % (tc, capacity))
        self._chunk_fns[(tc, capacity)] = fn
        return fn

    @staticmethod
    def _quantize_pages(pages, plen, tp):
        """Quantize per-layer fp K or V (1, H, tp, D) into int8 pages with
        a fresh per-head scale, masking positions ≥ plen out of the amax
        (pad garbage must not inflate the scale). Fresh overwrite, not a
        running max: slot reuse relies on prefill/inject resetting the
        page scale. Returns [(q (1,H,tp,D) int8, scale (1,H,1,1) f32)]."""
        maskf = (jnp.arange(tp) < plen).astype(jnp.float32).reshape(
            (1, 1, tp, 1))
        out = []
        for a in pages:
            a = a.astype(jnp.float32) * maskf
            amax = jnp.max(jnp.abs(a), axis=(2, 3), keepdims=True)
            scale = jnp.maximum(amax / 127.0, 1e-8)
            q = jnp.clip(jnp.round(a / scale), -127, 127).astype(jnp.int8)
            out.append((q, scale))
        return out

    def _prefill_fn(self, tp, capacity):
        fn = self._prefill_fns.get((tp, capacity))
        if fn is not None:
            return fn
        model, plist, top_k = self.model, self._plist, self.top_k
        zero = jnp.int32(0)

        if self._quantize:
            quantize_pages = self._quantize_pages

            def pure(params, kcs, kss, vcs, vss, valid, toks, tokens, plen,
                     slot, key, temp):
                engine.decode_compile_counter.bump()
                with _trace.trace_scope(jax.random.PRNGKey(0), False) as t:
                    t.param_store = {id(p): a
                                     for p, a in zip(plist, params)}
                    logits, kvs = model.forward_collect_kv(_trace.F, tokens)
                qk = quantize_pages([k for k, _v in kvs], plen, tp)
                qv = quantize_pages([v for _k, v in kvs], plen, tp)
                kcs = [jax.lax.dynamic_update_slice(
                    kc, q, (slot, zero, zero, zero))
                    for kc, (q, _s) in zip(kcs, qk)]
                kss = [jax.lax.dynamic_update_slice(
                    ks, s, (slot, zero, zero, zero))
                    for ks, (_q, s) in zip(kss, qk)]
                vcs = [jax.lax.dynamic_update_slice(
                    vc, q, (slot, zero, zero, zero))
                    for vc, (q, _s) in zip(vcs, qv)]
                vss = [jax.lax.dynamic_update_slice(
                    vs, s, (slot, zero, zero, zero))
                    for vs, (_q, s) in zip(vss, qv)]
                valid = jax.lax.dynamic_update_slice(
                    valid, jnp.reshape(plen, (1,)), (slot,))
                last = jnp.reshape(jax.lax.dynamic_slice(
                    logits, (zero, plen - 1, zero),
                    (1, 1, logits.shape[2])), (1, -1))
                t0 = sample_tokens(last, key[None], plen[None], temp[None],
                                   top_k)
                toks = jax.lax.dynamic_update_slice(toks, t0, (slot,))
                return (kcs, kss, vcs, vss, valid, toks,
                        jnp.reshape(last, (-1,)))

            fn = self._jit(pure, donate=(1, 2, 3, 4, 5, 6),
                           hint="prefill@t%dc%d" % (tp, capacity))
            self._prefill_fns[(tp, capacity)] = fn
            return fn

        def pure(params, kcs, vcs, valid, toks, tokens, plen, slot, key,
                 temp):
            engine.decode_compile_counter.bump()
            with _trace.trace_scope(jax.random.PRNGKey(0), False) as t:
                t.param_store = {id(p): a for p, a in zip(plist, params)}
                logits, kvs = model.forward_collect_kv(_trace.F, tokens)
            kcs = [jax.lax.dynamic_update_slice(
                kc, k.astype(kc.dtype), (slot, zero, zero, zero))
                for kc, (k, _v) in zip(kcs, kvs)]
            vcs = [jax.lax.dynamic_update_slice(
                vc, v.astype(vc.dtype), (slot, zero, zero, zero))
                for vc, (_k, v) in zip(vcs, kvs)]
            valid = jax.lax.dynamic_update_slice(
                valid, jnp.reshape(plen, (1,)), (slot,))
            last = jnp.reshape(jax.lax.dynamic_slice(
                logits, (zero, plen - 1, zero),
                (1, 1, logits.shape[2])), (1, -1))
            t0 = sample_tokens(last, key[None], plen[None], temp[None],
                               top_k)
            toks = jax.lax.dynamic_update_slice(toks, t0, (slot,))
            return kcs, vcs, valid, toks, jnp.reshape(last, (-1,))

        fn = self._jit(pure, donate=(1, 2, 3, 4),
                       hint="prefill@t%dc%d" % (tp, capacity))
        self._prefill_fns[(tp, capacity)] = fn
        return fn

    def _inject_fn(self, tp, capacity):
        fn = self._inject_fns.get((tp, capacity))
        if fn is not None:
            return fn
        top_k = self.top_k
        zero = jnp.int32(0)

        if self._quantize:
            quantize_pages = self._quantize_pages

            def pure(kcs, kss, vcs, vss, valid, toks, k_stack, v_stack,
                     plen, slot, last, key, temp):
                engine.decode_compile_counter.bump()
                L = len(kcs)
                qk = quantize_pages([k_stack[i][None] for i in range(L)],
                                    plen, tp)
                qv = quantize_pages([v_stack[i][None] for i in range(L)],
                                    plen, tp)
                kcs = [jax.lax.dynamic_update_slice(
                    kc, q, (slot, zero, zero, zero))
                    for kc, (q, _s) in zip(kcs, qk)]
                kss = [jax.lax.dynamic_update_slice(
                    ks, s, (slot, zero, zero, zero))
                    for ks, (_q, s) in zip(kss, qk)]
                vcs = [jax.lax.dynamic_update_slice(
                    vc, q, (slot, zero, zero, zero))
                    for vc, (q, _s) in zip(vcs, qv)]
                vss = [jax.lax.dynamic_update_slice(
                    vs, s, (slot, zero, zero, zero))
                    for vs, (_q, s) in zip(vss, qv)]
                valid = jax.lax.dynamic_update_slice(
                    valid, jnp.reshape(plen, (1,)), (slot,))
                t0 = sample_tokens(last[None], key[None], plen[None],
                                   temp[None], top_k)
                toks = jax.lax.dynamic_update_slice(toks, t0, (slot,))
                return kcs, kss, vcs, vss, valid, toks

            fn = self._jit(pure, donate=(0, 1, 2, 3, 4, 5),
                           hint="inject@t%dc%d" % (tp, capacity))
            self._inject_fns[(tp, capacity)] = fn
            return fn

        def pure(kcs, vcs, valid, toks, k_stack, v_stack, plen, slot, last,
                 key, temp):
            engine.decode_compile_counter.bump()
            kcs = [jax.lax.dynamic_update_slice(
                kc, k_stack[i][None].astype(kc.dtype),
                (slot, zero, zero, zero)) for i, kc in enumerate(kcs)]
            vcs = [jax.lax.dynamic_update_slice(
                vc, v_stack[i][None].astype(vc.dtype),
                (slot, zero, zero, zero)) for i, vc in enumerate(vcs)]
            valid = jax.lax.dynamic_update_slice(
                valid, jnp.reshape(plen, (1,)), (slot,))
            t0 = sample_tokens(last[None], key[None], plen[None], temp[None],
                               top_k)
            toks = jax.lax.dynamic_update_slice(toks, t0, (slot,))
            return kcs, vcs, valid, toks

        fn = self._jit(pure, donate=(0, 1, 2, 3),
                       hint="inject@t%dc%d" % (tp, capacity))
        self._inject_fns[(tp, capacity)] = fn
        return fn

    def _extract_fn(self, tp, capacity):
        fn = self._extract_fns.get((tp, capacity))
        if fn is not None:
            return fn
        H, D = self.cache.heads, self.cache.head_dim
        zero = jnp.int32(0)

        if self._quantize:
            def pure(kcs, kss, vcs, vss, slot):
                # prefix entries store fp pages: dequantize on read-out so
                # the PrefixCache format is quantization-agnostic (inject
                # re-quantizes exactly — the max element re-derives the
                # same scale)
                engine.decode_compile_counter.bump()

                def slice_deq(cs, ss):
                    out = []
                    for c, s in zip(cs, ss):
                        page = jax.lax.dynamic_slice(
                            c, (slot, zero, zero, zero), (1, H, tp, D))
                        sc = jax.lax.dynamic_slice(
                            s, (slot, zero, zero, zero), (1, H, 1, 1))
                        out.append((page.astype(jnp.float32) * sc)[0])
                    return jnp.stack(out)

                return slice_deq(kcs, kss), slice_deq(vcs, vss)

            # reads live caches: never donate
            fn = self._jit(pure, donate=(),
                           hint="extract@t%dc%d" % (tp, capacity))
            self._extract_fns[(tp, capacity)] = fn
            return fn

        def pure(kcs, vcs, slot):
            engine.decode_compile_counter.bump()
            ks = jnp.stack([jax.lax.dynamic_slice(
                kc, (slot, zero, zero, zero), (1, H, tp, D))[0]
                for kc in kcs])
            vs = jnp.stack([jax.lax.dynamic_slice(
                vc, (slot, zero, zero, zero), (1, H, tp, D))[0]
                for vc in vcs])
            return ks, vs

        # reads live caches: never donate
        fn = self._jit(pure, donate=(),
                       hint="extract@t%dc%d" % (tp, capacity))
        self._extract_fns[(tp, capacity)] = fn
        return fn

    # ------------------------------------------------------------ warmup
    def warmup(self, prompt_buckets=(), max_tokens=None):
        """Compile ahead of traffic: the decode step at the current (or
        requested) capacity, plus prefill programs for the given pow2
        prompt-length buckets — after this a steady token stream never
        bumps ``engine.decode_compile_counter``."""
        need = max(int(max_tokens or 0),
                   max([int(b) for b in prompt_buckets], default=1) + 1)
        self.cache.ensure_capacity(need + self._spec_margin)
        for b in prompt_buckets:
            stream = GenerationStream([1] * int(b), 1, 0.0, 0, 0)
            slot = self.cache.acquire(stream)
            if slot is None:
                break
            tp = min(next_pow2(int(b)), self.cache.capacity)
            fn = self._prefill_fn(tp, self.cache.capacity)
            params = self._params()
            key = np.asarray(jax.random.PRNGKey(0), np.uint32)
            padded = np.zeros((1, tp), np.int32)
            if self._quantize:
                kcs, kss, vcs, vss, valid, toks, _last = fn(
                    params, self.cache.k, self.cache.k_scale, self.cache.v,
                    self.cache.v_scale, self.cache.valid, self._tok,
                    jnp.asarray(padded), jnp.int32(int(b)), jnp.int32(slot),
                    jnp.asarray(key), jnp.float32(0.0))
                self.cache.update(kcs, vcs, valid, kss, vss)
            else:
                kcs, vcs, valid, toks, _last = fn(
                    params, self.cache.k, self.cache.v, self.cache.valid,
                    self._tok, jnp.asarray(padded), jnp.int32(int(b)),
                    jnp.int32(slot), jnp.asarray(key), jnp.float32(0.0))
                self.cache.update(kcs, vcs, valid)
            self._tok = toks
            if self.prefix is not None:
                # prefix-store (extract) and replay (inject) programs are
                # part of the join path: compile them now too
                if self._quantize:
                    ks, vs = self._extract_fn(tp, self.cache.capacity)(
                        self.cache.k, self.cache.k_scale, self.cache.v,
                        self.cache.v_scale, jnp.int32(slot))
                    kcs, kss, vcs, vss, valid, toks = self._inject_fn(
                        tp, self.cache.capacity)(
                        self.cache.k, self.cache.k_scale, self.cache.v,
                        self.cache.v_scale, self.cache.valid, self._tok,
                        ks, vs, jnp.int32(int(b)), jnp.int32(slot),
                        jnp.asarray(_last), jnp.asarray(key),
                        jnp.float32(0.0))
                    self.cache.update(kcs, vcs, valid, kss, vss)
                else:
                    ks, vs = self._extract_fn(tp, self.cache.capacity)(
                        self.cache.k, self.cache.v, jnp.int32(slot))
                    kcs, vcs, valid, toks = self._inject_fn(
                        tp, self.cache.capacity)(
                        self.cache.k, self.cache.v, self.cache.valid,
                        self._tok, ks, vs, jnp.int32(int(b)),
                        jnp.int32(slot), jnp.asarray(_last),
                        jnp.asarray(key), jnp.float32(0.0))
                    self.cache.update(kcs, vcs, valid)
                self._tok = toks
            self.cache.release(slot)
        if self._draft is not None:
            # draft-side programs (cache fill per prompt bucket + the
            # k-unrolled propose step); the dummy decode below compiles
            # the verify program through the normal speculation path
            self._draft.warm([min(next_pow2(int(b)), self.cache.capacity)
                              for b in prompt_buckets])
        if (self._prefill_chunk is not None
                and self.cache.capacity >= self._prefill_chunk):
            self._warm_chunk()
        # one masked all-free decode dispatch compiles the step program
        # (the verify program when a draft is configured)
        dummy = GenerationStream([1], 1, 0.0, 0, 0)
        slot = self.cache.acquire(dummy)
        if slot is not None:
            self._remaining[slot] = 1
            self._decode_once()
            if self.cache.owner(slot) is dummy:
                self._retire(slot)
        return self

    def _warm_chunk(self):
        """Compile the chunked-prefill program on a throwaway slot (a
        single final chunk: pos0=0, plen=chunk — same program every real
        chunk reuses, only the scalar operands differ)."""
        tc = self._prefill_chunk
        dummy = GenerationStream([1] * tc, 1, 0.0, 0, 0)
        slot = self.cache.acquire(dummy)
        if slot is None:
            return
        fn = self._chunk_fn(tc, self.cache.capacity)
        params = self._params()
        key = np.asarray(jax.random.PRNGKey(0), np.uint32)
        chunk = np.zeros((1, tc), np.int32)
        if self._quantize:
            kcs, kss, vcs, vss, valid, toks = fn(
                params, self.cache.k, self.cache.k_scale, self.cache.v,
                self.cache.v_scale, self.cache.valid, self._tok,
                jnp.asarray(chunk), jnp.int32(0), jnp.int32(tc),
                jnp.int32(slot), jnp.asarray(key), jnp.float32(0.0))
            self.cache.update(kcs, vcs, valid, kss, vss)
        else:
            kcs, vcs, valid, toks = fn(
                params, self.cache.k, self.cache.v, self.cache.valid,
                self._tok, jnp.asarray(chunk), jnp.int32(0), jnp.int32(tc),
                jnp.int32(slot), jnp.asarray(key), jnp.float32(0.0))
            self.cache.update(kcs, vcs, valid)
        self._tok = toks
        self.cache.release(slot)

    # ------------------------------------------------ snapshot interface
    def export_executables(self):
        """Every compiled generative program, tagged for the snapshot
        manifest: [{key, kind, tp, capacity, compiled}] covering decode
        steps AND the join path (prefill/inject/extract buckets) — a warm
        replica must reach its first token with zero compiles."""
        out = []
        for cap, fn in sorted(self._decode_fns.items()):
            c = fn.compiled_for()
            if c is not None:
                out.append({"key": "decode@c%d" % cap, "kind": "decode",
                            "tp": 0, "capacity": int(cap), "compiled": c})
        for cap, fn in sorted(self._verify_fns.items()):
            c = fn.compiled_for()
            if c is not None:
                out.append({"key": "verify@c%d" % cap, "kind": "verify",
                            "tp": 0, "capacity": int(cap), "compiled": c})
        for kind, fns in (("prefill", self._prefill_fns),
                          ("inject", self._inject_fns),
                          ("extract", self._extract_fns)):
            for (tp, cap), fn in sorted(fns.items()):
                c = fn.compiled_for()
                if c is not None:
                    out.append({"key": "%s@t%dc%d" % (kind, tp, cap),
                                "kind": kind, "tp": int(tp),
                                "capacity": int(cap), "compiled": c})
        # chunk programs key on (chunk_len, capacity) like prompt buckets
        for (tc, cap), fn in sorted(self._chunk_fns.items()):
            c = fn.compiled_for()
            if c is not None:
                out.append({"key": "chunk@t%dc%d" % (tc, cap),
                            "kind": "chunk", "tp": int(tc),
                            "capacity": int(cap), "compiled": c})
        if self._draft is not None:
            out.extend(self._draft.export_executables())
        return out

    def preload_executable(self, kind, tp, capacity, compiled):
        """Adopt one deserialized program (snapshot warm start): builds
        the wrapper for its key — cheap, no trace — and installs the
        executable. A mismatched executable recompiles with one warning at
        first use (AotFn's recovery path)."""
        if kind == "decode":
            fn = self._decode_fn(capacity)
        elif kind == "verify":
            fn = self._verify_fn(capacity)
        elif kind == "prefill":
            fn = self._prefill_fn(tp, capacity)
        elif kind == "inject":
            fn = self._inject_fn(tp, capacity)
        elif kind == "extract":
            fn = self._extract_fn(tp, capacity)
        elif kind == "chunk":
            fn = self._chunk_fn(tp, capacity)
        elif kind in ("draftstep", "draftfill"):
            if self._draft is None:
                raise ServeError(
                    "snapshot carries %r programs but this server has no "
                    "draft configured" % kind)
            self._draft.preload_executable(kind, tp, capacity, compiled)
            return
        else:
            raise ServeError("unknown snapshot program kind %r" % kind)
        fn.adopt(compiled)

    def snapshot(self, prefix):
        """Write the AOT serving artifact for this server (checkpoint +
        decode config + every warmed program's serialized executable) —
        see serve.snapshot / cache.snapshot."""
        from ..cache.snapshot import save_snapshot

        return save_snapshot(self, prefix)

    # ------------------------------------------------------------- stats
    def stats(self):
        """Snapshot for ``serve.stats()`` / tools/diagnose.py: generative
        counters on top of the base queue/latency metrics."""
        snap = self.metrics.snapshot()
        snap.update(
            slots=self.slots,
            capacity=self.cache.capacity,
            in_flight=self.cache.num_active,
            tokens_in_flight=self.tokens_in_flight(),
            swap_epoch=self._swap_epoch,
            cache_migrations=self.cache.migrations,
            prefix_hits=self.prefix.hits if self.prefix is not None else None,
            prefix_misses=(self.prefix.misses if self.prefix is not None
                           else None),
            prefix_entries=(len(self.prefix) if self.prefix is not None
                            else None),
            decode_compile_counter=engine.decode_compile_counter.count,
            verify_dispatches=engine.verify_dispatch_counter.count,
            spec_k=self.spec_k if self._draft is not None else None,
            draft=(type(self._draft).__name__
                   if self._draft is not None else None),
            prefill_chunk=self._prefill_chunk,
            chunk_queue_depth=len(self._chunk_jobs),
            quantize=self._quantize,
            kv_cache_bytes=self.cache.nbytes(),
            kv_cache_bytes_unquantized=self.cache.nbytes_unquantized(),
            running=(self._loop_thread is not None
                     and self._loop_thread.is_alive()),
        )
        return snap
