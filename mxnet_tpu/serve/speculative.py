"""Draft proposers for speculative decode (serve.GenerativeServer).

Speculative decoding amortizes the target model over k tokens per verify
dispatch: a cheap DRAFT proposes k-1 tokens per slot, the target scores
the whole window in ONE wide ``decode_step_speculative`` dispatch, and the
longest sampled-prefix-equals-drafted-prefix is accepted (the first
mismatching row's sample IS the resample — for a deterministic draft the
proposal distribution is one-hot, so "sample y ~ p, accept iff y == d,
else emit y" is exactly the standard rejection-sampling identity:
accept w.p. p(d), residual norm(max(p - q, 0)) = p with d masked out).
Greedy requests therefore emit BYTE-IDENTICAL streams to plain greedy
decode, and sampled requests emit the same per-(seed, position) tokens as
the plain path — each emitted token is sampled at its own sequence
position with the slot key folded exactly as ``decode_step_fixed`` would.

Two drafts, one protocol (``join``/``propose``/``release``/``warm``):

* ``NGramDraft`` — HOST-side order-n pattern matcher over each stream's
  own prompt+generated history. Zero extra dispatches: a speculation
  round is ONE verify dispatch. The right draft when prompts are
  repetitive (code, logs, templated text) or when no small model exists.
* ``ModelDraft`` — a smaller ``GPTModel``-API model with its OWN paged
  KV cache mirroring the target's slots/capacity. One multi-step dispatch
  per round: k single-token steps UNROLLED inside one traced program
  (the k-th step re-decodes the last proposal purely to write its K/V —
  the draft cache would otherwise hold a hole at ``valid+k-1`` after a
  full accept). Draft rollback is the same trick as the target's: the
  shared ``valid_len`` masks rejected positions and the next window
  overwrites them in place.

Both drafts keep every shape fixed — the k-window, the caches, the slot
batch — so steady-state speculation is exactly ``1 + dispatches_per_round``
dispatches per round with ZERO retrace (``engine.decode_compile_counter``
flat, ``engine.verify_dispatch_counter`` counting verify dispatches at the
call site; tests/test_speculative.py pins both with the watchdog armed).
"""
from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp

from .. import _trace, engine
from .batcher import ServeError
from .kv_cache import PagedKVCache

__all__ = ["NGramDraft", "ModelDraft", "ngram_propose"]


def ngram_propose(history, n, order=3):
    """Propose ``n`` continuation tokens for one stream by suffix matching:
    find the most recent earlier occurrence of the last ``m`` tokens
    (longest m ≤ order first) and propose the token that followed it;
    extend iteratively. Falls back to repeating the last token — a wrong
    proposal only costs acceptance rate, never correctness (the verify
    pass emits its own sample on mismatch)."""
    out = []
    h = list(history)   # caller passes python ints; copy only for append
    for _ in range(n):
        nxt = None
        for m in range(min(order, len(h) - 1), 0, -1):
            ctx = h[-m:]
            for i in range(len(h) - m - 1, -1, -1):
                if h[i:i + m] == ctx:
                    nxt = h[i + m]
                    break
            if nxt is not None:
                break
        if nxt is None:
            nxt = h[-1] if h else 0
        out.append(nxt)
        h.append(nxt)
    return out


class NGramDraft:
    """Host-side n-gram draft: proposes from each stream's own history
    (prompt + generated tokens, which already ends with the slot's current
    input token). No device state, no dispatches — ``dispatches_per_round``
    is 0, so a speculation round costs exactly ONE (verify) dispatch."""

    needs_history = True
    dispatches_per_round = 0

    def __init__(self, order=3):
        self.order = int(order)
        self._server = None

    def bind(self, server):
        self._server = server

    def ensure_capacity(self):
        pass

    def join(self, slot, stream, padded, t0_len):
        pass

    def release(self, slot):
        pass

    def warm(self, tp_buckets=()):
        pass

    def propose(self, histories, k):
        """(slots, k-1) int32 host proposals; rows with no history (free
        slots) propose zeros — the verify mask ignores them."""
        slots = len(histories)
        out = np.zeros((slots, max(0, k - 1)), np.int32)
        if k <= 1:
            return out
        for s, h in enumerate(histories):
            if h:
                out[s] = ngram_propose(h, k - 1, self.order)
        return out

    # ----------------------------------------------- snapshot interface
    def export_executables(self):
        return []

    def preload_executable(self, kind, tp, capacity, compiled):
        raise ServeError("NGramDraft has no compiled programs (kind %r)"
                         % kind)


class ModelDraft:
    """Device draft: a smaller model speaking the same fixed-capacity
    decode protocol (``decode_state_spec``/``forward_collect_kv``/
    ``decode_step_fixed``) with its own slot-paged KV cache mirroring the
    target server's slots and capacity buckets. The draft model must share
    the target's vocabulary and cover its ``max_length``."""

    needs_history = False
    dispatches_per_round = 1

    def __init__(self, model):
        self.model = model
        self._plist = list(model.collect_params().values())
        self._spec = model.decode_state_spec()
        self._server = None
        self.cache = None
        self._step_fns = {}   # capacity -> k-unrolled propose program
        self._fill_fns = {}   # (tp, capacity) -> whole-prompt cache fill

    def bind(self, server):
        self._server = server
        if self._spec["max_length"] < server.cache.max_capacity:
            raise ServeError(
                "draft max_length=%d < target max_length=%d — the draft "
                "must cover every target position it speculates at"
                % (self._spec["max_length"], server.cache.max_capacity))
        self.cache = PagedKVCache(
            self._spec["layers"], self._spec["heads"],
            self._spec["head_dim"], server.slots, server.cache.max_capacity,
            dtype=self._spec["dtype"])

    def ensure_capacity(self):
        """Mirror the target cache's capacity bucket (same pow2, so the
        draft migrates exactly when the target does)."""
        self.cache.ensure_capacity(self._server.cache.capacity)

    def release(self, slot):
        pass

    # -------------------------------------------------------- programs
    def _step_fn(self, capacity):
        fn = self._step_fns.get(capacity)
        if fn is not None:
            return fn
        model, plist = self.model, self._plist
        k = self._server.spec_k

        def pure(params, kcs, vcs, valid, toks):
            # trace-time bump: the zero-steady-state-retrace proof covers
            # the draft program too (tests/test_speculative.py)
            engine.decode_compile_counter.bump()
            props = []
            x = toks
            with _trace.trace_scope(jax.random.PRNGKey(0), False) as t:
                t.param_store = {id(p): a for p, a in zip(plist, params)}
                # k UNROLLED greedy steps in one dispatch: steps 0..k-2
                # propose d_1..d_{k-1}; step k-1 re-decodes d_{k-1} only
                # to write its K/V at valid+k-1 (else a full accept next
                # round would attend over a hole) — its argmax is dropped
                for j in range(k):
                    logits, kcs, vcs = model.decode_step_fixed(
                        _trace.F, x, kcs, vcs, valid + j)
                    x = jnp.argmax(logits, axis=-1).astype(jnp.int32)
                    if j < k - 1:
                        props.append(x)
            if props:
                drafts = jnp.stack(props, axis=1)
            else:
                drafts = jnp.zeros((toks.shape[0], 0), jnp.int32)
            return kcs, vcs, drafts

        fn = self._server._jit(pure, donate=(1, 2),
                               hint="draftstep@c%d" % capacity)
        self._step_fns[capacity] = fn
        return fn

    def _fill_fn(self, tp, capacity):
        fn = self._fill_fns.get((tp, capacity))
        if fn is not None:
            return fn
        model, plist = self.model, self._plist
        zero = jnp.int32(0)

        def pure(params, kcs, vcs, tokens, slot):
            engine.decode_compile_counter.bump()
            with _trace.trace_scope(jax.random.PRNGKey(0), False) as t:
                t.param_store = {id(p): a for p, a in zip(plist, params)}
                _logits, kvs = model.forward_collect_kv(_trace.F, tokens)
            kcs = [jax.lax.dynamic_update_slice(
                kc, kv[0].astype(kc.dtype), (slot, zero, zero, zero))
                for kc, kv in zip(kcs, kvs)]
            vcs = [jax.lax.dynamic_update_slice(
                vc, kv[1].astype(vc.dtype), (slot, zero, zero, zero))
                for vc, kv in zip(vcs, kvs)]
            return kcs, vcs

        fn = self._server._jit(pure, donate=(1, 2),
                               hint="draftfill@t%dc%d" % (tp, capacity))
        self._fill_fns[(tp, capacity)] = fn
        return fn

    # ------------------------------------------------------- scheduling
    def join(self, slot, stream, padded, t0_len):
        """Fill the draft's page for a joining stream: one whole-prompt
        dispatch (the draft is small by design — chunking it would cost
        more in round trips than it saves). The draft has no prefix cache;
        a target prefix hit still pays this one small fill. Positions
        beyond the prompt hold stale garbage masked by the shared
        ``valid_len`` and overwritten by later windows."""
        self.ensure_capacity()
        engine.dispatch_counter.bump()
        fn = self._fill_fn(padded.shape[1], self.cache.capacity)
        params = [p.data()._data for p in self._plist]
        kcs, vcs = fn(params, self.cache.k, self.cache.v,
                      jnp.asarray(padded), jnp.int32(slot))
        self.cache.update(kcs, vcs, self.cache.valid)

    def propose(self, histories, k):
        """(slots, k-1) device proposals via ONE k-unrolled dispatch,
        positions taken from the TARGET's valid_len (the shared notion of
        the live prefix — draft rollback is implicit in it)."""
        srv = self._server
        engine.dispatch_counter.bump()
        fn = self._step_fn(self.cache.capacity)
        params = [p.data()._data for p in self._plist]
        kcs, vcs, drafts = fn(params, self.cache.k, self.cache.v,
                              srv.cache.valid, srv._tok)
        self.cache.update(kcs, vcs, self.cache.valid)
        return drafts

    def warm(self, tp_buckets=()):
        """Compile the draft programs ahead of traffic (fill per prompt
        bucket + the k-unrolled step at the current capacity)."""
        self.ensure_capacity()
        params = [p.data()._data for p in self._plist]
        for tp in tp_buckets:
            fn = self._fill_fn(int(tp), self.cache.capacity)
            kcs, vcs = fn(params, self.cache.k, self.cache.v,
                          jnp.zeros((1, int(tp)), jnp.int32), jnp.int32(0))
            self.cache.update(kcs, vcs, self.cache.valid)
        fn = self._step_fn(self.cache.capacity)
        kcs, vcs, _d = fn(params, self.cache.k, self.cache.v,
                          self._server.cache.valid, self._server._tok)
        self.cache.update(kcs, vcs, self.cache.valid)

    # ----------------------------------------------- snapshot interface
    def export_executables(self):
        """Draft programs for the snapshot manifest (kinds ``draftstep``/
        ``draftfill``) — a warm replica speculates with zero compiles."""
        out = []
        for cap, fn in sorted(self._step_fns.items()):
            c = fn.compiled_for()
            if c is not None:
                out.append({"key": "draftstep@c%d" % cap,
                            "kind": "draftstep", "tp": 0,
                            "capacity": int(cap), "compiled": c})
        for (tp, cap), fn in sorted(self._fill_fns.items()):
            c = fn.compiled_for()
            if c is not None:
                out.append({"key": "draftfill@t%dc%d" % (tp, cap),
                            "kind": "draftfill", "tp": int(tp),
                            "capacity": int(cap), "compiled": c})
        return out

    def preload_executable(self, kind, tp, capacity, compiled):
        if kind == "draftstep":
            fn = self._step_fn(capacity)
        elif kind == "draftfill":
            fn = self._fill_fn(tp, capacity)
        else:
            raise ServeError("unknown draft program kind %r" % kind)
        fn.adopt(compiled)
