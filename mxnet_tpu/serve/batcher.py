"""Dynamic request batcher (ref: mxnet-model-server's BatchAggregator —
mms/service_manager + the TF-Serving shared-batch-scheduler shape).

Single requests land in a bounded thread-safe queue; a worker coalesces
them into the largest bucket that fits under a ``max_wait_ms`` deadline —
the first request in a window starts the clock, late arrivals ride along
until the batch fills or the deadline passes. Admission control sheds load
at enqueue time (typed ``ServerBusy``, never silent drops); each request
carries its own timeout and is failed with ``ServeTimeout`` if a result
hasn't arrived in time. Dispatch (pad → compiled bucket program → split
results back per request) is delegated to the callable the server wires in,
run on a small per-replica dispatcher pool so replicas overlap.
"""
from __future__ import annotations

import threading
import time
from collections import deque
from concurrent.futures import ThreadPoolExecutor


class ServeError(RuntimeError):
    """Base class for typed serving failures."""


class ServerBusy(ServeError):
    """Admission control: the request queue is full (load shedding)."""


class ServeTimeout(ServeError):
    """The per-request deadline passed before a result arrived."""


class _Request:
    __slots__ = ("inputs", "n", "t_submit", "t_dequeue", "deadline",
                 "priority", "trace", "_event", "_result", "_error", "_done")

    def __init__(self, inputs, n, timeout_ms, priority=0):
        self.inputs = inputs
        self.n = n  # rows this request contributes to a batch
        self.t_submit = time.perf_counter()
        self.t_dequeue = None   # stamped when a batch claims this request
        self.deadline = (self.t_submit + timeout_ms / 1e3
                         if timeout_ms else None)
        self.priority = int(priority)  # higher = more urgent
        # observability.RequestTrace riding with the request (None when
        # tracing is off); the server attaches it at submit and closes
        # queue/pad/dispatch spans as the request moves through
        self.trace = None
        self._event = threading.Event()
        self._result = None
        self._error = None
        self._done = False

    @property
    def trace_id(self):
        return self.trace.trace_id if self.trace is not None else None

    def timing(self):
        """Per-request breakdown (queue_ms/pad_ms/dispatch_ms/tokens) —
        the response-object surface of the trace; None when tracing is
        disabled."""
        return self.trace.timing() if self.trace is not None else None

    # finish() is idempotent under race (batcher result vs. timeout sweep):
    # first writer wins, the event releases every waiter exactly once
    def finish(self, result=None, error=None):
        if self._done:
            return False
        self._done = True
        self._result = result
        self._error = error
        self._event.set()
        return True

    def expired(self, now=None):
        return self.deadline is not None \
            and (now or time.perf_counter()) > self.deadline

    def result(self, timeout_s=None):
        if not self._event.wait(timeout_s):
            raise ServeTimeout("no result within %.1fs" % (timeout_s or 0))
        if self._error is not None:
            raise self._error
        return self._result

    def done(self):
        return self._done


class DynamicBatcher:
    """Coalesces requests and hands batches to ``dispatch_fn``.

    dispatch_fn(requests, total_rows) is called on a dispatcher thread with
    a list of requests whose rows sum to ≤ max_batch; it must finish() every
    request (result or error).
    """

    def __init__(self, dispatch_fn, max_batch, max_wait_ms=2.0,
                 max_queue=256, num_dispatchers=1, metrics=None):
        self._dispatch_fn = dispatch_fn
        self._max_batch = int(max_batch)
        self._max_wait_s = float(max_wait_ms) / 1e3
        self._max_queue = int(max_queue)
        self._metrics = metrics
        self._queue = deque()
        self._queued_rows = 0
        self._cond = threading.Condition()
        self._stop = False
        self._worker = None
        # in-flight bound: without it the worker would drain the admission
        # queue into the executor's unbounded backlog and load shedding
        # would never fire — requests must WAIT IN the bounded queue while
        # every dispatcher is busy
        self._num_dispatchers = max(1, int(num_dispatchers))
        self._inflight = threading.Semaphore(self._num_dispatchers)
        self._pool = ThreadPoolExecutor(self._num_dispatchers,
                                        thread_name_prefix="serve-dispatch")
        # requests claimed by a dispatcher but not yet finished — the set
        # stop() sweeps so a process exiting mid-drain can never strand a
        # caller blocked on result() (guarded by _cond)
        self._claimed = set()

    # ------------------------------------------------------------ lifecycle
    def start(self):
        if self._worker is None or not self._worker.is_alive():
            self._stop = False
            if self._pool is None:
                # a previous stop() tore the executor down — restartable
                # start/stop cycles must not submit to a dead pool (and
                # must not leak the old pool's threads)
                self._inflight = threading.Semaphore(self._num_dispatchers)
                self._pool = ThreadPoolExecutor(
                    self._num_dispatchers,
                    thread_name_prefix="serve-dispatch")
            self._worker = threading.Thread(target=self._loop, daemon=True,
                                            name="serve-batcher")
            self._worker.start()
        return self

    def stop(self, drain=True, timeout_s=5.0, reason="server stopped"):
        """Stop the worker and tear down the dispatcher pool.

        drain=True lets the worker dispatch what is already queued before
        exiting; drain=False rejects the queue immediately. Either way the
        worker join is bounded by ``timeout_s`` and anything still queued
        after it is rejected with ServeError — stop() never strands a
        caller blocked on ``result()``. Requests a dispatcher had already
        CLAIMED when the bound expired (the process-exit-mid-drain window:
        a dispatch wedged past the join timeout used to leave its riders
        with no terminal error) are swept with a typed
        ``ServeError("worker retired: ...")`` — a fleet router reads that
        as retryable and re-lands the request on a sibling replica.
        ``reason`` names who stopped us in every rejection. Idempotent;
        start() after stop() builds a fresh pool, so repeated cycles leak
        no threads."""
        with self._cond:
            self._stop = True
            if not drain:
                pending = list(self._queue)
                self._queue.clear()
                self._queued_rows = 0
            else:
                pending = []
            self._cond.notify_all()
        err = ServeError(reason)
        for r in pending:
            r.finish(error=err)
        worker, self._worker = self._worker, None
        if worker is not None:
            worker.join(timeout=timeout_s)
        # drain-then-reject: whatever the worker did not dispatch within
        # the bound (or was enqueued in the closing window) is rejected
        with self._cond:
            leftover = list(self._queue)
            self._queue.clear()
            self._queued_rows = 0
        for r in leftover:
            r.finish(error=err)
        pool, self._pool = self._pool, None
        if pool is not None:
            # dispatchers hold requests whose callers may be blocked on
            # result(): give in-flight work a bounded window to finish
            # naturally, then SWEEP — shutdown(wait=True) with a wedged
            # dispatch would block stop() forever and the process would
            # exit mid-drain with the riders stranded
            pool.shutdown(wait=False)
            deadline = time.perf_counter() + timeout_s
            while time.perf_counter() < deadline:
                with self._cond:
                    if not self._claimed:
                        break
                time.sleep(0.005)
            with self._cond:
                stranded, self._claimed = list(self._claimed), set()
            retired = ServeError("worker retired: %s" % reason)
            for r in stranded:
                # idempotent finish: a dispatch that completes late is a
                # harmless no-op against this terminal error
                r.finish(error=retired)
            # bounded join so a clean stop leaves zero serve-dispatch
            # threads behind (test_concurrency's cycle pin); a wedged
            # dispatch past the bound stays a daemon and is abandoned
            join_by = time.perf_counter() + max(0.5, timeout_s / 2.0)
            for t in list(getattr(pool, "_threads", ())):
                t.join(timeout=max(0.0, join_by - time.perf_counter()))

    # ------------------------------------------------------------ admission
    def submit(self, inputs, n_rows, timeout_ms=None, priority=0,
               trace=None):
        """Enqueue one request (``n_rows`` ≥ 1 coalescible rows). Returns a
        future-like handle; raises ServerBusy when the queue is full —
        shedding at the door keeps tail latency bounded instead of letting
        the queue grow into a multi-deadline backlog.

        ``priority`` (higher = more urgent) orders the queue: dispatch
        drains the highest class first, FIFO within a class. When the
        queue is full and a strictly LOWER-priority request is waiting,
        admission is SLO-aware preemptive shedding: the victim is the
        lowest-priority queued request with the least deadline slack (the
        one most likely to miss its SLO anyway) — it gets ServerBusy and
        the new request takes its place.

        ``trace``: an observability.RequestTrace to ride with the request —
        attached BEFORE enqueue so the queue span can never be missed by an
        immediate dispatch."""
        req = _Request(inputs, int(n_rows), timeout_ms, priority)
        req.trace = trace
        evicted = []
        with self._cond:
            if self._stop:
                raise ServeError("server stopped")
            while self._queued_rows + req.n > self._max_queue:
                victim = min(
                    self._queue,
                    key=lambda r: (r.priority,
                                   r.deadline if r.deadline is not None
                                   else float("inf")),
                    default=None)
                if victim is None or victim.priority >= req.priority:
                    if self._metrics:
                        self._metrics.record_shed()
                    raise ServerBusy(
                        "queue full (%d rows queued, max %d)"
                        % (self._queued_rows, self._max_queue))
                self._queue.remove(victim)
                self._queued_rows -= victim.n
                evicted.append(victim)
            # sorted insert: before the first strictly-lower class (stable
            # FIFO within a class; O(queue) on a bounded queue)
            idx = next((i for i, r in enumerate(self._queue)
                        if r.priority < req.priority), len(self._queue))
            self._queue.insert(idx, req)
            self._queued_rows += req.n
            if self._metrics:
                self._metrics.record_admit(rows=req.n)
                self._metrics.record_queue_depth(self._queued_rows)
            self._cond.notify()
        for v in evicted:
            if v.finish(error=ServerBusy(
                    "shed from the queue by a priority-%d arrival"
                    % req.priority)) and self._metrics:
                self._metrics.record_shed()
        return req

    def queue_depth(self):
        with self._cond:
            return self._queued_rows

    # ------------------------------------------------------------ worker
    def _take_batch(self):
        """Block until a deadline-ripe batch is ready; None on stop. Runs
        under the condition lock except while waiting."""
        with self._cond:
            while True:
                if self._stop and not self._queue:
                    return None
                # drop requests that expired while queued — dispatching them
                # would waste a bucket slot on a caller that already left.
                # Whole-queue sweep: with priority classes an expired
                # request can sit behind a higher class, not just at head.
                now = time.perf_counter()
                for req in [r for r in self._queue if r.expired(now)]:
                    self._queue.remove(req)
                    self._queued_rows -= req.n
                    if req.finish(error=ServeTimeout(
                            "timed out after %.1fms in queue"
                            % ((now - req.t_submit) * 1e3))) and self._metrics:
                        self._metrics.record_timeout()
                if not self._queue:
                    if self._stop:
                        return None
                    self._cond.wait(0.05)
                    continue
                head = self._queue[0]
                batch_deadline = head.t_submit + self._max_wait_s
                if self._queued_rows >= self._max_batch \
                        or now >= batch_deadline or self._stop:
                    batch, rows = [], 0
                    while self._queue and rows + self._queue[0].n \
                            <= self._max_batch:
                        req = self._queue.popleft()
                        self._queued_rows -= req.n
                        batch.append(req)
                        rows += req.n
                    if self._metrics:
                        self._metrics.record_queue_depth(self._queued_rows)
                    if batch:
                        # queue-span close: one clock read per batch
                        t_deq = time.perf_counter()
                        for req in batch:
                            req.t_dequeue = t_deq
                        return batch, rows
                    # head alone exceeds max_batch: caller bug — fail it
                    req = self._queue.popleft()
                    self._queued_rows -= req.n
                    req.finish(error=ServeError(
                        "request of %d rows exceeds max batch %d"
                        % (req.n, self._max_batch)))
                    continue
                self._cond.wait(min(0.05, batch_deadline - now))

    def _run_dispatch(self, batch, rows):
        try:
            self._dispatch_fn(batch, rows)
        finally:
            with self._cond:
                self._claimed.difference_update(batch)
            self._inflight.release()

    def _loop(self):
        while True:
            # claim a dispatcher slot BEFORE popping a batch, so requests
            # keep aging (and shedding) in the bounded queue when saturated
            while not self._inflight.acquire(timeout=0.05):
                with self._cond:
                    if self._stop and not self._queue:
                        return
            got = self._take_batch()
            if got is None:
                self._inflight.release()
                return
            batch, rows = got
            pool = self._pool
            if pool is None:
                # stop() tore the pool down after a bounded join timed
                # out — reject rather than dispatch into nothing
                err = ServeError("server stopped")
                for req in batch:
                    req.finish(error=err)
                self._inflight.release()
                return
            with self._cond:
                self._claimed.update(batch)
            pool.submit(self._run_dispatch, batch, rows)
