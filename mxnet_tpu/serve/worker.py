"""serve.worker — one fleet replica as a subprocess (ref: mxnet-model-server
worker processes behind its frontend router).

A worker wraps ONE live server (ModelServer or GenerativeServer) and extends
its MetricsHTTPServer listener into the fleet data plane, so a replica has a
single port for traffic, control and observability:

* data  — POST ``/predict`` (npz in → npz out, dtype-exact: bf16 crosses the
  wire as bf16), POST ``/generate`` (JSON in → JSON token list out);
* control — POST ``/swap`` (push a checkpoint as raw npz bytes; structural
  validation rejects a mismatched tree with 409 and the old weights keep
  serving), POST ``/drain`` (stop admitting, finish what's in flight),
  GET ``/prefix/export`` / POST ``/prefix/import`` (prefix-cache KV
  migration for worker retirement), POST ``/shutdown``;
* observability — the inherited ``/metrics`` ``/snapshot`` ``/health``
  plus GET ``/server_stats`` (this server's ``stats()`` dict — what the
  autoscaler reads for p95 queue pressure and shed rate).

Launch: ``python -m mxnet_tpu.serve.worker --snapshot PREFIX`` (AOT
snapshot-warm: zero compiles to first request, watchdog armed) or
``--factory module:fn`` / ``--factory path/to/file.py:fn`` where ``fn()``
returns a ready server (the dryrun/test path). The process prints ONE
ready line of JSON (``{"ready": true, "port": N, "pid": P, ...}``) on
stdout and then serves until ``/shutdown`` or a signal.

Typed errors map to statuses the router understands: 503 ServerBusy /
draining (retry a sibling), 504 ServeTimeout, 409 SwapError (checkpoint
rejected), 500 anything else. Connection-level failures (the worker died)
surface on the router side as ``WorkerGone``.
"""
from __future__ import annotations

import argparse
import json
import os
import sys
import tempfile
import threading

import numpy as np

from ..checkpoint import SwapError
from ..util import dumps_npz_exact, loads_npz_exact
from .batcher import ServeError, ServerBusy, ServeTimeout


def _json_reply(status, obj):
    return status, "application/json", json.dumps(
        obj, sort_keys=True, default=str).encode("utf-8")


def _error_reply(e):
    """Typed serve failures → the status codes the fleet router routes on."""
    if isinstance(e, ServerBusy):
        status = 503
    elif isinstance(e, ServeTimeout):
        status = 504
    elif isinstance(e, SwapError):
        status = 409
    else:
        status = 500
    return _json_reply(status, {"error": type(e).__name__, "message": str(e)})


class ServeWorker:
    """One replica: a live server plus the fleet routes on its listener.

    Also usable in-process (tests construct a ServeWorker around a local
    server to exercise the HTTP surface without a subprocess); the module
    ``main()`` is the real fleet path — one worker per process, spawned
    and reaped by ``serve.fleet.FleetRouter``.
    """

    def __init__(self, server, port=0):
        self.server = server
        # duck-typed: only the generative scheduler migrates prefix KV
        self.kind = ("generative" if hasattr(server, "import_prefixes")
                     else "model")
        self.draining = False
        if server._metrics_port is None:
            server._metrics_port = int(port)
        server.start()
        self.http = server.metrics_http
        if self.http is None:
            raise ServeError("worker needs the server's HTTP listener — "
                             "pass metrics_port (0 = ephemeral) or let the "
                             "worker set it before start()")
        # /health gains the draining flag: a router must stop picking a
        # draining replica even though it is still alive and warm
        self.http.health_fn = self._health
        self.http.post_routes["/predict"] = self._r_predict
        self.http.post_routes["/generate"] = self._r_generate
        self.http.post_routes["/swap"] = self._r_swap
        self.http.post_routes["/drain"] = self._r_drain
        self.http.post_routes["/shutdown"] = self._r_shutdown
        self.http.get_routes["/server_stats"] = self._r_stats
        self.http.get_routes["/prefix/export"] = self._r_prefix_export
        self.http.post_routes["/prefix/import"] = self._r_prefix_import
        self._shutdown = threading.Event()

    @property
    def port(self):
        return self.http.port

    def describe(self):
        """The READY line payload (and what tests assert a spawn reports)."""
        h = self.server.health()
        return {"ready": True, "port": self.port, "pid": os.getpid(),
                "kind": self.kind, "warm": bool(h.get("warm")),
                "name": self.server.name}

    # ------------------------------------------------------------- routes
    def _health(self):
        h = self.server.health()
        h["draining"] = self.draining
        return h

    def _r_stats(self, query):
        return _json_reply(200, self.server.stats())

    def _r_predict(self, body, query):
        if self.draining:
            return _error_reply(ServerBusy("draining"))
        try:
            arrays = loads_npz_exact(body)
            xs = [arrays[k] for k in sorted(arrays, key=lambda k: int(k[1:]))]
            outs = self.server.predict(*xs)
            if not isinstance(outs, (list, tuple)):
                outs = [outs]
            return 200, "application/octet-stream", dumps_npz_exact(
                {"y%d" % i: o for i, o in enumerate(outs)})
        except Exception as e:
            return _error_reply(e)

    def _r_generate(self, body, query):
        if self.draining:
            return _error_reply(ServerBusy("draining"))
        try:
            req = json.loads(body.decode("utf-8"))
            stream = self.server.submit(
                np.asarray(req["prompt"], np.int32),
                max_new_tokens=int(req.get("max_new_tokens", 16)),
                temperature=float(req.get("temperature", 0.0)),
                seed=int(req.get("seed", 0)),
                priority=int(req.get("priority", 0)),
                timeout_ms=req.get("timeout_ms"))
            toks = stream.result(timeout_s=float(req.get("result_timeout_s",
                                                         60.0)))
            return _json_reply(200, {"tokens": toks})
        except Exception as e:
            return _error_reply(e)

    def _r_swap(self, body, query):
        """Weight hot-swap: the checkpoint travels as the request body (raw
        npz bytes). Rejection (409) leaves the old weights serving — the
        validate happens before any parameter is touched."""
        try:
            fd, path = tempfile.mkstemp(suffix=".params",
                                        prefix="mxtpu-swap-")
            try:
                with os.fdopen(fd, "wb") as f:
                    f.write(body)
                epoch = self.server.swap_parameters(path)
            finally:
                os.unlink(path)
            return _json_reply(200, {"swap_epoch": epoch})
        except Exception as e:
            return _error_reply(e)

    def _r_drain(self, body, query):
        """Stop admitting new work (data routes 503) but keep finishing
        what's in flight — the first half of drain-then-retire. The router
        polls /health until the load gauges hit zero, migrates prefixes,
        then POSTs /shutdown."""
        self.draining = True
        g = self.server.metrics.load_gauges()
        g["draining"] = True
        return _json_reply(200, g)

    def _r_prefix_export(self, query):
        if self.kind != "generative":
            return _json_reply(200, {"entries": 0})
        arrays, n = {}, 0
        for tok, k_stack, v_stack, plen, last in self.server.export_prefixes():
            arrays["tok%d" % n] = tok
            arrays["k%d" % n] = k_stack
            arrays["v%d" % n] = v_stack
            arrays["plen%d" % n] = np.asarray(plen, np.int64)
            arrays["last%d" % n] = last
            n += 1
        arrays["count"] = np.asarray(n, np.int64)
        return 200, "application/octet-stream", dumps_npz_exact(arrays)

    def _r_prefix_import(self, body, query):
        if self.kind != "generative":
            return _json_reply(200, {"imported": 0})
        arrays = loads_npz_exact(body)
        entries = []
        for i in range(int(arrays.get("count", 0))):
            entries.append((arrays["tok%d" % i], arrays["k%d" % i],
                            arrays["v%d" % i], int(arrays["plen%d" % i]),
                            arrays["last%d" % i]))
        return _json_reply(200,
                           {"imported": self.server.import_prefixes(entries)})

    def _r_shutdown(self, body, query):
        # reply first, then let the main thread tear down — the HTTP
        # listener must not be closed under the handler's feet
        self._shutdown.set()
        return _json_reply(200, {"ok": True})

    # ---------------------------------------------------------- lifecycle
    def wait(self):
        """Block until /shutdown (the module main's serve loop)."""
        self._shutdown.wait()

    def close(self, reason="worker retired"):
        self.server.stop(reason=reason)


def _resolve(spec):
    """``module.sub:fn`` or ``path/to/file.py:fn`` → the callable. The
    file-path form exists because tools/ and tests/ are not packages."""
    target, _, attr = spec.rpartition(":")
    if not target:
        raise ValueError("factory spec %r needs module:fn or file.py:fn"
                         % spec)
    if target.endswith(".py"):
        import importlib.util
        name = "_mxtpu_worker_factory_%s" % (
            os.path.basename(target)[:-3].replace("-", "_"))
        mod_spec = importlib.util.spec_from_file_location(name, target)
        mod = importlib.util.module_from_spec(mod_spec)
        mod_spec.loader.exec_module(mod)
    else:
        import importlib
        mod = importlib.import_module(target)
    return getattr(mod, attr)


def main(argv=None):
    p = argparse.ArgumentParser(
        prog="python -m mxnet_tpu.serve.worker",
        description="one fleet replica: serve a model over HTTP until "
                    "shutdown")
    p.add_argument("--snapshot", default=None,
                   help="AOT serving snapshot prefix (serve.load(..., "
                        "snapshot=True): deserialized programs, zero "
                        "compiles to first request)")
    p.add_argument("--factory", default=None,
                   help="module:fn or file.py:fn returning a ready server")
    p.add_argument("--model", default=None,
                   help="factory for the decode model (generative "
                        "snapshots carry params+programs, not code)")
    p.add_argument("--kwargs", default="{}",
                   help="JSON kwargs for the snapshot server constructor")
    p.add_argument("--port", type=int, default=0,
                   help="listener port (0 = ephemeral, reported on the "
                        "READY line)")
    args = p.parse_args(argv)
    if (args.snapshot is None) == (args.factory is None):
        p.error("exactly one of --snapshot / --factory")
    if args.factory is not None:
        server = _resolve(args.factory)()
    else:
        from . import load
        model = _resolve(args.model)() if args.model else None
        server = load(args.snapshot, snapshot=True, model=model,
                      **json.loads(args.kwargs))
    # snapshot-warm replicas must reach their first request with zero
    # compiles — arm the watchdog so any post-spawn retrace is an audited
    # anomaly (and scrapeable via /snapshot for the fleet bench to assert)
    from ..observability import arm_watchdog
    arm_watchdog()
    worker = ServeWorker(server, port=args.port)
    print(json.dumps(worker.describe(), sort_keys=True), flush=True)
    try:
        worker.wait()
    except KeyboardInterrupt:
        pass
    worker.close()
    return 0


if __name__ == "__main__":
    sys.exit(main())
