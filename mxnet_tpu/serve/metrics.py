"""Serving observability (ref: mxnet-model-server metrics — QPS, latency
percentiles, queue telemetry — mms/metrics/*; here collected in-process).

One ``ServeMetrics`` instance per server/pool. Two export paths:

* ``snapshot()`` — the ``serve.stats()`` dict tools/diagnose.py prints:
  request/batch counters, shed/timeout/error counts, p50/p95/p99 request
  latency, mean batch-fill ratio, current queue depth;
* profiler counter events — when the profiler is running, queue depth and
  shed/timeout totals are emitted as Chrome-trace 'C' tracks (and each
  dispatched batch gets a ``serve[...]`` duration event from the pool via
  profiler.serve_scope), so serving pressure lines up with the XLA trace.

Latency percentiles come from a bounded ring of the most recent ``window``
request latencies — O(1) per request, no unbounded growth in long-running
servers (the same concern graphlint GL006 polices for caches).

These objects are ABSORBED by ``mxnet_tpu.observability``: the registry's
``serve`` collector reads every live server's ``stats()`` (this module's
snapshots) at snapshot time, so they appear in
``observability.snapshot()``/``prometheus()`` and the opt-in ``/metrics``
endpoint without any push-site wiring here — this module stays the
recording surface, the registry is the export surface (GL009 polices new
metric state landing anywhere else).
"""
from __future__ import annotations

import threading

from .. import profiler


class ServeMetrics:
    def __init__(self, name="serve", window=2048):
        self.name = name
        self._lock = threading.Lock()
        self._window = int(window)
        self._lat = [0.0] * self._window  # ring buffer, ms
        self._lat_n = 0                   # total latencies ever recorded
        self.requests = 0                 # admitted requests
        self.completed = 0
        self.shed = 0                     # rejected at admission (ServerBusy)
        self.timeouts = 0                 # expired before a result arrived
        self.errors = 0                   # model/fault failures propagated
        self.batches = 0                  # dispatched batches
        self.batched_rows = 0             # real rows across batches
        self.bucket_rows = 0              # padded bucket rows across batches
        self.pad_rows = 0                 # bucket_rows - batched_rows, running
        self.row_bytes = None             # bytes per input row (server-set)
        # measured traffic shape — the autotuner's input (ir.tune
        # fit_buckets) and the pad-waste evidence pow2 defaults hide.
        # Both maps are bounded: request sizes are capped by the largest
        # admissible bucket and batches land on configured buckets only,
        # so keys ≤ max_bucket / len(buckets) — not per-request state
        # (GL006)
        self._request_rows = {}           # rows(int) -> request count
        self._bucket_hist = {}            # bucket -> {batches, rows, pad_rows}
        self._queue_depth = 0
        # work the server has admitted but not yet completed — with
        # queue_depth, the two gauges a fleet router scrapes per pick
        # (cheap /health reads, never a full snapshot parse)
        self._tokens_in_flight = 0
        # profiler 'C' counters are created lazily so importing serve never
        # touches profiler state; events are only emitted while it runs
        self._prof = None

    # ------------------------------------------------------------ recording
    def _counters(self):
        if self._prof is None:
            dom = profiler.Domain("serve")
            self._prof = {
                "queue": dom.new_counter("%s.queue_depth" % self.name),
                "shed": dom.new_counter("%s.shed" % self.name),
                "timeout": dom.new_counter("%s.timeouts" % self.name),
            }
        return self._prof

    def record_admit(self, n=1, rows=None):
        with self._lock:
            self.requests += n
            if rows is not None:
                r = int(rows)
                self._request_rows[r] = self._request_rows.get(r, 0) + 1

    def record_queue_depth(self, depth):
        with self._lock:
            self._queue_depth = depth
        if profiler.is_running():
            self._counters()["queue"].set_value(depth)

    def record_tokens_in_flight(self, n):
        """Gauge: tokens (generative) or rows (batch serving) admitted but
        not yet delivered — the load score a least-loaded router sums with
        queue depth."""
        with self._lock:
            self._tokens_in_flight = int(n)

    def load_gauges(self):
        """The two router-scraped gauges as a tiny dict — what the worker's
        ``/health`` endpoint embeds (no percentile sort, no history walk)."""
        with self._lock:
            return {"queue_depth": self._queue_depth,
                    "tokens_in_flight": self._tokens_in_flight}

    def record_shed(self, n=1):
        with self._lock:
            self.shed += n
            total = self.shed
        if profiler.is_running():
            self._counters()["shed"].set_value(total)

    def record_timeout(self, n=1):
        with self._lock:
            self.timeouts += n
            total = self.timeouts
        if profiler.is_running():
            self._counters()["timeout"].set_value(total)

    def record_error(self, n=1):
        with self._lock:
            self.errors += n

    def record_batch(self, n_real, bucket):
        with self._lock:
            self.batches += 1
            self.batched_rows += int(n_real)
            self.bucket_rows += int(bucket)
            self.pad_rows += max(0, int(bucket) - int(n_real))
            h = self._bucket_hist.get(int(bucket))
            if h is None:
                h = self._bucket_hist[int(bucket)] = {
                    "batches": 0, "rows": 0, "pad_rows": 0}
            h["batches"] += 1
            h["rows"] += int(n_real)
            h["pad_rows"] += max(0, int(bucket) - int(n_real))

    def request_rows(self):
        """Measured request-size histogram ``{rows: count}`` — what
        ``ir.tune.fit_buckets`` fits bucket sets to."""
        with self._lock:
            return dict(self._request_rows)

    def record_latency(self, ms):
        with self._lock:
            self._lat[self._lat_n % self._window] = float(ms)
            self._lat_n += 1
            self.completed += 1

    # ------------------------------------------------------------ snapshot
    def _percentiles(self):
        n = min(self._lat_n, self._window)
        if n == 0:
            return {"p50_ms": None, "p95_ms": None, "p99_ms": None}
        vals = sorted(self._lat[:n])
        # nearest-rank on the retained window
        pick = lambda q: vals[min(n - 1, int(q * (n - 1) + 0.5))]  # noqa: E731
        return {"p50_ms": round(pick(0.50), 3),
                "p95_ms": round(pick(0.95), 3),
                "p99_ms": round(pick(0.99), 3)}

    def snapshot(self):
        with self._lock:
            snap = {
                "name": self.name,
                "requests": self.requests,
                "completed": self.completed,
                "shed": self.shed,
                "timeouts": self.timeouts,
                "errors": self.errors,
                "batches": self.batches,
                "queue_depth": self._queue_depth,
                "tokens_in_flight": self._tokens_in_flight,
                "batch_fill_ratio": (round(self.batched_rows
                                           / self.bucket_rows, 4)
                                     if self.bucket_rows else None),
                "mean_batch_size": (round(self.batched_rows / self.batches, 2)
                                    if self.batches else None),
                "latency_window": min(self._lat_n, self._window),
                "pad_rows_total": self.pad_rows,
                "pad_waste_bytes": (self.pad_rows * self.row_bytes
                                    if self.row_bytes else None),
                "request_rows": {str(r): c for r, c in
                                 sorted(self._request_rows.items())},
                "bucket_hist": {str(b): dict(h) for b, h in
                                sorted(self._bucket_hist.items())},
            }
            snap.update(self._percentiles())
        return snap


def _ring_percentiles(ring, n, prefix):
    """Nearest-rank p50/p95/p99 over the retained window of a latency ring
    (same estimator as ServeMetrics._percentiles)."""
    out = {"%s_p50_ms" % prefix: None, "%s_p95_ms" % prefix: None,
           "%s_p99_ms" % prefix: None}
    if n == 0:
        return out
    vals = sorted(ring[:n])
    pick = lambda q: vals[min(n - 1, int(q * (n - 1) + 0.5))]  # noqa: E731
    out["%s_p50_ms" % prefix] = round(pick(0.50), 3)
    out["%s_p95_ms" % prefix] = round(pick(0.95), 3)
    out["%s_p99_ms" % prefix] = round(pick(0.99), 3)
    return out


class GenerativeMetrics(ServeMetrics):
    """ServeMetrics plus the token-level counters autoregressive serving
    is judged by: tokens/s (over decode-active wall time, not idle time),
    time-to-first-token (admission → first sampled token, the user-visible
    prefill latency), inter-token latency (one decode step of the shared
    batch), and in-flight batch fill (live slots / padded slots — how much
    of every decode dispatch is real work)."""

    def __init__(self, name="serve", window=2048):
        super().__init__(name, window)
        self._ttft = [0.0] * self._window   # admission → first token, ms
        self._ttft_n = 0
        self._itl = [0.0] * self._window    # per decode step, ms
        self._itl_n = 0
        self._itl_pf = [0.0] * self._window  # steps under chunked prefill
        self._itl_pf_n = 0
        self.tokens = 0                     # generated tokens, all requests
        self.steps = 0                      # decode dispatches
        self.prefills = 0                   # whole-prompt forward dispatches
        self.prefill_chunks = 0             # chunked-prefill dispatches
        self._decode_s = 0.0                # decode-active wall time
        self._active_slot_steps = 0         # live slots summed over steps
        self._slot_steps = 0                # padded slots summed over steps
        # speculative decode: drafted = proposals offered to verify
        # (active_slots × (k-1) per round), accepted = proposals the target
        # kept — accepted/drafted is the accept rate the k-vs-overhead
        # trade lives or dies by
        self.spec_rounds = 0
        self.drafted_tokens = 0
        self.accepted_tokens = 0
        # TTFT split by pow2 prompt-length bucket: long prompts have
        # honest multi-chunk TTFTs and must not hide behind short-prompt
        # medians (each bucket gets its own ring → per-bucket percentiles
        # under `bucket=` labels in /metrics)
        self._ttft_by_bucket = {}           # bucket(int) -> [ring, n]

    @staticmethod
    def _pow2_bucket(n):
        b = 1
        while b < n:
            b <<= 1
        return b

    def record_first_token(self, ms, prompt_len=None):
        with self._lock:
            self._ttft[self._ttft_n % self._window] = float(ms)
            self._ttft_n += 1
            self.tokens += 1   # the first token is sampled by prefill
            if prompt_len is not None:
                b = self._pow2_bucket(int(prompt_len))
                ent = self._ttft_by_bucket.get(b)
                if ent is None:
                    # bounded: one ring per pow2 bucket, log2(max_length)
                    # buckets total — not per-prompt state (GL006)
                    ent = self._ttft_by_bucket[b] = [[0.0] * self._window, 0]
                ent[0][ent[1] % self._window] = float(ms)
                ent[1] += 1

    def record_prefill(self, n=1):
        with self._lock:
            self.prefills += n

    def record_chunk(self, n=1):
        with self._lock:
            self.prefill_chunks += n

    def record_step(self, step_s, n_tokens, n_active, slots,
                    under_prefill=False):
        """One decode (or verify) dispatch: ``n_tokens`` emitted across
        ``n_active`` live slots. ``under_prefill`` marks steps taken while
        chunked prefills were in flight — their ITLs land in a separate
        ``itl_prefill`` ring so the interference chunking is supposed to
        bound is directly measurable."""
        with self._lock:
            self._itl[self._itl_n % self._window] = float(step_s) * 1e3
            self._itl_n += 1
            if under_prefill:
                self._itl_pf[self._itl_pf_n % self._window] = \
                    float(step_s) * 1e3
                self._itl_pf_n += 1
            self.steps += 1
            self.tokens += int(n_tokens)
            self._decode_s += float(step_s)
            self._active_slot_steps += int(n_active)
            self._slot_steps += int(slots)

    def record_spec_round(self, drafted, accepted):
        with self._lock:
            self.spec_rounds += 1
            self.drafted_tokens += int(drafted)
            self.accepted_tokens += int(accepted)

    def snapshot(self):
        snap = super().snapshot()
        with self._lock:
            snap.update({
                "tokens": self.tokens,
                "decode_steps": self.steps,
                "prefills": self.prefills,
                "prefill_chunks": self.prefill_chunks,
                "tokens_per_s": (round(self.tokens / self._decode_s, 1)
                                 if self._decode_s > 0 else None),
                "inflight_fill": (round(self._active_slot_steps
                                        / self._slot_steps, 4)
                                  if self._slot_steps else None),
                "spec_rounds": self.spec_rounds,
                "drafted_tokens": self.drafted_tokens,
                "accepted_tokens": self.accepted_tokens,
                "accept_rate": (round(self.accepted_tokens
                                      / self.drafted_tokens, 4)
                                if self.drafted_tokens else None),
            })
            snap.update(_ring_percentiles(
                self._ttft, min(self._ttft_n, self._window), "ttft"))
            snap.update(_ring_percentiles(
                self._itl, min(self._itl_n, self._window), "itl"))
            snap.update(_ring_percentiles(
                self._itl_pf, min(self._itl_pf_n, self._window),
                "itl_prefill"))
            snap["ttft_by_bucket"] = {
                str(b): {
                    k.replace("b_", ""): v for k, v in _ring_percentiles(
                        ring, min(n, self._window), "b").items()}
                for b, (ring, n) in sorted(self._ttft_by_bucket.items())}
        return snap
