"""Paged KV-cache state for continuous-batching generative decode.

The decode-side analogue of ``executor_pool``'s pad-to-bucket discipline
(TVM-style fixed compiled shapes, arXiv 1802.04799) applied to the KV cache:
instead of a per-request cache tensor whose time axis grows every token —
a new aval per step, so every compiled consumer retraces (graphlint GL007)
— all in-flight requests share per-layer ``(slots, heads, capacity,
head_dim)`` buffers. Each request owns one SLOT page; its tokens are
written in place at its own ``valid_len`` position via
``lax.dynamic_update_slice`` (the ``cache_write`` op) and attention masks
to the live prefix, so **no shape ever changes across decode steps**.

Capacity is bucketed in powers of two: when an admitted request needs more
room than the current bucket, the buffers are zero-padded up to the next
bucket (one rare migration dispatch) and the decode program for that
capacity compiles once — the same log2-many-programs bound the executor
pool gives batch sizes. Buffers are donated to the decode program on TPU
backends (they are pure carried state; XLA updates them in place), the
same donation discipline as ``executor_pool``.

``PrefixCache`` is the prompt-caching layer: completed prefills are keyed
by the token-prefix hash; a hit replays the stored K/V pages into the new
request's slot (one tiny inject dispatch) instead of re-running the
whole-prompt forward.
"""
from __future__ import annotations

import numpy as np

import jax.numpy as jnp

from ..base import BoundedCache, env_cap, next_pow2


class CacheError(RuntimeError):
    """Misuse of the paged cache (capacity/slot exhaustion)."""


class PagedKVCache:
    """Slot-paged fixed-capacity KV cache shared by all in-flight requests.

    Holds the device-side carried state of the decode loop — per-layer K/V
    buffers plus the per-slot ``valid_len`` vector — and the host-side slot
    bookkeeping (which request owns which page). The compiled prefill/
    decode programs take these arrays as (donated) inputs and return the
    updated ones; the server writes them back via :meth:`update`.

    Parameters
    ----------
    layers, heads, head_dim : int
        Per-layer buffer geometry (``model.decode_state_spec()``).
    slots : int
        Number of request pages — the padded decode batch size.
    max_capacity : int
        Hard ceiling on the time axis (the model's ``max_length``).
    dtype : np.dtype
        K/V element dtype (the model's parameter dtype; bf16 models
        cache in bf16).
    quantize : bool
        Store pages as int8 with per-page-per-head fp32 scales
        (``k_scale``/``v_scale``, (slots, H, 1, 1) per layer): ~0.5× the
        bf16 page bytes. Pages quantize on write (``quant_cache_write``'s
        running-max scale) and dequantize on read inside the decode
        program; capacity buckets, donation and the one-dispatch step are
        unchanged. Scale buffers are capacity-independent, so migrations
        only pad the int8 pages.
    """

    def __init__(self, layers, heads, head_dim, slots, max_capacity,
                 dtype=np.float32, quantize=False):
        self.layers = int(layers)
        self.heads = int(heads)
        self.head_dim = int(head_dim)
        self.slots = int(slots)
        self.max_capacity = int(max_capacity)
        self.quantize = bool(quantize)
        self.dtype = np.dtype(np.int8) if self.quantize else np.dtype(dtype)
        # what a non-quantized cache of the model's dtype would cost per
        # element — the denominator of the bytes-saved accounting
        self._ref_itemsize = np.dtype(dtype).itemsize
        self.capacity = 0
        self.k = None     # list[L] of (slots, H, capacity, D) jax arrays
        self.v = None
        self.k_scale = None  # list[L] of (slots, H, 1, 1) fp32 (quantized)
        self.v_scale = None
        self.valid = jnp.zeros((self.slots,), jnp.int32)
        self._free = list(range(self.slots))
        self._owner = [None] * self.slots
        self.migrations = 0  # capacity-bucket growths (rare by design)

    # ---------------------------------------------------------- capacity
    def capacity_bucket(self, need):
        """Pow2 capacity bucket for ``need`` tokens, clamped to the model's
        max length (positions beyond it have no embedding)."""
        if need > self.max_capacity:
            raise CacheError(
                "request needs %d cache positions but the model's "
                "max_length is %d" % (need, self.max_capacity))
        return min(self.max_capacity, next_pow2(need))

    def ensure_capacity(self, need):
        """Grow the buffers to the bucket that fits ``need`` (zero-padding
        the time axis — one migration dispatch per layer, then the decode
        program for the new capacity compiles once). Returns True when a
        migration happened — live programs for the old capacity stay
        cached, so shrinking traffic never re-migrates."""
        cap = self.capacity_bucket(need)
        if cap <= self.capacity and self.k is not None:
            return False
        shape = (self.slots, self.heads, cap, self.head_dim)
        if self.k is None:
            self.k = [jnp.zeros(shape, self.dtype) for _ in range(self.layers)]
            self.v = [jnp.zeros(shape, self.dtype) for _ in range(self.layers)]
            if self.quantize:
                sshape = (self.slots, self.heads, 1, 1)
                self.k_scale = [jnp.zeros(sshape, jnp.float32)
                                for _ in range(self.layers)]
                self.v_scale = [jnp.zeros(sshape, jnp.float32)
                                for _ in range(self.layers)]
        else:
            pad = ((0, 0), (0, 0), (0, cap - self.capacity), (0, 0))
            self.k = [jnp.pad(k, pad) for k in self.k]
            self.v = [jnp.pad(v, pad) for v in self.v]
            # scale buffers are (slots, H, 1, 1) — capacity-independent
            self.migrations += 1
        self.capacity = cap
        return True

    # ------------------------------------------------------------- slots
    def acquire(self, owner):
        """Claim a free page for ``owner``; None when fully booked (the
        scheduler leaves the request in the admission queue)."""
        if not self._free:
            return None
        slot = self._free.pop(0)
        self._owner[slot] = owner
        return slot

    def release(self, slot):
        """Free a page between decode steps — pure host bookkeeping: the
        next prefill overwrites the page from offset 0 and ``valid_len``
        masks everything stale, so no device-side scrub is needed (and no
        recompile: the batch layout is padded, not reshaped)."""
        self._owner[slot] = None
        self._free.append(slot)

    def owner(self, slot):
        return self._owner[slot]

    @property
    def active_slots(self):
        return [i for i, o in enumerate(self._owner) if o is not None]

    @property
    def num_active(self):
        return self.slots - len(self._free)

    def active_mask(self, exclude=()):
        """(slots,) int32 mask of live pages — a traced input of the decode
        program (free slots sample nothing and their valid_len holds), so
        join/leave between steps never changes a shape. ``exclude`` drops
        acquired-but-not-yet-decodable pages (chunked prefill in flight):
        the slot is owned, so admission can't reuse it, but decode must
        treat it as free until its final chunk lands."""
        return np.asarray([0 if (o is None or i in exclude) else 1
                           for i, o in enumerate(self._owner)], np.int32)

    def update(self, k, v, valid, k_scale=None, v_scale=None):
        """Install the arrays a compiled step returned (the old buffers
        were donated on TPU — they must not be touched again)."""
        self.k, self.v, self.valid = list(k), list(v), valid
        if k_scale is not None:
            self.k_scale = list(k_scale)
        if v_scale is not None:
            self.v_scale = list(v_scale)

    # ------------------------------------------------------------ accounting
    def nbytes(self):
        """Live page-buffer bytes (K + V + scales) — the measured side of
        the quantized-cache acceptance ratio."""
        if self.k is None:
            return 0
        total = sum(int(a.nbytes) for a in self.k)
        total += sum(int(a.nbytes) for a in self.v)
        if self.quantize:
            total += sum(int(a.nbytes) for a in self.k_scale)
            total += sum(int(a.nbytes) for a in self.v_scale)
        return total

    def nbytes_unquantized(self, itemsize=None):
        """What the SAME geometry would cost unquantized — the denominator
        of the ≤ 0.55× bytes acceptance check. ``itemsize`` defaults to the
        model dtype's (pass 2 to compare against a bf16 cache)."""
        if self.k is None:
            return 0
        elems = 2 * self.layers * self.slots * self.heads \
            * self.capacity * self.head_dim
        return elems * (self._ref_itemsize if itemsize is None else itemsize)


class PrefixCache:
    """Prompt/prefix cache: token-prefix hash → finished prefill state.

    Entries hold host-side copies ``(k_stack, v_stack, prompt_len,
    last_logits)`` with ``k_stack``/``v_stack`` of shape (layers, heads,
    padded_prompt_len, head_dim) — exact dtypes (bf16 stays bf16). A hit
    skips the whole-prompt forward: the stored pages are injected into the
    request's slot by a tiny compiled program and the first token is
    sampled from the stored logits with the request's own key/temperature
    (two requests sharing a prompt can still sample differently).

    Bounded (``MXNET_PREFIX_CACHE_CAP``, default 32 prompts): entries are
    full KV pages, the one cache in this subsystem where eviction is about
    host RAM, not compiled-program count.
    """

    def __init__(self, cap=None):
        self._store = BoundedCache(env_cap("MXNET_PREFIX_CACHE_CAP", 32)
                                   if cap is None else cap)
        self.hits = 0
        self.misses = 0

    @staticmethod
    def key(tokens):
        return tuple(int(t) for t in np.asarray(tokens).ravel())

    def get(self, tokens):
        ent = self._store.get(self.key(tokens))
        if ent is None:
            self.misses += 1
        else:
            self.hits += 1
        return ent

    def put(self, tokens, k_stack, v_stack, prompt_len, last_logits):
        self._store[self.key(tokens)] = (
            np.asarray(k_stack), np.asarray(v_stack), int(prompt_len),
            np.asarray(last_logits))

    def __len__(self):
        return len(self._store)
