"""ModelServer — dynamic-batching inference serving over an executor pool.

The mxnet-model-server analogue for this stack: take a hybridized
``gluon.Block`` (or a ``SymbolBlock`` loaded from an export/checkpoint),
pre-compile it at a set of batch-size buckets, and serve single requests
through a dynamic batcher that coalesces them into the largest fitting
bucket under a deadline. Steady state is one cached XLA dispatch per batch
(``engine.serve_compile_counter`` flat after warmup), with typed
load-shedding/timeout degradation and p50/p95/p99 observability.

    net = resnet18_v1(); net.initialize(); net.hybridize()
    srv = mxnet_tpu.serve.ModelServer(net, [((3, 224, 224), "float32")],
                                      buckets=(1, 4, 16), max_wait_ms=2.0)
    with srv:
        probs = srv.predict(img)          # sync, single sample
        handle = srv.submit(img)          # async, .result(timeout_s)
        srv.stats()                       # latency/queue/shed snapshot

Fault injection for degradation drills reuses the resilience hook shape
(``parallel/resilience.py`` ``fail_at``/``SimulatedFailure``): assign
``srv.inject_fault = lambda batch_idx: ...`` to raise on chosen batches —
affected requests get the error, the server keeps serving.
"""
from __future__ import annotations

import threading
import time

import numpy as np

from ..ndarray import NDArray
from .batcher import DynamicBatcher, ServeError, ServeTimeout
from .executor_pool import BucketedExecutor, symbol_infer_fn

DEFAULT_BUCKETS = (1, 2, 4, 8, 16, 32)


def _block_pool(model, devices, buckets, donate, params_lock=None):
    """Adapt a gluon block to (fn, params_fn): SymbolBlocks route through
    their stored graph, hybrid blocks through serving_fn's pure trace.

    ``params_lock`` is the weight hot-swap seam: params_fn (read once per
    dispatch) snapshots the whole list under it, and ``swap_parameters``
    writes every param under the same lock — a dispatch therefore sees
    all-old or all-new weights, never a mix, with zero pause in between
    (the lock is held for a list comprehension, not a compile)."""
    from ..gluon.block import SymbolBlock

    if isinstance(model, SymbolBlock):
        params = model.collect_params()
        input_names = [s.name for s in model._inputs]
        fn, pnames = symbol_infer_fn(model._outputs, input_names)
        if fn is None:
            raise ServeError(
                "model's eval graph draws randomness per call (mode='always' "
                "dropout?) — not servable from fixed compiled buckets")
        plist = [params[n] for n in pnames]
    else:
        fn, _ = model.serving_fn()
        plist = list(model.collect_params().values())

    if params_lock is None:
        def params_fn():
            return [p.data()._data for p in plist]
    else:
        def params_fn():
            with params_lock:
                return [p.data()._data for p in plist]

    return BucketedExecutor(fn, params_fn, buckets=buckets, devices=devices,
                            donate=donate, name=type(model).__name__)


class ModelServer:
    """Dynamic-batching server over a bucketed executor pool.

    Parameters
    ----------
    model : HybridBlock | SymbolBlock
        Initialized (and ideally hybridized) block; SymbolBlocks come from
        ``serve.load`` / ``checkpoint.load_for_serving``.
    input_specs : list of ((sample_shape), dtype)
        Per model input, the PER-SAMPLE shape (no batch dim) and dtype —
        fixes the compiled signatures; requests are cast to these.
    buckets : tuple of int
        Padded batch sizes compiled at startup (warm compile). The largest
        is also the coalescing limit.
    max_wait_ms : float
        Batching deadline: how long the first request in a window waits for
        company before dispatching a partial bucket.
    max_queue : int
        Admission bound in ROWS; beyond it submit() sheds with ServerBusy.
    timeout_ms : float
        Default per-request deadline (predict/submit can override).
    devices : list | Mesh | None
        Replica devices; batches round-robin over them (whole-batch
        replication — the inference-side complement of ``split_and_load``'s
        per-device sharding). A ``parallel.mesh`` Mesh serves on all its
        devices. None = one replica on the current placement.
    quantize : str | None
        Serve with quantized weights: ``"int8"`` (or ``"e4m3"``/``"e5m2"``
        where the backend ships fp8) swaps every eligible Dense/Conv2D for
        its quantized twin (``quant.quantize_model``) before the pool
        compiles, so the warmed bucket programs ARE the quantized programs
        — snapshot/load round-trips them like any other. SymbolBlocks are
        served as exported (quantize before export instead).
    calib_mode, calib_data :
        Activation-scale calibration for the quantized layers (``"naive"``
        or ``"entropy"``), run against ``calib_data`` — typically a warmup
        batch shaped like real traffic — before the pool compiles. Ignored
        unless ``quantize`` is set.
    """

    def __init__(self, model, input_specs, buckets=DEFAULT_BUCKETS,
                 max_wait_ms=2.0, max_queue=256, timeout_ms=1000.0,
                 devices=None, donate=None, name=None, warmup=True,
                 metrics_port=None, quantize=None, calib_mode="none",
                 calib_data=None):
        from .metrics import ServeMetrics

        if devices is not None and hasattr(devices, "devices"):
            # a parallel.mesh Mesh: replicate over every device in it
            import numpy as _np

            devices = list(_np.asarray(devices.devices).flat)
        self.name = name or ("serve:%s" % type(model).__name__.lower())
        self.quantize = quantize or None
        if self.quantize is not None:
            from ..gluon.block import SymbolBlock

            if isinstance(model, SymbolBlock):
                raise ServeError(
                    "quantize= needs a live HybridBlock (a SymbolBlock's "
                    "graph is frozen) — quantize before export, or load "
                    "the original block")
            from ..quantization import quantize_model

            quantize_model(model, mode=self.quantize,
                           calib_mode=calib_mode, calib_data=calib_data)
        self.model = model
        self.buckets = tuple(sorted(set(int(b) for b in buckets)))
        self._specs = [(tuple(shape), np.dtype(dt))
                       for shape, dt in input_specs]
        self.timeout_ms = float(timeout_ms)
        # rebuild ingredients for retune_buckets (pool + batcher rewire)
        self._devices = devices
        self._donate = donate
        self._max_wait_ms = max_wait_ms
        self._max_queue = max_queue
        self.metrics = ServeMetrics(self.name)
        # bytes one request row occupies across all inputs: turns the
        # metrics pad-row count into pad-waste bytes
        self.metrics.row_bytes = sum(
            int(np.prod(shape, dtype=np.int64)) * dt.itemsize
            for shape, dt in self._specs)
        # hot-swap seam: params_fn reads and swap_parameters writes under
        # this lock, so every dispatch sees one coherent weight set
        self._params_lock = threading.Lock()
        self._swap_epoch = 0
        self._pool = _block_pool(model, devices, self.buckets, donate,
                                 self._params_lock)
        self._batcher = DynamicBatcher(
            self._dispatch, max_batch=self.buckets[-1],
            max_wait_ms=max_wait_ms, max_queue=max_queue,
            num_dispatchers=self._pool.num_replicas, metrics=self.metrics)
        self._batch_idx = 0
        self._batch_lock = threading.Lock()
        self.inject_fault = None  # drill hook: callable(batch_idx) may raise
        self._started = False
        # opt-in /metrics scrape endpoint (observability.http); None = off.
        # 0 picks an ephemeral port, read back from metrics_http.port.
        self._metrics_port = metrics_port
        self.metrics_http = None
        if warmup:
            self.warmup()
        from . import _register
        _register(self)

    # ------------------------------------------------------------ lifecycle
    def warmup(self):
        """Compile every (bucket, replica) program before taking traffic;
        also proves row-aligned outputs (padding is only sound when each
        output carries the batch on axis 0)."""
        self._pool.warmup(self._specs, self.buckets)
        if not self._pool.row_aligned:
            raise ServeError(
                "model outputs do not all carry the batch on axis 0 — "
                "padded serving cannot slice per-request rows")
        return self

    def snapshot(self, prefix, input_names=None, epoch=0):
        """Write the AOT serving artifact: checkpoint + bucket config +
        every warmed bucket's serialized executable.
        ``serve.load(prefix, snapshot=True)`` rebuilds this server with
        ZERO compiles to first request (cache Tier B; see
        mxnet_tpu.cache.snapshot)."""
        from ..cache.snapshot import save_snapshot

        return save_snapshot(self, prefix, input_names=input_names,
                             epoch=epoch)

    def start(self):
        self._batcher.start()
        if self._metrics_port is not None and self.metrics_http is None:
            from ..observability import MetricsHTTPServer

            self.metrics_http = MetricsHTTPServer(self._metrics_port,
                                                  health_fn=self.health)
        self._started = True
        return self

    def stop(self, drain=True, timeout_s=5.0, reason="server stopped"):
        """Stop serving. drain=True dispatches what is already queued
        before shutdown; drain=False rejects it immediately. Dispatcher
        and worker joins are bounded by ``timeout_s``, any request still
        queued OR claimed afterwards is rejected typed (never stranded —
        mid-drain strands sweep to ``ServeError("worker retired: ...")``
        so a fleet router retries them on a sibling), and start() after
        stop() rebuilds the dispatcher pool — repeated cycles leak no
        threads (pinned by tests/test_concurrency.py)."""
        self._started = False
        self._batcher.stop(drain=drain, timeout_s=timeout_s, reason=reason)
        if self.metrics_http is not None:
            self.metrics_http.close()
            self.metrics_http = None

    def health(self):
        """Cheap liveness payload for the ``/health`` endpoint (and the
        fleet router's per-pick scrape): warmup-complete flag plus the two
        load gauges — no percentile sorts, no device reads."""
        queue = self._batcher.queue_depth()
        self.metrics.record_tokens_in_flight(queue)
        return {"warm": bool(self._pool.row_aligned),
                "running": self._started,
                "kind": "model",
                "queue_depth": queue,
                "tokens_in_flight": queue,
                "swap_epoch": self._swap_epoch}

    def swap_parameters(self, params_file):
        """Zero-downtime weight hot-swap: validate ``params_file``
        structurally against the live parameter tree
        (``checkpoint.validate_swap`` — missing/extra/reshaped/re-dtyped
        params, including quantized qweight/w_scale pages, raise SwapError
        with the OLD weights untouched), then flip every parameter
        atomically under the params_fn lock the pool reads per dispatch.
        In-flight batches finish on the weights they snapshotted; the next
        dispatch serves the new ones. Same shapes/dtypes = same compiled
        signatures: no retrace, no dropped requests. Returns the new swap
        epoch."""
        from ..checkpoint import validate_swap

        import jax.numpy as jnp

        from ..ndarray import NDArray

        picked = validate_swap(self.model, params_file)
        params = self.model._collect_params_with_prefix()
        # stage host→device transfers BEFORE taking the lock: the flip
        # itself is a pointer rebind per param, microseconds under traffic
        staged = {n: NDArray(jnp.asarray(a)) for n, a in picked.items()}
        with self._params_lock:
            for name, arr in staged.items():
                params[name].set_data(arr)
            self._swap_epoch += 1
        return self._swap_epoch

    def retune_buckets(self, buckets=None, max_buckets=6):
        """Rebuild the server on a new bucket set — the apply step of
        serve-bucket autotuning. With ``buckets=None`` the set is fit to
        this server's MEASURED request-size histogram
        (``ir.tune.fit_buckets`` over ``metrics.request_rows()``) instead
        of the blind pow2 default. Drains in-flight work, compiles the
        new bucket programs (warmup), rewires the batcher, and resumes if
        the server was running. Counters and histograms carry over — the
        next fit sees all traffic ever served."""
        if buckets is None:
            from ..ir import tune as _tune

            hist = self.metrics.request_rows()
            if not hist:
                raise ServeError(
                    "no request-size history to fit buckets to — serve "
                    "traffic first or pass buckets= explicitly")
            buckets = _tune.fit_buckets(hist, max_buckets=max_buckets,
                                        max_size=self.buckets[-1])
        new = tuple(sorted(set(int(b) for b in buckets)))
        if not new:
            raise ServeError("retune_buckets needs a non-empty bucket set")
        if new == self.buckets:
            return self
        was_started = self._started
        if was_started:
            self.stop()
        self.buckets = new
        self._pool = _block_pool(self.model, self._devices, self.buckets,
                                 self._donate, self._params_lock)
        self._batcher = DynamicBatcher(
            self._dispatch, max_batch=self.buckets[-1],
            max_wait_ms=self._max_wait_ms, max_queue=self._max_queue,
            num_dispatchers=self._pool.num_replicas, metrics=self.metrics)
        self.warmup()
        if was_started:
            self.start()
        return self

    def __enter__(self):
        return self.start()

    def __exit__(self, *a):
        self.stop()

    # ------------------------------------------------------------ requests
    def _coerce(self, xs):
        """Normalize one request's inputs to numpy with a leading batch dim;
        returns (arrays, n_rows, was_sample). A bare sample gets batch
        dim 1 (and ``was_sample`` lets predict drop it from the outputs)."""
        if len(xs) != len(self._specs):
            raise ServeError("model takes %d inputs, got %d"
                             % (len(self._specs), len(xs)))
        out, n, was_sample = [], None, False
        for x, (shape, dt) in zip(xs, self._specs):
            if isinstance(x, NDArray):
                x = x.asnumpy()
            x = np.asarray(x, dtype=dt)
            if x.shape == shape:
                x = x[None]
                was_sample = True
            elif x.shape[1:] != shape:
                raise ServeError("input shape %s matches neither sample %s "
                                 "nor batch (n,)+%s"
                                 % (x.shape, shape, shape))
            if n is None:
                n = x.shape[0]
            elif x.shape[0] != n:
                raise ServeError("inputs disagree on batch size")
            out.append(x)
        return out, n, was_sample

    def _submit_arrays(self, arrays, n, timeout_ms):
        if not self._started:
            self.start()
        if n > self.buckets[-1]:
            raise ServeError("request of %d rows exceeds the largest bucket "
                             "%d — split it or widen buckets"
                             % (n, self.buckets[-1]))
        # per-request trace context: rides the handle through queue →
        # coalesce → pad → dispatch; handle.timing()/handle.trace expose
        # the breakdown (observability.tracing; None when tracing is off)
        from ..observability import new_trace

        return self._batcher.submit(arrays, n, timeout_ms=timeout_ms,
                                    trace=new_trace(self.name))

    def submit(self, *xs, timeout_ms=None):
        """Async enqueue; returns a handle with ``.result(timeout_s)``.
        Raises ServerBusy immediately when admission control sheds."""
        arrays, n, _ = self._coerce(xs)
        tmo = self.timeout_ms if timeout_ms is None else float(timeout_ms)
        return self._submit_arrays(arrays, n, tmo)

    def predict(self, *xs, timeout_ms=None):
        """Synchronous single-request inference through the batcher. Returns
        one numpy array per model output (batch dim dropped for bare-sample
        requests)."""
        tmo = self.timeout_ms if timeout_ms is None else float(timeout_ms)
        arrays, n, was_sample = self._coerce(xs)
        req = self._submit_arrays(arrays, n, tmo)
        try:
            outs = req.result(timeout_s=tmo / 1e3 + 5.0)
        except ServeTimeout:
            if req.finish(error=ServeTimeout("result wait expired")):
                self.metrics.record_timeout()
            raise
        squeeze = was_sample and n == 1
        outs = [o[0] if squeeze and o.ndim >= 1 and o.shape[0] == 1 else o
                for o in outs]
        return outs[0] if len(outs) == 1 else outs

    # ------------------------------------------------------------ dispatch
    def _dispatch(self, requests, total_rows):
        """Batcher callback: coalesce → one bucket dispatch → scatter
        results. Runs on a dispatcher thread; must finish() every request."""
        with self._batch_lock:
            idx = self._batch_idx
            self._batch_idx += 1
        try:
            if self.inject_fault is not None:
                self.inject_fault(idx)
            # close each rider's queue span (submit → batch claim) before
            # the shared pad/dispatch spans the pool adds
            traces = []
            for r in requests:
                if r.trace is not None:
                    r.trace.add_span("queue", r.t_submit,
                                     r.t_dequeue or time.perf_counter())
                    traces.append(r.trace)
            ins = [np.concatenate([r.inputs[i] for r in requests], axis=0)
                   for i in range(len(self._specs))]
            bucket = self._pool.pick_bucket(total_rows)
            outs = self._pool.run(ins, n_real=total_rows, traces=traces)
            self.metrics.record_batch(total_rows, bucket)
            now = time.perf_counter()
            off = 0
            for r in requests:
                per = [o[off:off + r.n] if o.ndim >= 1
                       and o.shape[0] == total_rows else o for o in outs]
                off += r.n
                if r.finish(result=per):
                    self.metrics.record_latency((now - r.t_submit) * 1e3)
        except Exception as e:  # fault path: typed propagation, keep serving
            self.metrics.record_error()
            for r in requests:
                r.finish(error=e)

    # ------------------------------------------------------------ stats
    def stats(self):
        """One snapshot dict: batcher/latency metrics + pool shape — the
        payload tools/diagnose.py's Serving section prints."""
        snap = self.metrics.snapshot()
        snap.update(buckets=list(self.buckets),
                    replicas=self._pool.num_replicas,
                    quantize=self.quantize,
                    running=self._started)
        return snap
