"""serve.fleet — multi-process replica fleet: least-loaded routing, SLO
autoscaling, zero-downtime weight hot-swap (ref: mxnet-model-server's
frontend/worker split — its Netty router, ``scale-worker`` management API
and per-model worker pools — rebuilt over serve.worker subprocesses).

Topology: each replica is ONE subprocess (``python -m mxnet_tpu.serve.worker``)
wrapping a snapshot-warm ModelServer/GenerativeServer; the router is a
library in the caller's process. A worker's single HTTP port carries data
(``/predict``, ``/generate``), control (``/swap``, ``/drain``, prefix
migration) and observability (``/metrics``, ``/health``).

Routing: least-loaded by the two ``/health`` gauges — ``queue_depth +
tokens_in_flight`` — with a round-robin tiebreak, skipping draining
replicas. Generative sessions get prefix-cache-aware affinity: a
``session=`` id sticks to one worker so multi-turn prompts hit its
PrefixCache; on planned retirement the dying worker's prefix entries are
exported and injected into the inheriting sibling, so the sessions keep
their KV pages (PagedKVCache extract/inject, host-side npz in between).

Failure: a connection-level error (refused / reset / half-written reply)
is ``WorkerGone`` — the router removes the replica and retries the request
on a sibling. ``kill -9`` mid-wave therefore costs only that worker's
in-flight work, and even those requests are retried (predict and
fixed-seed generate are idempotent), so a wave completes with zero
failures. 503 (busy/draining) retries siblings too; 504 and model errors
propagate typed.

Autoscaling: ``Autoscaler`` samples worker stats on an interval; sustained
SLO pressure (p95 latency over target, or shedding above ``shed_rate``)
spawns a snapshot-warm replica (zero compiles to first request, watchdog
armed); sustained idle drains-then-retires down to ``min_workers``.

Hot swap: ``hot_swap()`` pushes a checkpoint (raw npz bytes) to every
replica; each validates structurally against its live ParameterDict
*before* touching a weight and flips atomically under the params seam
BucketedExecutor reads per dispatch — a mid-swap dispatch sees all-old or
all-new, never a mix, and a rejected push (missing/extra/reshaped/requantized
params) leaves the old weights serving everywhere.
"""
from __future__ import annotations

import http.client
import json
import os
import signal
import subprocess
import sys
import threading
import time
from collections import deque

import numpy as np

from ..checkpoint import SwapError
from ..util import dumps_npz_exact, loads_npz_exact
from .batcher import ServeError, ServerBusy, ServeTimeout

__all__ = ["WorkerGone", "WorkerSpec", "WorkerHandle", "FleetRouter",
           "Autoscaler"]

_STATUS_ERRORS = {503: ServerBusy, 504: ServeTimeout, 409: SwapError}


class WorkerGone(ServeError):
    """The replica's process or connection is gone (refused, reset, died
    mid-reply). Routers treat this as 'remove and retry a sibling' —
    never as a request failure."""


class WorkerSpec:
    """How to (re)spawn a replica — the unit the autoscaler clones.

    ``snapshot``: AOT serving snapshot prefix (the production path — the
    spawned process deserializes warmed programs, zero compiles to first
    request). ``factory``: ``module:fn`` / ``file.py:fn`` returning a ready
    server (the dryrun/test path). ``model``: factory for the decode model
    when the snapshot is generative. ``kwargs``: JSON-able constructor
    overrides for the snapshot path. ``env``: extra environment for the
    subprocess (inherits the parent's otherwise)."""

    def __init__(self, factory=None, snapshot=None, model=None, kwargs=None,
                 env=None):
        if (snapshot is None) == (factory is None):
            raise ValueError("exactly one of snapshot= / factory=")
        self.factory = factory
        self.snapshot = snapshot
        self.model = model
        self.kwargs = dict(kwargs or {})
        self.env = dict(env or {})

    def argv(self, port=0):
        argv = [sys.executable, "-m", "mxnet_tpu.serve.worker",
                "--port", str(int(port))]
        if self.factory is not None:
            argv += ["--factory", self.factory]
        else:
            argv += ["--snapshot", self.snapshot]
            if self.kwargs:
                argv += ["--kwargs", json.dumps(self.kwargs)]
        if self.model is not None:
            argv += ["--model", self.model]
        return argv


class WorkerHandle:
    """Client for one replica: typed HTTP calls + process lifecycle.

    Connections are per-thread with keep-alive (HTTP/1.1) — routing a
    request costs one round-trip on a warm socket, not a handshake. Every
    connection-level failure closes the socket and raises WorkerGone."""

    def __init__(self, host, port, proc=None, spec=None, kind="model",
                 name=None):
        self.host = host
        self.port = int(port)
        self.proc = proc
        self.spec = spec
        self.kind = kind
        # port-qualified: replicas of one model share the server name, and
        # hot_swap/stats key rows by handle name — collisions would merge
        self.name = "%s@%d" % (name or "worker", self.port)
        self.pid = proc.pid if proc is not None else None
        self._local = threading.local()

    # ------------------------------------------------------------- spawn
    @classmethod
    def spawn(cls, spec, port=0, timeout_s=180.0, debug=None):
        """Launch ``python -m mxnet_tpu.serve.worker`` and block until its
        READY line (JSON on stdout) reports the bound port. The child
        inherits the parent's environment (JAX_PLATFORMS et al.) plus
        ``spec.env`` overrides."""
        env = dict(os.environ)
        env.update(spec.env)
        if debug is None:
            debug = bool(env.get("MXTPU_FLEET_DEBUG"))
        proc = subprocess.Popen(
            spec.argv(port), stdout=subprocess.PIPE,
            stderr=None if debug else subprocess.DEVNULL,
            env=env, text=True)
        deadline = time.perf_counter() + timeout_s
        line = ""
        while time.perf_counter() < deadline:
            line = proc.stdout.readline()
            if not line:
                if proc.poll() is not None:
                    raise WorkerGone(
                        "worker exited rc=%s before READY (argv=%r%s)"
                        % (proc.returncode, spec.argv(port),
                           "" if debug else
                           "; rerun with MXTPU_FLEET_DEBUG=1 for stderr"))
                time.sleep(0.01)
                continue
            line = line.strip()
            if line.startswith("{"):
                break
        else:
            proc.kill()
            raise WorkerGone("worker not READY within %.0fs" % timeout_s)
        ready = json.loads(line)
        return cls("127.0.0.1", ready["port"], proc=proc, spec=spec,
                   kind=ready.get("kind", "model"), name=ready.get("name"))

    # ------------------------------------------------------------- client
    def _conn(self, timeout):
        conn = getattr(self._local, "conn", None)
        if conn is None:
            conn = http.client.HTTPConnection(self.host, self.port,
                                              timeout=timeout)
            self._local.conn = conn
        else:
            conn.timeout = timeout
        return conn

    def _drop_conn(self):
        conn = getattr(self._local, "conn", None)
        if conn is not None:
            self._local.conn = None
            try:
                conn.close()
            except Exception:
                pass

    def request(self, method, path, body=None, timeout=30.0):
        """One round-trip; returns (status, body bytes). Connection-level
        failures → WorkerGone (one silent retry on a fresh socket first:
        a keep-alive peer may have closed the idle connection under us)."""
        for attempt in (0, 1):
            conn = self._conn(timeout)
            try:
                conn.request(method, path, body=body)
                resp = conn.getresponse()
                return resp.status, resp.read()
            except (ConnectionError, http.client.HTTPException,
                    TimeoutError, OSError) as e:
                self._drop_conn()
                if attempt and not self.alive():
                    raise WorkerGone("worker %s: %s" % (self.name, e)) from e
                if attempt:
                    raise WorkerGone(
                        "worker %s unreachable: %s" % (self.name, e)) from e

    def _checked(self, method, path, body=None, timeout=30.0):
        status, data = self.request(method, path, body=body, timeout=timeout)
        if status == 200:
            return data
        try:
            payload = json.loads(data.decode("utf-8"))
        except Exception:
            payload = {"message": data[:200].decode("utf-8", "replace")}
        err = _STATUS_ERRORS.get(status, ServeError)
        raise err("%s %s -> %d: %s" % (method, path, status,
                                       payload.get("message", payload)))

    # ---------------------------------------------------------- endpoints
    def health(self, timeout=5.0):
        return json.loads(self._checked("GET", "/health", timeout=timeout))

    def server_stats(self, timeout=10.0):
        return json.loads(self._checked("GET", "/server_stats",
                                        timeout=timeout))

    def load_score(self):
        """queue_depth + tokens_in_flight, or None when unhealthy/draining
        (the router skips those)."""
        try:
            h = self.health()
        except (WorkerGone, ServeError):
            return None
        if not h.get("ok", True) or h.get("draining"):
            return None
        return int(h.get("queue_depth") or 0) + \
            int(h.get("tokens_in_flight") or 0)

    def predict(self, xs, timeout=60.0):
        blob = dumps_npz_exact({"x%d" % i: np.asarray(x)
                                for i, x in enumerate(xs)})
        out = loads_npz_exact(self._checked("POST", "/predict", body=blob,
                                            timeout=timeout))
        outs = [out[k] for k in sorted(out, key=lambda k: int(k[1:]))]
        return outs[0] if len(outs) == 1 else outs

    def generate(self, prompt, timeout=120.0, **kw):
        req = {"prompt": [int(t) for t in np.asarray(prompt).ravel()]}
        req.update(kw)
        body = json.dumps(req).encode("utf-8")
        return json.loads(self._checked("POST", "/generate", body=body,
                                        timeout=timeout))["tokens"]

    def swap(self, blob, timeout=120.0):
        """Push checkpoint bytes; returns the new swap epoch. 409 → raises
        SwapError, replica keeps its old weights."""
        return json.loads(self._checked("POST", "/swap", body=blob,
                                        timeout=timeout))["swap_epoch"]

    def drain(self, timeout=10.0):
        return json.loads(self._checked("POST", "/drain", body=b"",
                                        timeout=timeout))

    def export_prefixes(self, timeout=60.0):
        return self._checked("GET", "/prefix/export", timeout=timeout)

    def import_prefixes(self, blob, timeout=60.0):
        return json.loads(self._checked("POST", "/prefix/import", body=blob,
                                        timeout=timeout))["imported"]

    def shutdown(self, timeout=10.0):
        try:
            self._checked("POST", "/shutdown", body=b"", timeout=timeout)
        except WorkerGone:
            pass  # it raced its own exit — that IS a successful shutdown

    # ---------------------------------------------------------- lifecycle
    def alive(self):
        if self.proc is not None:
            return self.proc.poll() is None
        try:
            self.health(timeout=2.0)
            return True
        except Exception:
            return False

    def kill9(self):
        """The drill: SIGKILL, no goodbye. In-flight work on this replica
        is lost; the router's retry path is what keeps the wave at zero
        failures."""
        if self.proc is not None:
            self.proc.kill()
        elif self.pid is not None:
            os.kill(self.pid, signal.SIGKILL)

    def reap(self, timeout_s=10.0):
        self._drop_conn()
        if self.proc is not None:
            try:
                self.proc.wait(timeout=timeout_s)
            except subprocess.TimeoutExpired:
                self.proc.kill()
                self.proc.wait(timeout=timeout_s)


class _Pool:
    """One model's replicas + its spawn recipe + session affinity map."""

    def __init__(self, spec=None):
        self.spec = spec
        self.workers = []
        self.rr = 0                  # round-robin tiebreak cursor
        self.affinity = {}           # session id -> WorkerHandle


class FleetRouter:
    """The fleet frontend: per-model replica pools behind one routing
    surface. Thread-safe; every public call may be issued from concurrent
    client threads (the bench fires waves exactly that way)."""

    def __init__(self):
        self._lock = threading.RLock()
        self._models = {}
        self.events = deque(maxlen=512)   # (t, event, detail) audit trail
        self.retries = 0                  # requests re-routed to a sibling
        self.workers_lost = 0             # replicas removed as WorkerGone

    def _event(self, event, **detail):
        self.events.append({"t": round(time.time(), 3), "event": event,
                            **detail})

    # ---------------------------------------------------------- registry
    def register(self, model="default", spec=None, workers=0):
        """Register a model pool (name → spawn recipe), optionally spawning
        ``workers`` replicas now. Multi-model multiplexing is just multiple
        register() calls — pools share this router and its client threads."""
        with self._lock:
            pool = self._models.get(model)
            if pool is None:
                pool = self._models[model] = _Pool(spec)
            elif spec is not None:
                pool.spec = spec
        for _ in range(int(workers)):
            self.scale_out(model)
        return self

    def adopt(self, handle, model="default"):
        """Add an externally-started replica (tests; or workers spawned by
        a supervisor the router doesn't own)."""
        with self._lock:
            pool = self._models.setdefault(model, _Pool())
            pool.workers.append(handle)
        self._event("adopt", model=model, worker=handle.name)
        return handle

    def workers(self, model="default"):
        with self._lock:
            return list(self._models[model].workers)

    def models(self):
        with self._lock:
            return sorted(self._models)

    def scale_out(self, model="default", port=0):
        """Spawn one snapshot-warm replica from the pool's spec and add it
        to rotation once READY."""
        with self._lock:
            spec = self._models[model].spec
        if spec is None:
            raise ServeError("pool %r has no WorkerSpec — register(spec=...) "
                             "before scale_out" % model)
        handle = WorkerHandle.spawn(spec, port=port)
        with self._lock:
            self._models[model].workers.append(handle)
        self._event("scale_out", model=model, worker=handle.name,
                    pid=handle.pid)
        return handle

    # ----------------------------------------------------------- routing
    def _remove(self, model, handle, why):
        with self._lock:
            pool = self._models[model]
            if handle in pool.workers:
                pool.workers.remove(handle)
                self.workers_lost += 1
                for sess in [s for s, w in pool.affinity.items()
                             if w is handle]:
                    del pool.affinity[sess]
        self._event("worker_lost", model=model, worker=handle.name, why=why)
        handle.reap(timeout_s=2.0)

    def _pick(self, model, exclude=(), session=None):
        """Least-loaded pick: scrape each candidate's /health gauges, take
        the smallest queue_depth + tokens_in_flight, round-robin on ties.
        Sticky sessions short-circuit to their worker while it's healthy."""
        with self._lock:
            pool = self._models[model]
            candidates = [w for w in pool.workers if w not in exclude]
            sticky = pool.affinity.get(session) if session else None
        if sticky is not None and sticky in candidates:
            if sticky.load_score() is not None:
                return sticky
        scored = []
        for w in candidates:
            s = w.load_score()
            if s is None and not w.alive():
                self._remove(model, w, "dead at pick")
                continue
            if s is not None:
                scored.append((s, w))
        if not scored:
            raise WorkerGone("no routable workers for model %r" % model)
        best = min(s for s, _ in scored)
        ties = [w for s, w in scored if s == best]
        with self._lock:
            pool = self._models[model]
            w = ties[pool.rr % len(ties)]
            pool.rr += 1
            if session:
                pool.affinity[session] = w
        return w

    def _route(self, model, call, session=None):
        """Try distinct replicas until one answers: WorkerGone removes and
        retries, ServerBusy (shed or draining) skips to a sibling. Typed
        timeouts and model errors propagate — those are answers."""
        tried = []
        last = None
        while True:
            try:
                w = self._pick(model, exclude=tried, session=session)
            except WorkerGone:
                raise last or ServerBusy(
                    "no workers available for model %r" % model)
            try:
                return call(w)
            except WorkerGone as e:
                self._remove(model, w, str(e))
                with self._lock:
                    self.retries += 1
                tried.append(w)
                last = e
            except ServerBusy as e:
                with self._lock:
                    self.retries += 1
                tried.append(w)
                last = e

    def predict(self, xs, model="default", timeout=60.0):
        """Route one inference request; retries siblings on worker loss or
        shed, so callers see an answer or a typed failure — never a
        stranded socket."""
        if not isinstance(xs, (list, tuple)):
            xs = [xs]
        return self._route(model, lambda w: w.predict(xs, timeout=timeout))

    def generate(self, prompt, model="default", session=None, timeout=120.0,
                 **kw):
        """Route one generation. ``session=`` pins a conversation to one
        replica so its PrefixCache keeps the KV pages warm across turns
        (and migrates them on retirement)."""
        return self._route(
            model, lambda w: w.generate(prompt, timeout=timeout, **kw),
            session=session)

    # ------------------------------------------------------------ control
    def hot_swap(self, params_file, model="default"):
        """Push a checkpoint to every replica of ``model``. Each replica
        validates structurally before flipping (409 → SwapError raised
        here, old weights keep serving) and flips atomically under its
        params seam — traffic keeps flowing through the whole push.
        Returns {worker name: new swap epoch}."""
        with open(params_file, "rb") as f:
            blob = f.read()
        epochs = {}
        for w in self.workers(model):
            epochs[w.name] = w.swap(blob)
            self._event("hot_swap", model=model, worker=w.name,
                        epoch=epochs[w.name])
        return epochs

    def retire(self, handle, model="default", drain_timeout_s=30.0):
        """Drain-then-retire: stop admissions on the replica, wait for its
        in-flight work to finish, migrate its prefix cache to the
        least-loaded sibling (sessions follow), then shut it down."""
        handle.drain()
        self._event("drain", model=model, worker=handle.name)
        deadline = time.perf_counter() + drain_timeout_s
        while time.perf_counter() < deadline:
            try:
                h = handle.health()
            except (WorkerGone, ServeError):
                break
            if (int(h.get("queue_depth") or 0)
                    + int(h.get("tokens_in_flight") or 0)
                    + int(h.get("in_flight") or 0)) == 0:
                break
            time.sleep(0.02)
        heir = None
        if handle.kind == "generative":
            with self._lock:
                siblings = [w for w in self._models[model].workers
                            if w is not handle]
            if siblings:
                try:
                    blob = handle.export_prefixes()
                    heir = self._pick(model, exclude=[handle])
                    n = heir.import_prefixes(blob)
                    self._event("prefix_migrate", model=model,
                                src=handle.name, dst=heir.name, entries=n)
                except (WorkerGone, ServeError):
                    heir = None  # migration is best-effort; retire anyway
        with self._lock:
            pool = self._models[model]
            if handle in pool.workers:
                pool.workers.remove(handle)
            for sess, w in list(pool.affinity.items()):
                if w is handle:
                    if heir is not None:
                        pool.affinity[sess] = heir
                    else:
                        del pool.affinity[sess]
        handle.shutdown()
        handle.reap()
        self._event("retire", model=model, worker=handle.name)

    # -------------------------------------------------------------- stats
    def stats(self):
        out = {"models": {}, "retries": self.retries,
               "workers_lost": self.workers_lost,
               "events": list(self.events)}
        for model in self.models():
            rows = []
            for w in self.workers(model):
                try:
                    rows.append({"name": w.name, "pid": w.pid,
                                 **w.health()})
                except (WorkerGone, ServeError) as e:
                    rows.append({"name": w.name, "pid": w.pid,
                                 "ok": False, "error": str(e)})
            out["models"][model] = rows
        return out

    def close(self):
        """Shut down every replica (drainless — callers wanting graceful
        retirement call retire() per worker first)."""
        for model in self.models():
            for w in self.workers(model):
                try:
                    w.shutdown()
                except Exception:
                    pass
                w.reap()
            with self._lock:
                self._models[model].workers.clear()
                self._models[model].affinity.clear()

    def __enter__(self):
        return self

    def __exit__(self, *a):
        self.close()


class Autoscaler(threading.Thread):
    """SLO-pressure autoscaler: scale out on sustained breach, drain-then-
    retire on sustained idle (ref: mxnet-model-server's management-API
    ``scale-worker``, automated).

    Breach = aggregate p95 request latency above ``slo_p95_ms`` OR sheds
    since the last check above ``shed_rate`` of admissions. Pressure
    accumulates one point per breach sample and DECAYS one per clean
    sample (shedding is bursty — requiring strictly consecutive breaches
    would let real overload hide between samples); at ``sustain`` points
    one replica spawns (up to ``max_workers``) — a single spiky sample
    still can't trigger a process spawn. ``idle`` consecutive zero-load
    checks retire the highest-index replica (down to ``min_workers``).
    All decisions land in ``router.events``."""

    def __init__(self, router, model="default", min_workers=1, max_workers=4,
                 slo_p95_ms=100.0, shed_rate=0.02, sustain=3, idle=10,
                 interval_s=0.25):
        super().__init__(daemon=True, name="fleet-autoscaler")
        self.router = router
        self.model = model
        self.min_workers = int(min_workers)
        self.max_workers = int(max_workers)
        self.slo_p95_ms = float(slo_p95_ms)
        self.shed_rate = float(shed_rate)
        self.sustain = int(sustain)
        self.idle = int(idle)
        self.interval_s = float(interval_s)
        self._halt = threading.Event()
        self._pressure = 0
        self._idle = 0
        self._last = {}              # worker name -> (requests, shed)

    def _sample(self):
        """One control-loop reading: (p95 ms, shed delta, request delta,
        live worker count, total load)."""
        p95s, shed_d, req_d, load = [], 0, 0, 0
        workers = self.router.workers(self.model)
        for w in workers:
            try:
                s = w.server_stats()
            except (WorkerGone, ServeError):
                continue
            if s.get("p95_ms") is not None:
                p95s.append(float(s["p95_ms"]))
            prev_req, prev_shed = self._last.get(w.name, (0, 0))
            req, shed = int(s.get("requests") or 0), int(s.get("shed") or 0)
            # a respawned worker restarts its counters; clamp deltas at 0
            req_d += max(0, req - prev_req)
            shed_d += max(0, shed - prev_shed)
            self._last[w.name] = (req, shed)
            load += int(s.get("queue_depth") or 0) + \
                int(s.get("tokens_in_flight") or 0)
        return (max(p95s) if p95s else None, shed_d, req_d, len(workers),
                load)

    def step(self):
        """One control decision — called by run(), and directly by tests
        (deterministic, no sleeps)."""
        p95, shed_d, req_d, n, load = self._sample()
        admitted = req_d + shed_d
        breach = ((p95 is not None and p95 > self.slo_p95_ms)
                  or (admitted > 0 and shed_d / admitted > self.shed_rate))
        if breach:
            self._pressure += 1
            self._idle = 0
            if self._pressure >= self.sustain and n < self.max_workers:
                self.router._event("autoscale_out", model=self.model,
                                   p95_ms=p95, shed=shed_d,
                                   workers=n)
                self.router.scale_out(self.model)
                self._pressure = 0
            return "breach"
        self._pressure = max(0, self._pressure - 1)
        if load == 0 and req_d == 0:
            self._idle += 1
            if self._idle >= self.idle and n > self.min_workers:
                victim = self.router.workers(self.model)[-1]
                self.router._event("autoscale_in", model=self.model,
                                   worker=victim.name, workers=n)
                self.router.retire(victim, model=self.model)
                self._idle = 0
            return "idle"
        self._idle = 0
        return "steady"

    def run(self):
        while not self._halt.wait(self.interval_s):
            try:
                self.step()
            except Exception:
                # the control loop must outlive transient scrape failures;
                # scale decisions are retried next interval
                pass

    def stop(self, timeout_s=5.0):
        self._halt.set()
        if self.is_alive():
            self.join(timeout=timeout_s)
