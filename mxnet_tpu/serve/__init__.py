"""mxnet_tpu.serve — dynamic-batching inference serving on bucketed
compiled executors.

The serving layer the reference shipped as mxnet-model-server on top of
``Module.predict``/CachedOp, rebuilt TPU-native: a model compiles once per
batch-size bucket (executor_pool), single requests coalesce into the
largest fitting bucket under a deadline (batcher), batches round-robin
over device replicas, and the whole thing is observable (metrics →
``serve.stats()`` + profiler events). See README "Serving" and
MIGRATING.md for the mxnet-model-server mapping.

Generative decode is its own scheduler (decoder.py): ``GenerativeServer``
runs token-level continuous batching over a paged fixed-capacity KV cache
(kv_cache.py) — prefill/decode split, ONE fused dispatch per token step
for all in-flight requests with sampling in-program, join/leave by slot
assignment, prefix caching, and streaming per-request iterators.

    import mxnet_tpu as mx
    net = ...hybridized block...
    with mx.serve.ModelServer(net, [((3, 224, 224), "float32")]) as srv:
        out = srv.predict(img)

    with mx.serve.GenerativeServer(gpt, slots=8) as gsrv:   # decode
        for tok in gsrv.submit([1, 2, 3], max_new_tokens=32):
            ...

    blk = mx.serve.load("export/model", epoch=0)     # warm-start a export
    mx.serve.stats()                                 # all live servers
"""
from __future__ import annotations

import weakref

from .batcher import (DynamicBatcher, ServeError, ServerBusy,  # noqa: F401
                      ServeTimeout)
from .decoder import GenerationStream, GenerativeServer  # noqa: F401
from .executor_pool import (BucketedExecutor, PoolError,  # noqa: F401
                            symbol_infer_fn)
from .fleet import (Autoscaler, FleetRouter, WorkerGone,  # noqa: F401
                    WorkerHandle, WorkerSpec)
from .kv_cache import CacheError, PagedKVCache, PrefixCache  # noqa: F401
from .metrics import GenerativeMetrics, ServeMetrics  # noqa: F401
from .server import DEFAULT_BUCKETS, ModelServer  # noqa: F401
from .speculative import ModelDraft, NGramDraft  # noqa: F401

__all__ = ["ModelServer", "GenerativeServer", "GenerationStream",
           "BucketedExecutor", "DynamicBatcher", "PagedKVCache",
           "PrefixCache", "CacheError", "ServeMetrics", "GenerativeMetrics",
           "NGramDraft", "ModelDraft",
           "FleetRouter", "Autoscaler", "WorkerSpec", "WorkerHandle",
           "WorkerGone",
           "ServeError", "ServerBusy", "ServeTimeout", "PoolError",
           "DEFAULT_BUCKETS", "load", "snapshot", "stats"]

# live-server registry for the aggregate stats() snapshot; weak so a
# dropped server never lingers (and the registry never grows unbounded)
_SERVERS = weakref.WeakSet()


def _register(server):
    _SERVERS.add(server)
    # racecheck: when the runtime lock-order/race stage is armed
    # (MXNET_LOCK_CHECK=1), new servers are instrumented at construction
    # so their condition variables, queues and slot tables are watched
    # from the first request
    try:
        from ..analysis import concurrency as _conc

        if _conc.lock_check_enabled():
            _conc.instrument_server(server)
    except Exception:
        pass


def load(prefix, epoch=0, input_names=("data",), ctx=None, snapshot=False,
         model=None, **server_kwargs):
    """Warm-start a served model.

    Default (``snapshot=False``): load an export/checkpoint layout
    (``prefix-symbol.json`` + ``prefix-NNNN.params``) and return a
    SymbolBlock with the file's exact dtypes, ready for ModelServer —
    reload compiles the same bucket programs as the exporting process
    (checkpoint.load_for_serving).

    ``snapshot=True``: load an AOT serving snapshot written by
    ``serve.snapshot`` and return a READY SERVER whose warmed programs
    are **deserialized, not compiled** —
    ``engine.serve_compile_counter``/``decode_compile_counter`` stay 0
    from process start to the first served request. Generative snapshots
    need ``model=`` (the decode protocol is code; params/config/
    executables come from the artifact). Extra kwargs reach the server
    constructor (queue/deadline knobs)."""
    if snapshot:
        from ..cache.snapshot import load_snapshot

        return load_snapshot(prefix, model=model, **server_kwargs)
    from ..checkpoint import load_for_serving

    return load_for_serving(prefix, epoch=epoch, input_names=input_names,
                            ctx=ctx)


def snapshot(server, prefix, input_names=None, epoch=0):
    """Write the AOT serving artifact for a live (warmed) server — the
    executable-shipping complement of ``checkpoint.save_for_serving``
    (TVM export_library, arXiv 1802.04799). See
    ``serve.load(prefix, snapshot=True)`` and mxnet_tpu.cache.snapshot."""
    from ..cache.snapshot import save_snapshot

    return save_snapshot(server, prefix, input_names=input_names,
                         epoch=epoch)


def stats():
    """Snapshot of every live server, keyed by server name, plus the
    process-wide compile counter — what tools/diagnose.py prints and the
    observability registry's ``serve`` collector absorbs (so every field
    here is also a Prometheus sample on the ``/metrics`` endpoint, labeled
    ``server="<name>"``)."""
    from .. import engine

    return {
        "serve_compile_counter": engine.serve_compile_counter.count,
        "decode_compile_counter": engine.decode_compile_counter.count,
        "servers": {s.name: s.stats() for s in list(_SERVERS)},
    }
