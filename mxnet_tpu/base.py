"""Shared utilities: dtype handling, jit cache, op registry.

The op registry is the TPU-native analogue of MXNet's operator registration
(ref: nnvm/src/core/op.cc, src/operator/*-inl.h NNVM_REGISTER_OP): every pure
functional op registers once and both front-ends (imperative ``nd`` and the
traced/hybridized path) are generated from it.
"""
from __future__ import annotations

import functools
import os
import threading
from typing import Callable, Dict, NamedTuple

import jax
import numpy as np

string_types = (str,)

_DTYPE_ALIASES = {
    "float16": np.float16,
    "bfloat16": jax.numpy.bfloat16,
    "float32": np.float32,
    "float64": np.float64,
    "int8": np.int8,
    "uint8": np.uint8,
    "int16": np.int16,
    "int32": np.int32,
    "int64": np.int64,
    "bool": np.bool_,
}

# fp8 storage dtypes (quantization.py fp8 modes) resolve by name where the
# jax build ships them — load_npz_exact's __dtype__ sidecars round-trip fp8
# checkpoints through resolve_dtype
for _fp8 in ("float8_e4m3fn", "float8_e5m2"):
    if hasattr(jax.numpy, _fp8):
        _DTYPE_ALIASES[_fp8] = getattr(jax.numpy, _fp8)


def is_tpu_backend():
    """True when the default backend is a TPU — including relayed platforms
    that expose the chip under a different platform name (e.g. 'axon'), which
    ``jax.default_backend() == "tpu"`` misses. Used to gate pallas kernels."""
    try:
        return jax.default_backend() in ("tpu", "axon")
    except RuntimeError:
        return False


def next_pow2(n):
    """Smallest power of two ≥ n — the shared bucket-rounding rule (serve
    batch buckets, decode cache capacities, prompt-length buckets): any
    request stream compiles at most log2(max) programs per knob instead of
    one per distinct size."""
    p = 1
    while p < n:
        p <<= 1
    return p


def resolve_dtype(dtype):
    if dtype is None:
        return None
    if isinstance(dtype, str):
        return _DTYPE_ALIASES.get(dtype, np.dtype(dtype).type)
    return dtype


def _freeze(v):
    if isinstance(v, (list, tuple)):
        return tuple(_freeze(x) for x in v)
    if isinstance(v, dict):
        return tuple(sorted((k, _freeze(x)) for k, x in v.items()))
    if isinstance(v, np.ndarray):
        return ("__np__", v.shape, str(v.dtype), v.tobytes())
    return v


def env_cap(name, default):
    """Integer cache cap from the environment (graphlint GL006 knobs)."""
    try:
        return int(os.environ.get(name, default))
    except ValueError:
        return default


class BoundedCache(dict):
    """Capped dict for module-level program/metadata caches (graphlint
    GL006: an unbounded module cache grows forever in long-running serving
    processes). Eviction is insertion-order (oldest first) and happens only
    on insert — hits stay plain-dict speed with zero LRU bookkeeping on the
    per-op hot path. Entries must be pure caches: evicting one may cost a
    recompute/recompile, never correctness. ``evictions`` counts the
    drops — observability.snapshot() surfaces it per cache, so cap churn
    in a long-running replica is visible instead of silent.

    Inserts are serialized by an internal lock: serve dispatcher threads
    populate these tables concurrently, and the unguarded
    len-check/evict/store sequence could evict twice or over-fill
    (racecheck GL011). Hits never take the lock — reads stay plain-dict
    speed. ``_insert_locked`` is a seam for analysis.concurrency's
    runtime race probe (placed *inside* the lock so correctly serialized
    writers never report)."""

    __slots__ = ("cap", "evictions", "_lk")

    def __init__(self, cap):
        super().__init__()
        self.cap = max(int(cap), 1)
        self.evictions = 0
        self._lk = threading.Lock()

    def __setitem__(self, key, value):
        with self._lk:
            self._insert_locked(key, value)

    def _insert_locked(self, key, value):
        if len(self) >= self.cap and key not in self:
            del self[next(iter(self))]
            self.evictions += 1
        dict.__setitem__(self, key, value)


# per-(op, static attrs, device) jitted callables. Keys include static-attr
# VALUES (reshape targets, axis lists), whose diversity is unbounded under
# adversarial serving traffic — hence the cap (MXNET_JIT_CACHE_CAP).
_JIT_CACHE: Dict = BoundedCache(env_cap("MXNET_JIT_CACHE_CAP", 4096))

# bulk-window FRONT memo (engine.bulk): window-structural-key →
# (program, arg selection) resolved through the canonical IR cache below.
# Steady-state epochs re-running an identical imperative chain hit this
# memo at hash-and-lookup cost — the imperative analogue of MXNet's
# CachedOp handle reuse; a miss builds the typed IR graph and resolves
# through _IR_CACHE (which is where identical math from the tape or a
# Symbol lands on the SAME compiled program).
# Capped (MXNET_BULK_CACHE_CAP): chain-topology diversity is unbounded.
_BULK_CACHE: Dict = BoundedCache(env_cap("MXNET_BULK_CACHE_CAP", 1024))

# the ONE canonical program cache (mxnet_tpu.ir.lower): content-addressed
# canonical-graph key → IREntry (optimized graph + every program lowered
# from it). The bulk/tape/symbol key schemes all collapse into this cache;
# the per-capture dicts above/below are thin front memos over it.
# MXNET_IR_CACHE_CAP bounds it; evictions are surfaced in
# observability.snapshot()["ir"].
_IR_CACHE: Dict = BoundedCache(env_cap("MXNET_IR_CACHE_CAP", 2048))


def _key_note(kind, key, limit=200):
    """Compact, truncated rendering of a program-cache key for watchdog
    attribution (observability): enough to identify the offending chain /
    tape topology in a structured warning, never the full key blob."""
    s = repr(key)
    if len(s) > limit:
        s = s[:limit - 3] + "..."
    return "%s:%s" % (kind, s)


def _jit_backed(fn, device=None, donate=None, tier="jit", hint=""):
    """The ONE funnel from this stack's program builders to jax.jit: a
    plain ``jax.jit`` when the persistent compilation store is off (the
    default — zero added overhead), a ``cache.AotFn`` when
    ``MXNET_COMP_CACHE_DIR`` is configured, so the compiled executable is
    persisted across processes (mxnet_tpu.cache Tier A). graphlint GL008
    flags direct ``jax.jit`` call sites that bypass this funnel.

    Because every capture path funnels through here, cost attribution
    (observability.costs) sees every program: the AotFn path records
    eagerly inside ``_acquire``; the plain-jit path is wrapped by
    ``costs.tracked`` (a per-call cache-size poll + lazy analysis).
    ``MXNET_COST_ATTRIBUTION=0`` restores the bare ``jax.jit`` return."""
    from .cache import persistent_backed
    from .observability import costs

    backed = persistent_backed(fn, device=device, donate_argnums=donate,
                               tier=tier, hint=hint)
    if backed is not None:
        return backed
    kw = {}
    if donate:
        kw["donate_argnums"] = tuple(donate)
    if device is not None:
        kw["device"] = device
    return costs.tracked(jax.jit(fn, **kw), tier, hint)


def bulk_jitted(key, builder):
    """LEGACY SHIM (pre-IR): cached jitted composed program for a flushed
    bulk window. The live flush path now builds a typed ``mxnet_tpu.ir``
    graph and lowers through ``ir.lower_forward`` (see
    ndarray._flush_window); this entry point remains for external callers
    that hand-compose a window program. ``key`` is the structural chain
    key; ``builder`` returns the pure replay function leaves→outputs,
    called only on a cache miss (engine.bulk_compile_counter bumps then —
    the no-recompile hook)."""
    f = _BULK_CACHE.get(key)
    if f is None:
        from .engine import bulk_compile_counter

        # note= carries the chain key to the retrace watchdog: a post-warmup
        # miss here warns with the offending topology (observability)
        bulk_compile_counter.bump(note=_key_note("bulk", key))
        f = _BULK_CACHE[key] = _jit_backed(builder(), tier="bulk",
                                           hint="bulk")
    return f


# compiled tape-replay FRONT memo (autograd.backward): structural key
# (tape topology, static attrs, leaf signatures, head set,
# grad_req/donation layout) → (program, arg selection) resolved through
# the canonical IR cache — the whole-program analogue of MXNet's nnvm
# backward graph executed via Imperative::Backward, now sharing the
# forward region's canonical form with the other captures.
# Capped like the others (MXNET_TAPE_CACHE_CAP).
_TAPE_CACHE: Dict = BoundedCache(env_cap("MXNET_TAPE_CACHE_CAP", 512))


def tape_jitted(key, builder):
    """LEGACY SHIM (pre-IR): cached jitted compiled-tape backward program.
    The live backward path now lowers the recorded region through
    ``mxnet_tpu.ir`` (autograd._compiled_backward); kept for external
    callers. ``builder`` (called only on a miss) returns
    ``(prog, donate_argnums)``; a steady-state record→backward loop must
    hit the cache every iteration — engine.tape_compile_counter (misses) /
    engine.tape_cache_hit_counter (hits) are the proof hooks tests and
    tools/diagnose.py read."""
    from .engine import tape_cache_hit_counter, tape_compile_counter

    f = _TAPE_CACHE.get(key)
    if f is None:
        tape_compile_counter.bump(note=_key_note("tape", key))
        prog, donate = builder()
        f = _TAPE_CACHE[key] = _jit_backed(prog, donate=donate or None,
                                           tier="tape", hint="tape")
    else:
        tape_cache_hit_counter.bump()
    return f


def jitted(fn: Callable, static_kwargs: dict, device=None):
    """Return a cached jitted callable of ``fn`` with the given static kwargs
    closed over. Equivalent role to MXNet's cached op handles for imperative
    invocation (ref: src/imperative/imperative.cc:InvokeOp)."""
    key = (fn, _freeze(static_kwargs), device)
    cached = _JIT_CACHE.get(key)
    if cached is None:
        f = functools.partial(fn, **static_kwargs) if static_kwargs else fn
        cached = _jit_backed(f, device=device, tier="jit",
                             hint=getattr(fn, "__name__", "op"))
        _JIT_CACHE[key] = cached
    return cached


class OpDef(NamedTuple):
    name: str
    fn: Callable
    # kwargs listed here are array-valued (traced); everything else static
    array_kwargs: tuple = ()
    # ops that need an rng key get one injected as kwarg `key`
    needs_rng: bool = False
    # ops that need the training flag get kwarg `training`
    needs_training: bool = False
    # number of outputs that are differentiable (None = all)
    nondiff: bool = False
    # tuple-returning ops declare their arity so the symbol builder can
    # mirror it with _item projections (MXNet: nnvm op num_outputs)
    n_outputs: int = 1
    # precomputed at registration: eligible for the imperative fast/lazy
    # path (single output, no rng/training-key injection) — one attr read
    # on the per-op hot loop instead of three
    fast_ok: bool = True


OP_REGISTRY: Dict[str, OpDef] = {}


def register_op(name=None, array_kwargs=(), needs_rng=False, needs_training=False, nondiff=False,
                n_outputs=1):
    def deco(fn):
        opname = name or fn.__name__
        OP_REGISTRY[opname] = OpDef(opname, fn, tuple(array_kwargs), needs_rng, needs_training,
                                    nondiff, n_outputs,
                                    n_outputs == 1 and not needs_rng and not needs_training)
        return fn

    return deco


class MXNetError(RuntimeError):
    pass


def check_call(ret):
    if ret != 0:
        raise MXNetError("native call failed with code %d" % ret)
