"""mxnet_tpu.observability — unified runtime telemetry.

One registry absorbs every signal the repo already proves its dispatch
story with — the engine ``DispatchCounter``s (dispatch + the
bulk/tape/serve/decode compile counters and comp-cache hit/miss/
deserialize), the serve/generative latency rings, the bounded program
caches, the profiler record buffer — and exports them two ways from one
``snapshot()``:

* ``observability.snapshot()`` — stable JSON; ``tools/diagnose.py``
  renders its human report from this dict and ``--json`` emits it
  verbatim;
* ``observability.prometheus()`` — Prometheus text exposition, served by
  the opt-in ``/metrics`` endpoint (``ModelServer``/``GenerativeServer``
  ``metrics_port=``, http.py).

Per-request tracing (tracing.py) threads a trace-id from ``submit()``
through queue → coalesce → pad → dispatch → (decode) token steps; the
retrace watchdog (watchdog.py) turns the zero-steady-state-retrace test
contract into a runtime alarm. The old names all still work —
``engine.dispatch_counter``, ``serve.stats()``, ``ServeMetrics`` — the
registry reads them, it does not replace them.
"""
from __future__ import annotations

from . import costs  # noqa: F401
from . import watchdog  # noqa: F401
from .http import MetricsHTTPServer  # noqa: F401
from .registry import (Counter, Gauge, Histogram,  # noqa: F401
                       MetricsRegistry, render_prometheus)
from .tracing import (RequestTrace, new_trace, set_tracing,  # noqa: F401
                      tracing_enabled)

__all__ = ["registry", "snapshot", "prometheus", "MetricsRegistry",
           "Counter", "Gauge", "Histogram", "RequestTrace", "new_trace",
           "set_tracing", "tracing_enabled", "arm_watchdog",
           "disarm_watchdog", "MetricsHTTPServer", "enable_op_telemetry",
           "op_telemetry_enabled", "note_compile", "render_prometheus",
           "device_section", "costs"]

# the process-wide default registry (module-level by design: it is the
# blessed home for metric state — graphlint GL009 polices ad-hoc metric
# state anywhere else)
registry = MetricsRegistry()

arm_watchdog = watchdog.arm
disarm_watchdog = watchdog.disarm

# compile accounting (fed by cache.AotFn around lower/compile): cumulative
# XLA compile wall-time + count — the "is this replica compiling under
# traffic" gauge the watchdog's per-event warnings aggregate into
_compiles_total = registry.counter(
    "compiles_total", "explicit lower/compile builds (cache.AotFn)")
_compile_seconds = registry.counter(
    "compile_seconds_total", "wall-clock seconds spent in lower/compile")


def note_compile(seconds):
    _compiles_total.inc()
    _compile_seconds.inc(float(seconds))


# ---------------------------------------------------------- op telemetry
# per-op-name dispatch counts from the imperative hot loop. Off by default:
# ndarray.invoke reads ONE precomputed module boolean (the _prof_on trick);
# when on, the cost is one dict increment per op into this registry-owned
# dict (bounded by len(OP_REGISTRY)).
_op_counts = {}


def enable_op_telemetry(on=True):
    """Count imperative dispatches per op name (``snapshot()['ops']``).
    Returns the previous state."""
    from .. import ndarray as _nd

    prev = _nd._obs_on
    _nd._obs_counts = _op_counts
    _nd._obs_on = bool(on)
    return prev


def op_telemetry_enabled():
    from .. import ndarray as _nd

    return _nd._obs_on


# ------------------------------------------------------------- collectors
def _collect_engine():
    from .. import engine

    return {
        "dispatch": engine.dispatch_counter.count,
        "bulk_compile": engine.bulk_compile_counter.count,
        "tape_compile": engine.tape_compile_counter.count,
        "tape_cache_hit": engine.tape_cache_hit_counter.count,
        "symbol_compile": engine.symbol_compile_counter.count,
        "serve_compile": engine.serve_compile_counter.count,
        "decode_compile": engine.decode_compile_counter.count,
        "comp_cache_hit": engine.comp_cache_hit_counter.count,
        "comp_cache_miss": engine.comp_cache_miss_counter.count,
        "comp_cache_deserialize": engine.comp_cache_deserialize_counter.count,
        "dist_bucket": engine.dist_bucket_counter.count,
        "dist_compile": engine.dist_compile_counter.count,
    }


def _collect_caches():
    from .. import base
    from ..autograd import tape_compile_enabled
    from ..ir import graph as irgraph

    return {
        "jit": {"entries": len(base._JIT_CACHE), "cap": base._JIT_CACHE.cap,
                "evictions": base._JIT_CACHE.evictions},
        "bulk": {"entries": len(base._BULK_CACHE),
                 "cap": base._BULK_CACHE.cap,
                 "evictions": base._BULK_CACHE.evictions},
        "tape": {"entries": len(base._TAPE_CACHE),
                 "cap": base._TAPE_CACHE.cap,
                 "evictions": base._TAPE_CACHE.evictions,
                 "compile_enabled": tape_compile_enabled()},
        "ir": {"entries": len(base._IR_CACHE), "cap": base._IR_CACHE.cap,
               "evictions": base._IR_CACHE.evictions},
        "aval": {"entries": len(irgraph._AVAL_CACHE),
                 "cap": irgraph._AVAL_CACHE.cap,
                 "evictions": irgraph._AVAL_CACHE.evictions},
        "sig_intern": {"entries": len(irgraph._SIG_IDS),
                       "cap": irgraph._SIG_INTERN_CAP},
    }


def _collect_comp_cache():
    from .. import cache

    return cache.stats()


def _collect_serve():
    from .. import serve

    return serve.stats()


def _collect_profiler():
    from .. import profiler

    return {
        "running": profiler.is_running(),
        "records": profiler.num_records(),
        "records_cap": profiler.record_cap(),
        "records_dropped": profiler.records_dropped(),
    }


def _collect_ops():
    # copy under the GIL: the hot loop mutates this dict lock-free
    return {"enabled": op_telemetry_enabled(), "dispatches": dict(_op_counts)}


def _collect_ir():
    # unified graph IR (mxnet_tpu.ir): canonical-cache occupancy +
    # evictions, the shared signature interner, build tallies, and the
    # per-pass node/edge delta counters — tools/diagnose.py's "Graph IR"
    # section renders this dict
    from ..ir import lower as irlower

    return irlower.stats()


def _collect_dist():
    # distributed gradient exchange (mxnet_tpu.dist) + resilience events.
    # The registry counters are get-or-create so the section is complete
    # (zeros) even before the first stall/save/restore; the subsystem
    # stats only appear once mxnet_tpu.dist has actually been imported —
    # a collector must never force-load the package it observes.
    import sys

    from .. import engine

    out = {
        "bucket_dispatches": engine.dist_bucket_counter.count,
        "bucket_compiles": engine.dist_compile_counter.count,
        "heartbeat_stalls": registry.counter(
            "dist_heartbeat_stalls",
            "device round-trips exceeding the heartbeat timeout").value,
        "checkpoint_saves": registry.counter(
            "dist_checkpoint_saves", "sharded checkpoint writes").value,
        "checkpoint_restores": registry.counter(
            "dist_checkpoint_restores", "sharded checkpoint restores").value,
        "elastic_recoveries": registry.counter(
            "dist_elastic_recoveries",
            "mesh re-formations after a replica loss").value,
    }
    d = sys.modules.get("mxnet_tpu.dist")
    if d is not None:
        out.update(d.stats())
    else:
        out["subsystem"] = "not loaded"
    return out


def _collect_quant():
    # quantized inference (mxnet_tpu.quant): swap/calibration tallies from
    # the quantization module's fixed-key stats table. Like dist, the
    # subsystem detail only appears once the module has actually been
    # imported — a collector must never force-load the package it
    # observes.
    import sys

    q = sys.modules.get("mxnet_tpu.quantization")
    if q is None:
        return {"subsystem": "not loaded"}
    return q.stats()


def _collect_costs():
    # per-program cost attribution (costs.py): drains any parked lowered
    # handles (the one place the lazy path pays its explicit compiles),
    # then reports bounded profiles + per-tier totals + the live-server
    # HBM ledger
    return costs.snapshot_section()


def _collect_concurrency():
    # racecheck runtime stage (analysis.concurrency): lock-order graph
    # size, deadlock cycles, race reports. Brief form — stacks stay in
    # concurrency.runtime_stats(verbose=True) / tools/diagnose.py
    from ..analysis import concurrency

    return concurrency.runtime_stats()


def _collect_hlolint():
    # program-level StableHLO lint (analysis.hlolint, ISSUE 18): ranked
    # findings over every program captured at the costs seam. Drains the
    # lazy cost path first so the corpus is complete at scrape time, and
    # joins the cost ledger so findings rank by real bytes
    if costs.enabled():
        costs.materialize()
    from ..analysis import hlolint

    return hlolint.snapshot_section(costs.profiles())


def _collect_tune():
    # IR autotuner (ir.tune): search telemetry + tuned-config store
    # shape. Same never-force-load rule as dist/quant — tuning telemetry
    # only appears once something actually imported the tuner.
    import sys

    t = sys.modules.get("mxnet_tpu.ir.tune")
    if t is None:
        return {"subsystem": "not loaded"}
    return t.stats()


registry.register_collector("engine", _collect_engine)
registry.register_collector("concurrency", _collect_concurrency)
registry.register_collector("costs", _collect_costs)
registry.register_collector("hlolint", _collect_hlolint)
registry.register_collector("dist", _collect_dist)
registry.register_collector("quant", _collect_quant)
registry.register_collector("caches", _collect_caches)
registry.register_collector("comp_cache", _collect_comp_cache)
registry.register_collector("serve", _collect_serve)
registry.register_collector("profiler", _collect_profiler)
registry.register_collector("ops", _collect_ops)
registry.register_collector("ir", _collect_ir)
registry.register_collector("tune", _collect_tune)
registry.register_collector("watchdog", watchdog.snapshot)
registry.register_collector(
    "tracing", lambda: {"enabled": tracing_enabled()})


def device_section():
    """HBM live-buffer gauges from the XLA client's own accounting
    (authoritative on TPU — jax owns the HBM pool). Separate from the
    collector set because a device probe can block when the accelerator
    relay is down (``diagnose.py --no-device``)."""
    from .. import profiler

    try:
        stats = profiler.device_memory_summary()
    except Exception as e:
        return {"error": "%s: %s" % (type(e).__name__, e)}
    return {"hbm_bytes_in_use": stats.get("bytes_in_use"),
            "hbm_peak_bytes_in_use": stats.get("peak_bytes_in_use"),
            "hbm_bytes_limit": stats.get("bytes_limit")}


def snapshot(device=False):
    """The stable JSON telemetry snapshot: registry metrics + every
    collector section. ``device=True`` adds the HBM gauges (it probes the
    backend, which can block on a downed relay — opt in)."""
    snap = registry.snapshot()
    if device:
        snap["device"] = device_section()
    return snap


def prometheus(device=False):
    """Prometheus text exposition of :func:`snapshot` — the ``/metrics``
    payload."""
    return render_prometheus(snapshot(device=device))
