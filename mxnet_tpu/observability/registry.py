"""Metrics registry — counters, gauges, bounded histograms.

The one place runtime telemetry lives (ref: mxnet-model-server's
mms/metrics MetricsStore, here process-wide instead of per-worker).
Everything the repo already proves with ad-hoc state — the engine
dispatch/compile ``DispatchCounter``s, the serve latency rings, the
comp-cache tallies, the bounded program caches — is *absorbed* by
registered collectors (pull model: the existing objects stay the source
of truth and keep their names/APIs; the registry reads them at snapshot
time, so the hot paths pay nothing). New telemetry is created through
:class:`MetricsRegistry` — graphlint GL009 flags ad-hoc metric state
declared anywhere else.

Two export shapes, both derived from one ``snapshot()`` dict:

* stable JSON (``observability.snapshot()``) — what
  ``tools/diagnose.py --json`` emits verbatim;
* Prometheus text exposition (:func:`render_prometheus`) — what the
  opt-in ``/metrics`` HTTP endpoint serves.

Histograms are bounded rings (the ``ServeMetrics`` discipline — O(1) per
observation, no unbounded growth in long-running replicas; the GL006
concern applied to telemetry itself).
"""
from __future__ import annotations

import threading


class Counter:
    """Monotonic counter. ``inc()`` takes the metric's own lock — this is
    for control-plane events (compiles, sheds, HTTP scrapes), not the
    per-op hot loop; the hot loop keeps its lock-free ``DispatchCounter``s
    and the registry reads them through a collector."""

    __slots__ = ("name", "help", "_value", "_lock")

    def __init__(self, name, help=""):
        self.name = name
        self.help = help
        self._value = 0
        self._lock = threading.Lock()

    def inc(self, n=1):
        with self._lock:
            self._value += n

    @property
    def value(self):
        return self._value


class Gauge:
    """Point-in-time value: ``set()`` stores one, or ``set_fn()`` installs
    a zero-arg callable evaluated lazily at snapshot time (how live sizes —
    cache entries, HBM bytes — are exposed without any push-site wiring)."""

    __slots__ = ("name", "help", "_value", "_fn")

    def __init__(self, name, help=""):
        self.name = name
        self.help = help
        self._value = None
        self._fn = None

    def set(self, value):
        self._value = value

    def set_fn(self, fn):
        self._fn = fn
        return self

    @property
    def value(self):
        if self._fn is not None:
            try:
                return self._fn()
            except Exception:
                return None
        return self._value


class Histogram:
    """Bounded-ring histogram with nearest-rank p50/p95/p99 — the same
    estimator and O(1)-per-observation ring as ``ServeMetrics``."""

    __slots__ = ("name", "help", "_window", "_ring", "_n", "_sum", "_lock")

    def __init__(self, name, help="", window=2048):
        self.name = name
        self.help = help
        self._window = int(window)
        self._ring = [0.0] * self._window
        self._n = 0
        self._sum = 0.0
        self._lock = threading.Lock()

    def observe(self, value):
        with self._lock:
            self._ring[self._n % self._window] = float(value)
            self._n += 1
            self._sum += float(value)

    @staticmethod
    def _pctiles(vals):
        # explicit empty guard: percentiles of nothing are None (rendered
        # as absent samples), never a silent 0.0 that reads as "fast"
        n = len(vals)
        if n == 0:
            return {"p50": None, "p95": None, "p99": None}
        pick = lambda q: vals[min(n - 1, int(q * (n - 1) + 0.5))]  # noqa: E731
        return {"p50": round(pick(0.50), 4), "p95": round(pick(0.95), 4),
                "p99": round(pick(0.99), 4)}

    def percentiles(self):
        with self._lock:
            vals = sorted(self._ring[:min(self._n, self._window)])
        return self._pctiles(vals)

    def snapshot(self):
        # count, sum, and the ring are read as ONE locked view — a
        # concurrent observe() can otherwise tear count from sum (count
        # incremented, sum not yet) and the snapshot lies about the mean
        with self._lock:
            count = self._n
            total = self._sum
            vals = sorted(self._ring[:min(self._n, self._window)])
        out = {"count": count, "sum": round(total, 4)}
        out.update(self._pctiles(vals))
        return out


class MetricsRegistry:
    """Named metrics + named collectors. ``counter``/``gauge``/``histogram``
    are get-or-create (idempotent across modules); ``register_collector``
    hooks a zero-arg callable whose dict return becomes a top-level section
    of :meth:`snapshot` — how the pre-existing signals (engine counters,
    serve rings, comp-cache) are absorbed without rewiring their owners."""

    def __init__(self):
        self._lock = threading.Lock()
        self._counters = {}
        self._gauges = {}
        self._histograms = {}
        self._collectors = {}   # section name -> fn() -> dict

    # ------------------------------------------------------------ creation
    def counter(self, name, help=""):
        with self._lock:
            m = self._counters.get(name)
            if m is None:
                m = self._counters[name] = Counter(name, help)
            return m

    def gauge(self, name, help=""):
        with self._lock:
            m = self._gauges.get(name)
            if m is None:
                m = self._gauges[name] = Gauge(name, help)
            return m

    def histogram(self, name, help="", window=2048):
        with self._lock:
            m = self._histograms.get(name)
            if m is None:
                m = self._histograms[name] = Histogram(name, help, window)
            return m

    def register_collector(self, section, fn):
        with self._lock:
            self._collectors[section] = fn

    # ------------------------------------------------------------ export
    def snapshot(self):
        """Stable JSON-able dict: one ``metrics`` section for registry-owned
        metrics plus one section per collector. Collector failures degrade
        to an ``error`` entry — a snapshot must never raise (it is the
        diagnose/HTTP surface)."""
        with self._lock:
            counters = dict(self._counters)
            gauges = dict(self._gauges)
            histograms = dict(self._histograms)
            collectors = dict(self._collectors)
        out = {"schema": 1}
        metrics = {
            "counters": {n: c.value for n, c in sorted(counters.items())},
            "gauges": {n: g.value for n, g in sorted(gauges.items())},
            "histograms": {n: h.snapshot()
                           for n, h in sorted(histograms.items())},
        }
        out["metrics"] = metrics
        for section in sorted(collectors):
            try:
                out[section] = collectors[section]()
            except Exception as e:  # snapshot never raises
                out[section] = {"error": "%s: %s" % (type(e).__name__, e)}
        return out


def _sanitize(name):
    out = []
    for ch in str(name):
        out.append(ch if ch.isalnum() or ch == "_" else "_")
    s = "".join(out)
    return "_" + s if s and s[0].isdigit() else (s or "_")


def _walk(prefix, value, labels, lines):
    """Flatten a snapshot subtree into Prometheus samples. Numeric leaves
    become gauges named by their path; the ``servers`` map becomes a
    ``server=\"name\"`` label instead of a path component (per-replica
    aggregation is the whole point of the label)."""
    if isinstance(value, bool):
        lines.append((prefix, labels, int(value)))
    elif isinstance(value, (int, float)):
        lines.append((prefix, labels, value))
    elif isinstance(value, dict):
        for k, v in sorted(value.items()):
            if k == "servers" and isinstance(v, dict):
                for sname, sval in sorted(v.items()):
                    _walk(prefix + "_server", sval,
                          labels + (("server", sname),), lines)
            elif k == "profiles" and isinstance(v, dict):
                # cost-attribution profiles: the "tier:key" map becomes a
                # program="..." label (same reasoning as servers — the
                # per-program aggregation is the point of the label)
                for pname, pval in sorted(v.items()):
                    _walk(prefix + "_program", pval,
                          labels + (("program", pname),), lines)
            elif k.endswith("_by_bucket") and isinstance(v, dict):
                # per-bucket splits (e.g. TTFT by pow2 prompt length):
                # the bucket key becomes a bucket="..." label so one
                # metric name carries the whole distribution family
                stem = k[:-len("_by_bucket")]
                for bname, bval in sorted(
                        v.items(), key=lambda it: str(it[0])):
                    _walk(prefix + "_" + _sanitize(stem) if prefix
                          else _sanitize(stem), bval,
                          labels + (("bucket", bname),), lines)
            else:
                _walk(prefix + "_" + _sanitize(k) if prefix
                      else _sanitize(k), v, labels, lines)
    # strings/None/lists are descriptive, not samples — skipped


def render_prometheus(snap, prefix="mxtpu"):
    """Prometheus text exposition (v0.0.4) of a snapshot dict. Counter-like
    sections (engine counters, registry counters) get ``# TYPE ... counter``;
    everything else is a gauge."""
    samples = []
    _walk("", snap, (), samples)
    counter_prefixes = ("engine_", "metrics_counters_")
    out = []
    seen_type = set()
    for name, labels, value in samples:
        if name in ("schema",):
            continue
        full = "%s_%s" % (prefix, name)
        if full not in seen_type:
            seen_type.add(full)
            # histogram _sum/_count are monotonic series (Prometheus
            # summary convention) — typing them gauge breaks rate()
            kind = "counter" if (
                name.startswith(counter_prefixes)
                or (name.startswith("metrics_histograms_")
                    and name.endswith(("_sum", "_count")))) else "gauge"
            out.append("# TYPE %s %s" % (full, kind))
        label_s = ""
        if labels:
            label_s = "{%s}" % ",".join(
                '%s="%s"' % (_sanitize(k), str(v).replace('"', "'"))
                for k, v in labels)
        if isinstance(value, float):
            out.append("%s%s %.6g" % (full, label_s, value))
        else:
            out.append("%s%s %d" % (full, label_s, value))
    return "\n".join(out) + "\n"
