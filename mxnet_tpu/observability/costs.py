"""Per-program cost attribution: flops / bytes / peak-HBM as telemetry.

XLA's compiled executables already answer "what does this program cost"
on every backend: ``Compiled.cost_analysis()`` reports flops and bytes
accessed, ``Compiled.memory_analysis()`` reports argument / output /
temp / aliased buffer sizes — all deterministic per (program, jax
version, backend), including on CPU. This module turns every program
built through the ``base._jit_backed`` funnel (imperative jit ops, bulk
windows, tape replays, hybrid blocks, Symbol executors, serve buckets,
decode steps, dist buckets, the fused optimizer step) into a recorded
:class:`CostProfile`, keyed ``(tier, key)`` where ``key`` follows the
persistent comp-cache's content-address discipline — a sha256 over the
lowered StableHLO text, so the same program gets the same key in every
process.

Two recording paths, matching the funnel's two shapes:

* ``cache.AotFn`` (serve/decode always; every tier when the persistent
  store is on): the executable is acquired explicitly in ``_acquire``,
  so :func:`record_compiled` profiles it on the spot — zero extra
  compiles, two XLA property reads.
* plain ``jax.jit`` (the store-off default): :func:`tracked` wraps the
  jit callable. After each call it polls the wrapper's executable-cache
  size (one cheap probe on the hot path); on growth it parks the
  *lowered* handle on a bounded pending list. The analysis needs a
  ``Compiled``, which jax's dispatch cache does not expose — pending
  entries are materialized LAZILY at snapshot time
  (:func:`materialize`), so a train/serve loop never pays the one extra
  explicit compile inline.

Surfaced as ``observability.snapshot()["costs"]`` (a registry
collector), in the Prometheus exposition (``profiles`` become
``program="tier:key"``-labelled samples), and ranked by
``tools/cost_report.py`` — whose ``--quick`` artifact pins the
flops/bytes/peak-HBM columns of the pinned bench programs as a CI gate
(tests/test_costs.py).

Kill switch: ``MXNET_COST_ATTRIBUTION=0`` (or :func:`set_enabled`) —
the funnel then returns bare ``jax.jit`` callables and every record
call is a no-op.
"""
from __future__ import annotations

import hashlib
import os
import sys
import threading

import jax


def _env_enabled():
    v = os.environ.get("MXNET_COST_ATTRIBUTION", "1").strip().lower()
    return v not in ("0", "false", "off", "no")


_enabled = _env_enabled()
_lock = threading.Lock()
# bounded like every other telemetry structure (the GL006 concern applied
# to telemetry itself): program diversity is unbounded under adversarial
# shapes, profiles and parked handles are not
_PROFILE_CAP = max(int(os.environ.get("MXNET_COST_PROFILE_CAP", "512")), 1)
_PENDING_CAP = max(int(os.environ.get("MXNET_COST_PENDING_CAP", "256")), 1)
_profiles = {}          # (tier, key) -> CostProfile, insertion-ordered
_pending = []           # (tier, hint, jax.stages.Lowered) awaiting analysis
_dropped = 0            # profiles/pending evicted past the caps
_errors = 0             # analysis failures swallowed (never break dispatch)

_FIELDS = ("flops", "bytes_accessed", "output_bytes", "argument_bytes",
           "alias_bytes", "temp_bytes", "generated_code_bytes",
           "peak_hbm_bytes")


class CostProfile:
    """One compiled program's deterministic cost columns.

    ``peak_hbm_bytes`` is the program's working set — arguments +
    outputs + XLA temp buffers, minus aliased (donated) bytes, which
    would otherwise be double-counted."""

    __slots__ = ("tier", "key", "hint", "builds") + _FIELDS

    def __init__(self, tier, key, hint, **cols):
        self.tier = tier
        self.key = key
        self.hint = hint
        self.builds = 1
        for f in _FIELDS:
            setattr(self, f, cols.get(f, 0))

    def as_dict(self):
        d = {"tier": self.tier, "key": self.key, "hint": self.hint,
             "builds": self.builds}
        for f in _FIELDS:
            d[f] = getattr(self, f)
        return d


def program_key(lowered_text):
    """Content address of a program: sha256 over its lowered StableHLO
    text — the same text the comp-cache's ``store.digest`` hashes, so the
    key is stable across processes for the same program + jax version.
    Truncated to 16 hex chars for label/report use."""
    h = hashlib.sha256()
    h.update(lowered_text.encode("utf-8")
             if isinstance(lowered_text, str) else lowered_text)
    return h.hexdigest()[:16]


def _analyze(compiled):
    """Cost columns from a ``jax.stages.Compiled``. Both XLA surfaces are
    best-effort per backend — missing properties degrade to zeros, never
    to an exception."""
    cols = {f: 0 for f in _FIELDS}
    try:
        ca = compiled.cost_analysis()
    except Exception:
        ca = None
    if isinstance(ca, (list, tuple)):
        ca = ca[0] if ca else None
    if isinstance(ca, dict):
        cols["flops"] = float(ca.get("flops", 0.0) or 0.0)
        cols["bytes_accessed"] = float(ca.get("bytes accessed", 0.0) or 0.0)
        cols["output_bytes"] = float(ca.get("bytes accessedout{}", 0.0)
                                     or 0.0)
    try:
        mem = compiled.memory_analysis()
    except Exception:
        mem = None
    if mem is not None:
        arg = int(getattr(mem, "argument_size_in_bytes", 0) or 0)
        out = int(getattr(mem, "output_size_in_bytes", 0) or 0)
        tmp = int(getattr(mem, "temp_size_in_bytes", 0) or 0)
        ali = int(getattr(mem, "alias_size_in_bytes", 0) or 0)
        cols["argument_bytes"] = arg
        cols["alias_bytes"] = ali
        cols["temp_bytes"] = tmp
        cols["generated_code_bytes"] = int(
            getattr(mem, "generated_code_size_in_bytes", 0) or 0)
        if out:
            cols["output_bytes"] = out
        cols["peak_hbm_bytes"] = arg + out + tmp - ali
    return cols


def _put(tier, key, hint, cols):
    global _dropped
    with _lock:
        prof = _profiles.get((tier, key))
        if prof is not None:
            prof.builds += 1
            return prof
        if len(_profiles) >= _PROFILE_CAP:
            _profiles.pop(next(iter(_profiles)))
            _dropped += 1
        prof = CostProfile(tier, key, hint, **cols)
        _profiles[(tier, key)] = prof
        return prof


def _hlolint_capture(tier, hint, key, lowered):
    """Hand the lowered program to the hlolint corpus (ISSUE 18): the
    same seam that records costs also captures the StableHLO text for
    program-level lint. Never raises into the record path; hlolint has
    its own kill switch + bounded corpus."""
    try:
        from mxnet_tpu.analysis import hlolint
        hlolint.capture(tier, hint, key, lowered)
    except Exception:
        pass


def record_compiled(tier, hint, lowered, compiled):
    """EAGER record (cache.AotFn._acquire): the ``Compiled`` is already
    in hand, so profiling costs two XLA property reads and one hash."""
    global _errors
    if not _enabled:
        return None
    try:
        key = program_key(lowered.as_text())
        _hlolint_capture(tier, hint, key, lowered)
        return _put(tier, key, hint, _analyze(compiled))
    except Exception:
        _errors += 1
        return None


class _TrackedJit:
    """Thin cost-attribution wrapper over a ``jax.jit`` callable (the
    store-off funnel shape). Forwards the call, polls the wrapper's
    executable-cache size, and on growth parks the lowered handle for
    lazy analysis. Attribute access delegates to the jit wrapper, so
    ``lower``/``eval_shape``/``__wrapped__`` users are unaffected;
    ``cache.traceable`` passes it through unchanged (it inlines under an
    outer trace exactly like the bare jit callable)."""

    __slots__ = ("_jit", "_tier", "_hint", "_seen")

    def __init__(self, jitfn, tier, hint):
        self._jit = jitfn
        self._tier = tier
        self._hint = hint
        self._seen = 0

    def __call__(self, *args, **kwargs):
        out = self._jit(*args, **kwargs)
        try:
            n = self._jit._cache_size()
        except Exception:
            return out
        if n != self._seen:
            self._seen = n
            self._note(args, kwargs)
        return out

    def _note(self, args, kwargs):
        global _dropped, _errors
        if not _enabled or not jax.core.trace_state_clean():
            return
        try:
            # lower() reads avals only — safe even when the call just
            # donated (and deleted) its input buffers
            lowered = self._jit.lower(*args, **kwargs)
        except Exception:
            _errors += 1
            return
        with _lock:
            if len(_pending) >= _PENDING_CAP:
                _pending.pop(0)
                _dropped += 1
            _pending.append((self._tier, self._hint, lowered))

    def __getattr__(self, name):
        return getattr(self._jit, name)


def tracked(jitfn, tier="jit", hint=""):
    """Wrap a jit callable for cost attribution; returns it unwrapped
    when compiles can't be observed (no ``_cache_size`` probe — e.g. a
    non-jit callable handed through the funnel by a test double)."""
    if not _enabled or not hasattr(jitfn, "_cache_size"):
        return jitfn
    return _TrackedJit(jitfn, tier, hint)


def materialize(limit=None):
    """Compile + analyze parked programs (snapshot time). Each unique
    program costs ONE explicit compile here — jax's dispatch cache and
    the AOT ``Lowered.compile()`` do not share executables — and repeats
    are deduplicated by content key before compiling. Returns the number
    of pending entries drained."""
    global _errors
    done = 0
    while limit is None or done < limit:
        with _lock:
            if not _pending:
                break
            tier, hint, lowered = _pending.pop(0)
        done += 1
        try:
            key = program_key(lowered.as_text())
            _hlolint_capture(tier, hint, key, lowered)
            with _lock:
                prof = _profiles.get((tier, key))
            if prof is not None:
                with _lock:
                    prof.builds += 1
                continue
            _put(tier, key, hint, _analyze(lowered.compile()))
        except Exception:
            _errors += 1
    return done


def profiles():
    """Recorded profiles as ``{"tier:key": dict}`` (copies)."""
    with _lock:
        return {"%s:%s" % (t, k): p.as_dict()
                for (t, k), p in _profiles.items()}


# ------------------------------------------------------------ HBM ledger
def _params_nbytes(block):
    total = 0
    for p in block.collect_params().values():
        try:
            total += int(p.data()._data.nbytes)
        except Exception:
            pass
    return total


def _server_ledger(s):
    row = {"params_bytes": _params_nbytes(s.model)}
    cache = getattr(s, "cache", None)
    if cache is not None and hasattr(cache, "nbytes"):
        row["kv_cache_bytes"] = int(cache.nbytes())
        row["kv_cache_bytes_unquantized"] = int(cache.nbytes_unquantized())
    with _lock:
        peaks = [p.peak_hbm_bytes for (t, _k), p in _profiles.items()
                 if t in ("serve", "decode")]
    row["program_peak_bytes"] = int(max(peaks)) if peaks else 0
    row["total_bytes"] = (row["params_bytes"] + row.get("kv_cache_bytes", 0)
                          + row["program_peak_bytes"])
    return row


def hbm_ledger():
    """Per-live-server HBM accounting: parameter bytes (live arrays),
    paged-KV bytes (``PagedKVCache.nbytes()`` — exact and
    quantization-aware, the int8 pages count their fp32 scale planes),
    and the peak serve/decode program working set from the recorded
    profiles. Only servers self-register (``serve._SERVERS``); trainer
    rows are built by callers via :func:`trainer_ledger`."""
    out = {"servers": {}}
    serve = sys.modules.get("mxnet_tpu.serve")
    if serve is None:
        out["subsystem"] = "not loaded"
        return out
    for s in list(getattr(serve, "_SERVERS", ())):
        try:
            out["servers"][s.name] = _server_ledger(s)
        except Exception as e:
            out["servers"][getattr(s, "name", "?")] = {
                "error": "%s: %s" % (type(e).__name__, e)}
    return out


def trainer_ledger(trainer):
    """HBM row for a ``gluon.Trainer``: parameter + gradient + optimizer
    state bytes (live arrays) plus the peak jit-tier program working set
    — the training-side counterpart of a server's ledger row."""
    import jax.tree_util as jtu

    params_b = grads_b = 0
    for p in getattr(trainer, "_params", ()):
        try:
            params_b += int(p.data()._data.nbytes)
        except Exception:
            pass
        try:
            g = p.grad()
            grads_b += int(getattr(g, "_data", g).nbytes)
        except Exception:
            pass
    states_b = 0
    for attr in ("_states", "_state", "_updaters"):
        st = getattr(trainer, attr, None)
        if st:
            for leaf in jtu.tree_leaves(st):
                states_b += int(getattr(leaf, "nbytes", 0) or 0)
            break
    with _lock:
        peaks = [p.peak_hbm_bytes for (t, _k), p in _profiles.items()
                 if t == "jit"]
    row = {"params_bytes": params_b, "grads_bytes": grads_b,
           "optimizer_state_bytes": states_b,
           "program_peak_bytes": int(max(peaks)) if peaks else 0}
    row["total_bytes"] = sum(row.values())
    return row


# -------------------------------------------------------------- snapshot
def snapshot_section():
    """The ``snapshot()["costs"]`` section (registry collector): bounded,
    JSON-able, never raises past the registry's collector guard.
    Materializes parked programs first so the section is complete at
    scrape time — the one place the lazy path pays its explicit
    compiles."""
    if _enabled:
        materialize()
    profs = profiles()
    with _lock:
        pend, dropped, errors = len(_pending), _dropped, _errors
    totals = {}
    for prof in profs.values():
        t = totals.setdefault(prof["tier"], {
            "programs": 0, "flops": 0.0, "bytes_accessed": 0.0,
            "peak_hbm_bytes": 0})
        t["programs"] += 1
        t["flops"] += prof["flops"]
        t["bytes_accessed"] += prof["bytes_accessed"]
        t["peak_hbm_bytes"] = max(t["peak_hbm_bytes"],
                                  prof["peak_hbm_bytes"])
    return {"enabled": _enabled, "profiles": profs, "totals": totals,
            "pending": pend, "dropped": dropped, "errors": errors,
            "ledger": hbm_ledger()}


# ------------------------------------------------------------- switches
def enabled():
    return _enabled


def set_enabled(on=True):
    """Runtime kill switch (also ``MXNET_COST_ATTRIBUTION=0`` at import).
    Returns the previous state. Programs built while disabled are never
    retroactively profiled — the funnel returned them unwrapped."""
    global _enabled
    prev = _enabled
    _enabled = bool(on)
    return prev


def reset():
    """Test hook: drop every recorded profile and parked handle."""
    global _dropped, _errors
    with _lock:
        _profiles.clear()
        del _pending[:]
        _dropped = _errors = 0
