"""Retrace/recompile watchdog — anomaly detection on the compile counters.

The repo's zero-steady-state-retrace contract is proven by tests; in a
long-running replica the same contract is *enforced at runtime* by this
watchdog: after warmup (``arm()``), any increment of a ``*_compile_counter``
— ``bulk``/``tape``/``serve``/``decode`` — logs ONE structured warning per
event with the offending cache key, through the stdlib ``logging`` module
(logger ``mxnet_tpu.observability.watchdog``), and records it in a bounded
``events`` ring the registry snapshot exposes.

Key attribution: the cache-miss sites that own a key pass it directly
(``base.bulk_jitted``/``tape_jitted`` → ``bump(note=...)``); the serve and
decode counters bump INSIDE traced bodies, so ``cache.AotFn`` brackets its
lower/compile with :func:`compile_context` and the hook reads the
thread-local description (``serve:mlp:r0 sig=...`` / ``decode:step@c64``).

Arming is explicit (``observability.arm_watchdog()`` or
``MXNET_RETRACE_WATCHDOG=1``): warmup-time compiles are expected, and
deliberate later builds (a new bucket, a capacity growth) are policy the
operator opts into watching.
"""
from __future__ import annotations

import json
import logging
import os
import threading
import time

logger = logging.getLogger("mxnet_tpu.observability.watchdog")

_EVENT_CAP = 256
events = []                 # bounded ring of structured event dicts
_armed = False
_lock = threading.Lock()
_tls = threading.local()    # .ctx — current compile-site description


class compile_context:
    """Thread-local description of the program being lowered/compiled —
    set by ``cache.AotFn`` so a post-warmup retrace warning can name the
    offending program even when the counter bump sits inside the traced
    body."""

    __slots__ = ("_desc", "_prev")

    def __init__(self, desc):
        self._desc = desc

    def __enter__(self):
        self._prev = getattr(_tls, "ctx", None)
        _tls.ctx = self._desc
        return self

    def __exit__(self, *a):
        _tls.ctx = self._prev


def current_context():
    return getattr(_tls, "ctx", None)


def _on_compile(counter, n, note):
    """DispatchCounter watch hook: one structured warning per post-warmup
    compile event, with the best key attribution available."""
    key = note if note is not None else current_context()
    evt = {
        "event": "retrace_after_warmup",
        "counter": counter.name or "compile",
        "key": str(key) if key is not None else "<unattributed jit site>",
        "count": counter.count,
        "ts": time.time(),
    }
    with _lock:
        if len(events) >= _EVENT_CAP:
            del events[0]
        events.append(evt)
    logger.warning("retrace after warmup: %s",
                   json.dumps(evt, sort_keys=True))


def _compile_counters():
    from .. import engine

    return (engine.bulk_compile_counter, engine.tape_compile_counter,
            engine.symbol_compile_counter, engine.serve_compile_counter,
            engine.decode_compile_counter, engine.dist_compile_counter)


def arm():
    """Start watching: from now until :func:`disarm`, every compile-counter
    bump is an anomaly event. Idempotent."""
    global _armed
    for c in _compile_counters():
        c._watch = _on_compile
    _armed = True


def disarm():
    global _armed
    for c in _compile_counters():
        c._watch = None
    _armed = False


def armed():
    return _armed


def reset_events():
    with _lock:
        del events[:]


def snapshot():
    with _lock:
        last = events[-1] if events else None
    return {"armed": _armed, "events": len(events), "last_event": last}


if os.environ.get("MXNET_RETRACE_WATCHDOG", "0").lower() in (
        "1", "true", "yes", "on"):
    arm()
