"""Opt-in ``/metrics`` HTTP endpoint — stdlib only, no server framework.

The scrape surface mxnet-model-server exposed on its management port,
rebuilt on ``http.server``: GET ``/metrics`` returns the Prometheus text
exposition of ``observability.snapshot()``, GET ``/snapshot`` (or
``/stats``) the stable JSON form, and GET ``/health`` a CHEAP liveness
probe — a tiny JSON payload (``ok`` + whatever the owning server's
``health_fn`` reports: warmup-complete flag, queue-depth and
tokens-in-flight gauges) that reads two counters, never sorts a latency
ring and never touches device state, so a fleet router can scrape it per
routing pick. Bound to loopback by default; a serving replica opts in
with ``ModelServer(..., metrics_port=9090)`` /
``GenerativeServer(..., metrics_port=9090)`` (0 = ephemeral port, read
back from ``.port`` — how tests avoid collisions).

``serve.worker`` extends this server into the fleet data plane: extra
GET/POST routes registered on ``get_routes``/``post_routes`` (predict/
generate/swap/drain/prefix-migration) ride the same listener, so a
worker process has ONE port for traffic, control and observability.
"""
from __future__ import annotations

import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer


class MetricsHTTPServer:
    """Background thread serving the observability snapshot. ``close()``
    (or the owning server's ``stop()``) shuts it down; scrapes never touch
    the dispatch path — they read counters and bounded rings.

    ``health_fn``: zero-arg callable returning a dict merged into the
    ``/health`` payload (e.g. a server's warm flag + load gauges). Must be
    cheap — the router calls it on the routing path. An exception inside
    it flips ``ok`` to False rather than 500ing the probe.

    ``get_routes`` / ``post_routes``: path -> handler extension points.
    GET handlers take the query string; POST handlers take (body bytes,
    query string). Both return ``(status, content_type, body_bytes)``;
    an exception becomes a 500 with a JSON error envelope.
    """

    def __init__(self, port=0, host="127.0.0.1", health_fn=None):
        from . import prometheus, snapshot

        self.health_fn = health_fn
        self.get_routes = {}
        self.post_routes = {}
        outer = self

        class _Handler(BaseHTTPRequestHandler):
            # the worker data plane rides this listener: keep-alive saves a
            # TCP handshake per routed request
            protocol_version = "HTTP/1.1"

            def _reply(self, status, ctype, body):
                self.send_response(status)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def _run_route(self, fn, *args):
                try:
                    status, ctype, body = fn(*args)
                except Exception as e:
                    body = json.dumps({"error": type(e).__name__,
                                       "message": str(e)}).encode("utf-8")
                    status, ctype = 500, "application/json"
                self._reply(status, ctype, body)

            def do_GET(self):  # noqa: N802 (stdlib API name)
                # device=True: a live server's backend is already
                # initialized, so the HBM gauges are a cached read — the
                # downed-relay hang risk diagnose --no-device guards
                # against doesn't apply here
                path, _, query = self.path.partition("?")
                if path == "/metrics":
                    body = prometheus(device=True).encode("utf-8")
                    ctype = "text/plain; version=0.0.4; charset=utf-8"
                elif path in ("/snapshot", "/stats"):
                    body = json.dumps(snapshot(device=True), indent=1,
                                      sort_keys=True,
                                      default=str).encode("utf-8")
                    ctype = "application/json"
                elif path == "/health":
                    # cheap by contract: counters and flags only, so a
                    # router can afford one scrape per routing window
                    payload = {"ok": True}
                    if outer.health_fn is not None:
                        try:
                            payload.update(outer.health_fn() or {})
                        except Exception as e:
                            payload = {"ok": False, "error": repr(e)}
                    body = json.dumps(payload, sort_keys=True,
                                      default=str).encode("utf-8")
                    ctype = "application/json"
                elif path in outer.get_routes:
                    self._run_route(outer.get_routes[path], query)
                    return
                else:
                    self.send_response(404)
                    self.send_header("Content-Length", "0")
                    self.end_headers()
                    return
                self._reply(200, ctype, body)

            def do_POST(self):  # noqa: N802 (stdlib API name)
                path, _, query = self.path.partition("?")
                fn = outer.post_routes.get(path)
                if fn is None:
                    self.send_response(404)
                    self.send_header("Content-Length", "0")
                    self.end_headers()
                    return
                n = int(self.headers.get("Content-Length") or 0)
                body = self.rfile.read(n) if n else b""
                self._run_route(fn, body, query)

            def log_message(self, *a):  # scrapes are not stdout events
                pass

        self._httpd = ThreadingHTTPServer((host, int(port)), _Handler)
        self.host = host
        self.port = self._httpd.server_address[1]
        self._thread = threading.Thread(target=self._httpd.serve_forever,
                                        daemon=True, name="mxtpu-metrics")
        self._thread.start()

    def url(self, path="/metrics"):
        return "http://%s:%d%s" % (self.host, self.port, path)

    def close(self):
        try:
            self._httpd.shutdown()
            self._httpd.server_close()
        except Exception:
            pass
