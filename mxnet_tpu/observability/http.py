"""Opt-in ``/metrics`` HTTP endpoint — stdlib only, no server framework.

The scrape surface mxnet-model-server exposed on its management port,
rebuilt on ``http.server``: GET ``/metrics`` returns the Prometheus text
exposition of ``observability.snapshot()``, GET ``/snapshot`` (or
``/stats``) the stable JSON form. Bound to loopback by default; a serving
replica opts in with ``ModelServer(..., metrics_port=9090)`` /
``GenerativeServer(..., metrics_port=9090)`` (0 = ephemeral port, read
back from ``.port`` — how tests avoid collisions).
"""
from __future__ import annotations

import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer


class MetricsHTTPServer:
    """Background thread serving the observability snapshot. ``close()``
    (or the owning server's ``stop()``) shuts it down; scrapes never touch
    the dispatch path — they read counters and bounded rings."""

    def __init__(self, port=0, host="127.0.0.1"):
        from . import prometheus, snapshot

        class _Handler(BaseHTTPRequestHandler):
            def do_GET(self):  # noqa: N802 (stdlib API name)
                # device=True: a live server's backend is already
                # initialized, so the HBM gauges are a cached read — the
                # downed-relay hang risk diagnose --no-device guards
                # against doesn't apply here
                path = self.path.split("?", 1)[0]
                if path == "/metrics":
                    body = prometheus(device=True).encode("utf-8")
                    ctype = "text/plain; version=0.0.4; charset=utf-8"
                elif path in ("/snapshot", "/stats"):
                    body = json.dumps(snapshot(device=True), indent=1,
                                      sort_keys=True,
                                      default=str).encode("utf-8")
                    ctype = "application/json"
                else:
                    self.send_response(404)
                    self.end_headers()
                    return
                self.send_response(200)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def log_message(self, *a):  # scrapes are not stdout events
                pass

        self._httpd = ThreadingHTTPServer((host, int(port)), _Handler)
        self.host = host
        self.port = self._httpd.server_address[1]
        self._thread = threading.Thread(target=self._httpd.serve_forever,
                                        daemon=True, name="mxtpu-metrics")
        self._thread.start()

    def url(self, path="/metrics"):
        return "http://%s:%d%s" % (self.host, self.port, path)

    def close(self):
        try:
            self._httpd.shutdown()
            self._httpd.server_close()
        except Exception:
            pass
