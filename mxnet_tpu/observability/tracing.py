"""Per-request tracing — a trace-id/span-id context threaded through the
serving stack.

A ``RequestTrace`` is created at ``submit()`` (ModelServer and
GenerativeServer) and rides on the request/stream handle through admission
queue → batcher coalesce → bucket pad → executor dispatch → (decode)
per-token steps. Each phase closes a named span; the response handle's
``.trace.timing()`` returns the per-request breakdown
(``queue_ms/pad_ms/dispatch_ms/tokens``), and when the profiler is
running every span is also emitted into the Chrome-trace record stream
(category ``request``, name ``req[<id8>] <span>``, ``args.trace_id``
carrying the full id) — so one Perfetto timeline shows request lifecycle,
host scopes (``bulk[...]``/``serve[...]``/``decode[...]``), and XLA
kernels together.

Cost discipline: a trace is a uuid + a handful of (name, t0, t1) tuples
per REQUEST (never per token — decode steps accumulate into one float).
``set_tracing(False)`` (or ``MXNET_REQUEST_TRACING=0``) makes
``new_trace`` return None and every call site is ``if trace is not
None``-guarded, so the off-state costs one attribute test.
"""
from __future__ import annotations

import os
import time
import uuid

_enabled = os.environ.get("MXNET_REQUEST_TRACING", "1").lower() \
    not in ("0", "false", "off", "no")


def set_tracing(on):
    """Toggle request-trace creation; returns the previous state. Always-on
    by default — the overhead artifact (tools/observability_overhead_quick
    .json) prices it at well under the 3% budget."""
    global _enabled
    prev, _enabled = _enabled, bool(on)
    return prev


def tracing_enabled():
    return _enabled


def new_trace(name="request"):
    """A fresh RequestTrace with a process-unique trace id, or None when
    tracing is disabled (call sites guard on None)."""
    if not _enabled:
        return None
    return RequestTrace(name)


class RequestTrace:
    __slots__ = ("trace_id", "name", "t_start", "spans", "tokens",
                 "_acc_dispatch_ms", "_decode_t0")

    def __init__(self, name="request"):
        self.trace_id = uuid.uuid4().hex[:16]
        self.name = name
        self.t_start = time.perf_counter()
        self.spans = []            # (name, t0, t1, args) perf_counter secs
        self.tokens = 0            # generated tokens (decode requests)
        self._acc_dispatch_ms = 0.0  # per-token step time, accumulated
        self._decode_t0 = None

    # ------------------------------------------------------------ recording
    def add_span(self, span, t0, t1, **args):
        """Close one named child span [t0, t1] (perf_counter seconds) and
        mirror it into the profiler's Chrome-trace records when running."""
        self.spans.append((span, t0, t1, args or None))
        from .. import profiler

        if profiler.is_running():
            a = {"trace_id": self.trace_id}
            if args:
                a.update(args)
            profiler._record("req[%s] %s" % (self.trace_id[:8], span),
                             (t0 - profiler._epoch) * 1e6,
                             (t1 - t0) * 1e3, cat="request", args=a)

    def note_decode_step(self, step_s, t_now=None):
        """Attribute one shared decode-step dispatch to this request:
        O(1) per token — a float add and a token count, never a span."""
        if self._decode_t0 is None:
            self._decode_t0 = (t_now or time.perf_counter()) - step_s
        self.tokens += 1
        self._acc_dispatch_ms += step_s * 1e3

    def close_decode(self, t_now=None):
        """Emit the aggregate ``decode`` span (first step → now) once, at
        request retire — per-token spans would grow with the stream."""
        if self._decode_t0 is not None:
            self.add_span("decode", self._decode_t0,
                          t_now or time.perf_counter(), tokens=self.tokens)
            self._decode_t0 = None

    # ------------------------------------------------------------- reading
    def span_ms(self, span):
        return sum((t1 - t0) for n, t0, t1, _ in self.spans if n == span) \
            * 1e3

    def timing(self):
        """The per-request breakdown the response object carries:
        queue/pad/dispatch wall-clock (ms) + generated token count (0 for
        non-generative requests). ``dispatch_ms`` includes decode-step
        time attributed via :meth:`note_decode_step`."""
        return {
            "trace_id": self.trace_id,
            "queue_ms": round(self.span_ms("queue"), 3),
            "pad_ms": round(self.span_ms("pad"), 3),
            "dispatch_ms": round(self.span_ms("dispatch")
                                 + self._acc_dispatch_ms, 3),
            "tokens": self.tokens,
            "total_ms": round((time.perf_counter() - self.t_start) * 1e3, 3),
        }
