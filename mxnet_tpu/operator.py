"""User-defined operators (CustomOp) — MXNet parity + TPU-native paths.

TPU-native equivalent of MXNet's custom operator machinery (ref:
python/mxnet/operator.py CustomOp/CustomOpProp/register,
src/operator/custom/custom.cc). Three tiers, fastest first:

1. ``register_jax_op(name, fn, vjp=...)`` — the native path: a pure
   jax function (optionally with an analytic ``jax.custom_vjp``) registered
   into the shared op registry, so it appears as ``nd.<name>`` AND fuses into
   hybridized/jitted programs like any built-in op. This is what MXNet users
   porting a CUDA custom op should use.
2. ``CustomOp``/``CustomOpProp``/``register`` + ``nd.Custom`` — API-parity
   tier: host Python forward/backward over NDArrays, dispatched eagerly and
   recorded on the autograd tape. Matches MXNet semantics (req write/add,
   infer_shape/infer_type, need_top_grad).
3. ``as_jax_fn(op_type)`` — escape hatch embedding tier-2 ops inside traced
   code via ``jax.pure_callback`` (host roundtrip each call; correctness tool,
   not a perf path — same caveat as MXNet's warning that Custom breaks fusion).
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

from .base import register_op

__all__ = ["CustomOp", "CustomOpProp", "register", "get", "Custom",
           "register_jax_op", "as_jax_fn"]


class CustomOp:
    """Base class for user ops (ref: python/mxnet/operator.py:CustomOp)."""

    def forward(self, is_train, req, in_data, out_data, aux):
        raise NotImplementedError

    def backward(self, req, out_grad, in_data, out_data, in_grad, aux):
        raise NotImplementedError

    def assign(self, dst, req, src):
        """Write ``src`` into ``dst`` honoring the write/add/null request."""
        if req == "null":
            return
        from .ndarray import NDArray

        s = src._data if isinstance(src, NDArray) else jnp.asarray(src)
        if req == "add":
            dst._data = dst._data + s.astype(dst.dtype)
        else:  # 'write' / 'inplace'
            dst._data = s.astype(dst.dtype).reshape(dst.shape)


class CustomOpProp:
    """Op metadata: arity, shapes, types (ref: CustomOpProp upstream)."""

    def __init__(self, need_top_grad=True):
        self.need_top_grad_ = need_top_grad

    def list_arguments(self):
        return ["data"]

    def list_outputs(self):
        return ["output"]

    def list_auxiliary_states(self):
        return []

    def infer_shape(self, in_shape):
        return in_shape, [in_shape[0]] * len(self.list_outputs()), []

    def infer_type(self, in_type):
        t = in_type[0] if in_type else np.float32
        return in_type, [t] * len(self.list_outputs()), \
            [t] * len(self.list_auxiliary_states())

    def declare_backward_dependency(self, out_grad, in_data, out_data):
        deps = []
        if self.need_top_grad_:
            deps.extend(out_grad)
        deps.extend(in_data)
        deps.extend(out_data)
        return deps

    def create_operator(self, ctx, in_shapes, in_dtypes):
        return CustomOp()


_CUSTOM_REGISTRY = {}


def register(op_type):
    """Decorator registering a CustomOpProp subclass under ``op_type``
    (ref: python/mxnet/operator.py:register)."""

    def deco(prop_cls):
        if not issubclass(prop_cls, CustomOpProp):
            raise TypeError("register() expects a CustomOpProp subclass")
        _CUSTOM_REGISTRY[op_type] = prop_cls
        return prop_cls

    return deco


def get(op_type):
    try:
        return _CUSTOM_REGISTRY[op_type]
    except KeyError:
        raise ValueError("custom op %r is not registered" % (op_type,))


def _build(op_type, in_shapes, in_dtypes, kwargs):
    prop = get(op_type)(**kwargs)
    n_out = len(prop.list_outputs())
    n_aux = len(prop.list_auxiliary_states())
    _, out_shapes, aux_shapes = prop.infer_shape([list(s) for s in in_shapes])
    _, out_dtypes, aux_dtypes = prop.infer_type(list(in_dtypes))
    op = prop.create_operator(None, in_shapes, in_dtypes)
    return (prop, op, [tuple(s) for s in out_shapes[:n_out]], out_dtypes[:n_out],
            [tuple(s) for s in aux_shapes[:n_aux]], aux_dtypes[:n_aux])


def _alloc(shapes, dtypes):
    from .ndarray import NDArray

    return [NDArray(jnp.zeros(s, d)) for s, d in zip(shapes, dtypes)]


def Custom(*data, op_type=None, **kwargs):
    """Imperative entry point, exposed as ``nd.Custom`` (ref:
    src/operator/custom/custom.cc registration of op "Custom")."""
    if op_type is None:
        raise ValueError("Custom(...) requires op_type=")
    from . import autograd
    from .ndarray import NDArray

    in_data = [x if isinstance(x, NDArray) else NDArray(jnp.asarray(x)) for x in data]
    prop, op, out_shapes, out_dtypes, aux_shapes, aux_dtypes = _build(
        op_type, [x.shape for x in in_data], [x.dtype for x in in_data], kwargs)

    out_data = _alloc(out_shapes, out_dtypes)
    aux = _alloc(aux_shapes, aux_dtypes)
    op.forward(autograd.is_training(), ["write"] * len(out_data), in_data, out_data, aux)

    if autograd.is_recording():
        n_in = len(in_data)

        def vjp_fn(cots):
            if not isinstance(cots, tuple):
                cots = (cots,)
            # need_top_grad=False ops (loss-style) compute grads without the
            # head cotangent, as in the reference's CustomOpProp contract
            out_grad = [NDArray(c) for c in cots] if prop.need_top_grad_ else []
            in_grad = [NDArray(jnp.zeros(x.shape, x.dtype)) for x in in_data]
            op.backward(["write"] * n_in, out_grad, in_data, out_data, in_grad, aux)
            return tuple(g._data for g in in_grad)

        autograd.append_node(autograd.TapeNode(list(in_data), list(out_data), vjp_fn))

    return out_data[0] if len(out_data) == 1 else out_data


# ------------------------------------------------------------------ tier 1


def register_jax_op(name, fn, vjp=None, fwd=None, **reg_kwargs):
    """Register a pure-jax function as a first-class op in both front-ends.

    ``fn(*arrays, **static)`` must be pure/jit-able. If ``vjp`` is given it is
    ``vjp(residuals, cotangent) -> tuple(input_cots)``; ``fwd`` (default: run
    ``fn`` and keep the primal inputs as residuals) is
    ``fwd(*arrays) -> (out, residuals)``. The op lands in OP_REGISTRY so it is
    available as ``nd.<name>``, records on the tape, and inlines into
    hybridized XLA programs — the TPU-native replacement for writing a CUDA
    kernel + CustomOpProp pair in the reference.
    """
    if vjp is not None:
        f = jax.custom_vjp(fn)
        f_fwd = fwd if fwd is not None else (lambda *xs: (fn(*xs), xs))
        f.defvjp(f_fwd, vjp)
        functools.update_wrapper(f, fn)
        target = f
    else:
        target = fn
    register_op(name, **reg_kwargs)(target)
    return target


# ------------------------------------------------------------------ tier 3


def as_jax_fn(op_type, **kwargs):
    """Wrap a registered tier-2 CustomOp as a traceable jax function via
    ``jax.pure_callback`` (forward AND backward host roundtrips). Use only
    when the op genuinely needs host Python (I/O, external libs)."""
    from .ndarray import NDArray

    def run_forward(np_inputs):
        in_data = [NDArray(jnp.asarray(a)) for a in np_inputs]
        prop, op, out_shapes, out_dtypes, aux_shapes, aux_dtypes = _build(
            op_type, [a.shape for a in np_inputs], [a.dtype for a in np_inputs], kwargs)
        out_data = _alloc(out_shapes, out_dtypes)
        aux = _alloc(aux_shapes, aux_dtypes)
        op.forward(False, ["write"] * len(out_data), in_data, out_data, aux)
        return tuple(np.asarray(o._data) for o in out_data + aux)

    def run_backward(np_cots, np_inputs, np_outputs, np_aux):
        # the primal pass's outputs AND aux states ride along as residuals —
        # backward never re-runs forward, so stateful/nondeterministic ops
        # (dropout-style) see exactly what forward produced
        in_data = [NDArray(jnp.asarray(a)) for a in np_inputs]
        prop, op, _, _, _, _ = _build(
            op_type, [a.shape for a in np_inputs], [a.dtype for a in np_inputs], kwargs)
        out_data = [NDArray(jnp.asarray(o)) for o in np_outputs]
        aux = [NDArray(jnp.asarray(a)) for a in np_aux]
        out_grad = ([NDArray(jnp.asarray(c)) for c in np_cots]
                    if prop.need_top_grad_ else [])
        in_grad = [NDArray(jnp.zeros(a.shape, a.dtype)) for a in np_inputs]
        op.backward(["write"] * len(in_data), out_grad, in_data, out_data, in_grad, aux)
        return tuple(np.asarray(g._data) for g in in_grad)

    def _result_shapes(xs):
        _, _, out_shapes, out_dtypes, aux_shapes, aux_dtypes = _build(
            op_type, [x.shape for x in xs], [x.dtype for x in xs], kwargs)
        n_out = len(out_shapes)
        shapes = tuple(jax.ShapeDtypeStruct(s, d) for s, d in
                       zip(out_shapes + aux_shapes, out_dtypes + aux_dtypes))
        return shapes, n_out

    @jax.custom_vjp
    def f(*xs):
        shapes, n_out = _result_shapes(xs)
        res = jax.pure_callback(lambda *a: run_forward(a), shapes, *xs,
                                vmap_method="sequential")
        outs = res[:n_out]
        return outs[0] if len(outs) == 1 else outs

    def f_fwd(*xs):
        shapes, n_out = _result_shapes(xs)
        res = jax.pure_callback(lambda *a: run_forward(a), shapes, *xs,
                                vmap_method="sequential")
        outs, auxs = res[:n_out], res[n_out:]
        return (outs[0] if len(outs) == 1 else outs), (xs, outs, auxs)

    def f_bwd(res, cots):
        xs, outs, auxs = res
        if not isinstance(cots, tuple):
            cots = (cots,)
        shapes = tuple(jax.ShapeDtypeStruct(x.shape, x.dtype) for x in xs)
        n_c, n_x, n_o = len(cots), len(xs), len(outs)
        grads = jax.pure_callback(
            lambda *a: run_backward(a[:n_c], a[n_c:n_c + n_x],
                                    a[n_c + n_x:n_c + n_x + n_o], a[n_c + n_x + n_o:]),
            shapes, *cots, *xs, *outs, *auxs, vmap_method="sequential")
        return tuple(grads)

    f.defvjp(f_fwd, f_bwd)
    return f
