"""``mx.kvstore_server`` (ref: python/mxnet/kvstore_server.py).

Justified N/A, like ``dist_async`` (kvstore.py): upstream's dist training
runs dedicated parameter-server processes (ps-lite roles scheduler/server/
worker); the TPU-native distributed backend has NO server role — gradients
reduce via XLA collectives over ICI/DCN inside the compiled step
(parallel/*, DistKVStore), so every process is a worker and the "server"
is the interconnect. This module exists so role-launching scripts fail
loudly with that explanation instead of an ImportError."""
from __future__ import annotations

__all__ = ["KVStoreServer"]

_RATIONALE = (
    "TPU-native distributed training has no parameter-server role: "
    "reduction happens via XLA collectives (psum/reduce_scatter) inside "
    "the compiled train step across all workers (see mxnet_tpu/parallel "
    "and kvstore.DistKVStore). Launch every process as a worker with "
    "jax.distributed.initialize (tools/launch.py)."
)


class KVStoreServer:
    """(ref: kvstore_server.py:KVStoreServer) — N/A on this backend."""

    def __init__(self, kvstore=None):
        raise RuntimeError(_RATIONALE)


def _init_kvstore_server_module():
    """Upstream calls this when DMLC_ROLE=server; here it explains why
    there is no such role."""
    raise RuntimeError(_RATIONALE)
