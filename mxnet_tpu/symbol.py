"""Symbolic graph API (ref: python/mxnet/symbol/symbol.py, nnvm graph).

MXNet builds an nnvm DAG, plans memory, and executes via GraphExecutor
(ref: src/executor/graph_executor.cc). TPU-natively the DAG is *lowered to one
XLA computation*: binding a Symbol jits a pure function of its arguments —
XLA then does scheduling/fusion/memory-planning (the jobs of nnvm's passes).
Shape/type inference is ``jax.eval_shape`` over the same function.
"""
from __future__ import annotations

from collections import OrderedDict

import jax
import jax.numpy as jnp
from jax import lax
import numpy as np

from .base import OP_REGISTRY, _jit_backed, resolve_dtype
from .context import current_context
from .ndarray import NDArray

__all__ = ["Symbol", "var", "Variable", "Group", "load", "Executor", "cond",
           "foreach", "while_loop"]


class Symbol:
    def __init__(self, op=None, inputs=(), attrs=None, name=None, shape=None,
                 dtype=None, out_index=None, n_outputs=1):
        self._op = op  # registry op name, None for variables, "_group" for groups
        self._inputs = list(inputs)
        self._attrs = dict(attrs or {})   # op kwargs — splatted into the op fn
        self._annotations = {}            # AttrScope metadata — never executed
        if name is None:
            # auto names flow through the ambient NameManager/Prefix scope
            # (ref: python/mxnet/name.py; symbol.py passes name=None to it)
            from . import name as _name_mod

            name = _name_mod.current().get(None, op if op else "var")
        self.name = name
        self._shape = tuple(shape) if shape is not None else None
        self._dtype = resolve_dtype(dtype)
        self._out_index = out_index
        self._n_outputs = n_outputs

    # ------------------------------------------------------------- structure
    def is_var(self):
        return self._op is None

    def list_arguments(self):
        """Free variables, depth-first order (ref: symbol.py:list_arguments)."""
        seen = set()
        out = []

        def walk(s):
            if id(s) in seen:
                return
            seen.add(id(s))
            if s.is_var():
                if s.name not in [o.name for o in out]:
                    out.append(s)
                return
            for i in s._inputs:
                walk(i)

        walk(self)
        return [s.name for s in out]

    def _arg_symbols(self):
        seen = set()
        out = OrderedDict()

        def walk(s):
            if id(s) in seen:
                return
            seen.add(id(s))
            if s.is_var():
                out.setdefault(s.name, s)
                return
            for i in s._inputs:
                walk(i)

        walk(self)
        return list(out.values())

    def list_outputs(self):
        if self._op == "_group":
            return [i.name + "_output" for i in self._inputs]
        return [self.name + "_output"]

    def get_internals(self):
        return self

    def __getitem__(self, index):
        if self._op == "_group":
            return self._inputs[index]
        return Symbol("_item", [self], {"index": index}, name="%s%d" % (self.name, index))

    def attr(self, key):
        # op kwargs are what actually executes — they win over scope
        # annotations on a key collision (AttrScope.get's "node wins" rule)
        if key in self._attrs:
            return self._attrs[key]
        return self._annotations.get(key)

    # ------------------------------------------------------------- build ops
    def __add__(self, o):
        return _make("add", self, o)

    __radd__ = __add__

    def __sub__(self, o):
        return _make("subtract", self, o)

    def __rsub__(self, o):
        return _make("subtract", o, self)

    def __mul__(self, o):
        return _make("multiply", self, o)

    __rmul__ = __mul__

    def __truediv__(self, o):
        return _make("divide", self, o)

    def __rtruediv__(self, o):
        return _make("divide", o, self)

    def __pow__(self, o):
        return _make("power", self, o)

    def __neg__(self):
        return _make("negative", self)

    def __lt__(self, o):
        return _make("lesser", self, o)

    def __le__(self, o):
        return _make("lesser_equal", self, o)

    def __gt__(self, o):
        return _make("greater", self, o)

    def __ge__(self, o):
        return _make("greater_equal", self, o)

    # ------------------------------------------------------------- evaluate
    @property
    def shape(self):
        """Static shape of this symbol's output, inferred through the graph
        when every argument var declares a shape (jax.eval_shape — the
        nnvm-infer-shape equivalent). Lets shape-dependent hybrid_forward
        logic (e.g. rnn_layer's initial-state sizing) trace symbolically
        when the user supplies sym.var(name, shape=...)."""
        if self._shape is not None:
            return self._shape
        if self.is_var():
            raise ValueError(
                "shape of variable %r unknown — declare it: var(%r, shape=...)"
                % (self.name, self.name))
        fn, names = self._build_fn()
        specs = []
        for a in self._arg_symbols():
            if a._shape is None:
                raise ValueError(
                    "cannot infer shape through %r: variable %r has no "
                    "declared shape (use var(name, shape=...))"
                    % (self.name, a.name))
            specs.append(jax.ShapeDtypeStruct(a._shape, a._dtype or jnp.float32))
        out = jax.eval_shape(fn, *specs)
        if isinstance(out, (list, tuple)):
            out = out[self._out_index or 0]
        elif self._out_index:
            raise ValueError(
                "symbol output %d requested but %r produced a single output "
                "with these attributes" % (self._out_index, self._op))
        self._shape = tuple(out.shape)
        return self._shape

    def _build_fn(self, thread_key=False):
        """Return (fn, arg names). With ``thread_key``, fn takes a leading
        PRNG key argument from which every stochastic node derives its
        subkey — the caller jits ONCE and passes a fresh key per call."""
        args = self._arg_symbols()
        names = [a.name for a in args]
        # nodes reachable from multiple regions (main graph / cond branches):
        # only these hoist out of lax.cond for order-independent single draws
        shared = _shared_stochastic_ids(self)

        if thread_key:
            def fn(key, *values):
                env = dict(zip(names, values))
                return _eval(self, env, {}, _KeyCtx(key), shared)
        else:
            def fn(*values):
                env = dict(zip(names, values))
                return _eval(self, env, {}, None, shared)

        return fn, names

    def eval(self, ctx=None, **kwargs):
        # deterministic registry-op graphs lower through the unified
        # typed IR (mxnet_tpu.ir): canonical content-addressed key, so
        # two Symbols with identical math — or the same math captured by
        # the bulk window or the autograd tape — share ONE compiled
        # program; the rewrite-pass pipeline (CSE/fold/cast-sink/DCE)
        # runs once per canonical graph before jit
        out = _ir_symbol_eval(self, kwargs)
        if out is not None:
            return out
        # fallback (stochastic / control-flow / multi-output graphs):
        # per-symbol jit cache (graphlint GL002): _build_fn returns a FRESH
        # closure, so jitting it per call would retrace + recompile every
        # eval; the graph is fixed at construction, so one jitted callable
        # serves the symbol's lifetime (jax keys further by input signature)
        cached = getattr(self, "_eval_exec", None)
        if cached is None:
            fn, names = self._build_fn()
            cached = self._eval_exec = (_jit_backed(fn, tier="jit",
                                                    hint="symbol.eval"),
                                        names)
        jfn, names = cached
        vals = [kwargs[n]._data if isinstance(kwargs[n], NDArray) else jnp.asarray(kwargs[n])
                for n in names]
        out = jfn(*vals)
        out = out if isinstance(out, (list, tuple)) else [out]
        return [NDArray(o) for o in out]

    def infer_shape(self, **kwargs):
        """Infer all argument + output shapes from the given input shapes.
        Parameter variables need no declared shape — per-op rules deduce them
        (ref: nnvm InferShape pass; see shape_inference.py)."""
        from .shape_inference import format_infer_errors, infer_shapes_partial

        known = {n: tuple(s) for n, s in kwargs.items()}
        var_shapes, out, errors = infer_shapes_partial(self, known)
        names = self.list_arguments()
        missing = [n for n in names if var_shapes.get(n) is None]
        if missing:
            raise ValueError("shape of %s could not be inferred%s"
                             % (missing, format_infer_errors(errors)))
        outs = out if isinstance(out, list) else [out]
        if any(o is None for o in outs):
            raise ValueError("output shape could not be inferred%s"
                             % format_infer_errors(errors))
        return ([var_shapes[n] for n in names], [tuple(o) for o in outs], [])

    def infer_type(self, **kwargs):
        return ([np.float32] * len(self.list_arguments()), [np.float32], [])

    # ------------------------------------------------------------- binding
    def simple_bind(self, ctx=None, grad_req="write", **shapes):
        """Allocate arguments and bind. Shapes not given are inferred from the
        given ones through the graph (ref: symbol.py:simple_bind + the
        executor infer pass; see shape_inference.py)."""
        names = self.list_arguments()
        if any(shapes.get(n) is None for n in names):
            arg_shapes, _, _ = self.infer_shape(
                **{n: s for n, s in shapes.items() if s is not None})
            shapes = dict(zip(names, arg_shapes))
        args = {}
        for name in names:
            args[name] = NDArray(jnp.zeros(shapes[name], jnp.float32))
        grads = {n: NDArray(jnp.zeros_like(a._data)) for n, a in args.items()} \
            if grad_req != "null" else None
        return Executor(self, ctx or current_context(), args, grads, grad_req)

    def bind(self, ctx=None, args=None, args_grad=None, grad_req="write", **kwargs):
        if isinstance(args, (list, tuple)):
            args = dict(zip(self.list_arguments(), args))
        if isinstance(args_grad, (list, tuple)):
            args_grad = dict(zip(self.list_arguments(), args_grad))
        return Executor(self, ctx or current_context(), args, args_grad, grad_req)

    def tojson(self):
        """Graph serialization, same spirit as MXNet's symbol json
        (ref: nnvm/src/core/graph.cc:SaveJSON). Attrs stored as reprs so
        ``load`` round-trips tuples/numbers exactly."""
        import json

        def ser(s, nodes, index):
            if id(s) in index:
                return index[id(s)]
            if s._op == "_callable":
                raise ValueError(
                    "symbol %r wraps a host closure (autograd.get_symbol "
                    "tape capture) and cannot be serialized to json; "
                    "rebuild the graph with symbol ops to save it" % s.name)
            # children first so inputs reference earlier node ids
            child_ids = [ser(i, nodes, index) for i in s._inputs]
            nid_attrs = {}
            for k, v in s._attrs.items():
                if isinstance(v, Symbol):
                    # subgraph attr (cond branches): serialize into the SAME
                    # node table — branch vars are shared with the outer
                    # graph, so the shared index keeps one copy
                    nid_attrs[k] = {"__sym__": ser(v, nodes, index)}
                elif isinstance(v, list) and any(isinstance(e, Symbol)
                                                 for e in v):
                    nid_attrs[k] = {"__symlist__": [ser(e, nodes, index)
                                                    for e in v]}
                else:
                    nid_attrs[k] = repr(v)
            nid = len(nodes)
            index[id(s)] = nid
            node = {"op": s._op or "null", "name": s.name,
                    "attrs": nid_attrs,
                    "shape": list(s._shape) if s._shape else None,
                    "inputs": child_ids}
            if s._annotations:
                # AttrScope annotations persist like upstream node attrs
                node["annotations"] = dict(s._annotations)
            nodes.append(node)
            return nid

        nodes = []
        ser(self, nodes, {})
        return json.dumps({"nodes": nodes, "head": len(nodes) - 1}, indent=2)

    def save(self, fname):
        with open(fname, "w") as f:
            f.write(self.tojson())

    def __repr__(self):
        return "<Symbol %s>" % self.name


def _attr_symbols(attrs):
    """Symbol-valued attr entries, including lists of Symbols (foreach's
    state_syms)."""
    for v in attrs.values():
        if isinstance(v, Symbol):
            yield v
        elif isinstance(v, list):
            for e in v:
                if isinstance(e, Symbol):
                    yield e


def _node_is_stochastic(sym):
    """Will this node actually DRAW at run time? needs_rng without an
    explicit key, and — for training-gated ops like Dropout — only when the
    node's training attr enables it (inference dropout is the identity, so
    marking it stochastic would needlessly forfeit jit)."""
    if sym._op in (None, "_group", "_item", "_const"):
        return False
    opdef = OP_REGISTRY.get(sym._op)
    if opdef is None or not opdef.needs_rng or "key" in sym._attrs:
        return False
    if opdef.needs_training and not sym._attrs.get("training", False):
        return False
    return True


def _graph_has_rng(sym):
    """True when any node — in the main graph or inside a Symbol-valued
    attr (cond branch subgraphs) — will draw randomness at run time."""
    seen = set()
    stack = [sym]
    while stack:
        s = stack.pop()
        if id(s) in seen:
            continue
        seen.add(id(s))
        if _node_is_stochastic(s):
            return True
        stack.extend(s._inputs)
        stack.extend(_attr_symbols(s._attrs))
    return False


def _stochastic_nodes(sym, seen, out):
    """Collect stochastic nodes of a subgraph (attr subgraphs included)."""
    if id(sym) in seen:
        return
    seen.add(id(sym))
    if _node_is_stochastic(sym):
        out.append(sym)
    for i in sym._inputs:
        _stochastic_nodes(i, seen, out)
    for v in _attr_symbols(sym._attrs):
        _stochastic_nodes(v, seen, out)


def _shared_stochastic_ids(roots):
    """Ids of stochastic nodes reachable from MORE THAN ONE region. Regions
    are: the main graph (all roots, one inputs-only walk stopping at cond
    attrs) and each cond BRANCH (also stopping at nested cond attrs, whose
    branches form their own regions; conds deduped by id so a cond used
    twice doesn't double-count its branches). Only shared nodes need
    hoisting out of lax.cond for order-independent single draws —
    branch-PRIVATE draws stay inside the untaken-branch-skipping cond."""
    if isinstance(roots, Symbol):
        roots = [roots]
    subgraph_nodes = []   # (list of region-root Symbols) per cond/foreach
    seen_owners = set()

    def walk(s, acc, seen):
        # region walk: stop at subgraph attrs (they are separate regions)
        if id(s) in seen:
            return
        seen.add(id(s))
        acc.add(id(s))
        if id(s) not in seen_owners:
            if s._op == "_cond":
                seen_owners.add(id(s))
                subgraph_nodes.append([s._attrs["then_sym"]])
                subgraph_nodes.append([s._attrs["else_sym"]])
            elif s._op == "_foreach":
                seen_owners.add(id(s))
                subgraph_nodes.append([s._attrs["out_sym"]]
                                      + list(s._attrs["state_syms"]))
            elif s._op == "_while":
                seen_owners.add(id(s))
                subgraph_nodes.append([s._attrs["pred_sym"],
                                       s._attrs["out_sym"]]
                                      + list(s._attrs["var_syms"]))
        for i in s._inputs:
            walk(i, acc, seen)

    regions = []
    main = set()
    seen_main = set()
    for r in roots:
        walk(r, main, seen_main)
    regions.append(main)
    i = 0
    while i < len(subgraph_nodes):   # walks discover nested subgraphs
        region_roots = subgraph_nodes[i]
        i += 1
        acc = set()
        seen = set()
        for b in region_roots:
            walk(b, acc, seen)
        regions.append(acc)
    counts = {}
    for r in regions:
        for nid in r:
            counts[nid] = counts.get(nid, 0) + 1
    return frozenset(nid for nid, n in counts.items() if n > 1)


class _KeyCtx:
    """Derives one subkey per stochastic node from a traced base key — the
    base key is a jit ARGUMENT, so one cached program yields fresh noise
    every call (the bench.py step(…, key, …) pattern)."""

    def __init__(self, key):
        self._key = key
        self._n = 0

    def next(self):
        self._n += 1
        return jax.random.fold_in(self._key, self._n)


def _eval(sym, env, cache, keyctx=None, shared=frozenset()):
    if id(sym) in cache:
        return cache[id(sym)]
    if sym.is_var():
        if sym.name not in env:
            raise KeyError("unbound variable %s" % sym.name)
        val = env[sym.name]
    elif sym._op == "_group":
        val = [_eval(i, env, cache, keyctx, shared) for i in sym._inputs]
    elif sym._op == "_callable":
        # a host jax-traceable closure wrapped as one graph node — produced
        # by autograd.get_symbol (tape capture); evals/binds/differentiates
        # like any registry op but cannot serialize
        ins = [_eval(i, env, cache, keyctx, shared) for i in sym._inputs]
        val = sym._attrs["fn"](*ins)
    elif sym._op == "_item":
        parent = _eval(sym._inputs[0], env, cache, keyctx, shared)
        idx = sym._attrs["index"]
        if not isinstance(parent, (list, tuple)) and idx != 0:
            # an op whose output arity depends on attrs (e.g. Proposal with
            # output_score=False) returned a single array — indexing past it
            # must fail loudly, not silently alias output 0
            raise ValueError(
                "symbol output %d requested but %r produced a single "
                "output with these attributes" % (idx, sym._inputs[0]._op))
        val = parent[idx] if isinstance(parent, (list, tuple)) else parent
    elif sym._op == "_while":
        n_vars = sym._attrs["n_vars"]
        var_vs = [_eval(i, env, cache, keyctx, shared)
                  for i in sym._inputs[:n_vars]]
        free_vs = [_eval(i, env, cache, keyctx, shared)
                   for i in sym._inputs[n_vars:]]
        free_env = dict(zip(sym._attrs["free_names"], free_vs))
        stochastic = _hoist_shared_draws(
            [sym._attrs["pred_sym"], sym._attrs["out_sym"]]
            + list(sym._attrs["var_syms"]), env, cache, keyctx, shared)
        val = _while_scan(sym._attrs["pred_sym"], sym._attrs["out_sym"],
                          sym._attrs["var_syms"], sym._attrs["var_names"],
                          free_env, var_vs, sym._attrs["max_iterations"],
                          cache, keyctx, shared, stochastic)
    elif sym._op == "_foreach":
        n_states = sym._attrs["n_states"]
        data_v = _eval(sym._inputs[0], env, cache, keyctx, shared)
        state_vs = [_eval(i, env, cache, keyctx, shared)
                    for i in sym._inputs[1:1 + n_states]]
        free_vs = [_eval(i, env, cache, keyctx, shared)
                   for i in sym._inputs[1 + n_states:]]
        free_env = dict(zip(sym._attrs["free_names"], free_vs))
        out_sym = sym._attrs["out_sym"]
        state_syms = sym._attrs["state_syms"]
        slice_name = sym._attrs["slice_name"]
        state_names = sym._attrs["state_names"]

        # nodes shared with the outer graph hoist BEFORE the scan (same
        # single-draw guarantee as cond); the body sees them via the cache
        stochastic = _hoist_shared_draws(
            [out_sym] + list(state_syms), env, cache, keyctx, shared)
        val = _foreach_scan(out_sym, state_syms, slice_name, state_names,
                            free_env, state_vs, data_v, cache, keyctx,
                            shared, stochastic)
    elif sym._op == "_cond":
        # evaluated HERE (not via the registry fn) so branches share the
        # outer cache: a node used both outside and inside a branch
        # evaluates once — one noise draw per node per forward — and
        # branch-internal rng nodes reach the threaded keyctx
        pred = _eval(sym._inputs[0], env, cache, keyctx, shared)
        vals = [_eval(i, env, cache, keyctx, shared) for i in sym._inputs[1:]]
        benv = dict(zip(sym._attrs["arg_names"], vals))
        p = jnp.asarray(pred).reshape(()).astype(bool)
        then_sym, else_sym = sym._attrs["then_sym"], sym._attrs["else_sym"]
        # HOIST stochastic branch nodes into the outer scope first: their
        # draws land in the SHARED cache regardless of whether the rest of
        # the graph evaluates them before or after this cond (a draw inside
        # the branch lambda would live in a throwaway cache copy, so a
        # later outer use would re-draw — order-dependent inconsistency)
        hoist, hseen = [], set()
        _stochastic_nodes(then_sym, hseen, hoist)
        _stochastic_nodes(else_sym, hseen, hoist)
        hoist = [n for n in hoist if id(n) in shared]
        if hoist:
            menv = {**env, **benv}
            for node in hoist:
                _eval(node, menv, cache, keyctx, shared)
        val = lax.cond(
            p,
            lambda e: _eval(then_sym, e, dict(cache), keyctx, shared),
            lambda e: _eval(else_sym, e, dict(cache), keyctx, shared),
            benv)
    else:
        ins = [_eval(i, env, cache, keyctx, shared) for i in sym._inputs]
        opdef = OP_REGISTRY[sym._op]
        attrs = sym._attrs
        if opdef.needs_rng and "key" not in attrs:
            if keyctx is not None:
                # key threaded as a jit argument → cached program, fresh
                # noise per call
                attrs = {**attrs, "key": keyctx.next()}
            else:
                # no threaded key (Symbol.eval retraces per call; shape
                # inference discards values): draw a trace-time constant
                from . import random as _rng

                attrs = {**attrs, "key": _rng.next_key()}
        val = opdef.fn(*ins, **attrs)
    cache[id(sym)] = val
    return val


def _eval_symbols(outputs, feed):
    cache = {}
    outs = []
    # shared-draw classification must cover ALL outputs' graphs at once —
    # the SymbolBlock path needs the same cond-hoist guarantee as Executor.
    # Memoized ON the first output symbol (its lifetime bounds the memo, so
    # id() reuse after GC can never serve a stale set), keyed by the full
    # output id tuple in case the same head appears in different groupings.
    ck = tuple(id(s) for s in outputs)
    memo = outputs[0].__dict__.get("_shared_memo") if outputs else None
    if memo is not None and memo[0] == ck:
        shared = memo[1]
    else:
        shared = _shared_stochastic_ids(outputs)
        if outputs:
            outputs[0]._shared_memo = (ck, shared)
    for s in outputs:
        o = _eval(s, feed, cache, None, shared)
        outs.extend(o if isinstance(o, list) else [o])
    return outs




# ----------------------------------------------------- unified IR lowering
#
# Deterministic Symbol graphs convert into mxnet_tpu.ir's typed canonical
# form and lower through its shared content-addressed cache — the third
# capture collapsing into the one-key scheme (the other two are the bulk
# window and the autograd tape). Graphs the IR cannot represent (rng
# draws, control flow, multi-output ops, host closures) keep their legacy
# evaluation paths; conversion failure is memoized per symbol so the probe
# costs once.


def _ir_skeleton_of(root):
    """Memoized IR skeleton of this symbol's graph (False = the graph is
    not IR-representable)."""
    sk = root.__dict__.get("_ir_skel")
    if sk is None:
        from . import ir as _ir

        roots = root._inputs if root._op == "_group" else [root]
        try:
            sk = _ir.symbol_skeleton(roots)
        except _ir.UnsupportedGraph:
            sk = False
        root._ir_skel = sk
    return sk


def _ir_symbol_eval(sym, kwargs):
    """Symbol.eval through the unified IR, or None to use the legacy
    path. One compiled program per canonical (graph, signatures) —
    shared across symbols and captures; engine.symbol_compile_counter
    bumps only on a real build."""
    sk = _ir_skeleton_of(sym)
    if sk is False:
        return None
    from . import ir as _ir
    from .base import BoundedCache
    from .ir.graph import _sig_id

    _steps, leaf_names, _out_specs = sk
    vals = []
    for n in leaf_names:
        if n not in kwargs:
            raise KeyError("unbound variable %s" % n)
        v = kwargs[n]
        vals.append(v._data if isinstance(v, NDArray) else jnp.asarray(v))
    sigids = []
    for v in vals:
        sid = _sig_id((v.dtype, tuple(v.shape)))
        if sid is None:
            return None  # interner at cap: legacy path still works
        sigids.append(sid)
    memo = sym.__dict__.get("_ir_execs")
    if memo is None:
        memo = sym._ir_execs = BoundedCache(32)
    mk = tuple(sigids)
    ent = memo.get(mk)
    if ent is None:
        try:
            g = _ir.from_symbol(sk, sigids)
        except _ir.UnsupportedGraph:
            memo[mk] = False  # these signatures can't lower; legacy path
            return None
        ent = memo[mk] = _ir.lower_forward(g, "symbol", hint="symbol.eval")
    if ent is False:
        return None
    prog, sel = ent
    out = prog(*[vals[i] for i in sel])
    return [NDArray(o) for o in out]


def _ir_executor_callable(s, names):
    """Per-signature dispatching callable over the IR-lowered graph for
    symbol.Executor, or None when the graph is unsupported. Falls back
    to a directly-jitted ``_build_fn`` INSIDE the callable for
    signatures the IR rejects, so shape errors surface from the same
    place they always did."""
    sk = _ir_skeleton_of(s)
    if sk is False:
        return None
    _steps, leaf_names, out_specs = sk
    name_idx = {n: i for i, n in enumerate(names)}
    pos = []
    for n in leaf_names:
        i = name_idx.get(n)
        if i is None:
            return None
        pos.append(i)
    from . import ir as _ir
    from .base import BoundedCache
    from .ir.graph import _sig_id

    memo = BoundedCache(32)
    is_group = s._op == "_group"
    fallback = []

    def _legacy(*vals):
        if not fallback:
            fn, fnames = s._build_fn()
            fallback.append(_jit_backed(fn, tier="jit", hint="executor"))
        return fallback[0](*vals)

    def call(*vals):
        lv = [vals[i] for i in pos]
        sigids = []
        for v in lv:
            sid = _sig_id((v.dtype, tuple(v.shape)))
            if sid is None:
                return _legacy(*vals)
            sigids.append(sid)
        mk = tuple(sigids)
        ent = memo.get(mk)
        if ent is None:
            try:
                g = _ir.from_symbol(sk, sigids)
            except _ir.UnsupportedGraph:
                memo[mk] = False
                return _legacy(*vals)
            ent = memo[mk] = _ir.lower_forward(g, "symbol",
                                               hint="executor")
        if ent is False:
            return _legacy(*vals)
        prog, sel = ent
        out = prog(*[lv[i] for i in sel])
        return list(out) if is_group else out[0]

    return call


def _ir_infer_runner(root):
    """(runner, leaf names) executing the pass-optimized STRUCTURAL IR
    graph of a deterministic symbol DAG, or None when unsupported —
    serve's ``symbol_infer_fn`` jits the runner through its own AotFn
    path, so symbolic serving graphs get whole-graph CSE/fold/DCE before
    each bucket compiles."""
    sk = _ir_skeleton_of(root)
    if sk is False:
        return None
    from . import ir as _ir

    _steps, leaf_names, out_specs = sk
    g = _ir.from_symbol(sk, None)
    final, leaf_sel, _slot_fwd = _ir.passes.optimize(g)
    run = _ir.build_runner(final)
    is_group = root._op == "_group"

    def inner(*vals):
        out = run([vals[i] for i in leaf_sel])
        return list(out) if is_group else out[0]

    return inner, list(leaf_names)


def _substitute(outputs, mapping):
    """Graph splicing: rebuild ``outputs`` with free variables whose names
    appear in ``mapping`` replaced by the mapped symbols.

    This is how a SymbolBlock composes into an enclosing symbolic trace
    (net(sym.var('data')) on an imported model, e.g. ONNX re-export): the
    stored graph's input vars are spliced out for the caller's symbols while
    parameter vars (absent from the mapping) stay free. Control-flow bodies
    (cond/foreach subgraphs held in attrs) reference outer values by NAME
    through their free_names/arg_names env, so substituting the input spine
    is sufficient — body-internal vars are scoped and never collide with
    data input names."""
    memo = {}

    def sub(s):
        got = memo.get(id(s))
        if got is not None:
            return got
        if s.is_var():
            out = mapping.get(s.name, s)
        else:
            new_ins = [sub(i) for i in s._inputs]
            if all(n is o for n, o in zip(new_ins, s._inputs)):
                out = s  # untouched subtree: reuse (keeps memoized walks)
            else:
                out = Symbol(s._op, new_ins, s._attrs, name=s.name,
                             shape=s._shape, dtype=s._dtype,
                             out_index=s._out_index, n_outputs=s._n_outputs)
                out._annotations = dict(s._annotations)
        memo[id(s)] = out
        return out

    return [sub(s) for s in outputs]


def _make(op, *args, name=None, **attrs):
    inputs = []
    for a in args:
        if a is None:
            continue
        if isinstance(a, Symbol):
            inputs.append(a)
        else:
            inputs.append(Symbol("_const", [], {"value": float(a)}, name="const"))
    if name is None:
        # ambient NameManager/Prefix scope allocates 'op0', 'op1', ... and
        # applies any with-block prefix (ref: python/mxnet/name.py)
        from . import name as _name_mod

        name = _name_mod.current().get(None, op.lower())
    # AttrScope attaches only at operator-creation time — NOT in
    # Symbol.__init__, so deserialization (load) and internal rebuilds never
    # absorb ambient scope attributes. Scope attrs are node ANNOTATIONS
    # (ctx_group etc.), kept apart from op kwargs which _eval splats into the
    # registry fn (ref: python/mxnet/attribute.py)
    from . import attribute as _attr_mod

    s = Symbol(op, inputs, attrs, name=name)
    s._annotations = _attr_mod.current().get(None)
    return s


# const evaluation support
from .base import register_op  # noqa: E402


@register_op("_const")
def _const(*, value):
    return jnp.asarray(value, jnp.float32)


@register_op("_filled")
def _filled(*, shape, value, dtype="float32"):
    return jnp.full(tuple(shape), value, resolve_dtype(dtype))


@register_op("_arange")
def _arange(*, start, stop, step=1.0, repeat=1, dtype="float32"):
    out = jnp.arange(start, stop, step, dtype=resolve_dtype(dtype or "float32"))
    return jnp.repeat(out, repeat) if repeat != 1 else out


@register_op("_item")
def _item(x, *, index):
    return x[index]


def cond(pred, then_sym, else_sym, name=None):
    """Symbolic conditional: lowers to lax.cond — both branch subgraphs are
    traced into ONE compiled program and selected at run time (TPU-native
    replacement for MXNet's contrib cond subgraph op,
    src/operator/control_flow.cc). Branch symbols may reference any graph
    variables; the ONNX exporter maps this to an If node.

    Branches may also be zero-arg callables returning Symbols (upstream
    sym.contrib.cond's then_func/else_func form)."""
    if callable(then_sym) and not isinstance(then_sym, Symbol):
        then_sym = then_sym()
    if callable(else_sym) and not isinstance(else_sym, Symbol):
        else_sym = else_sym()
    for b in (then_sym, else_sym):
        if not isinstance(b, Symbol):
            raise NotImplementedError(
                "cond branches must be (or return) a single Symbol, got %s "
                "— multi-output branches are not supported yet (Group them "
                "or use several conds)" % type(b).__name__)
    seen = {}
    for branch in (then_sym, else_sym):
        for a in branch._arg_symbols():
            seen.setdefault(a.name, a)
    arg_names = list(seen)
    return Symbol("_cond", [pred] + [seen[n] for n in arg_names],
                  {"then_sym": then_sym, "else_sym": else_sym,
                   "arg_names": arg_names}, name=name or "cond")


_foreach_uid = 0


def foreach(body, data, init_states, name=None):
    """Symbolic scan (ref: python/mxnet/symbol/contrib.py:foreach,
    src/operator/control_flow.cc). ``body(slice_sym, states) ->
    (out_sym, new_states)`` is traced ONCE over fresh loop variables; the
    node lowers to lax.scan at evaluation, so the whole loop is one compiled
    XLA while-op. Returns (outputs, states) like upstream."""
    single_state = not isinstance(init_states, (list, tuple))
    states = [init_states] if single_state else list(init_states)
    for s in [data] + states:
        if not isinstance(s, Symbol):
            raise TypeError("foreach data/init_states must be Symbols, got "
                            "%s — nd.contrib.foreach is the eager form"
                            % type(s).__name__)

    # loop vars get reserved '_fe*' names no user var can plausibly carry
    global _foreach_uid
    _foreach_uid += 1
    slice_v = Symbol(None, name="_fe%d_x" % _foreach_uid,
                     shape=(data._shape[1:] if data._shape else None))
    state_vs = [Symbol(None, name="_fe%d_s%d" % (_foreach_uid, j),
                       shape=(s._shape if isinstance(s, Symbol) else None))
                for j, s in enumerate(states)]
    out_sym, new_states = body(slice_v,
                               state_vs[0] if single_state else state_vs)
    if isinstance(out_sym, (list, tuple)):
        raise NotImplementedError(
            "foreach bodies with multiple per-step outputs are not "
            "supported yet — return one Symbol (stack/concat inside the "
            "body, or run several foreach loops)")
    new_states = [new_states] if not isinstance(new_states, (list, tuple)) \
        else list(new_states)
    if len(new_states) != len(states):
        raise ValueError("body returned %d states, expected %d"
                         % (len(new_states), len(states)))

    node = _foreach_node(data, states, out_sym, new_states, slice_v.name,
                         [v.name for v in state_vs], name)
    outputs = node[0]
    out_states = [node[i + 1] for i in range(len(states))]
    return outputs, (out_states[0] if single_state else out_states)


def _foreach_node(data, states, out_sym, state_syms, slice_name, state_names,
                  name=None):
    """Build the _foreach Symbol from already-traced body subgraphs — shared
    by foreach() and the ONNX Scan importer."""
    # free variables of the body = everything its subgraphs reference that
    # is not a loop variable; their values come from the outer graph
    loop_names = {slice_name} | set(state_names)
    free = _free_args([out_sym] + list(state_syms), loop_names)
    return Symbol("_foreach", [data] + list(states) + free,
                  {"out_sym": out_sym, "state_syms": list(state_syms),
                   "slice_name": slice_name,
                   "state_names": list(state_names),
                   "free_names": [a.name for a in free],
                   "n_states": len(states)},
                  name=name)


def while_loop(cond_fn, func, loop_vars, max_iterations, name=None):
    """Symbolic bounded while loop (ref: python/mxnet/symbol/contrib.py:
    while_loop). ``cond_fn(vars) -> pred_sym``; ``func(vars) ->
    (out_sym, new_vars)``. Lowers to a masked lax.scan of length
    ``max_iterations`` (the TPU-static form — XLA needs a bound to stack
    per-step outputs); steps after the predicate turns false leave the vars
    unchanged and emit zero rows. Returns (outputs, final_vars)."""
    if max_iterations is None:
        raise ValueError("symbolic while_loop needs max_iterations (static "
                         "output stacking; the nd.contrib form allows None)")
    single = not isinstance(loop_vars, (list, tuple))
    vars_in = [loop_vars] if single else list(loop_vars)
    for v in vars_in:
        if not isinstance(v, Symbol):
            raise TypeError("while_loop loop_vars must be Symbols, got %s — "
                            "nd.contrib.while_loop is the eager form"
                            % type(v).__name__)

    global _foreach_uid
    _foreach_uid += 1
    var_vs = [Symbol(None, name="_wl%d_v%d" % (_foreach_uid, j),
                     shape=(v._shape if isinstance(v, Symbol) else None))
              for j, v in enumerate(vars_in)]
    packed = var_vs[0] if single else var_vs
    pred_sym = cond_fn(packed)
    out_sym, new_vars = func(packed)
    if isinstance(out_sym, (list, tuple)):
        raise NotImplementedError(
            "while_loop bodies with multiple per-step outputs are not "
            "supported yet — return one Symbol")
    new_vars = [new_vars] if not isinstance(new_vars, (list, tuple)) \
        else list(new_vars)
    if len(new_vars) != len(vars_in):
        raise ValueError("func returned %d loop vars, expected %d"
                         % (len(new_vars), len(vars_in)))

    loop_names = {v.name for v in var_vs}
    free = _free_args([pred_sym, out_sym] + new_vars, loop_names)

    node = Symbol("_while", list(vars_in) + free,
                  {"pred_sym": pred_sym, "out_sym": out_sym,
                   "var_syms": new_vars,
                   "var_names": [v.name for v in var_vs],
                   "free_names": [a.name for a in free],
                   "n_vars": len(vars_in),
                   "max_iterations": int(max_iterations)},
                  name=name)
    outputs = node[0]
    out_vars = [node[i + 1] for i in range(len(vars_in))]
    return outputs, (out_vars[0] if single else out_vars)


def _hoist_shared_draws(roots, env, cache, keyctx, shared):
    """Evaluate subgraph stochastic nodes that are SHARED with the outer
    graph into the outer cache (one draw per forward); returns whether any
    body-PRIVATE stochastic nodes remain (those need per-iteration keys)."""
    body_stoch, seen = [], set()
    for s in roots:
        _stochastic_nodes(s, seen, body_stoch)
    for node in body_stoch:
        if id(node) in shared:
            _eval(node, env, cache, keyctx, shared)
    return any(id(n) not in shared for n in body_stoch)


def _free_args(roots, loop_names):
    """Free variables of the body subgraphs, outer-graph order, deduped —
    everything the body references that is not a loop variable."""
    free, seen = [], set()
    for s in roots:
        if not isinstance(s, Symbol):
            raise TypeError(
                "loop body must return Symbols, got %s — nd.contrib offers "
                "the eager NDArray form" % type(s).__name__)
        for a in s._arg_symbols():
            if a.name not in loop_names and a.name not in seen:
                seen.add(a.name)
                free.append(a)
    return free


def _foreach_scan(out_sym, state_syms, slice_name, state_names, free_env,
                  state_vs, data_v, cache, keyctx, shared, stochastic):
    """The ONE scan-step implementation for foreach (value evaluation and
    shape inference both route here)."""
    from . import random as _rng

    def body(st, x, sctx):
        senv = {slice_name: x, **dict(zip(state_names, st)), **free_env}
        sc = dict(cache)
        o = _eval(out_sym, senv, sc, sctx, shared)
        new = tuple(_eval(s, senv, sc, sctx, shared) for s in state_syms)
        return new, o

    if stochastic:
        # per-iteration noise: thread a key through the scan CARRY and split
        # each step — a trace-constant key would repeat the same draw (e.g.
        # one dropout mask) every timestep
        k0 = keyctx.next() if keyctx is not None else _rng.next_key()

        def step(carry, x):
            key, st = carry
            key, sub = jax.random.split(key)
            new, o = body(st, x, _KeyCtx(sub))
            return (key, new), o

        (_, final), outs = lax.scan(step, (k0, tuple(state_vs)), data_v)
    else:
        def step(st, x):
            new, o = body(st, x, keyctx)
            return new, o

        final, outs = lax.scan(step, tuple(state_vs), data_v)
    return [outs] + list(final)


def _while_scan(pred_sym, out_sym, var_syms, var_names, free_env, var_vs,
                max_iterations, cache, keyctx, shared, stochastic):
    """Masked bounded scan shared by _eval's _while branch and the
    shape-inference registry fn."""
    from . import random as _rng

    def body(st, key):
        senv = {**dict(zip(var_names, st)), **free_env}
        sc = dict(cache)
        sctx = _KeyCtx(key) if key is not None else keyctx
        pred = jnp.asarray(
            _eval(pred_sym, senv, sc, sctx, shared)).reshape(()).astype(bool)
        o = _eval(out_sym, senv, sc, sctx, shared)
        new = tuple(_eval(s, senv, sc, sctx, shared) for s in var_syms)
        return pred, o, new

    if stochastic:
        k0 = keyctx.next() if keyctx is not None else _rng.next_key()

        def step(carry, _):
            key, st = carry
            key, sub = jax.random.split(key)
            pred, o, new = body(st, sub)
            st2 = tuple(jnp.where(pred, n, s) for n, s in zip(new, st))
            o = jnp.where(pred, o, jnp.zeros_like(o))
            return (key, st2), o

        (_, final), outs = lax.scan(step, (k0, tuple(var_vs)), None,
                                    length=max_iterations)
    else:
        def step(st, _):
            pred, o, new = body(st, None)
            st2 = tuple(jnp.where(pred, n, s) for n, s in zip(new, st))
            o = jnp.where(pred, o, jnp.zeros_like(o))
            return st2, o

        final, outs = lax.scan(step, tuple(var_vs), None,
                               length=max_iterations)
    return [outs] + list(final)


@register_op("_while")
def _while_op(*rest, pred_sym, out_sym, var_syms, var_names, free_names,
              n_vars, max_iterations):
    """SHAPE-INFERENCE ONLY — value evaluation goes through _eval's _while
    branch (cache sharing + per-iteration keys)."""
    var_vs = rest[:n_vars]
    free_env = dict(zip(free_names, rest[n_vars:]))
    return _while_scan(pred_sym, out_sym, var_syms, var_names, free_env,
                       var_vs, max_iterations, {}, None, frozenset(), False)


@register_op("_foreach")
def _foreach_op(data, *rest, out_sym, state_syms, slice_name, state_names,
                free_names, n_states):
    """SHAPE-INFERENCE ONLY (shape_inference.py eval_shapes through the
    registry) — like _cond_op below, value evaluation goes through _eval's
    dedicated _foreach branch (cache sharing + per-iteration keys)."""
    free_env = dict(zip(free_names, rest[n_states:]))
    return _foreach_scan(out_sym, state_syms, slice_name, state_names,
                         free_env, rest[:n_states], data, {}, None,
                         frozenset(), False)


@register_op("_cond")
def _cond_op(pred, *vals, then_sym, else_sym, arg_names):
    """SHAPE-INFERENCE ONLY (shape_inference.py eval_shapes through the
    registry). Value evaluation goes through _eval's dedicated _cond branch,
    which shares the outer cache and keyctx — this fallback has neither, so
    its noise semantics are wrong for values. Do not route execution here."""
    env = dict(zip(arg_names, vals))
    p = jnp.asarray(pred).reshape(()).astype(bool)
    return lax.cond(p,
                    lambda e: _eval(then_sym, e, {}),
                    lambda e: _eval(else_sym, e, {}), env)


def var(name, shape=None, dtype=None, **kwargs):
    from . import attribute as _attr_mod

    s = Symbol(None, name=name, shape=shape, dtype=dtype)
    s._annotations = _attr_mod.current().get(None)
    return s


Variable = var


def Group(symbols):
    return Symbol("_group", list(symbols), name="group")


def load(fname):
    with open(fname) as f:
        return loads(f.read())


def loads(json_str):
    """Rebuild a Symbol graph from ``tojson`` output."""
    import ast
    import json

    blob = json.loads(json_str)
    built = []
    for node in blob["nodes"]:
        attrs = {}
        for k, v in node["attrs"].items():
            if isinstance(v, dict) and "__sym__" in v:
                attrs[k] = built[v["__sym__"]]  # subgraph attr (cond branch)
            elif isinstance(v, dict) and "__symlist__" in v:
                attrs[k] = [built[i] for i in v["__symlist__"]]
            else:
                attrs[k] = ast.literal_eval(v)
        if node["op"] == "null":
            s = Symbol(None, name=node["name"], shape=node.get("shape"))
        else:
            inputs = [built[i] for i in node["inputs"]]
            s = Symbol(node["op"], inputs, attrs, name=node["name"])
        s._annotations = dict(node.get("annotations", {}))
        built.append(s)
    return built[blob.get("head", len(built) - 1)]


def _with_training(sym, training):
    """Clone the DAG with ``training=training`` on every training-gated op
    that does not pin the attr explicitly (explicit pins win, like
    upstream's mode='always' dropout). This is how ``forward(is_train=...)``
    actually governs Dropout/BatchNorm behavior — the reference threads
    is_train through its executors at run time (src/executor), while here
    each mode is its own jitted program (XLA needs the flag static)."""
    import copy

    memo = {}

    def clone(s):
        if not isinstance(s, Symbol):
            return s
        if s._op in (None, "_const"):
            return s  # variables/consts: identity matters for arg mapping
        c = memo.get(id(s))
        if c is not None:
            return c
        c = copy.copy(s)
        memo[id(s)] = c
        c._inputs = [clone(i) for i in s._inputs]
        attrs = {}
        for k, v in s._attrs.items():
            if isinstance(v, Symbol):
                attrs[k] = clone(v)
            elif isinstance(v, (list, tuple)) and any(
                    isinstance(x, Symbol) for x in v):
                attrs[k] = type(v)(clone(x) for x in v)
            else:
                attrs[k] = v
        opdef = OP_REGISTRY.get(s._op)
        if (opdef is not None and opdef.needs_training
                and "training" not in s._attrs):
            attrs["training"] = bool(training)
        c._attrs = attrs
        return c

    return clone(sym)


class Executor:
    """(ref: src/executor/graph_executor.cc → one jitted XLA callable +
    its jitted VJP). ``forward(is_train=...)`` selects between two jitted
    programs: the train variant runs Dropout/BatchNorm in training mode
    (fresh PRNG key threaded per call when that makes the graph
    stochastic), the eval variant is the deterministic inference program."""

    def __init__(self, sym, ctx, args, args_grad, grad_req):
        self._sym = sym
        self._ctx = ctx
        self.arg_dict = args
        self.grad_dict = args_grad or {}
        self._grad_req = grad_req
        self._modes = {}  # is_train -> (jitted fn, keyed)
        # arg order is mode-independent (same variables); build it once
        self._names = [a.name for a in sym._arg_symbols()]
        self._vjp = None
        self._vjp_keyed = False
        self.outputs = []
        # eval variant built at bind (upstream binds eagerly); its
        # stochasticity is the bind-time contract tests/users observe
        _, keyed = self._get_fn(False)
        self._stochastic = self._keyed = keyed

    def _get_fn(self, is_train):
        ent = self._modes.get(bool(is_train))
        if ent is None:
            s = _with_training(self._sym, is_train)
            # Sampling nodes must not bake trace-time keys into one cached
            # program (that would replay identical noise every forward):
            # stochastic graphs thread the key as a jit ARGUMENT.
            keyed = _graph_has_rng(s)
            if not keyed:
                # deterministic mode variant: lower through the unified
                # typed IR — canonical key, shared pass-optimized program
                irfn = _ir_executor_callable(s, self._names)
                if irfn is not None:
                    ent = self._modes[bool(is_train)] = (irfn, False)
                    return ent
            fn, names = s._build_fn(thread_key=keyed)
            assert names == self._names
            ent = (_jit_backed(fn, tier="jit", hint="executor"), keyed)
            self._modes[bool(is_train)] = ent
        return ent

    def forward(self, is_train=False, **kwargs):
        for k, v in kwargs.items():
            self.arg_dict[k] = v if isinstance(v, NDArray) else NDArray(jnp.asarray(v))
        fn, keyed = self._get_fn(is_train)
        self._keyed = keyed
        vals = [self.arg_dict[n]._data for n in self._names]
        if keyed:
            from . import random as _rng

            key = _rng.next_key()
            vals = [key] + vals
        if is_train:
            out, self._vjp = jax.vjp(lambda *v: fn(*v), *vals)
            # backward must strip the key cotangent iff THIS vjp's program
            # was keyed — a later eval forward must not flip that decision
            self._vjp_keyed = keyed
        else:
            out = fn(*vals)
        outs = out if isinstance(out, (list, tuple)) else [out]
        self.outputs = [NDArray(o) for o in outs]
        return self.outputs

    def backward(self, out_grads=None):
        assert self._vjp is not None, "call forward(is_train=True) first"
        if out_grads is None:
            cots = [jnp.ones(o.shape, o.dtype) for o in self.outputs]
        else:
            if isinstance(out_grads, NDArray):
                out_grads = [out_grads]
            cots = [g._data for g in out_grads]
        # cotangent must match the primal output structure (list for groups)
        grads = self._vjp(list(cots) if self._sym._op == "_group" else cots[0])
        if self._vjp_keyed:
            grads = grads[1:]   # leading entry is the PRNG key's float0
        for n, g in zip(self._names, grads):
            if n in self.grad_dict and self.grad_dict[n] is not None:
                if self._grad_req == "add":
                    self.grad_dict[n]._data = self.grad_dict[n]._data + g
                else:
                    self.grad_dict[n]._data = g

    @property
    def aux_dict(self):
        """(ref: executor.py:Executor.aux_dict) — auxiliary states. BN
        moving stats etc. live in arg_dict here (XLA treats them as plain
        inputs; Module does the train-mode write-back), so this is empty by
        construction; kept for API parity with code that iterates it."""
        return {}

    def copy_params_from(self, arg_params, aux_params=None,
                         allow_extra_params=False):
        """(ref: executor.py:Executor.copy_params_from)"""
        merged = dict(arg_params or {})
        merged.update(aux_params or {})
        for n, v in merged.items():
            if n in self.arg_dict:
                # fresh wrapper around the (immutable) buffer: the caller
                # rebinding their NDArray's ._data later must not leak into
                # this executor — upstream's copy contract
                self.arg_dict[n] = NDArray(v._data) if isinstance(v, NDArray) \
                    else NDArray(jnp.asarray(v))
            elif not allow_extra_params:
                raise ValueError("Executor has no argument %r" % n)

    def reshape(self, partial_shaping=False, allow_up_sizing=False, **kwargs):
        """(ref: executor.py:Executor.reshape). XLA programs are
        shape-specialized, so a new shape simply means a new compiled
        program on the next forward — which is why partial_shaping /
        allow_up_sizing are accepted but moot here: upstream uses them to
        police reuse of fixed-size CUDA buffers, and there is no buffer
        reuse to police (args are re-materialized at the new shapes)."""
        unknown = [n for n in kwargs if n not in self.arg_dict]
        if unknown:
            raise ValueError("reshape: no such argument(s) %s (have %s)"
                             % (unknown, sorted(self.arg_dict)))
        ex = Executor(self._sym, self._ctx, dict(self.arg_dict),
                      dict(self.grad_dict), self._grad_req)
        for n, shape in kwargs.items():
            if tuple(ex.arg_dict[n].shape) != tuple(shape):
                ex.arg_dict[n] = NDArray(jnp.zeros(shape,
                                                   ex.arg_dict[n].dtype))
        # fresh zero grads at each arg's (possibly new) shape: sharing the
        # parent's grad arrays would corrupt it on 'write' and break
        # broadcasting on 'add'
        ex.grad_dict = {n: NDArray(jnp.zeros(ex.arg_dict[n].shape,
                                             ex.arg_dict[n].dtype))
                        for n, g in self.grad_dict.items() if g is not None}
        return ex
