"""Optimizers, MXNet API surface, jit-fused TPU updates.

(ref: python/mxnet/optimizer/optimizer.py, src/operator/optimizer_op.cc).
MXNet fuses updates in handwritten CUDA kernels (sgd_mom_update, adam_update…);
here each optimizer defines a pure ``_step(w, g, state, lr, wd) -> (w, state)``
that XLA fuses into a single kernel per parameter. ``lr`` and ``wd`` are traced
scalars so LR schedules never retrace. Multi-precision keeps an fp32 master
copy in state when weights are bf16/fp16 (the AMP recipe on TPU).
"""
from __future__ import annotations

import math

import jax
import jax.numpy as jnp
import numpy as np

from .base import _jit_backed, jitted
# dispatch_counter's home is the engine (it observes EVERY jitted dispatch —
# imperative ops, bulk flushes, optimizer updates); these names stay
# importable here for back-compat with pre-promotion callers
from .engine import DispatchCounter, dispatch_counter
from .ndarray import NDArray

__all__ = ["Optimizer", "SGD", "NAG", "Adam", "AdamW", "AdaGrad", "AdaDelta",
           "AdaMax", "FTML", "DCASGD", "LARS",
           "RMSProp", "Ftrl", "LAMB", "Signum", "SGLD", "create", "register",
           "dispatch_counter"]

def register(klass):
    """Backed by the generic mx.registry machinery (ref: registry.py) —
    one registration mechanism across optimizer/initializer/metric."""
    from . import registry as _reg
    return _reg.get_register_func(Optimizer, "optimizer")(klass)


def create(name, **kwargs):
    """Accepts an Optimizer instance, a name, or a JSON config string
    '{"type": "adam", "learning_rate": ...}' (ref: registry.py)."""
    from . import registry as _reg
    return _reg.get_create_func(Optimizer, "optimizer")(name, **kwargs)


class Optimizer:
    def __init__(self, learning_rate=0.01, wd=0.0, rescale_grad=1.0,
                 clip_gradient=None, lr_scheduler=None, param_idx2name=None,
                 begin_num_update=0, multi_precision=False, **kwargs):
        self.lr = learning_rate
        self.wd = wd
        self.rescale_grad = rescale_grad
        self.clip_gradient = clip_gradient
        self.lr_scheduler = lr_scheduler
        self.num_update = begin_num_update
        self.begin_num_update = begin_num_update
        self.multi_precision = multi_precision
        self.idx2name = param_idx2name or {}
        self.lr_mult = {}
        self.wd_mult = {}
        self._index_update_count = {}

    # --------------------------------------------------------- MXNet surface
    def set_learning_rate(self, lr):
        self.lr = lr

    @property
    def learning_rate(self):
        if self.lr_scheduler is not None:
            return self.lr_scheduler(self.num_update)
        return self.lr

    def set_lr_mult(self, args_lr_mult):
        self.lr_mult.update(args_lr_mult)

    def set_wd_mult(self, args_wd_mult):
        self.wd_mult.update(args_wd_mult)

    def _update_count(self, index):
        self._index_update_count.setdefault(index, self.begin_num_update)
        self._index_update_count[index] += 1
        self.num_update = max(self.num_update, self._index_update_count[index])

    def _get_lr(self, index):
        lr = self.learning_rate
        name = self.idx2name.get(index, index)
        return lr * self.lr_mult.get(name, self.lr_mult.get(index, 1.0))

    def _get_wd(self, index):
        name = self.idx2name.get(index, index)
        wd = self.wd * self.wd_mult.get(name, self.wd_mult.get(index, 1.0))
        return wd

    # --------------------------------------------------------- functional core
    def create_state(self, index, weight):
        arr = getattr(weight, "_data", weight)  # NDArray, _Box shim, or raw array
        state = self.init_state(arr)
        if self.multi_precision and weight.dtype in (jnp.bfloat16, jnp.float16):
            return {"master": arr.astype(jnp.float32), "state": state}
        return state

    def init_state(self, w):
        return ()

    def _step(self, w, g, state, lr, wd, t):
        raise NotImplementedError

    def _preprocess_grad(self, g, rescale=None):
        # rescale arrives as a traced scalar from update() so that
        # Trainer.step(batch_size) mutating rescale_grad between steps never
        # hits a stale compiled constant; compiled-train-step paths that bake
        # it at build time (fixed batch) pass None and close over the value.
        g = g * (self.rescale_grad if rescale is None else rescale)
        if self.clip_gradient is not None:
            g = jnp.clip(g, -self.clip_gradient, self.clip_gradient)
        return g

    def _stepper(self):
        def step(w, g, state, lr, wd, t, rescale=None):
            g = self._preprocess_grad(g, rescale)
            if isinstance(state, dict) and "master" in state:
                m = state["master"]
                new_m, new_s = self._step(m, g.astype(jnp.float32), state["state"], lr, wd, t)
                return new_m.astype(w.dtype), {"master": new_m, "state": new_s}
            return self._step(w, g, state, lr, wd, t)

        return step

    def update(self, index, weight, grad, state):
        """In-place MXNet-style update (ref: optimizer.py:Optimizer.update)."""
        from .sparse import RowSparseNDArray
        if isinstance(grad, RowSparseNDArray):
            if getattr(self, "lazy_update", True):
                return self._update_rsp(index, weight, grad, state)
            grad = grad.todense()
        self._update_count(index)
        t = self._index_update_count[index]
        lr = self._get_lr(index)
        wd = self._get_wd(index)
        f = getattr(self, "_jit_step", None)
        if f is None:
            f = self._jit_step = _jit_backed(self._stepper(), tier="jit",
                                             hint="opt_step")
        dispatch_counter.bump()
        new_w, new_state = f(weight._data, grad._data if isinstance(grad, NDArray) else grad,
                             state, jnp.float32(lr), jnp.float32(wd), jnp.int32(t),
                             jnp.float32(self.rescale_grad))
        weight._data = new_w
        return new_state

    def _rsp_stepper(self):
        """Row-lazy update: gather touched rows of weight + row-shaped state
        leaves, run the dense ``_step`` on just those rows, scatter back
        (ref: src/operator/optimizer_op.cc SGDUpdateRsp / AdamUpdateRsp —
        lazy_update touches only rows present in the sparse gradient)."""
        base = self._stepper()

        def step(w, rows, gvals, state, lr, wd, t, rescale=None):
            nrows = w.shape[0]
            # rows may contain nrows (out of bounds) as padding from
            # sparse.dense_to_row_sparse_padded: gathers fill 0, scatters drop.

            def take(leaf):
                if hasattr(leaf, "shape") and leaf.shape[:1] == (nrows,) and \
                        leaf.shape[1:] == w.shape[1:]:
                    return jnp.take(leaf, rows, axis=0, mode="fill", fill_value=0)
                return leaf

            sub_state = jax.tree_util.tree_map(take, state)
            w_rows = jnp.take(w, rows, axis=0, mode="fill", fill_value=0)
            new_rows, new_sub = base(w_rows, gvals, sub_state, lr, wd, t, rescale)

            def put(leaf, new_leaf):
                if hasattr(leaf, "shape") and leaf.shape[:1] == (nrows,) and \
                        leaf.shape[1:] == w.shape[1:]:
                    return leaf.at[rows].set(new_leaf, mode="drop")
                return new_leaf

            new_state = jax.tree_util.tree_map(put, state, new_sub)
            return w.at[rows].set(new_rows, mode="drop"), new_state

        return step

    def _update_rsp(self, index, weight, grad, state):
        self._update_count(index)
        t = self._index_update_count[index]
        lr = self._get_lr(index)
        wd = self._get_wd(index)
        f = getattr(self, "_jit_rsp_step", None)
        if f is None:
            f = self._jit_rsp_step = _jit_backed(self._rsp_stepper(),
                                                 tier="jit",
                                                 hint="opt_rsp_step")
        dispatch_counter.bump()
        new_w, new_state = f(weight._data, grad.indices._data, grad.data._data,
                             state, jnp.float32(lr), jnp.float32(wd), jnp.int32(t),
                             jnp.float32(self.rescale_grad))
        weight._data = new_w
        return new_state

    def update_multi_precision(self, index, weight, grad, state):
        return self.update(index, weight, grad, state)

    # ------------------------------------------------- fused multi-tensor step
    def _fused_stepper(self, mesh=None, shard_axis="dp", keep_sharded=False):
        """One traced function applying ``_step`` leaf-wise to EVERY
        parameter — the multi_sgd_update / multi_mp_sgd_update analogue
        (ref: src/operator/optimizer_op.cc MultiSGDUpdate &co): N per-param
        XLA dispatches collapse into one program. With ``mesh``, each
        update additionally runs on a 1/N shard of the replicas along
        ``shard_axis`` and the updated weights are all-gathered back while
        optimizer state stays sharded — ZeRO-1-style weight-update sharding
        (Xu et al., arXiv 2004.13336). ``keep_sharded`` skips that final
        all-gather: weights LEAVE the step sharded like the state (ZeRO-3
        parameter residency — mxnet_tpu.dist gathers them back per-bucket
        on demand before the next forward)."""
        base = self._stepper()
        if mesh is not None:
            from jax.sharding import NamedSharding, PartitionSpec

            nshard = mesh.shape[shard_axis] if shard_axis is not None else 1

            def _spec(shape):
                # shard the first axis the replica count divides; tensors
                # too small to split stay replicated (their update is noise
                # next to the big ones the paper targets). shard_axis=None
                # runs the update ON the mesh but fully replicated — the
                # residency mxnet_tpu.dist needs when its exchanged grads
                # live mesh-committed without ZeRO sharding.
                if shard_axis is None:
                    return PartitionSpec()
                for d, s in enumerate(shape):
                    if s >= nshard and s % nshard == 0:
                        return PartitionSpec(*([None] * d + [shard_axis]))
                return PartitionSpec()

            def _con(x, spec):
                return jax.lax.with_sharding_constraint(
                    x, NamedSharding(mesh, spec))

        def fused(ws, gs, ss, lrs, wds, ts, rescale):
            new_ws, new_ss = [], []
            for k, (w, g, s) in enumerate(zip(ws, gs, ss)):
                if mesh is not None:
                    spec = _spec(w.shape)
                    wshape = w.shape
                    w = _con(w, spec)
                    g = _con(g, spec)
                    # weight-shaped state leaves (momenta, masters) shard
                    # with the weight; odd-shaped leaves stay as they are
                    s = jax.tree_util.tree_map(
                        lambda l: _con(l, spec)
                        if getattr(l, "shape", None) == wshape else l, s)
                nw, ns = base(w, g, s, lrs[k], wds[k], ts[k], rescale)
                if mesh is not None:
                    # all-gather the updated shard back to replicated; the
                    # state stays sharded across replicas (ZeRO-1's memory
                    # and weight-update-FLOP saving). ZeRO-3 keeps the
                    # weights sharded too — the all-gather moves to the
                    # consumer side (dist.Zero3ParamManager, per-bucket).
                    nw = _con(nw, spec if keep_sharded else PartitionSpec())
                new_ws.append(nw)
                new_ss.append(ns)
            return new_ws, new_ss

        return fused

    def fused_update(self, params, grads, states, wrappers=None, indices=None,
                     mesh=None, shard_axis="dp", donate=True,
                     keep_sharded=False):
        """Apply the update to every parameter in ONE jitted XLA dispatch
        with weight and state buffers donated. Per-param lr/wd (multipliers
        included) and update counts enter as traced arrays, so LR schedules
        and Trainer.step(batch_size) rescale changes never retrace.

        params: list of NDArray weights (updated in place) or raw arrays;
        ``wrappers`` (optional, same length) receives the new weights when
        given — NDArray or gluon Parameter entries are written in place.
        grads / states: lists matching ``params``; returns the new states.
        indices: per-param keys for lr_mult/wd_mult lookup + update counts
        (defaults to positions). Caching: one jitted program per
        (optimizer instance, mesh); jax.jit's signature cache keys the
        rest by treedef/shapes/dtypes.

        donate=False keeps the input weight buffers alive — required when
        raw ``._data`` arrays are aliased elsewhere (KVStore.pull hands the
        store's buffer to ``out``); states are donated either way (the
        caller always replaces its references with the returned ones)."""
        n = len(params)
        if n == 0:
            return []
        if donate:
            # deferred imperative work (bulk window / recorded tape region)
            # may still hold the CURRENT weight buffers as captured leaves;
            # donating them would leave the eventual flush reading deleted
            # arrays — drain the window first (no-op when nothing pends)
            from . import engine

            engine.flush()
        if indices is None:
            indices = list(range(n))
        for i in indices:
            self._update_count(i)
        # stacked (N,) arrays, not N scalars: three host->device transfers
        # per step instead of 3N tiny ones
        ts = jnp.asarray([self._index_update_count[i] for i in indices],
                         jnp.int32)
        lrs = jnp.asarray([self._get_lr(i) for i in indices], jnp.float32)
        wds = jnp.asarray([self._get_wd(i) for i in indices], jnp.float32)
        ws = [getattr(w, "_data", w) for w in params]
        gs = [getattr(g, "_data", g) for g in grads]
        states = list(states)
        if mesh is not None:
            # arrays committed to a single device can't feed a computation
            # constrained over the mesh — replicate them on first entry
            # (in-mesh steady state: already on the mesh, no transfer)
            from jax.sharding import NamedSharding, PartitionSpec

            rep = NamedSharding(mesh, PartitionSpec())

            def _on_mesh(x):
                sh = getattr(x, "sharding", None)
                if getattr(sh, "mesh", None) == mesh:
                    return x
                return jax.device_put(x, rep)

            ws = [_on_mesh(w) for w in ws]
            gs = [_on_mesh(g) for g in gs]
            states = jax.tree_util.tree_map(_on_mesh, states)
        cache = getattr(self, "_jit_fused", None)
        if cache is None:
            cache = self._jit_fused = {}
        ckey = (None if mesh is None else (mesh, shard_axis), bool(donate),
                bool(keep_sharded))
        f = cache.get(ckey)
        if f is None:
            f = cache[ckey] = _jit_backed(
                self._fused_stepper(mesh, shard_axis,
                                    keep_sharded=keep_sharded),
                donate=(0, 2) if donate else (2,), tier="jit",
                hint="fused_step")
        dispatch_counter.bump()
        new_ws, new_states = f(ws, gs, list(states), lrs, wds, ts,
                               jnp.float32(self.rescale_grad))
        for tgt, nw in zip(params if wrappers is None else wrappers, new_ws):
            if isinstance(tgt, NDArray):
                tgt._data = nw
            elif isinstance(getattr(tgt, "_data", None), NDArray):
                tgt._data._data = nw  # gluon Parameter wrapper
        return list(new_states)


@register
class SGD(Optimizer):
    """(ref: src/operator/optimizer_op.cc:sgd_mom_update)"""

    def __init__(self, momentum=0.0, lazy_update=True, **kwargs):
        super().__init__(**kwargs)
        self.momentum = momentum
        self.lazy_update = lazy_update

    def init_state(self, w):
        return jnp.zeros_like(w, dtype=jnp.float32) if self.momentum else ()

    def _step(self, w, g, state, lr, wd, t):
        g = g + wd * w
        if self.momentum:
            mom = self.momentum * state - lr * g
            return w + mom.astype(w.dtype), mom
        return w - (lr * g).astype(w.dtype), state


@register
class NAG(SGD):
    """Nesterov accelerated SGD (ref: optimizer.py:NAG)."""

    def _step(self, w, g, state, lr, wd, t):
        g = g + wd * w
        if self.momentum:
            mom = self.momentum * state - lr * g
            return w + (self.momentum * mom - lr * g).astype(w.dtype), mom
        return w - (lr * g).astype(w.dtype), state


@register
class Adam(Optimizer):
    """(ref: src/operator/optimizer_op.cc:adam_update)"""

    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999, epsilon=1e-8, **kwargs):
        super().__init__(learning_rate=learning_rate, **kwargs)
        self.beta1, self.beta2, self.epsilon = beta1, beta2, epsilon

    def init_state(self, w):
        return (jnp.zeros_like(w, dtype=jnp.float32),
                jnp.zeros_like(w, dtype=jnp.float32))

    def _step(self, w, g, state, lr, wd, t):
        m, v = state
        g = g.astype(jnp.float32) + wd * w.astype(jnp.float32)
        m = self.beta1 * m + (1 - self.beta1) * g
        v = self.beta2 * v + (1 - self.beta2) * jnp.square(g)
        tf = t.astype(jnp.float32)
        mhat = m / (1 - self.beta1 ** tf)
        vhat = v / (1 - self.beta2 ** tf)
        upd = lr * mhat / (jnp.sqrt(vhat) + self.epsilon)
        return (w.astype(jnp.float32) - upd).astype(w.dtype), (m, v)


@register
class AdamW(Adam):
    """Decoupled weight decay (ref: python/mxnet/contrib/optimizer.py? + AdamW paper)."""

    def _step(self, w, g, state, lr, wd, t):
        m, v = state
        g = g.astype(jnp.float32)
        m = self.beta1 * m + (1 - self.beta1) * g
        v = self.beta2 * v + (1 - self.beta2) * jnp.square(g)
        tf = t.astype(jnp.float32)
        mhat = m / (1 - self.beta1 ** tf)
        vhat = v / (1 - self.beta2 ** tf)
        upd = lr * (mhat / (jnp.sqrt(vhat) + self.epsilon) + wd * w.astype(jnp.float32))
        return (w.astype(jnp.float32) - upd).astype(w.dtype), (m, v)


@register
class AdaGrad(Optimizer):
    def __init__(self, eps=1e-7, **kwargs):
        super().__init__(**kwargs)
        self.float_stable_eps = eps

    def init_state(self, w):
        return jnp.zeros_like(w, dtype=jnp.float32)

    def _step(self, w, g, state, lr, wd, t):
        g = g + wd * w
        hist = state + jnp.square(g)
        return (w - lr * g / (jnp.sqrt(hist) + self.float_stable_eps)).astype(w.dtype), hist


@register
class AdaDelta(Optimizer):
    def __init__(self, rho=0.9, epsilon=1e-5, **kwargs):
        super().__init__(**kwargs)
        self.rho, self.epsilon = rho, epsilon

    def init_state(self, w):
        return (jnp.zeros_like(w, dtype=jnp.float32),
                jnp.zeros_like(w, dtype=jnp.float32))

    def _step(self, w, g, state, lr, wd, t):
        acc_g, acc_d = state
        g = g + wd * w
        acc_g = self.rho * acc_g + (1 - self.rho) * jnp.square(g)
        d = jnp.sqrt(acc_d + self.epsilon) / jnp.sqrt(acc_g + self.epsilon) * g
        acc_d = self.rho * acc_d + (1 - self.rho) * jnp.square(d)
        return (w - d).astype(w.dtype), (acc_g, acc_d)


@register
class RMSProp(Optimizer):
    def __init__(self, learning_rate=0.001, gamma1=0.9, gamma2=0.9, epsilon=1e-8,
                 centered=False, **kwargs):
        super().__init__(learning_rate=learning_rate, **kwargs)
        self.gamma1, self.gamma2, self.epsilon, self.centered = gamma1, gamma2, epsilon, centered

    def init_state(self, w):
        mk = lambda: jnp.zeros_like(w, dtype=jnp.float32)
        return (mk(), mk(), mk()) if self.centered else (mk(),)

    def _step(self, w, g, state, lr, wd, t):
        g = g + wd * w
        if self.centered:
            n, mg, mom = state
            n = self.gamma1 * n + (1 - self.gamma1) * jnp.square(g)
            mg = self.gamma1 * mg + (1 - self.gamma1) * g
            mom = self.gamma2 * mom - lr * g / jnp.sqrt(n - jnp.square(mg) + self.epsilon)
            return (w + mom).astype(w.dtype), (n, mg, mom)
        (n,) = state
        n = self.gamma1 * n + (1 - self.gamma1) * jnp.square(g)
        return (w - lr * g / (jnp.sqrt(n) + self.epsilon)).astype(w.dtype), (n,)


@register
class Ftrl(Optimizer):
    def __init__(self, lamda1=0.01, learning_rate=0.1, beta=1.0, **kwargs):
        super().__init__(learning_rate=learning_rate, **kwargs)
        self.lamda1, self.beta = lamda1, beta

    def init_state(self, w):
        return (jnp.zeros_like(w, dtype=jnp.float32),
                jnp.zeros_like(w, dtype=jnp.float32))

    def _step(self, w, g, state, lr, wd, t):
        z, n = state
        sigma = (jnp.sqrt(n + jnp.square(g)) - jnp.sqrt(n)) / lr
        z = z + g - sigma * w
        n = n + jnp.square(g)
        new_w = jnp.where(
            jnp.abs(z) > self.lamda1,
            -(z - jnp.sign(z) * self.lamda1) / ((self.beta + jnp.sqrt(n)) / lr + wd),
            0.0,
        )
        return new_w.astype(w.dtype), (z, n)


@register
class LAMB(Optimizer):
    """Layer-wise adaptive moments for large-batch BERT (ref: contrib LAMB)."""

    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999, epsilon=1e-6,
                 lower_bound=None, upper_bound=None, bias_correction=True, **kwargs):
        super().__init__(learning_rate=learning_rate, **kwargs)
        self.beta1, self.beta2, self.epsilon = beta1, beta2, epsilon
        self.lower_bound, self.upper_bound = lower_bound, upper_bound
        self.bias_correction = bias_correction

    def init_state(self, w):
        return (jnp.zeros_like(w, dtype=jnp.float32),
                jnp.zeros_like(w, dtype=jnp.float32))

    def _step(self, w, g, state, lr, wd, t):
        m, v = state
        g = g.astype(jnp.float32)
        m = self.beta1 * m + (1 - self.beta1) * g
        v = self.beta2 * v + (1 - self.beta2) * jnp.square(g)
        if self.bias_correction:
            tf = t.astype(jnp.float32)
            mhat = m / (1 - self.beta1 ** tf)
            vhat = v / (1 - self.beta2 ** tf)
        else:
            mhat, vhat = m, v
        r = mhat / (jnp.sqrt(vhat) + self.epsilon) + wd * w.astype(jnp.float32)
        wnorm = jnp.linalg.norm(w.astype(jnp.float32))
        rnorm = jnp.linalg.norm(r)
        ratio = jnp.where((wnorm > 0) & (rnorm > 0), wnorm / rnorm, 1.0)
        if self.lower_bound is not None:
            ratio = jnp.maximum(ratio, self.lower_bound)
        if self.upper_bound is not None:
            ratio = jnp.minimum(ratio, self.upper_bound)
        return (w.astype(jnp.float32) - lr * ratio * r).astype(w.dtype), (m, v)


@register
class Signum(Optimizer):
    def __init__(self, learning_rate=0.01, momentum=0.9, wd_lh=0.0, **kwargs):
        super().__init__(learning_rate=learning_rate, **kwargs)
        self.momentum, self.wd_lh = momentum, wd_lh

    def init_state(self, w):
        return jnp.zeros_like(w, dtype=jnp.float32)

    def _step(self, w, g, state, lr, wd, t):
        mom = self.momentum * state + (1 - self.momentum) * (g + wd * w)
        return (w * (1 - lr * self.wd_lh) - lr * jnp.sign(mom)).astype(w.dtype), mom


@register
class AdaMax(Optimizer):
    """Adam variant with infinity-norm second moment
    (ref: python/mxnet/optimizer/adamax.py)."""

    def __init__(self, learning_rate=0.002, beta1=0.9, beta2=0.999, **kwargs):
        super().__init__(learning_rate=learning_rate, **kwargs)
        self.beta1, self.beta2 = beta1, beta2

    def init_state(self, w):
        return (jnp.zeros_like(w, dtype=jnp.float32),
                jnp.zeros_like(w, dtype=jnp.float32))

    def _step(self, w, g, state, lr, wd, t):
        m, u = state
        g = g.astype(jnp.float32) + wd * w.astype(jnp.float32)
        m = self.beta1 * m + (1 - self.beta1) * g
        u = jnp.maximum(self.beta2 * u, jnp.abs(g))
        lr_t = lr / (1 - self.beta1 ** t.astype(jnp.float32))
        return (w.astype(jnp.float32) - lr_t * m / (u + 1e-8)).astype(w.dtype), (m, u)


@register
class FTML(Optimizer):
    """Follow the Moving Leader (ref: python/mxnet/optimizer/ftml.py,
    src/operator/optimizer_op.cc:ftml_update)."""

    def __init__(self, learning_rate=0.0025, beta1=0.6, beta2=0.999,
                 epsilon=1e-8, **kwargs):
        super().__init__(learning_rate=learning_rate, **kwargs)
        self.beta1, self.beta2, self.epsilon = beta1, beta2, epsilon

    def init_state(self, w):
        z = jnp.zeros_like(w, dtype=jnp.float32)
        return (z, z, z)  # d, v, z

    def _step(self, w, g, state, lr, wd, t):
        d, v, z = state
        wf = w.astype(jnp.float32)
        g = g.astype(jnp.float32) + wd * wf
        tf = t.astype(jnp.float32)
        v = self.beta2 * v + (1 - self.beta2) * jnp.square(g)
        d_t = (1 - self.beta1 ** tf) / lr * (
            jnp.sqrt(v / (1 - self.beta2 ** tf)) + self.epsilon)
        sigma = d_t - self.beta1 * d
        z = self.beta1 * z + (1 - self.beta1) * g - sigma * wf
        return (-z / d_t).astype(w.dtype), (d_t, v, z)


@register
class DCASGD(Optimizer):
    """Delay-compensated async SGD (ref: python/mxnet/optimizer/dcasgd.py):
    corrects a stale gradient with lambda * g² * (w_now - w_then)."""

    def __init__(self, learning_rate=0.01, momentum=0.0, lamda=0.04, **kwargs):
        super().__init__(learning_rate=learning_rate, **kwargs)
        self.momentum, self.lamda = momentum, lamda

    def init_state(self, w):
        return (jnp.zeros_like(w, dtype=jnp.float32),
                jnp.asarray(w, jnp.float32))  # momentum, previous weight

    def _step(self, w, g, state, lr, wd, t):
        mom, prev = state
        wf = w.astype(jnp.float32)
        g = g.astype(jnp.float32) + wd * wf
        mom = self.momentum * mom - lr * (
            g + self.lamda * jnp.square(g) * (wf - prev))
        # previous_weight records the PRE-update value (upstream dcasgd.py
        # assigns it before applying mom), so next step's compensation term
        # sees this step's delta
        return (wf + mom).astype(w.dtype), (mom, wf)


@register
class LARS(Optimizer):
    """Layer-wise adaptive rate scaling (ref: python/mxnet/optimizer/lars.py):
    per-tensor trust ratio eta·||w||/(||g||+wd·||w||) scales the SGD-momentum
    step — the large-batch vision-training staple."""

    def __init__(self, learning_rate=0.1, momentum=0.9, eta=0.001,
                 epsilon=1e-8, **kwargs):
        super().__init__(learning_rate=learning_rate, **kwargs)
        self.momentum, self.eta, self.epsilon = momentum, eta, epsilon

    def init_state(self, w):
        return jnp.zeros_like(w, dtype=jnp.float32)

    def _step(self, w, g, state, lr, wd, t):
        wf = w.astype(jnp.float32)
        g = g.astype(jnp.float32)
        w_norm = jnp.linalg.norm(wf)
        g_norm = jnp.linalg.norm(g)
        ratio = jnp.where(
            (w_norm > 0) & (g_norm > 0),
            self.eta * w_norm / (g_norm + wd * w_norm + self.epsilon),
            1.0)
        g = g + wd * wf
        mom = self.momentum * state + lr * ratio * g
        return (wf - mom).astype(w.dtype), mom


@register
class SGLD(Optimizer):
    """Stochastic gradient Langevin dynamics (ref: optimizer.py:SGLD)."""

    def init_state(self, w):
        return jnp.zeros((2,), jnp.uint32)  # fold counter as pseudo-state

    def _step(self, w, g, state, lr, wd, t):
        g = g + wd * w
        key = jax.random.fold_in(jax.random.PRNGKey(0), t)
        noise = jax.random.normal(key, w.shape, jnp.float32) * jnp.sqrt(lr)
        return (w - 0.5 * lr * g + noise.astype(w.dtype)).astype(w.dtype), state


class Updater:
    """(ref: optimizer.py:Updater) — kvstore-side updater closure."""

    def __init__(self, optimizer):
        self.optimizer = optimizer
        self.states = {}
        # set via KVStore.set_weight_update_sharding (ZeRO-1 opt-in)
        self.wu_mesh = None
        self.wu_axis = "dp"

    def __call__(self, index, grad, weight):
        if index not in self.states:
            self.states[index] = self.optimizer.create_state(index, weight)
        self.states[index] = self.optimizer.update(index, weight, grad, self.states[index])

    def batch_call(self, indices, grads, weights):
        """Fused multi-tensor update: the whole key batch in ONE jitted,
        donated dispatch via Optimizer.fused_update (vs one per key)."""
        for i, w in zip(indices, weights):
            if i not in self.states:
                self.states[i] = self.optimizer.create_state(i, w)
        # donate=False: KVStore.pull aliases the store's raw buffers into
        # ``out`` arrays — donating them would invalidate earlier pulls
        new = self.optimizer.fused_update(
            list(weights), list(grads), [self.states[i] for i in indices],
            indices=list(indices), mesh=self.wu_mesh, shard_axis=self.wu_axis,
            donate=False)
        for i, s in zip(indices, new):
            self.states[i] = s


def get_updater(optimizer):
    return Updater(optimizer)
