"""Linear-algebra namespace (ref: src/operator/tensor/la_op.cc — MXNet's
mx.nd.linalg backed by cuSolver/LAPACK; here XLA's native decompositions)."""
from __future__ import annotations

import jax.numpy as jnp

from .ndarray import NDArray

__all__ = ["gemm2", "potrf", "potri", "trsm", "trmm", "syrk", "det", "inverse",
           "cholesky", "qr", "svd", "eigh", "norm", "solve"]


def _w(x):
    return x._data if isinstance(x, NDArray) else jnp.asarray(x)


# --------------------------------------------------------- jnp-level kernels
# ONE implementation per algorithm; the NDArray namespace below and the flat
# registry ops (ops/legacy_ops.py linalg_*) both call these.

def k_gemm2(A, B, transpose_a=False, transpose_b=False, alpha=1.0):
    if transpose_a:
        A = jnp.swapaxes(A, -1, -2)
    if transpose_b:
        B = jnp.swapaxes(B, -1, -2)
    return alpha * (A @ B)


def k_potri(L):
    """Inverse from the Cholesky FACTOR: (L Lᵀ)⁻¹ given L (the MXNet
    linalg_potri contract — input is potrf's output, not the SPD matrix)."""
    inv_l = jnp.linalg.inv(L)
    return jnp.swapaxes(inv_l, -1, -2) @ inv_l


def k_trsm(A, B, transpose=False, rightside=False, alpha=1.0, lower=True):
    import jax.scipy.linalg as jsl

    if transpose:
        A = jnp.swapaxes(A, -1, -2)
        lower = not lower
    if rightside:
        return alpha * jnp.swapaxes(
            jsl.solve_triangular(jnp.swapaxes(A, -1, -2),
                                 jnp.swapaxes(B, -1, -2), lower=not lower),
            -1, -2)
    return alpha * jsl.solve_triangular(A, B, lower=lower)


def k_trmm(A, B, transpose=False, rightside=False, alpha=1.0):
    if transpose:
        A = jnp.swapaxes(A, -1, -2)
    return alpha * ((B @ A) if rightside else (A @ B))


def k_syrk(A, transpose=False, alpha=1.0):
    if transpose:
        A = jnp.swapaxes(A, -1, -2)
    return alpha * (A @ jnp.swapaxes(A, -1, -2))


def k_gelqf(A):
    """LQ via QR of the transpose: A = L Q, Aᵀ = Qᵀ Lᵀ."""
    q, r = jnp.linalg.qr(jnp.swapaxes(A, -1, -2))
    return jnp.swapaxes(r, -1, -2), jnp.swapaxes(q, -1, -2)


# --------------------------------------------------------- NDArray namespace

def gemm2(a, b, transpose_a=False, transpose_b=False, alpha=1.0):
    return NDArray(k_gemm2(_w(a), _w(b), transpose_a, transpose_b, alpha))


def potrf(a):
    """Cholesky factor (lower), MXNet linalg_potrf."""
    return NDArray(jnp.linalg.cholesky(_w(a)))


cholesky = potrf


def potri(a):
    """Inverse from Cholesky factor: (L L^T)^-1 given L."""
    return NDArray(k_potri(_w(a)))


def trsm(a, b, transpose=False, rightside=False, alpha=1.0, lower=True):
    return NDArray(k_trsm(_w(a), _w(b), transpose, rightside, alpha, lower))


def trmm(a, b, transpose=False, rightside=False, alpha=1.0):
    return NDArray(k_trmm(_w(a), _w(b), transpose, rightside, alpha))


def syrk(a, transpose=False, alpha=1.0):
    return NDArray(k_syrk(_w(a), transpose, alpha))


def det(a):
    return NDArray(jnp.linalg.det(_w(a)))


def inverse(a):
    return NDArray(jnp.linalg.inv(_w(a)))


def solve(a, b):
    return NDArray(jnp.linalg.solve(_w(a), _w(b)))


def qr(a):
    q, r = jnp.linalg.qr(_w(a))
    return NDArray(q), NDArray(r)


def svd(a):
    u, s, vt = jnp.linalg.svd(_w(a), full_matrices=False)
    return NDArray(u), NDArray(s), NDArray(vt)


def eigh(a):
    w, v = jnp.linalg.eigh(_w(a))
    return NDArray(w), NDArray(v)


def norm(a, ord=None, axis=None):
    return NDArray(jnp.linalg.norm(_w(a), ord=ord, axis=axis))
