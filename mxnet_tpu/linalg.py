"""Linear-algebra namespace (ref: src/operator/tensor/la_op.cc — MXNet's
mx.nd.linalg backed by cuSolver/LAPACK; here XLA's native decompositions)."""
from __future__ import annotations

import jax.numpy as jnp

from .ndarray import NDArray

__all__ = ["gemm2", "potrf", "potri", "trsm", "trmm", "syrk", "det", "inverse",
           "cholesky", "qr", "svd", "eigh", "norm", "solve"]


def _w(x):
    return x._data if isinstance(x, NDArray) else jnp.asarray(x)


def gemm2(a, b, transpose_a=False, transpose_b=False, alpha=1.0):
    A, B = _w(a), _w(b)
    if transpose_a:
        A = jnp.swapaxes(A, -1, -2)
    if transpose_b:
        B = jnp.swapaxes(B, -1, -2)
    return NDArray(alpha * (A @ B))


def potrf(a):
    """Cholesky factor (lower), MXNet linalg_potrf."""
    return NDArray(jnp.linalg.cholesky(_w(a)))


cholesky = potrf


def potri(a):
    """Inverse from Cholesky factor: (L L^T)^-1 given L."""
    L = _w(a)
    inv_l = jnp.linalg.inv(L)
    return NDArray(jnp.swapaxes(inv_l, -1, -2) @ inv_l)


def trsm(a, b, transpose=False, rightside=False, alpha=1.0, lower=True):
    import jax.scipy.linalg as jsl

    A, B = _w(a), _w(b)
    if transpose:
        A = jnp.swapaxes(A, -1, -2)
        lower = not lower
    if rightside:
        X = jnp.swapaxes(
            jsl.solve_triangular(jnp.swapaxes(A, -1, -2),
                                 jnp.swapaxes(B, -1, -2), lower=not lower), -1, -2)
    else:
        X = jsl.solve_triangular(A, B, lower=lower)
    return NDArray(alpha * X)


def trmm(a, b, transpose=False, rightside=False, alpha=1.0):
    A, B = _w(a), _w(b)
    if transpose:
        A = jnp.swapaxes(A, -1, -2)
    out = (B @ A) if rightside else (A @ B)
    return NDArray(alpha * out)


def syrk(a, transpose=False, alpha=1.0):
    A = _w(a)
    if transpose:
        A = jnp.swapaxes(A, -1, -2)
    return NDArray(alpha * (A @ jnp.swapaxes(A, -1, -2)))


def det(a):
    return NDArray(jnp.linalg.det(_w(a)))


def inverse(a):
    return NDArray(jnp.linalg.inv(_w(a)))


def solve(a, b):
    return NDArray(jnp.linalg.solve(_w(a), _w(b)))


def qr(a):
    q, r = jnp.linalg.qr(_w(a))
    return NDArray(q), NDArray(r)


def svd(a):
    u, s, vt = jnp.linalg.svd(_w(a), full_matrices=False)
    return NDArray(u), NDArray(s), NDArray(vt)


def eigh(a):
    w, v = jnp.linalg.eigh(_w(a))
    return NDArray(w), NDArray(v)


def norm(a, ord=None, axis=None):
    return NDArray(jnp.linalg.norm(_w(a), ord=ord, axis=axis))
