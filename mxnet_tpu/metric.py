"""Evaluation metrics (ref: python/mxnet/metric.py)."""
from __future__ import annotations

import numpy

from .ndarray import NDArray

__all__ = ["EvalMetric", "Accuracy", "TopKAccuracy", "F1", "MAE", "MSE", "RMSE",
           "CrossEntropy", "Perplexity", "PearsonCorrelation", "Loss",
           "CompositeEvalMetric", "create"]

def register(klass):
    """Backed by the generic mx.registry machinery (ref: registry.py)."""
    from . import registry as _reg
    return _reg.get_register_func(EvalMetric, "metric")(klass)


def create(metric, **kwargs):
    if isinstance(metric, EvalMetric):
        return metric
    if isinstance(metric, (list, tuple)):
        return CompositeEvalMetric([create(m) for m in metric])
    if callable(metric):
        return CustomMetric(metric, **kwargs)
    from . import registry as _reg
    return _reg.get_create_func(EvalMetric, "metric")(metric, **kwargs)


def _np(x):
    return x.asnumpy() if isinstance(x, NDArray) else numpy.asarray(x)


class EvalMetric:
    def __init__(self, name, output_names=None, label_names=None, **kwargs):
        self.name = name
        self.output_names = output_names
        self.label_names = label_names
        self.reset()

    def reset(self):
        self.num_inst = 0
        self.sum_metric = 0.0

    def update(self, labels, preds):
        raise NotImplementedError

    def get(self):
        if self.num_inst == 0:
            return self.name, float("nan")
        return self.name, self.sum_metric / self.num_inst

    def get_name_value(self):
        name, value = self.get()
        if not isinstance(name, list):
            name, value = [name], [value]
        return list(zip(name, value))


@register
class Accuracy(EvalMetric):
    def __init__(self, axis=1, name="accuracy", **kwargs):
        super().__init__(name, **kwargs)
        self.axis = axis

    def update(self, labels, preds):
        if isinstance(labels, (NDArray, numpy.ndarray)):
            labels, preds = [labels], [preds]
        for label, pred in zip(labels, preds):
            label, pred = _np(label), _np(pred)
            if pred.ndim > label.ndim:
                pred = numpy.argmax(pred, axis=self.axis)
            self.sum_metric += float((pred.astype("int64").flat == label.astype("int64").flat).sum())
            self.num_inst += label.size


@register
class TopKAccuracy(EvalMetric):
    def __init__(self, top_k=1, name="top_k_accuracy", **kwargs):
        super().__init__("%s_%d" % (name, top_k), **kwargs)
        self.top_k = top_k

    def update(self, labels, preds):
        if isinstance(labels, (NDArray, numpy.ndarray)):
            labels, preds = [labels], [preds]
        for label, pred in zip(labels, preds):
            label, pred = _np(label).astype("int64"), _np(pred)
            topk = numpy.argsort(-pred, axis=-1)[:, :self.top_k]
            self.sum_metric += float((topk == label[:, None]).any(axis=1).sum())
            self.num_inst += label.shape[0]


class _ConfusionMetric(EvalMetric):
    """Accumulates per-class tp/fp/fn (ref: python/mxnet/metric.py
    _BinaryClassificationMetrics, generalized to multiclass)."""

    def reset(self):
        super().reset()
        self.tp = {}
        self.fp = {}
        self.fn = {}

    def update(self, labels, preds):
        if isinstance(labels, (NDArray, numpy.ndarray)):
            labels, preds = [labels], [preds]
        for label, pred in zip(labels, preds):
            label, pred = _np(label).astype("int64").ravel(), _np(pred)
            if pred.ndim > 1:
                pred = numpy.argmax(pred, axis=-1)
            pred = pred.astype("int64").ravel()
            # one-pass confusion matrix; per-class loops would cost O(C)
            # full-array scans per batch
            c = int(max(label.max(initial=0), pred.max(initial=0))) + 1
            cm = numpy.bincount(label * c + pred,
                             minlength=c * c).reshape(c, c).astype(numpy.float64)
            row = cm.sum(axis=1)  # true class counts
            col = cm.sum(axis=0)  # predicted class counts
            diag = numpy.diag(cm)
            for k in numpy.nonzero(row + col)[0]:
                k = int(k)
                self.tp[k] = self.tp.get(k, 0.0) + diag[k]
                self.fp[k] = self.fp.get(k, 0.0) + (col[k] - diag[k])
                self.fn[k] = self.fn.get(k, 0.0) + (row[k] - diag[k])
            self.num_inst += 1


@register
class F1(_ConfusionMetric):
    """F1 with micro/macro averaging (ref: python/mxnet/metric.py:F1).
    For the binary case with average='macro' this reports the class-1 F1,
    matching the upstream binary F1."""

    def __init__(self, name="f1", average="macro", **kwargs):
        super().__init__(name, **kwargs)
        self.average = average

    @staticmethod
    def _f1(tp, fp, fn):
        prec = tp / max(tp + fp, 1e-12)
        rec = tp / max(tp + fn, 1e-12)
        return 2 * prec * rec / max(prec + rec, 1e-12)

    def get(self):
        classes = sorted(self.tp)
        if not classes:
            return self.name, 0.0
        if self.average == "micro":
            tp = sum(self.tp.values())
            fp = sum(self.fp.values())
            fn = sum(self.fn.values())
            return self.name, self._f1(tp, fp, fn)
        if classes == [0, 1] or classes == [1] or classes == [0]:
            # binary: upstream F1 is the positive-class score
            return self.name, self._f1(self.tp.get(1, 0.0),
                                       self.fp.get(1, 0.0),
                                       self.fn.get(1, 0.0))
        scores = [self._f1(self.tp[c], self.fp[c], self.fn[c])
                  for c in classes]
        return self.name, float(numpy.mean(scores))


@register
class MCC(_ConfusionMetric):
    """Matthews correlation coefficient (ref: python/mxnet/metric.py:MCC),
    binary: (tp·tn − fp·fn) / sqrt((tp+fp)(tp+fn)(tn+fp)(tn+fn))."""

    def __init__(self, name="mcc", **kwargs):
        super().__init__(name, **kwargs)

    def get(self):
        tp = self.tp.get(1, 0.0)
        fp = self.fp.get(1, 0.0)
        fn = self.fn.get(1, 0.0)
        tn = self.tp.get(0, 0.0)
        denom = numpy.sqrt((tp + fp) * (tp + fn) * (tn + fp) * (tn + fn))
        return self.name, float((tp * tn - fp * fn) / max(denom, 1e-12))


@register
class MAE(EvalMetric):
    def __init__(self, name="mae", **kwargs):
        super().__init__(name, **kwargs)

    def update(self, labels, preds):
        if isinstance(labels, (NDArray, numpy.ndarray)):
            labels, preds = [labels], [preds]
        for label, pred in zip(labels, preds):
            label, pred = _np(label), _np(pred)
            self.sum_metric += float(numpy.abs(label - pred.reshape(label.shape)).mean())
            self.num_inst += 1


@register
class MSE(EvalMetric):
    def __init__(self, name="mse", **kwargs):
        super().__init__(name, **kwargs)

    def update(self, labels, preds):
        if isinstance(labels, (NDArray, numpy.ndarray)):
            labels, preds = [labels], [preds]
        for label, pred in zip(labels, preds):
            label, pred = _np(label), _np(pred)
            self.sum_metric += float(((label - pred.reshape(label.shape)) ** 2).mean())
            self.num_inst += 1


@register
class RMSE(MSE):
    def __init__(self, name="rmse", **kwargs):
        super().__init__(name=name, **kwargs)

    def get(self):
        name, value = super().get()
        return name, float(numpy.sqrt(value))


@register
class CrossEntropy(EvalMetric):
    def __init__(self, eps=1e-12, name="cross-entropy", **kwargs):
        super().__init__(name, **kwargs)
        self.eps = eps

    def update(self, labels, preds):
        if isinstance(labels, (NDArray, numpy.ndarray)):
            labels, preds = [labels], [preds]
        for label, pred in zip(labels, preds):
            label, pred = _np(label).astype("int64").ravel(), _np(pred)
            prob = pred.reshape(-1, pred.shape[-1])[numpy.arange(label.size), label]
            self.sum_metric += float(-numpy.log(prob + self.eps).sum())
            self.num_inst += label.size


@register
class NegativeLogLikelihood(CrossEntropy):
    """Mean -log p(label) (ref: python/mxnet/metric.py:NegativeLogLikelihood)
    — CrossEntropy under its upstream alias/name."""

    def __init__(self, eps=1e-12, name="nll-loss", **kwargs):
        super().__init__(eps=eps, name=name, **kwargs)


@register
class Perplexity(CrossEntropy):
    def __init__(self, ignore_label=None, name="perplexity", **kwargs):
        super().__init__(name=name, **kwargs)
        self.ignore_label = ignore_label

    def update(self, labels, preds):
        if isinstance(labels, (NDArray, numpy.ndarray)):
            labels, preds = [labels], [preds]
        for label, pred in zip(labels, preds):
            label, pred = _np(label).astype("int64").ravel(), _np(pred)
            prob = pred.reshape(-1, pred.shape[-1])[numpy.arange(label.size), label]
            if self.ignore_label is not None:
                mask = label != self.ignore_label
                prob = prob[mask]
            self.sum_metric += float(-numpy.log(prob + self.eps).sum())
            self.num_inst += prob.size

    def get(self):
        if self.num_inst == 0:
            return self.name, float("nan")
        return self.name, float(numpy.exp(self.sum_metric / self.num_inst))


@register
class PearsonCorrelation(EvalMetric):
    def __init__(self, name="pearsonr", **kwargs):
        super().__init__(name, **kwargs)

    def update(self, labels, preds):
        if isinstance(labels, (NDArray, numpy.ndarray)):
            labels, preds = [labels], [preds]
        for label, pred in zip(labels, preds):
            label, pred = _np(label).ravel(), _np(pred).ravel()
            self.sum_metric += float(numpy.corrcoef(label, pred)[0, 1])
            self.num_inst += 1


@register
class Loss(EvalMetric):
    def __init__(self, name="loss", **kwargs):
        super().__init__(name, **kwargs)

    def update(self, _, preds):
        if isinstance(preds, (NDArray, numpy.ndarray)):
            preds = [preds]
        for pred in preds:
            pred = _np(pred)
            self.sum_metric += float(pred.sum())
            self.num_inst += pred.size


class CustomMetric(EvalMetric):
    def __init__(self, feval, name="custom", allow_extra_outputs=False, **kwargs):
        super().__init__(name, **kwargs)
        self.feval = feval
        self._allow_extra_outputs = allow_extra_outputs

    def update(self, labels, preds):
        if isinstance(labels, (NDArray, numpy.ndarray)):
            labels, preds = [labels], [preds]
        if not self._allow_extra_outputs and len(labels) != len(preds):
            raise ValueError(
                "%d labels vs %d predictions — pass allow_extra_outputs=True "
                "to ignore extra outputs (ref: metric.py:CustomMetric)"
                % (len(labels), len(preds)))
        for label, pred in zip(labels, preds):
            v = self.feval(_np(label), _np(pred))
            if isinstance(v, tuple):
                s, n = v
                self.sum_metric += s
                self.num_inst += n
            else:
                self.sum_metric += v
                self.num_inst += 1


np_metric = CustomMetric


class CompositeEvalMetric(EvalMetric):
    def __init__(self, metrics=None, name="composite", **kwargs):
        super().__init__(name, **kwargs)
        self.metrics = [create(m) for m in (metrics or [])]

    def add(self, metric):
        self.metrics.append(create(metric))

    def update(self, labels, preds):
        for m in self.metrics:
            m.update(labels, preds)

    def reset(self):
        for m in getattr(self, "metrics", []):
            m.reset()

    def get(self):
        names, values = [], []
        for m in self.metrics:
            n, v = m.get()
            names.append(n)
            values.append(v)
        return names, values


def np(numpy_feval, name=None, allow_extra_outputs=False):
    """Wrap a numpy feval(label, pred) into a CustomMetric (ref:
    python/mxnet/metric.py:np)."""

    def feval(label, pred):
        return numpy_feval(label, pred)

    feval.__name__ = name or getattr(numpy_feval, "__name__", "custom")
    return CustomMetric(feval, feval.__name__, allow_extra_outputs)


# upstream's short registry aliases (ref: python/mxnet/metric.py @alias)
from . import registry as _registry_mod
_alias = _registry_mod.get_alias_func(EvalMetric, "metric")
_alias("acc")(Accuracy)
_alias("top_k_accuracy", "top_k_acc")(TopKAccuracy)
_alias("ce")(CrossEntropy)


def check_label_shapes(labels, preds, wrap=False, shape=False):
    """(ref: python/mxnet/metric.py:check_label_shapes). Upstream compares
    ``len()`` BEFORE any wrapping — for a single array that is its batch
    dim, so a batch-size mismatch between two bare arrays raises here, not
    just list-length mismatches. ``shape=True`` compares full ``.shape``
    attributes directly; always returns ``(labels, preds)``, wrapped in
    lists only when ``wrap=True``."""
    if not shape:
        label_shape, pred_shape = len(labels), len(preds)
    else:
        label_shape, pred_shape = tuple(labels.shape), tuple(preds.shape)
    if label_shape != pred_shape:
        raise ValueError("Shape of labels %s does not match shape of "
                         "predictions %s" % (label_shape, pred_shape))
    if wrap:
        if isinstance(labels, (NDArray, numpy.ndarray)):
            labels = [labels]
        if isinstance(preds, (NDArray, numpy.ndarray)):
            preds = [preds]
    return labels, preds
