"""ROI ops for two-stage detectors (ref: src/operator/contrib/roi_align.cc,
src/operator/roi_pooling.cc).

TPU-native formulation: fixed sampling grids (static shapes — no per-ROI
dynamic extents like the CUDA kernels), bilinear gather vectorized with vmap;
XLA lowers the gathers efficiently and the whole op is differentiable through
autodiff (the reference hand-writes the atomicAdd backward).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ..base import register_op


def _bilinear(img, y, x):
    """img: (C, H, W); y, x: sample grids (...,). Returns (C, ...)."""
    H, W = img.shape[1], img.shape[2]
    y = jnp.clip(y, 0.0, H - 1.0)
    x = jnp.clip(x, 0.0, W - 1.0)
    y0 = jnp.floor(y).astype(jnp.int32)
    x0 = jnp.floor(x).astype(jnp.int32)
    y1 = jnp.minimum(y0 + 1, H - 1)
    x1 = jnp.minimum(x0 + 1, W - 1)
    wy1 = y - y0
    wx1 = x - x0
    wy0 = 1.0 - wy1
    wx0 = 1.0 - wx1
    v00 = img[:, y0, x0]
    v01 = img[:, y0, x1]
    v10 = img[:, y1, x0]
    v11 = img[:, y1, x1]
    return v00 * wy0 * wx0 + v01 * wy0 * wx1 + v10 * wy1 * wx0 + v11 * wy1 * wx1


def _roi_grid(roi, pooled, sample_ratio, spatial_scale):
    ph, pw = pooled
    x1, y1, x2, y2 = roi[0], roi[1], roi[2], roi[3]
    x1, y1, x2, y2 = (v * spatial_scale for v in (x1, y1, x2, y2))
    rh = jnp.maximum(y2 - y1, 1.0)
    rw = jnp.maximum(x2 - x1, 1.0)
    bh = rh / ph
    bw = rw / pw
    sr = sample_ratio
    iy = jnp.arange(ph)[:, None, None, None]
    ix = jnp.arange(pw)[None, :, None, None]
    sy = jnp.arange(sr)[None, None, :, None]
    sx = jnp.arange(sr)[None, None, None, :]
    ys = y1 + iy * bh + (sy + 0.5) * bh / sr
    xs = x1 + ix * bw + (sx + 0.5) * bw / sr
    ys = jnp.broadcast_to(ys, (ph, pw, sr, sr))
    xs = jnp.broadcast_to(xs, (ph, pw, sr, sr))
    return ys, xs


@register_op("ROIAlign")
def ROIAlign(data, rois, *, pooled_size, spatial_scale=1.0, sample_ratio=2):
    """data (N, C, H, W); rois (R, 5) [batch_idx, x1, y1, x2, y2] →
    (R, C, ph, pw) with average pooling of bilinear samples."""
    ph, pw = pooled_size

    def one(roi):
        img = data[roi[0].astype(jnp.int32)]
        ys, xs = _roi_grid(roi[1:], (ph, pw), sample_ratio, spatial_scale)
        vals = _bilinear(img, ys, xs)  # (C, ph, pw, sr, sr)
        return jnp.mean(vals, axis=(-1, -2))

    return jax.vmap(one)(rois)


@register_op("ROIPooling")
def ROIPooling(data, rois, *, pooled_size, spatial_scale=1.0):
    """(ref: src/operator/roi_pooling.cc). Max over a fixed dense sample grid
    per bin — static-shape approximation of the quantized-bin max; exact when
    the grid covers every integer location in the bin."""
    ph, pw = pooled_size

    def one(roi):
        img = data[roi[0].astype(jnp.int32)]
        ys, xs = _roi_grid(roi[1:], (ph, pw), 4, spatial_scale)
        vals = _bilinear(img, ys, xs)
        return jnp.max(vals, axis=(-1, -2))

    return jax.vmap(one)(rois)
