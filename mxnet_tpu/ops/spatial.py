"""Spatial transformer ops (ref: src/operator/spatial_transformer.cc,
src/operator/bilinear_sampler.cc, src/operator/grid_generator.cc)."""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ..base import register_op


@register_op("GridGenerator")
def GridGenerator(data, *, transform_type="affine", target_shape=None):
    """affine: data (N, 6) → sampling grid (N, 2, H, W) in [-1, 1] (x, y).
    warp: data (N, 2, H, W) pixel-space flow field added to the identity grid
    (ref: src/operator/grid_generator.cc both kTransFormType branches)."""
    if transform_type == "affine":
        if target_shape is None:
            raise ValueError(
                "GridGenerator(transform_type='affine') requires target_shape=(H, W)")
        H, W = target_shape
        theta = data.reshape(-1, 2, 3)
        ys = jnp.linspace(-1.0, 1.0, H)
        xs = jnp.linspace(-1.0, 1.0, W)
        gy, gx = jnp.meshgrid(ys, xs, indexing="ij")
        ones = jnp.ones_like(gx)
        base = jnp.stack([gx.ravel(), gy.ravel(), ones.ravel()])  # (3, HW)
        out = jnp.einsum("nij,jk->nik", theta, base)  # (N, 2, HW)
        return out.reshape(-1, 2, H, W)
    if transform_type == "warp":
        if data.ndim != 4 or data.shape[1] != 2:
            raise ValueError("warp flow must have shape (N, 2, H, W), got %s"
                             % (data.shape,))
        _, _, H, W = data.shape
        xs = jnp.arange(W, dtype=data.dtype)
        ys = jnp.arange(H, dtype=data.dtype)
        gy, gx = jnp.meshgrid(ys, xs, indexing="ij")
        x_s = (data[:, 0] + gx) * (2.0 / max(W - 1, 1)) - 1.0
        y_s = (data[:, 1] + gy) * (2.0 / max(H - 1, 1)) - 1.0
        return jnp.stack([x_s, y_s], axis=1)
    raise ValueError("unknown transform_type %r" % (transform_type,))


def _bilinear_zero(img, y, x):
    """Bilinear sample with zero padding outside the image: each of the four
    corner taps outside [0,H)x[0,W) contributes 0, matching the boundary
    handling in src/operator/bilinear_sampler.cc (between() guards)."""
    H, W = img.shape[1], img.shape[2]
    y0 = jnp.floor(y).astype(jnp.int32)
    x0 = jnp.floor(x).astype(jnp.int32)
    y1 = y0 + 1
    x1 = x0 + 1
    wy1 = y - y0
    wx1 = x - x0
    wy0 = 1.0 - wy1
    wx0 = 1.0 - wx1

    def tap(yi, xi):
        ok = (yi >= 0) & (yi < H) & (xi >= 0) & (xi < W)
        v = img[:, jnp.clip(yi, 0, H - 1), jnp.clip(xi, 0, W - 1)]
        return jnp.where(ok, v, 0.0)

    return (tap(y0, x0) * wy0 * wx0 + tap(y0, x1) * wy0 * wx1
            + tap(y1, x0) * wy1 * wx0 + tap(y1, x1) * wy1 * wx1)


@register_op("BilinearSampler")
def BilinearSampler(data, grid):
    """data (N, C, H, W); grid (N, 2, Ho, Wo) normalized [-1, 1] (x, y).
    Out-of-boundary samples are 0 (ref: src/operator/bilinear_sampler.cc)."""
    N, C, H, W = data.shape

    def one(img, g):
        gx = (g[0] + 1.0) * (W - 1) / 2.0
        gy = (g[1] + 1.0) * (H - 1) / 2.0
        return _bilinear_zero(img, gy, gx)  # (C, Ho, Wo)

    return jax.vmap(one)(data, grid)


@register_op("SpatialTransformer")
def SpatialTransformer(data, loc, *, target_shape=None, transform_type="affine",
                       sampler_type="bilinear"):
    """(ref: src/operator/spatial_transformer.cc) — affine STN."""
    grid = GridGenerator(loc, transform_type=transform_type,
                         target_shape=target_shape or data.shape[2:])
    return BilinearSampler(data, grid)


@register_op("space_to_depth_stem_conv")
def space_to_depth_stem_conv(x, weight):
    """conv(kernel 7, stride 2, pad 3, no bias) computed as 2x2
    space-to-depth + an equivalent 4x4 stride-1 conv — bit-identical math,
    TPU-shaped: the MXU's input-channel lanes see 12 channels (75%
    utilization after padding to a multiple of 8) instead of 3 (<=37.5%),
    the classic MLPerf ResNet conv0 trick. The weight keeps the standard
    (O, C, 7, 7) layout so checkpoints and torchvision converters are
    untouched; the reparametrization is a linear gather over the weight,
    done at trace time, so gradients flow to the standard weight through
    the same gather's transpose.
    (ref upstream analogue: none — upstream runs conv0 on cuDNN, which has
    its own C=3 special path; this is the XLA/TPU-native equivalent.)
    """
    B, C, H, W = x.shape
    O, Cw, KH, KW = weight.shape
    if (KH, KW) != (7, 7):
        raise ValueError("space_to_depth_stem_conv is specialized to "
                         "kernel 7, stride 2, pad 3; got kernel %s"
                         % ((KH, KW),))
    if H % 2 or W % 2:
        # odd H/W can't 2x2-space-to-depth; fall back to the plain stride-2
        # conv (same math, without the MXU channel-packing win) so
        # get_resnet(stem_s2d=True) accepts every size the plain stem does
        return jax.lax.conv_general_dilated(
            x, weight, window_strides=(2, 2), padding=((3, 3), (3, 3)),
            dimension_numbers=("NCHW", "OIHW", "NCHW"))
    # z[b, c*4 + py*2 + px, by, bx] = x[b, c, 2*by+py, 2*bx+px]
    z = x.reshape(B, C, H // 2, 2, W // 2, 2)
    z = z.transpose(0, 1, 3, 5, 2, 4).reshape(B, C * 4, H // 2, W // 2)
    # Wp[o, c*4+py*2+px, DB, DX] = W[o, c, 2*DB+py-1, 2*DX+px-1] (0 outside):
    # output row oy reads block rows oy-2 .. oy+1 (DB in 0..3), and original
    # row 2*oy-3+ky lands in block oy-2+DB phase py with ky = 2*DB+py-1
    ky = 2 * jnp.arange(4)[None, :] + jnp.arange(2)[:, None] - 1  # (py, DB)
    valid = ((ky >= 0) & (ky < 7)).astype(weight.dtype)
    kyc = jnp.clip(ky, 0, 6)
    wr = weight[:, :, kyc, :] * valid[None, None, :, :, None]  # (O,C,2,4,7)
    wrc = wr[:, :, :, :, kyc] * valid[None, None, None, None]  # (O,C,2,4,2,4)
    wp = wrc.transpose(0, 1, 2, 4, 3, 5).reshape(O, C * 4, 4, 4)
    return jax.lax.conv_general_dilated(
        z, wp, window_strides=(1, 1), padding=((2, 1), (2, 1)),
        dimension_numbers=("NCHW", "OIHW", "NCHW"))
