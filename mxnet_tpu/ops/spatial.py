"""Spatial transformer ops (ref: src/operator/spatial_transformer.cc,
src/operator/bilinear_sampler.cc, src/operator/grid_generator.cc)."""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ..base import register_op


@register_op("GridGenerator")
def GridGenerator(data, *, transform_type="affine", target_shape=None):
    """affine: data (N, 6) → sampling grid (N, 2, H, W) in [-1, 1] (x, y).
    warp: data (N, 2, H, W) pixel-space flow field added to the identity grid
    (ref: src/operator/grid_generator.cc both kTransFormType branches)."""
    if transform_type == "affine":
        if target_shape is None:
            raise ValueError(
                "GridGenerator(transform_type='affine') requires target_shape=(H, W)")
        H, W = target_shape
        theta = data.reshape(-1, 2, 3)
        ys = jnp.linspace(-1.0, 1.0, H)
        xs = jnp.linspace(-1.0, 1.0, W)
        gy, gx = jnp.meshgrid(ys, xs, indexing="ij")
        ones = jnp.ones_like(gx)
        base = jnp.stack([gx.ravel(), gy.ravel(), ones.ravel()])  # (3, HW)
        out = jnp.einsum("nij,jk->nik", theta, base)  # (N, 2, HW)
        return out.reshape(-1, 2, H, W)
    if transform_type == "warp":
        if data.ndim != 4 or data.shape[1] != 2:
            raise ValueError("warp flow must have shape (N, 2, H, W), got %s"
                             % (data.shape,))
        _, _, H, W = data.shape
        xs = jnp.arange(W, dtype=data.dtype)
        ys = jnp.arange(H, dtype=data.dtype)
        gy, gx = jnp.meshgrid(ys, xs, indexing="ij")
        x_s = (data[:, 0] + gx) * (2.0 / max(W - 1, 1)) - 1.0
        y_s = (data[:, 1] + gy) * (2.0 / max(H - 1, 1)) - 1.0
        return jnp.stack([x_s, y_s], axis=1)
    raise ValueError("unknown transform_type %r" % (transform_type,))


def _bilinear_zero(img, y, x):
    """Bilinear sample with zero padding outside the image: each of the four
    corner taps outside [0,H)x[0,W) contributes 0, matching the boundary
    handling in src/operator/bilinear_sampler.cc (between() guards)."""
    H, W = img.shape[1], img.shape[2]
    y0 = jnp.floor(y).astype(jnp.int32)
    x0 = jnp.floor(x).astype(jnp.int32)
    y1 = y0 + 1
    x1 = x0 + 1
    wy1 = y - y0
    wx1 = x - x0
    wy0 = 1.0 - wy1
    wx0 = 1.0 - wx1

    def tap(yi, xi):
        ok = (yi >= 0) & (yi < H) & (xi >= 0) & (xi < W)
        v = img[:, jnp.clip(yi, 0, H - 1), jnp.clip(xi, 0, W - 1)]
        return jnp.where(ok, v, 0.0)

    return (tap(y0, x0) * wy0 * wx0 + tap(y0, x1) * wy0 * wx1
            + tap(y1, x0) * wy1 * wx0 + tap(y1, x1) * wy1 * wx1)


@register_op("BilinearSampler")
def BilinearSampler(data, grid):
    """data (N, C, H, W); grid (N, 2, Ho, Wo) normalized [-1, 1] (x, y).
    Out-of-boundary samples are 0 (ref: src/operator/bilinear_sampler.cc)."""
    N, C, H, W = data.shape

    def one(img, g):
        gx = (g[0] + 1.0) * (W - 1) / 2.0
        gy = (g[1] + 1.0) * (H - 1) / 2.0
        return _bilinear_zero(img, gy, gx)  # (C, Ho, Wo)

    return jax.vmap(one)(data, grid)


@register_op("SpatialTransformer")
def SpatialTransformer(data, loc, *, target_shape=None, transform_type="affine",
                       sampler_type="bilinear"):
    """(ref: src/operator/spatial_transformer.cc) — affine STN."""
    grid = GridGenerator(loc, transform_type=transform_type,
                         target_shape=target_shape or data.shape[2:])
    return BilinearSampler(data, grid)
