"""Spatial transformer ops (ref: src/operator/spatial_transformer.cc,
src/operator/bilinear_sampler.cc, src/operator/grid_generator.cc)."""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ..base import register_op
from .roi import _bilinear


@register_op("GridGenerator")
def GridGenerator(data, *, transform_type="affine", target_shape=None):
    """affine: data (N, 6) → sampling grid (N, 2, H, W) in [-1, 1] coords."""
    H, W = target_shape
    theta = data.reshape(-1, 2, 3)
    ys = jnp.linspace(-1.0, 1.0, H)
    xs = jnp.linspace(-1.0, 1.0, W)
    gy, gx = jnp.meshgrid(ys, xs, indexing="ij")
    ones = jnp.ones_like(gx)
    base = jnp.stack([gx.ravel(), gy.ravel(), ones.ravel()])  # (3, HW)
    out = jnp.einsum("nij,jk->nik", theta, base)  # (N, 2, HW)
    return out.reshape(-1, 2, H, W)


@register_op("BilinearSampler")
def BilinearSampler(data, grid):
    """data (N, C, H, W); grid (N, 2, Ho, Wo) normalized [-1, 1] (x, y)."""
    N, C, H, W = data.shape

    def one(img, g):
        gx = (g[0] + 1.0) * (W - 1) / 2.0
        gy = (g[1] + 1.0) * (H - 1) / 2.0
        return _bilinear(img, gy, gx)  # (C, Ho, Wo)

    return jax.vmap(one)(data, grid)


@register_op("SpatialTransformer")
def SpatialTransformer(data, loc, *, target_shape=None, transform_type="affine",
                       sampler_type="bilinear"):
    """(ref: src/operator/spatial_transformer.cc) — affine STN."""
    grid = GridGenerator(loc, transform_type=transform_type,
                         target_shape=target_shape or data.shape[2:])
    return BilinearSampler(data, grid)
