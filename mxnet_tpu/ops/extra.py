"""Remaining MXNet op families: legacy CamelCase aliases, elemwise_* names,
regression output heads, and assorted tensor ops
(ref: src/operator/tensor/*.cc, src/operator/regression_output.cc,
src/operator/correlation.cc — TPU-native rewrites, everything jnp/lax).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ..base import register_op
from . import functional as F


# ---------------------------------------------------------- legacy aliases
# MXNet's original CamelCase symbol ops (ref: src/operator/tensor/matrix_op.cc
# registrations keep both names alive; so do we, one fn per name).

register_op("Reshape")(F.reshape)
register_op("Flatten")(F.flatten)
register_op("Cast")(F.cast)
register_op("Concat")(F.concat)
register_op("SwapAxis")(F.swapaxes)


@register_op("elemwise_add")
def elemwise_add(lhs, rhs):
    """Same-shape add (ref: elemwise_binary_op_basic.cc). Unlike
    broadcast_add, shapes must match exactly."""
    assert lhs.shape == rhs.shape, "elemwise_add requires equal shapes"
    return lhs + rhs


@register_op("elemwise_sub")
def elemwise_sub(lhs, rhs):
    assert lhs.shape == rhs.shape, "elemwise_sub requires equal shapes"
    return lhs - rhs


@register_op("elemwise_mul")
def elemwise_mul(lhs, rhs):
    assert lhs.shape == rhs.shape, "elemwise_mul requires equal shapes"
    return lhs * rhs


@register_op("elemwise_div")
def elemwise_div(lhs, rhs):
    assert lhs.shape == rhs.shape, "elemwise_div requires equal shapes"
    return lhs / rhs


@register_op("add_n")
def add_n(*args):
    """Sum of N arrays in one fused kernel (ref: elemwise_sum.cc,
    ElementWiseSum). XLA fuses the chain into a single loop."""
    out = args[0]
    for a in args[1:]:
        out = out + a
    return out


register_op("ElementWiseSum")(add_n)


# ------------------------------------------------------------- tensor ops

@register_op("argmax_channel")
def argmax_channel(x):
    """argmax over axis 1, squeezed (ref: broadcast_reduce_op_index.cc)."""
    return jnp.argmax(x, axis=1).astype(jnp.float32)


@register_op("batch_take")
def batch_take(x, indices):
    """out[i] = x[i, indices[i]] (ref: indexing_op.cc batch_take)."""
    idx = indices.astype(jnp.int32)
    return jnp.take_along_axis(x, idx[:, None], axis=1)[:, 0]


@register_op("broadcast_axis")
def broadcast_axis(x, *, axis, size):
    """Broadcast size-1 axes to the requested sizes
    (ref: broadcast_reduce_op_value.cc)."""
    axes = (axis,) if isinstance(axis, int) else tuple(axis)
    sizes = (size,) if isinstance(size, int) else tuple(size)
    shape = list(x.shape)
    for ax, s in zip(axes, sizes):
        if shape[ax] != 1:
            raise ValueError("broadcast_axis: axis %d has size %d != 1" % (ax, shape[ax]))
        shape[ax] = s
    return jnp.broadcast_to(x, tuple(shape))


register_op("broadcast_axes")(broadcast_axis)


@register_op("hard_sigmoid")
def hard_sigmoid(x, *, alpha=0.2, beta=0.5):
    """(ref: mshadow_op.h hard_sigmoid)"""
    return jnp.clip(alpha * x + beta, 0.0, 1.0)


@register_op("reshape_like")
def reshape_like(lhs, rhs):
    return jnp.reshape(lhs, rhs.shape)


@register_op("moments", n_outputs=2)
def moments(x, *, axes=None, keepdims=False):
    """Returns (mean, var) in one pass (ref: moments.cc)."""
    ax = tuple(axes) if axes is not None else None
    return (jnp.mean(x, axis=ax, keepdims=keepdims),
            jnp.var(x, axis=ax, keepdims=keepdims))


@register_op("unravel_index", nondiff=True)
def unravel_index(indices, *, shape):
    """Flat → multi index, stacked on a leading axis (ref: ravel.cc)."""
    coords = jnp.unravel_index(indices.astype(jnp.int32), tuple(shape))
    return jnp.stack(coords, axis=0)


@register_op("ravel_multi_index", nondiff=True)
def ravel_multi_index(coords, *, shape):
    """Multi (leading axis) → flat index (ref: ravel.cc)."""
    shape = tuple(shape)
    strides = []
    acc = 1
    for s in reversed(shape):
        strides.append(acc)
        acc *= s
    strides = jnp.asarray(list(reversed(strides)), coords.dtype)
    return jnp.tensordot(strides, coords, axes=1)


@register_op("SoftmaxActivation")
def SoftmaxActivation(x, *, mode="instance"):
    """(ref: softmax_activation.cc): mode='instance' softmaxes the trailing
    flattened dims per sample; 'channel' softmaxes axis 1."""
    if mode == "channel":
        return jax.nn.softmax(x, axis=1)
    flat = x.reshape(x.shape[0], -1)
    return jax.nn.softmax(flat, axis=-1).reshape(x.shape)


@register_op("shuffle", needs_rng=True, nondiff=True)
def shuffle(x, *, key=None):
    """Random permutation along axis 0 (ref: shuffle_op.cc)."""
    return jax.random.permutation(key, x, axis=0)


@register_op("relu6")
def relu6(x):
    return jnp.clip(x, 0.0, 6.0)


# ------------------------------------------------- training output heads
# MXNet's *Output ops are identity-like forward with a HARD-CODED backward:
# d(data) = (out - label) * grad_scale, regardless of any loss applied on top
# (ref: src/operator/regression_output-inl.h). jax.custom_vjp reproduces that
# contract exactly.

def _regression_output(transform, grad_fn, opname):
    @jax.custom_vjp
    def op(data, label, grad_scale=1.0):
        return transform(data)

    def fwd(data, label, grad_scale=1.0):
        out = transform(data)
        return out, (out, label, grad_scale)

    def bwd(res, g):
        out, label, grad_scale = res
        n = label.size // (label.shape[0] if label.ndim else 1) or 1
        dgrad = grad_fn(out, label.reshape(out.shape)) * grad_scale / n
        return dgrad.astype(out.dtype), jnp.zeros_like(label), None

    op.defvjp(fwd, bwd)

    def wrapped(data, label, *, grad_scale=1.0):
        return op(data, label, grad_scale)

    wrapped.__name__ = opname
    return wrapped


register_op("LinearRegressionOutput")(_regression_output(
    lambda d: d, lambda out, y: out - y, "LinearRegressionOutput"))

register_op("MAERegressionOutput")(_regression_output(
    lambda d: d, lambda out, y: jnp.sign(out - y), "MAERegressionOutput"))

register_op("LogisticRegressionOutput")(_regression_output(
    jax.nn.sigmoid, lambda out, y: out - y, "LogisticRegressionOutput"))


@register_op("SVMOutput")
def SVMOutput(data, label, *, margin=1.0, regularization_coefficient=1.0,
              use_linear=False):
    """Forward is identity; the SVM hinge gradient lives in backward
    (ref: src/operator/svm_output-inl.h)."""
    @jax.custom_vjp
    def op(d, y):
        return d

    def fwd(d, y):
        return d, (d, y)

    def bwd(res, g):
        d, y = res
        yi = y.astype(jnp.int32)
        onehot = jax.nn.one_hot(yi, d.shape[1], dtype=d.dtype)
        signed = jnp.where(onehot > 0, -1.0, 1.0)
        viol = (margin + signed * d) > 0
        if use_linear:
            grad = jnp.where(viol, signed, 0.0)
        else:  # squared hinge
            grad = jnp.where(viol, 2.0 * (margin + signed * d) * signed, 0.0)
        return (regularization_coefficient * grad).astype(d.dtype), jnp.zeros_like(y)

    op.defvjp(fwd, bwd)
    return op(data, label)


@register_op("MakeLoss")
def MakeLoss(data, *, grad_scale=1.0, normalization="null", valid_thresh=0.0):
    """Turn any symbol into a loss head: forward is identity, backward seeds
    the gradient with grad_scale (ref: src/operator/make_loss.cc).
    normalization='valid' divides by the count of elements > valid_thresh."""
    @jax.custom_vjp
    def op(d):
        return d

    def fwd(d):
        return d, d

    def bwd(d, g):
        scale = jnp.asarray(grad_scale, d.dtype)
        if normalization == "batch":
            scale = scale / d.shape[0]
        elif normalization == "valid":
            valid = jnp.sum((d > valid_thresh).astype(d.dtype))
            scale = scale / jnp.maximum(valid, 1)
        return (jnp.broadcast_to(scale, d.shape).astype(d.dtype),)

    op.defvjp(fwd, bwd)
    return op(data)


@register_op("Correlation")
def Correlation(f1, f2, *, kernel_size=1, max_displacement=4, stride1=1,
                stride2=1, pad_size=4, is_multiply=True):
    """FlowNet-style correlation of two feature maps
    (ref: src/operator/correlation.cu). TPU-native: every displacement is a
    shifted elementwise product reduced over channels, then a kernel_size²
    mean filter (reduce_window) for patch correlation — a static double loop
    over (2d+1)² displacements that XLA fuses; no explicit patch extraction.
    Output: (N, D*D, ceil(H/stride1), ceil(W/stride1))."""
    n, c, h, w = f1.shape
    d = max_displacement // stride2
    p = int(pad_size)
    shift_max = d * stride2
    if p < shift_max:
        raise ValueError("pad_size %d < max shift %d" % (p, shift_max))
    f2p = jnp.pad(f2, ((0, 0), (0, 0), (p, p), (p, p)))
    k = int(kernel_size)
    outs = []
    for dy in range(-d, d + 1):
        for dx in range(-d, d + 1):
            oy, ox = (dy * stride2 + p), (dx * stride2 + p)
            shifted = jax.lax.dynamic_slice(f2p, (0, 0, oy, ox), (n, c, h, w))
            if is_multiply:
                corr = jnp.mean(f1 * shifted, axis=1)
            else:
                corr = jnp.mean(jnp.abs(f1 - shifted), axis=1)
            if k > 1:
                # patch correlation: k×k mean over the product map, SAME pad
                corr = jax.lax.reduce_window(
                    corr, 0.0, jax.lax.add, (1, k, k), (1, 1, 1), "SAME") / (k * k)
            outs.append(corr)
    out = jnp.stack(outs, axis=1)
    if stride1 > 1:
        out = out[:, :, ::stride1, ::stride1]
    return out


@register_op("IdentityAttachKLSparseReg")
def IdentityAttachKLSparseReg(data, *, sparseness_target=0.1, penalty=0.001,
                              momentum=0.9):
    """Identity forward; backward adds the KL sparsity penalty gradient
    penalty * (-ρ̂/ρ + (1-ρ̂)/(1-ρ)) where ρ is the per-unit batch-mean
    activation (ref: src/operator/identity_attach_KL_sparse_reg.cc — the
    reference keeps a momentum-smoothed ρ across batches; the functional
    form uses the current batch's ρ, the stateless jit-safe equivalent)."""
    t = sparseness_target

    @jax.custom_vjp
    def op(d):
        return d

    def fwd(d):
        return d, d

    def bwd(d, g):
        rho = jnp.clip(jnp.mean(d, axis=0, keepdims=True), 1e-6, 1 - 1e-6)
        kl_grad = penalty * (-t / rho + (1 - t) / (1 - rho))
        return ((g + kl_grad.astype(d.dtype)),)

    op.defvjp(fwd, bwd)
    return op(data)


# ------------------------------------------------- round-2 parity additions

@register_op("identity")
def identity(x):
    """(ref: elemwise_unary_op_basic.cc _copy/identity)."""
    return x


@register_op("softmin")
def softmin(x, *, axis=-1, temperature=None):
    """softmax of the negated input (ref: softmax.cc softmin)."""
    if temperature is not None:
        x = x / temperature
    return jax.nn.softmax(-x, axis=axis)


# legacy name for split (ref: slice_channel.cc — SliceChannel predates split)
register_op("SliceChannel")(F.split)


@register_op("choose_element_0index")
def choose_element_0index(lhs, rhs, *, axis=1, keepdims=False):
    """Pick lhs[i, rhs[i]] along axis (ref: broadcast_reduce_op_index.cc —
    the historical name of pick)."""
    return F.pick(lhs, rhs, axis=axis, keepdims=keepdims)


@register_op("fill_element_0index")
def fill_element_0index(lhs, mhs, rhs):
    """out[i, rhs[i]] = mhs[i], other entries copied from lhs
    (ref: broadcast_reduce_op_index.cc fill_element_0index)."""
    idx = rhs.astype(jnp.int32)[:, None]
    vals = mhs[:, None].astype(lhs.dtype)
    return jnp.put_along_axis(lhs, idx, vals, axis=1, inplace=False)


@register_op("Crop")
def Crop(*args, offset=(0, 0), h_w=(0, 0), center_crop=False, num_args=None):
    """Legacy spatial crop of NCHW maps (ref: src/operator/crop.cc). With two
    inputs, crops the first to the second's H×W; otherwise to ``h_w``.
    ``center_crop`` centers the window, else ``offset`` anchors it."""
    data = args[0]
    H, W = data.shape[2], data.shape[3]
    if len(args) == 2:
        th, tw = args[1].shape[2], args[1].shape[3]
    else:
        th, tw = h_w
    if th <= 0 or tw <= 0 or th > H or tw > W:
        raise ValueError("invalid crop size (%d, %d) for input %s"
                         % (th, tw, (H, W)))
    if center_crop:
        y0, x0 = (H - th) // 2, (W - tw) // 2
    else:
        y0, x0 = offset
    if y0 < 0 or x0 < 0 or y0 + th > H or x0 + tw > W:
        raise ValueError("crop window out of bounds")
    return data[:, :, y0:y0 + th, x0:x0 + tw]


def _im2col_patches(data, kernel, stride, dilate, pad):
    n, c, h, w = data.shape
    return jax.lax.conv_general_dilated_patches(
        data, filter_shape=tuple(kernel), window_strides=tuple(stride),
        padding=[(p, p) for p in pad], rhs_dilation=tuple(dilate),
        dimension_numbers=("NCHW", "OIHW", "NCHW"))


@register_op("im2col")
def im2col(data, *, kernel, stride=(1, 1), dilate=(1, 1), pad=(0, 0)):
    """Sliding-window patch extraction: (N, C, H, W) → (N, C·kh·kw, L)
    (ref: src/operator/nn/im2col.h). On TPU this is one XLA patches op —
    the conv lowering MXNet hand-writes in CUDA."""
    kernel, stride = F._pair(kernel, 2), F._pair(stride, 2)
    dilate, pad = F._pair(dilate, 2), F._pair(pad, 2)
    cols = _im2col_patches(data, kernel, stride, dilate, pad)
    n = cols.shape[0]
    return cols.reshape(n, cols.shape[1], -1)


@register_op("col2im")
def col2im(data, *, output_size, kernel, stride=(1, 1), dilate=(1, 1),
           pad=(0, 0)):
    """Adjoint of im2col: overlap-add patches back to (N, C, H, W)
    (ref: src/operator/nn/im2col.h col2im). Implemented as the exact VJP of
    the im2col patches op, which IS the overlap-add scatter."""
    kernel, stride = F._pair(kernel, 2), F._pair(stride, 2)
    dilate, pad = F._pair(dilate, 2), F._pair(pad, 2)
    oh, ow = output_size
    n = data.shape[0]
    ckk = data.shape[1]
    c = ckk // (kernel[0] * kernel[1])
    ref = jnp.zeros((n, c, oh, ow), data.dtype)
    primal, vjp = jax.vjp(
        lambda x: _im2col_patches(x, kernel, stride, dilate, pad), ref)
    (out,) = vjp(data.reshape(primal.shape))
    return out


# ---------------------------------------------------------- ONNX-parity ops
# Registry ops backing the ONNX importer's opset breadth (each with an
# exporter in onnx/export.py so they round-trip). jnp-native, fully static.

@register_op("einsum")
def einsum(*args, equation):
    """ONNX Einsum / np.einsum (ref: onnx.ai Einsum; upstream
    mxnet.np.einsum). Variadic inputs; the subscripts string is static."""
    return jnp.einsum(equation, *args)


@register_op("take_along_axis")
def take_along_axis(a, indices, *, axis):
    """ONNX GatherElements semantics: pick one element per output position
    along ``axis`` (np.take_along_axis)."""
    return jnp.take_along_axis(a, indices.astype(jnp.int32), axis=int(axis))


@register_op("scatter_elements")
def scatter_elements(data, indices, updates, *, axis=0, reduction="none"):
    """ONNX ScatterElements (and the deprecated Scatter): write ``updates``
    at per-element positions along ``axis``. reduction none/add/mul map to
    .at[].set/add/multiply — XLA scatter either way."""
    idx = indices.astype(jnp.int32)
    axis = int(axis)
    # build full coordinate grids: every dim is its own index except `axis`
    coords = list(jnp.meshgrid(*[jnp.arange(s) for s in idx.shape],
                               indexing="ij"))
    coords[axis] = idx
    at = data.at[tuple(coords)]
    if reduction == "add":
        return at.add(updates)
    if reduction == "mul":
        return at.multiply(updates)
    return at.set(updates)


@register_op("trilu")
def trilu(x, *, k=0, upper=True):
    """ONNX Trilu: upper/lower triangle of the last two dims."""
    return jnp.triu(x, k=int(k)) if upper else jnp.tril(x, k=int(k))


@register_op("celu")
def celu(x, *, alpha=1.0):
    """ONNX Celu: max(0, x) + min(0, alpha*(exp(x/alpha) - 1))."""
    return jax.nn.celu(x, alpha=float(alpha))


@register_op("hardswish")
def hardswish(x):
    """ONNX HardSwish (opset 14): x * clip(x/6 + 0.5, 0, 1)."""
    return x * jnp.clip(x / 6.0 + 0.5, 0.0, 1.0)


@register_op("thresholded_relu")
def thresholded_relu(x, *, alpha=1.0):
    """ONNX ThresholdedRelu: x if x > alpha else 0."""
    return jnp.where(x > alpha, x, jnp.zeros_like(x))


@register_op("logsumexp")
def logsumexp(data, *, axis=None, keepdims=False):
    """ONNX ReduceLogSumExp, numerically stable (max-shifted) — a naive
    log(sum(exp)) decomposition overflows in fp16/bf16."""
    ax = axis if axis is None or isinstance(axis, tuple) else (int(axis),)
    return jax.nn.logsumexp(data, axis=ax, keepdims=bool(keepdims))
