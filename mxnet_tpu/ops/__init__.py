"""Functional op library; see functional.py for the registry."""
from . import functional  # noqa: F401  (populates OP_REGISTRY)
from . import detection  # noqa: F401
from . import control_flow  # noqa: F401
from . import attention  # noqa: F401
from . import ctc  # noqa: F401
from . import roi  # noqa: F401
from . import rcnn  # noqa: F401
from . import spatial  # noqa: F401
from . import extra  # noqa: F401
from . import legacy_ops  # noqa: F401
from .functional import *  # noqa: F401,F403
