"""Functional op library; see functional.py for the registry."""
from . import functional  # noqa: F401  (populates OP_REGISTRY)
from . import detection  # noqa: F401
from . import control_flow  # noqa: F401
from . import attention  # noqa: F401
from . import ctc  # noqa: F401
from . import roi  # noqa: F401
from . import rcnn  # noqa: F401
from . import spatial  # noqa: F401
from . import extra  # noqa: F401
from . import legacy_ops  # noqa: F401
from . import contrib_extra  # noqa: F401
from .functional import *  # noqa: F401,F403

# Upstream exposes every CamelCase op under a snake_case name too
# (python/mxnet/ndarray/register.py generates both); mirror that by
# aliasing registry entries (same OpDef, two names) before the nd/sym
# namespaces generate their wrappers.
import re as _re
from ..base import OP_REGISTRY as _R


def _snake(name):
    s = _re.sub(r"([A-Z]+)([A-Z][a-z])", r"\1_\2", name)
    s = _re.sub(r"([a-z0-9])([A-Z])", r"\1_\2", s)
    return s.lower()


for _n in list(_R):
    if _n[:1].isupper():
        _s = _snake(_n)
        if _s not in _R:
            _R[_s] = _R[_n]
del _n
