"""Detection ops: anchors, IoU, NMS, multibox target/detection.

TPU-native equivalents of MXNet/GluonCV contrib detection ops (ref:
src/operator/contrib/bounding_box.cc, multibox_prior.cc, multibox_target.cc,
multibox_detection.cc). The CUDA kernels are replaced with jittable XLA code:
NMS is the classic O(N^2)-IoU + fori_loop greedy suppression, which XLA
vectorizes on the VPU — fixed shapes, no dynamic output sizes (suppressed boxes
are masked with score -1, matching MXNet's convention).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from ..base import register_op


def _iou_corner(a, b):
    """a: (..., M, 4), b: (..., N, 4) corner format -> (..., M, N)."""
    tl = jnp.maximum(a[..., :, None, :2], b[..., None, :, :2])
    br = jnp.minimum(a[..., :, None, 2:], b[..., None, :, 2:])
    wh = jnp.clip(br - tl, 0.0, None)
    inter = wh[..., 0] * wh[..., 1]
    area_a = jnp.clip(a[..., 2] - a[..., 0], 0, None) * jnp.clip(a[..., 3] - a[..., 1], 0, None)
    area_b = jnp.clip(b[..., 2] - b[..., 0], 0, None) * jnp.clip(b[..., 3] - b[..., 1], 0, None)
    union = area_a[..., :, None] + area_b[..., None, :] - inter
    return inter / jnp.maximum(union, 1e-12)


@register_op("box_iou")
def box_iou(lhs, rhs, *, format="corner"):
    if format == "center":
        lhs = _center_to_corner(lhs)
        rhs = _center_to_corner(rhs)
    return _iou_corner(lhs, rhs)


def _center_to_corner(b):
    xy, wh = b[..., :2], b[..., 2:]
    return jnp.concatenate([xy - wh / 2, xy + wh / 2], axis=-1)


def _nms_single(boxes, scores, ids, overlap_thresh, valid_thresh, force_suppress):
    n = scores.shape[0]
    order = jnp.argsort(-scores)
    b = boxes[order]
    s = scores[order]
    c = ids[order]
    iou = _iou_corner(b, b)
    same_cls = (c[:, None] == c[None, :]) | force_suppress
    valid = s > valid_thresh

    def body(i, keep):
        sup = (iou[i] > overlap_thresh) & same_cls[i] & (jnp.arange(n) > i)
        return jnp.where(keep[i], keep & ~sup, keep)

    keep = lax.fori_loop(0, n, body, valid)
    s = jnp.where(keep, s, -1.0)
    inv = jnp.argsort(order)
    return b[inv], s[inv], c[inv]


@register_op("box_nms", nondiff=True)
def box_nms(data, *, overlap_thresh=0.5, valid_thresh=0.0, topk=-1,
            coord_start=2, score_index=1, id_index=0, force_suppress=False,
            in_format="corner", out_format="corner"):
    """data: (B, N, 6) [id, score, x1,y1,x2,y2] -> same shape, suppressed
    entries get score -1 (ref: src/operator/contrib/bounding_box.cc:BoxNMS)."""
    squeeze = data.ndim == 2
    if squeeze:
        data = data[None]

    def one(d):
        boxes = lax.dynamic_slice_in_dim(d, coord_start, 4, axis=1)
        if in_format == "center":
            boxes = _center_to_corner(boxes)
        scores = d[:, score_index]
        ids = d[:, id_index] if id_index >= 0 else jnp.zeros_like(scores)
        b, s, c = _nms_single(boxes, scores, ids, overlap_thresh, valid_thresh,
                              force_suppress or id_index < 0)
        out = d.at[:, score_index].set(s)
        return out

    out = jax.vmap(one)(data)
    return out[0] if squeeze else out


@register_op("multibox_prior", nondiff=True)
def multibox_prior(data, *, sizes=(1.0,), ratios=(1.0,), steps=(-1.0, -1.0),
                   offsets=(0.5, 0.5), clip=False):
    """Anchor boxes per feature-map pixel, corner format, normalized to [0,1]
    (ref: src/operator/contrib/multibox_prior.cc). Output (1, H*W*A, 4)."""
    h, w = data.shape[2], data.shape[3]
    step_y = steps[0] if steps[0] > 0 else 1.0 / h
    step_x = steps[1] if steps[1] > 0 else 1.0 / w
    cy = (jnp.arange(h) + offsets[0]) * step_y
    cx = (jnp.arange(w) + offsets[1]) * step_x
    cy, cx = jnp.meshgrid(cy, cx, indexing="ij")
    centers = jnp.stack([cx, cy], axis=-1).reshape(-1, 2)  # (HW, 2)
    whs = []
    for i, s in enumerate(sizes):
        r = ratios[0] if len(ratios) else 1.0
        whs.append((s * jnp.sqrt(r), s / jnp.sqrt(r)))
    for r in ratios[1:]:
        s = sizes[0]
        whs.append((s * jnp.sqrt(r), s / jnp.sqrt(r)))
    wh = jnp.array(whs)  # (A, 2)
    a = wh.shape[0]
    ctr = jnp.repeat(centers[:, None, :], a, axis=1)  # (HW, A, 2)
    half = wh[None, :, :] / 2
    boxes = jnp.concatenate([ctr - half, ctr + half], axis=-1).reshape(1, -1, 4)
    if clip:
        boxes = jnp.clip(boxes, 0.0, 1.0)
    return boxes


@register_op("multibox_target", n_outputs=3)
def multibox_target(anchors, labels, cls_preds, *, overlap_threshold=0.5,
                    ignore_label=-1.0, negative_mining_ratio=3.0,
                    negative_mining_thresh=0.5, variances=(0.1, 0.1, 0.2, 0.2)):
    """Match anchors to GT, encode regression targets
    (ref: src/operator/contrib/multibox_target.cc).
    anchors (1, N, 4) corner; labels (B, M, 5) [cls, x1,y1,x2,y2] (cls<0 = pad);
    cls_preds (B, num_cls+1, N).
    Returns (box_target (B, N*4), box_mask (B, N*4), cls_target (B, N))."""
    anc = anchors[0]  # (N, 4)

    def one(lab, cls_pred):
        gt_valid = lab[:, 0] >= 0
        gt_boxes = lab[:, 1:5]
        iou = _iou_corner(anc, gt_boxes)  # (N, M)
        iou = jnp.where(gt_valid[None, :], iou, -1.0)
        best_gt = jnp.argmax(iou, axis=1)
        best_iou = jnp.max(iou, axis=1)
        # force-match: each VALID gt's best anchor is positive. Invalid
        # (padding) rows all argmax to index 0 (their iou column is -1
        # everywhere) — scattering them directly would collide with and
        # overwrite a valid gt's force-match, so they're routed to a
        # dropped extra row instead.
        N = anc.shape[0]
        best_anchor_per_gt = jnp.argmax(iou, axis=0)  # (M,)
        idx = jnp.where(gt_valid, best_anchor_per_gt, N)
        forced = jnp.zeros(N + 1, bool).at[idx].set(True)[:N]
        gt_for_forced = jnp.zeros(N + 1, jnp.int32).at[idx].set(
            jnp.arange(lab.shape[0], dtype=jnp.int32))[:N]
        pos = (best_iou >= overlap_threshold) | forced
        matched_gt = jnp.where(forced, gt_for_forced, best_gt.astype(jnp.int32))
        mb = gt_boxes[matched_gt]  # (N, 4)
        # encode: center-offset with variances
        acx = (anc[:, 0] + anc[:, 2]) / 2
        acy = (anc[:, 1] + anc[:, 3]) / 2
        aw = jnp.maximum(anc[:, 2] - anc[:, 0], 1e-8)
        ah = jnp.maximum(anc[:, 3] - anc[:, 1], 1e-8)
        gcx = (mb[:, 0] + mb[:, 2]) / 2
        gcy = (mb[:, 1] + mb[:, 3]) / 2
        gw = jnp.maximum(mb[:, 2] - mb[:, 0], 1e-8)
        gh = jnp.maximum(mb[:, 3] - mb[:, 1], 1e-8)
        tx = (gcx - acx) / aw / variances[0]
        ty = (gcy - acy) / ah / variances[1]
        tw = jnp.log(gw / aw) / variances[2]
        th = jnp.log(gh / ah) / variances[3]
        bt = jnp.stack([tx, ty, tw, th], axis=1)
        bt = jnp.where(pos[:, None], bt, 0.0)
        bm = jnp.broadcast_to(pos[:, None], bt.shape).astype(bt.dtype)
        cls_t = jnp.where(pos, lab[matched_gt, 0] + 1.0, 0.0)
        # hard negative mining: keep top (ratio * npos) negatives by max prob of non-bg
        npos = jnp.sum(pos)
        neg_score = jnp.max(cls_pred[1:], axis=0)  # (N,)
        neg_score = jnp.where(pos, -jnp.inf, neg_score)
        k = jnp.minimum(npos * negative_mining_ratio, anc.shape[0] - 1).astype(jnp.int32)
        order = jnp.argsort(-neg_score)
        rank = jnp.argsort(order)
        keep_neg = rank < k
        cls_t = jnp.where(pos | keep_neg, cls_t, ignore_label)
        return bt.reshape(-1), bm.reshape(-1), cls_t

    return jax.vmap(one)(labels, cls_preds)


@register_op("multibox_detection", nondiff=True)
def multibox_detection(cls_prob, loc_pred, anchors, *, clip=True, threshold=0.01,
                       nms_threshold=0.5, force_suppress=False, nms_topk=400,
                       variances=(0.1, 0.1, 0.2, 0.2)):
    """Decode predictions + per-class NMS → (B, N, 6) [id, score, x1,y1,x2,y2]
    (ref: src/operator/contrib/multibox_detection.cc)."""
    anc = anchors[0]
    acx = (anc[:, 0] + anc[:, 2]) / 2
    acy = (anc[:, 1] + anc[:, 3]) / 2
    aw = anc[:, 2] - anc[:, 0]
    ah = anc[:, 3] - anc[:, 1]

    def one(cp, lp):
        lp = lp.reshape(-1, 4)
        cx = lp[:, 0] * variances[0] * aw + acx
        cy = lp[:, 1] * variances[1] * ah + acy
        w = jnp.exp(lp[:, 2] * variances[2]) * aw
        h = jnp.exp(lp[:, 3] * variances[3]) * ah
        boxes = jnp.stack([cx - w / 2, cy - h / 2, cx + w / 2, cy + h / 2], axis=1)
        if clip:
            boxes = jnp.clip(boxes, 0.0, 1.0)
        scores = jnp.max(cp[1:], axis=0)
        ids = jnp.argmax(cp[1:], axis=0).astype(jnp.float32)
        ids = jnp.where(scores > threshold, ids, -1.0)
        scores = jnp.where(scores > threshold, scores, -1.0)
        det = jnp.concatenate([ids[:, None], scores[:, None], boxes], axis=1)
        return box_nms(det, overlap_thresh=nms_threshold, valid_thresh=threshold,
                       force_suppress=force_suppress)

    return jax.vmap(one)(cls_prob, loc_pred)


# ------------------------------------------------------------------- YOLOv3

def _yolo_grid(size, strides, anchors):
    """Static per-slot metadata for YOLOv3's concatenated prediction list.

    Slot order matches the model's head concat: scales in ``strides`` order,
    each scale row-major over its grid with 3 anchors per cell. Returns
    numpy (N,2) cell xy, (N,2) anchor wh (pixels), (N,) stride."""
    import numpy as np
    xs, whs, sts = [], [], []
    a = np.asarray(anchors, np.float32).reshape(len(strides), 3, 2)
    for si, s in enumerate(strides):
        g = size // s
        jj, ii = np.meshgrid(np.arange(g), np.arange(g), indexing="ij")
        cell = np.stack([ii, jj], -1).reshape(-1, 1, 2)  # (G*G, 1, 2) [x, y]
        cell = np.broadcast_to(cell, (g * g, 3, 2)).reshape(-1, 2)
        xs.append(cell.astype(np.float32))
        whs.append(np.broadcast_to(a[si][None], (g * g, 3, 2)).reshape(-1, 2))
        sts.append(np.full((g * g * 3,), s, np.float32))
    return (np.concatenate(xs), np.concatenate(whs).astype(np.float32),
            np.concatenate(sts))


@register_op("yolo3_target", n_outputs=5, nondiff=True)
def yolo3_target(labels, *, size, strides, anchors):
    """YOLOv3 training-target assignment, fully on device (ref: gluon-cv
    gluoncv/model_zoo/yolo/yolo_target.py:YOLOV3PrefetchTargetGenerator —
    there a CPU prefetch pass, here a jittable static-shape op).

    Each valid gt is assigned to the anchor (of 9) with best wh-IoU, at the
    grid cell containing its center on that anchor's scale. Collisions keep
    the LAST gt (upstream's overwrite semantics) via an argmax-priority
    one-hot scatter — no dynamic indexing.

    labels (B, M, 5) rows [cls, x1, y1, x2, y2] normalized, cls<0 = pad.
    Returns obj_t (B,N,1), center_t (B,N,2) in-cell offsets, scale_t (B,N,2)
    log(gt/anchor), weight (B,N,1) = 2 - area, cls_t (B,N) (-1 = no gt)."""
    cell, awh, stride = (jnp.asarray(v) for v in
                         _yolo_grid(size, strides, anchors))
    N = cell.shape[0]
    all_a = jnp.asarray(anchors, jnp.float32).reshape(-1, 2)  # (9, 2) slotted
    n_per = 3
    # flat slot offset of each scale block
    import numpy as np
    offs = np.cumsum([0] + [(size // s) ** 2 * 3 for s in strides])[:-1]
    offs = jnp.asarray(offs, jnp.int32)
    g_per = jnp.asarray([size // s for s in strides], jnp.int32)
    st_per = jnp.asarray(strides, jnp.float32)

    def one(lab):
        valid = lab[:, 0] >= 0
        wh = (lab[:, 3:5] - lab[:, 1:3]) * size          # (M, 2) pixels
        ctr = (lab[:, 1:3] + lab[:, 3:5]) / 2 * size     # (M, 2) pixels
        inter = (jnp.minimum(wh[:, None, 0], all_a[None, :, 0])
                 * jnp.minimum(wh[:, None, 1], all_a[None, :, 1]))
        union = (wh[:, 0:1] * wh[:, 1:2] + all_a[None, :, 0] * all_a[None, :, 1]
                 - inter)
        iou = inter / jnp.maximum(union, 1e-12)          # (M, 9)
        best = jnp.argmax(iou, axis=1).astype(jnp.int32)  # (M,)
        sidx = best // n_per
        st = st_per[sidx]
        g = g_per[sidx]
        gij = jnp.floor(ctr / st[:, None]).astype(jnp.int32)
        gij = jnp.clip(gij, 0, (g - 1)[:, None])
        flat = offs[sidx] + (gij[:, 1] * g + gij[:, 0]) * n_per + best % n_per
        flat = jnp.where(valid, flat, N)                 # pads → dropped row
        # per-gt targets
        t_ctr = ctr / st[:, None] - gij                  # in-cell offset
        t_wh = jnp.log(jnp.maximum(wh, 1e-8) / all_a[best])
        t_wt = 2.0 - (wh[:, 0] * wh[:, 1]) / (size * size)
        # LAST-gt-wins scatter: one-hot weighted by gt index, argmax per slot
        M = lab.shape[0]
        E = (flat[:, None] == jnp.arange(N)[None, :])    # (M, N)
        winner = jnp.argmax(E * (jnp.arange(M)[:, None] + 1), axis=0)
        has = jnp.any(E, axis=0)
        obj = has.astype(jnp.float32)[:, None]
        ctr_t = jnp.where(has[:, None], t_ctr[winner], 0.0)
        wh_t = jnp.where(has[:, None], t_wh[winner], 0.0)
        wt = jnp.where(has[:, None], t_wt[winner, None], 0.0)
        cls_t = jnp.where(has, lab[winner, 0], -1.0)
        return obj, ctr_t, wh_t, wt, cls_t

    return tuple(jax.vmap(one)(labels))


@register_op("yolo3_decode", n_outputs=3)
def yolo3_decode(raw, *, size, strides, anchors):
    """Decode raw YOLOv3 head output (B, N, 5+C) → corner boxes (B, N, 4)
    normalized to [0,1], objectness (B, N, 1), class probs (B, N, C)
    (ref: gluoncv yolo3 YOLOOutputV3 — grid offsets + anchor exp there are
    baked into the head; here one decode op shared by loss and detect)."""
    cell, awh, stride = (jnp.asarray(v) for v in
                         _yolo_grid(size, strides, anchors))
    ctr = (jax.nn.sigmoid(raw[..., 0:2]) + cell) * stride[:, None] / size
    wh = jnp.exp(jnp.clip(raw[..., 2:4], -10.0, 10.0)) * awh / size
    boxes = jnp.concatenate([ctr - wh / 2, ctr + wh / 2], axis=-1)
    obj = jax.nn.sigmoid(raw[..., 4:5])
    cls = jax.nn.sigmoid(raw[..., 5:])
    return jnp.clip(boxes, 0.0, 1.0), obj, cls


# --------------------------------------------------------------- ONNX interop

@register_op("_onnx_nms", nondiff=True)
def onnx_nms(boxes, scores, *, max_output_boxes_per_class=0,
             iou_threshold=0.0, score_threshold=None, center_point_box=0):
    """ONNX NonMaxSuppression semantics on TPU: fixed-shape output.

    boxes (B, N, 4) corner format, scores (B, C, N) → selected indices
    (B*C*K, 3) rows [batch, class, box], K = min(max_output, N). Invalid
    rows are padded with -1 — the TPU-native encoding of ONNX's dynamic M
    (consumers drop pad rows; see _onnx_scatter_nd)."""
    if center_point_box:
        boxes = _center_to_corner(boxes)
    B, N, _ = boxes.shape
    C = scores.shape[1]
    K = int(min(max_output_boxes_per_class or N, N))
    vt = -jnp.inf if score_threshold is None else float(score_threshold)

    def one(bx, sc):  # (N, 4), (N,) → (K,) selected original indices or -1
        order = jnp.argsort(-sc)
        b2, s2 = bx[order], sc[order]
        iou = _iou_corner(b2, b2)
        valid = s2 > vt

        def body(i, keep):
            sup = (iou[i] > iou_threshold) & (jnp.arange(N) > i)
            return jnp.where(keep[i], keep & ~sup, keep)

        keep = lax.fori_loop(0, N, body, valid)
        rank = jnp.cumsum(keep) - 1
        take = keep & (rank < K)
        sel = jnp.where(take, order, -1)
        comp = jnp.argsort(~take, stable=True)  # taken rows first, in order
        return sel[comp][:K]

    sel = jax.vmap(jax.vmap(one, in_axes=(None, 0)))(boxes, scores)  # (B,C,K)
    bi = jnp.broadcast_to(jnp.arange(B)[:, None, None], (B, C, K))
    ci = jnp.broadcast_to(jnp.arange(C)[None, :, None], (B, C, K))
    rows = jnp.stack([bi, ci, sel], axis=-1).reshape(B * C * K, 3)
    return jnp.where(rows[:, 2:3] >= 0, rows, -1).astype(jnp.int32)


@register_op("_onnx_gather_nd")
def onnx_gather_nd(data, indices):
    """ONNX GatherND (batch_dims=0). Negative (pad) index rows produce
    arbitrary values — pair with _onnx_scatter_nd, which drops them."""
    idx = indices.astype(jnp.int32)
    return data[tuple(jnp.moveaxis(idx, -1, 0))]


@register_op("_onnx_scatter_nd")
def onnx_scatter_nd(data, indices, updates):
    """ONNX ScatterND; rows of ``indices`` with any negative entry are
    dropped (the pad encoding used by _onnx_nms)."""
    idx = indices.astype(jnp.int32)
    valid = jnp.all(idx >= 0, axis=-1)
    safe = jnp.where(valid[..., None], idx, 0)
    coords = tuple(jnp.moveaxis(safe, -1, 0))
    cur = data[coords]
    delta = jnp.where(valid, updates - cur, jnp.zeros_like(updates))
    # add-of-delta instead of set: pad rows all alias index 0 and must not
    # clobber a real update that also targets it
    return data.at[coords].add(delta)


@register_op("bipartite_matching", n_outputs=2, nondiff=True)
def bipartite_matching(x, *, threshold, is_ascend=False, topk=-1):
    """Greedy global bipartite matching over a (B, N, M) score matrix
    (ref: src/operator/contrib/bounding_box.cc:BipartiteMatching — the
    GluonCV SSD/matcher primitive).

    Repeatedly takes the globally best unused (row, col) edge whose score
    passes ``threshold`` (>= when descending, <= when is_ascend) and pairs
    them off. Returns (row_match (B, N) col index or -1,
    col_match (B, M) row index or -1), float32 like upstream. ``topk`` > 0
    caps the number of matches per batch row. Static shapes: the greedy
    loop is a lax.fori_loop of min(N, M) [or topk] steps, so the whole op
    jits once per shape — where upstream runs a CPU/GPU kernel with a
    data-dependent loop."""
    sign = 1.0 if is_ascend else -1.0

    def one(s):
        N, M = s.shape
        steps = min(N, M) if topk <= 0 else min(topk, min(N, M))
        keyed = s * sign  # minimize keyed == extremize s per direction
        # explicit availability mask (not an inf sentinel in keyed): legit
        # +/-inf scores stay matchable, and an exhausted matrix just
        # no-ops the remaining loop steps instead of stalling on one cell
        avail0 = ((s <= threshold) if is_ascend else (s >= threshold)) \
            & ~jnp.isnan(s)

        def step(_, carry):
            avail, rm, cm = carry
            masked = jnp.where(avail, keyed, jnp.inf)
            flat = jnp.argmin(masked)
            r, c = flat // M, flat % M
            valid = avail[r, c]
            rm = jnp.where(valid, rm.at[r].set(c.astype(jnp.float32)), rm)
            cm = jnp.where(valid, cm.at[c].set(r.astype(jnp.float32)), cm)
            avail = jnp.where(valid,
                              avail.at[r, :].set(False).at[:, c].set(False),
                              avail)
            return avail, rm, cm

        rm = jnp.full((N,), -1.0, jnp.float32)
        cm = jnp.full((M,), -1.0, jnp.float32)
        _, rm, cm = jax.lax.fori_loop(0, steps, step, (avail0, rm, cm))
        return rm, cm

    return jax.vmap(one)(x)
