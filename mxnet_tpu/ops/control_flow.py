"""Control-flow ops: foreach / while_loop / cond.

TPU-native equivalents of MXNet contrib control-flow operators (ref:
src/operator/control_flow.cc, python/mxnet/ndarray/contrib.py:foreach). These
lower directly onto lax.scan / lax.while_loop / lax.cond so loops stay inside
one compiled XLA program — the whole point of compiler-friendly control flow on
TPU (the reference unrolls imperative loops or uses its own subgraph ops).

These take Python callables so they are library functions, not registry ops;
they work on raw jax arrays and on NDArray (unwrapped transparently).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax


def _unwrap(x):
    from ..ndarray import NDArray

    return jax.tree_util.tree_map(
        lambda v: v._data if isinstance(v, NDArray) else v, x,
        is_leaf=lambda v: isinstance(v, NDArray))


def _wrap_like(template_is_nd, x):
    if not template_is_nd:
        return x
    from ..ndarray import NDArray

    return jax.tree_util.tree_map(NDArray, x)


def _any_nd(x):
    from ..ndarray import NDArray

    found = [False]

    def chk(v):
        if isinstance(v, NDArray):
            found[0] = True
        return v

    jax.tree_util.tree_map(chk, x, is_leaf=lambda v: isinstance(v, NDArray))
    return found[0]


def foreach(body, data, init_states):
    """scan `body(slice, states) -> (out, new_states)` over axis 0 of data."""
    is_nd = _any_nd(data) or _any_nd(init_states)
    data = _unwrap(data)
    init_states = _unwrap(init_states)

    def step(states, xs):
        out, new_states = body(xs, states)
        return _unwrap(new_states), _unwrap(out)

    final_states, outs = lax.scan(step, init_states, data)
    return _wrap_like(is_nd, outs), _wrap_like(is_nd, final_states)


def while_loop(cond, func, loop_vars, max_iterations=None):
    """(ref: python/mxnet/ndarray/contrib.py:while_loop). `func` returns
    (step_output, new_loop_vars); outputs are stacked to max_iterations."""
    is_nd = _any_nd(loop_vars)
    loop_vars = _unwrap(loop_vars)
    if max_iterations is None:
        # pure while loop, no per-step outputs
        def c(vs):
            return jnp.asarray(_unwrap(cond(vs))).reshape(())

        def b(vs):
            _, new = func(vs)
            return _unwrap(new)

        out = lax.while_loop(lambda vs: c(vs).astype(bool), b, loop_vars)
        return None, _wrap_like(is_nd, out)

    # bounded loop with stacked outputs via scan + predicate masking
    probe_out, _ = func(loop_vars)
    probe_out = _unwrap(probe_out)

    def step(carry, _):
        vs, active = carry
        pred = jnp.asarray(_unwrap(cond(vs))).reshape(()).astype(bool) & active
        out, new_vs = func(vs)
        out, new_vs = _unwrap(out), _unwrap(new_vs)
        vs2 = jax.tree_util.tree_map(lambda n, o: jnp.where(pred, n, o), new_vs, vs)
        out = jax.tree_util.tree_map(lambda o: jnp.where(pred, o, jnp.zeros_like(o)), out)
        return (vs2, pred), out

    (final_vars, _), outs = lax.scan(step, (loop_vars, jnp.asarray(True)), None,
                                     length=max_iterations)
    return _wrap_like(is_nd, outs), _wrap_like(is_nd, final_vars)


def cond(pred, then_func, else_func, inputs=()):
    is_nd = _any_nd(inputs) or _any_nd(pred)
    p = jnp.asarray(_unwrap(pred)).reshape(()).astype(bool)
    inputs = _unwrap(inputs)
    out = lax.cond(p, lambda xs: _unwrap(then_func(*xs)), lambda xs: _unwrap(else_func(*xs)), inputs)
    return _wrap_like(is_nd, out)
