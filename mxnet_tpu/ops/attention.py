"""Attention ops.

Single functional seam for all transformer models: models call
``F.scaled_dot_attention``; the implementation dispatches to the Pallas flash
kernel on TPU (mxnet_tpu/ops/pallas/flash_attention.py) and to a reference
jnp implementation elsewhere (CPU tests, interpret mode). This replaces the
reference's unfused softmax(QK^T)V graph (MXNet had no flash attention;
ref: gluonnlp attention_cell.py:DotProductAttentionCell).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ..base import is_tpu_backend, register_op

_FLASH_MIN_LEN = 256  # below this, XLA's fused unblocked attention wins


def _reference_attention(q, k, v, mask=None, *, causal=False, scale=None):
    if scale is None:
        scale = 1.0 / (q.shape[-1] ** 0.5)
    s = jnp.einsum("bhqd,bhkd->bhqk", q.astype(jnp.float32) * scale, k.astype(jnp.float32))
    if mask is not None:
        s = jnp.where(mask.astype(bool), s, -1e30)
    if causal:
        T, S = s.shape[-2], s.shape[-1]
        cm = jnp.arange(T)[:, None] >= jnp.arange(S)[None, :]
        s = jnp.where(cm[None, None], s, -1e30)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bhqk,bhkd->bhqd", p, v.astype(jnp.float32)).astype(q.dtype)


@register_op("scaled_dot_attention")
def scaled_dot_attention(q, k, v, mask=None, *, causal=False, scale=None):
    """q,k,v: (B, H, T, D); mask broadcastable to (B, H, Tq, Tk), 1=keep."""
    if is_tpu_backend() and q.shape[2] >= _FLASH_MIN_LEN and mask is None:
        try:
            from .pallas.flash_attention import flash_attention

            return flash_attention(q, k, v, causal=causal, scale=scale)
        except Exception:
            pass
    return _reference_attention(q, k, v, mask, causal=causal, scale=scale)


@register_op("masked_softmax")
def masked_softmax(x, mask=None, *, axis=-1):
    if mask is not None:
        x = jnp.where(mask.astype(bool), x, -1e30)
    return jax.nn.softmax(x, axis=axis)
