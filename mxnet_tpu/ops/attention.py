"""Attention ops.

Single functional seam for all transformer models: models call
``F.scaled_dot_attention``; the implementation dispatches to the Pallas flash
kernel on TPU (mxnet_tpu/ops/pallas/flash_attention.py) and to a reference
jnp implementation elsewhere (CPU tests, interpret mode). This replaces the
reference's unfused softmax(QK^T)V graph (MXNet had no flash attention;
ref: gluonnlp attention_cell.py:DotProductAttentionCell).
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

from ..base import is_tpu_backend, register_op

_FLASH_MIN_LEN = 256  # static GUESS, used only until a hardware sweep lands


def _flash_min_len():
    """Measured flash-vs-dense crossover from the sweep artifact when one
    exists (flash_blocks.json "min_len", written by flash_sweep --apply),
    else the static guess. The headline bert runs at seq 128 — whether it
    takes the flash kernel is hardware's call, not a constant's."""
    try:
        from .pallas import flash_attention as _fa

        if _fa.MIN_LEN is not None:
            return _fa.MIN_LEN
    except Exception:  # pragma: no cover - pallas import unavailable
        pass
    return _FLASH_MIN_LEN

import threading

_SP_SCOPE = threading.local()


class sequence_parallel_scope:
    """Route every ``F.scaled_dot_attention`` inside the scope through
    sequence-parallel attention over ``mesh``'s ``axis_name`` axis —
    ``impl='ring'`` (ppermute ring, any head count) or ``'ulysses'``
    (all_to_all head scatter, needs H % axis == 0). Models need no edits;
    this is how a single-chip model becomes a long-context sp model.
    Exposed as ``mxnet_tpu.parallel.sequence_parallel_scope``.

    The scope is consulted AT TRACE TIME: a ``jax.jit``/``hybridize`` cache
    entry keeps whichever dispatch was active when it was first traced
    (same contract as ``autograd.train_mode`` and the keyed-jit stochastic
    executors) — enter the scope before the first call, and don't reuse a
    function jitted outside it."""

    def __init__(self, mesh, axis_name="sp", impl="ring"):
        if impl not in ("ring", "ulysses"):
            raise ValueError("impl must be 'ring' or 'ulysses', got %r"
                             % (impl,))
        self._cfg = (mesh, axis_name, impl)

    def __enter__(self):
        stack = getattr(_SP_SCOPE, "stack", None)
        if stack is None:
            stack = _SP_SCOPE.stack = []
        stack.append(self._cfg)
        return self

    def __exit__(self, *a):
        _SP_SCOPE.stack.pop()


def _current_sp_scope():
    stack = getattr(_SP_SCOPE, "stack", None)
    return stack[-1] if stack else None


@functools.partial(jax.custom_vjp, nondiff_argnums=(4,))
def _dense_attention_core(q, k, v, bias, scale):
    """Mixed-precision dense attention: bf16 MXU matmuls with fp32
    accumulation (preferred_element_type); softmax in fp32; ``bias`` is the
    additive fp32 mask (0 keep / -1e30 drop), already combining key-padding
    and causal terms."""
    out, _ = _dense_attention_fwd(q, k, v, bias, scale)
    return out


def _dense_attention_fwd(q, k, v, bias, scale):
    # scale applied to the fp32 logits, not to bf16 q: exact in scale and no
    # extra bf16 rounding before the MXU matmul
    s = scale * jnp.einsum("bhqd,bhkd->bhqk", q, k,
                           preferred_element_type=jnp.float32)
    if bias is not None:
        s = s + bias
    p = jax.nn.softmax(s, axis=-1)
    pb = p.astype(v.dtype)
    out = jnp.einsum("bhqk,bhkd->bhqd", pb, v,
                     preferred_element_type=jnp.float32).astype(q.dtype)
    return out, (q, k, v, pb, bias)


def _dense_attention_bwd(scale, res, do):
    # Without this hand-written VJP the fp32 softmax cotangent promotes
    # every backward matmul to f32 (measured: 48 of the BERT step's 228
    # dots). Standard recipe: softmax-grad math in f32, then ONE cast of ds
    # down to the compute dtype before the dq/dk/dv MXU matmuls.
    q, k, v, pb, bias = res
    do = do.astype(v.dtype)
    dv = jnp.einsum("bhqk,bhqd->bhkd", pb, do,
                    preferred_element_type=jnp.float32).astype(v.dtype)
    dp = jnp.einsum("bhqd,bhkd->bhqk", do, v,
                    preferred_element_type=jnp.float32)
    pf = pb.astype(jnp.float32)
    ds = pf * (dp - jnp.sum(dp * pf, axis=-1, keepdims=True))
    # s = scale·(q·kᵀ) + bias  →  both dq and dk carry the scale factor
    dsb = (ds * scale).astype(q.dtype)
    dq = jnp.einsum("bhqk,bhkd->bhqd", dsb, k,
                    preferred_element_type=jnp.float32).astype(q.dtype)
    dk = jnp.einsum("bhqk,bhqd->bhkd", dsb, q,
                    preferred_element_type=jnp.float32).astype(k.dtype)
    # the mask bias derives from non-differentiable booleans upstream; its
    # cotangent is structurally zero (None for the bias=None pytree)
    dbias = jax.tree_util.tree_map(lambda b: jnp.zeros(b.shape, b.dtype),
                                   bias)
    return dq, dk, dv, dbias


_dense_attention_core.defvjp(_dense_attention_fwd, _dense_attention_bwd)


def _mask_bias(mask, causal, T, S):
    """Combine key-padding mask + causal triangle into one additive fp32
    bias (or None)."""
    bias = None
    if mask is not None:
        bias = jnp.where(mask.astype(bool), 0.0, -1e30).astype(jnp.float32)
    if causal:
        cm = jnp.arange(T)[:, None] >= jnp.arange(S)[None, :]
        cb = jnp.where(cm, 0.0, -1e30).astype(jnp.float32)[None, None]
        bias = cb if bias is None else bias + cb
    return bias


def _reference_attention(q, k, v, mask=None, *, causal=False, scale=None):
    if scale is None:
        scale = 1.0 / (q.shape[-1] ** 0.5)
    try:
        scale = float(scale)  # nondiff_argnums needs a static python scalar
    except (TypeError, jax.errors.TracerArrayConversionError,
            jax.errors.ConcretizationTypeError):
        # traced/learned scale: fall back to the upcast reference (rare;
        # keeps the public op seam's accepted domain unchanged)
        s = jnp.einsum("bhqd,bhkd->bhqk", q.astype(jnp.float32),
                       k.astype(jnp.float32)) * scale
        bias = _mask_bias(mask, causal, q.shape[-2], k.shape[-2])
        if bias is not None:
            s = s + bias
        p = jax.nn.softmax(s, axis=-1)
        return jnp.einsum("bhqk,bhkd->bhqd", p,
                          v.astype(jnp.float32)).astype(q.dtype)
    bias = _mask_bias(mask, causal, q.shape[-2], k.shape[-2])
    return _dense_attention_core(q, k, v, bias, scale)


@register_op("scaled_dot_attention")
def scaled_dot_attention(q, k, v, mask=None, *, causal=False, scale=None,
                         prefix_mask=False):
    """q,k,v: (B, H, T, D); mask broadcastable to (B, H, Tq, Tk), 1=keep.

    prefix_mask=True is the caller's STATIC declaration that ``mask`` is a
    key-padding prefix (mask[b, ..., t] = t < valid_len[b], BERT-style) —
    then the O(T)-memory flash path applies with a per-example valid length
    recovered as the mask's row sum, instead of falling back to the dense
    T×T reference the way arbitrary masks must.

    Inside ``parallel.sequence_parallel_scope(mesh, ...)`` this seam
    dispatches to ring/ulysses attention over the scope's mesh axis — the
    model code doesn't change, the sequence dimension just shards."""
    sp = _current_sp_scope()
    if sp is not None:
        mesh, axis_name, impl = sp
        if mask is not None:
            raise ValueError(
                "sequence_parallel_scope: ring/ulysses attention supports "
                "causal or unmasked only — key-padding masks would need "
                "per-shard valid lengths (pad to full length instead)")
        n_sp = int(mesh.shape[axis_name])
        if q.shape[2] % n_sp or k.shape[2] % n_sp:
            raise ValueError(
                "sequence_parallel_scope: sequence length %d/%d must divide "
                "the %r axis (%d) — incremental decode (T=1) and ragged "
                "lengths cannot shard; run generation outside the scope"
                % (q.shape[2], k.shape[2], axis_name, n_sp))
        from jax.sharding import NamedSharding
        from jax.sharding import PartitionSpec as _P

        from ..parallel import ring_attention, ulysses_attention

        fn = ring_attention if impl == "ring" else ulysses_attention
        # eager NDArray data is committed to one device; the shard_map needs
        # the whole mesh, so reshard in and gather back out to the caller's
        # original placement. Inside a single-device jit both puts are
        # no-ops; users doing whole-program mesh sharding should call
        # parallel.ring_attention directly.
        orig = getattr(q, "sharding", None)  # None for tracers
        s_in = NamedSharding(mesh, _P(None, None, axis_name, None))
        q, k, v = (jax.device_put(a, s_in) for a in (q, k, v))
        out = fn(q, k, v, mesh, axis_name=axis_name, causal=causal,
                 scale=scale)
        return jax.device_put(out, orig if orig is not None
                              else mesh.devices.flat[0])
    if (is_tpu_backend() and q.shape[2] >= _flash_min_len()
            and (mask is None or prefix_mask)):
        try:
            from .pallas.flash_attention import flash_attention

            vl = None if mask is None else _prefix_mask_to_valid_len(mask)
            return flash_attention(q, k, v, causal=causal, scale=scale,
                                   kv_valid_len=vl)
        except Exception as e:  # pragma: no cover - depends on backend
            import warnings

            warnings.warn("flash attention unavailable, using dense "
                          "reference: %s" % e)
    return _reference_attention(q, k, v, mask, causal=causal, scale=scale)


def _prefix_mask_to_valid_len(mask):
    """(B, ..., Tk) prefix key-padding mask → (B,) valid lengths. A prefix
    mask is row-constant, so any one row's sum is the length."""
    return jnp.sum(mask.reshape(mask.shape[0], -1, mask.shape[-1])
                   [:, 0, :].astype(jnp.int32), axis=-1)


@register_op("masked_softmax")
def masked_softmax(x, mask=None, *, axis=-1):
    if mask is not None:
        x = jnp.where(mask.astype(bool), x, -1e30)
    return jax.nn.softmax(x, axis=axis)


@register_op("cache_write", nondiff=True)
def cache_write(cache, update, index):
    """Write ``update`` (B, H, T, D) into the fixed-capacity KV cache
    ``cache`` (B, H, C, D) at time offset ``index`` along axis 2 — the
    decode-cache primitive: the cache shape NEVER changes across steps, so
    a jitted decode step compiles once instead of retracing per token (the
    growing-``concat`` cache layout graphlint GL007 flags).

    ``index`` is a scalar (whole-batch write at one offset: prefill, the
    uniform imperative decode loop) or a per-row ``(B,)`` vector (continuous
    batching: each slot is at its own position). Lowers to
    ``lax.dynamic_update_slice`` — with the cache buffer donated, XLA
    updates it in place. Writes past the capacity are the caller's bug;
    like dynamic_update_slice, the start index clamps to ``C - T``."""
    index = jnp.asarray(index, jnp.int32)
    update = update.astype(cache.dtype)
    zero = jnp.int32(0)
    if index.ndim == 0:
        return jax.lax.dynamic_update_slice(cache, update,
                                            (zero, zero, index, zero))
    return jax.vmap(
        lambda c, u, i: jax.lax.dynamic_update_slice(c, u, (zero, i, zero))
    )(cache, update, index)


@register_op("quant_cache_write", nondiff=True, n_outputs=2)
def quant_cache_write(cache, scale, update, index):
    """:func:`cache_write` for the int8 paged KV cache: quantize ``update``
    (B, H, T, D) fp on write into ``cache`` (B, H, C, D) int8 with a
    per-page-per-head scale ``scale`` (B, H, 1, 1) fp32, returning
    ``(new_cache, new_scale)``.

    The scale is a RUNNING per-(page, head) max — monotone non-decreasing,
    so already-written positions only ever rescale DOWN (ratio ≤ 1) and the
    branchless requantize below is an exact no-op when the scale did not
    move (int8→fp32 × 1.0 → round reproduces the integer). Both buffers are
    donated by the decode step, so the whole thing is an in-place page
    update; shapes never change across steps — one compiled program."""
    index = jnp.asarray(index, jnp.int32)
    zero = jnp.int32(0)
    update = update.astype(jnp.float32)
    amax = jnp.max(jnp.abs(update), axis=(2, 3), keepdims=True)
    new_scale = jnp.maximum(scale, jnp.maximum(amax / 127.0, 1e-8))
    ratio = scale / new_scale            # ≤ 1; 0 for never-written pages
    requant = jnp.clip(jnp.round(cache.astype(jnp.float32) * ratio),
                       -127, 127).astype(jnp.int8)
    qupd = jnp.clip(jnp.round(update / new_scale), -127, 127).astype(jnp.int8)
    if index.ndim == 0:
        out = jax.lax.dynamic_update_slice(requant, qupd,
                                           (zero, zero, index, zero))
    else:
        out = jax.vmap(
            lambda c, u, i: jax.lax.dynamic_update_slice(c, u, (zero, i, zero))
        )(requant, qupd, index)
    return out, new_scale


@register_op("quant_cache_write_read", nondiff=True, n_outputs=3)
def quant_cache_write_read(cache, scale, update, index):
    """:func:`quant_cache_write` fused with the :func:`dequant_cache` read
    of the page it just wrote, returning ``(new_cache, new_scale, deq)``
    with ``deq`` (B, H, C, D) fp32 ready for attention.

    The separate write-then-read pair is the hlolint GL024 convert churn:
    the write quantizes the full page f32→int8 and the read immediately
    converts the SAME page int8→f32 with nothing but the cache update in
    between — two full-page converts per layer per step, which is what
    caps int8 decode below units=256. Here the fp32 requant/quantize
    values computed for the write are reused for the read, so the int8
    round trip never happens. Bit-exact with the unfused pair: the
    written values are integer-valued fp32 in [-127, 127], which int8
    represents exactly, so ``deq == dequant_cache(new_cache, new_scale)``
    to the last ulp."""
    index = jnp.asarray(index, jnp.int32)
    zero = jnp.int32(0)
    update = update.astype(jnp.float32)
    amax = jnp.max(jnp.abs(update), axis=(2, 3), keepdims=True)
    new_scale = jnp.maximum(scale, jnp.maximum(amax / 127.0, 1e-8))
    ratio = scale / new_scale            # ≤ 1; 0 for never-written pages
    requant = jnp.clip(jnp.round(cache.astype(jnp.float32) * ratio),
                       -127, 127)
    qupd = jnp.clip(jnp.round(update / new_scale), -127, 127)
    if index.ndim == 0:
        starts = (zero, zero, index, zero)
        out = jax.lax.dynamic_update_slice(
            requant.astype(jnp.int8), qupd.astype(jnp.int8), starts)
        deq = jax.lax.dynamic_update_slice(requant, qupd, starts)
    else:
        def _dus(c, u, i):
            return jax.lax.dynamic_update_slice(c, u, (zero, i, zero))

        out = jax.vmap(_dus)(requant.astype(jnp.int8),
                             qupd.astype(jnp.int8), index)
        deq = jax.vmap(_dus)(requant, qupd, index)
    return out, new_scale, deq * new_scale


@register_op("dequant_cache", nondiff=True)
def dequant_cache(cache, scale):
    """int8 KV pages → fp32 for attention: ``cache`` (B, H, C, D) int8 ×
    ``scale`` (B, H, 1, 1) fp32. XLA fuses the convert+scale into the
    attention matmul's operand read — no materialized fp32 cache copy."""
    return cache.astype(jnp.float32) * scale
