"""Attention ops.

Single functional seam for all transformer models: models call
``F.scaled_dot_attention``; the implementation dispatches to the Pallas flash
kernel on TPU (mxnet_tpu/ops/pallas/flash_attention.py) and to a reference
jnp implementation elsewhere (CPU tests, interpret mode). This replaces the
reference's unfused softmax(QK^T)V graph (MXNet had no flash attention;
ref: gluonnlp attention_cell.py:DotProductAttentionCell).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ..base import is_tpu_backend, register_op

_FLASH_MIN_LEN = 256  # below this, XLA's fused unblocked attention wins


def _reference_attention(q, k, v, mask=None, *, causal=False, scale=None):
    if scale is None:
        scale = 1.0 / (q.shape[-1] ** 0.5)
    s = jnp.einsum("bhqd,bhkd->bhqk", q.astype(jnp.float32) * scale, k.astype(jnp.float32))
    if mask is not None:
        s = jnp.where(mask.astype(bool), s, -1e30)
    if causal:
        T, S = s.shape[-2], s.shape[-1]
        cm = jnp.arange(T)[:, None] >= jnp.arange(S)[None, :]
        s = jnp.where(cm[None, None], s, -1e30)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bhqk,bhkd->bhqd", p, v.astype(jnp.float32)).astype(q.dtype)


@register_op("scaled_dot_attention")
def scaled_dot_attention(q, k, v, mask=None, *, causal=False, scale=None,
                         prefix_mask=False):
    """q,k,v: (B, H, T, D); mask broadcastable to (B, H, Tq, Tk), 1=keep.

    prefix_mask=True is the caller's STATIC declaration that ``mask`` is a
    key-padding prefix (mask[b, ..., t] = t < valid_len[b], BERT-style) —
    then the O(T)-memory flash path applies with a per-example valid length
    recovered as the mask's row sum, instead of falling back to the dense
    T×T reference the way arbitrary masks must."""
    if (is_tpu_backend() and q.shape[2] >= _FLASH_MIN_LEN
            and (mask is None or prefix_mask)):
        try:
            from .pallas.flash_attention import flash_attention

            vl = None if mask is None else _prefix_mask_to_valid_len(mask)
            return flash_attention(q, k, v, causal=causal, scale=scale,
                                   kv_valid_len=vl)
        except Exception as e:  # pragma: no cover - depends on backend
            import warnings

            warnings.warn("flash attention unavailable, using dense "
                          "reference: %s" % e)
    return _reference_attention(q, k, v, mask, causal=causal, scale=scale)


def _prefix_mask_to_valid_len(mask):
    """(B, ..., Tk) prefix key-padding mask → (B,) valid lengths. A prefix
    mask is row-constant, so any one row's sum is the length."""
    return jnp.sum(mask.reshape(mask.shape[0], -1, mask.shape[-1])
                   [:, 0, :].astype(jnp.int32), axis=-1)


@register_op("masked_softmax")
def masked_softmax(x, mask=None, *, axis=-1):
    if mask is not None:
        x = jnp.where(mask.astype(bool), x, -1e30)
    return jax.nn.softmax(x, axis=axis)
