"""Long-tail mx.nd.contrib ops (ref: src/operator/contrib/*).

The attention ops reproduce upstream's interleaved-projection layout
(contrib/transformer.cc) — gluonnlp's fused-transformer path — as einsums
XLA tiles straight onto the MXU; the rest are small utility/coder ops.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from ..base import register_op

__all__ = []


@register_op("arange_like", nondiff=True)
def arange_like(data, *, start=0.0, step=1.0, repeat=1, axis=None,
                ctx=None):
    """(ref: contrib/arange_like) arange shaped like data (or its one
    axis) — the shape is STATIC under jit, unlike a host-side arange."""
    def fill(n):
        # `repeat` repeats each VALUE (nd.arange semantics): 0,0,1,1,...
        base = start + step * jnp.arange(-(-n // repeat))
        return jnp.repeat(base, repeat)[:n].astype(data.dtype)

    if axis is None:
        return fill(data.size).reshape(data.shape)
    return fill(data.shape[axis])


@register_op("index_array", nondiff=True)
def index_array(data, *, axes=None):
    """(ref: contrib/index_array.cc) element coordinates of data: shape
    data.shape + (len(axes),). int32 (TPU-native; upstream emits int64)."""
    nd_ = data.ndim
    axes = tuple(range(nd_)) if axes is None else tuple(a % nd_ for a in axes)
    grids = [lax.broadcasted_iota(jnp.int32, data.shape, a) for a in axes]
    return jnp.stack(grids, axis=-1)


@register_op("index_copy", nondiff=True)
def index_copy(old, index, new_tensor):
    """(ref: contrib/index_copy.cc) rows of old at `index` replaced by
    new_tensor's rows."""
    return old.at[index.astype(jnp.int32)].set(new_tensor)


@register_op("allclose", nondiff=True)
def allclose(a, b, *, rtol=1e-5, atol=1e-8, equal_nan=False):
    """(ref: contrib/allclose_op.cc) 1.0/0.0 scalar array."""
    ok = jnp.allclose(a, b, rtol=rtol, atol=atol, equal_nan=equal_nan)
    return ok.astype(jnp.float32).reshape(1)


@register_op("div_sqrt_dim")
def div_sqrt_dim(data):
    """(ref: contrib/transformer.cc DivSqrtDim) data / sqrt(last dim)."""
    return data / jnp.sqrt(jnp.asarray(data.shape[-1], data.dtype))


@jax.custom_vjp
def _grad_multiply(data, scalar):
    return data


def _gm_fwd(data, scalar):
    return data, scalar


def _gm_bwd(scalar, g):
    return g * scalar.astype(g.dtype), None


_grad_multiply.defvjp(_gm_fwd, _gm_bwd)


@register_op("gradientmultiplier")
def gradientmultiplier(data, *, scalar=1.0):
    """(ref: contrib/gradient_multiplier_op.cc) BIT-EXACT identity forward,
    gradient scaled by `scalar` (the GRL trick at scalar < 0). custom_vjp,
    not the ``x*s + stop_gradient(x - x*s)`` algebra: upstream applies the
    scale only in backward, and the algebraic form drifts by a rounding ulp
    (a + (b - a) != b in floating point, ADVICE r4)."""
    return _grad_multiply(data, jnp.asarray(scalar, data.dtype))


@register_op("quantize_v2", nondiff=True, n_outputs=3)
def quantize_v2(data, *, out_type="int8", min_calib_range=None,
                max_calib_range=None):
    """(ref: quantization/quantize_v2.cc) affine uint8 / symmetric int8
    quantization; calibrated when ranges are given, else from data.
    out_type='auto' picks uint8 for an all-non-negative calibrated range
    (upstream's rule), int8 otherwise."""
    if out_type not in ("auto", "int8", "uint8"):
        raise ValueError("out_type must be auto/int8/uint8, got %r"
                         % (out_type,))
    if min_calib_range is not None and max_calib_range is not None:
        dmin = jnp.asarray(min_calib_range, jnp.float32)
        dmax = jnp.asarray(max_calib_range, jnp.float32)
        if out_type == "auto":
            out_type = "uint8" if min_calib_range >= 0 else "int8"
    else:
        dmin = jnp.min(data).astype(jnp.float32)
        dmax = jnp.max(data).astype(jnp.float32)
        if out_type == "auto":
            out_type = "int8"  # data-dependent sign can't pick a dtype under jit
    if out_type == "uint8":
        scale = 255.0 / jnp.maximum(dmax - dmin, 1e-20)
        q = jnp.clip(jnp.round((data - dmin) * scale), 0, 255).astype(jnp.uint8)
        return q, dmin.reshape(1), dmax.reshape(1)
    absmax = jnp.maximum(jnp.abs(dmin), jnp.abs(dmax))
    scale = 127.0 / jnp.maximum(absmax, 1e-20)
    q = jnp.clip(jnp.round(data * scale), -127, 127).astype(jnp.int8)
    return q, (-absmax).reshape(1), absmax.reshape(1)


@register_op("group_adagrad_update", nondiff=True, n_outputs=2)
def group_adagrad_update(weight, grad, history, *, lr, rescale_grad=1.0,
                         clip_gradient=-1.0, epsilon=1e-5):
    """(ref: contrib/optimizer_op.cc GroupAdagradUpdate) AdaGrad with ONE
    accumulator per row (dim-0 group) — the embedding optimizer."""
    from .legacy_ops import _clip
    g = _clip(grad * rescale_grad, clip_gradient)
    axes = tuple(range(1, g.ndim))
    h = history + jnp.mean(jnp.square(g), axis=axes, keepdims=True) \
        if axes else history + jnp.square(g)
    return weight - lr * g / (jnp.sqrt(h) + epsilon), h


def _corner_to_center(box):
    x0, y0, x1, y1 = jnp.split(box, 4, axis=-1)
    w = x1 - x0
    h = y1 - y0
    return x0 + w * 0.5, y0 + h * 0.5, w, h


@register_op("box_encode", nondiff=True, n_outputs=2)
def box_encode(samples, matches, anchors, refs, means=(0., 0., 0., 0.),
               stds=(0.1, 0.1, 0.2, 0.2)):
    """(ref: contrib/bounding_box.cc BoxEncode) matched gt boxes vs anchors
    -> normalized (dx,dy,dw,dh) targets + positive-sample masks.
    samples (B,N) in {+1,0,-1}; matches (B,N) gt indices; anchors (B,N,4)
    and refs (B,M,4) corner format."""
    matched = jnp.take_along_axis(
        refs, jnp.clip(matches, 0, refs.shape[1] - 1)[..., None]
        .astype(jnp.int32).repeat(4, axis=-1), axis=1)
    ax, ay, aw, ah = _corner_to_center(anchors)
    gx, gy, gw, gh = _corner_to_center(matched)
    t = jnp.concatenate([
        ((gx - ax) / aw - means[0]) / stds[0],
        ((gy - ay) / ah - means[1]) / stds[1],
        (jnp.log(gw / aw) - means[2]) / stds[2],
        (jnp.log(gh / ah) - means[3]) / stds[3]], axis=-1)
    mask = (samples > 0.5)[..., None].astype(t.dtype) * jnp.ones_like(t)
    return t * mask, mask


@register_op("box_decode", nondiff=True)
def box_decode(data, anchors, std0=0.1, std1=0.1, std2=0.2, std3=0.2,
               clip=-1.0, format="corner"):
    """(ref: contrib/bounding_box.cc BoxDecode) inverse of box_encode:
    (dx,dy,dw,dh) deltas + anchors -> corner boxes."""
    if format == "corner":
        ax, ay, aw, ah = _corner_to_center(anchors)
    else:
        ax, ay, aw, ah = jnp.split(anchors, 4, axis=-1)
    dx, dy, dw, dh = jnp.split(data, 4, axis=-1)
    cx = dx * std0 * aw + ax
    cy = dy * std1 * ah + ay
    dw = dw * std2
    dh = dh * std3
    if clip is not None and clip > 0:
        dw = jnp.minimum(dw, clip)
        dh = jnp.minimum(dh, clip)
    w = jnp.exp(dw) * aw
    h = jnp.exp(dh) * ah
    return jnp.concatenate([cx - w * 0.5, cy - h * 0.5,
                            cx + w * 0.5, cy + h * 0.5], axis=-1)


@register_op("contrib_fft", nondiff=True)
def contrib_fft(data, *, compute_size=128):
    """(ref: contrib/fft.cc) FFT along the last axis, output interleaved
    [re0, im0, re1, im1, ...] — last dim doubles."""
    out = jnp.fft.fft(data.astype(jnp.complex64), axis=-1)
    ri = jnp.stack([out.real, out.imag], axis=-1)
    return ri.reshape(data.shape[:-1] + (2 * data.shape[-1],)) \
        .astype(jnp.float32)


@register_op("contrib_ifft", nondiff=True)
def contrib_ifft(data, *, compute_size=128):
    """(ref: contrib/ifft.cc) inverse of contrib_fft: interleaved pairs in,
    real part out (last dim halves). Like upstream (cuFFT), UNNORMALIZED —
    ifft(fft(x)) == n * x."""
    n = data.shape[-1] // 2
    ri = data.reshape(data.shape[:-1] + (n, 2))
    comp = ri[..., 0] + 1j * ri[..., 1]
    return (jnp.fft.ifft(comp, axis=-1).real * n).astype(jnp.float32)


# ---------------------------------------------- interleaved attention ops
# (ref: src/operator/contrib/transformer.cc — gluonnlp's fused self/encdec
# attention path). Layout: projections per head are interleaved along the
# feature dim: qkv (L, B, H*3*D) = per-head [q; k; v].

def _split_qkv(qkv, heads):
    L, B, F = qkv.shape
    d = F // (3 * heads)
    x = qkv.reshape(L, B, heads, 3, d)
    return x[..., 0, :], x[..., 1, :], x[..., 2, :], d


@register_op("interleaved_matmul_selfatt_qk")
def interleaved_matmul_selfatt_qk(queries_keys_values, *, heads):
    q, k, _, d = _split_qkv(queries_keys_values, heads)
    scores = jnp.einsum("lbhd,mbhd->bhlm", q * (1.0 / jnp.sqrt(
        jnp.asarray(d, q.dtype))), k)
    B, H, L, M = scores.shape
    return scores.reshape(B * H, L, M)


@register_op("interleaved_matmul_selfatt_valatt")
def interleaved_matmul_selfatt_valatt(queries_keys_values, attention, *,
                                      heads):
    _, _, v, d = _split_qkv(queries_keys_values, heads)
    L, B = v.shape[0], v.shape[1]
    att = attention.reshape(B, heads, attention.shape[1],
                            attention.shape[2])
    out = jnp.einsum("bhlm,mbhd->lbhd", att, v)
    return out.reshape(L, B, heads * d)


@register_op("interleaved_matmul_encdec_qk")
def interleaved_matmul_encdec_qk(queries, keys_values, *, heads):
    Lq, B, F = queries.shape
    d = F // heads
    q = queries.reshape(Lq, B, heads, d)
    kv = keys_values.reshape(keys_values.shape[0], B, heads, 2, d)
    k = kv[..., 0, :]
    scores = jnp.einsum("lbhd,mbhd->bhlm", q * (1.0 / jnp.sqrt(
        jnp.asarray(d, q.dtype))), k)
    return scores.reshape(B * heads, Lq, keys_values.shape[0])


@register_op("interleaved_matmul_encdec_valatt")
def interleaved_matmul_encdec_valatt(keys_values, attention, *, heads):
    M, B, F = keys_values.shape
    d = F // (2 * heads)
    kv = keys_values.reshape(M, B, heads, 2, d)
    v = kv[..., 1, :]
    att = attention.reshape(B, heads, attention.shape[1],
                            attention.shape[2])
    out = jnp.einsum("bhlm,mbhd->lbhd", att, v)
    return out.reshape(attention.shape[1], B, heads * d)
