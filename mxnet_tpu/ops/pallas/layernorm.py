"""Fused LayerNorm Pallas kernel.

MXNet's LayerNorm is a handwritten CUDA kernel (ref: src/operator/nn/
layer_norm.cu). XLA already fuses the naive formulation into ~2 passes; this
kernel does the whole normalize-scale-shift in ONE VMEM-resident pass per row
block with fp32 statistics — saves an HBM round trip for bf16 activations at
transformer widths. Used by ops/functional.py:LayerNorm on TPU for 2-D inputs;
interpret mode covers CPU tests.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu


def _ln_kernel(x_ref, g_ref, b_ref, o_ref, *, eps):
    x = x_ref[:].astype(jnp.float32)
    m = jnp.mean(x, axis=-1, keepdims=True)
    xc = x - m
    v = jnp.mean(xc * xc, axis=-1, keepdims=True)
    y = xc * jax.lax.rsqrt(v + eps)
    o_ref[:] = (y * g_ref[:].astype(jnp.float32) +
                b_ref[:].astype(jnp.float32)).astype(o_ref.dtype)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4))
def layernorm(x, gamma, beta, eps=1e-5, interpret=False):
    """Differentiable fused LN: pallas forward, analytic XLA backward."""
    return fused_layernorm(x, gamma, beta, eps, interpret=interpret)


def _ln_fwd(x, gamma, beta, eps, interpret):
    return fused_layernorm(x, gamma, beta, eps, interpret=interpret), (x, gamma)


def _ln_bwd(eps, interpret, res, dy):
    x, gamma = res
    xf = x.astype(jnp.float32)
    dyf = dy.astype(jnp.float32)
    gf = gamma.astype(jnp.float32)
    m = jnp.mean(xf, axis=-1, keepdims=True)
    v = jnp.mean(jnp.square(xf - m), axis=-1, keepdims=True)
    inv = jax.lax.rsqrt(v + eps)
    xhat = (xf - m) * inv
    dg = jnp.sum(dyf * xhat, axis=0)
    db = jnp.sum(dyf, axis=0)
    t = dyf * gf
    dx = inv * (t - jnp.mean(t, axis=-1, keepdims=True)
                - xhat * jnp.mean(t * xhat, axis=-1, keepdims=True))
    return dx.astype(x.dtype), dg.astype(gamma.dtype), db.astype(gamma.dtype)


layernorm.defvjp(_ln_fwd, _ln_bwd)


def fused_layernorm(x, gamma, beta, eps=1e-5, block_rows=256, interpret=False):
    """x: (R, C); gamma/beta: (C,). C should be a multiple of 128."""
    R, C = x.shape
    br = min(block_rows, R)
    while R % br:
        br //= 2
    br = max(br, 1)
    return pl.pallas_call(
        functools.partial(_ln_kernel, eps=eps),
        interpret=interpret,
        grid=(R // br,),
        in_specs=[
            pl.BlockSpec((br, C), lambda i: (i, 0)),
            pl.BlockSpec((C,), lambda i: (0,)),
            pl.BlockSpec((C,), lambda i: (0,)),
        ],
        out_specs=pl.BlockSpec((br, C), lambda i: (i, 0)),
        out_shape=jax.ShapeDtypeStruct((R, C), x.dtype),
    )(x, gamma, beta)
