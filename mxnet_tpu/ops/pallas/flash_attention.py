"""Flash attention Pallas kernels (forward + backward) for TPU.

Replaces the reference's unfused softmax(QK^T)V chain (three HBM round trips
for the T×T score matrix) with blockwise kernels: Q blocks stay resident in
VMEM while K/V blocks stream through, online-softmax accumulating in fp32
scratch — O(T) HBM traffic instead of O(T^2), forward AND backward. The
forward also emits the per-row logsumexp (lane-broadcast, matching the
(bq, 128) scratch layout Mosaic likes); the backward is the flash-attention-2
recompute scheme as two kernels — dq over (q-block, k-inner) and dk/dv over
(k-block, q-inner) — so no T×T tensor ever materializes in either pass.

Pattern source: /opt/skills/guides/pallas_guide.md (double-buffered matmul,
custom-VJP kernels). Falls back to the jnp reference off-TPU (ops/attention.py).
"""
from __future__ import annotations

import functools
import json
import os

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

if not hasattr(pltpu, "CompilerParams"):
    # jax < 0.5 exposes the same dataclass as TPUCompilerParams
    pltpu.CompilerParams = pltpu.TPUCompilerParams

NEG_INF = -1e30
LANES = 128


def _scores(q_ref, k_ref, q_idx, kv_idx, *, scale, causal, bq, bk, vl=None):
    """Shared Q·Kᵀ score-block recompute — the ONE definition of scaling,
    causal masking, and key-padding masking used by forward and both backward
    kernels, so their numerics can never desynchronize. ``vl`` is a traced
    per-example valid K length: columns >= vl are masked (BERT-style prefix
    padding)."""
    # native-dtype (bf16) MXU operands with fp32 accumulation; scale applied
    # to the fp32 scores so no extra bf16 rounding hits the matmul inputs
    q = q_ref[0]                              # (bq, d)
    k = k_ref[0]                              # (bk, d)
    s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                            preferred_element_type=jnp.float32) * scale
    if causal:
        rows = q_idx * bq + jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 0)
        cols = kv_idx * bk + jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 1)
        s = jnp.where(rows >= cols, s, NEG_INF)
    if vl is not None:
        cols = kv_idx * bk + jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 1)
        s = jnp.where(cols < vl, s, NEG_INF)
    return s


def _fwd_kernel(q_ref, k_ref, v_ref, *rest, scale, causal, bq, bk,
                emit_lse, masked):
    if masked:
        vl_ref, rest = rest[0], rest[1:]
        vl = vl_ref[0, 0, 0]
    else:
        vl = None
    o_ref, rest = rest[0], rest[1:]
    if emit_lse:
        lse_ref, m_ref, l_ref, acc_ref = rest
    else:
        (m_ref, l_ref, acc_ref), lse_ref = rest, None
    kv_idx = pl.program_id(2)
    q_idx = pl.program_id(1)

    @pl.when(kv_idx == 0)
    def _init():
        m_ref[:] = jnp.full_like(m_ref, NEG_INF)
        l_ref[:] = jnp.zeros_like(l_ref)
        acc_ref[:] = jnp.zeros_like(acc_ref)

    run = True
    if causal:
        # skip fully-masked K blocks: first query row of this Q block is
        # q_idx*bq; block contributes iff kv_idx*bk <= q_idx*bq + bq - 1
        run = kv_idx * bk <= q_idx * bq + bq - 1
    if masked:
        # dynamic skip: K blocks entirely past this example's valid length
        run = jnp.logical_and(run, kv_idx * bk < vl)

    @pl.when(run)
    def _compute():
        v = v_ref[0]                            # (bk, d) native dtype
        s = _scores(q_ref, k_ref, q_idx, kv_idx, scale=scale, causal=causal,
                    bq=bq, bk=bk, vl=vl)
        m_prev = m_ref[:]                       # (bq, 128) broadcast lanes
        m_cur = jnp.max(s, axis=1, keepdims=True)  # (bq, 1)
        m_new = jnp.maximum(m_prev, jnp.broadcast_to(m_cur, m_prev.shape))
        corr = jnp.exp(m_prev - m_new)
        p = jnp.exp(s - m_new[:, :1])           # (bq, bk) fp32
        l_ref[:] = l_ref[:] * corr + jnp.broadcast_to(
            jnp.sum(p, axis=1, keepdims=True), m_prev.shape)
        # p downcast to the operand dtype for the MXU; accumulator stays fp32
        acc_ref[:] = acc_ref[:] * corr[:, :1] + jax.lax.dot_general(
            p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        m_ref[:] = m_new

    @pl.when(kv_idx == pl.num_programs(2) - 1)
    def _finish():
        # max guard: a vl=0 example has an all-masked row (l == 0)
        o_ref[0] = (acc_ref[:] / jnp.maximum(l_ref[:, :1], 1e-30)).astype(
            o_ref.dtype)
        if emit_lse:
            lse_ref[0] = m_ref[:] + jnp.log(jnp.maximum(l_ref[:], 1e-30))


def _vl_operand(kv_valid_len, B, H):
    """valid_len (B,) → (B*H, 1, LANES) int32 VMEM operand (one scalar per
    grid row, lane-broadcast to the native tile width)."""
    vl = jnp.broadcast_to(kv_valid_len.astype(jnp.int32)[:, None, None, None],
                          (B, H, 1, LANES))
    return vl.reshape(B * H, 1, LANES)


def _flash_fwd(q, k, v, kv_valid_len, scale, causal, bq, bk, interpret=False,
               return_lse=False):
    B, H, Tq, D = q.shape
    Tk = k.shape[2]
    bq = min(bq, Tq)
    bk = min(bk, Tk)
    qr = q.reshape(B * H, Tq, D)
    kr = k.reshape(B * H, Tk, D)
    vr = v.reshape(B * H, Tk, D)
    masked = kv_valid_len is not None
    grid = (B * H, Tq // bq, Tk // bk)
    in_specs = [
        pl.BlockSpec((1, bq, D), lambda b, i, j: (b, i, 0)),
        pl.BlockSpec((1, bk, D), lambda b, i, j: (b, j, 0)),
        pl.BlockSpec((1, bk, D), lambda b, i, j: (b, j, 0)),
    ]
    operands = [qr, kr, vr]
    if masked:
        in_specs.append(pl.BlockSpec((1, 1, LANES), lambda b, i, j: (b, 0, 0)))
        operands.append(_vl_operand(kv_valid_len, B, H))
    out_specs = [pl.BlockSpec((1, bq, D), lambda b, i, j: (b, i, 0))]
    out_shape = [jax.ShapeDtypeStruct((B * H, Tq, D), q.dtype)]
    if return_lse:  # inference path skips the lse output entirely — XLA
        # cannot DCE an output of an opaque pallas_call
        out_specs.append(pl.BlockSpec((1, bq, LANES), lambda b, i, j: (b, i, 0)))
        out_shape.append(jax.ShapeDtypeStruct((B * H, Tq, LANES), jnp.float32))
    res = pl.pallas_call(
        functools.partial(_fwd_kernel, scale=scale, causal=causal, bq=bq, bk=bk,
                          emit_lse=return_lse, masked=masked),
        interpret=interpret,
        grid=grid,
        in_specs=in_specs,
        out_specs=out_specs,
        out_shape=out_shape,
        scratch_shapes=[
            pltpu.VMEM((bq, LANES), jnp.float32),  # running max (lane-broadcast)
            pltpu.VMEM((bq, LANES), jnp.float32),  # running denom
            pltpu.VMEM((bq, D), jnp.float32),      # output accumulator
        ],
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary")),
    )(*operands)
    if return_lse:
        out, lse = res
        # keep only one lane as the residual (saving the full 128-lane
        # broadcast would hold 128x the memory across fwd→bwd)
        return out.reshape(B, H, Tq, D), lse[..., :1]
    return res[0].reshape(B, H, Tq, D)


def _dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, *rest,
               scale, causal, bq, bk, masked):
    if masked:
        vl_ref, dq_ref, dq_acc = rest
        vl = vl_ref[0, 0, 0]
    else:
        (dq_ref, dq_acc), vl = rest, None
    kv_idx = pl.program_id(2)
    q_idx = pl.program_id(1)

    @pl.when(kv_idx == 0)
    def _init():
        dq_acc[:] = jnp.zeros_like(dq_acc)

    run = True
    if causal:
        run = kv_idx * bk <= q_idx * bq + bq - 1
    if masked:
        run = jnp.logical_and(run, kv_idx * bk < vl)

    @pl.when(run)
    def _compute():
        k = k_ref[0]
        v = v_ref[0]
        do = do_ref[0]
        s = _scores(q_ref, k_ref, q_idx, kv_idx, scale=scale, causal=causal,
                    bq=bq, bk=bk, vl=vl)
        p = jnp.exp(s - lse_ref[0][:, :1])                       # (bq, bk)
        dp = jax.lax.dot_general(do, v, (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        ds = p * (dp - delta_ref[0][:, :1])
        # one fp32→native downcast of ds before the MXU matmul (FA2 recipe)
        dq_acc[:] += jax.lax.dot_general(
            ds.astype(k.dtype), k, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32) * scale

    @pl.when(kv_idx == pl.num_programs(2) - 1)
    def _finish():
        dq_ref[0] = dq_acc[:].astype(dq_ref.dtype)


def _dkv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, *rest,
                scale, causal, bq, bk, masked):
    if masked:
        vl_ref, dk_ref, dv_ref, dk_acc, dv_acc = rest
        vl = vl_ref[0, 0, 0]
    else:
        (dk_ref, dv_ref, dk_acc, dv_acc), vl = rest, None
    q_idx = pl.program_id(2)   # inner: sweep q blocks
    kv_idx = pl.program_id(1)  # outer: this kernel instance's k/v block

    @pl.when(q_idx == 0)
    def _init():
        dk_acc[:] = jnp.zeros_like(dk_acc)
        dv_acc[:] = jnp.zeros_like(dv_acc)

    run = True
    if causal:
        # q block contributes iff its last row >= first k row
        run = q_idx * bq + bq - 1 >= kv_idx * bk
    if masked:
        # whole K block past valid length → dk = dv = 0 there
        run = jnp.logical_and(run, kv_idx * bk < vl)

    @pl.when(run)
    def _compute():
        q = q_ref[0]
        v = v_ref[0]
        do = do_ref[0]
        s = _scores(q_ref, k_ref, q_idx, kv_idx, scale=scale, causal=causal,
                    bq=bq, bk=bk, vl=vl)
        p = jnp.exp(s - lse_ref[0][:, :1])                       # (bq, bk)
        # dv += p^T @ do — p downcast to the operand dtype for the MXU
        dv_acc[:] += jax.lax.dot_general(p.astype(do.dtype), do,
                                         (((0,), (0,)), ((), ())),
                                         preferred_element_type=jnp.float32)
        dp = jax.lax.dot_general(do, v, (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        ds = p * (dp - delta_ref[0][:, :1])                      # (bq, bk)
        # dk += ds^T @ q * scale
        dk_acc[:] += jax.lax.dot_general(ds.astype(q.dtype), q,
                                         (((0,), (0,)), ((), ())),
                                         preferred_element_type=jnp.float32) * scale

    @pl.when(q_idx == pl.num_programs(2) - 1)
    def _finish():
        dk_ref[0] = dk_acc[:].astype(dk_ref.dtype)
        dv_ref[0] = dv_acc[:].astype(dv_ref.dtype)


def _flash_bwd(q, k, v, o, lse, do, kv_valid_len, scale, causal, bq, bk,
               interpret=False):
    B, H, Tq, D = q.shape
    Tk = k.shape[2]
    bq = min(bq, Tq)
    bk = min(bk, Tk)
    qr = q.reshape(B * H, Tq, D)
    kr = k.reshape(B * H, Tk, D)
    vr = v.reshape(B * H, Tk, D)
    dor = do.reshape(B * H, Tq, D)
    masked = kv_valid_len is not None
    # delta_i = rowsum(dO ⊙ O); both row stats lane-broadcast to the
    # (bq, 128) layout transiently (the saved lse residual is 1-lane)
    delta = jnp.sum(o.astype(jnp.float32) * do.astype(jnp.float32), axis=-1)
    delta = jnp.broadcast_to(delta.reshape(B * H, Tq, 1), (B * H, Tq, LANES))
    lse = jnp.broadcast_to(lse, (B * H, Tq, LANES))

    spec_q = pl.BlockSpec((1, bq, D), lambda b, i, j: (b, i, 0))
    spec_kv_in = pl.BlockSpec((1, bk, D), lambda b, i, j: (b, j, 0))
    spec_row = pl.BlockSpec((1, bq, LANES), lambda b, i, j: (b, i, 0))
    spec_vl = pl.BlockSpec((1, 1, LANES), lambda b, i, j: (b, 0, 0))

    dq_in_specs = [spec_q, spec_kv_in, spec_kv_in, spec_q, spec_row, spec_row]
    dq_operands = [qr, kr, vr, dor, lse, delta]
    if masked:
        dq_in_specs.append(spec_vl)
        dq_operands.append(_vl_operand(kv_valid_len, B, H))
    dq = pl.pallas_call(
        functools.partial(_dq_kernel, scale=scale, causal=causal, bq=bq,
                          bk=bk, masked=masked),
        interpret=interpret,
        grid=(B * H, Tq // bq, Tk // bk),
        in_specs=dq_in_specs,
        out_specs=pl.BlockSpec((1, bq, D), lambda b, i, j: (b, i, 0)),
        out_shape=jax.ShapeDtypeStruct((B * H, Tq, D), q.dtype),
        scratch_shapes=[pltpu.VMEM((bq, D), jnp.float32)],
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary")),
    )(*dq_operands)

    # dk/dv: k block is the resident (outer) axis, q blocks stream (inner)
    spec_q_inner = pl.BlockSpec((1, bq, D), lambda b, i, j: (b, j, 0))
    spec_kv_outer = pl.BlockSpec((1, bk, D), lambda b, i, j: (b, i, 0))
    spec_row_inner = pl.BlockSpec((1, bq, LANES), lambda b, i, j: (b, j, 0))
    dkv_in_specs = [spec_q_inner, spec_kv_outer, spec_kv_outer, spec_q_inner,
                    spec_row_inner, spec_row_inner]
    dkv_operands = [qr, kr, vr, dor, lse, delta]
    if masked:
        dkv_in_specs.append(spec_vl)
        dkv_operands.append(_vl_operand(kv_valid_len, B, H))
    dk, dv = pl.pallas_call(
        functools.partial(_dkv_kernel, scale=scale, causal=causal, bq=bq,
                          bk=bk, masked=masked),
        interpret=interpret,
        grid=(B * H, Tk // bk, Tq // bq),
        in_specs=dkv_in_specs,
        out_specs=[
            pl.BlockSpec((1, bk, D), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, bk, D), lambda b, i, j: (b, i, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((B * H, Tk, D), k.dtype),
            jax.ShapeDtypeStruct((B * H, Tk, D), v.dtype),
        ],
        scratch_shapes=[pltpu.VMEM((bk, D), jnp.float32),
                        pltpu.VMEM((bk, D), jnp.float32)],
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary")),
    )(*dkv_operands)

    return (dq.reshape(B, H, Tq, D), dk.reshape(B, H, Tk, D),
            dv.reshape(B, H, Tk, D))


@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5, 6, 7, 8))
def _flash(q, k, v, kv_valid_len, scale, causal, bq, bk, interpret=False):
    return _flash_fwd(q, k, v, kv_valid_len, scale, causal, bq, bk,
                      interpret=interpret)


def _flash_vjp_fwd(q, k, v, kv_valid_len, scale, causal, bq, bk,
                   interpret=False):
    o, lse = _flash_fwd(q, k, v, kv_valid_len, scale, causal, bq, bk,
                        interpret=interpret, return_lse=True)
    return o, (q, k, v, kv_valid_len, o, lse)


def _flash_vjp_bwd(scale, causal, bq, bk, interpret, res, do):
    q, k, v, kv_valid_len, o, lse = res
    dq, dk, dv = _flash_bwd(q, k, v, o, lse, do, kv_valid_len, scale, causal,
                            bq, bk, interpret=interpret)
    return dq, dk, dv, None  # int valid-length carries no tangent


_flash.defvjp(_flash_vjp_fwd, _flash_vjp_bwd)


# Tuned (block_q, block_k) per sequence-length bucket — ONE table every
# caller picks up. Keys are the smallest seq the row applies to, scanned
# descending. The tuned table is DATA, not code: tools/flash_sweep.py
# measures candidates on hardware and (with --apply) writes the winners to
# flash_blocks.json next to this file; import picks it up. Until a sweep
# lands, the single fallback row is the VMEM-friendly 256x512 point.
BLOCK_DEFAULTS = {
    0: (256, 512),
}

# Measured flash-vs-dense crossover seq from the sweep artifact ("min_len"),
# or None until a hardware sweep lands — attention.py's gate falls back to
# its static _FLASH_MIN_LEN guess while this is None.
MIN_LEN = None

_BLOCKS_ARTIFACT = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "flash_blocks.json")

# provenance of the loaded artifact (tuned_by/swept_at/backend/source) —
# empty until _load_block_artifact succeeds; diagnose and the interim
# warning read it
_ARTIFACT_META = {}

_INTERIM_WARNED = False


def _warn_if_interim():
    """Warn ONCE per process when serving from an interim table — either
    no artifact loaded at all, or one whose ``swept_at`` is null (hand-
    authored placeholder, never measured on hardware). Tuned tables from
    flash_sweep --apply or ir.tune.tune_flash_blocks carry a timestamp
    and stay silent."""
    global _INTERIM_WARNED
    if _INTERIM_WARNED:
        return
    if _ARTIFACT_META.get("swept_at"):
        return
    _INTERIM_WARNED = True
    import warnings

    warnings.warn(
        "flash_attention is serving an INTERIM block table (%s) — blocks "
        "were never measured on this hardware; run tools/flash_sweep.py "
        "--apply or ir.tune.tune_flash_blocks(apply=True) to tune them"
        % (_ARTIFACT_META.get("source") or "built-in fallback"))


def write_block_artifact(blocks, source, swept_at=None, tuned_by=None,
                         backend=None, min_len=None, note=None, path=None):
    """THE writer for flash_blocks.json — flash_sweep --apply and
    ir.tune.tune_flash_blocks both emit through here, so the two formats
    cannot diverge. Validates the table shape, writes atomically
    (tmp + os.replace), reloads the live BLOCK_DEFAULTS, and returns the
    artifact dict."""
    table = {}
    for seq, blk in dict(blocks).items():
        bq, bk = int(blk[0]), int(blk[1])
        if bq <= 0 or bk <= 0:
            raise ValueError("non-positive block pair %r for seq %r"
                             % (blk, seq))
        table[str(int(seq))] = [bq, bk]
    if not table:
        raise ValueError("refusing to write an empty block table")
    if "0" not in table:
        raise ValueError("block table needs a catch-all '0' row")
    artifact = {
        "blocks": {k: table[k] for k in sorted(table, key=int)},
        "min_len": int(min_len) if min_len is not None else None,
        "source": source,
        "tuned_by": tuned_by,
        "swept_at": swept_at,
        "backend": backend,
        "note": note,
    }
    out = path or _BLOCKS_ARTIFACT
    tmp = out + ".tmp"
    with open(tmp, "w") as f:
        json.dump(artifact, f, indent=1, sort_keys=True)
        f.write("\n")
    os.replace(tmp, out)
    _load_block_artifact(out)
    return artifact


def _load_block_artifact(path=None):
    """Replace BLOCK_DEFAULTS with the committed hardware-sweep winners.

    The artifact maps seq-bucket lower bounds to [block_q, block_k] and
    carries provenance ("swept_at", "source"). An ABSENT default artifact
    leaves the fallback table untouched silently (tuning must never break
    import); a PRESENT-but-malformed file warns — a corrupted
    ``flash_sweep --apply`` output silently reverting every bench to the
    untuned table is exactly the failure that must not be quiet (ADVICE
    r4). An explicit ``path`` argument raises on any failure: the caller
    asked for that file specifically."""
    global BLOCK_DEFAULTS, MIN_LEN, _ARTIFACT_META, _INTERIM_WARNED
    explicit = path is not None
    path = path or _BLOCKS_ARTIFACT
    if not os.path.exists(path):
        if explicit:
            raise FileNotFoundError("flash block artifact %r not found" % path)
        return False
    try:
        with open(path) as f:
            raw = json.load(f)
        table = {int(k): (int(v[0]), int(v[1]))
                 for k, v in raw["blocks"].items()}
        if not table:
            raise ValueError("empty 'blocks' table")
    except Exception as e:
        if explicit:
            raise ValueError(
                "flash block artifact %r is malformed: %s" % (path, e)) from e
        import warnings

        warnings.warn(
            "ignoring malformed flash block artifact %s (%s); "
            "falling back to the untuned table" % (path, e))
        return False
    BLOCK_DEFAULTS = table
    # reset too: a reloaded artifact without min_len must not leave a stale
    # crossover from a superseded sweep paired with the new block table
    MIN_LEN = raw["min_len"] if isinstance(raw.get("min_len"), int) else None
    # fixed-key provenance record (replaced whole on every load, GL006-safe)
    _ARTIFACT_META = dict(
        {k: raw.get(k) for k in
         ("source", "tuned_by", "swept_at", "backend", "note")},
        path=path)
    # a freshly tuned table may land mid-process: re-arm the interim check
    _INTERIM_WARNED = False
    return True


_load_block_artifact()


def _default_blocks(seq):
    for lo in sorted(BLOCK_DEFAULTS, reverse=True):
        if seq >= lo:
            return BLOCK_DEFAULTS[lo]
    return BLOCK_DEFAULTS[min(BLOCK_DEFAULTS)]


def flash_attention(q, k, v, causal=False, scale=None, block_q=None,
                    block_k=None, interpret=False, kv_valid_len=None):
    """q,k,v: (B, H, T, D). D should be a multiple of 128 lanes ideally;
    T must be divisible by the chosen blocks (callers pad).

    block_q/block_k default from the seq-bucketed BLOCK_DEFAULTS table
    (where the committed hardware sweep lands its winners).

    kv_valid_len: optional (B,) int — BERT-style key-padding: each example
    attends only to K/V positions < its valid length (columns beyond are
    masked AND their blocks skipped entirely, forward and backward)."""
    if scale is None:
        scale = 1.0 / (q.shape[-1] ** 0.5)
    if block_q is None or block_k is None:
        _warn_if_interim()  # explicit blocks aren't served from the table
    Tq, Tk = q.shape[2], k.shape[2]
    # bucket each axis by ITS length: cross-attention (short queries, long
    # keys) must not take the long-seq row's block_q
    if block_q is None:
        block_q = _default_blocks(Tq)[0]
    if block_k is None:
        block_k = _default_blocks(Tk)[1]
    bq = _largest_divisor_block(Tq, block_q)
    bk = _largest_divisor_block(Tk, block_k)
    return _flash(q, k, v, kv_valid_len, float(scale), bool(causal), bq, bk,
                  interpret)


def _largest_divisor_block(t, prefer):
    b = min(prefer, t)
    while t % b:
        b //= 2
    return max(b, 1)
