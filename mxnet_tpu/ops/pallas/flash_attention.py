"""Flash attention Pallas kernel for TPU.

Replaces the reference's unfused softmax(QK^T)V chain (three HBM round trips
for the T×T score matrix) with a blockwise kernel: Q blocks stay resident in
VMEM while K/V blocks stream through, online-softmax accumulating in fp32
scratch — O(T) HBM traffic instead of O(T^2). Grid (B*H, Tq/bq, Tk/bk) with
the K dimension innermost ("arbitrary" semantics) so the accumulator carries
across K steps. Custom VJP recomputes attention blockwise in the backward
(flash-attention-2 style) so no T×T tensor ever materializes.

Pattern source: /opt/skills/guides/pallas_guide.md (double-buffered matmul,
custom-VJP kernels). Falls back to the jnp reference off-TPU (ops/attention.py).
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

NEG_INF = -1e30


def _fwd_kernel(q_ref, k_ref, v_ref, o_ref, m_ref, l_ref, acc_ref,
                *, scale, causal, bq, bk):
    kv_idx = pl.program_id(2)
    q_idx = pl.program_id(1)

    @pl.when(kv_idx == 0)
    def _init():
        m_ref[:] = jnp.full_like(m_ref, NEG_INF)
        l_ref[:] = jnp.zeros_like(l_ref)
        acc_ref[:] = jnp.zeros_like(acc_ref)

    run = True
    if causal:
        # skip fully-masked K blocks: first query row of this Q block is
        # q_idx*bq; block contributes iff kv_idx*bk <= q_idx*bq + bq - 1
        run = kv_idx * bk <= q_idx * bq + bq - 1

    @pl.when(run if causal else True)
    def _compute():
        q = q_ref[0].astype(jnp.float32) * scale  # (bq, d)
        k = k_ref[0].astype(jnp.float32)          # (bk, d)
        v = v_ref[0].astype(jnp.float32)          # (bk, d)
        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32)  # (bq, bk)
        if causal:
            rows = q_idx * bq + jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 0)
            cols = kv_idx * bk + jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 1)
            s = jnp.where(rows >= cols, s, NEG_INF)
        m_prev = m_ref[:]                       # (bq, 128) broadcast lanes
        m_cur = jnp.max(s, axis=1, keepdims=True)  # (bq, 1)
        m_new = jnp.maximum(m_prev, jnp.broadcast_to(m_cur, m_prev.shape))
        corr = jnp.exp(m_prev - m_new)
        p = jnp.exp(s - m_new[:, :1])           # (bq, bk)
        l_ref[:] = l_ref[:] * corr + jnp.broadcast_to(
            jnp.sum(p, axis=1, keepdims=True), m_prev.shape)
        acc_ref[:] = acc_ref[:] * corr[:, :1] + jax.lax.dot_general(
            p, v, (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32)
        m_ref[:] = m_new

    @pl.when(kv_idx == pl.num_programs(2) - 1)
    def _finish():
        o_ref[0] = (acc_ref[:] / l_ref[:, :1]).astype(o_ref.dtype)


def _flash_fwd(q, k, v, scale, causal, bq, bk, interpret=False):
    B, H, Tq, D = q.shape
    Tk = k.shape[2]
    bq = min(bq, Tq)
    bk = min(bk, Tk)
    qr = q.reshape(B * H, Tq, D)
    kr = k.reshape(B * H, Tk, D)
    vr = v.reshape(B * H, Tk, D)
    grid = (B * H, Tq // bq, Tk // bk)
    out = pl.pallas_call(
        functools.partial(_fwd_kernel, scale=scale, causal=causal, bq=bq, bk=bk),
        interpret=interpret,
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, bq, D), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, bk, D), lambda b, i, j: (b, j, 0)),
            pl.BlockSpec((1, bk, D), lambda b, i, j: (b, j, 0)),
        ],
        out_specs=pl.BlockSpec((1, bq, D), lambda b, i, j: (b, i, 0)),
        out_shape=jax.ShapeDtypeStruct((B * H, Tq, D), q.dtype),
        scratch_shapes=[
            pltpu.VMEM((bq, 128), jnp.float32),  # running max (lane-broadcast)
            pltpu.VMEM((bq, 128), jnp.float32),  # running denom
            pltpu.VMEM((bq, D), jnp.float32),    # output accumulator
        ],
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary")),
    )(qr, kr, vr)
    return out.reshape(B, H, Tq, D)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6))
def _flash(q, k, v, scale, causal, bq, bk):
    return _flash_fwd(q, k, v, scale, causal, bq, bk)


def _flash_vjp_fwd(q, k, v, scale, causal, bq, bk):
    o = _flash_fwd(q, k, v, scale, causal, bq, bk)
    return o, (q, k, v, o)


def _flash_vjp_bwd(scale, causal, bq, bk, res, do):
    # Blockwise recompute backward in plain XLA (fused well by Mosaic/XLA);
    # a dedicated pallas backward kernel is an r2 perf item.
    q, k, v, o = res
    qf = q.astype(jnp.float32) * scale
    kf = k.astype(jnp.float32)
    vf = v.astype(jnp.float32)
    of = o.astype(jnp.float32)
    dof = do.astype(jnp.float32)
    s = jnp.einsum("bhqd,bhkd->bhqk", qf, kf)
    if causal:
        T, S = s.shape[-2], s.shape[-1]
        mask = jnp.arange(T)[:, None] >= jnp.arange(S)[None, :]
        s = jnp.where(mask[None, None], s, NEG_INF)
    p = jax.nn.softmax(s, axis=-1)
    dv = jnp.einsum("bhqk,bhqd->bhkd", p, dof)
    dp = jnp.einsum("bhqd,bhkd->bhqk", dof, vf)
    delta = jnp.sum(of * dof, axis=-1, keepdims=True)
    ds = p * (dp - delta)
    dq = jnp.einsum("bhqk,bhkd->bhqd", ds, kf) * scale
    dk = jnp.einsum("bhqk,bhqd->bhkd", ds, qf)
    return dq.astype(q.dtype), dk.astype(k.dtype), dv.astype(v.dtype)


_flash.defvjp(_flash_vjp_fwd, _flash_vjp_bwd)


def flash_attention(q, k, v, causal=False, scale=None, block_q=256, block_k=512):
    """q,k,v: (B, H, T, D). D should be a multiple of 128 lanes ideally;
    T must be divisible by the chosen blocks (callers pad)."""
    if scale is None:
        scale = 1.0 / (q.shape[-1] ** 0.5)
    Tq, Tk = q.shape[2], k.shape[2]
    bq = _largest_divisor_block(Tq, block_q)
    bk = _largest_divisor_block(Tk, block_k)
    return _flash(q, k, v, float(scale), bool(causal), bq, bk)


def _largest_divisor_block(t, prefer):
    b = min(prefer, t)
    while t % b:
        b //= 2
    return max(b, 1)
