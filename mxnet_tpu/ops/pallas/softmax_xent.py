"""Fused softmax cross-entropy Pallas kernel.

MXNet fuses softmax+grad in SoftmaxOutput's CUDA kernels (ref:
src/operator/softmax_output.cu); for the LM/BERT loss the hot pattern is
logits (N, V≈30k) → per-row NLL. Done naively that is three HBM sweeps of the
logits (max, sum-exp, gather). This kernel produces loss AND logsumexp in one
VMEM-resident pass per row block; the backward kernel forms
``(softmax − onehot)·dy`` in one more pass, reusing the saved lse instead of
recomputing the reduction. fp32 math inside regardless of logits dtype.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl


def _fwd_kernel(x_ref, l_ref, loss_ref, lse_ref):
    x = x_ref[:].astype(jnp.float32)            # (br, V)
    lab = l_ref[:]                              # (br, 1) int32
    m = jnp.max(x, axis=-1, keepdims=True)
    s = jnp.sum(jnp.exp(x - m), axis=-1, keepdims=True)
    lse = jnp.log(s) + m                        # (br, 1)
    cols = jax.lax.broadcasted_iota(jnp.int32, x.shape, 1)
    picked = jnp.sum(jnp.where(cols == lab, x, 0.0), axis=-1, keepdims=True)
    loss_ref[:] = (lse - picked).astype(loss_ref.dtype)
    lse_ref[:] = lse.astype(lse_ref.dtype)


def _bwd_kernel(x_ref, l_ref, lse_ref, dy_ref, dx_ref):
    x = x_ref[:].astype(jnp.float32)
    lab = l_ref[:]
    lse = lse_ref[:]
    dy = dy_ref[:]
    p = jnp.exp(x - lse)                        # softmax via saved lse
    cols = jax.lax.broadcasted_iota(jnp.int32, x.shape, 1)
    onehot = (cols == lab).astype(jnp.float32)
    dx_ref[:] = ((p - onehot) * dy).astype(dx_ref.dtype)


def _block_rows(R, V, want=128, vmem_budget=2 << 20):
    """Rows per block, capped so one fp32 logits block stays within a VMEM
    budget (double-buffered pipelining means the real footprint is ~2x) —
    at V≈30k that is ~16 rows, not 128."""
    cap = max(1, vmem_budget // (V * 4))
    br = min(want, cap, R)
    while R % br:
        br -= 1
    return max(br, 1)


_PAD_NEG = -1e30  # finite: exp(_PAD_NEG - m) underflows to 0, no inf-inf NaN


def _pad_lanes(logits):
    """Lane-align V to a multiple of 128 by padding with a large negative
    constant. Mosaic's guarantees are simplest (and fastest) for aligned
    lane dims, and real vocabularies (BERT 30522, GPT-2 50257) are NOT
    aligned — padding costs one fused pad (+<0.3% lanes) and keeps the
    kernel itself aligned by construction. Padded lanes contribute
    exp(-1e30 - m) = 0 to the row lse and can never be a label."""
    V = logits.shape[-1]
    pad = (-V) % 128
    if pad:
        logits = jnp.pad(logits, ((0, 0), (0, pad)), constant_values=_PAD_NEG)
    return logits, V


def _run_fwd(logits, labels, interpret=False):
    R, V = logits.shape
    br = _block_rows(R, V)
    lab2 = labels.astype(jnp.int32).reshape(R, 1)
    loss, lse = pl.pallas_call(
        _fwd_kernel,
        interpret=interpret,
        grid=(R // br,),
        in_specs=[pl.BlockSpec((br, V), lambda i: (i, 0)),
                  pl.BlockSpec((br, 1), lambda i: (i, 0))],
        out_specs=[pl.BlockSpec((br, 1), lambda i: (i, 0)),
                   pl.BlockSpec((br, 1), lambda i: (i, 0))],
        out_shape=[jax.ShapeDtypeStruct((R, 1), jnp.float32),
                   jax.ShapeDtypeStruct((R, 1), jnp.float32)],
    )(logits, lab2)
    return loss[:, 0], lse


def _run_bwd(logits, labels, lse, dy, interpret=False):
    R, V = logits.shape
    br = _block_rows(R, V)
    lab2 = labels.astype(jnp.int32).reshape(R, 1)
    dy2 = dy.astype(jnp.float32).reshape(R, 1)
    return pl.pallas_call(
        _bwd_kernel,
        interpret=interpret,
        grid=(R // br,),
        in_specs=[pl.BlockSpec((br, V), lambda i: (i, 0)),
                  pl.BlockSpec((br, 1), lambda i: (i, 0)),
                  pl.BlockSpec((br, 1), lambda i: (i, 0)),
                  pl.BlockSpec((br, 1), lambda i: (i, 0))],
        out_specs=pl.BlockSpec((br, V), lambda i: (i, 0)),
        out_shape=jax.ShapeDtypeStruct((R, V), logits.dtype),
    )(logits, lab2, lse, dy2)


@functools.partial(jax.custom_vjp, nondiff_argnums=(2,))
def softmax_xent(logits, labels, interpret=False):
    """Per-row NLL of int labels under softmax(logits). logits (N, V) any
    float dtype and ANY V (lane-aligned internally), labels (N,) int.
    Returns (N,) fp32."""
    padded, _ = _pad_lanes(logits)
    return _run_fwd(padded, labels, interpret)[0]


def _sx_fwd(logits, labels, interpret):
    padded, v_real = _pad_lanes(logits)
    loss, lse = _run_fwd(padded, labels, interpret)
    return loss, (padded, v_real, labels, lse)


def _sx_bwd(interpret, res, dy):
    padded, v_real, labels, lse = res
    dx = _run_bwd(padded, labels, lse, dy, interpret)
    # padded lanes carry p·dy (p=0 there), so the slice drops exact zeros
    return dx[:, :v_real], None


softmax_xent.defvjp(_sx_fwd, _sx_bwd)
