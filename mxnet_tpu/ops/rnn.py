"""Fused recurrent op via lax.scan.

TPU-native replacement for MXNet's fused RNN operator (ref:
src/operator/rnn.cc, which dispatches to cuDNN RNN on GPU). On TPU the whole
multi-layer (bi)directional recurrence compiles to nested lax.scan — XLA keeps
the per-step matmuls on the MXU and the carried state in registers/VMEM, which
is the analogue of cuDNN's persistent-RNN kernels. Weights are per-layer
matrices (not cuDNN's packed 1-D blob): that keeps shardings natural for tp.

Gate orders follow MXNet: LSTM [i, f, g, o], GRU [r, z, n]
(ref: src/operator/rnn-inl.h).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from ..base import register_op


def _lstm_step(h, c, xw, whh, bhh):
    g = xw + jnp.matmul(h, whh.T) + bhh
    i, f, gg, o = jnp.split(g, 4, axis=-1)
    i = jax.nn.sigmoid(i)
    f = jax.nn.sigmoid(f)
    gg = jnp.tanh(gg)
    o = jax.nn.sigmoid(o)
    c = f * c + i * gg          # cell state keeps ITS OWN dtype (callers may
    h = o * jnp.tanh(c)         # deliberately carry c in f32 under AMP)
    return h.astype(xw.dtype), c


def _gru_step(h, xw, whh, bhh):
    hw = jnp.matmul(h, whh.T) + bhh
    xr, xz, xn = jnp.split(xw, 3, axis=-1)
    hr, hz, hn = jnp.split(hw, 3, axis=-1)
    r = jax.nn.sigmoid(xr + hr)
    z = jax.nn.sigmoid(xz + hz)
    n = jnp.tanh(xn + r * hn)
    return (1 - z) * n + z * h


def _rnn_relu_step(h, xw, whh, bhh, act):
    return act(xw + jnp.matmul(h, whh.T) + bhh)


def _single_direction(x, h0, c0, wih, whh, bih, bhh, mode):
    """x: (T, N, C); h0/c0: (N, H). Precompute input projections as one big
    matmul (MXU-friendly), scan only the recurrent part."""
    xw = jnp.einsum("tnc,gc->tng", x, wih) + bih  # (T, N, G*H)

    if mode == "lstm":
        def step(carry, xt):
            h, c = carry
            h, c = _lstm_step(h, c, xt, whh, bhh)
            return (h, c), h

        (h, c), ys = lax.scan(step, (h0, c0), xw)
        return ys, h, c
    if mode == "gru":
        def step(h, xt):
            h = _gru_step(h, xt, whh, bhh)
            return h, h

        h, ys = lax.scan(step, h0, xw)
        return ys, h, c0
    act = jnp.tanh if mode == "rnn_tanh" else jax.nn.relu

    def step(h, xt):
        h = _rnn_relu_step(h, xt, whh, bhh, act)
        return h, h

    h, ys = lax.scan(step, h0, xw)
    return ys, h, c0


@register_op("_rnn_init")
def _rnn_init(x, *, num, hidden):
    """Zero initial state (num, N, H) shaped from x (T, N, C) — used by the
    ONNX importer when a recurrent node omits initial_h/initial_c (shape is
    static under jit, so this stays XLA-friendly)."""
    return jnp.zeros((num, x.shape[1], hidden), x.dtype)


@register_op("RNN", needs_rng=True, needs_training=True, n_outputs=3)
def RNN(x, state_h, state_c, *weights, mode="lstm", num_layers=1,
        bidirectional=False, p=0.0, training=False, key=None):
    """x: (T, N, C); state_h/state_c: (L*D, N, H);
    weights: per (layer, direction): i2h_w, h2h_w, i2h_b, h2h_b.
    Returns (out (T, N, H*D), new_h, new_c)."""
    D = 2 if bidirectional else 1
    # h (the matmul operand) follows the input dtype: f32 default initial
    # states would otherwise promote every recurrent h@Whh matmul (and with
    # it the whole scan body) to f32 under AMP — measured as 12 of the
    # LSTM-PTB step's 15 dots before this cast. c is NOT cast: the cell
    # state only flows through elementwise VPU math, so a caller-provided
    # f32 c keeps full-precision accumulation across the sequence.
    state_h = state_h.astype(x.dtype)
    out = x
    hs, cs = [], []
    wi = 0
    for layer in range(num_layers):
        layer_outs = []
        for d in range(D):
            idx = layer * D + d
            wih, whh, bih, bhh = weights[wi:wi + 4]
            wi += 4
            inp = jnp.flip(out, axis=0) if d == 1 else out
            ys, h, c = _single_direction(inp, state_h[idx], state_c[idx], wih, whh, bih, bhh, mode)
            if d == 1:
                ys = jnp.flip(ys, axis=0)
            layer_outs.append(ys)
            hs.append(h)
            cs.append(c)
        out = jnp.concatenate(layer_outs, axis=-1) if D == 2 else layer_outs[0]
        if p > 0.0 and training and key is not None and layer < num_layers - 1:
            k = jax.random.fold_in(key, layer)
            mask = jax.random.bernoulli(k, 1.0 - p, out.shape)
            out = jnp.where(mask, out / (1.0 - p), 0.0).astype(out.dtype)
    return out, jnp.stack(hs), jnp.stack(cs)
