"""CTC loss (ref: src/operator/contrib/ctc_loss.cc — warp-ctc CUDA replaced by
a lax.scan forward algorithm in log space; XLA keeps the whole recursion in
one compiled loop, gradients come from autodiff of the scan)."""
from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from ..base import register_op

NEG = -1e30


def _logsumexp3(a, b, c):
    m = jnp.maximum(jnp.maximum(a, b), c)
    m_safe = jnp.where(m > NEG / 2, m, 0.0)
    # bound every exponent so grads stay finite when a branch is -inf-like
    sa = jnp.where(a > NEG / 2, a - m_safe, -40.0)
    sb = jnp.where(b > NEG / 2, b - m_safe, -40.0)
    sc = jnp.where(c > NEG / 2, c - m_safe, -40.0)
    out = m_safe + jnp.log(jnp.exp(sa) + jnp.exp(sb) + jnp.exp(sc))
    return jnp.where(m > NEG / 2, out, NEG)


@register_op("CTCLoss")
def CTCLoss(pred, label, pred_lengths=None, label_lengths=None, *, blank=0):
    """pred: (N, T, V) unnormalized; label: (N, L) int (padded with -1 or any
    value beyond label_lengths); returns per-sample loss (N,).
    Follows mx.gluon.loss.CTCLoss semantics with blank_label='first'."""
    N, T, V = pred.shape
    L = label.shape[1]
    S = 2 * L + 1
    logp = jax.nn.log_softmax(pred.astype(jnp.float32), axis=-1)
    label = label.astype(jnp.int32)
    if pred_lengths is None:
        pred_lengths = jnp.full((N,), T, jnp.int32)
    else:
        pred_lengths = pred_lengths.astype(jnp.int32)
    if label_lengths is None:
        label_lengths = jnp.full((N,), L, jnp.int32)
    else:
        label_lengths = label_lengths.astype(jnp.int32)

    # extended label sequence: blank, l1, blank, l2, ..., blank
    s_idx = jnp.arange(S)
    ext = jnp.where(s_idx % 2 == 0, blank,
                    jnp.take_along_axis(label, jnp.maximum((s_idx[None, :] - 1) // 2, 0),
                                        axis=1))  # (N, S) via broadcast
    ext = jnp.broadcast_to(ext, (N, S))
    # allow skip transition s-2 → s when ext[s] != ext[s-2] and ext[s] != blank
    ext_m2 = jnp.pad(ext, ((0, 0), (2, 0)), constant_values=blank)[:, :S]
    can_skip = (ext != blank) & (ext != ext_m2)

    alpha0 = jnp.full((N, S), NEG)
    alpha0 = alpha0.at[:, 0].set(logp[:, 0, blank])
    alpha0 = alpha0.at[:, 1].set(
        jnp.take_along_axis(logp[:, 0], ext[:, 1:2], axis=1)[:, 0])

    def step(alpha, t):
        lp_t = jnp.take_along_axis(logp[:, t], ext, axis=1)  # (N, S)
        a1 = jnp.pad(alpha, ((0, 0), (1, 0)), constant_values=NEG)[:, :S]
        a2 = jnp.pad(alpha, ((0, 0), (2, 0)), constant_values=NEG)[:, :S]
        a2 = jnp.where(can_skip, a2, NEG)
        new = _logsumexp3(alpha, a1, a2) + lp_t
        # freeze past each sample's input length
        active = (t < pred_lengths)[:, None]
        new = jnp.where(active, new, alpha)
        return new, None

    alpha, _ = lax.scan(step, alpha0, jnp.arange(1, T))
    # final: logsumexp of positions 2*label_len and 2*label_len - 1
    end = 2 * label_lengths
    a_end = jnp.take_along_axis(alpha, end[:, None], axis=1)[:, 0]
    a_end1 = jnp.take_along_axis(alpha, jnp.maximum(end - 1, 0)[:, None], axis=1)[:, 0]
    m = jnp.maximum(a_end, a_end1)
    m_safe = jnp.where(m > NEG / 2, m, 0.0)
    se = jnp.where(a_end > NEG / 2, a_end - m_safe, -40.0)
    se1 = jnp.where(a_end1 > NEG / 2, a_end1 - m_safe, -40.0)
    ll = m_safe + jnp.log(jnp.exp(se) + jnp.exp(se1))
    return -ll
