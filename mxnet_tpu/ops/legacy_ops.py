"""Flat legacy registry names: linalg_*, random_*/sample_*, optimizer
*_update kernels, and remaining aliases (ref: src/operator/tensor/la_op.cc,
src/operator/random/sample_op.cc, src/operator/optimizer_op.cc).

MXNet exposes every one of these as a flat nd/sym op; the structured
namespaces (mx.linalg, nd.random, mx.optimizer) are this repo's primary
surfaces, and these wrappers keep old call sites working. Optimizer kernels
are PURE here (return (new_weight, *new_states)), which is what jit wants;
the nd facade restores MXNet's in-place contract by writing the returned
states back into the state arguments and honoring out= for the weight
(see nd/__init__.py _UPDATE_STATE_ARGS).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ..base import register_op, resolve_dtype
from . import functional as F

# ---------------------------------------------------------------- aliases
register_op("stop_gradient")(F.BlockGrad)
register_op("sum_axis")(F.sum)
register_op("crop")(F.slice)            # historical name of slice (matrix_op.cc)
register_op("Pad")(F.pad)
register_op("Convolution_v1")(F.Convolution)
register_op("Pooling_v1")(F.Pooling)


@register_op("log_sigmoid")
def log_sigmoid(x):
    return jax.nn.log_sigmoid(x)


@register_op("mish")
def mish(x):
    """x · tanh(softplus(x)) (ref: mxnet 2.x leakyrelu.cc mish mode)."""
    return x * jnp.tanh(jax.nn.softplus(x))


@register_op("multi_all_finite", nondiff=True)
def multi_all_finite(*arrays, num_arrays=None, init_output=True):
    """1.0 iff every element of every input is finite (ref:
    contrib/all_finite.cc) — the AMP overflow check as ONE fused reduction."""
    ok = jnp.bool_(True) if init_output else None
    for a in arrays:
        fin = jnp.isfinite(a).all()
        ok = fin if ok is None else jnp.logical_and(ok, fin)
    return ok.astype(jnp.float32).reshape(1)


@register_op("multi_sum_sq", nondiff=True)
def multi_sum_sq(*arrays, num_arrays=None):
    """Per-array sum of squares, stacked (ref: contrib/multi_sum_sq.cc —
    the LARS/clip-global-norm building block)."""
    return jnp.stack([jnp.sum(jnp.square(a.astype(jnp.float32)))
                      for a in arrays])


# ---------------------------------------------------------------- linalg_*
# Thin registry fronts over linalg.py's k_* kernels — one algorithm, two
# surfaces. Differentiable (MXNet's la_ops have gradients; jnp provides them).
def _reg_linalg(name, fn, n_outputs=1):
    register_op(name, n_outputs=n_outputs)(fn)


from .. import linalg as _la  # noqa: E402  (kernel sharing, no cycle)

_reg_linalg("linalg_gemm2", lambda a, b, *, transpose_a=False,
            transpose_b=False, alpha=1.0:
            _la.k_gemm2(a, b, transpose_a, transpose_b, alpha))


def _linalg_gemm(a, b, c, *, transpose_a=False, transpose_b=False, alpha=1.0,
                 beta=1.0):
    return _la.k_gemm2(a, b, transpose_a, transpose_b, alpha) + beta * c


_reg_linalg("linalg_gemm", _linalg_gemm)
_reg_linalg("linalg_potrf", lambda a: jnp.linalg.cholesky(a))
_reg_linalg("linalg_potri", lambda a: _la.k_potri(a))
_reg_linalg("linalg_det", lambda a: jnp.linalg.det(a))
_reg_linalg("linalg_inverse", lambda a: jnp.linalg.inv(a))
_reg_linalg("linalg_slogdet", lambda a: jnp.linalg.slogdet(a), n_outputs=2)
_reg_linalg("linalg_sumlogdiag", lambda a: jnp.sum(
    jnp.log(jnp.diagonal(a, axis1=-2, axis2=-1)), axis=-1))
_reg_linalg("linalg_extractdiag", lambda a, *, offset=0:
            jnp.diagonal(a, offset=offset, axis1=-2, axis2=-1))
_reg_linalg("linalg_makediag", lambda a, *, offset=0:
            _makediag(a, offset))
_reg_linalg("linalg_syrk", lambda a, *, transpose=False, alpha=1.0:
            _la.k_syrk(a, transpose, alpha))
_reg_linalg("linalg_trmm", lambda a, b, *, transpose=False, rightside=False,
            lower=True, alpha=1.0:
            _la.k_trmm(jnp.tril(a) if lower else jnp.triu(a), b,
                       transpose, rightside, alpha))
_reg_linalg("linalg_trsm", lambda a, b, *, transpose=False, rightside=False,
            lower=True, alpha=1.0:
            _la.k_trsm(jnp.tril(a) if lower else jnp.triu(a), b,
                       transpose, rightside, alpha, lower))
_reg_linalg("linalg_gelqf", lambda a: _la.k_gelqf(a), n_outputs=2)


def _makediag(a, offset):
    n = a.shape[-1] + abs(offset)
    out = jnp.zeros(a.shape[:-1] + (n, n), a.dtype)
    idx = jnp.arange(a.shape[-1])
    r = idx + max(-offset, 0)
    c = idx + max(offset, 0)
    return out.at[..., r, c].set(a)


def _trian_indices(n, offset, lower):
    """MXNet la_op contract: offset picks WHICH triangle — offset>0 the
    super-diagonal triangle starting at that diagonal, offset<0 the
    sub-diagonal one (``lower`` is only consulted at offset 0)."""
    import numpy as onp

    if offset > 0:
        return onp.triu_indices(n, offset)
    if offset < 0:
        # triangle BELOW diagonal `offset`: rows-cols >= -offset
        rows, cols = onp.tril_indices(n, offset)
        return rows, cols
    return onp.tril_indices(n, 0) if lower else onp.triu_indices(n, 0)


def _extracttrian(a, *, offset=0, lower=True):
    rows, cols = _trian_indices(a.shape[-1], offset, lower)
    return a[..., rows, cols]


_reg_linalg("linalg_extracttrian", _extracttrian)


def _maketrian(a, *, offset=0, lower=True):
    # invert the count: find n whose offset-triangle holds exactly m entries
    m = a.shape[-1]
    n = 1
    while len(_trian_indices(n, offset, lower)[0]) < m:
        n += 1
    rows, cols = _trian_indices(n, offset, lower)
    out = jnp.zeros(a.shape[:-1] + (n, n), a.dtype)
    return out.at[..., rows, cols].set(a)


_reg_linalg("linalg_maketrian", _maketrian)


# ------------------------------------------------------------ random_* ops
def _rand_shape(shape):
    if isinstance(shape, int):
        return (shape,)
    return tuple(shape)


def _reg_random(name, sampler):
    @register_op(name, needs_rng=True, nondiff=True)
    def op(*, shape=(1,), dtype="float32", ctx=None, key=None, **kw):
        dt = resolve_dtype(dtype) or jnp.float32
        return sampler(key, _rand_shape(shape), dt, **kw)

    op.__name__ = name
    return op


from . import rand_kernels as _rk  # noqa: E402  (shared with nd.random)

_reg_random("random_uniform",
            lambda key, shp, dt, low=0.0, high=1.0:
            _rk.k_uniform(key, shp, dt, low, high))
_reg_random("random_normal",
            lambda key, shp, dt, loc=0.0, scale=1.0:
            _rk.k_normal(key, shp, dt, loc, scale))
_reg_random("random_exponential",
            lambda key, shp, dt, lam=1.0:
            _rk.k_exponential(key, shp, dt, 1.0 / lam))
_reg_random("random_gamma",
            lambda key, shp, dt, alpha=1.0, beta=1.0:
            _rk.k_gamma(key, shp, dt, alpha, beta))
_reg_random("random_poisson",
            lambda key, shp, dt, lam=1.0: _rk.k_poisson(key, shp, dt, lam))
_reg_random("random_negative_binomial",
            lambda key, shp, dt, k=1, p=0.5:
            _rk.k_negative_binomial(key, shp, dt, k, p))



def _k_gnb(key, shp, dt, mu, alpha):
    """Gamma-Poisson mixture (ref: sample_op.cc
    GeneralizedNegativeBinomialSampler): lam ~ Gamma(1/alpha, mu*alpha),
    x ~ Poisson(lam); alpha == 0 is the Poisson(mu) limit (upstream's
    degenerate case)."""
    if alpha <= 0:
        return jax.random.poisson(key, mu, shp).astype(dt)
    k1, k2 = jax.random.split(key)
    lam = jax.random.gamma(k1, 1.0 / alpha, shp) * (mu * alpha)
    return jax.random.poisson(k2, lam, shp).astype(dt)


_reg_random("random_generalized_negative_binomial",
            lambda key, shp, dt, mu=1.0, alpha=1.0:
            _k_gnb(key, shp, dt, mu, alpha))


@register_op("random_randint", needs_rng=True, nondiff=True)
def random_randint(*, low, high, shape=(1,), dtype="int32", ctx=None,
                   key=None):
    return _rk.k_randint(key, _rand_shape(shape),
                         resolve_dtype(dtype) or jnp.int32, low, high)


# sample_*: per-row parameter arrays → `shape` draws per row
def _sample_expand(params, shape):
    shp = _rand_shape(shape) if shape else ()
    return shp, tuple(params[0].shape) + shp


@register_op("sample_uniform", needs_rng=True, nondiff=True)
def sample_uniform(low, high, *, shape=(), dtype="float32", key=None):
    extra, out_shape = _sample_expand([low], shape)
    u = jax.random.uniform(key, out_shape,
                           resolve_dtype(dtype) or jnp.float32)
    exp = (...,) + (None,) * len(extra)
    return low[exp] + u * (high - low)[exp]


@register_op("sample_normal", needs_rng=True, nondiff=True)
def sample_normal(mu, sigma, *, shape=(), dtype="float32", key=None):
    extra, out_shape = _sample_expand([mu], shape)
    z = jax.random.normal(key, out_shape, resolve_dtype(dtype) or jnp.float32)
    exp = (...,) + (None,) * len(extra)
    return mu[exp] + z * sigma[exp]


@register_op("sample_exponential", needs_rng=True, nondiff=True)
def sample_exponential(lam, *, shape=(), dtype="float32", key=None):
    extra, out_shape = _sample_expand([lam], shape)
    e = jax.random.exponential(key, out_shape,
                               resolve_dtype(dtype) or jnp.float32)
    return e / lam[(...,) + (None,) * len(extra)]


@register_op("sample_gamma", needs_rng=True, nondiff=True)
def sample_gamma(alpha, beta, *, shape=(), dtype="float32", key=None):
    extra, out_shape = _sample_expand([alpha], shape)
    exp = (...,) + (None,) * len(extra)
    g = jax.random.gamma(key, alpha[exp],
                         out_shape, resolve_dtype(dtype) or jnp.float32)
    return g * beta[exp]


@register_op("sample_poisson", needs_rng=True, nondiff=True)
def sample_poisson(lam, *, shape=(), dtype="float32", key=None):
    extra, out_shape = _sample_expand([lam], shape)
    p = jax.random.poisson(key, lam[(...,) + (None,) * len(extra)], out_shape)
    return p.astype(resolve_dtype(dtype) or jnp.float32)


def _multinomial_draw(data, shape, dtype, key):
    extra = _rand_shape(shape) if shape else ()
    logits = jnp.log(jnp.maximum(data, 1e-30))
    n = 1
    for e in extra:
        n *= e
    draws = jax.random.categorical(key, logits[..., None, :], axis=-1,
                                   shape=data.shape[:-1] + (max(n, 1),))
    out = draws.reshape(data.shape[:-1] + extra) if extra \
        else draws.reshape(data.shape[:-1])
    return out.astype(resolve_dtype(dtype) or jnp.int32), logits


@register_op("sample_multinomial", needs_rng=True, nondiff=True)
def sample_multinomial(data, *, shape=(), get_prob=False, dtype="int32",
                       key=None):
    """Draw index samples from probability rows (ref: sample_op.cc
    _sample_multinomial). get_prob=True has its own 2-output registry entry
    (static arity keeps the symbol facade's tuple mirroring honest); the nd
    facade dispatches between the two."""
    if get_prob:
        raise ValueError("get_prob=True resolves to the 2-output op "
                         "'_sample_multinomial_prob' (the nd facade does "
                         "this automatically)")
    out, _ = _multinomial_draw(data, shape, dtype, key)
    return out


@register_op("_sample_multinomial_prob", needs_rng=True, nondiff=True,
             n_outputs=2)
def _sample_multinomial_prob(data, *, shape=(), dtype="int32", key=None):
    out, logits = _multinomial_draw(data, shape, dtype, key)
    lp = jnp.take_along_axis(
        jax.nn.log_softmax(logits, axis=-1),
        out.reshape(data.shape[:-1] + (-1,)).astype(jnp.int32),
        axis=-1).reshape(out.shape)
    return out, lp


# ------------------------------------------------- optimizer update kernels
def _clip(g, clip_gradient):
    if clip_gradient is not None and clip_gradient > 0:
        g = jnp.clip(g, -clip_gradient, clip_gradient)
    return g


@register_op("sgd_update", nondiff=True)
def sgd_update(weight, grad, *, lr, wd=0.0, rescale_grad=1.0,
               clip_gradient=-1.0, lazy_update=True):
    """(ref: optimizer_op.cc SGDUpdate) — pure: returns the new weight.
    Through the nd facade, pass out=weight for MXNet's in-place behavior;
    stateful kernels additionally write their new states back into the
    passed state arrays."""
    g = _clip(grad * rescale_grad, clip_gradient) + wd * weight
    return weight - lr * g


@register_op("sgd_mom_update", nondiff=True, n_outputs=2)
def sgd_mom_update(weight, grad, mom, *, lr, momentum=0.0, wd=0.0,
                   rescale_grad=1.0, clip_gradient=-1.0, lazy_update=True):
    g = _clip(grad * rescale_grad, clip_gradient) + wd * weight
    new_mom = momentum * mom - lr * g
    return weight + new_mom, new_mom


@register_op("adam_update", nondiff=True, n_outputs=3)
def adam_update(weight, grad, mean, var, *, lr, beta1=0.9, beta2=0.999,
                epsilon=1e-8, wd=0.0, rescale_grad=1.0, clip_gradient=-1.0,
                lazy_update=True):
    g = _clip(grad * rescale_grad, clip_gradient) + wd * weight
    m = beta1 * mean + (1 - beta1) * g
    v = beta2 * var + (1 - beta2) * jnp.square(g)
    return weight - lr * m / (jnp.sqrt(v) + epsilon), m, v


@register_op("lamb_update_phase1", nondiff=True, n_outputs=3)
def lamb_update_phase1(weight, grad, mean, var, *, beta1=0.9, beta2=0.999,
                       epsilon=1e-6, t=1, bias_correction=True, wd=0.0,
                       rescale_grad=1.0, clip_gradient=-1.0):
    """LAMB raw update direction (ref: optimizer_op.cc LambUpdatePhaseOne):
    adam moments + decoupled wd, NO lr yet — phase 2 applies the
    layerwise trust ratio. Returns (g, new_mean, new_var)."""
    g = _clip(grad * rescale_grad, clip_gradient)
    m = beta1 * mean + (1 - beta1) * g
    v = beta2 * var + (1 - beta2) * jnp.square(g)
    if bias_correction:
        mh = m / (1.0 - beta1 ** t)
        vh = v / (1.0 - beta2 ** t)
    else:
        mh, vh = m, v
    return mh / (jnp.sqrt(vh) + epsilon) + wd * weight, m, v


def _lamb_trust(weight_norm, g_norm, lr, lower_bound, upper_bound):
    r1 = weight_norm
    if lower_bound is not None and lower_bound > 0:
        r1 = jnp.maximum(r1, lower_bound)
    if upper_bound is not None and upper_bound > 0:
        r1 = jnp.minimum(r1, upper_bound)
    ratio = jnp.where((r1 > 0) & (g_norm > 0), r1 / g_norm, 1.0)
    return lr * ratio


@register_op("lamb_update_phase2", nondiff=True)
def lamb_update_phase2(weight, g, r1, r2, *, lr, lower_bound=-1.0,
                       upper_bound=-1.0):
    """(ref: optimizer_op.cc LambUpdatePhaseTwo) r1/r2 = ||weight||/||g||
    as computed by the caller (upstream chains norm ops)."""
    return weight - _lamb_trust(r1, r2, lr, lower_bound, upper_bound) * g


@register_op("mp_lamb_update_phase1", nondiff=True, n_outputs=3)
def mp_lamb_update_phase1(weight, grad, mean, var, weight32, *, beta1=0.9,
                          beta2=0.999, epsilon=1e-6, t=1,
                          bias_correction=True, wd=0.0, rescale_grad=1.0,
                          clip_gradient=-1.0):
    """Multi-precision phase 1: moments/update in fp32 against the master
    copy; the low-precision weight is only a cast source."""
    g = _clip(grad.astype(jnp.float32) * rescale_grad, clip_gradient)
    m = beta1 * mean + (1 - beta1) * g
    v = beta2 * var + (1 - beta2) * jnp.square(g)
    if bias_correction:
        mh = m / (1.0 - beta1 ** t)
        vh = v / (1.0 - beta2 ** t)
    else:
        mh, vh = m, v
    return mh / (jnp.sqrt(vh) + epsilon) + wd * weight32, m, v


@register_op("mp_lamb_update_phase2", nondiff=True, n_outputs=2)
def mp_lamb_update_phase2(weight, g, r1, r2, weight32, *, lr,
                          lower_bound=-1.0, upper_bound=-1.0):
    """Multi-precision phase 2: step the fp32 master, emit the cast weight.
    Returns (new_weight, new_weight32)."""
    new32 = weight32 - _lamb_trust(r1, r2, lr, lower_bound, upper_bound) * g
    return new32.astype(weight.dtype), new32


@register_op("multi_lars", nondiff=True)
def multi_lars(lrs, weights_sum_sq, grads_sum_sq, wds, *, eta, eps,
               rescale_grad=1.0):
    """Per-tensor LARS learning rates in ONE fused op over the stacked
    norms (ref: optimizer_op.cc MultiLars; pairs with multi_sum_sq)."""
    w_norm = jnp.sqrt(weights_sum_sq)
    g_norm = jnp.sqrt(grads_sum_sq) * rescale_grad
    ratio = jnp.where(
        (w_norm > 0) & (g_norm > 0),
        eta * w_norm / (g_norm + wds * w_norm + eps), 1.0)
    return lrs * ratio


@register_op("preloaded_multi_sgd_update", nondiff=True)
def preloaded_multi_sgd_update(*arrays, num_weights=None, rescale_grad=1.0,
                               clip_gradient=-1.0):
    """Fused SGD over many tensors with lrs/wds as DEVICE arrays (ref:
    optimizer_op.cc PreloadedMultiSGDUpdate — the 'preloaded' part is
    exactly that lrs/wds stay on device, no per-tensor host scalars).
    arrays = [w0, g0, w1, g1, ..., lrs, wds]; returns the updated weights
    as ONE list output (the arity varies with num_weights, so this is a
    single grouped result rather than positional heads)."""
    if num_weights is None:
        num_weights = (len(arrays) - 2) // 2
    lrs, wds = arrays[-2], arrays[-1]
    outs = []
    for i in range(num_weights):
        w, g = arrays[2 * i], arrays[2 * i + 1]
        g = _clip(g * rescale_grad, clip_gradient)
        outs.append(w - lrs[i] * (g + wds[i] * w))
    return outs


@register_op("rmsprop_update", nondiff=True, n_outputs=2)
def rmsprop_update(weight, grad, n, *, lr, gamma1=0.95, epsilon=1e-8,
                   wd=0.0, rescale_grad=1.0, clip_gradient=-1.0):
    g = _clip(grad * rescale_grad, clip_gradient) + wd * weight
    new_n = gamma1 * n + (1 - gamma1) * jnp.square(g)
    return weight - lr * g / jnp.sqrt(new_n + epsilon), new_n


@register_op("signsgd_update", nondiff=True)
def signsgd_update(weight, grad, *, lr, wd=0.0, rescale_grad=1.0,
                   clip_gradient=-1.0):
    g = _clip(grad * rescale_grad, clip_gradient)
    return weight - lr * (jnp.sign(g) + wd * weight)


@register_op("signum_update", nondiff=True, n_outputs=2)
def signum_update(weight, grad, mom, *, lr, momentum=0.0, wd=0.0,
                  rescale_grad=1.0, clip_gradient=-1.0, wd_lh=0.0):
    """(ref: optimizer_op.cc SignumUpdate): wd enters the momentum's
    gradient term; wd_lh decays the weight directly."""
    g = _clip(grad * rescale_grad, clip_gradient) + wd * weight
    new_mom = momentum * mom - (1 - momentum) * g
    return (1 - lr * wd_lh) * weight + lr * jnp.sign(new_mom), new_mom


@register_op("ftrl_update", nondiff=True, n_outputs=3)
def ftrl_update(weight, grad, z, n, *, lr, lamda1=0.01, beta=1.0, wd=0.0,
                rescale_grad=1.0, clip_gradient=-1.0):
    g = _clip(grad * rescale_grad, clip_gradient)
    new_n = n + jnp.square(g)
    sigma = (jnp.sqrt(new_n) - jnp.sqrt(n)) / lr
    new_z = z + g - sigma * weight
    new_w = jnp.where(
        jnp.abs(new_z) <= lamda1, jnp.zeros_like(weight),
        -(new_z - jnp.sign(new_z) * lamda1)
        / ((beta + jnp.sqrt(new_n)) / lr + wd))
    return new_w, new_z, new_n


@register_op("mp_sgd_update", nondiff=True, n_outputs=2)
def mp_sgd_update(weight, grad, weight32, *, lr, wd=0.0, rescale_grad=1.0,
                  clip_gradient=-1.0, lazy_update=True):
    """Mixed precision: bf16/fp16 weight + fp32 master (ref:
    optimizer_op.cc MP_SGDUpdate)."""
    g = _clip(grad.astype(jnp.float32) * rescale_grad, clip_gradient) \
        + wd * weight32
    new32 = weight32 - lr * g
    return new32.astype(weight.dtype), new32


@register_op("mp_sgd_mom_update", nondiff=True, n_outputs=3)
def mp_sgd_mom_update(weight, grad, mom, weight32, *, lr, momentum=0.0,
                      wd=0.0, rescale_grad=1.0, clip_gradient=-1.0,
                      lazy_update=True):
    """Momentum SGD with fp32 master weight + fp32 momentum (ref:
    optimizer_op.cc MP_SGDMomUpdate). Returns (new lp weight, mom, w32)."""
    g = _clip(grad.astype(jnp.float32) * rescale_grad, clip_gradient) \
        + wd * weight32
    new_mom = momentum * mom - lr * g
    new32 = weight32 + new_mom
    return new32.astype(weight.dtype), new_mom, new32


@register_op("nag_mom_update", nondiff=True, n_outputs=2)
def nag_mom_update(weight, grad, mom, *, lr, momentum=0.0, wd=0.0,
                   rescale_grad=1.0, clip_gradient=-1.0):
    """Nesterov momentum (ref: optimizer_op.cc NAGMomUpdate): the weight
    steps along grad + momentum*new_mom (the look-ahead term)."""
    g = _clip(grad * rescale_grad, clip_gradient) + wd * weight
    new_mom = momentum * mom + g
    return weight - lr * (g + momentum * new_mom), new_mom


@register_op("mp_nag_mom_update", nondiff=True, n_outputs=3)
def mp_nag_mom_update(weight, grad, mom, weight32, *, lr, momentum=0.0,
                      wd=0.0, rescale_grad=1.0, clip_gradient=-1.0):
    """(ref: optimizer_op.cc MP_NAGMomUpdate)."""
    g = _clip(grad.astype(jnp.float32) * rescale_grad, clip_gradient) \
        + wd * weight32
    new_mom = momentum * mom + g
    new32 = weight32 - lr * (g + momentum * new_mom)
    return new32.astype(weight.dtype), new_mom, new32


@register_op("ftml_update", nondiff=True, n_outputs=4)
def ftml_update(weight, grad, d, v, z, *, lr, t, beta1=0.6, beta2=0.999,
                epsilon=1e-8, wd=0.0, rescale_grad=1.0, clip_grad=-1.0):
    """Follow The Moving Leader (ref: optimizer_op.cc FTMLUpdate).
    Returns (weight, d, v, z); ``t`` is the 1-based step count."""
    g = _clip(grad * rescale_grad, clip_grad) + wd * weight
    new_v = beta2 * v + (1 - beta2) * jnp.square(g)
    d_t = (1 - beta1 ** t) / lr * (
        jnp.sqrt(new_v / (1 - beta2 ** t)) + epsilon)
    sigma = d_t - beta1 * d
    new_z = beta1 * z + (1 - beta1) * g - sigma * weight
    return -new_z / d_t, d_t, new_v, new_z


@register_op("rmspropalex_update", nondiff=True, n_outputs=4)
def rmspropalex_update(weight, grad, n, g, delta, *, lr, gamma1=0.95,
                       gamma2=0.9, epsilon=1e-8, wd=0.0, rescale_grad=1.0,
                       clip_gradient=-1.0, clip_weights=-1.0):
    """Centered RMSProp, Graves 2013 (ref: optimizer_op.cc
    RMSPropAlexUpdate). Returns (weight, n, g, delta)."""
    grd = _clip(grad * rescale_grad, clip_gradient) + wd * weight
    new_n = gamma1 * n + (1 - gamma1) * jnp.square(grd)
    new_g = gamma1 * g + (1 - gamma1) * grd
    new_delta = gamma2 * delta - lr * grd / jnp.sqrt(
        new_n - jnp.square(new_g) + epsilon)
    new_w = weight + new_delta
    if clip_weights > 0:
        new_w = jnp.clip(new_w, -clip_weights, clip_weights)
    return new_w, new_n, new_g, new_delta


def _multi_sgd(arrays, stride, lrs, wds, rescale_grad, clip_gradient,
               momentum=None, mp=False):
    """Shared body of the multi_/preloaded_multi_ SGD family (ref:
    optimizer_op.cc MultiSGDUpdate/PreloadedMultiSGDUpdate et al.):
    per-weight groups of ``stride`` arrays, host or device lrs/wds.
    Returns updated weights first, then updated states group-major."""
    num = len(arrays) // stride
    ws, states = [], []
    for i in range(num):
        grp = arrays[stride * i:stride * i + stride]
        w, grad = grp[0], grp[1]
        w32 = grp[-1] if mp else w
        g = _clip(grad.astype(w32.dtype) * rescale_grad, clip_gradient) \
            + wds[i] * w32
        if momentum is None:
            new32 = w32 - lrs[i] * g
            ws.append(new32.astype(w.dtype))
            if mp:
                states.append(new32)
        else:
            mom = grp[2]
            new_mom = momentum * mom - lrs[i] * g
            new32 = w32 + new_mom
            ws.append(new32.astype(w.dtype))
            states.append(new_mom)
            if mp:
                states.append(new32)
    return ws + states


@register_op("multi_sgd_update", nondiff=True)
def multi_sgd_update(*arrays, lrs, wds, num_weights=None, rescale_grad=1.0,
                     clip_gradient=-1.0):
    """[w0,g0, w1,g1, ...] with HOST lr/wd lists (ref: optimizer_op.cc
    MultiSGDUpdate). One grouped list output: the updated weights."""
    return _multi_sgd(arrays, 2, lrs, wds, rescale_grad, clip_gradient)


@register_op("multi_sgd_mom_update", nondiff=True)
def multi_sgd_mom_update(*arrays, lrs, wds, momentum=0.0, num_weights=None,
                         rescale_grad=1.0, clip_gradient=-1.0):
    """[w0,g0,m0, ...]; returns updated weights then updated momenta."""
    return _multi_sgd(arrays, 3, lrs, wds, rescale_grad, clip_gradient,
                      momentum=momentum)


@register_op("multi_mp_sgd_update", nondiff=True)
def multi_mp_sgd_update(*arrays, lrs, wds, num_weights=None,
                        rescale_grad=1.0, clip_gradient=-1.0):
    """[w0,g0,w32_0, ...]; returns updated lp weights then fp32 masters."""
    return _multi_sgd(arrays, 3, lrs, wds, rescale_grad, clip_gradient,
                      mp=True)


@register_op("multi_mp_sgd_mom_update", nondiff=True)
def multi_mp_sgd_mom_update(*arrays, lrs, wds, momentum=0.0,
                            num_weights=None, rescale_grad=1.0,
                            clip_gradient=-1.0):
    """[w0,g0,m0,w32_0, ...]; weights, then (mom, w32) pairs group-major."""
    return _multi_sgd(arrays, 4, lrs, wds, rescale_grad, clip_gradient,
                      momentum=momentum, mp=True)


def _split_preloaded(arrays):
    return arrays[:-2], arrays[-2], arrays[-1]


@register_op("preloaded_multi_sgd_mom_update", nondiff=True)
def preloaded_multi_sgd_mom_update(*arrays, momentum=0.0, num_weights=None,
                                   rescale_grad=1.0, clip_gradient=-1.0):
    """[w0,g0,m0, ..., lrs, wds] with DEVICE lr/wd vectors (ref:
    optimizer_op.cc PreloadedMultiSGDMomUpdate)."""
    body, lrs, wds = _split_preloaded(arrays)
    return _multi_sgd(body, 3, lrs, wds, rescale_grad, clip_gradient,
                      momentum=momentum)


@register_op("preloaded_multi_mp_sgd_update", nondiff=True)
def preloaded_multi_mp_sgd_update(*arrays, num_weights=None,
                                  rescale_grad=1.0, clip_gradient=-1.0):
    """[w0,g0,w32_0, ..., lrs, wds]."""
    body, lrs, wds = _split_preloaded(arrays)
    return _multi_sgd(body, 3, lrs, wds, rescale_grad, clip_gradient,
                      mp=True)


@register_op("preloaded_multi_mp_sgd_mom_update", nondiff=True)
def preloaded_multi_mp_sgd_mom_update(*arrays, momentum=0.0,
                                      num_weights=None, rescale_grad=1.0,
                                      clip_gradient=-1.0):
    """[w0,g0,m0,w32_0, ..., lrs, wds]."""
    body, lrs, wds = _split_preloaded(arrays)
    return _multi_sgd(body, 4, lrs, wds, rescale_grad, clip_gradient,
                      momentum=momentum, mp=True)


@register_op("all_finite", nondiff=True)
def all_finite(data, *, init_output=True):
    """Scalar 1.0 iff every element is finite (ref: contrib/all_finite.cc;
    single-array sibling of multi_all_finite)."""
    return jnp.isfinite(data).all().astype(jnp.float32).reshape(1)


@register_op("amp_cast")
def amp_cast(x, *, dtype):
    """Differentiable dtype cast inserted by AMP (ref: tensor/amp_cast.h)."""
    return x.astype(resolve_dtype(dtype))


@register_op("amp_multicast")
def amp_multicast(*arrays, num_outputs=None, cast_narrow=False):
    """Cast every FLOAT input to the widest (or, with cast_narrow, the
    narrowest) floating dtype among them; non-float inputs pass through
    untouched — AMP never casts integers (ref: tensor/amp_cast.h
    AMPMultiCast)."""
    fdts = [a.dtype for a in arrays if jnp.issubdtype(a.dtype, jnp.floating)]
    if not fdts:
        return list(arrays)
    pick_fn = min if cast_narrow else max
    target = pick_fn(fdts, key=lambda d: jnp.finfo(d).bits)
    return [a.astype(target) if jnp.issubdtype(a.dtype, jnp.floating) else a
            for a in arrays]


# deprecated pre-1.0 alias still exposed by upstream's registry
register_op("Softmax")(F.SoftmaxOutput)


# ------------------------------------------------ r5 long-tail closures
def _syevd(a):
    """Upstream syevd returns (U, lambda) with ROWS of U the eigenvectors
    (ref: la_op.cc syevd: A = U^T diag(L) U); jnp.linalg.eigh returns
    (w, v) with columns of v the eigenvectors, so U = v^T."""
    w, v = jnp.linalg.eigh(a)
    return jnp.swapaxes(v, -1, -2), w


_reg_linalg("linalg_syevd", _syevd, n_outputs=2)


@register_op("onehot_encode", nondiff=True)
def onehot_encode(indices, out_like):
    """Legacy one-hot into a preallocated-shaped output (ref:
    ndarray_function.cc onehot_encode: (N,) indices, (N, C) out)."""
    C = out_like.shape[-1]
    cols = jax.lax.broadcasted_iota(jnp.int32, (indices.shape[0], C), 1)
    return (cols == indices.astype(jnp.int32)[:, None]).astype(out_like.dtype)


@register_op("softmax_with_length")
def softmax_with_length(data, length, *, axis=-1, temperature=None):
    """Softmax over ``axis`` with valid lengths: positions at or past
    ``length`` get zero probability (ref: nn/softmax-inl.h
    SoftmaxWithLength). ``length`` is shaped like ``data`` minus the
    softmax axis (upstream's contract) — e.g. (B,) for (B, T) scores,
    (B, H) for (B, H, T); a size mismatch fails the reshape loudly."""
    if temperature is not None and temperature != 1.0:
        data = data / temperature
    ax = axis % data.ndim
    pos = jax.lax.broadcasted_iota(jnp.int32, data.shape, ax)
    lshape = list(data.shape)
    lshape[ax] = 1
    valid = pos < length.astype(jnp.int32).reshape(lshape)
    masked = jnp.where(valid, data, -jnp.inf)
    out = jax.nn.softmax(masked, axis=ax)
    return jnp.where(valid, out, 0.0)


def _alias_op(new, old):
    """Registry alias preserving EVERY OpDef field (rng, arity, and any
    future ones) — upstream's NNVM add_alias."""
    from ..base import OP_REGISTRY
    OP_REGISTRY[new] = OP_REGISTRY[old]._replace(name=new)


# deprecated/legacy flat aliases still exposed by upstream's registry
_alias_op("normal", "random_normal")
_alias_op("uniform", "random_uniform")
_alias_op("exponential", "random_exponential")
_alias_op("poisson", "random_poisson")
_alias_op("max_axis", "max")
_alias_op("min_axis", "min")
_alias_op("BatchNorm_v1", "BatchNorm")


@register_op("cast_storage")
def cast_storage(data, *, stype="default"):
    """Symbolic-surface parity shim (ref: tensor/cast_storage.cc). The
    imperative nd.cast_storage (sparse.py) converts between real storage
    classes; inside a traced/symbolic graph every array is dense, so
    'default' is the identity and sparse targets refuse loudly rather than
    silently densifying."""
    if stype != "default":
        raise ValueError(
            "cast_storage(stype=%r) inside a traced graph: the symbolic "
            "executor is dense-only; convert imperatively with "
            "nd.cast_storage" % (stype,))
    return data
